"""Incremental version builds: fold a delta batch into a ``GraphVersion``.

The read half of dynamic serving (PR 6) swaps prebuilt versions
atomically; this module builds those versions INCREMENTALLY.  A full
``from_coo`` pipeline re-sorts and re-buckets every edge and re-uploads
every artifact; :func:`apply_delta` instead

1. folds the delta into the retained sorted edge-key set (an O(nnz)
   merge of two sorted runs — no full re-sort; ``delta.fold_ops``),
2. patches ONLY the changed rows inside the retained host bucket arrays
   of the ``EllParMat`` (slot-capacity-aware: a row whose entries still
   fit its current degree-class slots is rewritten in place; a row that
   outgrows them claims a free padding slot in a wider class —
   "re-bucketed"; no free slot anywhere = spill), and
3. re-uploads only the bucket classes that changed, REUSING the old
   version's device arrays for every untouched class — so a small delta
   uploads a small fraction of the graph, and the new version has
   IDENTICAL operand shapes (the zero-retrace guarantee survives the
   swap).

The CSC / transpose / normalized twins ride the same machinery: the
weighted matrix and the PageRank transition matrix share the structural
bucket layout (their values are derived per class from the merged
weights / out-degrees), the transpose twin is patched through a second
orientation of the same patcher, and the lazy CSC companion is reset to
rebuild on demand from the carried host COO (it has no compiled-shape
contract to preserve).

SPILL POLICY — the incremental path falls back to a full rebuild
(``dynamic.merge.applied{mode=rebuild}``, labeled reason) when:

* the structural change fraction exceeds ``spill_frac``
  (``COMBBLAS_DYNAMIC_SPILL_FRAC``, default 0.10) — past that point the
  per-row patching plus class re-uploads cost more than one rebuild;
* a changed row needs a slot no bucket class can provide
  (``bucket_full``) — growing a bucket would change operand shapes and
  retrace anyway, so the rebuild is honest about it;
* the version carries no retained host state and no host COO to
  bootstrap it from (``no_state``; build the engine with
  ``keep_coo=True``).

Counters (``dynamic.merge.*``, cataloged in ``obs/metrics.py``) make
the incremental-vs-rebuild amortization measurable; the serve bench's
``BENCH_SERVE_MUTATE=1`` scenario gates on them.
"""

from __future__ import annotations

import bisect
import dataclasses
import time

import numpy as np

from .. import obs
from .delta import COMBINES, DeltaBatch, fold_ops


class _Spill(Exception):
    """Internal: abandon the incremental attempt, rebuild instead."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


@dataclasses.dataclass
class MergeStats:
    """What one ``apply_delta`` did (also mirrored into obs)."""

    mode: str                  # "incremental" | "rebuild"
    reason: str = ""           # spill reason when mode == "rebuild"
    inserted: int = 0          # edges added
    removed: int = 0           # edges removed
    value_changed: int = 0     # edges whose weight changed (structure kept)
    rows_patched: int = 0      # rows rewritten in place (all orientations)
    rows_rebucketed: int = 0   # rows that claimed a slot in a new class
    headroom_used: int = 0     # free padding slots claimed by re-bucketing
    #                            (the headroom reserve paying off)
    buckets_uploaded: int = 0  # device bucket classes re-uploaded
    buckets_reused: int = 0    # device bucket classes shared with parent
    latency_s: float = 0.0
    bootstrapped: bool = False # host merge state built on this call
    nnz: int = 0               # edge count after the merge


@dataclasses.dataclass
class _Orientation:
    """Host bucket structure of one ELL layout (row-major for
    E/E_weighted/P_ell, transposed for ET).  ``keys`` is the sorted
    major-order key array (``major * minor_dim + minor``); ``bc``/``br``
    the per-class host arrays matching the device buckets exactly."""

    keys: np.ndarray
    nrows: int                 # this orientation's major dim
    ncols: int                 # this orientation's minor dim
    lr: int
    lc: int
    kbs: list                  # bucket width per class position
    bc: list                   # [pr, pc, nb, kb] int32 per class
    br: list                   # [pr, pc, nb] int32 per class
    ladder: np.ndarray
    max_k: int


@dataclasses.dataclass
class MergeState:
    """Retained host-side merge state riding on a ``GraphVersion``
    (``version.dyn``).  Arrays are shared with the parent version's
    state until a merge copies-on-write the classes it touches, so
    branching (applying two different deltas to one version) is safe."""

    row: _Orientation
    t: _Orientation | None     # transpose twin (ET), or None
    weights: np.ndarray | None # aligned with row.keys; None = unweighted
    deg: np.ndarray
    outdeg: np.ndarray
    symmetric: bool
    last_stats: MergeStats | None = None


# -- host structure builders -------------------------------------------------


def _orientation_from_buckets(grid, buckets, major, minor,
                              nrows: int, ncols: int) -> _Orientation:
    """Assemble an ``_Orientation`` from host ``(bc, bv, br)`` bucket
    triples + the layout's (major, minor) index arrays — the ONE place
    the key encoding (``major * ncols + minor``), the fine ladder, and
    the contiguous-bc/br invariants live (shared by fresh builds and
    snapshot restores; drift between them silently corrupts merges)."""
    from ..parallel.ellmat import _width_ladder

    lr, lc = grid.local_rows(nrows), grid.local_cols(ncols)
    max_k = max(int(lc), 1)
    keys = np.sort(
        np.asarray(major, np.int64) * np.int64(ncols)
        + np.asarray(minor, np.int64)
    )
    return _Orientation(
        keys=keys, nrows=int(nrows), ncols=int(ncols), lr=lr, lc=lc,
        kbs=[int(bc.shape[-1]) for bc, _bv, _br in buckets],
        bc=[np.ascontiguousarray(bc) for bc, _bv, _br in buckets],
        br=[np.ascontiguousarray(br) for _bc, _bv, br in buckets],
        ladder=_width_ladder(max_k, "fine"), max_k=max_k,
    )


def _is_symmetric(rows, cols, nrows: int, ncols: int) -> bool:
    """Structural symmetry of a key-sorted deduped COO (the merge
    state's bc-serving guard input)."""
    rows = np.asarray(rows, np.int64)
    cols = np.asarray(cols, np.int64)
    keys = rows * np.int64(ncols) + cols
    return bool(
        int(nrows) == int(ncols)
        and np.array_equal(np.sort(cols * np.int64(ncols) + rows), keys)
    )


def _build_orientation(grid, rows, cols, nrows: int, ncols: int,
                       headroom: float | None = None) -> _Orientation:
    """Host bucket structure for one layout — the SAME deterministic
    ``EllParMat.host_build`` the loaded matrices came from (INCLUDING
    the headroom over-allocation: mismatched slack would change bucket
    shapes and forfeit untouched-class sharing), so untouched classes
    can be shared with the existing device arrays."""
    from ..parallel.ellmat import EllParMat

    buckets = EllParMat.host_build(
        grid, rows, cols, np.ones(len(rows), np.float32), nrows, ncols,
        headroom=headroom,
    )
    return _orientation_from_buckets(
        grid, buckets, rows, cols, nrows, ncols
    )


def bootstrap_state(version, grid=None) -> MergeState:
    """Build the retained merge state for a version that lacks one —
    needs the host COO (``GraphEngine.from_coo(..., keep_coo=True)``).
    One host re-bucketing pass (no device reads: the axon D2H rule);
    every later ``apply_delta`` updates the state incrementally."""
    if version.host_coo is None:
        raise ValueError(
            "the mutation lane needs the host edge list: build the "
            "engine with GraphEngine.from_coo(..., keep_coo=True)"
        )
    rows, cols, ncols = version.host_coo
    rows = np.asarray(rows, np.int64)
    cols = np.asarray(cols, np.int64)
    nrows = int(version.nrows)
    ncols = int(ncols)
    grid = version.E.grid if grid is None else grid
    hr = getattr(version, "headroom", None)
    row_o = _build_orientation(grid, rows, cols, nrows, ncols,
                               headroom=hr)
    t_o = (
        _build_orientation(grid, cols, rows, ncols, nrows, headroom=hr)
        if version.ET is not None else None
    )
    weights = getattr(version, "host_weights", None)
    if weights is not None:
        weights = np.asarray(weights, np.float32)
    return MergeState(
        row=row_o, t=t_o, weights=weights,
        deg=np.bincount(rows, minlength=nrows).astype(np.int32),
        outdeg=np.bincount(cols, minlength=ncols).astype(np.int64),
        symmetric=_is_symmetric(rows, cols, nrows, ncols),
    )


def state_from_host_buckets(grid, row_buckets, t_buckets, host_coo,
                            host_weights, deg, outdeg) -> MergeState:
    """Merge state from retained HOST bucket arrays — the snapshot-
    restore path (round 16, ``utils.checkpoint.load_version``).

    A snapshot of an incrementally merged version carries STICKY-SLOT
    bucket layouts that a fresh ``host_build`` of the same edge list
    would NOT reproduce (in-place patching deliberately never moves a
    shrunk-then-regrown row) — so ``bootstrap_state``'s rebuild-from-
    COO assumption breaks on restored versions: patching against the
    wrong slot map corrupts the graph.  This constructor derives the
    state from the snapshot's own host arrays instead — exactly the
    device layout, no device reads (the axon D2H rule holds).

    ``row_buckets`` / ``t_buckets`` are lists of host ``(bc, bv, br)``
    triples in the E / ET layouts (``t_buckets=None`` for symmetric
    versions); ``host_coo`` the retained ``(rows, cols, ncols)``.
    """
    rows, cols, ncols = host_coo
    rows = np.asarray(rows, np.int64)
    cols = np.asarray(cols, np.int64)
    ncols = int(ncols)
    nrows = int(len(deg))
    row_o = _orientation_from_buckets(
        grid, row_buckets, rows, cols, nrows, ncols
    )
    t_o = (
        _orientation_from_buckets(
            grid, t_buckets, cols, rows, ncols, nrows
        )
        if t_buckets is not None else None
    )
    return MergeState(
        row=row_o, t=t_o,
        weights=(
            np.asarray(host_weights, np.float32)
            if host_weights is not None else None
        ),
        deg=np.asarray(deg, np.int32),
        outdeg=(
            np.asarray(outdeg, np.int64) if outdeg is not None
            else np.bincount(cols, minlength=ncols).astype(np.int64)
        ),
        symmetric=_is_symmetric(rows, cols, nrows, ncols),
    )


# -- per-class value derivation ----------------------------------------------


def _class_globals(orient: _Orientation, b: int):
    """(gr, gc, valid) index arrays for one class's host buckets."""
    bc, br = orient.bc[b], orient.br[b]
    pr, pc = bc.shape[0], bc.shape[1]
    valid = (bc < orient.lc) & (br[..., None] < orient.lr)
    gr = (
        np.arange(pr, dtype=np.int64)[:, None, None] * orient.lr + br
    )[..., None]
    gc = np.arange(pc, dtype=np.int64)[None, :, None, None] * orient.lc + bc
    gr = np.broadcast_to(gr, bc.shape)
    return gr, np.where(valid, gc, 0), valid


def _vals_ones(orient, b, state):
    _gr, _gc, valid = _class_globals(orient, b)
    return valid.astype(np.float32)


def _vals_weights(orient, b, state):
    gr, gc, valid = _class_globals(orient, b)
    key = np.where(valid, gr * np.int64(orient.ncols) + gc, 0)
    pos = np.searchsorted(orient.keys, key)
    pos = np.minimum(pos, max(len(orient.keys) - 1, 0))
    w = state.weights[pos]
    return np.where(valid, w, 0.0).astype(np.float32)


def _vals_pagerank(orient, b, state):
    # column-stochastic values: 1 / outdeg(col) per slot (the host-side
    # DimApply of serve.engine._build_version, derived per class)
    _gr, gc, valid = _class_globals(orient, b)
    v = 1.0 / np.maximum(state.outdeg[gc], 1)
    return np.where(valid, v, 0.0).astype(np.float32)


# -- the row patcher ---------------------------------------------------------


def _dirty_tiles(orient: _Orientation, majors: np.ndarray,
                 minors: np.ndarray) -> dict:
    """Group changed (major, minor) coordinates by owning tile:
    {(i, j): sorted unique local major rows}."""
    i = majors // orient.lr
    j = minors // orient.lc
    lrow = majors - i * orient.lr
    out: dict = {}
    for ti, tj, r in zip(i.tolist(), j.tolist(), lrow.tolist()):
        out.setdefault((ti, tj), set()).add(r)
    return {k: np.asarray(sorted(v), np.int64) for k, v in out.items()}


def _patch_orientation(orient: _Orientation, new_keys: np.ndarray,
                       tiles: dict, stats: MergeStats) -> set:
    """Patch every dirty row of one orientation in place (copy-on-write
    per class).  Returns the set of touched class indices.  Raises
    ``_Spill("bucket_full")`` when a row cannot be placed."""
    ncls = len(orient.kbs)
    lr, lc, ncols = orient.lr, orient.lc, orient.ncols
    touched: set = set()
    copied: set = set()

    def ensure_copy(b):
        if b not in copied:
            orient.bc[b] = orient.bc[b].copy()
            orient.br[b] = orient.br[b].copy()
            copied.add(b)
        touched.add(b)

    for (i, j) in sorted(tiles):
        rows_arr = tiles[(i, j)]
        rowset = set(rows_arr.tolist())
        slots_of: dict = {r: [] for r in rowset}
        for b in range(ncls):
            brt = orient.br[b][i, j]
            for p in np.nonzero(np.isin(brt, rows_arr))[0]:
                slots_of[int(brt[p])].append((b, int(p)))
        freelist: dict = {}

        def free_positions(b):
            if b not in freelist:
                freelist[b] = np.nonzero(
                    orient.br[b][i, j] == lr
                )[0].tolist()
            return freelist[b]

        for lrow in rows_arr.tolist():
            gr = i * lr + lrow
            lo = np.searchsorted(new_keys, gr * np.int64(ncols) + j * lc)
            hi = np.searchsorted(
                new_keys,
                gr * np.int64(ncols) + min((j + 1) * lc, ncols),
            )
            seg = new_keys[lo:hi]
            cols_local = (seg - gr * np.int64(ncols) - j * lc).astype(
                np.int32
            )
            d = int(hi - lo)
            # widest slots first so hub rows keep their big chunks;
            # deterministic tie-break on (class, position)
            slots = sorted(
                slots_of[lrow],
                key=lambda bp: (-orient.kbs[bp[0]], bp[0], bp[1]),
            )
            writes = []
            remaining, off = d, 0
            for (b, p) in slots:
                take = min(remaining, orient.kbs[b], orient.max_k)
                if take > 0:
                    writes.append((b, p, off, take))
                    off += take
                    remaining -= take
                else:  # surplus slot: release it (degree shrank)
                    fl = free_positions(b)
                    ensure_copy(b)
                    orient.bc[b][i, j, p, :] = lc
                    orient.br[b][i, j, p] = lr
                    bisect.insort(fl, p)
            rebucketed = False
            while remaining > 0:
                need = min(remaining, orient.max_k)
                # tightest class that fits the chunk and has a free
                # slot; else the widest free slot (partial chunk)
                cand = [
                    b for b in range(ncls)
                    if orient.kbs[b] >= need and free_positions(b)
                ]
                if cand:
                    b = min(cand, key=lambda bb: (orient.kbs[bb], bb))
                    take = need
                else:
                    cand = [b for b in range(ncls) if free_positions(b)]
                    if not cand:
                        raise _Spill("bucket_full")
                    b = max(cand, key=lambda bb: (orient.kbs[bb], -bb))
                    take = min(remaining, orient.kbs[b])
                p = free_positions(b).pop(0)
                writes.append((b, p, off, take))
                off += take
                remaining -= take
                rebucketed = True
                # every claimed free padding row is headroom paying
                # off (build-time reserve or natural tile imbalance) —
                # the counter the headroom= knob is sized against
                stats.headroom_used += 1
            for (b, p, o0, take) in writes:
                ensure_copy(b)
                orient.bc[b][i, j, p, :take] = cols_local[o0:o0 + take]
                orient.bc[b][i, j, p, take:] = lc
                orient.br[b][i, j, p] = lrow
            stats.rows_patched += 1
            if rebucketed:
                stats.rows_rebucketed += 1
    return touched


# -- device assembly ---------------------------------------------------------


def _put_buckets(grid, host_buckets):
    """ONE batched ``device_put`` for a whole list of (bc, bv, br)
    host triples: per-array puts pay ~1 ms of sharding dispatch EACH on
    a multi-device mesh (profiled: 51 puts = 59 ms of a 69 ms merge),
    while a single batched transfer pays it once."""
    import jax

    sh = grid.tile_sharding()
    flat = [a for triple in host_buckets for a in triple]
    if not flat:
        return []
    moved = jax.device_put(flat, [sh] * len(flat))
    return [tuple(moved[i:i + 3]) for i in range(0, len(moved), 3)]


def _assemble(grid, orient: _Orientation, old_ell, touched: set,
              vals_fn, state: MergeState, stats: MergeStats):
    """New ``EllParMat`` mixing freshly-uploaded touched classes with
    the old version's device arrays for untouched ones."""
    from ..parallel.ellmat import EllParMat

    to_put = []
    order = []
    for b in range(len(orient.kbs)):
        if b in touched:
            to_put.append((
                orient.bc[b], vals_fn(orient, b, state), orient.br[b]
            ))
            order.append(b)
            stats.buckets_uploaded += 1
        else:
            stats.buckets_reused += 1
    fresh = dict(zip(order, _put_buckets(grid, to_put)))
    buckets = tuple(
        fresh[b] if b in fresh else old_ell.buckets[b]
        for b in range(len(orient.kbs))
    )
    return EllParMat(
        buckets=buckets, nrows=orient.nrows, ncols=orient.ncols,
        grid=grid,
    )


# -- full rebuild ------------------------------------------------------------


def _full_build(grid, version, keys: np.ndarray,
                weights: np.ndarray | None, stats: MergeStats):
    """Rebuild every artifact from the merged edge set — the spill
    path.  Mirrors ``serve.engine._build_version`` (which artifacts
    exist follows the PARENT version, so a swap stays valid) while
    retaining the host structure as fresh merge state."""
    from ..parallel.ellmat import EllParMat
    from ..parallel.vec import DistVec
    from ..serve.engine import GraphVersion

    nrows, ncols = int(version.nrows), int(version.ncols)
    rows = (keys // np.int64(ncols)).astype(np.int64)
    cols = (keys % np.int64(ncols)).astype(np.int64)
    hr = getattr(version, "headroom", None)
    row_o = _build_orientation(grid, rows, cols, nrows, ncols,
                               headroom=hr)
    t_o = (
        _build_orientation(grid, cols, rows, ncols, nrows, headroom=hr)
        if version.ET is not None else None
    )
    state = MergeState(
        row=row_o, t=t_o, weights=weights,
        deg=np.bincount(rows, minlength=nrows).astype(np.int32),
        outdeg=np.bincount(cols, minlength=ncols).astype(np.int64),
        symmetric=bool(
            nrows == ncols and np.array_equal(
                np.sort(cols * np.int64(ncols) + rows), keys
            )
        ),
    )

    def build(orient, vals_fn):
        buckets = tuple(_put_buckets(grid, [
            (orient.bc[b], vals_fn(orient, b, state), orient.br[b])
            for b in range(len(orient.kbs))
        ]))
        stats.buckets_uploaded += len(buckets)
        return EllParMat(
            buckets=buckets, nrows=orient.nrows, ncols=orient.ncols,
            grid=grid,
        )

    E = build(row_o, _vals_ones)
    E_weighted = (
        build(row_o, _vals_weights)
        if version.E_weighted is not None and weights is not None
        else None
    )
    P_ell = dangling = None
    if version.P_ell is not None:
        P_ell = build(row_o, _vals_pagerank)
        dangling = DistVec.from_global(
            grid, (state.outdeg == 0).astype(np.float32), align="col"
        )
    ET = build(t_o, _vals_ones) if t_o is not None else None
    new_version = GraphVersion(
        nrows=nrows, ncols=ncols, nnz=int(len(keys)), E=E,
        deg=state.deg, outdeg=state.outdeg, E_weighted=E_weighted,
        P_ell=P_ell, dangling=dangling, ET=ET,
        host_coo=(rows, cols, ncols),
        # the feature table is edge-independent: the rebuilt version
        # keeps serving the same device arrays (invdeg stays None —
        # degrees changed, it lazily rebuilds)
        X=getattr(version, "X", None),
        feat_dim=int(getattr(version, "feat_dim", 0)),
        headroom=getattr(version, "headroom", None),
    )
    new_version.host_weights = weights
    new_version.dyn = state
    return new_version


# -- the entry point ---------------------------------------------------------


def apply_delta(version, batch: DeltaBatch, *,
                kinds: tuple | None = None,
                combine: str | None = None,
                spill_frac: float | None = None,
                force_rebuild: bool = False,
                grid=None):
    """Merge one delta batch into ``version``; returns the NEXT
    ``GraphVersion`` (hand it to ``engine.swap`` / ``Server.swap_graph``
    — this function never touches the serving pointer).  See the module
    docstring for the incremental/spill contract; the parent version is
    never mutated (its host state is copied-on-write), so it keeps
    serving while this builds and remains a valid branch point.

    ``kinds`` (the engine's served kinds) gates the structural-symmetry
    check a ``bc``-serving symmetric engine relies on; ``combine`` names
    the upsert monoid (defaults to the ``min`` convention of
    ``GraphEngine.from_coo``); ``spill_frac`` overrides the env default
    (``COMBBLAS_DYNAMIC_SPILL_FRAC``).
    """
    from ..serve.engine import GraphVersion
    from ..tuner import config as tuner_config

    t0 = time.perf_counter()
    grid = version.E.grid if grid is None else grid
    combine = "min" if combine is None else combine
    if combine not in COMBINES:
        raise ValueError(f"unknown combine {combine!r}")
    spill_frac = (
        tuner_config.dynamic_spill_frac()
        if spill_frac is None else float(spill_frac)
    )
    stats = MergeStats(mode="incremental")
    state = getattr(version, "dyn", None)
    if state is None:
        # snapshot-restored versions carry a LAZY state constructor
        # (``dyn_source``, utils/checkpoint.load_version): the merge
        # state must describe the restored sticky-slot bucket layout
        # — bootstrap_state's fresh host_build would not reproduce it
        src = getattr(version, "dyn_source", None)
        if src is not None:
            # the source stays on the parent (construction is
            # idempotent): if THIS merge fails, a retry must rebuild
            # the restored-layout state again — falling back to
            # bootstrap_state's fresh host_build would patch the
            # wrong slot map
            state = src()
        else:
            state = bootstrap_state(version, grid=grid)
        stats.bootstrapped = True
        obs.count("dynamic.state.bootstrap")
    ncols = int(version.ncols)
    nrows = int(version.nrows)
    if len(batch) and (
        int(batch.rows.max()) >= nrows or int(batch.cols.max()) >= ncols
        or int(batch.rows.min()) < 0 or int(batch.cols.min()) < 0
    ):
        raise ValueError(
            f"delta indices outside [0, {nrows}) x [0, {ncols})"
        )
    base_keys = state.row.keys
    base_w = state.weights
    uniq, present, fw = fold_ops(batch, base_keys, base_w, ncols, combine)
    # classify touched keys against the base
    bpos = np.searchsorted(base_keys, uniq)
    safe = np.minimum(bpos, max(len(base_keys) - 1, 0))
    in_base = (
        (bpos < len(base_keys)) & (base_keys[safe] == uniq)
        if len(base_keys) else np.zeros(len(uniq), bool)
    )
    ins = uniq[present & ~in_base]
    rem = uniq[~present & in_base]
    if base_w is not None:
        wchg = uniq[present & in_base & (fw != base_w[safe])]
    else:
        wchg = np.empty(0, np.int64)
    stats.inserted = int(len(ins))
    stats.removed = int(len(rem))
    stats.value_changed = int(len(wchg))

    # merged edge set: delete removed, update changed, insert new —
    # O(nnz) passes over sorted runs, no full re-sort
    keep = np.ones(len(base_keys), bool)
    keep[np.searchsorted(base_keys, rem)] = False
    new_keys = base_keys[keep]
    new_w = base_w[keep] if base_w is not None else None
    if base_w is not None and len(wchg):
        cpos = np.searchsorted(new_keys, wchg)
        new_w = new_w.copy()
        new_w[cpos] = fw[np.searchsorted(uniq, wchg)]
    if len(ins):
        ipos = np.searchsorted(new_keys, ins)
        new_keys = np.insert(new_keys, ipos, ins)
        if new_w is not None:
            new_w = np.insert(new_w, ipos, fw[np.searchsorted(uniq, ins)])

    # symmetry: a bc- or propagate-serving symmetric engine must STAY
    # symmetric (the same verification serve.engine._build_version
    # performs — both kinds reuse E as its own transpose when ET is
    # absent, so an asymmetric delta would silently flip the edge
    # direction every served result walks)
    require_sym = (
        kinds is not None
        and ("bc" in kinds or "propagate" in kinds)
        and version.ET is None
    )
    if require_sym and nrows == ncols:
        def _sym(k):
            return np.array_equal(
                np.sort((k % ncols) * np.int64(ncols) + k // ncols), k
            )
        # structural check only (like _build_version's): asymmetric
        # WEIGHTS are fine, bc reads E structurally
        if not (_sym(ins) and _sym(rem)):
            raise ValueError(
                "delta breaks structural symmetry but the engine "
                "serves 'bc' with E as its own transpose; symmetrize "
                "the delta or rebuild with symmetric=False"
            )

    changed_struct = int(len(ins) + len(rem))
    nnz_ref = max(len(new_keys), len(base_keys), 1)
    new_deg = state.deg.copy()
    new_outdeg = state.outdeg.copy()
    if len(ins):
        np.add.at(new_deg, ins // ncols, 1)
        np.add.at(new_outdeg, ins % ncols, 1)
    if len(rem):
        np.subtract.at(new_deg, rem // ncols, 1)
        np.subtract.at(new_outdeg, rem % ncols, 1)

    def _finish(v, mode, reason=""):
        stats.mode, stats.reason = mode, reason
        stats.nnz = int(len(new_keys))
        stats.latency_s = time.perf_counter() - t0
        v.dyn.last_stats = stats
        v.delta_from = (
            int(getattr(version, "vid", 0)),
            ins.copy(), rem.copy(),
        )
        obs.count("dynamic.merge.applied", mode=mode)
        if reason:
            obs.count("dynamic.merge.spill", reason=reason)
        obs.observe("dynamic.merge.latency_s", stats.latency_s)
        obs.count("dynamic.merge.rows_patched", stats.rows_patched)
        obs.count("dynamic.merge.rows_rebucketed", stats.rows_rebucketed)
        obs.count("dynamic.merge.headroom_used", stats.headroom_used)
        obs.count("dynamic.merge.edges_inserted", stats.inserted)
        obs.count("dynamic.merge.edges_removed", stats.removed)
        return v

    if force_rebuild or version.host_coo is None:
        reason = "forced" if force_rebuild else "no_state"
        return _finish(
            _full_build(grid, version, new_keys, new_w, stats),
            "rebuild", reason,
        )
    if changed_struct / nnz_ref > spill_frac:
        return _finish(
            _full_build(grid, version, new_keys, new_w, stats),
            "rebuild", "threshold",
        )

    # -- incremental attempt ----------------------------------------------
    touched_keys = np.unique(np.concatenate([ins, rem, wchg]))
    new_state = MergeState(
        row=dataclasses.replace(
            state.row, keys=new_keys,
            bc=list(state.row.bc), br=list(state.row.br),
        ),
        t=(
            dataclasses.replace(
                state.t,
                bc=list(state.t.bc), br=list(state.t.br),
            )
            if state.t is not None else None
        ),
        weights=new_w, deg=new_deg, outdeg=new_outdeg,
        symmetric=state.symmetric,
    )
    try:
        r_major = touched_keys // ncols
        r_minor = touched_keys % ncols
        tiles = _dirty_tiles(new_state.row, r_major, r_minor)
        touched_row = _patch_orientation(
            new_state.row, new_keys, tiles, stats
        )
        touched_t: set = set()
        if new_state.t is not None:
            # patch the transposed sorted key set with the same
            # sorted-run passes as the row side (a full re-sort of all
            # nnz transposed keys would forfeit the incremental win on
            # directed engines)
            t_ins = np.sort(
                (ins % ncols) * np.int64(nrows) + ins // ncols
            )
            t_rem = np.sort(
                (rem % ncols) * np.int64(nrows) + rem // ncols
            )
            tk = state.t.keys
            tkeep = np.ones(len(tk), bool)
            tkeep[np.searchsorted(tk, t_rem)] = False
            tk = tk[tkeep]
            if len(t_ins):
                tk = np.insert(tk, np.searchsorted(tk, t_ins), t_ins)
            new_state.t.keys = tk
            t_dirty = np.sort(
                r_minor * np.int64(nrows) + r_major
            )
            tiles_t = _dirty_tiles(
                new_state.t, t_dirty // nrows, t_dirty % nrows
            )
            touched_t = _patch_orientation(
                new_state.t, new_state.t.keys, tiles_t, stats
            )
    except _Spill as sp:
        return _finish(
            _full_build(grid, version, new_keys, new_w, stats),
            "rebuild", sp.reason,
        )

    # PageRank values depend on OUT-DEGREES: every class holding an
    # edge in a changed column re-derives its values (structure is
    # untouched for those rows — only the bv upload).  Affected rows
    # come from ONE pass over the merged keys; class membership is
    # then a bucket-ROW scan (no slot-level work).
    touched_p = set(touched_row)
    if version.P_ell is not None:
        changed_cols = np.nonzero(new_outdeg != state.outdeg)[0]
        if len(changed_cols):
            o = new_state.row
            mask = np.isin(new_keys % np.int64(ncols), changed_cols)
            if mask.any():
                aff = new_keys[mask]
                gr_a = aff // ncols
                gc_a = aff % ncols
                hit = np.zeros(
                    (grid.pr, grid.pc, o.lr + 1), bool
                )
                hit[gr_a // o.lr, gc_a // o.lc, gr_a % o.lr] = True
                ii = np.arange(grid.pr)[:, None, None]
                jj = np.arange(grid.pc)[None, :, None]
                for b in range(len(o.kbs)):
                    if b in touched_p:
                        continue
                    brb = o.br[b]
                    if hit[ii, jj, np.minimum(brb, o.lr)].any():
                        touched_p.add(b)

    E = _assemble(
        grid, new_state.row, version.E, touched_row, _vals_ones,
        new_state, stats,
    )
    E_weighted = None
    if version.E_weighted is not None and new_w is not None:
        E_weighted = _assemble(
            grid, new_state.row, version.E_weighted, touched_row,
            _vals_weights, new_state, stats,
        )
    P_ell = dangling = None
    if version.P_ell is not None:
        P_ell = _assemble(
            grid, new_state.row, version.P_ell, touched_p,
            _vals_pagerank, new_state, stats,
        )
        old_zero = state.outdeg == 0
        new_zero = new_outdeg == 0
        if np.array_equal(old_zero, new_zero):
            dangling = version.dangling
        else:
            from ..parallel.vec import DistVec

            dangling = DistVec.from_global(
                grid, new_zero.astype(np.float32), align="col"
            )
    ET = None
    if version.ET is not None:
        ET = _assemble(
            grid, new_state.t, version.ET, touched_t, _vals_ones,
            new_state, stats,
        )
    rows = (new_keys // np.int64(ncols)).astype(np.int64)
    cols = (new_keys % np.int64(ncols)).astype(np.int64)
    new_version = GraphVersion(
        nrows=nrows, ncols=ncols, nnz=int(len(new_keys)), E=E,
        deg=new_deg, outdeg=new_outdeg, E_weighted=E_weighted,
        P_ell=P_ell, dangling=dangling, ET=ET,
        host_coo=(rows, cols, ncols),
        # BUGFIX (round 12): the lazy CSC companion is STRUCTURAL
        # (indptr + row ids, no values) — a fold that touched no edges
        # (no-op upsert batch, weight-only change) leaves it exactly
        # valid, so carry it instead of resetting to a full
        # rebuild-from-COO on next use.  Any structural change still
        # resets (None -> lazily rebuilt).  coldeg rides the same
        # argument: out-degrees are untouched when no edge moved.
        csc=(version.csc if changed_struct == 0 else None),
        coldeg=(version.coldeg if changed_struct == 0 else None),
        X=getattr(version, "X", None),
        feat_dim=int(getattr(version, "feat_dim", 0)),
        # same argument as csc/coldeg: no edge moved -> degrees are
        # bit-identical -> the cached 1/deg vector stays valid (a
        # normalized propagate engine would otherwise rebuild+upload
        # it under the execution lock on the next batch)
        invdeg=(
            getattr(version, "invdeg", None)
            if changed_struct == 0 else None
        ),
        headroom=getattr(version, "headroom", None),
    )
    new_version.host_weights = new_w
    new_version.dyn = new_state
    return _finish(new_version, "incremental")
