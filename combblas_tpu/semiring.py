"""Semirings as trace-time-specialized closures.

The reference encodes semirings as compile-time C++ functor classes
(``/root/reference/include/CombBLAS/Semirings.h:51-259``) so that one SpGEMM /
SpMV implementation serves BFS, SSSP, MIS, triangle counting, MCL, etc.  The
TPU-native analog is a frozen dataclass of jittable ``add`` / ``mul`` closures:
JAX traces them once per (semiring, shape, dtype) combination, which plays the
same role as template instantiation — zero runtime dispatch cost inside the
compiled XLA program.

``add_kind`` is a monoid hint that lets reductions ride XLA's native
scatter-add / scatter-min / scatter-max and ``psum`` / ``pmin`` / ``pmax``
collectives instead of a generic segmented scan (see ``ops/segment.py``).

The reference's ``returnedSAID()`` "do not store" sentinel protocol
(``Semirings.h:36-49``) is expressed here structurally: a ``mul`` may return
the additive identity (``zero``), which is inert under ``add`` and is
compacted away by ``SpTuples.compact`` — no sentinel flag needed because the
padded static-shape representation already carries validity.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax.numpy as jnp
import numpy as np

# Monoid kinds with an XLA-native fast path.
ADD_KINDS = ("sum", "min", "max", "generic")


def _minval(dtype) -> Any:
    dtype = jnp.dtype(dtype)
    if jnp.issubdtype(dtype, jnp.floating):
        return -jnp.inf
    if dtype == jnp.bool_:
        return False
    return np.iinfo(dtype).min


def _maxval(dtype) -> Any:
    dtype = jnp.dtype(dtype)
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.inf
    if dtype == jnp.bool_:
        return True
    return np.iinfo(dtype).max


@dataclasses.dataclass(frozen=True)
class Semiring:
    """An algebraic semiring ``(add, zero) / (mul, one)``.

    Attributes:
      name: stable identifier (used for caching / debugging).
      add: associative + commutative jittable binary op (the monoid).
      mul: jittable binary op ``mul(a_val, x_val)``; must absorb ``zero`` in
        its second argument (``mul(a, zero) == zero``) so that padded vector
        slots stay inert.
      zero_fn: dtype -> additive identity scalar.
      one_fn: dtype -> multiplicative identity scalar (may be None).
      add_kind: one of ``ADD_KINDS``; selects the XLA-native reduction path.
    """

    name: str
    add: Callable[[Any, Any], Any]
    mul: Callable[[Any, Any], Any]
    zero_fn: Callable[[Any], Any]
    one_fn: Callable[[Any], Any] | None = None
    add_kind: str = "generic"

    def zero(self, dtype) -> Any:
        return jnp.asarray(self.zero_fn(dtype), dtype=dtype)

    def one(self, dtype) -> Any:
        if self.one_fn is None:
            raise ValueError(f"semiring {self.name} has no multiplicative identity")
        return jnp.asarray(self.one_fn(dtype), dtype=dtype)

    # Semirings are static (trace-time) configuration: hash by name.
    def __hash__(self):
        return hash(("Semiring", self.name))

    def __eq__(self, other):
        return isinstance(other, Semiring) and other.name == self.name


# --- The standard semiring zoo (reference: Semirings.h) -------------------

#: Ordinary arithmetic (+, *): PageRank, BC, SpGEMM nnz structure, MCL.
#: Reference: ``PlusTimesSRing`` (Semirings.h:213).
PLUS_TIMES = Semiring(
    name="plus_times",
    add=lambda x, y: x + y,
    mul=lambda a, x: a * x,
    zero_fn=lambda dt: 0,
    one_fn=lambda dt: 1,
    add_kind="sum",
)

def _saturating_plus(a, x):
    """a + x that absorbs the MIN_PLUS identity (＋∞ / INT_MAX) exactly.

    Plain integer addition would wrap INT_MAX + w around to a huge negative
    "distance"; the reference's MinPlusSRing avoids this with an explicit
    infinity check in ``add``/``multiply`` — we do the same branch-free.
    """
    rd = jnp.result_type(a, x)
    top = _maxval(rd)
    a_ = jnp.asarray(a).astype(rd)
    x_ = jnp.asarray(x).astype(rd)
    return jnp.where((a_ >= top) | (x_ >= top), top, a_ + x_)


#: Tropical (min, +): SSSP / Bellman-Ford.
#: Reference: ``MinPlusSRing`` (Semirings.h:236).
MIN_PLUS = Semiring(
    name="min_plus",
    add=jnp.minimum,
    mul=_saturating_plus,
    zero_fn=_maxval,
    one_fn=lambda dt: 0,
    add_kind="min",
)

#: (max, *): used by Graph500 BFS in the reference (``SelectMaxSRing``,
#: Semirings.h:166): multiply returns the vector value (a parent id), add
#: picks any one — max makes it deterministic.
SELECT2ND_MAX = Semiring(
    name="select2nd_max",
    add=jnp.maximum,
    mul=lambda a, x: x,
    zero_fn=lambda dt: (
        -1 if jnp.issubdtype(jnp.dtype(dt), jnp.signedinteger) else _minval(dt)
    ),
    one_fn=None,
    add_kind="max",
)

#: (min, select2nd): FastSV / LACC connected components propagate the minimum
#: label. Reference: ``Select2ndMinSR`` (CC.h, FastSV.h usage).
SELECT2ND_MIN = Semiring(
    name="select2nd_min",
    add=jnp.minimum,
    mul=lambda a, x: x,
    zero_fn=_maxval,
    one_fn=None,
    add_kind="min",
)

#: Boolean (or, and): reachability / structure-only products.
#: Reference: ``BoolCopy2ndSRing`` / bool specializations (Semirings.h:51-142).
OR_AND = Semiring(
    name="or_and",
    add=jnp.logical_or,
    mul=jnp.logical_and,
    zero_fn=lambda dt: False,
    one_fn=lambda dt: True,
    add_kind="max",  # max == or on bool
)

#: (max, min): bottleneck / widest-path semiring.
MAX_MIN = Semiring(
    name="max_min",
    add=jnp.maximum,
    mul=jnp.minimum,
    zero_fn=_minval,
    one_fn=_maxval,
    add_kind="max",
)

STANDARD_SEMIRINGS = {
    sr.name: sr
    for sr in (PLUS_TIMES, MIN_PLUS, SELECT2ND_MAX, SELECT2ND_MIN, OR_AND, MAX_MIN)
}
