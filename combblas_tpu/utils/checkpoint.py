"""Checkpoint / resume of distributed objects (≈ SURVEY §5 checkpointing).

The reference persists whole objects only (ParallelWriteMM /
ParallelBinaryWrite / SaveGathered, SpParMat.cpp:620-714,4128; vector
ParallelWrite) and rebuilds from files. Here distributed matrices/vectors
are pytrees of sharded arrays, so checkpointing is generic:

* ``save`` / ``load``: self-describing .npz + meta (host-gathered, portable,
  no extra deps) — the ParallelBinaryWrite analog.
* ``save_orbax`` / ``load_orbax``: orbax-backed sharded checkpoint for
  async, per-device-chunked persistence of big matrices (the
  "orbax-style async checkpoint of sharded arrays" called for by SURVEY §5).
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.grid import Grid
from ..parallel.spmat import SpParMat
from ..parallel.vec import DistVec


def _meta_of(obj) -> dict:
    if isinstance(obj, SpParMat):
        return {
            "kind": "SpParMat",
            "nrows": obj.nrows,
            "ncols": obj.ncols,
            "grid": [obj.grid.pr, obj.grid.pc],
        }
    if isinstance(obj, DistVec):
        meta = {
            "kind": "DistVec",
            "length": obj.length,
            "align": obj.align,
            "grid": [obj.grid.pr, obj.grid.pc],
        }
        # Persist the padding fill so cross-grid restore can rebuild blocks
        # whose padding slots fold correctly (e.g. -1 parents, -inf maxima).
        # Only the LAST element is read (always a padding slot when padding
        # exists) — not the whole vector.
        pa, L = obj.blocks.shape
        if pa * L > obj.length:
            meta["fill"] = np.asarray(obj.blocks[-1, -1]).item()
        return meta
    raise TypeError(f"unsupported checkpoint object: {type(obj)}")


def save(path: str, obj) -> None:
    """Write a .npz checkpoint (portable across grid shapes via re-shard on
    load when the device count differs)."""
    meta = _meta_of(obj)
    arrays = (
        {
            "rows": obj.rows, "cols": obj.cols, "vals": obj.vals,
            "nnz": obj.nnz,
        }
        if meta["kind"] == "SpParMat"
        else {"blocks": obj.blocks}
    )
    np.savez_compressed(
        path,
        __meta__=np.frombuffer(json.dumps(meta).encode(), np.uint8),
        **{k: np.asarray(v) for k, v in arrays.items()},
    )


def load(path: str, grid: Grid, fill=None):
    """Load a .npz checkpoint onto ``grid``.

    Same grid shape → direct device_put of the tile arrays. Different
    shape → rebuilt from global tuples (the reference's read-back path).
    """
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
        if meta["kind"] == "SpParMat":
            pr, pc = meta["grid"]
            if (pr, pc) == (grid.pr, grid.pc):
                sh = grid.tile_sharding()
                return SpParMat(
                    rows=jax.device_put(jnp.asarray(z["rows"]), sh),
                    cols=jax.device_put(jnp.asarray(z["cols"]), sh),
                    vals=jax.device_put(jnp.asarray(z["vals"]), sh),
                    nnz=jax.device_put(jnp.asarray(z["nnz"]), sh),
                    nrows=meta["nrows"], ncols=meta["ncols"], grid=grid,
                )
            # Re-shard via global tuples (grid-shape independent).
            rows, cols, vals = _npz_to_tuples(z, meta)
            return SpParMat.from_global_coo(
                grid, rows, cols, vals, meta["nrows"], meta["ncols"]
            )
        if meta["kind"] == "DistVec":
            return _restore_vec(np.asarray(z["blocks"]), meta, grid, fill)
        raise TypeError(meta["kind"])


def _restore_vec(blocks: np.ndarray, meta: dict, grid: Grid,
                 fill_override=None) -> DistVec:
    """Rebuild a DistVec preserving padding fill values.

    Matching grid shape → the saved padded blocks are device_put verbatim
    (padding slots keep whatever fill the vector was built with — reduce()
    folds padding, so 0-filling a -1/-inf-padded vector would corrupt it).
    Different shape → rebuild from the global values with the persisted
    fill (0 only when the saved vector had no padding slot to sample).
    """
    pr, pc = meta["grid"]
    pa = pr if meta["align"] == "row" else pc
    pa_now = grid.pr if meta["align"] == "row" else grid.pc
    if pa == pa_now and blocks.shape[0] == pa_now:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.grid import COL_AXIS, ROW_AXIS

        sh = NamedSharding(
            grid.mesh, P(ROW_AXIS if meta["align"] == "row" else COL_AXIS)
        )
        return DistVec(
            blocks=jax.device_put(jnp.asarray(blocks), sh),
            length=meta["length"], align=meta["align"], grid=grid,
        )
    flat = blocks.reshape(-1)[: meta["length"]]
    fill = meta.get("fill", fill_override)
    if fill_override is not None:
        fill = fill_override
    if fill is None:
        import warnings

        warnings.warn(
            "cross-grid checkpoint restore: the saved vector had no padding "
            "slot to record its fill value; padding with 0. If the vector "
            "was built with a non-zero fill (e.g. -1 parents), pass "
            "fill=... to load()/load_orbax.",
            stacklevel=3,
        )
        fill = 0
    return DistVec.from_global(
        grid, flat, align=meta["align"],
        fill=np.asarray(fill, dtype=blocks.dtype),
    )


def _npz_to_tuples(z, meta):
    """Host: stored tile arrays → global (rows, cols, vals)."""
    pr, pc = meta["grid"]
    R, C, V, N = z["rows"], z["cols"], z["vals"], z["nnz"]
    lr = -(-meta["nrows"] // pr)
    lc = -(-meta["ncols"] // pc)
    rs, cs, vs = [], [], []
    for i in range(pr):
        for j in range(pc):
            m = R[i, j] < lr
            rs.append(R[i, j, m].astype(np.int64) + i * lr)
            cs.append(C[i, j, m].astype(np.int64) + j * lc)
            vs.append(V[i, j, m])
    return np.concatenate(rs), np.concatenate(cs), np.concatenate(vs)


# --- orbax (async, sharded) -------------------------------------------------


def save_orbax(path: str, obj) -> None:
    """Sharded async-capable checkpoint via orbax (big-matrix path).

    Saves a plain dict of the object's sharded arrays (orbax persists each
    array per-device-chunked) + a small JSON meta sidecar.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    meta = _meta_of(obj)
    state = (
        {"rows": obj.rows, "cols": obj.cols, "vals": obj.vals, "nnz": obj.nnz}
        if meta["kind"] == "SpParMat"
        else {"blocks": obj.blocks}
    )
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, state)
    ckptr.wait_until_finished()
    with open(os.path.join(path, "cbtpu_meta.json"), "w") as f:
        json.dump(meta, f)


def load_orbax(path: str, grid: Grid, fill=None):
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with open(os.path.join(path, "cbtpu_meta.json")) as f:
        meta = json.load(f)
    ckptr = ocp.StandardCheckpointer()
    state = ckptr.restore(path)
    if meta["kind"] == "SpParMat":
        sh = grid.tile_sharding()
        assert meta["grid"] == [grid.pr, grid.pc], (
            "orbax path restores onto the same grid shape; use save/load "
            "(.npz) for cross-shape restore"
        )
        return SpParMat(
            rows=jax.device_put(jnp.asarray(state["rows"]), sh),
            cols=jax.device_put(jnp.asarray(state["cols"]), sh),
            vals=jax.device_put(jnp.asarray(state["vals"]), sh),
            nnz=jax.device_put(jnp.asarray(state["nnz"]), sh),
            nrows=meta["nrows"], ncols=meta["ncols"], grid=grid,
        )
    if meta["kind"] == "DistVec":
        return _restore_vec(np.asarray(state["blocks"]), meta, grid, fill)
    raise TypeError(meta["kind"])
