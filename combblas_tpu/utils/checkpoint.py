"""Checkpoint / resume of distributed objects (≈ SURVEY §5 checkpointing).

The reference persists whole objects only (ParallelWriteMM /
ParallelBinaryWrite / SaveGathered, SpParMat.cpp:620-714,4128; vector
ParallelWrite) and rebuilds from files. Here distributed matrices/vectors
are pytrees of sharded arrays, so checkpointing is generic:

* ``save`` / ``load``: self-describing .npz + meta (host-gathered, portable,
  no extra deps) — the ParallelBinaryWrite analog.
* ``save_orbax`` / ``load_orbax``: orbax-backed sharded checkpoint for
  async, per-device-chunked persistence of big matrices (the
  "orbax-style async checkpoint of sharded arrays" called for by SURVEY §5).
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.grid import Grid
from ..parallel.spmat import SpParMat
from ..parallel.vec import DistVec


def _meta_of(obj) -> dict:
    if isinstance(obj, SpParMat):
        return {
            "kind": "SpParMat",
            "nrows": obj.nrows,
            "ncols": obj.ncols,
            "grid": [obj.grid.pr, obj.grid.pc],
        }
    if isinstance(obj, DistVec):
        meta = {
            "kind": "DistVec",
            "length": obj.length,
            "align": obj.align,
            "grid": [obj.grid.pr, obj.grid.pc],
        }
        # Persist the padding fill so cross-grid restore can rebuild blocks
        # whose padding slots fold correctly (e.g. -1 parents, -inf maxima).
        # Only the LAST element is read (always a padding slot when padding
        # exists) — not the whole vector.
        pa, L = obj.blocks.shape
        if pa * L > obj.length:
            meta["fill"] = np.asarray(obj.blocks[-1, -1]).item()
        return meta
    raise TypeError(f"unsupported checkpoint object: {type(obj)}")


def save(path: str, obj) -> None:
    """Write a .npz checkpoint (portable across grid shapes via re-shard on
    load when the device count differs)."""
    meta = _meta_of(obj)
    arrays = (
        {
            "rows": obj.rows, "cols": obj.cols, "vals": obj.vals,
            "nnz": obj.nnz,
        }
        if meta["kind"] == "SpParMat"
        else {"blocks": obj.blocks}
    )
    np.savez_compressed(
        path,
        __meta__=np.frombuffer(json.dumps(meta).encode(), np.uint8),
        **{k: np.asarray(v) for k, v in arrays.items()},
    )


def load(path: str, grid: Grid, fill=None):
    """Load a .npz checkpoint onto ``grid``.

    Same grid shape → direct device_put of the tile arrays. Different
    shape → rebuilt from global tuples (the reference's read-back path).
    """
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
        if meta["kind"] == "SpParMat":
            pr, pc = meta["grid"]
            if (pr, pc) == (grid.pr, grid.pc):
                sh = grid.tile_sharding()
                return SpParMat(
                    rows=jax.device_put(jnp.asarray(z["rows"]), sh),
                    cols=jax.device_put(jnp.asarray(z["cols"]), sh),
                    vals=jax.device_put(jnp.asarray(z["vals"]), sh),
                    nnz=jax.device_put(jnp.asarray(z["nnz"]), sh),
                    nrows=meta["nrows"], ncols=meta["ncols"], grid=grid,
                )
            # Re-shard via global tuples (grid-shape independent).
            rows, cols, vals = _npz_to_tuples(z, meta)
            return SpParMat.from_global_coo(
                grid, rows, cols, vals, meta["nrows"], meta["ncols"]
            )
        if meta["kind"] == "DistVec":
            return _restore_vec(np.asarray(z["blocks"]), meta, grid, fill)
        raise TypeError(meta["kind"])


def _restore_vec(blocks: np.ndarray, meta: dict, grid: Grid,
                 fill_override=None) -> DistVec:
    """Rebuild a DistVec preserving padding fill values.

    Matching grid shape → the saved padded blocks are device_put verbatim
    (padding slots keep whatever fill the vector was built with — reduce()
    folds padding, so 0-filling a -1/-inf-padded vector would corrupt it).
    Different shape → rebuild from the global values with the persisted
    fill (0 only when the saved vector had no padding slot to sample).
    """
    pr, pc = meta["grid"]
    pa = pr if meta["align"] == "row" else pc
    pa_now = grid.pr if meta["align"] == "row" else grid.pc
    if pa == pa_now and blocks.shape[0] == pa_now:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.grid import COL_AXIS, ROW_AXIS

        sh = NamedSharding(
            grid.mesh, P(ROW_AXIS if meta["align"] == "row" else COL_AXIS)
        )
        return DistVec(
            blocks=jax.device_put(jnp.asarray(blocks), sh),
            length=meta["length"], align=meta["align"], grid=grid,
        )
    flat = blocks.reshape(-1)[: meta["length"]]
    fill = meta.get("fill", fill_override)
    if fill_override is not None:
        fill = fill_override
    if fill is None:
        import warnings

        warnings.warn(
            "cross-grid checkpoint restore: the saved vector had no padding "
            "slot to record its fill value; padding with 0. If the vector "
            "was built with a non-zero fill (e.g. -1 parents), pass "
            "fill=... to load()/load_orbax.",
            stacklevel=3,
        )
        fill = 0
    return DistVec.from_global(
        grid, flat, align=meta["align"],
        fill=np.asarray(fill, dtype=blocks.dtype),
    )


def _npz_to_tuples(z, meta):
    """Host: stored tile arrays → global (rows, cols, vals)."""
    pr, pc = meta["grid"]
    R, C, V, N = z["rows"], z["cols"], z["vals"], z["nnz"]
    lr = -(-meta["nrows"] // pr)
    lc = -(-meta["ncols"] // pc)
    rs, cs, vs = [], [], []
    for i in range(pr):
        for j in range(pc):
            m = R[i, j] < lr
            rs.append(R[i, j, m].astype(np.int64) + i * lr)
            cs.append(C[i, j, m].astype(np.int64) + j * lc)
            vs.append(V[i, j, m])
    return np.concatenate(rs), np.concatenate(cs), np.concatenate(vs)


# --- GraphVersion snapshots (round 14 — the serving fleet's warm start) ----

#: Schema tag of ``save_version`` snapshots; a mismatched tag is
#: refused at load (never guessed at — the plan-store convention).
VERSION_SCHEMA = "combblas_tpu.graph_version/v1"

#: The EllParMat fields of a GraphVersion, in a fixed serialization
#: order (absent twins are recorded as null bucket counts).
_VERSION_MATS = ("E", "E_weighted", "P_ell", "ET")


class SnapshotError(ValueError):
    """A snapshot that must not be loaded: corrupt, truncated, wrong
    schema, or wrong grid.  The message names the file — and
    ``load_latest_version`` treats any instance as "fall back to the
    previous retained snapshot" (round 16)."""


def snapshot_name(wal_seq: int) -> str:
    """Canonical snapshot file name for a version at WAL frontier
    ``wal_seq``: zero-padded so lexicographic order IS recovery order
    (``wal_seq`` is a global lineage — monotone across recoveries,
    unlike per-engine version ids)."""
    return f"ckpt-{int(wal_seq) + 1:012d}.npz"


def snapshot_seq(path: str) -> int:
    """The ``wal_seq`` stamp encoded in a snapshot's file name (the
    inverse of ``snapshot_name``; no file read)."""
    name = os.path.basename(path)
    return int(name[len("ckpt-"):-len(".npz")]) - 1


def list_snapshots(dirpath: str) -> list[str]:
    """Retained ``save_version`` snapshots in ``dirpath``, OLDEST
    first (the retention pruner drops a prefix; recovery walks the
    reverse)."""
    try:
        names = os.listdir(dirpath)
    except OSError:
        return []
    return sorted(
        os.path.join(dirpath, nm) for nm in names
        # a sibling process's in-flight atomic write (``*.npz.tmp``)
        # is not a snapshot — never list it as a candidate (round 17:
        # multi-process fleets checkpoint concurrently)
        if nm.startswith("ckpt-") and nm.endswith(".npz")
        and ".tmp" not in nm
    )


def load_latest_version(dirpath: str, grid, *, writable: bool = True):
    """The newest LOADABLE snapshot in ``dirpath`` as ``(version,
    path)`` — a corrupt/truncated newest file (the crash-mid-write
    artifact atomic replace makes rare, or disk damage) falls back to
    the previous retained snapshot with a warning naming the bad file.

    Concurrent-sibling tolerance (round 17, the process fleet): a
    file that VANISHES between listing and open (a sibling's
    retention pruner unlinked it, or its ``os.replace`` superseded
    it) is not corruption — it is skipped silently, and if nothing in
    the stale listing loads the directory is re-listed ONCE (the
    sibling that pruned our candidate also wrote a newer one).
    Raises ``dynamic.wal.RecoveryError`` when no candidate loads."""
    import warnings

    candidates = []
    errors = []
    for attempt in (0, 1):
        candidates = list_snapshots(dirpath)
        vanished = 0
        for path in reversed(candidates):
            try:
                return load_version(path, grid, writable=writable), path
            except FileNotFoundError:
                # pruned/replaced under us: never a SnapshotError —
                # no rejected-counter, no warning, just the next
                # candidate (and one fresh listing below)
                vanished += 1
                continue
            except SnapshotError as e:
                errors.append(str(e))
                from .. import obs

                obs.count("serve.recovery.snapshot_rejected")
                warnings.warn(
                    f"skipping unloadable snapshot (falling back to "
                    f"the previous retained one): {e}",
                    stacklevel=2,
                )
        if vanished == 0:
            break  # a re-list cannot surface anything new
    from ..dynamic.wal import RecoveryError

    raise RecoveryError(
        f"no loadable GraphVersion snapshot in {dirpath!r} "
        f"({len(candidates)} candidate(s)"
        + (f"; errors: {errors}" if errors else "")
        + ")"
    )


def save_version(path: str, version, *, extra_meta: dict | None = None) -> None:
    """Snapshot a serve ``GraphVersion`` to one self-describing .npz —
    the warm-start half of the replicated fleet (docs/serving.md
    "Multi-tenant pool & fleet").

    What makes this different from re-running ``from_coo`` on the
    replica: the BUCKET ARRAYS are persisted exactly as built —
    per-class cols/vals/rowids including the headroom-resolved padding
    rows — so ``load_version`` re-uploads bit-identical shapes with
    ``EllParMat.from_host_buckets`` (one ``device_put`` per array, no
    dedup sort, no host bucket pass) and a warmed plan cache keeps
    every compiled executable: ZERO retraces after ``swap()``, the
    regression-tested guarantee.  The host COO/weights ride along when
    the version retained them (``keep_coo=True``), so a restored
    replica can still serve the write lane.

    Round 16 (durability): the write is ATOMIC — the .npz lands in a
    sibling tmp file and ``os.replace``s into place, so a crash
    mid-save leaves the previous snapshot intact, never a truncated
    one under the real name — and the version's WAL position
    (``version.wal_seq``) is stamped into the meta: recovery replays
    exactly the log suffix this snapshot does not already contain.

    ``extra_meta`` (round 20, sharded serving): an arbitrary
    JSON-able dict stored under ``meta["extra"]`` and surfaced as
    ``version.extra_meta`` on load — slab snapshots use it to be
    SELF-DESCRIBING (``{"shard": {idx, row0, row1, ...}}``), so
    slice recovery needs only the slice's home directory, never the
    service manifest.
    """
    import time

    from .. import obs

    t0 = time.perf_counter()
    meta = {
        "kind": "GraphVersion",
        "v": VERSION_SCHEMA,
        "nrows": int(version.nrows),
        "ncols": int(version.ncols),
        "nnz": int(version.nnz),
        "feat_dim": int(version.feat_dim),
        "headroom": version.headroom,
        "wal_seq": int(getattr(version, "wal_seq", -1)),
        "grid": [version.E.grid.pr, version.E.grid.pc],
        "mats": {},
    }
    if extra_meta is not None:
        meta["extra"] = extra_meta
    arrays: dict = {
        "deg": np.asarray(version.deg),
    }
    if version.outdeg is not None:
        arrays["outdeg"] = np.asarray(version.outdeg)
    for nm in _VERSION_MATS:
        M = getattr(version, nm)
        if M is None:
            meta["mats"][nm] = None
            continue
        meta["mats"][nm] = {
            "nbuckets": len(M.buckets),
            "nrows": int(M.nrows),
            "ncols": int(M.ncols),
        }
        for i, (bc, bv, br) in enumerate(M.buckets):
            arrays[f"{nm}.{i}.c"] = np.asarray(jax.device_get(bc))
            arrays[f"{nm}.{i}.v"] = np.asarray(jax.device_get(bv))
            arrays[f"{nm}.{i}.r"] = np.asarray(jax.device_get(br))
    if version.dangling is not None:
        arrays["dangling"] = np.asarray(
            jax.device_get(version.dangling.blocks)
        )
    if version.X is not None:
        arrays["X"] = np.asarray(jax.device_get(version.X.blocks))
    if version.host_coo is not None:
        rows, cols, _nc = version.host_coo
        arrays["coo_rows"] = np.asarray(rows)
        arrays["coo_cols"] = np.asarray(cols)
        if version.host_weights is not None:
            arrays["coo_weights"] = np.asarray(version.host_weights)
    # atomic: write a sibling tmp (same filesystem — os.replace must
    # not cross devices) through a FILE OBJECT so np.savez cannot
    # append its own .npz suffix, fsync, then replace into place
    tmp = path + ".tmp"
    try:
        with open(tmp, "wb") as f:
            np.savez_compressed(
                f,
                __meta__=np.frombuffer(
                    json.dumps(meta).encode(), np.uint8
                ),
                **arrays,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    obs.observe("serve.checkpoint.save_s", time.perf_counter() - t0)


def load_version(path: str, grid: Grid, *, writable: bool = True):
    """Restore a ``save_version`` snapshot onto ``grid`` as a
    ``GraphVersion`` ready for ``GraphEngine(grid, version=...)`` or
    ``engine.swap()``.

    ``writable=False`` skips retaining the host bucket arrays the
    lazy merge-state derivation needs (round 16): a READ-ONLY replica
    loading a shared snapshot must not pin an O(nnz) host copy of the
    graph structure it will never merge into — only the write-lane
    owner (the fleet's home) loads writable.

    Same grid shape ONLY (the fleet's replicas share one mesh layout;
    cross-shape restore would re-bucket and forfeit the bit-identical
    shapes the zero-retrace guarantee rests on — rebuild from COO for
    that).  Uploads are one ``device_put`` per persisted array.

    A corrupt or truncated file is REFUSED with a ``SnapshotError``
    naming it (round 16) — never half-loaded; ``load_latest_version``
    turns that refusal into a fallback to the previous retained
    snapshot.
    """
    try:
        return _load_version(path, grid, writable)
    except SnapshotError:
        raise  # already diagnostic (schema / grid mismatch)
    except FileNotFoundError:
        # the file vanished between listing and open (a sibling's
        # pruner or os.replace) — NOT corruption: propagate so
        # load_latest_version retries over a fresh listing instead
        # of mis-counting a spurious SnapshotError
        raise
    except Exception as e:
        raise SnapshotError(
            f"refusing corrupt or truncated GraphVersion snapshot "
            f"{path!r}: {type(e).__name__}: {e}"
        ) from e


def _load_version(path: str, grid: Grid, writable: bool = True):
    import time

    from jax.sharding import NamedSharding, PartitionSpec as P

    from .. import obs
    from ..parallel.ellmat import EllParMat
    from ..parallel.grid import COL_AXIS, ROW_AXIS
    from ..parallel.vec import DistMultiVec
    from ..serve.engine import GraphVersion

    t0 = time.perf_counter()
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta__"]).decode())
        if meta.get("v") != VERSION_SCHEMA:
            raise SnapshotError(
                f"{path!r} is not a GraphVersion snapshot (schema "
                f"{meta.get('v')!r} != {VERSION_SCHEMA!r})"
            )
        pr, pc = meta["grid"]
        if (pr, pc) != (grid.pr, grid.pc):
            raise SnapshotError(
                f"snapshot was taken on a {pr}x{pc} grid; load_version "
                f"restores onto the SAME grid shape (got {grid.pr}x"
                f"{grid.pc}) — rebuild from COO to re-shard"
            )
        mats = {}
        host_mats = {}  # host (bc, bv, br) triples: the merge-state
        #                 derivation below needs them pre-upload
        for nm in _VERSION_MATS:
            info = meta["mats"].get(nm)
            if info is None:
                mats[nm] = None
                continue
            host_buckets = [
                (
                    z[f"{nm}.{i}.c"], z[f"{nm}.{i}.v"], z[f"{nm}.{i}.r"],
                )
                for i in range(info["nbuckets"])
            ]
            host_mats[nm] = host_buckets
            mats[nm] = EllParMat.from_host_buckets(
                grid, host_buckets, info["nrows"], info["ncols"]
            )
        dangling = None
        if "dangling" in z:
            dangling = DistVec(
                blocks=jax.device_put(
                    jnp.asarray(z["dangling"]),
                    NamedSharding(grid.mesh, P(COL_AXIS)),
                ),
                length=meta["ncols"], align="col", grid=grid,
            )
        X = None
        if "X" in z:
            X = DistMultiVec(
                blocks=jax.device_put(
                    jnp.asarray(z["X"]),
                    NamedSharding(grid.mesh, P(ROW_AXIS)),
                ),
                length=meta["ncols"], align="row", grid=grid,
            )
        host_coo = None
        host_weights = None
        if "coo_rows" in z:
            host_coo = (
                np.asarray(z["coo_rows"]), np.asarray(z["coo_cols"]),
                meta["ncols"],
            )
            if "coo_weights" in z:
                host_weights = np.asarray(z["coo_weights"])
        version = GraphVersion(
            nrows=meta["nrows"], ncols=meta["ncols"], nnz=meta["nnz"],
            E=mats["E"],
            deg=np.asarray(z["deg"]),
            outdeg=(
                np.asarray(z["outdeg"]) if "outdeg" in z else None
            ),
            E_weighted=mats["E_weighted"],
            P_ell=mats["P_ell"],
            dangling=dangling,
            ET=mats["ET"],
            host_coo=host_coo,
            host_weights=host_weights,
            X=X,
            feat_dim=meta["feat_dim"],
            headroom=meta["headroom"],
            wal_seq=int(meta.get("wal_seq", -1)),
        )
        # self-description channel (round 20): slab snapshots carry a
        # shard descriptor here; absent for whole-graph snapshots
        version.extra_meta = meta.get("extra")
        if host_coo is not None and writable:
            # round 16: the merge state must describe the RESTORED
            # bucket layout, sticky slots included — a later
            # apply_delta that bootstrapped a fresh host_build from
            # the COO would patch against the wrong slot map and
            # corrupt the graph (snapshots of incrementally merged
            # versions drift from fresh builds by design).  Derived
            # LAZILY (apply_delta consumes ``dyn_source`` on the
            # first merge): read-only replicas loading the same
            # snapshot must not each pay the O(nnz log nnz) key sort
            # and bucket copies — only the write-lane owner merges.
            e_buckets = host_mats["E"]
            t_buckets = host_mats.get("ET")
            deg_host = np.asarray(z["deg"])
            outdeg_host = (
                np.asarray(z["outdeg"]) if "outdeg" in z else None
            )

            def _dyn_source():
                from ..dynamic.merge import state_from_host_buckets

                return state_from_host_buckets(
                    grid, e_buckets, t_buckets, host_coo,
                    host_weights, deg_host, outdeg_host,
                )

            version.dyn_source = _dyn_source
    obs.observe("serve.checkpoint.load_s", time.perf_counter() - t0)
    return version


# --- orbax (async, sharded) -------------------------------------------------


def save_orbax(path: str, obj) -> None:
    """Sharded async-capable checkpoint via orbax (big-matrix path).

    Saves a plain dict of the object's sharded arrays (orbax persists each
    array per-device-chunked) + a small JSON meta sidecar.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    meta = _meta_of(obj)
    state = (
        {"rows": obj.rows, "cols": obj.cols, "vals": obj.vals, "nnz": obj.nnz}
        if meta["kind"] == "SpParMat"
        else {"blocks": obj.blocks}
    )
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, state)
    ckptr.wait_until_finished()
    with open(os.path.join(path, "cbtpu_meta.json"), "w") as f:
        json.dump(meta, f)


def load_orbax(path: str, grid: Grid, fill=None):
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with open(os.path.join(path, "cbtpu_meta.json")) as f:
        meta = json.load(f)
    ckptr = ocp.StandardCheckpointer()
    state = ckptr.restore(path)
    if meta["kind"] == "SpParMat":
        sh = grid.tile_sharding()
        assert meta["grid"] == [grid.pr, grid.pc], (
            "orbax path restores onto the same grid shape; use save/load "
            "(.npz) for cross-shape restore"
        )
        return SpParMat(
            rows=jax.device_put(jnp.asarray(state["rows"]), sh),
            cols=jax.device_put(jnp.asarray(state["cols"]), sh),
            vals=jax.device_put(jnp.asarray(state["vals"]), sh),
            nnz=jax.device_put(jnp.asarray(state["nnz"]), sh),
            nrows=meta["nrows"], ncols=meta["ncols"], grid=grid,
        )
    if meta["kind"] == "DistVec":
        return _restore_vec(np.asarray(state["blocks"]), meta, grid, fill)
    raise TypeError(meta["kind"])
