"""Deterministic Graph500 v2.1 R-MAT generator (≈ RefGen21.h:88-323).

Bit-identical reimplementation of the reference's ``packed=true`` generator
path (``include/CombBLAS/RefGen21.h`` wrapping the vendored graph500-1.2
generator): the L'Ecuyer 5-term multiple recursive generator (MRG) over
Z_{2^31-1} with leapfrog skip matrices, the 4-way Bernoulli square picker
(a=0.57, b=c=0.19 as integer fractions), clip-and-flip, and the two-round
multiplicative bit-reverse vertex scramble.

Everything is vectorized numpy over edges in exact uint64 integer
arithmetic — products of Z_{2^31-1} residues stay below 2^62, so plain
``uint64`` multiplication is exact; the 2^64 wraparound of the scramble's
multiplies is numpy's native uint64 behavior (matching C).

The skip table (A^(256^byte * k) for byte < 24, k < 256 — the reference's
generated ``mrg_transitions.c``) is recomputed here from the transition
algebra at first use and cached in-process; identical by construction
(verified by the golden-edge test against output of the reference
generator, tests/test_refgen21.py).

Edge semantics match ``RefGen21::make_graph`` (RefGen21.h:246-283): edge
``ei`` of ``M`` total is generated from state ``skip(seeded, 0, ei, 0)``,
so any sub-range [start, end) of the global stream can be produced on any
host/device independently — the same property the MPI code exploits, and
what makes multi-host generation embarrassingly parallel here.
"""

from __future__ import annotations

import numpy as np

_P = np.uint64(0x7FFFFFFF)  # 2^31 - 1
_X = np.uint64(107374182)
_Y = np.uint64(104480)
_A_NUM = 5700
_BC_NUM = 1900
_DENOM = 10000
_REJECT_LIMIT = np.uint64(0xFFFFFFFF % _DENOM)


def _mod(a):
    return a % _P


def _mod_mul(a, b):
    return (a * b) % _P  # operands < 2^31, product < 2^62: exact in uint64


def _mat_cache(m):
    """m: dict with s,t,u,v,w → adds a,b,c,d (the Toeplitz completion)."""
    m = dict(m)
    m["a"] = _mod(_X * m["s"] + m["t"])
    m["b"] = _mod(_X * m["a"] + m["u"])
    m["c"] = _mod(_X * m["b"] + m["v"])
    m["d"] = _mod(_X * m["c"] + m["w"])
    return m


def _mat_identity():
    z = np.uint64(0)
    return _mat_cache({"s": z, "t": z, "u": z, "v": z, "w": np.uint64(1)})


def _mat_A():
    z = np.uint64(0)
    return _mat_cache({"s": z, "t": z, "u": z, "v": np.uint64(1), "w": z})


def _mat_mul(m, n):
    """Transition-matrix product in the 5-parameter representation
    (splittable_mrg.c:85-100)."""
    y = _Y
    s = _mod(
        _mod_mul(m["s"], n["d"]) + _mod_mul(m["t"], n["c"])
        + _mod_mul(m["u"], n["b"]) + _mod_mul(m["v"], n["a"])
        + _mod_mul(m["w"], n["s"])
    )
    t = _mod(
        _mod_mul(_mod_mul(m["s"], n["s"]), y) + _mod_mul(m["t"], n["w"])
        + _mod_mul(m["u"], n["v"]) + _mod_mul(m["v"], n["u"])
        + _mod_mul(m["w"], n["t"])
    )
    u = _mod(
        _mod_mul(_mod(_mod_mul(m["s"], n["a"]) + _mod_mul(m["t"], n["s"])), y)
        + _mod_mul(m["u"], n["w"]) + _mod_mul(m["v"], n["v"])
        + _mod_mul(m["w"], n["u"])
    )
    v = _mod(
        _mod_mul(
            _mod(
                _mod_mul(m["s"], n["b"]) + _mod_mul(m["t"], n["a"])
                + _mod_mul(m["u"], n["s"])
            ),
            y,
        )
        + _mod_mul(m["v"], n["w"]) + _mod_mul(m["w"], n["v"])
    )
    w = _mod(
        _mod_mul(
            _mod(
                _mod_mul(m["s"], n["c"]) + _mod_mul(m["t"], n["b"])
                + _mod_mul(m["u"], n["a"]) + _mod_mul(m["v"], n["s"])
            ),
            y,
        )
        + _mod_mul(m["w"], n["w"])
    )
    return _mat_cache({"s": s, "t": t, "u": u, "v": v, "w": w})


_SKIP_TABLE = None  # [24, 256, 9] uint64, lazily built


def _mat_to_row(m):
    return [m[k] for k in ("s", "t", "u", "v", "w", "a", "b", "c", "d")]


def skip_table() -> np.ndarray:
    """A^(256^i * j) for i < 24, j < 256 — [24, 256, 9] uint64.

    Recomputes the reference's generated mrg_transitions.c table from the
    transition algebra (dump_mrg_powers, splittable_mrg.c:238-260):
    row i, col j is A^(256^i)^j, built by cumulative products.
    """
    global _SKIP_TABLE
    if _SKIP_TABLE is not None:
        return _SKIP_TABLE
    table = np.zeros((24, 256, 9), np.uint64)
    base = _mat_A()
    for i in range(24):
        cur = _mat_identity()
        table[i, 0] = _mat_to_row(cur)
        for j in range(1, 256):
            cur = _mat_mul(cur, base)
            table[i, j] = _mat_to_row(cur)
        # next byte level: base = base^256 = (cur = base^255) * base
        base = _mat_mul(cur, base)
    _SKIP_TABLE = table
    return table


def make_mrg_seed(userseed1: int, userseed2: int) -> np.ndarray:
    """utils.c:83-89 — spread two 64-bit seeds into five MRG residues."""
    u1, u2 = np.uint64(userseed1), np.uint64(userseed2)
    return np.array(
        [
            (u1 & np.uint64(0x3FFFFFFF)) + np.uint64(1),
            ((u1 >> np.uint64(30)) & np.uint64(0x3FFFFFFF)) + np.uint64(1),
            (u2 & np.uint64(0x3FFFFFFF)) + np.uint64(1),
            ((u2 >> np.uint64(30)) & np.uint64(0x3FFFFFFF)) + np.uint64(1),
            ((u2 >> np.uint64(60)) << np.uint64(4))
            + (u1 >> np.uint64(60)) + np.uint64(1),
        ],
        np.uint64,
    )


def _apply_transition(mat, z):
    """mrg_apply_transition (splittable_mrg.c:121-168), vectorized.

    mat: [..., 9] uint64 rows (s,t,u,v,w,a,b,c,d); z: [..., 5] states.
    """
    s, t, u, v, w, a, b, c, d = (mat[..., k] for k in range(9))
    z1, z2, z3, z4, z5 = (z[..., k] for k in range(5))
    y = _Y

    def mac(*pairs):
        acc = np.zeros_like(z1)
        for p, q in pairs:
            acc = _mod(acc + _mod_mul(p, q))
        return acc

    o1 = _mod(
        _mod_mul(d, z1)
        + _mod_mul(mac((s, z2), (a, z3), (b, z4), (c, z5)), y)
    )
    o2 = _mod(
        mac((c, z1), (w, z2)) + _mod_mul(mac((s, z3), (a, z4), (b, z5)), y)
    )
    o3 = _mod(
        mac((b, z1), (v, z2), (w, z3))
        + _mod_mul(mac((s, z4), (a, z5)), y)
    )
    o4 = _mod(
        mac((a, z1), (u, z2), (v, z3), (w, z4)) + _mod_mul(_mod_mul(s, z5), y)
    )
    o5 = mac((s, z1), (t, z2), (u, z3), (v, z4), (w, z5))
    return np.stack([o1, o2, o3, o4, o5], axis=-1)


def _skip(z, high: int, middle, low: int):
    """mrg_skip (splittable_mrg.c:190-206): advance by the 192-bit count
    high·2^128 + middle·2^64 + low. ``middle`` may be a vector (per-edge
    stream offsets); the per-byte matrices come from the skip table."""
    tab = skip_table()
    middle = np.asarray(middle, np.uint64)
    scalarish = middle.ndim == 0
    if scalarish:
        middle = middle[None]
        z = z[None]
    for byte_index in range(8):
        val = (np.uint64(low) >> np.uint64(8 * byte_index)) & np.uint64(0xFF)
        if val:
            z = _apply_transition(tab[byte_index, int(val)], z)
    for byte_index in range(8):
        vals = (middle >> np.uint64(8 * byte_index)) & np.uint64(0xFF)
        if np.any(vals):
            z = _apply_transition(tab[8 + byte_index][vals], z)
    for byte_index in range(8):
        val = (np.uint64(high) >> np.uint64(8 * byte_index)) & np.uint64(0xFF)
        if val:
            z = _apply_transition(tab[16 + byte_index, int(val)], z)
    return z[0] if scalarish else z


def _get_uint_orig(z):
    """mrg_orig_step + return z1 (vectorized, in place semantics)."""
    new_elt = _mod(_mod_mul(_X, z[..., 0]) + _mod_mul(_Y, z[..., 4]))
    z = np.concatenate([new_elt[..., None], z[..., :4]], axis=-1)
    return new_elt, z


def _bitreverse64(x):
    """RefGen21::bitreverse (RefGen21.h:135-180), 64-bit path."""
    x = (x >> np.uint64(32)) | (x << np.uint64(32))
    m = np.uint64(0x0000FFFF0000FFFF)
    x = ((x >> np.uint64(16)) & m) | ((x & m) << np.uint64(16))
    m = np.uint64(0x00FF00FF00FF00FF)
    x = ((x >> np.uint64(8)) & m) | ((x & m) << np.uint64(8))
    m = np.uint64(0x0F0F0F0F0F0F0F0F)
    x = ((x >> np.uint64(4)) & m) | ((x & m) << np.uint64(4))
    m = np.uint64(0x3333333333333333)
    x = ((x >> np.uint64(2)) & m) | ((x & m) << np.uint64(2))
    m = np.uint64(0x5555555555555555)
    x = ((x >> np.uint64(1)) & m) | ((x & m) << np.uint64(1))
    return x


def _scramble(v, lgN: int, val0, val1):
    """RefGen21::scramble (RefGen21.h:184-196)."""
    v = v.astype(np.uint64)
    with np.errstate(over="ignore"):
        v = v + (val0 + val1)
        v = v * (val0 | np.uint64(0x4519840211493211))
        v = _bitreverse64(v) >> np.uint64(64 - lgN)
        v = v * (val1 | np.uint64(0x3050852102C843A5))
        v = _bitreverse64(v) >> np.uint64(64 - lgN)
    return v.astype(np.int64)


def _bernoulli4(z):
    """generate_4way_bernoulli (RefGen21.h:103-131), vectorized with exact
    rejection semantics: redraw while raw < (2^32 - 1) % 10000 = 7295 —
    the reference's UINT32_C(0xFFFFFFFF) % INITIATOR_DENOMINATOR, NOT
    2^32 % 10000; changing this constant silently breaks bit fidelity."""
    val, z = _get_uint_orig(z)
    pending = val < _REJECT_LIMIT
    while np.any(pending):
        redraw, z2 = _get_uint_orig(z[pending])
        # only the pending lanes advance their state
        znew = z.copy()
        znew[pending] = z2
        z = znew
        vnew = val.copy()
        vnew[pending] = redraw
        val = vnew
        pending = val < _REJECT_LIMIT
    val = val % np.uint64(_DENOM)
    sq = np.full(val.shape, 3, np.int64)
    v = val.astype(np.int64)
    sq = np.where(v < _BC_NUM, 1, sq)
    v2 = v - _BC_NUM
    sq = np.where((v >= _BC_NUM) & (v2 < _BC_NUM), 2, sq)
    v3 = v2 - _BC_NUM
    sq = np.where((v2 >= _BC_NUM) & (v3 < _A_NUM), 0, sq)
    return sq, z


def generate_kronecker_range(
    seed5: np.ndarray, logN: int, start_edge: int, end_edge: int
) -> tuple[np.ndarray, np.ndarray]:
    """RefGen21::generate_kronecker_range (RefGen21.h:246-263):
    edges [start_edge, end_edge) of the global deterministic stream.
    Returns (src, dst) int64 arrays of length end_edge - start_edge.
    """
    nverts = np.int64(1) << np.int64(logN)
    state = seed5.astype(np.uint64)

    # MakeScrambleValues (RefGen21.h:228-241)
    zs = _skip(state.copy(), 50, 7, 0)
    v0a, zs = _get_uint_orig(zs)
    v0b, zs = _get_uint_orig(zs)
    v1a, zs = _get_uint_orig(zs)
    v1b, zs = _get_uint_orig(zs)
    with np.errstate(over="ignore"):
        val0 = v0a * np.uint64(0xFFFFFFFF) + v0b
        val1 = v1a * np.uint64(0xFFFFFFFF) + v1b

    ei = np.arange(start_edge, end_edge, dtype=np.uint64)
    E = len(ei)
    z = np.broadcast_to(state, (E, 5)).copy()
    z = _skip(z, 0, ei, 0)

    base_src = np.zeros(E, np.int64)
    base_tgt = np.zeros(E, np.int64)
    nv = np.int64(nverts)
    for _level in range(logN):
        sq, z = _bernoulli4(z)
        src_offset = sq // 2
        tgt_offset = sq % 2
        # clip-and-flip for undirected graphs (make_one_edge)
        flip = (base_src == base_tgt) & (src_offset > tgt_offset)
        src_offset, tgt_offset = (
            np.where(flip, tgt_offset, src_offset),
            np.where(flip, src_offset, tgt_offset),
        )
        nv = nv // 2
        base_src = base_src + nv * src_offset
        base_tgt = base_tgt + nv * tgt_offset

    return (
        _scramble(base_src, logN, val0, val1),
        _scramble(base_tgt, logN, val0, val1),
    )


def graph500_edges(
    scale: int,
    nedges: int | None = None,
    userseed: int = 0xDECAFBAD,
    edgefactor: int = 16,
    start_edge: int = 0,
    end_edge: int | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """The DistEdgeList::GenGraph500Data packed path
    (``DistEdgeList.cpp:223-330`` via RefGen21::make_graph): deterministic
    edge list for a scale-``scale`` Kronecker graph.

    ``userseed`` defaults to the reference's fallback constant
    (``init_random``, RefGen21.h:305-316: 0xDECAFBAD when no SEED env);
    pass 0 for the reference's ``-DDETERMINISTIC`` builds
    (TopDownBFS.cpp:29). Any [start_edge, end_edge) sub-range of the
    stream can be generated independently (multi-host sharding).
    """
    if nedges is None:
        nedges = edgefactor << scale
    if end_edge is None:
        end_edge = nedges
    seed5 = make_mrg_seed(userseed, userseed)
    return generate_kronecker_range(seed5, scale, start_edge, end_edge)


# --- native (C++) fast path -------------------------------------------------
#
# The reference's generator is native C; io/native/graphgen.cpp is this
# module's native twin (same MRG/skip/scramble stream, threaded over
# edges). graph500_edges_native builds it on demand and falls back to the
# numpy implementation when no toolchain is available.

_NATIVE_LIB = None
_NATIVE_FAILED = False
_NATIVE_LOCK = None


def _load_native():
    global _NATIVE_LIB, _NATIVE_FAILED, _NATIVE_LOCK
    if _NATIVE_LIB is not None or _NATIVE_FAILED:
        return _NATIVE_LIB
    import ctypes
    import os
    import subprocess
    import threading

    if _NATIVE_LOCK is None:
        _NATIVE_LOCK = threading.Lock()
    with _NATIVE_LOCK:
        if _NATIVE_LIB is not None or _NATIVE_FAILED:
            return _NATIVE_LIB
        return _load_native_locked(ctypes, os, subprocess)


def _load_native_locked(ctypes, os, subprocess):
    """Build+load under _NATIVE_LOCK (concurrent first calls must not race
    the g++ build of the .so — same discipline as io/mm._load_native)."""
    global _NATIVE_LIB, _NATIVE_FAILED
    ndir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "io", "native",
    )
    src = os.path.join(ndir, "graphgen.cpp")
    so = os.path.join(ndir, "libgraphgen.so")
    try:
        if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
            subprocess.run(
                ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                 "-pthread", src, "-o", so],
                check=True, capture_output=True,
            )
        lib = ctypes.CDLL(so)
        lib.cbtpu_graph500_edges.restype = ctypes.c_int
        lib.cbtpu_graph500_edges.argtypes = [
            ctypes.c_uint64, ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int,
        ]
        _NATIVE_LIB = lib
    except Exception:
        _NATIVE_FAILED = True
    return _NATIVE_LIB  # noqa: returned under the caller's lock


def graph500_edges_native(
    scale: int,
    nedges: int | None = None,
    userseed: int = 0xDECAFBAD,
    edgefactor: int = 16,
    start_edge: int = 0,
    end_edge: int | None = None,
    nthreads: int | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """``graph500_edges`` through the native generator (bit-identical;
    threaded C++). Falls back to the numpy path without a toolchain."""
    import ctypes
    import os

    if nedges is None:
        nedges = edgefactor << scale
    if end_edge is None:
        end_edge = nedges
    lib = _load_native()
    if lib is None:
        return graph500_edges(
            scale, nedges, userseed, edgefactor, start_edge, end_edge
        )
    m = end_edge - start_edge
    src = np.empty(m, np.int64)
    dst = np.empty(m, np.int64)
    if nthreads is None:
        nthreads = min(os.cpu_count() or 1, 16)
    rc = lib.cbtpu_graph500_edges(
        ctypes.c_uint64(userseed), scale, start_edge, end_edge,
        src.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        nthreads,
    )
    if rc != 0:
        raise ValueError(f"native generator failed (rc={rc})")
    return src, dst
