"""Phase timers + profiler hooks (≈ the reference's TIMING subsystem).

The reference accumulates global per-phase wall times inside kernels under
``#ifdef TIMING`` (``CombBLAS.h:77-102``: cblas_alltoalltime /
allgathertime / localspmvtime / mergeconttime / transvectime, plus the
mcl_* family) and prints them per app (``TopDownBFS.cpp:472-479``). Under
XLA, phases inside one compiled program can't be host-timed — the analog
is (a) named host-side phase accumulation around jitted calls (this module)
and (b) ``jax.profiler`` traces with named annotations for on-device
timelines (``trace`` / ``annotate`` below; view in TensorBoard/Perfetto).
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax

_ACC: dict[str, float] = defaultdict(float)
_COUNT: dict[str, int] = defaultdict(int)
ENABLED = True


@contextlib.contextmanager
def phase(name: str, *, sync=None):
    """Accumulate wall time under ``name`` (≈ one cblas_* counter).

    ``sync``: optional array/pytree to ``block_until_ready`` before closing
    the timer, so async dispatch doesn't hide device time.
    """
    if not ENABLED:
        yield
        return
    with jax.profiler.TraceAnnotation(name):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if sync is not None:
                jax.block_until_ready(sync)
            _ACC[name] += time.perf_counter() - t0
            _COUNT[name] += 1


def get(name: str) -> float:
    return _ACC.get(name, 0.0)


def report(reset: bool = False) -> dict[str, tuple[float, int]]:
    """{name: (seconds, calls)} — the per-app timing table the reference
    prints after each run."""
    out = {k: (_ACC[k], _COUNT[k]) for k in sorted(_ACC)}
    if reset:
        reset_all()
    return out


def reset_all():
    _ACC.clear()
    _COUNT.clear()


def print_report(reset: bool = False):
    for k, (sec, n) in report(reset=reset).items():
        print(f"{k:32s} {sec:10.4f}s  x{n}")


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a jax.profiler device trace for the enclosed block
    (TensorBoard/Perfetto — the PAPI/MPI_Pcontrol analog)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


annotate = jax.profiler.TraceAnnotation
