"""Phase timers — COMPATIBILITY SHIM over ``combblas_tpu.obs``.

This module used to be the whole TIMING story (host-side phase
accumulation ≈ the reference's cblas_* counters, CombBLAS.h:77-102). The
structured telemetry subsystem (``combblas_tpu/obs/``) subsumes it:
spans carry nesting, attributes, per-iteration events, and JSONL export.
Existing callers keep working — ``phase`` records into the same span
accumulator ``obs.report()`` reads — but new code should use
``obs.span`` / ``obs.span_event`` directly.

``ENABLED`` here keeps its historical meaning (phases accumulate even
when the global obs flag is off); flip it False to silence this module
alone.
"""

from __future__ import annotations

import contextlib

import jax

from .. import obs

ENABLED = True


def phase(name: str, *, sync=None):
    """Accumulate wall time under ``name`` (≈ one cblas_* counter).

    ``sync``: optional array/pytree to ``block_until_ready`` before closing
    the timer, so async dispatch doesn't hide device time.
    """
    if not ENABLED:  # the historical silencing knob, obs flag or not
        return obs.NULL_SPAN
    return obs.span(name, sync=sync, force=True)


def get(name: str) -> float:
    return obs.span_seconds(name)


def report(reset: bool = False) -> dict[str, tuple[float, int]]:
    """{name: (seconds, calls)} — the per-app timing table the reference
    prints after each run."""
    return obs.report(reset=reset)


def reset_all():
    obs.reset_spans()


def print_report(reset: bool = False):
    obs.print_report(reset=reset)


@contextlib.contextmanager
def trace(logdir: str):
    """Capture a jax.profiler device trace for the enclosed block
    (TensorBoard/Perfetto — the PAPI/MPI_Pcontrol analog)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


annotate = jax.profiler.TraceAnnotation
