"""Graph500 R-MAT edge generator — jittable, deterministic, TPU-resident.

The reference vendors the Graph500 v1.2/v2.1 generators (C, MRG random
stream) and drives them through ``DistEdgeList::GenGraph500Data``
(``DistEdgeList.cpp:223-330``, ``RefGen21.h:88-323``).  The TPU-native
re-design generates all edges on-device with ``jax.random`` (threefry is our
deterministic counter-based stream, replacing MRG) in one vectorized pass
over [nedges, scale] quadrant choices — no host loop, no MPI scatter; under
jit the edge list never leaves HBM.

Graph500 parameters: (A, B, C, D) = (0.57, 0.19, 0.19, 0.05), edgefactor 16,
per-level probability noise as in the spec's octave kernel, plus the random
vertex relabeling that ``DistEdgeList::RenameVertices`` applies.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnums=(1, 2, 3))
def rmat_edges(
    key: jax.Array,
    scale: int,
    nedges: int,
    noise: bool = True,
    a: float = 0.57,
    b: float = 0.19,
    c: float = 0.19,
):
    """Generate ``nedges`` R-MAT edges over 2**scale vertices.

    Returns (src, dst) int32 arrays. Self-loops and duplicates are NOT
    filtered (the reference keeps them in the edge list too and filters at
    matrix-build time, ``SpTuples`` Graph500 ctor).
    """
    d = 1.0 - a - b - c
    k_src, k_dst, k_noise, k_perm = jax.random.split(key, 4)
    u = jax.random.uniform(k_src, (nedges, scale))
    v = jax.random.uniform(k_dst, (nedges, scale))
    if noise:
        # Per-level multiplicative noise on A as in the Graph500 octave
        # kernel; renormalized via the conditional-probability formulation.
        mu = jax.random.uniform(k_noise, (nedges, scale), minval=0.95, maxval=1.05)
        a_eff = a * mu
    else:
        a_eff = jnp.full((nedges, scale), a)
    # P(src_bit=1) = 1 - (a + b); quadrant split conditioned on src_bit.
    ab = a_eff + b
    src_bit = u >= ab
    p_dst1 = jnp.where(src_bit, d / (c + d), b / ab)
    dst_bit = v < p_dst1
    weights = (1 << jnp.arange(scale, dtype=jnp.int32))[None, :]
    src = jnp.sum(src_bit.astype(jnp.int32) * weights, axis=1)
    dst = jnp.sum(dst_bit.astype(jnp.int32) * weights, axis=1)
    # Random vertex relabeling (≈ RenameVertices) to break the R-MAT
    # degree-locality correlation.
    n = 1 << scale
    perm = jax.random.permutation(k_perm, n)
    return perm[src].astype(jnp.int32), perm[dst].astype(jnp.int32)


def rmat_symmetric_coo_host(
    seed: int, scale: int, edgefactor: int = 16, noise: bool = True
):
    """Pure-numpy R-MAT (same kernel as ``rmat_edges``) → symmetrized COO.

    Exists for real-chip benchmarking: the axon TPU runtime permanently
    degrades launch performance after any device→host readback, so the
    bench pipeline must construct the graph entirely host-side and only
    upload (see bench.py). Deterministic in ``seed``.
    """
    import numpy as np

    rng = np.random.default_rng(seed)
    a, b, c = 0.57, 0.19, 0.19
    d = 1.0 - a - b - c
    n = 1 << scale
    nedges = edgefactor * n
    # Level-at-a-time generation: [nedges]-sized temporaries instead of
    # [nedges, scale] (a >10x peak-memory reduction — scale 21 would need
    # ~25 GB of float64 otherwise), identical output distribution.
    src = np.zeros(nedges, np.int64)
    dst = np.zeros(nedges, np.int64)
    for level in range(scale):
        u = rng.random(nedges)
        v = rng.random(nedges)
        a_eff = a * rng.uniform(0.95, 1.05, nedges) if noise else a
        ab = a_eff + b
        src_bit = u >= ab
        p_dst1 = np.where(src_bit, d / (c + d), b / ab)
        dst_bit = v < p_dst1
        w = np.int64(1) << level
        src += src_bit * w
        dst += dst_bit * w
    perm = rng.permutation(n)
    src, dst = perm[src], perm[dst]
    keep = src != dst
    src, dst = src[keep], dst[keep]
    rows = np.concatenate([src, dst])
    cols = np.concatenate([dst, src])
    return rows, cols


def rmat_symmetric_coo(key, scale: int, edgefactor: int = 16, noise: bool = True):
    """Edge list → symmetrized COO (both directions, no loops) on host.

    The app-level Symmetricize + RemoveLoops pipeline of the Graph500 drivers
    (``TopDownBFS.cpp:270-370``, ``SpParMat::RemoveLoops`` SpParMat.cpp:3257).
    Returns numpy (rows, cols) with duplicates retained (dedup at matrix
    construction).
    """
    import numpy as np

    n = 1 << scale
    src, dst = rmat_edges(key, scale, edgefactor * n, noise)
    src = np.asarray(src)
    dst = np.asarray(dst)
    keep = src != dst
    src, dst = src[keep], dst[keep]
    rows = np.concatenate([src, dst])
    cols = np.concatenate([dst, src])
    return rows, cols
