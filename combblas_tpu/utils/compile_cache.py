"""Persistent XLA compilation cache switch, shared by every driver.

Verified to work through the axon remote compiler (2.7 s -> 0.5 s
cold-process recompile). One definition so the official bench and every
probe measure under identical cache behavior; ``BENCH_NOCACHE=1``
disables for diagnostics.

IDEMPOTENCE CONTRACT: the cache dir is process-global jax config, so
the first ``enable_compile_cache`` call wins. Re-enabling with no
argument ("ensure the cache is on") or with the SAME (resolved) dir is
a no-op; an EXPLICIT different dir raises — silently retargeting the
cache mid-process would split compiled artifacts across two dirs and
make hit/miss counters unattributable. ``_reset_for_tests()`` is the
explicit test-only escape hatch.

When telemetry is on (``combblas_tpu.obs``), enabling the cache also
installs the jax.monitoring bridge so persistent-cache hits/misses
surface as the ``compile_cache.hits`` / ``compile_cache.misses``
counters, and registers a pull-provider publishing the
``compile_cache.entries`` gauge (files currently in the cache dir) into
every report/JSONL dump.
"""

from __future__ import annotations

import os

from .. import obs

CACHE_DIR = os.path.normpath(
    os.path.join(os.path.dirname(__file__), "..", "..", ".jax_cache")
)

#: The dir the process committed to on the first successful enable call
#: (None = not yet enabled). See the idempotence contract above.
_configured_dir: str | None = None


def configured_dir() -> str | None:
    """The cache dir this process committed to, or None when the cache
    was never enabled — the public accessor (the underlying global is
    an internal invariant of the idempotence contract)."""
    return _configured_dir


def plan_store_dir() -> str:
    """Default MEASURED-PLAN store dir (round 10): the ``.plan_store``
    SIBLING of the compile cache dir, so a fleet that ships its warm
    compile cache to new replicas ships the measured tier plans with
    the same rsync.  ``COMBBLAS_PLAN_STORE`` overrides (parsed by
    ``tuner.config.store_dir``, which calls this for the default)."""
    base = _configured_dir or CACHE_DIR
    return os.path.join(
        os.path.dirname(os.path.abspath(base)), ".plan_store"
    )


def _record_cache_entries() -> None:
    """obs provider: persistent-cache entry count, polled at export time
    (a push on every compile would race the async cache writer).  ONE
    health surface covers both caches: the sibling plan store's entry
    count is published by the same provider (``cache="plans"`` labeled
    series + the ``tuner.store.entries`` gauge), so a fleet dashboard
    watching compile-cache health sees plan-store health for free."""
    if _configured_dir is not None:
        try:
            entries = sum(
                1 for e in os.scandir(_configured_dir) if e.is_file()
            )
        except OSError:
            entries = 0
        obs.gauge("compile_cache.entries", entries, dir=_configured_dir)
    try:
        from ..tuner import store as plan_store

        st = plan_store.get_store()
    except Exception:
        st = None
    if st is not None:
        obs.gauge(
            "compile_cache.entries", st.entries(),
            cache="plans", dir=st.path,
        )
        obs.gauge("tuner.store.entries", st.entries(), dir=st.path)


def enable_compile_cache(cache_dir: str | None = None) -> None:
    global _configured_dir
    import jax

    if obs.ENABLED:
        obs.install_jax_hooks()
    if os.environ.get("BENCH_NOCACHE") == "1":
        obs.count("compile_cache.disabled")
        return
    # abspath: "cache" and os.path.abspath("cache") are the same dir,
    # and the committed identity must not drift under a later chdir
    resolved = os.path.abspath(cache_dir or CACHE_DIR)
    if _configured_dir is not None:
        # cache_dir=None means "ensure enabled", not "move to the
        # default dir" — every argless caller (bench.py, probes) must
        # keep working after someone committed a custom dir
        if cache_dir is None or resolved == _configured_dir:
            return  # idempotent re-enable
        raise ValueError(
            f"compile cache already enabled at {_configured_dir!r}; "
            f"cannot retarget to {resolved!r} in the same process "
            "(jax_compilation_cache_dir is process-global — see the "
            "idempotence contract in utils/compile_cache.py)"
        )
    jax.config.update("jax_compilation_cache_dir", resolved)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _configured_dir = resolved
    obs.register_provider(_record_cache_entries)


def _reset_for_tests() -> None:
    """Forget the committed cache dir (TEST-ONLY: lets a test exercise
    the idempotence contract without poisoning the process for later
    callers — restore the prior value afterwards)."""
    global _configured_dir
    _configured_dir = None
