"""Persistent XLA compilation cache switch, shared by every driver.

Verified to work through the axon remote compiler (2.7 s -> 0.5 s
cold-process recompile). One definition so the official bench and every
probe measure under identical cache behavior; ``BENCH_NOCACHE=1``
disables for diagnostics.

When telemetry is on (``combblas_tpu.obs``), enabling the cache also
installs the jax.monitoring bridge so persistent-cache hits/misses
surface as the ``compile_cache.hits`` / ``compile_cache.misses``
counters in every report/JSONL dump.
"""

from __future__ import annotations

import os

from .. import obs

CACHE_DIR = os.path.normpath(
    os.path.join(os.path.dirname(__file__), "..", "..", ".jax_cache")
)


def enable_compile_cache(cache_dir: str | None = None) -> None:
    import jax

    if obs.ENABLED:
        obs.install_jax_hooks()
    if os.environ.get("BENCH_NOCACHE") == "1":
        obs.count("compile_cache.disabled")
        return
    jax.config.update(
        "jax_compilation_cache_dir", cache_dir or CACHE_DIR
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
