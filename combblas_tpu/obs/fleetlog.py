"""Supervision event timeline: the process fleet's append-only
``combblas_tpu.fleetlog/v1`` JSONL log (round 18).

The flight recorder (``obs/recorder.py``) answers "what was the DEVICE
doing before this failure"; the fleet log answers the control-plane
question: what happened to replica 2 at 14:03?  Spawn, heartbeat-miss,
quarantine, SIGKILL/SIGSTOP detection, respawn, promotion,
drain/restore, rolling-restart, and fan-out lag/heal all land here as
they happen — written by the supervisor (one thread, no request-path
cost), so the timeline is ordered the way the supervisor actually saw
events, not the way post-hoc metric scrapes infer them.

Format: one meta line under ``FLEETLOG_SCHEMA`` (written lazily on the
first event so an idle fleet leaves no file), then ordinary ``event``
records that ``obs.parse_jsonl`` validates — the flightrec precedent.
Unlike the flight recorder the file is APPENDED per event rather than
dumped on demand (a timeline that dies with the supervisor is not a
post-mortem tool), but both the in-memory ring and the file are
bounded: the ring keeps the last ``capacity`` events for ``stats()``,
the file stops growing at ``max_file_events`` (the ring keeps
rotating, and ``truncated`` in ``describe()`` says the file is a
prefix).  Best-effort like every obs writer: a full disk increments
``write_errors``, never raises into the supervisor loop.
"""

from __future__ import annotations

import json
import os
import threading
import time

from .sinks import FLEETLOG_SCHEMA, SCHEMA_VERSION

#: Default in-memory ring capacity — a chaos soak's worth of
#: supervision churn without unbounded memory.
DEFAULT_EVENTS = 512

#: File growth cap: the timeline is per-fleet-lifetime, so 10k events
#: covers any realistic supervision history; past it the file is a
#: truncated prefix (flagged in describe()), the ring stays live.
DEFAULT_MAX_FILE_EVENTS = 10_000


class FleetLog:
    """Bounded supervision timeline: in-memory ring + JSONL append."""

    #: Envelope field names (the FlightRecorder convention): a caller
    #: field by one of these names is remapped to ``f_<name>`` so it
    #: cannot corrupt the schema discriminators.
    RESERVED = frozenset(("v", "kind", "name", "ts"))

    def __init__(self, path: str, capacity: int = DEFAULT_EVENTS,
                 max_file_events: int = DEFAULT_MAX_FILE_EVENTS,
                 tenant: str | None = None):
        if capacity < 1:
            raise ValueError("fleet log needs capacity >= 1")
        self.path = os.path.abspath(path)
        self.capacity = int(capacity)
        self.max_file_events = int(max_file_events)
        self.tenant = tenant
        self._lock = threading.Lock()
        self._ring: list[dict] = []
        self._head = 0  # next overwrite slot once the ring is full
        self._meta_written = False
        self.recorded = 0
        self.file_events = 0
        self.write_errors = 0

    def event(self, name: str, **fields) -> None:
        """Record one supervision event (``name`` + JSON-scalar
        fields): ring append + one file append.  Never raises — the
        supervisor loop must survive a full disk."""
        ev = {"name": f"fleet.{name}", "ts": time.time()}
        if self.tenant is not None:
            ev["tenant"] = self.tenant
        for k, v in fields.items():
            ev[f"f_{k}" if k in self.RESERVED else k] = v
        with self._lock:
            self.recorded += 1
            if len(self._ring) < self.capacity:
                self._ring.append(ev)
            else:
                self._ring[self._head] = ev
                self._head = (self._head + 1) % self.capacity
            lines = []
            if not self._meta_written:
                meta = {
                    "v": SCHEMA_VERSION, "kind": "meta",
                    "schema": FLEETLOG_SCHEMA, "ts": time.time(),
                    "process": os.getpid(), "nprocs": 1,
                }
                if self.tenant is not None:
                    meta["tenant"] = self.tenant
                lines.append(meta)
            if self.file_events < self.max_file_events:
                lines.append({"v": SCHEMA_VERSION, "kind": "event", **ev})
            if lines:
                try:
                    os.makedirs(
                        os.path.dirname(self.path) or ".", exist_ok=True
                    )
                    with open(self.path, "a") as f:
                        for rec in lines:
                            f.write(json.dumps(rec) + "\n")
                except OSError:
                    self.write_errors += 1
                else:
                    self._meta_written = True
                    self.file_events += sum(
                        1 for rec in lines if rec["kind"] == "event"
                    )
        from combblas_tpu import obs

        obs.count("serve.fleetlog.events", event=name)

    def snapshot(self) -> list[dict]:
        """The ring's events, oldest first."""
        with self._lock:
            return self._ring[self._head:] + self._ring[: self._head]

    def describe(self) -> dict:
        with self._lock:
            return {
                "path": self.path,
                "capacity": self.capacity,
                "events": len(self._ring),
                "recorded": self.recorded,
                "file_events": self.file_events,
                "truncated": self.recorded > self.file_events,
                "write_errors": self.write_errors,
            }
