"""Telemetry sinks: JSONL export/parse/validate, host-side multi-process
merge, and the device psum path for add-monoid counters.

JSONL schema (``combblas_tpu.obs/v1``): one event per line, every line a
JSON object with

    {"v": 1, "kind": <kind>, ...}

kinds and their required fields:

    meta       schema (str, == SCHEMA), ts (float), process (int),
               nprocs (int)
    span       name (str), path (str), ts (float), wall_s (number >= 0);
               optional attrs (obj), events (list of {"name", "t_s", ...}),
               failed (bool)
    event      name (str), ts (float)  — span-less, process-level
    counter    name (str), value (number), labels (obj)
    gauge      name (str), value (number), labels (obj)
    histogram  name (str), count (int), sum/min/max (number), labels (obj);
               optional samples (list) + p50/p95/p99 (the round-15
               reservoir quantiles, computed by the registry snapshot)
    trace      name (str), rid (int|str), ts (float), wall_s (>= 0),
               stages (list of {"stage", "s"} summing to wall_s),
               labels (obj) — one served request's latency
               decomposition (round 15, ``obs/trace.py``)

Flight-recorder snapshots (round 15, ``obs/recorder.py``) are JSONL
files under ``combblas_tpu.flightrec/v1``: one meta line carrying that
schema plus a ``reason`` field, then ordinary ``event`` records — the
same validator accepts both schemas.

Multihost aggregation: each process dumps its own file (the exporter
stamps ``process``); ``merge_jsonl_files`` merges them host-side —
counters and histograms add across processes, gauges and spans keep a
``process`` qualifier. For counters that must be combined ON DEVICE
(inside a timed section, no readback), ``psum_counters`` reduces a
per-device counter block over the mesh with the add monoid
(``parallel/collectives.axis_reduce`` — the MPI_Allreduce-on-MPI_SUM
analog of the reference's TIMING reduction).
"""

from __future__ import annotations

import json
import numbers
import time

SCHEMA = "combblas_tpu.obs/v1"
SCHEMA_VERSION = 1

#: Flight-recorder snapshot schema (round 15, ``obs/recorder.py``): a
#: dump file is one meta line under THIS schema (plus ``reason``)
#: followed by ordinary ``event`` records — parse_jsonl validates both.
FLIGHTREC_SCHEMA = "combblas_tpu.flightrec/v1"

#: Supervision-timeline schema (round 18, ``obs/fleetlog.py``): the
#: process fleet's event log is one meta line under THIS schema
#: followed by ordinary ``event`` records (spawn, heartbeat-miss,
#: quarantine, respawn, promotion, ...) — parse_jsonl validates all
#: three schemas with the same code.
FLEETLOG_SCHEMA = "combblas_tpu.fleetlog/v1"

_KINDS = ("meta", "span", "event", "counter", "gauge", "histogram",
          "trace")
_META_SCHEMAS = (SCHEMA, FLIGHTREC_SCHEMA, FLEETLOG_SCHEMA)

#: Quantiles every histogram summary carries (round 15): computed ONCE
#: here and reused by the Prometheus exporter and the bench sidecars —
#: benches must not re-derive percentiles by hand.
QUANTILES = (0.5, 0.95, 0.99)


def quantiles(values, qs=QUANTILES) -> dict:
    """Linear-interpolation quantiles of a sample list:
    ``{q: value}`` (None-valued when ``values`` is empty).  The one
    percentile implementation the registry snapshot, ``aggregate()``,
    the exporter and every bench share."""
    vs = sorted(float(v) for v in values)
    out: dict = {}
    for q in qs:
        if not vs:
            out[q] = None
            continue
        pos = float(q) * (len(vs) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(vs) - 1)
        out[q] = vs[lo] + (vs[hi] - vs[lo]) * (pos - lo)
    return out


def quantile_summary(values) -> dict:
    """``{"p50": ..., "p95": ..., "p99": ...}`` from a sample list —
    the field names histogram records and aggregate summaries carry."""
    qs = quantiles(values)
    return {f"p{int(q * 100)}": v for q, v in qs.items()}


def validate_record(rec: dict) -> None:
    """Raise ``ValueError`` unless ``rec`` is a valid v1 schema record."""

    def need(field, types):
        if field not in rec:
            raise ValueError(f"{rec.get('kind')}: missing field {field!r}")
        if not isinstance(rec[field], types):
            raise ValueError(
                f"{rec.get('kind')}.{field}: {type(rec[field]).__name__} "
                f"is not {types}"
            )

    if not isinstance(rec, dict):
        raise ValueError(f"record is {type(rec).__name__}, not an object")
    need("v", numbers.Integral)
    if rec["v"] != SCHEMA_VERSION:
        raise ValueError(f"unknown schema version {rec['v']}")
    need("kind", str)
    kind = rec["kind"]
    if kind not in _KINDS:
        raise ValueError(f"unknown kind {kind!r}")
    if kind == "meta":
        need("schema", str)
        if rec["schema"] not in _META_SCHEMAS:
            raise ValueError(f"unknown schema {rec['schema']!r}")
        need("ts", numbers.Real)
        need("process", numbers.Integral)
        need("nprocs", numbers.Integral)
        return
    need("name", str)
    if kind == "trace":
        # per-request serve trace (round 15, obs/trace.py): stage
        # durations sum to wall_s — the latency decomposition record
        if "rid" not in rec or not isinstance(
            rec["rid"], (numbers.Integral, str)
        ):
            raise ValueError("trace.rid missing or not int/str")
        need("ts", numbers.Real)
        need("wall_s", numbers.Real)
        if rec["wall_s"] < 0:
            raise ValueError("trace.wall_s < 0")
        need("stages", list)
        for st in rec["stages"]:
            if (
                not isinstance(st, dict)
                or not isinstance(st.get("stage"), str)
                or not isinstance(st.get("s"), numbers.Real)
            ):
                raise ValueError(f"malformed trace stage: {st!r}")
        need("labels", dict)
    elif kind == "span":
        need("path", str)
        need("ts", numbers.Real)
        need("wall_s", numbers.Real)
        if rec["wall_s"] < 0:
            raise ValueError("span.wall_s < 0")
        for ev in rec.get("events", []):
            if not isinstance(ev, dict) or "name" not in ev:
                raise ValueError(f"span event without name: {ev!r}")
    elif kind == "event":
        need("ts", numbers.Real)
    elif kind in ("counter", "gauge"):
        need("value", numbers.Real)
        need("labels", dict)
    elif kind == "histogram":
        need("labels", dict)
        for f in ("count", "sum", "min", "max"):
            need(f, numbers.Real)


def encode_records(metric_records, span_tracker, *, process: int = 0,
                   nprocs: int = 1, traces=()) -> list[dict]:
    """Assemble the full schema record list from a registry snapshot and a
    SpanTracker (one meta line first, then spans, events, per-request
    traces, metrics)."""
    meta = {
        "v": SCHEMA_VERSION, "kind": "meta", "schema": SCHEMA,
        "ts": time.time(), "process": int(process), "nprocs": int(nprocs),
    }
    if span_tracker.dropped:
        meta["dropped_records"] = span_tracker.dropped
    out = [meta]
    for rec in span_tracker.log:
        out.append({"v": SCHEMA_VERSION, "kind": "span", **rec})
    for rec in span_tracker.events:
        out.append({"v": SCHEMA_VERSION, "kind": "event", **rec})
    for rec in traces:
        out.append({"v": SCHEMA_VERSION, "kind": "trace", **rec})
    for rec in metric_records:
        out.append({"v": SCHEMA_VERSION, **rec})
    return out


def write_jsonl(path: str, records) -> str:
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return path


def parse_jsonl(path: str, validate: bool = True) -> list[dict]:
    """Read a JSONL trace back; each line validated against the schema."""
    out = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: bad JSON: {e}") from e
            if validate:
                try:
                    validate_record(rec)
                except ValueError as e:
                    raise ValueError(f"{path}:{lineno}: {e}") from e
            out.append(rec)
    return out


def aggregate(records) -> dict:
    """Fold a record list (possibly spanning processes) into one summary:
    counters/histograms ADD, gauges keep (process, labels)-qualified last
    values, spans fold into the {name: (seconds, calls)} table."""
    counters: dict = {}
    gauges: dict = {}
    hists: dict = {}
    hist_samples: dict = {}
    span_table: dict = {}
    spans = []
    events = []
    traces = []
    nprocs = set()
    proc = 0
    for rec in records:
        kind = rec.get("kind")
        # per-record process stamps (merge_jsonl_files strips meta lines,
        # so the contributing-process set must come from the records too;
        # -1 is the synthetic merged-meta marker, not a process)
        if "process" in rec and rec["process"] >= 0:
            nprocs.add(rec["process"])
        if kind == "meta":
            proc = rec.get("process", 0)
            if proc >= 0:
                nprocs.add(proc)
        elif kind == "counter":
            key = (rec["name"], tuple(sorted(rec["labels"].items())))
            counters[key] = counters.get(key, 0) + rec["value"]
        elif kind == "gauge":
            key = (
                rec["name"],
                tuple(sorted(rec["labels"].items())),
                rec.get("process", proc),
            )
            gauges[key] = rec["value"]
        elif kind == "histogram":
            key = (rec["name"], tuple(sorted(rec["labels"].items())))
            h = hists.get(key)
            if h is None:
                hists[key] = [rec["count"], rec["sum"], rec["min"],
                              rec["max"]]
            else:
                h[0] += rec["count"]
                h[1] += rec["sum"]
                h[2] = min(h[2], rec["min"])
                h[3] = max(h[3], rec["max"])
            # reservoir samples ride along (metrics.py snapshots them):
            # concatenating across processes lets the quantile summary
            # below be computed ONCE, here, for everyone downstream.
            # The merge buffer is bounded ELEMENT-wise — a block-wise
            # gate would drop late processes' reservoirs wholesale and
            # silently bias the merged quantiles toward early files
            samples = rec.get("samples")
            if samples:
                buf = hist_samples.setdefault(key, [])
                take = 8192 - len(buf)
                if take > 0:
                    buf.extend(samples[:take])
        elif kind == "trace":
            traces.append({**rec, "process": rec.get("process", proc)})
        elif kind == "span":
            a = span_table.setdefault(rec["name"], [0.0, 0])
            a[0] += rec["wall_s"]
            a[1] += 1
            spans.append({**rec, "process": rec.get("process", proc)})
        elif kind == "event":
            events.append({**rec, "process": rec.get("process", proc)})
    return {
        "counters": {k[0] + _label_suffix(k[1]): v
                     for k, v in sorted(counters.items())},
        "gauges": {f"{k[0]}{_label_suffix(k[1])}@p{k[2]}": v
                   for k, v in sorted(gauges.items())},
        "histograms": {
            k[0] + _label_suffix(k[1]): {
                "count": h[0], "sum": h[1], "min": h[2], "max": h[3],
                **(
                    quantile_summary(hist_samples[k])
                    if k in hist_samples else {}
                ),
            }
            for k, h in sorted(hists.items())
        },
        "span_table": {k: (v[0], v[1]) for k, v in sorted(span_table.items())},
        "spans": spans,
        "events": events,
        "traces": traces,
        "processes": sorted(nprocs) or [0],
    }


def _label_suffix(label_items: tuple) -> str:
    if not label_items:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in label_items) + "}"


def merge_jsonl_files(paths, out_path: str | None = None) -> dict:
    """Host-side multi-process merge: parse every per-process file,
    stamp each record with its file's process id, aggregate. When
    ``out_path`` is given, also write the merged record stream (one meta
    line for the merge, then every stamped record)."""
    all_records = []
    for path in paths:
        recs = parse_jsonl(path)
        proc = next(
            (r.get("process", 0) for r in recs if r.get("kind") == "meta"), 0
        )
        for rec in recs:
            if rec.get("kind") != "meta":
                all_records.append({**rec, "process": proc})
    agg = aggregate(all_records)
    if out_path is not None:
        merged_meta = {
            "v": SCHEMA_VERSION, "kind": "meta", "schema": SCHEMA,
            "ts": time.time(), "process": -1,
            "nprocs": len(paths), "merged_from": len(paths),
        }
        write_jsonl(out_path, [merged_meta] + all_records)
        agg["path"] = out_path
    return agg


def psum_counters(grid, local_counts):
    """Device-side add-monoid counter reduction over the 2D mesh.

    ``local_counts``: [pr, pc, k] — each device's counter vector (e.g.
    per-tile drop counts or load tallies accumulated inside a jitted
    section). Returns the [k] global totals, REPLICATED so every process
    can read them whole under multi-host (same contract as
    ``redistribute_coo``'s drop count). This is the in-program
    aggregation path; the JSONL merge above is the post-hoc one.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from ..parallel.collectives import axis_reduce
    from ..parallel.grid import COL_AXIS, ROW_AXIS
    from ..parallel.spmat import TILE_SPEC
    from ..semiring import PLUS_TIMES

    def body(x):
        v = axis_reduce(
            PLUS_TIMES, axis_reduce(PLUS_TIMES, x[0, 0], ROW_AXIS), COL_AXIS
        )
        return v[None]

    out = jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(TILE_SPEC,),
        out_specs=P(),
        check_vma=False,
    )(local_counts)
    return out[0]
