"""Span/trace layer: nested named wall-time spans with attached events.

Subsumes ``utils/timers.py`` (now a compatibility shim over this module):
each span wraps ``jax.profiler.TraceAnnotation`` so host spans line up
with the on-device profiler timeline, accumulates into the per-app
timing table the reference prints after each run
(``TopDownBFS.cpp:472-479``), and keeps a bounded structured log for the
JSONL exporter. Span EVENTS carry the per-iteration records — BFS hop +
frontier nnz, MCL round + chaos, SUMMA stage — that the scalar timer
table cannot express.

Disabled-path cost: ``SpanTracker.open`` returns a shared null context
manager after one flag check — no allocation, no dict work — so
instrumented hot paths are free when telemetry is off.
"""

from __future__ import annotations

import threading
import time


class _NullSpan:
    """Reentrant no-op context manager returned when telemetry is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()

#: Bound on the structured span/event logs: long-running processes must
#: not grow memory without limit; overflow is counted, never silent.
MAX_LOG = 100_000


class _ActiveSpan:
    __slots__ = ("tracker", "name", "attrs", "sync", "events", "t0", "ts",
                 "path", "log", "_ann")

    def __init__(self, tracker, name, attrs, sync, log=True):
        self.tracker = tracker
        self.name = name
        self.attrs = attrs
        self.sync = sync
        self.log = log
        self.events = []

    def __enter__(self):
        stack = self.tracker._stack()
        parent = stack[-1].path if stack else ""
        self.path = f"{parent}/{self.name}" if parent else self.name
        stack.append(self)
        import jax

        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self.ts = time.time()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        try:
            if self.sync is not None:
                import jax

                jax.block_until_ready(self.sync)
        finally:
            wall = time.perf_counter() - self.t0
            self._ann.__exit__(exc_type, exc, tb)
            stack = self.tracker._stack()
            if stack and stack[-1] is self:
                stack.pop()
            self.tracker._close(self, wall, failed=exc_type is not None)
        return False

    def event(self, name: str, **fields):
        self.events.append({
            "name": name,
            "t_s": round(time.perf_counter() - self.t0, 6),
            **fields,
        })


class SpanTracker:
    """Owns the span stack (per thread), the accumulator table, and the
    bounded structured log."""

    def __init__(self):
        self._local = threading.local()
        self._lock = threading.Lock()
        self.acc: dict[str, list] = {}  # name -> [seconds, calls]
        self.log: list[dict] = []  # closed spans, schema-shaped
        self.events: list[dict] = []  # top-level (span-less) events
        self.dropped = 0

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def open(self, name: str, enabled: bool, sync=None, log=True, **attrs):
        if not enabled:
            return NULL_SPAN
        return _ActiveSpan(self, name, attrs, sync, log=log)

    def current(self) -> _ActiveSpan | None:
        st = self._stack()
        return st[-1] if st else None

    def event(self, name: str, **fields):
        """Attach to the innermost open span, else record top-level."""
        cur = self.current()
        if cur is not None and not isinstance(cur, _NullSpan):
            cur.event(name, **fields)
            return
        with self._lock:
            if len(self.events) >= MAX_LOG:
                self.dropped += 1
                return
            self.events.append({
                "name": name, "ts": time.time(), **fields,
            })

    def _close(self, span: _ActiveSpan, wall: float, failed: bool):
        with self._lock:
            a = self.acc.get(span.name)
            if a is None:
                self.acc[span.name] = [wall, 1]
            else:
                a[0] += wall
                a[1] += 1
            if not span.log:
                # table-only span (the timers-shim force path): the old
                # timers kept one (seconds, calls) pair per name, never
                # an unbounded structured record per call
                return
            if len(self.log) >= MAX_LOG:
                self.dropped += 1
                return
            rec = {
                "name": span.name,
                "path": span.path,
                "ts": span.ts,
                "wall_s": round(wall, 6),
            }
            if span.attrs:
                rec["attrs"] = span.attrs
            if span.events:
                rec["events"] = span.events
            if failed:
                rec["failed"] = True
            self.log.append(rec)

    # -- the per-app timing table (utils/timers.py compat) -----------------
    def seconds(self, name: str) -> float:
        a = self.acc.get(name)
        return a[0] if a else 0.0

    def table(self) -> dict[str, tuple[float, int]]:
        with self._lock:
            return {k: (v[0], v[1]) for k, v in sorted(self.acc.items())}

    def empty(self) -> bool:
        return not (self.acc or self.log or self.events)

    def clear_table(self):
        """Clear only the (seconds, calls) accumulator — the timers-shim
        reset; the structured log/events stay (they belong to obs)."""
        with self._lock:
            self.acc.clear()

    def clear(self):
        with self._lock:
            self.acc.clear()
            self.log.clear()
            self.events.clear()
            self.dropped = 0
