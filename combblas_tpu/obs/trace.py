"""Per-request tracing: the Dapper-style latency decomposition of the
serve path (round 15).

One sampled request carries a ``RequestTrace`` from admission to
settlement; every thread that touches it MARKS a stage transition, and
the durations between marks — queue wait, batch assembly, device
execute, bisection retries, result scatter (or the write lane's
buffer wait, merge, fan-out, swap) — telescope EXACTLY to the
end-to-end latency: ``sum(stage seconds) == wall_s`` by construction
(each mark records the time since the previous one).  Completed traces
land in a bounded log exported as schema ``trace`` records in the obs
JSONL (``combblas_tpu.obs/v1``; sinks.py documents the shape).

Sampling is DETERMINISTIC: a request is traced iff
``crc32(str(rid)) % 1e6 < rate * 1e6`` — the same ids at the same rate
give the same sampled set on every replica and every rerun, so a
fleet-wide trace collection lines up per request without coordination.
The rate comes from ``COMBBLAS_OBS_TRACE_SAMPLE`` (parsed in
tuner/config.py, resolved lazily and cached here) or
``set_sample_rate()``; the default is 0 — and tracing is additionally
gated on ``obs.ENABLED``, so the disabled serve path pays ONE function
call + flag check per submit (``obs.request_trace``), nothing more.
"""

from __future__ import annotations

import threading
import time
import zlib

#: Bound on the completed-trace log (the span-log convention: overflow
#: is counted, never silent, never unbounded memory).
MAX_TRACES = 10_000

_lock = threading.Lock()
_log: list[dict] = []
_dropped = 0
_rate: float | None = None  # None = unresolved (lazy env read)


def sample_rate() -> float:
    """The resolved sampling rate in [0, 1] (env read once, cached)."""
    global _rate
    if _rate is None:
        from ..tuner import config as tuner_config

        _rate = tuner_config.obs_trace_sample()
    return _rate


def set_sample_rate(rate: float | None) -> None:
    """Override the sampling rate programmatically (benches, tests);
    ``None`` re-resolves the env on next use."""
    global _rate
    if rate is None:
        _rate = None
        return
    _rate = min(max(float(rate), 0.0), 1.0)


def sampled(rid, rate: float | None = None) -> bool:
    """Deterministic sampling decision for one request id: stable
    across processes, reruns, and replicas (crc32, not Python's
    per-process-randomized ``hash``)."""
    rate = sample_rate() if rate is None else rate
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return (zlib.crc32(str(rid).encode()) % 1_000_000) < int(
        rate * 1_000_000
    )


class RequestTrace:
    """One request's stage clock.  ``mark(stage)`` charges the time
    since the previous mark (or creation) to ``stage``; repeated stage
    names ACCUMULATE (a bisection-retried request charges 'execute'
    several times), preserving first-seen order.  ``finish`` closes
    the trace and commits it to the bounded log."""

    __slots__ = ("rid", "name", "labels", "ts", "t0", "_last",
                 "stages", "_done", "_held", "_held_status")

    def __init__(self, rid, name: str, labels: dict):
        self.rid = rid
        self.name = name
        self.labels = labels
        self.ts = time.time()
        self.t0 = time.perf_counter()
        self._last = self.t0
        self.stages: list[list] = []  # [stage, seconds], ordered
        self._done = False
        self._held = False
        self._held_status = None

    def mark(self, stage: str, now: float | None = None) -> float:
        now = time.perf_counter() if now is None else now
        dt = now - self._last
        self._last = now
        for st in self.stages:
            if st[0] == stage:
                st[1] += dt
                break
        else:
            self.stages.append([stage, dt])
        return dt

    def annotate(self, **labels) -> None:
        """Attach attribution facts (lane width, plan warm/cold,
        graph version, ...) discovered after admission."""
        self.labels.update(labels)

    def hold(self) -> None:
        """Defer the commit past the next ``finish`` (round 19): a
        transport that wraps the serve path — the net frontend writes
        the reply AFTER the router/scheduler settles the request —
        needs to charge its tail stage (``net_write``) after the
        downstream layer has already called ``finish``.  While held,
        the first ``finish`` marks its tail stage and records the
        status but does NOT commit; :meth:`release` appends the
        transport tail and commits with that recorded status, so the
        ``sum(stages) == wall_s`` invariant survives the hand-off."""
        self._held = True

    def release(self, status: str | None = None,
                stage: str | None = None) -> None:
        """Close a held trace: charge ``stage`` (the transport tail)
        and commit under the status the downstream ``finish`` recorded
        (falling back to ``status``, then "ok")."""
        if self._done:
            return
        self._held = False
        st = self._held_status or status or "ok"
        self._held_status = None
        self.finish(status=st, stage=stage)

    def finish(self, status: str = "ok", stage: str | None = None
               ) -> None:
        """Close the trace (idempotent — the first settle wins, like
        the future it describes).  ``stage`` charges the tail interval
        (last mark -> now) under that name, so the stage sum stays
        equal to the end-to-end wall time."""
        if self._done:
            return
        if self._held:
            if self._held_status is None:  # first settle wins
                self._held_status = status
                if stage is not None:
                    self.mark(stage)
                self.labels["status"] = status
            return
        self._done = True
        if stage is not None:
            self.mark(stage)
        self.labels["status"] = status
        _commit(self)

    def record(self) -> dict:
        """The schema-``trace`` record body (sinks.py validates it)."""
        return {
            "name": self.name,
            "rid": self.rid,
            "ts": self.ts,
            "wall_s": round(self._last - self.t0, 9),
            "stages": [
                {"stage": s, "s": round(v, 9)} for s, v in self.stages
            ],
            "labels": dict(self.labels),
        }


def begin(rid, name: str = "serve.request", **labels
          ) -> RequestTrace | None:
    """Open a trace for ``rid`` if the deterministic sampler admits it
    (None otherwise).  Callers go through ``obs.request_trace`` /
    ``obs.update_trace``, which add the ``obs.ENABLED`` gate."""
    if not sampled(rid):
        return None
    from combblas_tpu import obs

    obs.count("serve.trace.sampled", lane=name.rsplit(".", 1)[-1])
    return RequestTrace(
        rid, name, {k: v for k, v in labels.items() if v is not None}
    )


def _commit(tr: RequestTrace) -> None:
    global _dropped
    with _lock:
        if len(_log) >= MAX_TRACES:
            _dropped += 1
            drop = True
        else:
            _log.append(tr.record())
            drop = False
    if drop:
        from combblas_tpu import obs

        obs.count("serve.trace.dropped")


def records() -> list[dict]:
    """Snapshot of the completed-trace records (not drained — like the
    span log, ``obs.reset()`` is the wipe)."""
    with _lock:
        return list(_log)


def dropped() -> int:
    with _lock:
        return _dropped


def clear() -> None:
    global _dropped
    with _lock:
        _log.clear()
        _dropped = 0


def stage_summary(trace_records=None) -> dict:
    """Fold trace records into the latency decomposition the bench
    summaries report: ``{stage: {"mean_s", "total_s", "count"}}`` plus
    a ``"_wall"`` row for the end-to-end latency.  Accepts any iterable
    of schema-``trace`` records (default: the in-process log)."""
    trace_records = records() if trace_records is None else trace_records
    acc: dict[str, list] = {}
    wall = [0.0, 0]
    for rec in trace_records:
        wall[0] += rec["wall_s"]
        wall[1] += 1
        for st in rec["stages"]:
            a = acc.setdefault(st["stage"], [0.0, 0])
            a[0] += st["s"]
            a[1] += 1
    out = {
        stage: {
            "mean_s": a[0] / a[1], "total_s": a[0], "count": a[1],
        }
        for stage, a in acc.items()
    }
    if wall[1]:
        out["_wall"] = {
            "mean_s": wall[0] / wall[1], "total_s": wall[0],
            "count": wall[1],
        }
    return out
