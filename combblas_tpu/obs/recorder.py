"""Flight recorder: an always-on bounded ring of recent per-batch
stage events, dumped on failure (round 15).

Tracing (``obs/trace.py``) answers "where did THIS request's latency
go" — but only for sampled requests, only when enabled.  The flight
recorder answers the post-mortem question: when a worker dies, a
breaker opens, a batch is poisoned, a merge fails, or an SLO budget
burns out, WHAT was the device doing in the seconds before?  It is a
fixed-size ``deque`` of small host-side event dicts (one per batch /
merge, never per request), recorded unconditionally by the serve
worker — the cost is one ring append next to a device launch, which is
why it can afford to be always on — and written out as a
schema-versioned JSONL snapshot (``combblas_tpu.flightrec/v1``: one
meta line carrying the dump ``reason``, then ordinary ``event``
records ``obs.parse_jsonl`` validates) only when something goes wrong.

Dumps are rate-limited (``min_interval_s``) so a failure storm produces
a bounded number of files, and counted in obs
(``serve.flightrec.dumps{reason}``) when telemetry is on.  Disable per
server with ``ServeConfig(flight_recorder=False)`` — the hot path then
pays one attribute read (the zero-cost contract's shape).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time

from .sinks import FLIGHTREC_SCHEMA, SCHEMA_VERSION

#: Default ring capacity: enough batches to cover the seconds before a
#: failure at serving cadence without unbounded memory.
DEFAULT_EVENTS = 256

#: Dump reasons the serve stack uses (an arbitrary string is accepted;
#: these are the wired trigger points).
REASONS = (
    "worker_error", "breaker_open", "poisoned", "merge_failed",
    "slo_breach", "manual",
)


def default_dir() -> str:
    return os.path.join(tempfile.gettempdir(), "combblas_flightrec")


class FlightRecorder:
    """Bounded ring of per-batch events + the snapshot writer."""

    def __init__(self, capacity: int = DEFAULT_EVENTS,
                 out_dir: str | None = None,
                 min_interval_s: float = 1.0,
                 tenant: str | None = None):
        if capacity < 1:
            raise ValueError("flight recorder needs capacity >= 1")
        self.capacity = int(capacity)
        self.out_dir = out_dir or default_dir()
        self.min_interval_s = float(min_interval_s)
        self.tenant = tenant
        self._lock = threading.Lock()
        self._ring: list[dict] = []
        self._head = 0  # next overwrite slot once the ring is full
        self.recorded = 0
        self.dumps = 0
        self.dump_errors = 0
        self.last_dump: str | None = None
        self._last_dump_at = 0.0
        self._seq = 0

    # -- recording ---------------------------------------------------------

    #: Field names owned by the JSONL record envelope — a caller field
    #: by one of these names would corrupt the schema discriminators,
    #: so record() remaps it to ``f_<name>`` (query kind travels as
    #: ``query=``, not ``kind=``, for exactly this reason).
    RESERVED = frozenset(("v", "kind", "name", "ts"))

    def record(self, name: str, **fields) -> None:
        """Append one event (``name`` + arbitrary JSON-scalar fields).
        O(1), no I/O — safe next to the device on every batch."""
        ev = {"name": name, "ts": time.time()}
        if self.tenant is not None:
            ev["tenant"] = self.tenant
        for k, v in fields.items():
            ev[f"f_{k}" if k in self.RESERVED else k] = v
        with self._lock:
            self.recorded += 1
            if len(self._ring) < self.capacity:
                self._ring.append(ev)
            else:
                self._ring[self._head] = ev
                self._head = (self._head + 1) % self.capacity
        from combblas_tpu import obs

        obs.count("serve.flightrec.events")

    def snapshot(self) -> list[dict]:
        """The ring's events, oldest first."""
        with self._lock:
            return self._ring[self._head:] + self._ring[: self._head]

    # -- snapshots ---------------------------------------------------------

    def dump(self, reason: str = "manual", *, force: bool = False,
             **extra) -> str | None:
        """Write the ring as one ``combblas_tpu.flightrec/v1`` JSONL
        snapshot; returns the path, or None when rate-limited / empty.
        Best-effort: a full disk must never take the serve worker down
        with it (errors are counted, not raised)."""
        now = time.monotonic()
        with self._lock:
            if not self._ring:
                return None
            if not force and now - self._last_dump_at < self.min_interval_s:
                return None
            self._last_dump_at = now
            self._seq += 1
            seq = self._seq
        events = self.snapshot()
        try:
            import jax

            process, nprocs = jax.process_index(), jax.process_count()
        except Exception:
            process, nprocs = 0, 1
        meta = {
            "v": SCHEMA_VERSION, "kind": "meta",
            "schema": FLIGHTREC_SCHEMA, "ts": time.time(),
            "process": int(process), "nprocs": int(nprocs),
            "reason": reason, "events": len(events),
        }
        if self.tenant is not None:
            meta["tenant"] = self.tenant
        for k, v in extra.items():  # same envelope protection as
            # record(): extra facts must not clobber the schema fields
            meta[f"f_{k}" if (k in meta or k in self.RESERVED) else k] = v
        name = (
            f"flightrec-{self.tenant or 'serve'}-{os.getpid()}"
            f"-{seq:04d}.jsonl"
        )
        path = os.path.join(self.out_dir, name)
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            with open(path, "w") as f:
                f.write(json.dumps(meta) + "\n")
                for ev in events:
                    f.write(json.dumps(
                        {"v": SCHEMA_VERSION, "kind": "event", **ev}
                    ) + "\n")
        except OSError:
            self.dump_errors += 1
            return None
        self.dumps += 1
        self.last_dump = path
        from combblas_tpu import obs

        obs.count("serve.flightrec.dumps", reason=reason)
        return path

    # -- introspection -----------------------------------------------------

    def describe(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "events": len(self._ring),
                "recorded": self.recorded,
                "dumps": self.dumps,
                "dump_errors": self.dump_errors,
                "last_dump": self.last_dump,
                "dir": self.out_dir,
            }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._head = 0
