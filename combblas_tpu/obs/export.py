"""Live metrics export: Prometheus text exposition + HTTP scrape
surface (round 15).

The JSONL sinks are pull-after-the-fact; a serving fleet is operated
through a PULL-based scrape loop (Prometheus/Monarch style).  This
module renders the metrics registry snapshot as Prometheus text
exposition format — counters and gauges verbatim, histograms as
summaries (``_count`` / ``_sum`` / ``_min`` / ``_max`` plus
``{quantile="0.5|0.95|0.99"}`` lines from the round-15 sample
reservoir, ``sinks.quantile_summary`` — ONE quantile implementation
for scrape, JSONL aggregate, and benches) — and serves it from a
stdlib ``ThreadingHTTPServer`` daemon thread attachable to any serve
front end (``Server.serve_metrics`` / ``PoolServer`` /
``FleetRouter``):

    GET /metrics   Prometheus text (the scrape target)
    GET /healthz   the owner's ``health()`` as JSON
    GET /statz     the owner's ``stats()`` as JSON (debug surface)

Nothing here runs unless explicitly started — the zero-cost contract:
no thread, no socket, no rendering until ``serve_scrape()`` (and the
registry itself is only populated when obs is enabled).

One-shot snapshot CLI (renders a recorded JSONL trace as Prometheus
text, e.g. for offline diffing or pushing through a gateway):

    python -m combblas_tpu.obs.export trace.jsonl [--out metrics.prom]
"""

from __future__ import annotations

import json
import re
import threading

#: Every exported series name is prefixed (Prometheus namespacing) and
#: dots become underscores: ``serve.queue.depth`` ->
#: ``combblas_serve_queue_depth``.
PREFIX = "combblas_"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def metric_name(name: str) -> str:
    return PREFIX + _NAME_RE.sub("_", name)


def _esc(v) -> str:
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _labels(labels: dict, extra: dict | None = None) -> str:
    items = sorted(labels.items())
    if extra:
        items = items + sorted(extra.items())
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_esc(v)}"' for k, v in items) + "}"


def _num(v) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render(records=None) -> str:
    """Prometheus text exposition of a metric-record list (default:
    the live registry snapshot, providers polled).  Counter and gauge
    values are emitted verbatim under their sanitized names;
    histograms become summaries with reservoir quantiles."""
    if records is None:
        from . import metrics_snapshot

        records = metrics_snapshot()
    by_name: dict[tuple, list] = {}
    for rec in records:
        kind = rec.get("kind")
        if kind not in ("counter", "gauge", "histogram"):
            continue
        by_name.setdefault((rec["name"], kind), []).append(rec)
    lines: list[str] = []
    for (name, kind), recs in sorted(by_name.items()):
        mname = metric_name(name)
        if kind == "histogram":
            lines.append(f"# TYPE {mname} summary")
            for rec in recs:
                lab = rec.get("labels", {})
                for q in ("p50", "p95", "p99"):
                    if rec.get(q) is not None:
                        lines.append(
                            f"{mname}"
                            f"{_labels(lab, {'quantile': '0.' + q[1:]})}"
                            f" {_num(rec[q])}"
                        )
                lines.append(
                    f"{mname}_count{_labels(lab)} {_num(rec['count'])}"
                )
                lines.append(
                    f"{mname}_sum{_labels(lab)} {_num(rec['sum'])}"
                )
                lines.append(
                    f"{mname}_min{_labels(lab)} {_num(rec['min'])}"
                )
                lines.append(
                    f"{mname}_max{_labels(lab)} {_num(rec['max'])}"
                )
        else:
            lines.append(f"# TYPE {mname} {kind}")
            for rec in recs:
                lines.append(
                    f"{mname}{_labels(rec.get('labels', {}))}"
                    f" {_num(rec['value'])}"
                )
    return "\n".join(lines) + ("\n" if lines else "")


def render_from_jsonl(path: str) -> str:
    """One-shot: parse a recorded obs JSONL trace and render its
    metric records as Prometheus text (the snapshot CLI's body)."""
    from .sinks import parse_jsonl

    return render(parse_jsonl(path))


def parse_exposition(text: str) -> dict:
    """Parse Prometheus text back into ``{(name, labelstr): value}`` —
    the parity-test helper (and a convenient programmatic reader)."""
    out: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        body, _, val = line.rpartition(" ")
        m = re.match(r"([a-zA-Z0-9_:]+)(\{.*\})?$", body)
        if not m:
            continue
        out[(m.group(1), m.group(2) or "")] = float(val)
    return out


# -- the scrape thread -------------------------------------------------------


class ScrapeServer:
    """Stdlib HTTP daemon serving /metrics, /healthz, /statz for one
    owner object (anything with optional ``health()``/``stats()``)."""

    def __init__(self, owner=None, host: str = "127.0.0.1",
                 port: int = 0):
        from http.server import (
            BaseHTTPRequestHandler, ThreadingHTTPServer,
        )

        scrape = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # no stderr chatter per scrape
                pass

            def do_GET(self):
                from . import count

                path = self.path.split("?", 1)[0]
                # label by KNOWN endpoint only: counting the raw
                # client-supplied path would let any prober mint
                # unbounded registry series (one per distinct URL)
                count(
                    "obs.scrape.requests",
                    path=(
                        path
                        if path in ("/metrics", "/healthz", "/statz")
                        else "other"
                    ),
                )
                try:
                    if path == "/metrics":
                        # an owner with metrics_records() federates its
                        # own view (the process fleet folds per-replica
                        # child snapshots in); plain owners scrape the
                        # process-local registry
                        fn = getattr(scrape.owner, "metrics_records", None)
                        body = render(
                            fn() if callable(fn) else None
                        ).encode()
                        ctype = "text/plain; version=0.0.4"
                    elif path == "/healthz":
                        body = scrape._json_of("health")
                        ctype = "application/json"
                    elif path == "/statz":
                        body = scrape._json_of("stats")
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                except Exception as e:  # a scrape must never wedge on
                    # a mid-shutdown owner: report, keep listening
                    self.send_error(500, repr(e))
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.owner = owner
        self._stopped = False
        self._stop_lock = threading.Lock()
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="combblas-obs-scrape", daemon=True,
        )
        self._thread.start()

    def _json_of(self, method: str) -> bytes:
        fn = getattr(self.owner, method, None)
        payload = fn() if callable(fn) else {"error": f"no {method}()"}
        # stats() payloads may hold numpy scalars etc. — stringify
        # anything json cannot express rather than 500 the scrape
        return json.dumps(payload, default=str).encode()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        """Idempotent: repeated stops (owner.close() called twice, or a
        detach racing a close-path teardown) must not shutdown() an
        already-closed ThreadingHTTPServer — that call blocks forever
        waiting for a serve_forever loop that already exited."""
        with self._stop_lock:
            if self._stopped:
                return
            self._stopped = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(5.0)


def serve_scrape(owner=None, port: int = 0, host: str = "127.0.0.1"
                 ) -> ScrapeServer:
    """Start the scrape thread (port 0 = ephemeral; read ``.port``)."""
    return ScrapeServer(owner, host=host, port=port)


#: Serializes owner-registration (attach/detach) across threads: the
#: old check-then-set on ``owner._scrape`` let two concurrent
#: ``serve_metrics()`` calls (a Server and the FleetRouter wrapping
#: it, or two API callers) BOTH start HTTP daemons — the loser's
#: server leaked its port and thread forever (round 20 bugfix).
_ATTACH_LOCK = threading.Lock()


def attach_scrape(owner, port: int = 0, host: str = "127.0.0.1"
                  ) -> int:
    """The ONE serve_metrics implementation behind ``Server`` /
    ``PoolServer`` / ``FleetRouter`` / ``ProcessFleet``: idempotently
    attach a scrape thread to ``owner._scrape`` and return the bound
    port.  Safe to call repeatedly and concurrently; repeated
    attach/close cycles re-attach a FRESH server each time (the
    previous one was stopped and cleared by ``detach_scrape``)."""
    with _ATTACH_LOCK:
        s = getattr(owner, "_scrape", None)
        if s is None or getattr(s, "_stopped", False):
            owner._scrape = serve_scrape(owner, port=port, host=host)
        return owner._scrape.port


def detach_scrape(owner) -> None:
    """Stop and clear an attached scrape thread (close()-path twin of
    ``attach_scrape``; no-op when never attached, idempotent when
    called twice).  The registration flip happens under the attach
    lock; the (blocking) HTTP shutdown happens outside it, so a slow
    teardown can never wedge a concurrent attach on another owner."""
    with _ATTACH_LOCK:
        s = getattr(owner, "_scrape", None)
        owner._scrape = None
    if s is not None:
        s.stop()


# -- one-shot snapshot CLI ---------------------------------------------------


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="Render a combblas_tpu obs JSONL trace (or the "
        "live in-process registry) as Prometheus text exposition."
    )
    ap.add_argument("jsonl", nargs="?", help="obs JSONL trace to render"
                    " (omit for the current process registry)")
    ap.add_argument("--out", help="write here instead of stdout")
    args = ap.parse_args(argv)
    text = render_from_jsonl(args.jsonl) if args.jsonl else render()
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    else:
        print(text, end="")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
