"""``combblas_tpu.obs`` — structured telemetry for the hot paths.

The reference ships a whole TIMING subsystem — global ``cblas_*`` phase
counters compiled in under ``#ifdef TIMING`` (``CombBLAS.h:77-102``) and
per-app tables printed after each run (``TopDownBFS.cpp:472-479``). This
package is its structured, machine-readable replacement, three layers:

1. **metrics registry** (``metrics.py``) — counters/gauges/histograms
   with labels for scalar facts: SpGEMM symbolic vs realized fill-in,
   redistribute/bucket drop counts, compile-cache hit/miss, per-op
   load imbalance, jit trace counts, BFS lru-cache growth.
2. **span/trace layer** (``spans.py``) — nested named wall-time spans
   wrapping ``jax.profiler.TraceAnnotation`` (host spans line up with
   the device profiler timeline), with attached per-iteration events
   (BFS hop + frontier nnz, MCL round + chaos, SUMMA stage).
3. **sinks** (``sinks.py``) — the in-memory per-app table, a
   schema-versioned JSONL exporter, host-side multi-process merge, and
   a device psum path for add-monoid counters.

Round 15 adds the production serving surfaces (docs/observability.md
"Serving observability"): **per-request tracing** (``trace.py`` —
deterministic-sampled stage decompositions that sum to the e2e
latency), the **flight recorder** (``recorder.py`` — always-on
bounded ring dumped on failure as ``combblas_tpu.flightrec/v1``), and
the **live export surface** (``export.py`` — Prometheus text
exposition with reservoir quantiles + the stdlib-HTTP scrape thread
``Server.serve_metrics`` attaches).

COST CONTRACT: everything is guarded by the module-level ``ENABLED``
flag, checked before any dict work — with telemetry off, an
instrumented call site costs one attribute read (and ``span`` returns a
shared null context manager). Instrumentation lives HOST-SIDE only: no
host callbacks or extra syncs are ever inserted into jitted code;
counters recorded inside jit-traced Python count traces (retraces), not
executions, and device facts are only read back where a host sync
already exists — or when ``DEVICE_SYNC`` is explicitly opted into (CPU
debugging; never on the readback-poisoned chip, see bench.py).

Usage::

    from combblas_tpu import obs
    obs.enable(jsonl_path="trace.jsonl")
    with obs.span("bfs", scale=20):
        ...
        obs.span_event("frontier", hop=3, nnz=1234)
    obs.count("redistribute.dropped", 0)
    obs.dump_jsonl()

See docs/observability.md for the event schema and worked examples.
"""

from __future__ import annotations

import os

from .metrics import MetricsRegistry
from .sinks import (
    FLEETLOG_SCHEMA,
    FLIGHTREC_SCHEMA,
    SCHEMA,
    SCHEMA_VERSION,
    aggregate,
    encode_records,
    merge_jsonl_files,
    parse_jsonl,
    psum_counters,
    quantile_summary,
    quantiles,
    validate_record,
    write_jsonl,
)
from .spans import NULL_SPAN, SpanTracker
from . import trace as _trace

#: Master switch, checked at every instrumentation site BEFORE any work.
#: Off by default: the hot paths must cost nothing unless telemetry is
#: asked for (env COMBBLAS_OBS=1 or obs.enable()).
ENABLED: bool = os.environ.get("COMBBLAS_OBS", "0") not in ("", "0")

#: Opt-in for instrumentation that READS DEVICE SCALARS (e.g. realized
#: SpGEMM output nnz). Never enable in timed sections on hardware where
#: a D2H readback degrades later launches (bench.py module docstring).
DEVICE_SYNC: bool = os.environ.get("COMBBLAS_OBS_SYNC", "0") not in ("", "0")

registry = MetricsRegistry()
_spans = SpanTracker()
_providers: list = []
_jsonl_path: str | None = None
_hooks_installed = False


# --- lifecycle --------------------------------------------------------------


def enable(jsonl_path: str | None = None, *, device_sync: bool | None = None,
           install_hooks: bool = True) -> None:
    """Turn telemetry on (idempotent). ``jsonl_path`` configures the
    default ``dump_jsonl`` target; ``device_sync`` opts into
    readback-requiring metrics (CPU debugging only)."""
    global ENABLED, DEVICE_SYNC, _jsonl_path
    ENABLED = True
    if device_sync is not None:
        DEVICE_SYNC = bool(device_sync)
    if jsonl_path is not None:
        _jsonl_path = jsonl_path
    if install_hooks:
        install_jax_hooks()


def disable() -> None:
    global ENABLED
    ENABLED = False


def enable_sidecar(tag: str) -> str | None:
    """The BENCH_OBS=1 convention shared by the bench drivers: enable
    telemetry with a per-process JSONL sidecar under ``$BENCH_OBS_DIR``
    (default ``<tmpdir>/combblas_obs``), named ``obs-<tag>-<pid>.jsonl``.
    Returns the sidecar path, or None when ``BENCH_OBS`` is not ``1``.
    ``DEVICE_SYNC`` stays off: a bench child must never gain a readback
    from telemetry (bench.py module docstring)."""
    if os.environ.get("BENCH_OBS") != "1":
        return None
    import tempfile

    d = os.environ.get("BENCH_OBS_DIR") or os.path.join(
        tempfile.gettempdir(), "combblas_obs"
    )
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"obs-{tag}-{os.getpid()}.jsonl")
    enable(jsonl_path=path, device_sync=False)
    return path


def enabled() -> bool:
    return ENABLED


def reset() -> None:
    """Clear every metric, span, event, and per-request trace (the
    flag is untouched)."""
    registry.clear()
    _spans.clear()
    _trace.clear()


def reset_spans() -> None:
    """Clear only the (seconds, calls) span table (the timers-shim
    reset) — the structured span log and events belong to the obs
    subsystem and survive; use ``reset()`` for a full wipe."""
    _spans.clear_table()


# --- writers ----------------------------------------------------------------


def count(name: str, value=1, **labels) -> None:
    if not ENABLED:
        return
    registry.count(name, value, **labels)


def gauge(name: str, value, **labels) -> None:
    if not ENABLED:
        return
    registry.gauge(name, value, **labels)


def observe(name: str, value, **labels) -> None:
    if not ENABLED:
        return
    registry.observe(name, value, **labels)


def span(name: str, *, sync=None, force: bool = False, **attrs):
    """Context manager timing the enclosed block under ``name``.

    ``sync``: optional array/pytree to ``block_until_ready`` before the
    timer closes (async dispatch must not hide device time). ``force``
    records even when telemetry is globally off (the ``utils/timers``
    compatibility path) — but then only into the (seconds, calls) table,
    like the old timers, never the per-call structured log. ``attrs``
    become span attributes in the export.
    """
    if not (ENABLED or force):
        return NULL_SPAN
    return _spans.open(name, True, sync=sync, log=ENABLED, **attrs)


def span_event(name: str, **fields) -> None:
    """Attach a per-iteration record (hop/round/stage) to the innermost
    open span — or log it top-level if no span is open."""
    if not ENABLED:
        return
    _spans.event(name, **fields)


# --- per-request tracing (round 15, obs/trace.py) ---------------------------


def request_trace(rid, kind: str | None = None,
                  tenant: str | None = None):
    """Open a deterministic-sampled per-request trace (None when obs
    is off or the sampler declines ``rid``) — the serve read lane's
    entry.  One function call + flag check when disabled."""
    if not ENABLED:
        return None
    return _trace.begin(rid, "serve.request", kind=kind, tenant=tenant)


def update_trace(rid, tenant: str | None = None):
    """The write lane's trace entry (``name="serve.update"``)."""
    if not ENABLED:
        return None
    return _trace.begin(rid, "serve.update", tenant=tenant)


def trace_records() -> list[dict]:
    """Completed per-request trace records (schema kind ``trace``)."""
    return _trace.records()


def prune_labels(**labels) -> int:
    """Drop every registry series labeled with ALL the given pairs
    (tenant-churn label-space hygiene; works whether or not telemetry
    is currently enabled — stale series from an earlier enabled phase
    must still be removable)."""
    return registry.prune_labels(**labels)


# --- providers (pull-style gauges, polled at export time) -------------------


def register_provider(fn) -> None:
    """Register a zero-arg callable that refreshes gauges (via
    ``obs.gauge``) when a report/dump is produced — e.g. lru_cache
    hit/miss/size exporters that would be wasteful to push on every
    cache access."""
    if fn not in _providers:
        _providers.append(fn)


def _run_providers() -> None:
    if not ENABLED:
        return
    for fn in list(_providers):
        try:
            fn()
        except Exception:  # a broken provider must not kill the export
            registry.count("obs.provider_errors")


# --- readers / sinks --------------------------------------------------------


def report(reset: bool = False) -> dict[str, tuple[float, int]]:
    """The per-app timing table: {span name: (seconds, calls)} — what the
    reference prints after each run (TopDownBFS.cpp:472-479).
    ``reset=True`` clears only this table, not the structured span
    log/events (``reset()`` is the full wipe)."""
    out = _spans.table()
    if reset:
        _spans.clear_table()
    return out


def span_seconds(name: str) -> float:
    return _spans.seconds(name)


def print_report(reset: bool = False) -> None:
    for k, (sec, n) in report(reset=reset).items():
        print(f"{k:32s} {sec:10.4f}s  x{n}")


def metrics_snapshot() -> list[dict]:
    _run_providers()
    return registry.snapshot()


def dump_jsonl(path: str | None = None, *, process: int | None = None,
               nprocs: int | None = None) -> str:
    """Write the full telemetry state as one schema-versioned JSONL file
    (meta line, spans, events, metrics). Default path is the one given
    to ``enable``; the file is rewritten whole on each call."""
    path = path or _jsonl_path
    if path is None:
        raise ValueError("no JSONL path: pass one or enable(jsonl_path=...)")
    if process is None or nprocs is None:
        try:
            import jax

            process = jax.process_index() if process is None else process
            nprocs = jax.process_count() if nprocs is None else nprocs
        except Exception:
            process, nprocs = process or 0, nprocs or 1
    _run_providers()
    records = encode_records(
        registry.snapshot(), _spans, process=process, nprocs=nprocs,
        traces=_trace.records(),
    )
    return write_jsonl(path, records)


# --- jax.monitoring bridge --------------------------------------------------

_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"


def install_jax_hooks() -> bool:
    """Bridge ``jax.monitoring`` into the registry (idempotent):
    persistent-compile-cache hits/misses become the ``compile_cache.*``
    counters, every other ``/jax/...`` event is counted under its own
    path, and duration events (tracing/backend-compile times) land in
    histograms — the jit retrace/compile visibility layer."""
    global _hooks_installed
    if _hooks_installed:
        return True
    try:
        from jax import monitoring
    except Exception:
        return False

    def _on_event(event: str, **kw):
        if not ENABLED:
            return
        if event == _CACHE_HIT_EVENT:
            registry.count("compile_cache.hits")
        elif event == _CACHE_MISS_EVENT:
            registry.count("compile_cache.misses")
        else:
            registry.count(event)

    def _on_duration(event: str, duration_secs: float, **kw):
        if not ENABLED:
            return
        registry.observe(event, duration_secs)

    monitoring.register_event_listener(_on_event)
    monitoring.register_event_duration_secs_listener(_on_duration)
    # seed the cache counters so every dump carries them, hit or not
    registry.count("compile_cache.hits", 0)
    registry.count("compile_cache.misses", 0)
    _hooks_installed = True
    return True


#: The per-request tracing module (``obs.trace`` — sampling knobs,
#: ``stage_summary`` for bench decompositions).
trace = _trace

__all__ = [
    "ENABLED", "DEVICE_SYNC", "SCHEMA", "SCHEMA_VERSION",
    "FLIGHTREC_SCHEMA", "FLEETLOG_SCHEMA",
    "enable", "disable", "enabled", "enable_sidecar", "reset",
    "reset_spans",
    "count", "gauge", "observe", "span", "span_event",
    "request_trace", "update_trace", "trace_records", "prune_labels",
    "register_provider", "report", "print_report", "span_seconds",
    "metrics_snapshot", "dump_jsonl", "install_jax_hooks",
    "parse_jsonl", "merge_jsonl_files", "aggregate", "validate_record",
    "encode_records", "write_jsonl", "psum_counters", "registry",
    "quantiles", "quantile_summary", "trace",
    "MetricsRegistry", "SpanTracker", "NULL_SPAN",
]
