"""Metrics registry: counters, gauges, histograms with labels.

The structured replacement for the reference's global ``cblas_*`` counter
variables (``CombBLAS.h:77-102``): instead of a fixed set of doubles, a
registry of named scalar facts — nnz in/out, SpGEMM symbolic flops,
redistribute drop counts, compile-cache hits, per-op load imbalance —
each optionally qualified by labels (``kernel="summa"``), snapshottable
for the JSONL exporter and mergeable across processes.

Everything here is plain host-side Python over dicts: no JAX arrays ever
enter the registry (call sites convert to ``int``/``float`` first), so a
metric can never smuggle a tracer or force a device sync.

SpGEMM tier-router series (round 6 — the auto-tiered kernel ladder,
docs/spgemm.md):

==================================  =======  ==============================
name                                kind     meaning
==================================  =======  ==============================
``spgemm.auto.tier``                counter  calls routed per tier; labels
                                             ``tier`` (mxu / windowed /
                                             scan / esc / edgeharvest) and
                                             ``sr`` (semiring name)
``spgemm.windowed.windows_skipped`` counter  row blocks skipped because the
                                             symbolic pass proved them
                                             empty (never scanned)
``spgemm.windowed.blocks``          gauge    row blocks in the last plan
``spgemm.auto.mask_density``        gauge    symbolic output-support bound
                                             over dense cells (the oracle's
                                             density estimate)
``trace.summa_spgemm_windowed``     counter  kernel (re)traces, labeled by
                                             accumulate ``backend``
                                             (``scatter``/``dot``/``dot2d``)
==================================  =======  ==============================

2D windowed ``dot`` backend series (round 7 — the B-column-windowed MXU
tier that makes ``windowed`` the TPU mid-scale default, docs/spgemm.md):

=========================================  =======  =====================
name                                       kind     meaning
=========================================  =======  =====================
``spgemm.windowed.col_windows_skipped``    counter  (row block, col
                                                    window) pairs proved
                                                    symbolically empty —
                                                    never densified,
                                                    matmul'd, or scanned
``spgemm.windowed.col_windows``            gauge    col windows per row
                                                    block in the last 2D
                                                    plan
``spgemm.windowed.panel_cells``            gauge    padded-k × padded-
                                                    window cells of one
                                                    dense B stage panel
                                                    (the stage-operand
                                                    memory envelope; ≤
                                                    WINDOWED_MAX_PANEL_
                                                    CELLS when routed)
``spgemm.windowed.window_density``         gauge    symbolic output bound
                                                    over dense cells,
                                                    restricted to LIVE
                                                    (non-skipped) windows
``spgemm.auto.dedup_fallback``             counter  mxu routings demoted
                                                    because a tile held
                                                    duplicate entries
                                                    (labels: ``sr``)
``spgemm.windowed.oracle_skipped``         counter  oracle=True requests
                                                    that fell back to
                                                    clamped-flops caps
                                                    (outside the oracle
                                                    envelope)
=========================================  =======  =====================

Serve resilience series (round 8 — fault injection, poisoned-batch
isolation, circuit breakers, graph hot-swap; docs/serving.md
"Resilience"):

==============================  =========  ==============================
name                            kind       meaning
==============================  =========  ==============================
``serve.faults.injected``       counter    faults fired by the injection
                                           framework; labels ``point``
                                           (serve/faults.py
                                           FAULT_POINTS) and ``rule``
                                           (script/rate/when)
``serve.retry.requests``        counter    requests re-executed by the
                                           poisoned-batch bisection
                                           retrier (labels: ``kind``)
``serve.poison.isolated``       counter    requests failed after
                                           exhausting the retry budget
                                           (the isolated poison, or
                                           every rider of a genuinely
                                           dead engine); labels ``kind``
``serve.breaker.state``         gauge      per-kind breaker state:
                                           0 closed / 1 half-open /
                                           2 open (labels: ``kind``)
``serve.breaker.opened``        counter    breaker open transitions
                                           (labels: ``kind``)
``serve.breaker.fast_fail``     counter    submits rejected by an open
                                           breaker (labels: ``kind``)
``serve.worker.errors``         counter    worker-loop (scheduler-bug)
                                           errors, labeled by
                                           ``exc_type``
``serve.worker.backoff_s``      gauge      current worker error backoff
                                           (exponential, capped, reset
                                           on success)
``serve.swap.latency_s``        histogram  atomic graph-version swap
                                           latency (lock wait + pointer
                                           flip)
``serve.swap.build_s``          histogram  off-lock build time of the
                                           next GraphVersion
``serve.swap.count``            counter    completed hot-swaps
``serve.graph.version``         gauge      currently-served graph
                                           version id
==============================  =========  ==============================

``serve.requests{status=timeout}`` now also counts EXECUTION-time
deadline drops (a request already expired when its batch reached the
device is settled before occupying a lane), not just queue-sweep
expiries.

Pipelined / packed / 3D SpGEMM series (round 9 — the stage-pipelined
windowed carousel, oracle-packed launches, and the windowed 3D tier;
docs/spgemm.md):

=======================================  =======  =====================
name                                     kind     meaning
=======================================  =======  =====================
``spgemm.pipeline.stages_overlapped``    counter  TRACE-TIME: carousel
                                                  stages whose
                                                  successor rotation
                                                  was issued before
                                                  their accumulate
                                                  (p−1 per compiled
                                                  pipelined ring
                                                  program; the jit
                                                  retrace-visibility
                                                  convention of the
                                                  ``trace.*`` series)
``spgemm.windowed.windows_packed``       counter  windows in the packed
                                                  launch list — the
                                                  MXU/scatter launches
                                                  a plan actually pays
                                                  (vs ``blocks`` ×
                                                  ``col_windows``
                                                  total)
``spgemm.windowed.pack_ratio``           gauge    windows_packed /
                                                  windows_total of the
                                                  last plan (< 1 means
                                                  the skip list or the
                                                  oracle pruned
                                                  launches)
``spgemm.summa3d.layers``                gauge    L of the last 3D
                                                  windowed product
                                                  (``spgemm3d_windowed``
                                                  / the ``windowed3d``
                                                  auto route)
``trace.summa3d_spgemm_windowed``        counter  3D windowed kernel
                                                  (re)traces, labeled
                                                  by accumulate
                                                  ``backend``
``trace.summa_spgemm_windowed``          counter  gains a ``ring``
                                                  label (gathered vs
                                                  carousel schedule)
=======================================  =======  =====================

Span events: the carousel body emits one ``spgemm.pipeline.stage``
event per stage at trace time (fields ``stage``,
``overlapped`` — whether the next rotation was issued early), so a
trace export shows the planned comm/compute overlap structure of the
compiled schedule.

Autotuner / plan-store series (round 10 — the measured-cost plan store
and micro-probe pass, docs/autotuning.md):

===================================  =======  =========================
name                                 kind     meaning
===================================  =======  =========================
``tuner.store.hits``                 counter  routing decisions served
                                              from a remembered plan
                                              (labels: ``op`` =
                                              spgemm / spgemm3d)
``tuner.store.misses``               counter  lookups with no matching
                                              plan (probe or fallback
                                              follows)
``tuner.store.entries``              gauge    plans currently loaded
                                              (labels: ``dir``); also
                                              published by the
                                              compile-cache provider
                                              as ``compile_cache.
                                              entries{cache=plans}`` —
                                              one health surface for
                                              both caches
``tuner.store.invalid``              counter  corrupted / truncated /
                                              schema-mismatched JSONL
                                              lines skipped at load
``tuner.store.write_errors``         counter  failed store appends
                                              (read-only replica; the
                                              in-memory plan still
                                              routes)
``tuner.probe.runs``                 counter  candidate rungs measured
                                              by the micro-probe pass
                                              (labels: ``tier``)
``tuner.probe.seconds``              counter  cumulative timed probe
                                              seconds (the obs-visible
                                              probe cost)
``tuner.probe.winner``               counter  probe passes won per
                                              tier (labels: ``tier``)
``tuner.probe.errors``               counter  candidate rungs that
                                              faulted on the proxy
                                              (dropped, not fatal)
``tuner.probe.budget_exhausted``     counter  probe passes cut short
                                              by the probe budget
``tuner.store.rejected``             counter  key-matched records
                                              DISCARDED at routing
                                              (labels: ``reason`` =
                                              tier / no_grid3 / dup) —
                                              pair with ``hits`` to
                                              read the true hit rate
``spgemm.windowed.dispatch_conflict``  counter  ring requests that
                                              overrode an explicit
                                              blocked dispatch (ring
                                              is fused-only; the more
                                              specific ask wins)
``spgemm.auto.plan_source``          counter  WHERE each routing came
                                              from: labels ``source``
                                              (arg / store / env /
                                              probe / heuristic),
                                              ``tier``, ``op``
``spgemm.windowed.dispatch``         counter  windowed-tier program
                                              decomposition per call:
                                              labels ``mode`` (local /
                                              fused / blocked — the
                                              building-block default)
===================================  =======  =========================

The ``tuner.probe`` span wraps each probe pass (attrs ``sr``, proxy
``dim``), so trace exports show probe cost inline with the product that
paid it.

Dynamic-graph mutation series (round 11 — delta buffers, incremental
version builds, warm-restart recompute, the serve write lane;
docs/dynamic.md):

====================================  =========  =======================
name                                  kind       meaning
====================================  =========  =======================
``dynamic.delta.depth``               gauge      ops pending in a
                                                 ``DeltaBuffer``
``dynamic.delta.ops``                 counter    ops admitted (labels:
                                                 ``op`` = insert /
                                                 delete / upsert)
``dynamic.delta.batches``             counter    batches drained
``dynamic.delta.age_s``               histogram  oldest-op age at drain
                                                 (write-coalescing
                                                 latency)
``dynamic.state.bootstrap``           counter    merge states built
                                                 from scratch (first
                                                 ``apply_delta`` on a
                                                 version without one)
``dynamic.merge.applied``             counter    ``apply_delta`` calls,
                                                 labels ``mode`` =
                                                 incremental / rebuild
                                                 (the amortization
                                                 ratio's numerator and
                                                 denominator)
``dynamic.merge.spill``               counter    incremental attempts
                                                 that fell back to a
                                                 rebuild; labels
                                                 ``reason`` (threshold /
                                                 bucket_full / no_state
                                                 / forced)
``dynamic.merge.latency_s``           histogram  wall time of one
                                                 ``apply_delta``
``dynamic.merge.rows_patched``        counter    rows rewritten in
                                                 place (degree class
                                                 survived)
``dynamic.merge.rows_rebucketed``     counter    rows that claimed a
                                                 free slot in another
                                                 degree class
``dynamic.merge.edges_inserted``      counter    edges added by merges
``dynamic.merge.edges_removed``       counter    edges removed by merges
``dynamic.refresh.runs``              counter    ``engine.refresh``
                                                 calls; labels ``kind``
                                                 (bfs / cc / pagerank),
                                                 ``mode`` (cached /
                                                 warm / cold)
``dynamic.refresh.iters``             histogram  sweeps/iterations one
                                                 refresh ran (labels
                                                 ``kind``, ``mode`` —
                                                 warm-restart savings)
``dynamic.refresh.latency_s``         histogram  refresh wall time
                                                 (labels ``kind``,
                                                 ``mode``)
``serve.update.submitted``            counter    ``submit_update``
                                                 admissions
``serve.update.rejected``             counter    write-lane
                                                 backpressure rejects
                                                 (full delta buffer)
``serve.update.invalid``              counter    malformed update
                                                 batches (failed their
                                                 own future)
``serve.update.merges``               counter    merge+swap cycles run
                                                 by the mutation
                                                 thread; labels
                                                 ``mode``
``serve.update.failed``               counter    merge cycles that
                                                 failed (their updates'
                                                 futures carry the
                                                 error); labels
                                                 ``exc_type``
``serve.update.coalesced``            histogram  ops per merged batch
                                                 (write coalescing)
``tuner.store.compacted``             counter    superseded/evicted
                                                 JSONL lines removed by
                                                 the load-time
                                                 compaction rewrite
``tuner.store.evicted``               counter    plans dropped by the
                                                 max-entries
                                                 oldest-cost eviction
====================================  =========  =======================

Batched-SpMM / propagate-lane series (round 12 — the MXU-resident
SpMM kernel family, the ``"propagate"`` serve kind, headroom-aware
bucket sizing and window-geometry probing; docs/spmm.md):

====================================  =======  =========================
name                                  kind     meaning
====================================  =======  =========================
``trace.spmm_ell``                    counter  TRACE-TIME: ELL SpMM
                                               kernel (re)traces,
                                               labels ``backend``
                                               (mxu_gather / scatter)
                                               and ``sr`` — the
                                               retrace-visibility
                                               convention of the
                                               ``trace.*`` series
``trace.summa_spmm``                  counter  SUMMA SpMM (re)traces,
                                               labels ``ring``
                                               (gathered vs carousel)
                                               and ``backend``
``trace.spmm_khop``                   counter  fused k-hop program
                                               (re)traces, labels
                                               ``hops`` / ``backend``
                                               / ``normalize``
``spmm.pipeline.stages_overlapped``   counter  TRACE-TIME: carousel
                                               stages whose successor
                                               panel rotation was
                                               issued before their
                                               contraction (p−1 per
                                               compiled pipelined ring
                                               program — the SpMM twin
                                               of ``spgemm.pipeline.
                                               stages_overlapped``)
``serve.propagate.feature_dim``       gauge    TRUE feature width of
                                               the loaded table (pad
                                               stripped; the pow2 pad
                                               width is the compiled
                                               shape)
``spgemm.auto.plan_source``           counter  gains ``op="spmm"``
                                               rows: where each SpMM
                                               backend resolution came
                                               from (arg / store / env
                                               / probe / heuristic)
``dynamic.merge.headroom_used``       counter  free padding slots
                                               claimed by re-bucketing
                                               rows (the
                                               ``from_coo(headroom=)``
                                               reserve paying off
                                               instead of a
                                               ``bucket_full`` spill)
``tuner.probe.geometry_runs``         counter  windowed block-geometry
                                               candidates measured by
                                               the probe's
                                               window-geometry sweep
                                               (the winner persists
                                               with ``block_rows`` /
                                               ``block_cols`` in its
                                               plan record)
====================================  =======  =========================

Merge-tier / 3D-carousel series (round 13 — the sort-free fiber
reduce and the carousel-pipelined per-layer 3D SUMMA;
docs/spgemm.md "merge tiers"):

====================================  =======  =========================
name                                  kind     meaning
====================================  =======  =========================
``spgemm.merge.tier``                 counter  combine-merge tier each
                                               merge-consuming entry
                                               resolved (labels
                                               ``tier`` = sort / runs
                                               / hash, ``source`` =
                                               arg / store / env /
                                               probe / heuristic /
                                               hash_fallback, with a
                                               ``_degraded`` suffix
                                               when a forced hash on a
                                               generic monoid degraded
                                               to runs at the knob,
                                               and ``op``)
``spgemm.merge.hash_overflow``        counter  entries the hash tier's
                                               bounded table failed to
                                               place (the product
                                               transparently reruns
                                               through the sorted-runs
                                               tier — this counter is
                                               how a mis-routed plan
                                               gets noticed)
``spgemm.summa3d.piece_overflow``     counter  fiber-exchange entries
                                               that exceeded
                                               piece_capacity (the
                                               entry RAISES naming the
                                               slack knob; round-13
                                               bugfix — previously
                                               detected but silently
                                               ignored by callers)
``trace.summa3d_spgemm``              counter  TRACE-TIME: ESC 3D
                                               SUMMA (re)traces,
                                               labels ``ring`` /
                                               ``merge``
``trace.summa3d_spgemm_windowed``     counter  gains ``ring`` /
                                               ``merge`` labels (the
                                               per-layer carousel)
``spgemm.pipeline.stages_overlapped`` counter  now ALSO emitted by the
                                               3D kernels' pipelined
                                               rings (p−1 per layer
                                               program per compiled
                                               trace, same trace-time
                                               convention)
``trace.summa_spgemm``                counter  gains the ``merge``
                                               label (2D ESC
                                               stage-chunk combine)
====================================  =======  =========================

Multi-tenant pool / fleet series (round 14 — the engine pool, WFQ
scheduling and the replicated serving fleet; docs/serving.md
"Multi-tenant pool & fleet"):

====================================  =======  =========================
name                                  kind     meaning
====================================  =======  =========================
``serve.pool.resident_bytes``         gauge    device bytes of all
                                               resident tenant
                                               versions (the LRU's
                                               accounting surface —
                                               ``GraphVersion.
                                               device_bytes``)
``serve.pool.resident_tenants``       gauge    tenants whose engine is
                                               currently on-device
``serve.pool.admits``                 counter  engine builds/rebuilds
                                               (label ``tenant``) —
                                               re-admission after an
                                               eviction counts here
``serve.pool.evictions``              counter  device-state evictions
                                               (label ``tenant``)
``serve.pool.over_budget``            counter  admits that found no
                                               idle victim and left
                                               the pool over its byte
                                               budget
``serve.pool.rebuild_s``              hist     admit-time engine build
                                               latency (the rebuild-
                                               not-reload cost)
``serve.wfq.rounds``                  counter  deficit-round-robin
                                               scheduling rounds
``serve.wfq.served``                  counter  requests/ops charged
                                               per tenant (label
                                               ``tenant``) — the
                                               weighted-share property
                                               is asserted on this
``serve.wfq.deficit``                 gauge    per-tenant deficit
                                               balance at round grant
                                               (label ``tenant``)
``serve.fleet.replicas``              gauge    replica count behind
                                               the router
``serve.fleet.submitted``             counter  queries routed (label
                                               ``replica``)
``serve.fleet.spillover``             counter  backpressure re-routes
                                               to the next replica
                                               (label ``replica`` =
                                               the one that rejected)
``serve.fleet.fanout``                counter  home-merge version
                                               fan-outs applied fleet-
                                               wide
``serve.fleet.fanout_s``              hist     wall time of one full
                                               fan-out (rebuilds +
                                               atomic swaps)
``serve.checkpoint.save_s``           hist     ``save_version``
                                               snapshot wall time
``serve.checkpoint.load_s``           hist     ``load_version``
                                               restore wall time (one
                                               device_put per array)
====================================  =======  =========================

Pre-existing serve series gain a ``tenant`` label when the emitting
scheduler/breaker is owned by a pool tenant (``serve.queue.depth``,
``serve.queue.rejected``, ``serve.requests``, ``serve.breaker.*``);
single-tenant servers emit the unchanged label sets.

Foundation series (rounds 1-5 — cataloged here since round 15; the
static catalog-drift sweep in tests/test_obs_catalog.py asserts every
literal ``obs.count/gauge/observe`` series name in the package appears
in this docstring):

=====================================  =========  =====================
name                                   kind       meaning
=====================================  =========  =====================
``spgemm.symbolic_fill_slots``         counter    symbolic fill-in of a
                                                  product (pre-launch)
``spgemm.realized_nnz``                counter    realized output nnz
                                                  (DEVICE_SYNC only)
``spgemm.load_imbalance``              gauge      max/mean per-tile
                                                  flops (the
                                                  reference's
                                                  LoadImbalance)
``spgemm.phases``                      gauge      multi-phase SpGEMM
                                                  phase count
``spgemm.phase_adjusted``              counter    phase counts adjusted
                                                  upward by the memory
                                                  estimator
``spgemm.scan.overflow_retries``       counter    scan-tier capacity
                                                  retries
``spgemm.scan.overflow_slots``         counter    slots dropped pre-
                                                  retry (always
                                                  retried to zero)
``spgemm.mxu.overflow_retries``        counter    mxu-tier extraction
                                                  retries
``trace.summa_spgemm_mxu``             counter    TRACE-TIME kernel
                                                  (re)traces (mxu tier)
``trace.summa_spgemm_scan``            counter    TRACE-TIME kernel
                                                  (re)traces (scan
                                                  tier)
``trace.redistribute_coo``             counter    TRACE-TIME
                                                  redistribute
                                                  (re)traces
``redistribute.dropped``               counter    entries dropped by a
                                                  capacity-bounded
                                                  route (0 = complete)
``redistribute.retries``               counter    capacity-doubling
                                                  retries
``redistribute.stage_capacity``        gauge      per-stage routing
                                                  capacity of the last
                                                  call
``redistribute.tile_capacity``         gauge      per-tile landing
                                                  capacity of the last
                                                  call
``spmv.dispatch``                      counter    SpMV dispatches per
                                                  kernel (labels:
                                                  ``kernel``)
``compile_cache.hits/misses``          counter    persistent XLA cache
                                                  traffic (the
                                                  jax.monitoring
                                                  bridge)
``compile_cache.entries``              gauge      cache files on disk
                                                  (labels ``cache`` =
                                                  xla / plans)
``compile_cache.disabled``             counter    enable_compile_cache
                                                  refusals (cache dir
                                                  conflicts)
``mcl.perturb_kicks``                  counter    MCL chaos-plateau
                                                  perturbation kicks
``mcl.block_rerolls``                  counter    MCL sparse-block
                                                  capacity rerolls
``k1.*`` (``k1.<stage>_s``)            histogram  Graph500 kernel-1
                                                  stage seconds
``cache.bfs.*``                        gauge      BFS lru-cache
                                                  hit/miss/size gauges
                                                  (provider-polled)
``serve.plan_cache.hits`` /            counter    engine plan-cache
``serve.plan_cache.misses``
                                                  traffic (labels
                                                  ``kind``, ``width``)
``trace.serve``                        counter    TRACE-TIME serve plan
                                                  (re)traces — the
                                                  zero-retrace gate
``serve.queue.depth``                  gauge      pending requests
``serve.queue.rejected``               counter    backpressure rejects
                                                  (labels ``kind``)
``serve.requests``                     counter    request dispositions
                                                  (labels ``kind``,
                                                  ``status`` = ok /
                                                  error / timeout /
                                                  invalid / cancelled)
``serve.request.latency_s``            histogram  submit-to-settle
                                                  latency (labels
                                                  ``kind``)
``serve.batch.occupancy``              histogram  live lanes / bucket
                                                  width per batch
``serve.batch.padding_waste``          histogram  pad lanes per batch
``serve.batches``                      gauge      total batches
                                                  executed
``obs.provider_errors``                counter    broken pull-provider
                                                  callbacks (caught)
``serve.bench.*``                      gauge      bench-scenario
                                                  headline gauges
                                                  (serve_bench.py)
=====================================  =========  =====================

Production-observability series (round 15 — per-request tracing, the
flight recorder, SLO error budgets, freshness gauges and the scrape
surface; docs/observability.md "Serving observability"):

========================================  =========  ==================
name                                      kind       meaning
========================================  =========  ==================
``serve.trace.sampled``                   counter    requests whose
                                                     deterministic
                                                     sample-hash
                                                     admitted a trace
                                                     (labels ``lane`` =
                                                     request / update)
``serve.trace.dropped``                   counter    completed traces
                                                     dropped by the
                                                     bounded trace log
``serve.flightrec.events``                counter    events recorded
                                                     into flight-
                                                     recorder rings
``serve.flightrec.dumps``                 counter    ring snapshots
                                                     written (labels
                                                     ``reason`` =
                                                     worker_error /
                                                     breaker_open /
                                                     poisoned /
                                                     merge_failed /
                                                     slo_breach /
                                                     manual)
``serve.slo.good``                        counter    requests that met
                                                     the SLO deadline
                                                     (labels ``kind``
                                                     [, ``tenant``])
``serve.slo.bad``                         counter    requests that blew
                                                     it — timeout,
                                                     error, poisoned,
                                                     rejected (labels
                                                     ``kind``
                                                     [, ``tenant``])
``serve.slo.budget_burn``                 gauge      rolling-window bad
                                                     count over the
                                                     error budget
                                                     ``(1 - target) x
                                                     window total``;
                                                     >= 1 = budget
                                                     exhausted (labels
                                                     [``tenant``])
``dynamic.freshness.versions_behind``     gauge      graph versions
                                                     between a cached
                                                     analytic and the
                                                     served version at
                                                     refresh time
                                                     (labels ``kind``)
``dynamic.freshness.repair_ratio``        gauge      warm / (warm +
                                                     cold) refresh
                                                     runs on this
                                                     engine — the
                                                     repair-vs-cold
                                                     ratio the
                                                     streaming bench
                                                     gates on
``obs.scrape.requests``                   counter    HTTP scrape hits
                                                     (labels ``path``)
========================================  =========  ==================

Durability & self-healing series (round 16 — the write-ahead log,
crash recovery, replica supervision and write-home failover;
docs/serving.md "Durability & self-healing"):

========================================  =========  ==================
name                                      kind       meaning
========================================  =========  ==================
``serve.wal.appends``                     counter    WAL records
                                                     durably appended
                                                     (data records and
                                                     drop tombstones;
                                                     frontier marks
                                                     are written by
                                                     truncation, not
                                                     counted here)
``serve.wal.append_s``                    histogram  per-append latency
                                                     (fsync included
                                                     under policy
                                                     ``always``)
``serve.wal.append_failed``               counter    appends that
                                                     failed — the
                                                     write was
                                                     REJECTED, never
                                                     acknowledged
                                                     undurable
``serve.wal.invalid``                     counter    damaged JSONL
                                                     lines skipped at
                                                     replay (counted
                                                     once per line;
                                                     the expected
                                                     torn-final-line
                                                     crash artifact
                                                     included)
``serve.wal.truncated``                   counter    replayed-prefix
                                                     records dropped
                                                     by checkpoint
                                                     truncation
``serve.checkpoint.auto``                 counter    snapshots taken
                                                     (labels
                                                     ``reason`` =
                                                     bootstrap / auto /
                                                     close / manual)
``serve.checkpoint.failed``               counter    failed snapshot
                                                     attempts (labels
                                                     ``exc_type``;
                                                     previous snapshot
                                                     + WAL stay
                                                     intact)
``serve.recovery.runs``                   counter    ``recover_version``
                                                     completions
``serve.recovery.replayed_ops``           counter    WAL ops replayed
                                                     through
                                                     ``apply_delta``
                                                     during recovery
``serve.recovery.recover_s``              histogram  snapshot-load +
                                                     replay wall time
``serve.recovery.snapshot_seq``           gauge      ``wal_seq`` stamp
                                                     of the snapshot
                                                     recovery loaded
``serve.recovery.snapshot_rejected``      counter    corrupt/truncated
                                                     snapshots skipped
                                                     (fallback to the
                                                     previous retained
                                                     one)
``serve.fleet.versions_behind``           gauge      fan-out
                                                     generations a
                                                     replica lags the
                                                     home (labels
                                                     ``replica``; > 0
                                                     degrades fleet
                                                     health)
``serve.fleet.fanout_failed``             counter    per-replica
                                                     rebuild/swap
                                                     failures inside
                                                     ``fan_out`` —
                                                     the replica lags,
                                                     the fleet
                                                     continues (labels
                                                     ``replica``)
``serve.fleet.supervisor``                counter    supervision events
                                                     (labels
                                                     ``action`` =
                                                     detected /
                                                     replaced / error /
                                                     warmup_error)
``serve.fleet.promotions``                counter    home promotions at
                                                     the WAL frontier
``serve.fleet.replaced``                  counter    dead replicas
                                                     rebuilt from
                                                     checkpoint+WAL
                                                     and re-admitted
                                                     (labels
                                                     ``replica``)
``serve.fleet.quarantined``               counter    dead servers taken
                                                     out of service,
                                                     pending futures
                                                     failed honestly
``serve.fleet.read_retry``                counter    reads re-submitted
                                                     to the next-best
                                                     replica after an
                                                     execution-side
                                                     failure (labels
                                                     ``replica`` — the
                                                     retry target)
``serve.fleet.drained`` /                 counter    rolling-restart
``serve.fleet.restored`` /                           lifecycle events
``serve.fleet.rolling_restarts``                     (labels
                                                     ``replica`` on
                                                     the per-replica
                                                     pair)
========================================  =========  ==================

Process-fleet series (round 17 — subprocess replicas with real crash
domains; docs/serving.md "Process fleet").  The shared policy layer
(``serve/policy.py``) emits the routing/supervision disposition under
the fleet's own prefix, so ``serve.procfleet.submitted`` /
``.spillover`` / ``.read_retry`` / ``.supervisor`` are the
``serve.fleet.*`` rows above with processes instead of threads; the
rows below are process-specific:

========================================  =========  ==================
name                                      kind       meaning
========================================  =========  ==================
``serve.procfleet.replicas``              gauge      subprocess replica
                                                     count behind the
                                                     router
``serve.procfleet.heartbeat_age_s``       gauge      seconds since a
                                                     replica's last
                                                     heartbeat (labels
                                                     ``replica``) —
                                                     the HANG detector:
                                                     a SIGSTOPped
                                                     process is alive
                                                     but silent, and
                                                     past the timeout
                                                     it is quarantined
                                                     and routed around
``serve.procfleet.rpc_latency_s``         histogram  per-RPC round-trip
                                                     over the framed
                                                     IPC channel
                                                     (labels ``op``)
``serve.procfleet.ipc_timeouts``          counter    RPCs that ran out
                                                     their per-request
                                                     deadline (labels
                                                     ``op``) — futures
                                                     fail; the router
                                                     never wedges on a
                                                     hung replica
``serve.procfleet.quarantined``           counter    replica processes
                                                     taken out of
                                                     service (in-flight
                                                     futures failed
                                                     honestly, process
                                                     SIGKILLed; labels
                                                     ``replica``)
``serve.procfleet.respawns``              counter    replacement
                                                     subprocesses
                                                     booted warm from
                                                     checkpoint+WAL
                                                     (labels
                                                     ``replica``)
``serve.procfleet.respawn_failed``        counter    failed respawn
                                                     attempts — the
                                                     fleet keeps
                                                     serving degraded
                                                     on survivors with
                                                     capped-backoff
                                                     retry (labels
                                                     ``replica``)
``serve.procfleet.promotions``            counter    dead-home
                                                     promotions at the
                                                     WAL frontier, over
                                                     IPC
``serve.procfleet.sigkills`` /            counter    scripted
``serve.procfleet.sigstops``                         ``ProcessFaultPlan``
                                                     signals fired at
                                                     replica processes
                                                     (labels
                                                     ``replica``)
``serve.procfleet.fanout``                counter    home-merge version
                                                     fan-outs (spooled
                                                     checkpoint file +
                                                     per-replica
                                                     ``swap_from_
                                                     checkpoint``)
``serve.procfleet.fanout_s``              histogram  wall time of one
                                                     full fan-out
                                                     (spool + swaps)
``serve.procfleet.fanout_failed``         counter    per-replica swap
                                                     failures inside a
                                                     fan-out — the
                                                     replica lags, the
                                                     fleet continues
                                                     (labels
                                                     ``replica``)
``serve.procfleet.versions_behind``       gauge      fan-out
                                                     generations a
                                                     replica lags the
                                                     home (labels
                                                     ``replica``)
``tuner.store.compact_skipped``           counter    plan-store
                                                     compactions
                                                     skipped on
                                                     advisory-lock
                                                     contention (a
                                                     sibling process
                                                     is compacting) —
                                                     the next loader
                                                     compacts instead
``tuner.store.append_unfenced``           counter    plan appends that
                                                     proceeded without
                                                     the shared fence
                                                     after the bounded
                                                     non-blocking
                                                     retries (a wedged
                                                     lock holder must
                                                     never hang the
                                                     write path)
========================================  =========  ==================

Fleet observability plane (round 18, the serve/procfleet.py +
serve/ipc.py cross-process plane; ``replica=``-labeled child-process
series additionally arrive in a ``ProcessFleet.serve_metrics()``
scrape via the heartbeat-piggybacked registry snapshots):

========================================  =========  ==================
``serve.ipc.bytes_out`` /                 counter    framed bytes sent/
``serve.ipc.bytes_in``                               received on one
                                                     IPC channel, wire
                                                     size incl. the
                                                     length prefix —
                                                     the isolation
                                                     tax's bandwidth
                                                     half (labels
                                                     ``peer``)
``serve.ipc.encode_s`` /                  histogram  frame encode /
``serve.ipc.decode_s``                               decode seconds —
                                                     the serialization
                                                     half of the
                                                     isolation tax
                                                     (labels ``peer``)
``serve.ipc.deadline_missed``             counter    RPCs that expired
                                                     in the parent-side
                                                     deadline sweep (a
                                                     hung replica's
                                                     per-request
                                                     failure; labels
                                                     ``replica``)
``serve.procfleet.hb_snapshots``          counter    child registry
                                                     snapshots
                                                     piggybacked on
                                                     heartbeats (the
                                                     federation wire;
                                                     emitted INSIDE the
                                                     child process)
``serve.fleetlog.events``                 counter    supervision
                                                     timeline events
                                                     appended to the
                                                     ``combblas_tpu.
                                                     fleetlog/v1`` log
                                                     (labels ``event``)
========================================  =========  ==================

Network front door (round 19, serve/net/ — the TCP frontend; wire
byte/serialization accounting rides the shared ``serve.ipc.*`` series
above with ``peer="net"`` / ``peer="netclient"``, one codec for both
transports):

========================================  =========  ==================
``serve.net.connections``                 gauge      currently-open
                                                     admitted
                                                     connections
``serve.net.accept_queue``                gauge      connections
                                                     accepted but still
                                                     mid-handshake
                                                     (hello pending)
``serve.net.requests``                    counter    request frames
                                                     dispatched (labels
                                                     ``op``)
``serve.net.bytes_in`` /                  counter    wire bytes per
``serve.net.bytes_out``                              reply direction
                                                     incl. the length
                                                     prefix (derived
                                                     from the channel
                                                     byte totals)
``serve.net.status``                      counter    replies by
                                                     protocol status
                                                     code (labels
                                                     ``code`` — the
                                                     error-taxonomy
                                                     wire mapping;
                                                     rejections are
                                                     COUNTED wire
                                                     replies, never
                                                     dropped
                                                     connections)
``serve.net.reply_drops``                 counter    replies whose
                                                     connection was
                                                     gone at send time
                                                     (the request still
                                                     settled — dropped
                                                     reply, not a
                                                     stranded future)
========================================  =========  ==================

Sharded serving (round 20, serve/shard.py + serve/_shardworker.py —
one graph partitioned over N slice processes, served as one engine;
slice-side series carry a ``slice=`` label):

========================================  =========  ==================
``serve.shard.slices``                    gauge      live slice count
                                                     (0 after close)
``serve.shard.batch``                     histogram  router wall per
                                                     batch (span;
                                                     labels ``kind``,
                                                     ``width``)
``serve.shard.hops``                      counter    bulk-synchronous
                                                     hop rounds fanned
                                                     to every slice
``serve.shard.hop_s``                     histogram  slice-side wall of
                                                     one hop program
``serve.shard.exec_retries``              counter    whole-batch
                                                     replays after a
                                                     mid-batch slice
                                                     death + heal
``serve.shard.writes``                    counter    two-phase write
                                                     batches committed
                                                     on every slice
``serve.shard.write_aborts``              counter    phase-1 append
                                                     failures (batch
                                                     tombstoned, write
                                                     rejected)
``serve.shard.wal_appends`` /             counter    per-slice phase-1
``serve.shard.wal_aborts``                           appends / abort
                                                     tombstones
``serve.shard.commits``                   counter    per-slice phase-2
                                                     applies (frontier
                                                     advances)
``serve.shard.merge_s``                   histogram  slice-side slab
                                                     merge latency
``serve.shard.frontier_min``              gauge      vector frontier
                                                     minimum (the
                                                     scalar wal_seq
                                                     projection)
``serve.shard.frontier_lag``              gauge      max-min frontier
                                                     spread right after
                                                     a commit round
``serve.shard.checkpoints``               counter    slab snapshots
                                                     (labels ``reason``)
``serve.shard.checkpoint_failed``         counter    failed slab
                                                     auto-snapshots
                                                     (previous snapshot
                                                     + WAL intact)
``serve.shard.recoveries``                counter    slice boots via
                                                     slab snapshot +
                                                     filtered WAL-
                                                     suffix replay
``serve.shard.slice_deaths``              counter    slices quarantined
                                                     (dead / hung /
                                                     failed RPC)
``serve.shard.replacements``              counter    successful slice
                                                     respawns
``serve.shard.respawn_failed``            counter    respawn attempts
                                                     that failed (next
                                                     try after capped
                                                     backoff)
``serve.shard.heal_wait_s``               histogram  wall spent driving
                                                     supervision until
                                                     all slices serve
``serve.shard.supervisor_errors``         counter    supervisor-loop
                                                     tick exceptions
``serve.shard.hb_snapshots``              counter    slice-worker
                                                     heartbeat metric
                                                     snapshots
                                                     federated to the
                                                     router
``trace.serve.shard``                     counter    slice hop-program
                                                     (re)traces (labels
                                                     ``kind``,
                                                     ``width``,
                                                     ``slice``)
========================================  =========  ==================

Sharded hop wire protocol (round 21, serve/shard.py — sparse frontier
triples + slice-resident loop state; see docs/serving.md "Sharded hop
wire protocol"):

========================================  =========  ==================
``serve.shard.hop_bytes``                 counter    logical payload
                                                     bytes per fan
                                                     (labels
                                                     ``direction``
                                                     out|in,
                                                     ``encoding``
                                                     sparse|dense|
                                                     final|collect)
``serve.shard.frontier_nnz``              histogram  router-side
                                                     frontier entries
                                                     per hop (label
                                                     ``kind``)
``serve.shard.encoding``                  counter    per-hop router
                                                     encoding decision
                                                     (label ``choice``
                                                     sparse|dense;
                                                     frontier hops
                                                     only)
``serve.shard.stale_epochs``              counter    healthy-slice
                                                     resident-state
                                                     misses that forced
                                                     a whole-batch
                                                     replay (label
                                                     ``kind``)
``serve.shard.wire_quant_err``            histogram  router-side max
                                                     abs bf16
                                                     quantization error
                                                     per outbound dense
                                                     payload (only
                                                     under
                                                     COMBBLAS_SHARD_
                                                     WIRE=bf16)
========================================  =========  ==================
"""

from __future__ import annotations

import threading

from .sinks import quantile_summary

#: Metric-kind tags used in snapshots and the JSONL schema.
KIND_COUNTER = "counter"
KIND_GAUGE = "gauge"
KIND_HISTOGRAM = "histogram"

#: Per-histogram sample reservoir size (round 15): the last RESERVOIR
#: observations ride along in snapshots so quantile summaries
#: (p50/p95/p99) are computable ONCE (``sinks.quantile_summary``) for
#: the Prometheus exporter, ``aggregate()`` and the bench sidecars —
#: instead of every bench keeping its own latency list.  Overflow
#: overwrites in arrival order (a sliding window of recent values).
RESERVOIR = 512


def _label_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


class MetricsRegistry:
    """Threadsafe in-memory metric store.

    Counters are monotonically-added floats/ints; gauges hold the last
    set value; histograms keep (count, sum, min, max) — enough for the
    per-app tables and for cross-process aggregation without binning
    policy baked in.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple, float] = {}
        self._gauges: dict[tuple, float] = {}
        # [count, sum, min, max, samples] — samples is the bounded
        # quantile reservoir (RESERVOIR), overwritten in arrival order
        self._hists: dict[tuple, list] = {}

    def _key(self, name: str, labels: dict) -> tuple:
        # labels live inside the key (sorted tuple); snapshot()
        # reconstructs the dict from it
        return (name, _label_key(labels))

    # -- writers -----------------------------------------------------------
    def count(self, name: str, value=1, **labels):
        with self._lock:
            key = self._key(name, labels)
            self._counters[key] = self._counters.get(key, 0) + value

    def gauge(self, name: str, value, **labels):
        with self._lock:
            self._gauges[self._key(name, labels)] = value

    def observe(self, name: str, value, **labels):
        with self._lock:
            key = self._key(name, labels)
            h = self._hists.get(key)
            if h is None:
                self._hists[key] = [1, value, value, value, [value]]
            else:
                h[0] += 1
                h[1] += value
                h[2] = min(h[2], value)
                h[3] = max(h[3], value)
                samples = h[4]
                if len(samples) < RESERVOIR:
                    samples.append(value)
                else:  # sliding window: overwrite in arrival order
                    samples[(h[0] - 1) % RESERVOIR] = value

    # -- readers -----------------------------------------------------------
    def get_counter(self, name: str, default=0, **labels):
        return self._counters.get((name, _label_key(labels)), default)

    def get_gauge(self, name: str, default=None, **labels):
        return self._gauges.get((name, _label_key(labels)), default)

    def get_histogram(self, name: str, **labels):
        h = self._hists.get((name, _label_key(labels)))
        if h is None:
            return None
        return {
            "count": h[0], "sum": h[1], "min": h[2], "max": h[3],
            **quantile_summary(h[4]),
        }

    def empty(self) -> bool:
        return not (self._counters or self._gauges or self._hists)

    def snapshot(self) -> list[dict]:
        """All metrics as schema records (no ``v``/``ts`` envelope — the
        sink adds those)."""
        with self._lock:
            out = []
            for (name, lk), v in sorted(self._counters.items()):
                out.append({
                    "kind": KIND_COUNTER, "name": name,
                    "labels": dict(lk), "value": v,
                })
            for (name, lk), v in sorted(self._gauges.items()):
                out.append({
                    "kind": KIND_GAUGE, "name": name,
                    "labels": dict(lk), "value": v,
                })
            for (name, lk), h in sorted(self._hists.items()):
                out.append({
                    "kind": KIND_HISTOGRAM, "name": name,
                    "labels": dict(lk), "count": h[0], "sum": h[1],
                    "min": h[2], "max": h[3],
                    # the bounded reservoir + its quantile summary:
                    # computed HERE once, reused by the exporter,
                    # aggregate() and the bench sidecars
                    "samples": [round(float(v), 9) for v in h[4]],
                    **quantile_summary(h[4]),
                })
            return out

    def prune_labels(self, **labels) -> int:
        """Delete every series whose label set CONTAINS all the given
        ``key=value`` pairs (round 15: the tenant-churn label-space
        prune — a removed pool tenant's ``tenant=...`` series must not
        live in the registry, and its scrape surface, forever).
        Returns the number of series removed."""
        items = tuple(labels.items())
        if not items:
            return 0

        def hit(lk: tuple) -> bool:
            d = dict(lk)
            return all(d.get(k) == v for k, v in items)

        removed = 0
        with self._lock:
            for store in (self._counters, self._gauges, self._hists):
                dead = [k for k in store if hit(k[1])]
                for k in dead:
                    del store[k]
                removed += len(dead)
        return removed

    def clear(self):
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
