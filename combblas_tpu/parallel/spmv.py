"""Distributed semiring SpMV over the 2D grid (≈ ParFriends SpMV family).

The reference's dense-vector SpMV (``include/CombBLAS/ParFriends.h:1925-2155``)
runs four explicit communication phases per call:

    TransposeVector (diag pair Sendrecv)  →  AllGatherVector (col world)
    →  local kernel  →  row-world fold (Alltoallv + MergeContributions)

On TPU the first two phases are *free*: a col-aligned ``DistVec`` is already
replicated down each grid column by its sharding, so the gather never appears
in the program — XLA materializes the replication once, when the vector is
built or realigned.  Only the fold remains: a semiring all-reduce over the
``"c"`` axis (ICI all-reduce via psum/pmin/pmax, see collectives.py).

The sparse-vector SpMSpV path (``ParFriends.h:1370-1923``,
``BFSFriends.h:328-395``) works on padded (ind, val) frontier blocks and uses
the same schedule with the local kernel swapped to ``ops.spmv.spmspv``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import obs
from ..ops.compressed import CSC
from ..ops.spmv import spmspv as local_spmspv
from ..ops.spmv import spmspv_dense_out
from ..ops.spmv import spmv as local_spmv
from ..semiring import Semiring
from .collectives import axis_reduce
from .grid import COL_AXIS, ROW_AXIS
from .spmat import TILE_SPEC, SpParMat
from .vec import DistVec


def dist_spmv(sr: Semiring, A, x: DistVec) -> DistVec:
    """y = A ⊗ x over the grid: ``y[i] = ⊕_j A[i,j] ⊗ x[j]``.

    x may be in either alignment; result is row-aligned. ``A`` may be an
    SpParMat or an EllParMat (the gather-only SpMV format) — the DER-swap
    seam: same schedule, local kernel chosen by type.
    """
    from .ellmat import EllParMat, dist_spmv_ell

    if obs.ENABLED:
        # host-visible dispatches: eager calls + jit traces (never runs
        # inside compiled code — trace-time Python only)
        obs.count("spmv.dispatch", kernel="dist_spmv")
    if isinstance(A, EllParMat):
        return dist_spmv_ell(sr, A, x)
    assert x.length == A.ncols, (x.length, A.ncols)
    x = x.realign("col")

    def body(rows, cols, vals, nnz, xblk):
        t = A.local_tile(rows, cols, vals, nnz)
        y_loc = local_spmv(sr, t, xblk[0])  # [lr]
        return axis_reduce(sr, y_loc, COL_AXIS)[None]

    blocks = jax.shard_map(
        body,
        mesh=A.grid.mesh,
        in_specs=(TILE_SPEC,) * 4 + (P(COL_AXIS),),
        out_specs=P(ROW_AXIS),
    )(A.rows, A.cols, A.vals, A.nnz, x.blocks)
    return DistVec(blocks=blocks, length=A.nrows, align="row", grid=A.grid)


def dist_spmv_masked(
    sr: Semiring, A, x: DistVec, row_active: DistVec
) -> DistVec:
    """SpMV suppressing rows where ``row_active`` (row-aligned bool) is False.

    ``A`` may be an SpParMat or an EllParMat (see ``dist_spmv``).

    The distributed analog of the Graph500 fused kernel's BitMap dedup
    (``BFSFriends.h:59-182``): already-visited vertices never re-enter y.
    Masking happens *before* the fold, so suppressed rows cost no collective
    bandwidth semantics-wise (XLA still moves the lane, but the value is the
    identity).
    """
    from .ellmat import EllParMat, dist_spmv_ell_masked

    if obs.ENABLED:
        obs.count("spmv.dispatch", kernel="dist_spmv_masked")
    if isinstance(A, EllParMat):
        return dist_spmv_ell_masked(sr, A, x, row_active)
    assert x.length == A.ncols
    x = x.realign("col")
    row_active = row_active.realign("row")

    def body(rows, cols, vals, nnz, xblk, actblk):
        t = A.local_tile(rows, cols, vals, nnz)
        y_loc = local_spmv(sr, t, xblk[0])
        y_loc = jnp.where(actblk[0], y_loc, sr.zero(y_loc.dtype))
        return axis_reduce(sr, y_loc, COL_AXIS)[None]

    blocks = jax.shard_map(
        body,
        mesh=A.grid.mesh,
        in_specs=(TILE_SPEC,) * 4 + (P(COL_AXIS), P(ROW_AXIS)),
        out_specs=P(ROW_AXIS),
    )(A.rows, A.cols, A.vals, A.nnz, x.blocks, row_active.blocks)
    return DistVec(blocks=blocks, length=A.nrows, align="row", grid=A.grid)


@partial(jax.jit, static_argnames=("sr",))
def dist_spmspv(
    sr: Semiring,
    A: SpParMat,
    x: DistVec,
    x_active: DistVec,
) -> tuple[DistVec, DistVec, jax.Array]:
    """Fully sparse-output distributed SpMSpV.

    The general FullyDistSpVec = SpMV(A, FullyDistSpVec) of the reference
    (``ParFriends.h:1725-1881``): y's active set is the union of reached
    rows. Returns (y values row-aligned, y active mask row-aligned, exact
    global active count) — the dense carrier keeps the representation exact
    (our masked-dense FullyDistSpVec stance; see parallel/vec.py docstring).
    """
    assert x.length == A.ncols
    if obs.ENABLED:
        obs.count("spmv.dispatch", kernel="dist_spmspv")
    lr = A.local_rows

    def mark(rows, cols, vals, nnz, xactblk):
        t = A.local_tile(rows, cols, vals, nnz)
        xa = xactblk[0]
        xapad = jnp.concatenate([xa, jnp.zeros((1,), xa.dtype)])
        touched = t.valid_mask() & xapad[jnp.minimum(t.cols, xa.shape[0])]
        local = (
            jnp.zeros((lr,), jnp.int32)
            .at[jnp.where(touched, t.rows, lr)]
            .add(1, mode="drop")
        )
        return (lax.psum(local, COL_AXIS) > 0)[None]

    x_active = x_active.realign("col")
    act_blocks = jax.shard_map(
        mark,
        mesh=A.grid.mesh,
        in_specs=(TILE_SPEC,) * 4 + (P(COL_AXIS),),
        out_specs=P(ROW_AXIS),
    )(A.rows, A.cols, A.vals, A.nnz, x_active.blocks)
    y_active = DistVec(
        blocks=act_blocks, length=A.nrows, align="row", grid=A.grid
    )
    xb = x.realign("col").blocks
    masked_x = DistVec(
        blocks=jnp.where(x_active.blocks, xb, sr.zero(xb.dtype)),
        length=x.length, align="col", grid=A.grid,
    )
    y = dist_spmv(sr, A, masked_x)
    nnz = jnp.sum(act_blocks).astype(jnp.int32)
    return y, y_active, nnz


@partial(
    jax.jit,
    static_argnames=("sr", "frontier_capacity", "exp_capacity"),
)
def dist_spmspv_masked(
    sr: Semiring,
    A: SpParMat,
    x: DistVec,
    x_active: DistVec,
    row_active: DistVec,
    *,
    frontier_capacity: int,
    exp_capacity: int,
) -> DistVec:
    """Masked SpMV where only columns with ``x_active`` participate, and the
    local kernel walks ONLY those columns.

    The distributed top-down BFS kernel (≈ ``BFSFriends.h:328-395`` over
    ``SpImpl::SpMXSpV``): per tile, the dense col-aligned candidate vector is
    compacted to at most ``frontier_capacity`` active local columns, and the
    column walk expands into ``exp_capacity`` static slots. The caller MUST
    guarantee (host-side, from the global frontier size / frontier edge
    count) that per-tile actives fit ``frontier_capacity`` and per-tile
    walked entries fit ``exp_capacity`` — the direction-optimizing driver
    falls back to ``dist_spmv_masked`` otherwise. Work per step scales with
    the static budgets, not the tile nnz: that is the whole point of the
    top-down regime.
    """
    assert x.length == A.ncols
    if obs.ENABLED:
        obs.count("spmv.dispatch", kernel="dist_spmspv_masked")
    x = x.realign("col")
    x_active = x_active.realign("col")
    row_active = row_active.realign("row")
    lc = A.local_cols

    def body(rows, cols, vals, nnz, xblk, xactblk, actblk):
        t = A.local_tile(rows, cols, vals, nnz)
        xv, xa = xblk[0], xactblk[0]
        # Compact active local columns into the static frontier buffer.
        pos = jnp.cumsum(xa.astype(jnp.int32)) - 1
        scatter = jnp.where(xa, pos, frontier_capacity)
        x_ind = (
            jnp.full((frontier_capacity,), lc, jnp.int32)
            .at[scatter]
            .set(jnp.arange(lc, dtype=jnp.int32), mode="drop")
        )
        x_val = (
            jnp.full((frontier_capacity,), sr.zero(xv.dtype), xv.dtype)
            .at[scatter]
            .set(xv, mode="drop")
        )
        csc = CSC.from_tuples(t)
        y_loc = spmspv_dense_out(
            sr, csc, x_ind, x_val, exp_capacity=exp_capacity
        )
        y_loc = jnp.where(actblk[0], y_loc, sr.zero(y_loc.dtype))
        return axis_reduce(sr, y_loc, COL_AXIS)[None]

    blocks = jax.shard_map(
        body,
        mesh=A.grid.mesh,
        in_specs=(TILE_SPEC,) * 4 + (P(COL_AXIS), P(COL_AXIS), P(ROW_AXIS)),
        out_specs=P(ROW_AXIS),
    )(
        A.rows, A.cols, A.vals, A.nnz,
        x.blocks, x_active.blocks, row_active.blocks,
    )
    return DistVec(blocks=blocks, length=A.nrows, align="row", grid=A.grid)
