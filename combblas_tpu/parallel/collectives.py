"""Semiring collectives over mesh axes — the MPIOp analog.

The reference lazily wraps arbitrary C++ binary functors into ``MPI_Op``s with
POD fast paths to ``MPI_SUM/MIN/MAX`` (``include/CombBLAS/MPIOp.h:66-110``).
The TPU analog: a semiring ``add`` with a known monoid kind rides the native
XLA cross-replica reductions (``psum``/``pmin``/``pmax`` → ICI all-reduce);
a generic monoid falls back to ``all_gather`` + a local tree fold, which XLA
still schedules on ICI — the "auto MPI_Op_create" path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..semiring import Semiring


def axis_reduce(sr: Semiring, x: jax.Array, axis_name) -> jax.Array:
    """All-reduce ``x`` over a mesh axis with the semiring's add monoid."""
    if sr.add_kind == "sum":
        return lax.psum(x, axis_name)
    if sr.add_kind == "min":
        return lax.pmin(x, axis_name)
    if sr.add_kind == "max":
        return lax.pmax(x, axis_name)
    gathered = lax.all_gather(x, axis_name)  # [axis_size, ...]
    n = gathered.shape[0]
    acc = gathered[0]
    for k in range(1, n):  # axis size is static; unrolled tree would also work
        acc = sr.add(acc, gathered[k])
    return acc


def axis_reduce_scatter(sr: Semiring, x: jax.Array, axis_name) -> jax.Array:
    """Reduce-scatter over a mesh axis (tiled along leading dim).

    ``x`` has shape [axis_size * L, ...] per device; returns this device's
    reduced [L, ...] chunk. Fast path uses ``psum_scatter``; generic monoids
    all-reduce then slice. This is the fiber reduction of 3D SpGEMM
    (``3DSpGEMM/Reductions.h``, ``ParFriends.h:3119-3180``) and the row-world
    fold of dense SpMV (``ParFriends.h:1925-2155``).
    """
    if sr.add_kind == "sum":
        return lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)
    full = axis_reduce(sr, x, axis_name)
    size = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    chunk = x.shape[0] // size
    return lax.dynamic_slice_in_dim(full, idx * chunk, chunk, axis=0)


def axis_ring_reduce(sr: Semiring, x: jax.Array, axis_name) -> jax.Array:
    """All-reduce via an explicit neighbor ring — the carousel schedule.

    The reference's bottom-up BFS rotates bitmap ownership around the
    process row in ``numcols`` sub-steps with neighbor-only traffic
    (``BFSFriends.h:457-560``, ``BitMapCarousel.h:192``). The TPU-native
    twin is a ``ppermute`` ring over the mesh axis: each of the
    ``size-1`` steps shifts the running partial one neighbor over ICI and
    folds it in — semantically identical to ``axis_reduce`` (the fused
    XLA all-reduce), structurally the pipelined neighbor-rotation
    schedule. Exposed so ring-scheduled kernels (``ring=True`` paths) are
    real, testable programs rather than a claim about XLA's lowering.

    Requires a COMMUTATIVE add (each device folds the rotation in a
    different order); the native-kind monoids all are, generic monoids
    are rejected rather than silently diverging per device.
    """
    assert sr.add_kind in ("sum", "min", "max"), (
        f"axis_ring_reduce needs a commutative add monoid; semiring "
        f"{sr.name} has add_kind={sr.add_kind!r} — use axis_reduce"
    )
    size = lax.axis_size(axis_name)
    if size == 1:
        return x
    perm = [(i, (i + 1) % size) for i in range(size)]
    acc = x
    cur = x
    for _ in range(size - 1):
        cur = lax.ppermute(cur, axis_name, perm)
        acc = sr.add(acc, cur)
    return acc
