"""Distributed sub-matrix extraction / assignment (≈ SpRef / SpAsgn).

The reference expresses ``B = A(ri, ci)`` as two SUMMA SpGEMMs with
distributed selection matrices (``SpParMat::SubsRef_SR``,
SpParMat.cpp:2028-2255): a row selector P (len(ri) × m, one 1 per row at
column ri[k]) and a column selector Q (n × len(ci), one 1 per column at row
ci[l]), giving B = P·A·Q. Assignment ``A(ri, ci) = B``
(``SpParMat::SpAsgn``, SpParMat.cpp:2427) is A = A − S(A)T + Pᵀ·B·Qᵀ.

TPU-native notes:

* Because each selector row/column holds exactly one nonzero, every output
  entry of the two products receives exactly one contribution — ordinary
  PLUS_TIMES (or OR_AND for bool) is numerically exact, so no SelectFirst/
  SelectSecond semiring machinery is needed for numeric payloads.
* The zero-out step of SpAsgn uses a direct two-sided masked prune
  (``SpParMat.prune_rowcol`` with row/col membership vectors) instead of the
  reference's S·A·T product — one local pass instead of two SUMMAs.
* Index vectors are host arrays here (selection matrices are built by the
  host-side tuple constructor); both products run the full distributed SUMMA.
"""

from __future__ import annotations

import numpy as np

from ..semiring import OR_AND, PLUS_TIMES, Semiring
from .grid import Grid
from .spgemm import spgemm
from .spmat import SpParMat
from .vec import DistVec


def _select_sr(mat: SpParMat) -> Semiring:
    import jax.numpy as jnp

    return OR_AND if jnp.dtype(mat.dtype) == jnp.bool_ else PLUS_TIMES


def row_selector(grid: Grid, ri, ncols: int, dtype) -> SpParMat:
    """P: len(ri) × ncols with P[k, ri[k]] = 1 — B = P·A picks rows ri."""
    ri = np.asarray(ri, dtype=np.int64)
    assert ri.ndim == 1 and len(ri) > 0
    assert (0 <= ri).all() and (ri < ncols).all(), "row indices out of range"
    vals = np.ones(len(ri), dtype=dtype)
    return SpParMat.from_global_coo(
        grid, np.arange(len(ri)), ri, vals, len(ri), ncols
    )


def col_selector(grid: Grid, ci, nrows: int, dtype) -> SpParMat:
    """Q: nrows × len(ci) with Q[ci[l], l] = 1 — B = A·Q picks columns ci."""
    ci = np.asarray(ci, dtype=np.int64)
    assert ci.ndim == 1 and len(ci) > 0
    assert (0 <= ci).all() and (ci < nrows).all(), "col indices out of range"
    vals = np.ones(len(ci), dtype=dtype)
    return SpParMat.from_global_coo(
        grid, ci, np.arange(len(ci)), vals, nrows, len(ci)
    )


def subsref(A: SpParMat, ri, ci) -> SpParMat:
    """B = A(ri, ci): B[k, l] = A[ri[k], ci[l]].

    Reference: ``SpParMat::SubsRef_SR`` (SpParMat.cpp:2028-2255) — the same
    two-SUMMA schedule (P·A then ·Q). Duplicate indices are allowed (the
    reference's SpRef semantics); B has shape (len(ri), len(ci)).
    """
    sr = _select_sr(A)
    dtype = np.dtype(A.dtype)
    P = row_selector(A.grid, ri, A.nrows, dtype)
    Q = col_selector(A.grid, ci, A.ncols, dtype)
    return spgemm(sr, spgemm(sr, P, A), Q)


def spasgn(A: SpParMat, ri, ci, B: SpParMat) -> SpParMat:
    """A(ri, ci) = B: zero the (ri × ci) block of A, then scatter B into it.

    Reference: ``SpParMat::SpAsgn`` (SpParMat.cpp:2427-2560). ri/ci must be
    duplicate-free (same requirement as the reference). Returns a new
    matrix (A is immutable here).
    """
    ri = np.asarray(ri, dtype=np.int64)
    ci = np.asarray(ci, dtype=np.int64)
    assert len(np.unique(ri)) == len(ri), "SpAsgn requires distinct row ids"
    assert len(np.unique(ci)) == len(ci), "SpAsgn requires distinct col ids"
    assert (B.nrows, B.ncols) == (len(ri), len(ci)), "B shape mismatch"
    sr = _select_sr(A)
    dtype = np.dtype(A.dtype)

    # Membership masks → two-sided prune of the assigned block.
    mrow = np.zeros(A.nrows, dtype=bool)
    mrow[ri] = True
    mcol = np.zeros(A.ncols, dtype=bool)
    mcol[ci] = True
    rvec = DistVec.from_global(A.grid, mrow, align="row", fill=False)
    cvec = DistVec.from_global(A.grid, mcol, align="col", fill=False)
    cleared = A.prune_rowcol(rvec, cvec, _keep_outside_block)

    # Scatter = Pᵀ·B·Qᵀ places B[k, l] at (ri[k], ci[l]).
    Pt = SpParMat.from_global_coo(
        A.grid, ri, np.arange(len(ri)), np.ones(len(ri), dtype), A.nrows,
        len(ri),
    )
    Qt = SpParMat.from_global_coo(
        A.grid, np.arange(len(ci)), ci, np.ones(len(ci), dtype), len(ci),
        A.ncols,
    )
    scattered = spgemm(sr, spgemm(sr, Pt, B), Qt)
    return cleared.ewise_add(scattered, sr)


def _keep_outside_block(v, inrow, incol):
    return ~(inrow & incol)
