"""Distributed SpGEMM: SUMMA over the device mesh (≈ ParFriends Mult_AnXBn_*).

The reference's baseline ``Mult_AnXBn_Synch`` (``ParFriends.h:1005-1108``)
runs √p stages; each stage broadcasts one A-block along the process row and
one B-block along the process column (``SpParHelper::BCastMatrix``), does a
local hash SpGEMM, and finally k-way-merges the √p stage outputs
(``MultiwayMerge.h:412``).

TPU-native schedule: the per-stage broadcasts collapse into ONE ``all_gather``
of the A-tiles over the ``"c"`` axis and of the B-tiles over the ``"r"`` axis
(same total bytes as the √p broadcasts, but a single fused ICI collective
that XLA can software-pipeline), then a static python loop over stages feeds
the local ESC kernel, and the merge is a single concat + sort + segmented
fold — the MultiwayMerge heap becomes the TPU's native sort.  The
double-buffered / overlapped variants (``ParFriends.h:799,1111``) are
subsumed: XLA overlaps the gather with the first stages automatically.

A ring variant (lower peak memory, ≈ SUMMA with in-place rotation à la
``BFSFriends``' carousel) swaps the all_gather for per-stage ``ppermute``;
see ``ring=True``.

Capacity model (the static-shape analog of ``EstimateFLOP`` /
``EstPerProcessNnzSUMMA``, ``ParFriends.h:356-448,1243-1349``): callers pass
``flop_capacity`` (per stage, per tile) and ``out_capacity`` (final tile
nnz), or use ``summa_capacities`` to measure them exactly with a cheap
distributed symbolic pass before jitting the numeric one.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import obs
from ..ops.compressed import CSR
from ..ops.spgemm import expand as esc_expand
from ..ops.tuples import SpTuples
from ..semiring import Semiring
from .grid import COL_AXIS, ROW_AXIS
from .spmat import TILE_SPEC, SpParMat


def host_value(x) -> np.ndarray:
    """Host numpy value of a FULLY-REPLICATED global array, multi-host
    safe: under multi-process JAX a replicated array still "spans"
    non-addressable devices, so read one addressable shard (each holds
    the whole array when the producing shard_map used ``out_specs=P()``).
    """
    if jax.process_count() > 1:
        return np.asarray(x.addressable_shards[0].data)
    return np.asarray(x)


def _check_compat(A: SpParMat, B: SpParMat):
    """≈ CheckSpGEMMCompliance + ProductGrid (ParFriends.h:161,
    CommGrid.cpp:164)."""
    assert A.grid == B.grid, "A and B must share a grid"
    assert A.grid.is_square, "SUMMA requires a square grid (pr == pc)"
    assert A.ncols == B.nrows, f"dim mismatch {A.ncols} != {B.nrows}"
    assert A.grid.local_cols(A.ncols) == A.grid.local_rows(B.nrows), (
        "A col-blocking must equal B row-blocking"
    )


def _gather_stage_tiles(t: SpTuples, axis_name, p: int) -> list[SpTuples]:
    """All-gather a tile's arrays over a mesh axis → one SpTuples per stage.

    The fused-collective replacement for the reference's per-stage
    ``SpParHelper::BCastMatrix`` loop.
    """
    g = [lax.all_gather(x, axis_name) for x in (t.rows, t.cols, t.vals, t.nnz)]
    return [
        SpTuples(
            rows=g[0][s], cols=g[1][s], vals=g[2][s], nnz=g[3][s],
            nrows=t.nrows, ncols=t.ncols,
        )
        for s in range(p)
    ]


def _carousel_perms(p: int):
    """Cannon-carousel permutation tables over the joint (row, col) axis:
    (skew_a, skew_b, rot_a, rot_b).  Pre-skew puts A_{i,(i+j)%p} /
    B_{(i+j)%p,j} on device (i, j) so both held tiles share the
    contraction index k=(i+j+s)%p at stage s; the rotations shift A left
    / B up one neighbor per stage (the ring schedule of the reference's
    carousel, BitMapCarousel.h)."""
    skew_a = [
        (i * p + (i + j) % p, i * p + j)
        for i in range(p) for j in range(p)
    ]
    skew_b = [
        (((i + j) % p) * p + j, i * p + j)
        for i in range(p) for j in range(p)
    ]
    rot_a = [
        (i * p + (j + 1) % p, i * p + j)
        for i in range(p) for j in range(p)
    ]
    rot_b = [
        (((i + 1) % p) * p + j, i * p + j)
        for i in range(p) for j in range(p)
    ]
    return skew_a, skew_b, rot_a, rot_b


def _rotate_tiles(t: SpTuples, perm) -> SpTuples:
    """One carousel hop: ``ppermute`` all four tile arrays over the joint
    (row, col) mesh axis.  Shared by the ESC, scan, and windowed carousel
    paths (this used to be duplicated as a local ``joint_permute`` in
    each ring kernel)."""
    return SpTuples(
        rows=lax.ppermute(t.rows, (ROW_AXIS, COL_AXIS), perm),
        cols=lax.ppermute(t.cols, (ROW_AXIS, COL_AXIS), perm),
        vals=lax.ppermute(t.vals, (ROW_AXIS, COL_AXIS), perm),
        nnz=lax.ppermute(t.nnz, (ROW_AXIS, COL_AXIS), perm),
        nrows=t.nrows, ncols=t.ncols,
    )


def _chain_tiles(t: SpTuples, dep) -> SpTuples:
    """Pin a schedule dependency: the returned tile's arrays cannot be
    consumed — so the NEXT rotation cannot be issued — before ``dep``
    (an array from the current stage's accumulate) has been computed.
    This is the explicit rotate→compute→rotate serial chain of the
    UNPIPELINED carousel, kept as the measurement control
    (``pipeline=False``); the pipelined schedule never calls this, so
    its next-stage ``ppermute`` is free to overlap the current stage's
    compute."""
    rows, cols, vals, nnz, _ = lax.optimization_barrier(
        (t.rows, t.cols, t.vals, t.nnz, dep)
    )
    return dataclasses.replace(t, rows=rows, cols=cols, vals=vals, nnz=nnz)


def _carousel_stages(a_mine: SpTuples, b_mine: SpTuples, p: int):
    """Generator driving the STAGE-PIPELINED carousel schedule: yields
    ``(s, a_stage, b_stage)`` for each of the ``p`` stages with the
    operands held in TWO-SLOT buffers.  The rotation producing stage
    ``s+1``'s tiles is issued BEFORE stage ``s``'s tiles are consumed
    (the yield), so XLA's latency-hiding scheduler can overlap the
    neighbor ICI traffic with the stage's accumulate.  A serial
    (unpipelined) control needs more than trace order — the rotation
    must be PINNED behind the accumulate with ``_chain_tiles``, which
    needs a stage-output array and so lives in the kernel's own loop
    (see ``_windowed_carousel_compute``); the ESC/scan rings using this
    generator are always pipelined."""
    skew_a, skew_b, rot_a, rot_b = _carousel_perms(p)
    a_cur = _rotate_tiles(a_mine, skew_a)
    b_cur = _rotate_tiles(b_mine, skew_b)
    for s in range(p):
        a_nxt = b_nxt = None
        if s != p - 1:
            a_nxt = _rotate_tiles(a_cur, rot_a)
            b_nxt = _rotate_tiles(b_cur, rot_b)
        yield s, a_cur, b_cur
        if s != p - 1:
            a_cur, b_cur = a_nxt, b_nxt


def _carousel_stages_pair(a_mine: SpTuples, x_mine, p: int, *,
                          pipeline: bool = True, dep=None):
    """Carousel schedule for a (sparse tile, DENSE panel) operand pair
    — the SpMM twin of ``_carousel_stages``: A rides ``_rotate_tiles``,
    the dense feature panel rides a plain joint-axis ``ppermute``.
    ``pipeline=True`` issues the rotation producing stage ``s+1``'s
    operands BEFORE stage ``s``'s are consumed (two-slot buffers, the
    r9 overlap schedule).  ``pipeline=False`` is the serial control:
    the next rotation is PINNED behind the caller's accumulate via
    ``dep`` (a zero-arg callable returning a stage-output array,
    evaluated after the caller's loop body ran — the generator resumes
    only on the next iteration request)."""
    skew_a, skew_b, rot_a, rot_b = _carousel_perms(p)
    a_cur = _rotate_tiles(a_mine, skew_a)
    x_cur = lax.ppermute(x_mine, (ROW_AXIS, COL_AXIS), skew_b)
    for s in range(p):
        a_nxt = x_nxt = None
        if pipeline and s != p - 1:
            a_nxt = _rotate_tiles(a_cur, rot_a)
            x_nxt = lax.ppermute(x_cur, (ROW_AXIS, COL_AXIS), rot_b)
        yield s, a_cur, x_cur
        if s != p - 1:
            if not pipeline:
                d = dep() if dep is not None else a_cur.nnz
                a_pin = _chain_tiles(a_cur, d)
                x_pin, _ = lax.optimization_barrier((x_cur, d))
                a_nxt = _rotate_tiles(a_pin, rot_a)
                x_nxt = lax.ppermute(x_pin, (ROW_AXIS, COL_AXIS), rot_b)
            a_cur, x_cur = a_nxt, x_nxt


@partial(
    jax.jit,
    static_argnames=("sr", "flop_capacity", "out_capacity", "ring",
                     "merge"),
)
def summa_spgemm(
    sr: Semiring,
    A: SpParMat,
    B: SpParMat,
    *,
    flop_capacity: int,
    out_capacity: int,
    ring: bool = False,
    merge: str = "sort",
) -> SpParMat:
    """C = A ⊗ B over the grid.

    ``flop_capacity`` bounds ONE stage's expansion on one tile;
    ``out_capacity`` bounds the final per-tile nnz.

    ``merge`` picks the stage-chunk combine (round 13): ``"sort"`` is
    the classic concat + full ``lax.sort`` compact; ``"runs"`` sorts
    each STAGE chunk individually (p sorts of flop_capacity — strictly
    less sort work than one sort of p·flop_capacity) and k-way merges
    the sorted runs by rank-space union
    (``ops.spgemm.merge_sorted_runs``), so the compact skips its sort
    entirely.  Bit-exact with ``"sort"`` for every semiring (ties keep
    stage order).
    """
    _check_compat(A, B)
    assert merge in ("sort", "runs"), merge
    grid = A.grid
    p = grid.pr
    if obs.ENABLED:
        # trace-time only (this fn is jitted): counts (re)traces per
        # static config, never executions — the jit retrace visibility
        obs.count("trace.summa_spgemm", ring=ring, merge=merge)
        if ring and p > 1:
            obs.count("spgemm.pipeline.stages_overlapped", p - 1)

    def body(ar, ac, av, an, br, bc, bv, bn):
        from ..ops.spgemm import merge_sorted_runs

        # stitch local tiles
        a_mine = A.local_tile(ar, ac, av, an)
        b_mine = B.local_tile(br, bc, bv, bn)

        def stage_output(a_stage: SpTuples, b_stage: SpTuples) -> SpTuples:
            b_csr = CSR.from_tuples(b_stage)
            return esc_expand(sr, a_stage, b_csr, flop_capacity)

        chunks = []
        if not ring:
            # A-tiles of my grid row / B-tiles of my grid column.
            a_stages = _gather_stage_tiles(a_mine, COL_AXIS, p)
            b_stages = _gather_stage_tiles(b_mine, ROW_AXIS, p)
            for s in range(p):
                chunks.append(stage_output(a_stages[s], b_stages[s]))
        else:
            # Cannon's algorithm: O(capacity) peak memory instead of
            # O(p·capacity), STAGE-PIPELINED — ``_carousel_stages``
            # issues the ppermute producing stage s+1's tiles before
            # stage s's tiles are consumed (two-slot operand buffers),
            # so the neighbor ICI rotation overlaps the local expand
            # instead of the old rotate→compute→rotate serial chain.
            for s, a_cur, b_cur in _carousel_stages(a_mine, b_mine, p):
                chunks.append(stage_output(a_cur, b_cur))

        if merge == "runs":
            # per-stage sorts + rank-space union: the stage chunks ARE
            # the sorted runs, so the compact skips its global sort
            merged = merge_sorted_runs(
                [ch.sort_rowmajor() for ch in chunks]
            )
            out = merged.compact(
                sr, capacity=out_capacity, assume_sorted=True
            )
        else:
            merged = SpTuples.concat(chunks)
            out = merged.compact(sr, capacity=out_capacity)
        return SpParMat._pack_tile(out)

    r, c, v, n = jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(TILE_SPEC,) * 8,
        out_specs=(TILE_SPEC,) * 4,
        check_vma=False,
    )(A.rows, A.cols, A.vals, A.nnz, B.rows, B.cols, B.vals, B.nnz)
    return SpParMat(
        rows=r, cols=c, vals=v, nnz=n,
        nrows=A.nrows, ncols=B.ncols, grid=grid,
    )


@partial(jax.jit, static_argnames=("padded",))
def summa_stage_flops(A: SpParMat, B: SpParMat, padded: bool = True) -> jax.Array:
    """[p, pr, pc] float32 flop count per stage per output tile.

    The distributed symbolic pass (≈ EstimateFLOP, ParFriends.h:356-448).
    Values only (no ``vals`` arrays) cross the ICI: flop counting needs A's
    (rows, cols) for validity/contraction ids and B's rows for row lengths.

    ``padded=True`` (the default) counts CHUNKED-EXPANSION SLOTS — each
    A-entry's B-row walk rounded up to ``ops.spgemm.CHUNK_W`` lanes, the
    capacity the expand kernel actually allocates; ``padded=False`` gives
    true scalar multiplies (EstimateFLOP parity, for reporting).
    """
    from ..ops.spgemm import CHUNK_W

    _check_compat(A, B)
    grid = A.grid
    p = grid.pr
    lrB = B.local_rows

    def body(ar, ac, br):
        a_rows, a_cols = ar[0, 0], ac[0, 0]
        b_rows = br[0, 0]
        ag_rows = lax.all_gather(a_rows, COL_AXIS)
        ag_cols = lax.all_gather(a_cols, COL_AXIS)
        bg_rows = lax.all_gather(b_rows, ROW_AXIS)
        per_stage = []
        for s in range(p):
            b_valid = bg_rows[s] < lrB
            blens = jax.ops.segment_sum(
                b_valid.astype(jnp.int32), bg_rows[s], num_segments=lrB + 1
            )
            if padded:
                blens = -(-blens // CHUNK_W) * CHUNK_W
            a_valid = ag_rows[s] < A.local_rows
            k = jnp.minimum(ag_cols[s], lrB)
            per_entry = jnp.where(a_valid, blens[k], 0)
            per_stage.append(jnp.sum(per_entry.astype(jnp.float32)))
        mine = jnp.stack(per_stage)  # [p]
        # Replicate the (tiny) result so every PROCESS can read it whole —
        # a mesh-sharded output is not host-addressable under multi-host
        # (sizing does np.asarray on it, tests/_multihost_worker.py).
        g = lax.all_gather(lax.all_gather(mine, COL_AXIS), ROW_AXIS)
        return jnp.transpose(g, (2, 0, 1))  # [p, pr, pc]

    return jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(TILE_SPEC,) * 3,
        out_specs=P(),
        check_vma=False,
    )(A.rows, A.cols, B.rows)


def _caps_from_stage_flops(per_stage: np.ndarray, dense_tile: int,
                           slack: float):
    flop_cap = max(int(per_stage.max() * slack) + 1, 1)
    total_per_tile = per_stage.sum(axis=0).max()
    out_cap = max(min(int(total_per_tile * slack) + 1, dense_tile), 1)
    return flop_cap, out_cap


def summa_capacities(A: SpParMat, B: SpParMat, slack: float = 1.05):
    """Host helper: symbolic pass → (flop_capacity, out_capacity).

    flop_capacity = max single-stage single-tile expansion; out_capacity =
    max per-tile total flops (a product has at most one output per flop),
    clamped to the dense tile size. ``slack`` covers the float32 rounding of
    the counts plus headroom for reusing compiled code across inputs.

    NOTE: reads the device symbolic pass back to host — on the axon chip
    use ``summa_capacities_host`` from the host COO *before* any device
    work (D2H poison, see bench.py).
    """
    per_stage = host_value(summa_stage_flops(A, B)).astype(np.float64)
    if obs.ENABLED:
        _record_symbolic_metrics(per_stage)
    return _caps_from_stage_flops(
        per_stage, A.local_rows * B.local_cols, slack
    )


def _record_symbolic_metrics(per_stage: np.ndarray) -> None:
    """Registry facts from one symbolic pass: total symbolic fill-in
    (expansion slots — the flops-side of symbolic-vs-realized) and the
    per-tile LoadImbalance (max/mean over output tiles, the reference's
    ``LoadImbalance`` statistic)."""
    per_tile = per_stage.sum(axis=0)
    mean = float(per_tile.mean())
    obs.count("spgemm.symbolic_fill_slots", float(per_stage.sum()))
    obs.gauge(
        "spgemm.load_imbalance",
        float(per_tile.max() / mean) if mean > 0 else 1.0,
    )


def summa_stage_flops_host(
    grid, rows_a, cols_a, rows_b, cols_b,
    nrows_a: int, ncols_a: int, ncols_b: int,
    padded: bool = True,
) -> np.ndarray:
    """Host-numpy twin of ``summa_stage_flops``: [p, pr, pc] flop counts
    computed from global COO arrays, with zero device interaction.

    For benchmarking on hardware where any D2H readback degrades later
    launches, the symbolic sizing must happen before upload; this computes
    the identical per-stage per-tile counts from the same owner math.
    """
    pr_, pc_ = grid.pr, grid.pc
    assert pr_ == pc_, "SUMMA requires a square grid"
    p = pr_
    lrA = grid.local_rows(nrows_a)
    lcA = grid.local_cols(ncols_a)
    lrB = grid.local_rows(ncols_a)
    lcB = grid.local_cols(ncols_b)
    assert lcA == lrB, "A col-blocking must equal B row-blocking"
    from ..ops.spgemm import CHUNK_W

    rows_a = np.asarray(rows_a, np.int64)
    cols_a = np.asarray(cols_a, np.int64)
    rows_b = np.asarray(rows_b, np.int64)
    cols_b = np.asarray(cols_b, np.int64)
    # countA[i, s, k] = nnz of A-tile (i,s) in local column k
    ia, sa, ka = rows_a // lrA, cols_a // lcA, cols_a % lcA
    countA = np.bincount(
        (ia * p + sa) * lcA + ka, minlength=p * p * lcA
    ).reshape(p, p, lcA)
    # countB[s, j, k] = nnz of B-tile (s,j) in local row k
    sb, jb, kb = rows_b // lrB, cols_b // lcB, rows_b % lrB
    countB = np.bincount(
        (sb * p + jb) * lrB + kb, minlength=p * p * lrB
    ).reshape(p, p, lrB)
    if padded:  # chunked-expansion slots (see summa_stage_flops)
        countB = -(-countB // CHUNK_W) * CHUNK_W
    # flops[s, i, j] = sum_k countA[i,s,k] * countB[s,j,k]
    return np.einsum(
        "isk,sjk->sij", countA.astype(np.float64), countB.astype(np.float64)
    )


def summa_capacities_host(
    grid, rows_a, cols_a, rows_b, cols_b,
    nrows_a: int, ncols_a: int, ncols_b: int, slack: float = 1.05,
    per_stage: np.ndarray | None = None,
):
    """Host-only twin of ``summa_capacities`` (flop_capacity, out_capacity)
    from global COO arrays — the public entry for D2H-sensitive callers
    (benchmarks on the axon chip size capacities before any upload).

    Pass a precomputed ``per_stage`` (from ``summa_stage_flops_host``) to
    avoid recomputing the O(nnz) symbolic pass."""
    if per_stage is None:
        per_stage = summa_stage_flops_host(
            grid, rows_a, cols_a, rows_b, cols_b, nrows_a, ncols_a, ncols_b
        )
    if obs.ENABLED:
        _record_symbolic_metrics(np.asarray(per_stage, np.float64))
    dense_tile = grid.local_rows(nrows_a) * grid.local_cols(ncols_b)
    return _caps_from_stage_flops(per_stage, dense_tile, slack)


def summa_rowblock_flops(
    A: SpParMat, B: SpParMat, block_rows: int, chunk_w: int = 0
) -> jax.Array:
    """[nblocks, p, pr, pc] float32 flop counts resolved by A ROW BLOCK —
    the symbolic pass that drives the windowed tier's per-block sizing
    and its skip list (a block with zero flops has zero output and is
    never scanned).

    ``chunk_w > 0`` counts chunked-expansion SLOTS (each B-row walk
    rounded up to ``chunk_w`` lanes — the capacity the windowed tier's
    expansion actually allocates, exact by the ``flops_padded``
    argument); ``chunk_w == 0`` counts true scalar multiplies (the
    ``estimate_nnz_upper``-style output bound).  Thin slice of the
    one-pass ``summa_rowblock_flops_pair`` (chunk_w=1 padding is the
    identity, so index 1 of the pair is always the true count).
    """
    pair = summa_rowblock_flops_pair(
        A, B, block_rows, chunk_w=max(chunk_w, 1)
    )
    return pair[0] if chunk_w else pair[1]


@partial(jax.jit, static_argnames=("block_rows", "chunk_w"))
def summa_rowblock_flops_pair(
    A: SpParMat, B: SpParMat, block_rows: int, chunk_w: int
) -> jax.Array:
    """[2, nblocks, p, pr, pc]: the ``chunk_w``-padded counts (index 0)
    and the true counts (index 1) from ONE symbolic pass — the sizing
    entry pays the all_gathers and segment sums once instead of running
    ``summa_rowblock_flops`` twice."""
    _check_compat(A, B)
    grid = A.grid
    p = grid.pr
    lrA = A.local_rows
    lrB = B.local_rows
    nblocks = -(-lrA // block_rows)

    def body(ar, ac, br):
        a_rows, a_cols = ar[0, 0], ac[0, 0]
        b_rows = br[0, 0]
        ag_rows = lax.all_gather(a_rows, COL_AXIS)
        ag_cols = lax.all_gather(a_cols, COL_AXIS)
        bg_rows = lax.all_gather(b_rows, ROW_AXIS)
        per_stage = []
        for s in range(p):
            b_valid = bg_rows[s] < lrB
            blens = jax.ops.segment_sum(
                b_valid.astype(jnp.int32), bg_rows[s], num_segments=lrB + 1
            )
            blens_pad = -(-blens // chunk_w) * chunk_w
            a_valid = ag_rows[s] < lrA
            k = jnp.minimum(ag_cols[s], lrB)
            g = jnp.where(a_valid, ag_rows[s] // block_rows, nblocks)
            both = []
            for bl in (blens_pad, blens):
                per_entry = jnp.where(a_valid, bl[k], 0).astype(jnp.float32)
                both.append(
                    jax.ops.segment_sum(
                        per_entry, g, num_segments=nblocks + 1
                    )[:nblocks]
                )
            per_stage.append(jnp.stack(both))  # [2, nblocks]
        mine = jnp.stack(per_stage)  # [p, 2, nblocks]
        g2 = lax.all_gather(lax.all_gather(mine, COL_AXIS), ROW_AXIS)
        return jnp.transpose(g2, (3, 4, 2, 0, 1))  # [2, nblocks, p, pr, pc]

    return jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(TILE_SPEC,) * 3,
        out_specs=P(),
        check_vma=False,
    )(A.rows, A.cols, B.rows)


def summa_rowblock_flops_host(
    grid, rows_a, cols_a, rows_b, cols_b,
    nrows_a: int, ncols_a: int, ncols_b: int,
    block_rows: int, chunk_w: int = 0,
) -> np.ndarray:
    """Host-numpy twin of ``summa_rowblock_flops`` from global COO arrays
    (zero device interaction — the axon-safe sizing path, like
    ``summa_stage_flops_host``)."""
    pr_, pc_ = grid.pr, grid.pc
    assert pr_ == pc_, "SUMMA requires a square grid"
    p = pr_
    lrA = grid.local_rows(nrows_a)
    lcA = grid.local_cols(ncols_a)
    lrB = grid.local_rows(ncols_a)
    assert lcA == lrB, "A col-blocking must equal B row-blocking"
    nblocks = -(-lrA // block_rows)
    rows_a = np.asarray(rows_a, np.int64)
    cols_a = np.asarray(cols_a, np.int64)
    rows_b = np.asarray(rows_b, np.int64)
    cols_b = np.asarray(cols_b, np.int64)
    ia, sa, ka = rows_a // lrA, cols_a // lcA, cols_a % lcA
    g = (rows_a % lrA) // block_rows
    countA = np.bincount(
        (((ia * p + sa) * nblocks) + g) * lcA + ka,
        minlength=p * p * nblocks * lcA,
    ).reshape(p, p, nblocks, lcA)
    sb, kb = rows_b // lrB, rows_b % lrB
    lcB = grid.local_cols(ncols_b)
    jb = cols_b // lcB
    countB = np.bincount(
        (sb * p + jb) * lrB + kb, minlength=p * p * lrB
    ).reshape(p, p, lrB)
    if chunk_w:
        countB = -(-countB // chunk_w) * chunk_w
    # flops[g, s, i, j] = sum_k countA[i, s, g, k] * countB[s, j, k]
    return np.einsum(
        "isgk,sjk->gsij",
        countA.astype(np.float64), countB.astype(np.float64),
    )


def _window_stage_symbolic(
    a_rows_s, a_cols_s, b_rows_s, b_cols_s,
    lrA: int, lrB: int, block_rows: int, block_cols: int,
    nblocks: int, ncw: int, chunk_w: int,
):
    """One SUMMA stage's [2, nblocks, ncw] windowed symbolic counts
    (index 0 chunk-padded, index 1 true) from the stage's gathered A/B
    index arrays — the inner kernel of ``summa_window_flops_pair``,
    shared with the per-layer 3D pass (``mesh3d.
    summa3d_window_flops_pair``)."""
    b_valid = b_rows_s < lrB
    # per-(col-window, B-row) walk lengths; invalid entries fall in the
    # ncw overflow bucket (a sentinel col == lcB would otherwise land in
    # the last window when block_cols ∤ lcB)
    h = jnp.where(
        b_valid, b_cols_s // block_cols, ncw
    ).astype(jnp.int32)
    key = h * (lrB + 1) + jnp.minimum(b_rows_s, lrB)
    blens2 = jax.ops.segment_sum(
        b_valid.astype(jnp.int32), key,
        num_segments=(ncw + 1) * (lrB + 1),
    ).reshape(ncw + 1, lrB + 1)
    a_valid = a_rows_s < lrA
    k = jnp.minimum(a_cols_s, lrB)
    g = jnp.where(a_valid, a_rows_s // block_rows, nblocks)
    # chunk_w == 1 padding is the identity: run the inner gather+segment
    # loop once and reuse it for both variants (the dot-backend sizing
    # path never consumes the padded counts, so it requests chunk_w=1)
    variants = (
        (blens2,) if chunk_w == 1
        else (-(-blens2 // chunk_w) * chunk_w, blens2)
    )
    both = []
    for bl in variants:
        per_h = []
        for hh in range(ncw):  # static loop bounds memory to
            per_entry = jnp.where(  # one [nnzA] gather per window
                a_valid, bl[hh, k], 0
            ).astype(jnp.float32)
            per_h.append(
                jax.ops.segment_sum(
                    per_entry, g, num_segments=nblocks + 1
                )[:nblocks]
            )
        both.append(jnp.stack(per_h, axis=1))  # [nblocks, ncw]
    if len(both) == 1:
        both = [both[0], both[0]]
    return jnp.stack(both)  # [2, nblocks, ncw]


@partial(
    jax.jit, static_argnames=("block_rows", "block_cols", "chunk_w")
)
def summa_window_flops_pair(
    A: SpParMat, B: SpParMat, block_rows: int, block_cols: int,
    chunk_w: int = 1,
) -> jax.Array:
    """[2, nblocks, ncolwin, p, pr, pc]: the 2D-resolved symbolic pass —
    flop counts per (A row block, B col window) per stage per output
    tile; index 0 is ``chunk_w``-padded, index 1 the true counts (one
    pass, like ``summa_rowblock_flops_pair``).

    This is what sizes the 2D ``dot`` backend: per-window output bounds
    and the 2D skip list (a window with zero symbolic flops produces
    nothing — its stage matmuls and its extraction scan are both
    elided at trace time).
    """
    _check_compat(A, B)
    grid = A.grid
    p = grid.pr
    lrA = A.local_rows
    lrB, lcB = B.local_rows, B.local_cols
    nblocks = -(-lrA // block_rows)
    ncw = -(-lcB // block_cols)

    def body(ar, ac, br, bc):
        a_rows, a_cols = ar[0, 0], ac[0, 0]
        b_rows, b_cols = br[0, 0], bc[0, 0]
        ag_rows = lax.all_gather(a_rows, COL_AXIS)
        ag_cols = lax.all_gather(a_cols, COL_AXIS)
        bg_rows = lax.all_gather(b_rows, ROW_AXIS)
        bg_cols = lax.all_gather(b_cols, ROW_AXIS)
        per_stage = [
            _window_stage_symbolic(
                ag_rows[s], ag_cols[s], bg_rows[s], bg_cols[s],
                lrA, lrB, block_rows, block_cols, nblocks, ncw, chunk_w,
            )
            for s in range(p)
        ]
        mine = jnp.stack(per_stage)  # [p, 2, nblocks, ncw]
        g2 = lax.all_gather(lax.all_gather(mine, COL_AXIS), ROW_AXIS)
        # [pr, pc, p, 2, nblocks, ncw] -> [2, nblocks, ncw, p, pr, pc]
        return jnp.transpose(g2, (3, 4, 5, 2, 0, 1))

    return jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(TILE_SPEC,) * 4,
        out_specs=P(),
        check_vma=False,
    )(A.rows, A.cols, B.rows, B.cols)


def summa_window_flops_host(
    grid, rows_a, cols_a, rows_b, cols_b,
    nrows_a: int, ncols_a: int, ncols_b: int,
    block_rows: int, block_cols: int, chunk_w: int = 0,
) -> np.ndarray:
    """Host-numpy twin of ``summa_window_flops_pair`` (one chunk_w at a
    time): [nblocks, ncolwin, p, pr, pc] float64 from global COO arrays,
    zero device interaction — the axon-safe 2D sizing path."""
    pr_, pc_ = grid.pr, grid.pc
    assert pr_ == pc_, "SUMMA requires a square grid"
    p = pr_
    lrA = grid.local_rows(nrows_a)
    lcA = grid.local_cols(ncols_a)
    lrB = grid.local_rows(ncols_a)
    lcB = grid.local_cols(ncols_b)
    assert lcA == lrB, "A col-blocking must equal B row-blocking"
    nblocks = -(-lrA // block_rows)
    ncw = -(-lcB // block_cols)
    rows_a = np.asarray(rows_a, np.int64)
    cols_a = np.asarray(cols_a, np.int64)
    rows_b = np.asarray(rows_b, np.int64)
    cols_b = np.asarray(cols_b, np.int64)
    ia, sa, ka = rows_a // lrA, cols_a // lcA, cols_a % lcA
    g = (rows_a % lrA) // block_rows
    countA = np.bincount(
        (((ia * p + sa) * nblocks) + g) * lcA + ka,
        minlength=p * p * nblocks * lcA,
    ).reshape(p, p, nblocks, lcA)
    sb, kb = rows_b // lrB, rows_b % lrB
    jb = cols_b // lcB
    hb = (cols_b % lcB) // block_cols
    countB = np.bincount(
        (((sb * p + jb) * ncw) + hb) * lrB + kb,
        minlength=p * p * ncw * lrB,
    ).reshape(p, p, ncw, lrB)
    if chunk_w:
        countB = -(-countB // chunk_w) * chunk_w
    # flops[g, h, s, i, j] = sum_k countA[i,s,g,k] * countB[s,j,h,k]
    return np.einsum(
        "isgk,sjhk->ghsij",
        countA.astype(np.float64), countB.astype(np.float64),
    )


@partial(jax.jit, static_argnames=("block_cols",))
def summa_window_bnnz(B: SpParMat, block_cols: int) -> jax.Array:
    """[pr, pc, ncolwin] int32, replicated: B-tile nnz per col window —
    the static gather capacity of the 2D dot backend's CSC panel slices
    (``panel_cap`` = global max)."""
    lrB, lcB = B.local_rows, B.local_cols
    ncw = -(-lcB // block_cols)

    def body(br, bc):
        b_rows, b_cols = br[0, 0], bc[0, 0]
        valid = b_rows < lrB
        h = jnp.where(valid, b_cols // block_cols, ncw).astype(jnp.int32)
        mine = jax.ops.segment_sum(
            valid.astype(jnp.int32), h, num_segments=ncw + 1
        )[:ncw]
        g2 = lax.all_gather(lax.all_gather(mine, COL_AXIS), ROW_AXIS)
        return g2  # [pr, pc, ncw]

    return jax.shard_map(
        body,
        mesh=B.grid.mesh,
        in_specs=(TILE_SPEC,) * 2,
        out_specs=P(),
        check_vma=False,
    )(B.rows, B.cols)


def summa_window_bnnz_host(
    grid, rows_b, cols_b, ncols_a: int, ncols_b: int, block_cols: int
) -> np.ndarray:
    """Host twin of ``summa_window_bnnz``: [pr, pc, ncolwin]."""
    lrB = grid.local_rows(ncols_a)
    lcB = grid.local_cols(ncols_b)
    ncw = -(-lcB // block_cols)
    rows_b = np.asarray(rows_b, np.int64)
    cols_b = np.asarray(cols_b, np.int64)
    sb, jb = rows_b // lrB, cols_b // lcB
    hb = (cols_b % lcB) // block_cols
    return np.bincount(
        ((sb * grid.pc + jb) * ncw) + hb,
        minlength=grid.pr * grid.pc * ncw,
    ).reshape(grid.pr, grid.pc, ncw)


def windowed_plan_2d(
    per_window_padded: np.ndarray | None,
    per_window_true: np.ndarray,
    block_rows: int,
    block_cols: int,
    local_rows: int,
    local_cols_b: int,
    slack: float = 1.02,
) -> tuple[tuple, tuple, tuple]:
    """2D twin of ``windowed_plan``: per-(row-block, col-window) static
    (flop_caps, out_caps, skip), each a tuple of per-block tuples.

    Out caps are the clamped-flops bound per WINDOW (true per-tile
    window flops, max over tiles, clamped by the window's dense cells);
    a window whose symbolic count is zero is skipped — its stage
    matmuls, its B panel, and its extraction scan are never emitted.
    ``per_window_padded`` may be ``None``: the ``dot`` backend does no
    chunked expansion, so its flop caps are never consumed — passing
    None (all-ones caps) saves the padded symbolic pass entirely (the
    device pair computes both in one pass; the HOST sizing path has to
    run one einsum per variant, so benchmarks skip the dead one).
    """
    pt = np.asarray(per_window_true, np.float64)
    pb = (
        None if per_window_padded is None
        else np.asarray(per_window_padded, np.float64)
    )
    nblocks, ncw = pt.shape[0], pt.shape[1]
    flop_caps, out_caps, skip = [], [], []
    for g in range(nblocks):
        rb = min(block_rows, local_rows - g * block_rows)
        fr, orow, sr_ = [], [], []
        for h in range(ncw):
            wc = min(block_cols, local_cols_b - h * block_cols)
            cells = rb * wc
            tot = pt[g, h].sum(axis=0).max()  # per-tile total, max
            sr_.append(bool(tot <= 0))
            fr.append(
                1 if pb is None
                else max(int(pb[g, h].max() * slack) + 1, 1)
            )
            orow.append(max(min(int(tot * slack) + 1, cells), 1))
        flop_caps.append(tuple(fr))
        out_caps.append(tuple(orow))
        skip.append(tuple(sr_))
    return tuple(flop_caps), tuple(out_caps), tuple(skip)


def windowed_plan(
    per_block_padded: np.ndarray,
    per_block_true: np.ndarray,
    block_rows: int,
    local_rows: int,
    local_cols_b: int,
    slack: float = 1.02,
) -> tuple[tuple[int, ...], tuple[int, ...], tuple[bool, ...]]:
    """Derive the windowed tier's static plan from the two symbolic
    passes: per-block expansion capacities (max over stages and tiles of
    the chunk-padded counts), per-block output capacities (the
    ``estimate_nnz_upper`` bound — per-tile true flops clamped by the
    dense block, max over tiles), and the SKIP LIST (blocks whose
    symbolic flop count is zero produce nothing and are never scanned).

    ``slack`` covers float32 rounding when the counts come from the
    device symbolic pass (the host pass is float64-exact; the padded
    counts are exact by the ``flops_padded`` argument either way).
    """
    pb = np.asarray(per_block_padded, np.float64)
    pt = np.asarray(per_block_true, np.float64)
    nblocks = pb.shape[0]
    flop_caps, out_caps, skip = [], [], []
    for g in range(nblocks):
        rb = min(block_rows, local_rows - g * block_rows)
        cells = rb * local_cols_b
        fmax = pb[g].max()
        tot = pt[g].sum(axis=0).max()  # per-tile total, max over tiles
        skip.append(bool(tot <= 0))
        flop_caps.append(max(int(fmax * slack) + 1, 1))
        out_caps.append(max(min(int(tot * slack) + 1, cells), 1))
    return tuple(flop_caps), tuple(out_caps), tuple(skip)


def packed_windows(skip) -> tuple[int, ...]:
    """1D skip list → dense LAUNCH LIST of occupied row blocks.

    The kernels iterate this packed list instead of the full block grid
    with per-block skip tests, so a sparse plan pays one launch per
    OCCUPIED block — the trace-level contract the oracle seeding
    tightens (`_oracle_out_caps_2d` turns flops-positive but
    output-empty windows into skips, which packing then never visits).
    """
    return tuple(g for g, s in enumerate(skip) if not s)


def packed_windows_2d(skip) -> tuple[tuple[int, int], ...]:
    """2D skip list → packed launch list of occupied (row block, col
    window) pairs, block-major then window-major — the kernels' output
    chunk order, so a packed run and a skip-list run emit IDENTICAL
    tiles."""
    return tuple(
        (g, h) for g, row in enumerate(skip)
        for h, s in enumerate(row) if not s
    )


def _live_windows_by_block(skip) -> tuple:
    """Packed 2D launch list grouped by row block:
    ``((g, (h, ...)), ...)`` — blocks with no live window are absent
    entirely (their A block is never masked or densified)."""
    out = []
    for g, row in enumerate(skip):
        hs = tuple(h for h, s in enumerate(row) if not s)
        if hs:
            out.append((g, hs))
    return tuple(out)


def _extract_window_2d(acc, zero, lo, h, rb, block_cols, lrA, lcB, out_cap):
    """One (row block, col window) extraction → (global-coord chunk,
    overflow vs the symbolic bound).  Shared by the gathered and
    carousel schedules (and the 3D per-layer kernel)."""
    from ..ops.spgemm import sparsify_windowed

    wc = min(block_cols, lcB - h * block_cols)
    t_blk, total = sparsify_windowed(acc, zero, rb, wc, out_cap)
    vm = t_blk.valid_mask()
    chunk = SpTuples(
        rows=jnp.where(vm, t_blk.rows + lo, lrA),
        cols=jnp.where(vm, t_blk.cols + h * block_cols, lcB),
        vals=t_blk.vals, nnz=t_blk.nnz, nrows=lrA, ncols=lcB,
    )
    return chunk, total - out_cap


def _extract_block_1d(acc, zero, lo, rb, lrA, lcB, out_cap):
    """One full-width row-block extraction → (chunk, overflow)."""
    from ..ops.spgemm import sparsify_windowed

    t_blk, total = sparsify_windowed(acc, zero, rb, lcB, out_cap)
    rows = jnp.where(t_blk.valid_mask(), t_blk.rows + lo, lrA)
    chunk = SpTuples(
        rows=rows, cols=t_blk.cols, vals=t_blk.vals,
        nnz=t_blk.nnz, nrows=lrA, ncols=lcB,
    )
    return chunk, total - out_cap


def _shift_rowblock(am: SpTuples, lo, arows: int) -> SpTuples:
    """Row-block tile → block-local coordinates: valid rows shift down
    by ``lo``; invalid slots land EXACTLY at the new sentinel ``arows``
    (= the padded block height) so ``valid_mask`` stays false after the
    ``nrows`` rewrite.  Shared by the fused and local dot kernels."""
    import dataclasses as _dc

    valid = am.valid_mask()
    a_loc = _dc.replace(am, rows=jnp.where(valid, am.rows - lo, arows))
    return _dc.replace(a_loc, nrows=arows)


def _dense_col_panel(
    sr: Semiring, bs: SpTuples, starts, h: int, block_cols: int,
    pk: int, pwin: int, panel_cap: int,
):
    """Dense [pk, pwin] panel of B col window ``h`` from the col-major-
    sorted stage tile ``bs``: the window's entries occupy one contiguous
    CSC slot range [starts[h], starts[h+1]), gathered with a static
    ``panel_cap``-slot slice and scattered with the semiring combiner —
    O(panel_cap) work per window (not O(nnz)), duplicate-entry safe.
    This is the stage operand of the 2D ``dot`` backend: peak memory
    pk × pwin cells, bounded by the column window instead of B's tile
    width."""
    from ..ops.spgemm import scatter_combine_for

    start = starts[h]
    idx = start + jnp.arange(panel_cap, dtype=jnp.int32)
    ok = idx < starts[h + 1]
    ii = jnp.minimum(idx, bs.capacity - 1)
    r = bs.rows[ii]
    c = bs.cols[ii]
    v = bs.vals[ii]
    ok = ok & (r < bs.nrows)
    flat = jnp.where(ok, r * pwin + (c - h * block_cols), pk * pwin)
    comb = scatter_combine_for(sr)
    dense = jnp.full((pk * pwin,), sr.zero(bs.vals.dtype), bs.vals.dtype)
    dense = getattr(dense.at[flat], comb)(v, mode="drop")
    return dense.reshape(pk, pwin)


def _window_stage_product(
    sr: Semiring, kind: str, da, panel, mode: str, interpret: bool,
):
    """One stage's dense window product on the matrix unit."""
    from ..ops.pallas_kernels import semiring_matmul

    if kind == "plus_times":
        return _mxu_dot(da, panel, mode, da.dtype)
    return semiring_matmul(
        kind, da, panel, bm=256, bk=512, bn=256, interpret=interpret
    )


def _windowed_dims(backend: str, block_cols, lrB: int, lcB: int):
    """Static padded dims of the windowed accumulate: (two_d, pcols, pk,
    pwin)."""
    two_d = backend == "dot" and block_cols is not None
    if backend == "dot":
        pcols = _pad128(lcB)
        pk = _pad128(lrB)
        pwin = _pad128(block_cols) if two_d else None
    else:
        pcols = -(-lcB // 128) * 128
        pk = pwin = None
    return two_d, pcols, pk, pwin


def _windowed_stage_b_side(sr, b_stage, backend, two_d, pk, pcols,
                           block_cols):
    """Per-stage B-side preprocessing: CSR (scatter), dense tile (1D
    dot), or (col-major sorted tile, window slot starts) (2D dot)."""
    from ..ops.spgemm import densify_combine

    if backend == "scatter":
        return CSR.from_tuples(b_stage)
    if not two_d:
        return densify_combine(sr, b_stage, pk, pcols)
    return _colmajor_with_starts(b_stage, block_cols)


def _windowed_gathered_compute(
    sr: Semiring, a_stages, b_stages, *, lrA, lrB, lcB, block_rows,
    flop_caps, out_caps, skip, backend, mode, chunk_w, interpret,
    block_cols, panel_cap, zero, dtype,
):
    """Block-outer windowed accumulate + extract over PRE-GATHERED stage
    tiles — the per-device core of the gathered schedule, shared by the
    2D shard_map kernel and the per-layer 3D kernel
    (``mesh3d.summa3d_spgemm_windowed``).  Iterates the PACKED launch
    list (``_live_windows_by_block`` / ``packed_windows``) so sparse
    plans pay one accumulate+extract per occupied window.  Returns
    (chunks, worst)."""
    from ..ops.spgemm import (
        accumulate_block_scatter,
        densify_combine,
        mask_rows,
    )

    p = len(a_stages)
    kind = _PALLAS_KINDS.get(sr.name)
    two_d, pcols, pk, pwin = _windowed_dims(backend, block_cols, lrB, lcB)
    b_sides = [
        _windowed_stage_b_side(sr, b, backend, two_d, pk, pcols, block_cols)
        for b in b_stages
    ]
    chunks = []
    worst = jnp.int32(0)
    if two_d:
        for g, hs in _live_windows_by_block(skip):
            lo = g * block_rows
            rb = min(block_rows, lrA - lo)
            arows = _pad128(rb)
            accs = {h: jnp.full((arows, pwin), zero, dtype) for h in hs}
            for s in range(p):
                am = mask_rows(a_stages[s], lo, lo + rb)
                da = densify_combine(
                    sr, _shift_rowblock(am, lo, arows), arows, pk
                )
                bs_sorted, b_starts = b_sides[s]
                for h in hs:
                    panel = _dense_col_panel(
                        sr, bs_sorted, b_starts, h, block_cols, pk,
                        pwin, panel_cap,
                    )
                    accs[h] = sr.add(
                        accs[h],
                        _window_stage_product(
                            sr, kind, da, panel, mode, interpret
                        ),
                    )
            for h in hs:
                chunk, over = _extract_window_2d(
                    accs[h], zero, lo, h, rb, block_cols, lrA, lcB,
                    out_caps[g][h],
                )
                worst = jnp.maximum(worst, over)
                chunks.append(chunk)
        return chunks, worst
    for g in packed_windows(skip):
        lo = g * block_rows
        rb = min(block_rows, lrA - lo)
        arows = _pad128(rb) if backend == "dot" else rb
        acc = jnp.full((arows, pcols), zero, dtype)
        for s in range(p):
            am = mask_rows(a_stages[s], lo, lo + rb)
            if backend == "scatter":
                acc = accumulate_block_scatter(
                    sr, acc, am, b_sides[s], row_lo=lo,
                    flop_capacity=max(flop_caps[g], chunk_w),
                    chunk_w=chunk_w,
                )
            else:
                da = densify_combine(
                    sr, _shift_rowblock(am, lo, arows), arows, pk
                )
                acc = sr.add(
                    acc,
                    _window_stage_product(
                        sr, kind, da, b_sides[s], mode, interpret
                    ),
                )
        chunk, over = _extract_block_1d(
            acc, zero, lo, rb, lrA, lcB, out_caps[g]
        )
        worst = jnp.maximum(worst, over)
        chunks.append(chunk)
    return chunks, worst


def _windowed_carousel_compute(
    sr: Semiring, a_mine, b_mine, *, p, lrA, lrB, lcB, block_rows,
    flop_caps, out_caps, skip, backend, mode, chunk_w, interpret,
    block_cols, panel_cap, zero, dtype, pipeline,
):
    """STAGE-OUTER carousel windowed accumulate + extract: the operands
    live in two-slot neighbor-rotation buffers (O(2·tile) sparse memory
    instead of the gathered schedule's O(p·tile)) and with
    ``pipeline=True`` stage ``s+1``'s ``ppermute`` is issued BEFORE
    stage ``s``'s tiles are consumed, so the ICI rotation overlaps the
    MXU/scatter accumulate.  The trade: ALL live block/window
    accumulators coexist across the stage loop (the gathered schedule
    keeps one block live at a time) — callers pick this schedule where
    the per-device dense tile is grid-divided small (the distributed
    mid-scale regime it is built for).

    ``pipeline=False`` is the measurement control: the rotation is
    pinned BEHIND the stage's accumulate (``_chain_tiles``), the strict
    rotate→compute→rotate serial chain."""
    from ..ops.spgemm import (
        accumulate_block_scatter,
        densify_combine,
        mask_rows,
    )

    kind = _PALLAS_KINDS.get(sr.name)
    two_d, pcols, pk, pwin = _windowed_dims(backend, block_cols, lrB, lcB)

    def block_geom(g):
        lo = g * block_rows
        rb = min(block_rows, lrA - lo)
        arows = _pad128(rb) if backend == "dot" else rb
        return lo, rb, arows

    if two_d:
        live = _live_windows_by_block(skip)
        accs = {
            (g, h): jnp.full((block_geom(g)[2], pwin), zero, dtype)
            for g, hs in live for h in hs
        }
    else:
        live = packed_windows(skip)
        accs = {
            g: jnp.full((block_geom(g)[2], pcols), zero, dtype)
            for g in live
        }
    skew_a, skew_b, rot_a, rot_b = _carousel_perms(p)
    a_cur = _rotate_tiles(a_mine, skew_a)
    b_cur = _rotate_tiles(b_mine, skew_b)
    for s in range(p):
        a_nxt = b_nxt = None
        overlapped = pipeline and s != p - 1
        if overlapped:
            a_nxt = _rotate_tiles(a_cur, rot_a)
            b_nxt = _rotate_tiles(b_cur, rot_b)
        if obs.ENABLED:
            # trace-time schedule record: one event per carousel stage
            # noting whether its successor rotation was issued early
            obs.span_event(
                "spgemm.pipeline.stage", stage=s,
                overlapped=bool(overlapped),
            )
        b_side = _windowed_stage_b_side(
            sr, b_cur, backend, two_d, pk, pcols, block_cols
        )
        if two_d:
            bs_sorted, b_starts = b_side
            for g, hs in live:
                lo, rb, arows = block_geom(g)
                am = mask_rows(a_cur, lo, lo + rb)
                da = densify_combine(
                    sr, _shift_rowblock(am, lo, arows), arows, pk
                )
                for h in hs:
                    panel = _dense_col_panel(
                        sr, bs_sorted, b_starts, h, block_cols, pk,
                        pwin, panel_cap,
                    )
                    accs[(g, h)] = sr.add(
                        accs[(g, h)],
                        _window_stage_product(
                            sr, kind, da, panel, mode, interpret
                        ),
                    )
        else:
            for g in live:
                lo, rb, arows = block_geom(g)
                am = mask_rows(a_cur, lo, lo + rb)
                if backend == "scatter":
                    accs[g] = accumulate_block_scatter(
                        sr, accs[g], am, b_side, row_lo=lo,
                        flop_capacity=max(flop_caps[g], chunk_w),
                        chunk_w=chunk_w,
                    )
                else:
                    da = densify_combine(
                        sr, _shift_rowblock(am, lo, arows), arows, pk
                    )
                    accs[g] = sr.add(
                        accs[g],
                        _window_stage_product(
                            sr, kind, da, b_side, mode, interpret
                        ),
                    )
        if s != p - 1:
            if not pipeline:
                # serial-chain control: rotation waits for this stage's
                # ENTIRE accumulate — every live accumulator, else XLA
                # may overlap the rotation with the unpinned blocks and
                # the control stops being serial
                dep = (
                    tuple(accs.values()) if accs else jnp.int32(0)
                )
                a_cur = _chain_tiles(a_cur, dep)
                b_cur = _chain_tiles(b_cur, dep)
                a_nxt = _rotate_tiles(a_cur, rot_a)
                b_nxt = _rotate_tiles(b_cur, rot_b)
            a_cur, b_cur = a_nxt, b_nxt
    chunks = []
    worst = jnp.int32(0)
    if two_d:
        for g, hs in live:
            lo, rb, _ = block_geom(g)
            for h in hs:
                chunk, over = _extract_window_2d(
                    accs[(g, h)], zero, lo, h, rb, block_cols, lrA,
                    lcB, out_caps[g][h],
                )
                worst = jnp.maximum(worst, over)
                chunks.append(chunk)
        return chunks, worst
    for g in live:
        lo, rb, _ = block_geom(g)
        chunk, over = _extract_block_1d(
            accs[g], zero, lo, rb, lrA, lcB, out_caps[g]
        )
        worst = jnp.maximum(worst, over)
        chunks.append(chunk)
    return chunks, worst


@partial(
    jax.jit,
    static_argnames=(
        "sr", "block_rows", "flop_caps", "out_caps", "skip", "backend",
        "mode", "chunk_w", "interpret", "block_cols", "panel_cap",
        "ring", "pipeline",
    ),
)
def summa_spgemm_windowed(
    sr: Semiring,
    A: SpParMat,
    B: SpParMat,
    *,
    block_rows: int,
    flop_caps: tuple,
    out_caps: tuple,
    skip: tuple | None = None,
    backend: str = "scatter",
    mode: str = "f32",
    chunk_w: int = 8,
    interpret: bool = False,
    block_cols: int | None = None,
    panel_cap: int | None = None,
    ring: bool = False,
    pipeline: bool = True,
) -> tuple[SpParMat, jax.Array]:
    """Sort-free SUMMA over dense ROW-BLOCK accumulators — the mid-scale
    general sparse-output tier.

    The classic ESC kernel's cost wall is the (row, col) sort over every
    expansion slot (~87 s at scale 16 on the chip; minutes on XLA:CPU,
    whose sort runs ~1 M slots/s).  Here each output row block is
    accumulated DENSELY and extracted once:

      per row block g (static python loop, empty blocks SKIPPED via the
      symbolic skip list):
        acc[g]  <- semiring-fold of every stage's expansion restricted
                   to the block's rows
            backend="scatter": chunked expansion + one native
                ``at[].{add,min,max}`` per stage (ops/spgemm.
                accumulate_block_scatter) — the general path on backends
                with a scatter unit (XLA:CPU);
            backend="dot": densified stage operands × `_mxu_dot` /
                the Pallas semiring matmul — the MXU path
                (``summa_spgemm_mxu`` generalized to row blocks).  With
                ``block_cols=None`` the dense B stage operand spans the
                whole tile width (legacy 1D form — only fits inside the
                mxu envelope); with ``block_cols`` set the output is
                tiled into (row block × col window) 2D windows and each
                stage densifies only B's COLUMN PANEL for the current
                window (CSC slot-range slice → [pk, pwin] dense panel,
                ``_dense_col_panel``), so peak stage-operand memory is
                pk × pwin cells — bounded by the window, which is what
                makes this the TPU mid-scale tier.  Both dot forms
                densify with the semiring's combining scatter
                (``densify_combine``), so duplicate-entry COO inputs
                are absorbed exactly on EVERY windowed backend; only
                the mxu tier keeps the unique-entries precondition.
        extract acc with the windowed output-driven extraction
        (``sparsify_windowed``), sized by the exact symbolic
        per-block (or per-window) output bound (``windowed_plan`` /
        ``windowed_plan_2d``); symbolically-empty 2D windows are never
        densified, matmul'd, or scanned.

    In 2D form ``flop_caps``/``out_caps``/``skip`` are tuples of
    per-block tuples from ``windowed_plan_2d`` and ``panel_cap`` bounds
    one window's B-panel nnz (``summa_window_bnnz``).  Returns
    (C, overflow) with the same overflow contract as
    ``summa_spgemm_mxu`` — though with symbolic-bound out_caps overflow
    is structurally zero (the bound dominates the realized nnz).

    The output tile's valid slots form a compacted PREFIX PER BLOCK
    (1D: globally row-ordered; 2D: row-block-major, then window-major
    within a block — NOT globally row-sorted), with padding interleaved
    between blocks — ``valid_mask`` semantics, which every downstream
    consumer (to_dense, CSR/CSC builds, ewise, redistribute) honors;
    a global re-sort would reintroduce the cost this kernel removes.

    SCHEDULES.  ``ring=False`` (default) is the GATHERED schedule: one
    fused all_gather per operand stages all tiles up front, then a
    block-outer loop keeps one dense accumulator live at a time (peak
    sparse memory O(p·tile)).  ``ring=True`` is the STAGE-PIPELINED
    CAROUSEL: operands rotate neighbor-to-neighbor in two-slot buffers
    (peak sparse memory O(2·tile)) and with ``pipeline=True`` stage
    s+1's ``ppermute`` is issued before stage s's tiles are consumed,
    so the ICI rotation overlaps the accumulate — the van de Geijn &
    Watts overlap the gathered schedule leaves to chance.  The carousel
    keeps every live block/window accumulator alive across the stage
    loop, so it fits where per-device tiles are grid-divided small (its
    distributed target regime).  ``pipeline=False`` pins the strict
    rotate→compute→rotate serial chain (the measurement control).
    Both schedules iterate the PACKED launch list (``packed_windows`` /
    ``packed_windows_2d``) and emit identical chunk layouts.
    """
    from ..ops.spgemm import scatter_combine_for

    _check_compat(A, B)
    grid = A.grid
    p = grid.pr
    lrA, lcA = A.local_rows, A.local_cols
    lrB, lcB = B.local_rows, B.local_cols
    nblocks = -(-lrA // block_rows)
    two_d = backend == "dot" and block_cols is not None
    ncw = -(-lcB // block_cols) if two_d else 1
    if skip is None:
        skip = ((False,) * ncw,) * nblocks if two_d else (False,) * nblocks
    assert len(flop_caps) == len(out_caps) == len(skip) == nblocks, (
        nblocks, len(flop_caps), len(out_caps), len(skip)
    )
    kind = _PALLAS_KINDS.get(sr.name)
    if backend == "dot":
        assert kind is not None, (
            f"backend='dot' supports semirings {sorted(_PALLAS_KINDS)}; "
            f"got {sr.name}"
        )
        assert scatter_combine_for(sr) is not None, sr.name
        if two_d:
            assert panel_cap is not None and panel_cap >= 1
            assert all(len(row) == ncw for row in skip), (ncw, skip)
    else:
        assert backend == "scatter", backend
        assert scatter_combine_for(sr) is not None, (
            f"semiring {sr.name} has no scatter combiner; use the ESC "
            "path"
        )
    if obs.ENABLED:
        obs.count(
            "trace.summa_spgemm_windowed",
            backend=("dot2d" if two_d else backend),
            ring=ring,
        )
        if ring and pipeline and p > 1:
            # trace-time: carousel stages whose successor rotation is
            # issued early (overlappable) in this compiled program
            obs.count("spgemm.pipeline.stages_overlapped", p - 1)
    zero = float(np.asarray(sr.zero_fn(A.vals.dtype)))
    static = dict(
        lrA=lrA, lrB=lrB, lcB=lcB, block_rows=block_rows,
        flop_caps=flop_caps, out_caps=out_caps, skip=skip,
        backend=backend, mode=mode, chunk_w=chunk_w,
        interpret=interpret, block_cols=block_cols if two_d else None,
        panel_cap=panel_cap, zero=zero, dtype=A.vals.dtype,
    )

    def body(ar, ac, av, an, br, bc, bv, bn):
        a_mine = A.local_tile(ar, ac, av, an)
        b_mine = B.local_tile(br, bc, bv, bn)
        if ring:
            chunks, worst = _windowed_carousel_compute(
                sr, a_mine, b_mine, p=p, pipeline=pipeline, **static
            )
        else:
            a_stages = _gather_stage_tiles(a_mine, COL_AXIS, p)
            b_stages = _gather_stage_tiles(b_mine, ROW_AXIS, p)
            chunks, worst = _windowed_gathered_compute(
                sr, a_stages, b_stages, **static
            )
        if not chunks:  # every block skipped: structurally empty output
            chunks.append(SpTuples.empty(lrA, lcB, 1, A.vals.dtype))
        out = SpTuples.concat(chunks)
        worst = lax.pmax(lax.pmax(worst, ROW_AXIS), COL_AXIS)
        return SpParMat._pack_tile(out) + (worst[None, None],)

    r, c, v, n, overflow = jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(TILE_SPEC,) * 8,
        out_specs=(TILE_SPEC,) * 5,
        check_vma=False,
    )(A.rows, A.cols, A.vals, A.nnz, B.rows, B.cols, B.vals, B.nnz)
    mat = SpParMat(
        rows=r, cols=c, vals=v, nnz=n,
        nrows=A.nrows, ncols=B.ncols, grid=grid,
    )
    return mat, overflow[0, 0]


class PhaseAdjustedWarning(UserWarning):
    """Structured phase-adaptation notice (VERDICT r3 weak #8): carries
    (requested, actual, local_cols) so a memory-budget caller can catch it
    programmatically (``warnings.catch_warnings(record=True)``) instead of
    parsing the message.  ``actual`` is always >= ``requested`` (phases
    only grow, so each phase stays within the budgeted size) and <= 4x."""

    def __init__(self, requested: int, actual: int, local_cols: int):
        self.requested = requested
        self.actual = actual
        self.local_cols = local_cols
        super().__init__(
            f"mem_efficient_spgemm: {requested} phases does not divide "
            f"local_cols={local_cols}; using the nearest divisor {actual} "
            "instead"
        )


def mem_efficient_spgemm(
    sr: Semiring,
    A: SpParMat,
    B: SpParMat,
    phases: int,
    *,
    slack: float = 1.05,
    prune_fn=None,
    scan: bool = False,
) -> SpParMat:
    """Phased SUMMA: C = A ⊗ B computed over column chunks of B.

    Reference: ``MemEfficientSpGEMM`` (ParFriends.h:450-731) — B is
    ``ColSplit`` into ``phases`` local column chunks; each phase runs a full
    SUMMA plus an optional ``prune_fn`` hook (MCL's prune/recover/select,
    ParFriends.h:186-350), and phase outputs concatenate back. Peak expansion
    memory drops ~``phases``-fold at the cost of re-gathering A every phase.
    The reference auto-computes ``phases`` from a memory budget via
    ``EstPerProcessNnzSUMMA``; here the symbolic pass inside ``spgemm`` sizes
    each phase exactly, so callers choose ``phases`` directly.

    ``scan=True`` additionally bounds each phase's EXPANSION memory by the
    output (``spgemm_scan``'s running accumulator) — phases cap the gather
    width, scan caps the ESC working set; together they give the
    O(output)-memory profile of the reference's hash path.
    """
    lc = B.local_cols
    if phases > 1 and B.ncols != lc * B.grid.pc:
        # An irregular (padded) column distribution cannot be phase-split;
        # silently unphasing would blow the caller's memory budget, so fail
        # loudly with guidance (reference phase contract: ParFriends.h:450).
        raise ValueError(
            f"mem_efficient_spgemm: ncols={B.ncols} is not evenly "
            f"distributed over pc={B.grid.pc} (local_cols={lc}); pad the "
            "matrix to a multiple of pc or run with phases=1"
        )
    if phases > 1 and lc % phases:
        # Nearest divisor >= requested keeps every phase AT MOST the size
        # the caller budgeted for (more phases = smaller phases = safe) —
        # but only within 4x, so a divisor-poor lc (e.g. prime) fails
        # loudly instead of silently multiplying the SUMMA pass count.
        adj = min(phases, lc)
        while adj <= lc and lc % adj:
            adj += 1
        if adj > 4 * phases:
            raise ValueError(
                f"mem_efficient_spgemm: {phases} phases does not divide "
                f"local_cols={lc} and the nearest divisor above it ({adj}) "
                "is >4x the request; choose a phase count dividing "
                f"local_cols (divisors of {lc}) or repad the matrix"
            )
        import warnings

        warnings.warn(
            PhaseAdjustedWarning(phases, adj, lc), stacklevel=2,
        )
        if obs.ENABLED:
            obs.count("spgemm.phase_adjusted")
        phases = adj
    if obs.ENABLED:
        # after adjustment: the phase count actually executed, matching
        # the number of spgemm.phase spans below
        obs.gauge("spgemm.phases", phases, scan=str(scan))
    mult = (
        (lambda a, b: spgemm_scan(sr, a, b, slack=slack))
        if scan
        else (lambda a, b: spgemm(sr, a, b, slack))
    )
    if phases <= 1:
        C = mult(A, B)
        return prune_fn(C) if prune_fn is not None else C
    outs = []
    for pi, Bs in enumerate(B.col_split(phases)):
        # A phase holds ~1/phases of the nnz but inherits B's full slot
        # capacity from col_split; truncate so the per-phase SUMMA gathers
        # phase-sized arrays (the point of phasing is peak-memory reduction).
        with obs.span("spgemm.phase", phase=pi):
            C = mult(A, Bs.shrink_to_fit())
            if prune_fn is not None:
                C = prune_fn(C)
        outs.append(C)
    return SpParMat.col_concatenate(outs)


def block_spgemm(
    sr: Semiring,
    A: SpParMat,
    B: SpParMat,
    row_blocks: int = 1,
    col_blocks: int = 1,
    slack: float = 1.05,
):
    """Generator over output blocks: yields ((i, j), C_ij) where
    C_ij = A[rowblock_i, :] ⊗ B[:, colblock_j].

    Reference: ``BlockSpGEMM`` (BlockSpGEMM.h:16-137) — iterate SUMMA over
    logical output blocks so no more than one block's expansion is live at
    a time (out-of-core-style memory bounding; the driver streams blocks to
    the caller, e.g. for writeout). Splits are LOCAL like col_split;
    ``SpParMat.col_concatenate`` / stacking reassembles if needed.
    """
    a_rows = A.row_split(row_blocks) if row_blocks > 1 else [A]
    b_cols = B.col_split(col_blocks) if col_blocks > 1 else [B]
    b_cols = [b.shrink_to_fit() for b in b_cols]  # once, not per row block
    for i, Ai in enumerate(a_rows):
        Ai = Ai.shrink_to_fit()
        for j, Bj in enumerate(b_cols):
            yield (i, j), spgemm(sr, Ai, Bj, slack)


def estimate_flops(A: SpParMat, B: SpParMat) -> int:
    """Total semiring multiplications of A ⊗ B.

    Reference: ``EstimateFLOP`` (ParFriends.h:356-448) — here the exact
    distributed symbolic pass summed over stages and tiles (true scalar
    multiplies, not chunk-padded slots).
    """
    import numpy as np

    return int(
        host_value(summa_stage_flops(A, B, padded=False)).astype(np.float64).sum()
    )


def calculate_phases(
    A: SpParMat, B: SpParMat, per_device_memory_bytes: int,
    slack: float = 1.05,
) -> int:
    """Phase count for ``mem_efficient_spgemm`` from a memory budget.

    Reference: ``CalculateNumberOfPhases`` (ParFriends.h:733-797) — there
    from ``perProcessMemory`` GB and the SUMMA nnz estimate; here from the
    peak per-device expansion of the unphased product (stage flops × slot
    bytes) against the caller's budget, rounded to a divisor-friendly
    power of two.
    """
    per_stage = host_value(summa_stage_flops(A, B)).astype(np.float64)
    slot_bytes = 4 + 4 + np.dtype(A.dtype).itemsize  # row + col + value
    # Peak per-device expansion follows the ALLOCATED shapes, not the valid
    # entries: summa_spgemm pads every one of the p coexisting stage chunks
    # to flop_capacity = max stage flops (static shapes), so the worst-case
    # skew allocates p x the single-stage max.
    p = A.grid.pr
    peak = per_stage.max() * p * slot_bytes * slack
    phases = max(1, int(np.ceil(peak / max(per_device_memory_bytes, 1))))
    phases = 1 << (phases - 1).bit_length()
    lc = B.local_cols
    if B.ncols != lc * B.grid.pc:
        # Irregular (padded) column distribution cannot be phase-split —
        # mem_efficient_spgemm rejects phases>1 there, so don't request it.
        return 1
    # Clamp to a divisor of B's local column count — mem_efficient_spgemm
    # only accepts divisors (it adjusts upward within 4x, errors beyond).
    phases = min(phases, max(lc, 1))
    while phases > 1 and lc % phases:
        phases >>= 1
    return phases


def estimate_nnz_upper(A: SpParMat, B: SpParMat) -> int:
    """Upper bound on nnz(C): per-tile flops clamped by the dense tile.

    The role of ``EstPerProcessNnzSUMMA``'s estimate (ParFriends.h:1243);
    exact nnz would need the hash symbolic pass — for capacity sizing the
    clamped-flops bound is what ``summa_capacities`` already uses.
    """
    import numpy as np

    # padded=False: size from TRUE flops (like estimate_flops) — the
    # chunk-padded counts belong to expansion capacities only, and at
    # CHUNK_W=32 they can inflate this bound 32x for short-B-row matrices
    # (ADVICE r3)
    per_stage = host_value(
        summa_stage_flops(A, B, padded=False)
    ).astype(np.float64)
    per_tile = per_stage.sum(axis=0)
    dense_tile = A.local_rows * B.local_cols
    return int(np.minimum(per_tile, dense_tile).sum())


def spgemm(
    sr: Semiring,
    A: SpParMat,
    B: SpParMat,
    slack: float = 1.05,
    *,
    pow2_caps: bool = True,
    merge: str | None = None,
    merge_source: str | None = None,
) -> SpParMat:
    """Convenience: symbolic pass → sized numeric SUMMA (unjitted entry).

    ≈ the user-facing ``Mult_AnXBn_Synch`` call; inside jit loops use
    ``summa_spgemm`` with pre-chosen capacities instead.

    ``pow2_caps`` rounds both capacities up to powers of two (≤2× memory
    slack) so iterative callers (MCL's expand loop, BC's per-level products)
    hit the XLA compilation cache instead of recompiling for every new nnz.

    ``merge``: the ESC stage-chunk combine (sort | runs) — ``None``
    resolves env ``COMBBLAS_SPGEMM_MERGE`` > ``"sort"`` (the classic
    path; ``spgemm_auto`` threads a plan record's remembered merge
    through with ``merge_source="store"`` so the provenance counter
    stays honest).  ``"hash"`` is a 3D-fiber tier; here it degrades
    to ``"runs"`` (the expansion-sized chunks would swamp an
    out-capacity table).
    """
    from ..tuner import config as tuner_config

    if merge is not None and merge_source is None:
        merge_source = "arg"
    if merge is None:
        merge = tuner_config.env_merge()
        merge_source = "env" if merge is not None else None
    if merge == "hash":
        merge = "runs"
    if merge is None:
        merge = "sort"
        merge_source = "heuristic"
    with obs.span("spgemm", sr=sr.name):
        if obs.ENABLED:
            obs.count(
                "spgemm.merge.tier", tier=merge, source=merge_source,
                op="spgemm",
            )
        flop_cap, out_cap = summa_capacities(A, B, slack)
        if pow2_caps:
            dense_tile = A.local_rows * B.local_cols
            flop_cap = 1 << (flop_cap - 1).bit_length()
            out_cap = min(1 << (out_cap - 1).bit_length(), max(dense_tile, 1))
        if obs.ENABLED:
            obs.span_event(
                "capacities", flop_capacity=flop_cap, out_capacity=out_cap
            )
        C = summa_spgemm(
            sr, A, B, flop_capacity=flop_cap, out_capacity=out_cap,
            merge=merge,
        )
        _record_realized_nnz(C)
        return C


def _record_realized_nnz(C: SpParMat) -> None:
    """Realized output fill-in (the other half of symbolic-vs-realized).
    Reading ``C.nnz`` is a device readback, so this records ONLY under
    the explicit ``obs.DEVICE_SYNC`` opt-in — never in a timed section
    on readback-poisoned hardware (bench.py module docstring)."""
    if obs.ENABLED and obs.DEVICE_SYNC:
        realized = int(np.asarray(host_value(C.nnz)).sum())
        obs.count("spgemm.realized_nnz", realized)


@partial(
    jax.jit,
    static_argnames=("sr", "flop_capacity", "out_capacity", "ring"),
)
def summa_spgemm_scan(
    sr: Semiring,
    A: SpParMat,
    B: SpParMat,
    *,
    flop_capacity: int,
    out_capacity: int,
    ring: bool = False,
) -> tuple[SpParMat, jax.Array]:
    """Output-bounded SUMMA: stage expansions fold into a RUNNING
    accumulator instead of coexisting.

    ``summa_spgemm`` keeps all p stage chunks live (peak ≈ p·flop_capacity
    slots — memory scales with FLOPs, the round-1 weakness); here each
    stage's expansion is immediately merged into an out_capacity-slot
    accumulator, so peak ≈ flop_capacity + 2·out_capacity slots — memory
    scales with the OUTPUT, the property the reference gets from hash
    accumulation (``LocalHybridSpGEMM``'s O(nnz_out) working set,
    mtSpGEMM.h:214-440). The trade is p small sorts instead of one big one.

    Returns (C, overflow): ``overflow`` is the global max, over tiles and
    stages, of (observed distinct keys − out_capacity). Zero means C is
    exact. Positive means truncation happened; note that once a stage
    truncates, its dropped keys vanish from later stages' counts, so a
    positive ``overflow`` is a LOWER BOUND on the true shortfall — always
    a correct truncation signal, not an exact requirement.
    ``spgemm_scan`` therefore grows capacity geometrically per retry
    rather than trusting one measurement (the estimateNNZ_Hash role,
    realized iteratively).
    """
    _check_compat(A, B)
    grid = A.grid
    p = grid.pr
    if obs.ENABLED:
        obs.count("trace.summa_spgemm_scan", ring=ring)
        if ring and p > 1:
            obs.count("spgemm.pipeline.stages_overlapped", p - 1)

    def body(ar, ac, av, an, br, bc, bv, bn):
        a_mine = A.local_tile(ar, ac, av, an)
        b_mine = B.local_tile(br, bc, bv, bn)
        acc = SpTuples.empty(
            a_mine.nrows, b_mine.ncols, out_capacity, A.vals.dtype
        )
        worst = jnp.int32(0)

        def merge(acc, worst, a_stage, b_stage):
            chunk = esc_expand(
                sr, a_stage, CSR.from_tuples(b_stage), flop_capacity
            )
            merged = SpTuples.concat([acc, chunk])
            acc, distinct = merged.compact_counted(sr, capacity=out_capacity)
            return acc, jnp.maximum(worst, distinct - out_capacity)

        if not ring:
            a_stages = _gather_stage_tiles(a_mine, COL_AXIS, p)
            b_stages = _gather_stage_tiles(b_mine, ROW_AXIS, p)
            for s in range(p):
                acc, worst = merge(acc, worst, a_stages[s], b_stages[s])
        else:
            # stage-pipelined carousel (shared two-slot schedule; see
            # summa_spgemm's ring path): stage s+1's rotation is issued
            # before stage s's expand+merge consumes the current tiles
            for s, a_cur, b_cur in _carousel_stages(a_mine, b_mine, p):
                acc, worst = merge(acc, worst, a_cur, b_cur)

        worst = lax.pmax(lax.pmax(worst, ROW_AXIS), COL_AXIS)
        return SpParMat._pack_tile(acc) + (worst[None, None],)

    r, c, v, n, overflow = jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(TILE_SPEC,) * 8,
        out_specs=(TILE_SPEC,) * 4 + (TILE_SPEC,),
        check_vma=False,
    )(A.rows, A.cols, A.vals, A.nnz, B.rows, B.cols, B.vals, B.nnz)
    mat = SpParMat(
        rows=r, cols=c, vals=v, nnz=n,
        nrows=A.nrows, ncols=B.ncols, grid=grid,
    )
    return mat, overflow[0, 0]


def spgemm_scan(
    sr: Semiring,
    A: SpParMat,
    B: SpParMat,
    *,
    out_capacity: int | None = None,
    slack: float = 1.1,
    max_retries: int = 3,
    ring: bool = False,
) -> SpParMat:
    """Output-bounded SpGEMM entry: size, run, retry on overflow.

    The initial ``out_capacity`` guess is deliberately cheap (a fraction of
    the clamped-flops bound); the first attempt's EXACT distinct-key count
    then corrects it, so high-collision products (MCL's A²) never allocate
    flops-shaped outputs. One host sync per attempt (off the hot path; on
    the axon chip prefer a caller-provided ``out_capacity``).
    """
    with obs.span("spgemm.scan", sr=sr.name):
        flop_cap, flops_out_cap = summa_capacities(A, B, slack)
        if out_capacity is None:
            # optimistic: half the flops bound, floor at the input sizes
            out_capacity = max(
                min(flops_out_cap, max(A.capacity, B.capacity)), 64
            )
        out_capacity = 1 << (int(out_capacity) - 1).bit_length()
        for attempt in range(max_retries + 1):
            C, overflow = summa_spgemm_scan(
                sr, A, B, flop_capacity=flop_cap, out_capacity=out_capacity,
                ring=ring,
            )
            over = int(overflow)
            if over <= 0:
                if obs.ENABLED:
                    obs.count("spgemm.scan.overflow_retries", attempt)
                    obs.span_event(
                        "sized", flop_capacity=flop_cap,
                        out_capacity=out_capacity, retries=attempt,
                    )
                    _record_realized_nnz(C)
                return C
            if obs.ENABLED:
                obs.count("spgemm.scan.overflow_slots", over)
            # ``over`` under-reports when an early stage truncated (see
            # summa_spgemm_scan docstring) — grow geometrically, at least 2x
            out_capacity = max(
                1 << (out_capacity + over - 1).bit_length(), out_capacity * 2
            )
        raise ValueError(
            f"spgemm_scan still overflowing by {over} after {max_retries} "
            "retries; pass an explicit out_capacity"
        )


def _pad128(x: int, to: int = 512) -> int:
    """Pad to a Pallas/MXU-friendly multiple (512 covers the tropical
    kernel's block sizes; plus_times only needs 128 but the extra padding
    is noise at these sizes)."""
    return -(-x // to) * to


_PALLAS_KINDS = {
    "plus_times": "plus_times",
    "min_plus": "min_plus",
    "max_min": "max_min",
}


def _mxu_dot(da, db, mode: str, out_dtype):
    """Dense plus_times stage product at the requested precision.

    Measured on the target chip (benchmarks/results/probe_r4a/b):
      f32 native dot      ~0.11 TFLOP/s  (exact f32)
      bf16 inputs         ~13.3 TFLOP/s  (EXACT when inputs are bf16-
                          representable — e.g. 0/1 adjacency — and the
                          f32-accumulated counts stay < 2^24)
      bf16x3 split-float  ~2-4 TFLOP/s   (hi/lo decomposition, error
                          ~2^-16 per operand — f32-grade for graph work)
    """
    if mode == "f32":
        return jnp.dot(da, db, preferred_element_type=out_dtype)
    if mode == "bf16":
        return jnp.dot(
            da.astype(jnp.bfloat16), db.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        ).astype(out_dtype)
    assert mode == "bf16x3", mode
    ah = da.astype(jnp.bfloat16)
    al = (da - ah.astype(da.dtype)).astype(jnp.bfloat16)
    bh = db.astype(jnp.bfloat16)
    bl = (db - bh.astype(db.dtype)).astype(jnp.bfloat16)
    out = (
        jnp.dot(ah, bh, preferred_element_type=jnp.float32)
        + jnp.dot(ah, bl, preferred_element_type=jnp.float32)
        + jnp.dot(al, bh, preferred_element_type=jnp.float32)
    )
    return out.astype(out_dtype)


@partial(
    jax.jit,
    static_argnames=("sr", "out_capacity", "mode", "interpret"),
)
def summa_spgemm_mxu(
    sr: Semiring,
    A: SpParMat,
    B: SpParMat,
    *,
    out_capacity: int,
    mode: str = "f32",
    interpret: bool = False,
) -> tuple[SpParMat, jax.Array]:
    """Dense-block SUMMA: stage products run on the MATRIX UNIT.

    On this TPU every sparse-side primitive is capped by the ~22 M/s
    per-element random-memory wall (PERF_NOTES_r3) while the MXU delivers
    13.3 TFLOP/s on bf16 blocks — below ~32K tile dims, spending n³ dense
    FLOPs beats sorting the sparse expansion outright: stage tiles densify
    (sorted-scatter), multiply via ``_mxu_dot`` (plus_times; ``mode``
    picks the precision/speed point) or the Pallas semiring matmul
    (min_plus/max_min — XLA has no tropical MXU lowering), accumulate into
    a DENSE [lr, lcB] buffer, and extract ONCE at the end with the
    windowed output-driven extraction (``ops.spgemm.sparsify_windowed``
    — ~2 contiguous-window ops per output slot; the round-2 searchsorted
    extraction cost 26+ s at scale 14 and is gone).  This is the
    "dense-block strategy for heavy columns" SURVEY §7 hard-part (b),
    taken to whole tiles.

    Returns (C, overflow) like ``summa_spgemm_scan`` (overflow = max tile
    nonzero count minus out_capacity; exact counts even when truncating).
    SUMMA3D layers compose the same way (per-layer tiles are smaller).
    """
    from ..ops.pallas_kernels import semiring_matmul
    from ..ops.spgemm import densify, sparsify_windowed

    _check_compat(A, B)
    if obs.ENABLED:
        obs.count("trace.summa_spgemm_mxu", mode=mode)
    kind = _PALLAS_KINDS.get(sr.name)
    assert kind is not None, (
        f"summa_spgemm_mxu supports semirings {sorted(_PALLAS_KINDS)}; "
        f"got {sr.name} (use summa_spgemm/summa_spgemm_scan)"
    )
    grid = A.grid
    p = grid.pr
    lrA, lcA = A.local_rows, A.local_cols
    lrB, lcB = B.local_rows, B.local_cols
    pm, pk, pn = _pad128(lrA), _pad128(lcA), _pad128(lcB)
    zero = float(np.asarray(sr.zero_fn(A.vals.dtype)))  # static python scalar

    def body(ar, ac, av, an, br, bc, bv, bn):
        a_mine = A.local_tile(ar, ac, av, an)
        b_mine = B.local_tile(br, bc, bv, bn)
        a_stages = _gather_stage_tiles(a_mine, COL_AXIS, p)
        b_stages = _gather_stage_tiles(b_mine, ROW_AXIS, p)
        acc = jnp.full((pm, pn), zero, A.vals.dtype)
        for s in range(p):
            da = densify(a_stages[s], pm, pk, zero)
            db = densify(b_stages[s], pk, pn, zero)
            if kind == "plus_times":
                prod = _mxu_dot(da, db, mode, acc.dtype)
            else:
                # XLA has no MXU/VPU lowering for tropical rings — this is
                # where the Pallas dense kernel earns its keep
                prod = semiring_matmul(
                    kind, da, db, bm=256, bk=512, bn=256,
                    interpret=interpret,
                )
            acc = sr.add(acc, prod)
        out, total = sparsify_windowed(acc, zero, lrA, lcB, out_capacity)
        worst = jnp.maximum(total - out_capacity, 0)
        worst = lax.pmax(lax.pmax(worst, ROW_AXIS), COL_AXIS)
        return SpParMat._pack_tile(out) + (worst[None, None],)

    r, c, v, n, overflow = jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(TILE_SPEC,) * 8,
        out_specs=(TILE_SPEC,) * 5,
        check_vma=False,
    )(A.rows, A.cols, A.vals, A.nnz, B.rows, B.cols, B.vals, B.nnz)
    mat = SpParMat(
        rows=r, cols=c, vals=v, nnz=n,
        nrows=A.nrows, ncols=B.ncols, grid=grid,
    )
    return mat, overflow[0, 0]


#: Above this local tile dimension the dense path loses: not to the
#: matmul (13.3 TFLOP/s bf16 — scale-14 tiles square in 0.7 s) but to the
#: sparse-output EXTRACTION, which is point-gather/padding-bound at ~3 s+
#: per 20M entries on the target chip (the full nine-design floor
#: analysis: benchmarks/results/PERF_NOTES_r4.md).  The sort-based
#: kernels take over beyond it.
MXU_MAX_TILE_DIM = 8192


#: Windowed-tier envelope. The tier scans every dense cell of each
#: non-skipped row block once during extraction, so it loses to the
#: ESC/scan sort once the output is EXTREMELY sparse relative to the
#: dense tile: the gate requires at most this many scanned cells per
#: symbolic flop (R-MAT A-squared at scale 16 sits near 11).
WINDOWED_MAX_CELLS_PER_FLOP = 16.0
#: Per-device dense-tile ceiling for the windowed tier (cells, not
#: bytes): one row-block accumulator plus the extraction pass must stay
#: cheap; 2^33 cells ≈ scale-17 square tiles on one device.
WINDOWED_MAX_TILE_CELLS = 1 << 33
#: Target cells per row-block accumulator (~256 MB f32) and an upper
#: bound on the unrolled block count (program size).
WINDOWED_BLOCK_CELLS = 1 << 26
WINDOWED_MAX_BLOCKS = 32
#: Expansion chunk width for the scatter backend: the scatter pays per
#: SLOT, so the narrow window keeps slot padding ~1.1x on R-MAT degree
#: tails (vs ~2x at the gather-bound ESC default of 32).
WINDOWED_CHUNK_W = 8
#: 2D ``dot`` backend envelope: one stage's dense B COLUMN PANEL
#: (padded k × padded col window) may hold at most this many cells
#: (2^27 ≈ 512 MB f32 / 256 MB bf16).  This is the cap that replaces
#: "B's whole dense tile must fit" — the reason the router can now
#: auto-route ``windowed`` on TPU above the mxu envelope.
WINDOWED_MAX_PANEL_CELLS = 1 << 27
#: Upper bound on the unrolled col-window count (program size, like
#: ``WINDOWED_MAX_BLOCKS`` for row blocks).
WINDOWED_MAX_COL_WINDOWS = 32


def default_block_cols(local_rows_b: int, local_cols_b: int) -> int:
    """Col-window width for the 2D ``dot`` backend: the widest
    512-multiple whose dense B panel (padded-k × window) stays within
    ``WINDOWED_MAX_PANEL_CELLS``, floored so at most
    ``WINDOWED_MAX_COL_WINDOWS`` windows unroll into the program.

    In the extreme region ``pad(k) · lcB > WINDOWED_MAX_COL_WINDOWS ·
    WINDOWED_MAX_PANEL_CELLS`` the two bounds conflict and the window-
    count floor wins (program size is a hard constraint; memory is the
    caller's budget) — the router never auto-routes there
    (``dot_panel_feasible`` gates it to scan), so only forced calls can
    exceed the envelope."""
    pk = _pad128(local_rows_b)
    bc = max((WINDOWED_MAX_PANEL_CELLS // pk) // 512 * 512, 512)
    floor_bc = -(-local_cols_b // WINDOWED_MAX_COL_WINDOWS)
    bc = max(bc, -(-floor_bc // 512) * 512)
    return min(bc, max(local_cols_b, 1))


def dot_panel_feasible(k_dim: int, n_dim: int | None = None) -> bool:
    """True iff a col window exists that fits the stage-operand
    envelope (``WINDOWED_MAX_PANEL_CELLS``) WITHOUT exceeding the
    unrolled-window budget: the narrowest admissible window is 512
    cols, raised to ``ceil(n / WINDOWED_MAX_COL_WINDOWS)`` when B's
    tile width is known (``default_block_cols`` floors there to bound
    program size, so the envelope must hold at that width too)."""
    win = 512
    if n_dim is not None:
        floor_bc = -(-n_dim // WINDOWED_MAX_COL_WINDOWS)
        win = max(win, -(-floor_bc // 512) * 512)
    return _pad128(k_dim) * win <= WINDOWED_MAX_PANEL_CELLS


def default_block_rows(local_rows: int, local_cols_b: int) -> int:
    """Row-block height for the windowed tier: close to
    ``WINDOWED_BLOCK_CELLS`` per dense accumulator, at most
    ``WINDOWED_MAX_BLOCKS`` blocks (the static loop is unrolled into the
    program), multiple-of-8 for the extraction's cell groups."""
    pcols = max(-(-local_cols_b // 128) * 128, 1)
    br = max(1, min(local_rows, WINDOWED_BLOCK_CELLS // pcols))
    br = max(br, -(-local_rows // WINDOWED_MAX_BLOCKS))
    return min(-(-br // 8) * 8, max(local_rows, 1))


@partial(
    jax.jit,
    static_argnames=("sr", "rb", "flop_cap", "out_cap", "chunk_w"),
)
def _windowed_block_local(
    sr: Semiring,
    a: SpTuples,
    b_csr,
    lo,
    *,
    rb: int,
    flop_cap: int,
    out_cap: int,
    chunk_w: int,
):
    """One row block of the LOCAL windowed tier (see
    ``local_spgemm_windowed``).  ``lo`` is a TRACED scalar so blocks with
    the same (rb, caps) signature share one compiled program."""
    from ..ops.spgemm import (
        accumulate_block_scatter,
        mask_rows,
        sparsify_windowed,
    )

    lrA, lcB = a.nrows, b_csr.ncols
    pcols = -(-lcB // 128) * 128
    zero = sr.zero(a.vals.dtype)
    am = mask_rows(a, lo, lo + rb)
    acc = jnp.full((rb, pcols), zero, a.vals.dtype)
    acc = accumulate_block_scatter(
        sr, acc, am, b_csr, row_lo=lo, flop_capacity=flop_cap,
        chunk_w=chunk_w,
    )
    t, total = sparsify_windowed(
        acc, float(np.asarray(sr.zero_fn(a.vals.dtype))), rb, lcB, out_cap
    )
    rows = jnp.where(t.valid_mask(), t.rows + lo, lrA)
    return rows, t.cols, t.vals, t.nnz, total


@jax.jit
def _local_csr(t: SpTuples) -> CSR:
    return CSR.from_tuples(t)


@partial(jax.jit, static_argnames=("block_cols",))
def _colmajor_with_starts(t: SpTuples, block_cols: int):
    """Col-major-sorted tile + per-window CSC slot starts (the panel
    slicing preamble of the 2D dot backend, hoisted out of the per-block
    programs on the local fast path)."""
    ts = t.sort_colmajor()
    ncw = -(-t.ncols // block_cols)
    bounds = jnp.minimum(
        jnp.arange(ncw + 1, dtype=jnp.int32) * block_cols, t.ncols
    )
    starts = jnp.searchsorted(ts.cols, bounds, side="left").astype(
        jnp.int32
    )
    return ts, starts


@partial(
    jax.jit,
    static_argnames=(
        "sr", "rb", "out_caps_row", "skip_row", "block_cols", "pk",
        "pwin", "panel_cap", "mode", "interpret",
    ),
)
def _windowed_block_local_dot(
    sr: Semiring,
    a: SpTuples,
    bs: SpTuples,
    b_starts,
    lo,
    *,
    rb: int,
    out_caps_row: tuple,
    skip_row: tuple,
    block_cols: int,
    pk: int,
    pwin: int,
    panel_cap: int,
    mode: str,
    interpret: bool,
):
    """One ROW BLOCK of the local 2D ``dot`` tier: all of the block's
    non-skipped col windows in one small program (single device → single
    stage, so the accumulator is the stage product itself).  ``lo`` is
    traced so blocks with the same static signature share a compile."""
    from ..ops.spgemm import densify_combine, mask_rows, sparsify_windowed

    lrA, lcB = a.nrows, bs.ncols
    kind = _PALLAS_KINDS[sr.name]
    arows = _pad128(rb)
    zero = float(np.asarray(sr.zero_fn(a.vals.dtype)))
    am = mask_rows(a, lo, lo + rb)
    da = densify_combine(sr, _shift_rowblock(am, lo, arows), arows, pk)
    rows_l, cols_l, vals_l = [], [], []
    nnz = jnp.int32(0)
    worst = jnp.int32(0)
    for h in packed_windows(skip_row):  # packed launch list
        panel = _dense_col_panel(
            sr, bs, b_starts, h, block_cols, pk, pwin, panel_cap
        )
        prod = _window_stage_product(sr, kind, da, panel, mode, interpret)
        wc = min(block_cols, lcB - h * block_cols)
        t, total = sparsify_windowed(prod, zero, rb, wc, out_caps_row[h])
        worst = jnp.maximum(worst, total - out_caps_row[h])
        vm = t.valid_mask()
        rows_l.append(jnp.where(vm, t.rows + lo, lrA))
        cols_l.append(jnp.where(vm, t.cols + h * block_cols, lcB))
        vals_l.append(t.vals)
        nnz = nnz + t.nnz
    return (
        jnp.concatenate(rows_l), jnp.concatenate(cols_l),
        jnp.concatenate(vals_l), nnz, worst,
    )


def local_spgemm_windowed(
    sr: Semiring,
    A: SpParMat,
    B: SpParMat,
    *,
    block_rows: int,
    flop_caps: tuple,
    out_caps: tuple,
    skip: tuple,
    chunk_w: int = 8,
    backend: str = "scatter",
    block_cols: int | None = None,
    panel_cap: int | None = None,
    mode: str = "f32",
    interpret: bool = False,
) -> tuple[SpParMat, jax.Array]:
    """Single-device (1x1 grid) fast path of the windowed tier: a HOST
    loop dispatching one small compiled program PER ROW BLOCK instead of
    the one fused shard_map graph.

    Measured on XLA:CPU at scale 16 (benchmarks/spgemm_bench.py): the
    32-block fused program runs 340 s while the same work as separate
    per-block programs runs ~100 s — the giant graph defeats the
    scheduler (and shard_map adds another layer even on one device), so
    on a single device the unfused dispatch is the honest kernel.  The
    shard_map kernel (``summa_spgemm_windowed``) remains the multi-device
    path where the stage collectives must live inside one program.

    Same plan/caps contract and return shape as ``summa_spgemm_windowed``.
    ``backend="dot"`` requires ``block_cols``/``panel_cap`` and 2D caps
    from ``windowed_plan_2d`` — each row block's program covers its
    non-skipped col windows (``_windowed_block_local_dot``).
    """
    assert A.grid.size == 1 and B.grid.size == 1
    _check_compat(A, B)
    lrA, lcB = A.local_rows, B.local_cols
    a = A.local_tile(A.rows, A.cols, A.vals, A.nnz)
    bt = B.local_tile(B.rows, B.cols, B.vals, B.nnz)
    if backend == "dot":
        assert block_cols is not None and panel_cap is not None
        bs, b_starts = _colmajor_with_starts(bt, block_cols)
        pk = _pad128(B.local_rows)
        pwin = _pad128(block_cols)
    else:
        assert backend == "scatter", backend
        b_csr = _local_csr(bt)
    rows_l, cols_l, vals_l = [], [], []
    nnz = None
    worst = jnp.int32(0)
    for g, (fc, oc, sk) in enumerate(zip(flop_caps, out_caps, skip)):
        if (all(sk) if backend == "dot" else sk):
            continue
        lo = g * block_rows
        rb = min(block_rows, lrA - lo)
        if backend == "dot":
            r, c, v, nz, over = _windowed_block_local_dot(
                sr, a, bs, b_starts, jnp.int32(lo), rb=rb,
                out_caps_row=oc, skip_row=sk, block_cols=block_cols,
                pk=pk, pwin=pwin, panel_cap=panel_cap, mode=mode,
                interpret=interpret,
            )
            rows_l.append(r)
            cols_l.append(c)
            vals_l.append(v)
            nnz = nz if nnz is None else nnz + nz
            worst = jnp.maximum(worst, over)
            continue
        r, c, v, nz, total = _windowed_block_local(
            sr, a, b_csr, jnp.int32(lo), rb=rb,
            flop_cap=max(fc, chunk_w), out_cap=oc, chunk_w=chunk_w,
        )
        rows_l.append(r)
        cols_l.append(c)
        vals_l.append(v)
        nnz = nz if nnz is None else nnz + nz
        worst = jnp.maximum(worst, total - oc)
    if not rows_l:
        t = SpTuples.empty(lrA, lcB, 1, A.vals.dtype)
        rows_l, cols_l, vals_l = [t.rows], [t.cols], [t.vals]
        nnz = t.nnz
    rows = jnp.concatenate(rows_l)
    cols = jnp.concatenate(cols_l)
    vals = jnp.concatenate(vals_l)
    mat = SpParMat(
        rows=rows[None, None], cols=cols[None, None],
        vals=vals[None, None], nnz=nnz[None, None],
        nrows=A.nrows, ncols=B.ncols, grid=A.grid,
    )
    return mat, worst


@partial(
    jax.jit,
    static_argnames=("sr", "rb", "flop_cap", "out_cap", "chunk_w"),
)
def _windowed_block_dist(
    sr: Semiring,
    A: SpParMat,
    B: SpParMat,
    lo,
    *,
    rb: int,
    flop_cap: int,
    out_cap: int,
    chunk_w: int,
):
    """One row block of the BLOCKED-DISPATCH distributed windowed tier
    (scatter backend): a self-contained shard_map program that gathers
    the stage tiles, accumulates ONE dense row block, and extracts it.
    ``lo`` is traced so blocks sharing (rb, caps) share a compile (the
    ``_windowed_block_local`` convention, distributed)."""
    from ..ops.spgemm import accumulate_block_scatter, mask_rows

    grid = A.grid
    p = grid.pr
    lrA, lcB = A.local_rows, B.local_cols
    pcols = -(-lcB // 128) * 128
    zero = float(np.asarray(sr.zero_fn(A.vals.dtype)))

    def body(lo_, ar, ac, av, an, br, bc, bv, bn):
        lo_ = lo_[0, 0]
        a_mine = A.local_tile(ar, ac, av, an)
        b_mine = B.local_tile(br, bc, bv, bn)
        a_stages = _gather_stage_tiles(a_mine, COL_AXIS, p)
        b_stages = _gather_stage_tiles(b_mine, ROW_AXIS, p)
        acc = jnp.full((rb, pcols), zero, A.vals.dtype)
        for s in range(p):
            am = mask_rows(a_stages[s], lo_, lo_ + rb)
            acc = accumulate_block_scatter(
                sr, acc, am, CSR.from_tuples(b_stages[s]), row_lo=lo_,
                flop_capacity=flop_cap, chunk_w=chunk_w,
            )
        chunk, over = _extract_block_1d(
            acc, zero, lo_, rb, lrA, lcB, out_cap
        )
        over = lax.pmax(lax.pmax(over, ROW_AXIS), COL_AXIS)
        return SpParMat._pack_tile(chunk) + (over[None, None],)

    lo_arr = jnp.broadcast_to(
        jnp.int32(lo), (grid.pr, grid.pc)
    )
    lo_arr = jax.device_put(
        lo_arr, jax.sharding.NamedSharding(grid.mesh, TILE_SPEC)
    )
    return jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(TILE_SPEC,) * 9,
        out_specs=(TILE_SPEC,) * 5,
        check_vma=False,
    )(lo_arr, A.rows, A.cols, A.vals, A.nnz,
      B.rows, B.cols, B.vals, B.nnz)


def summa_spgemm_windowed_blocked(
    sr: Semiring,
    A: SpParMat,
    B: SpParMat,
    *,
    block_rows: int,
    flop_caps: tuple,
    out_caps: tuple,
    skip: tuple,
    chunk_w: int = 8,
    serialize: bool = True,
) -> tuple[SpParMat, jax.Array]:
    """BLOCKED-DISPATCH distributed windowed tier (scatter backend): a
    host loop launching one small shard_map program per OCCUPIED row
    block instead of the one fused graph.

    The fused ``summa_spgemm_windowed`` unrolls every block into one
    program; XLA:CPU's scheduler then materializes many multi-GB dense
    accumulators concurrently — at scale 18 on the 2×2 virtual mesh the
    fused graph's live set exceeded 125 GB (r9 capture: OOM), the
    distributed incarnation of the r7 single-device lesson that led to
    ``local_spgemm_windowed``.  Per-block dispatch bounds the live set
    to ONE block's accumulator + expansion per device, at the cost of
    re-gathering the stage tiles per block (nblocks × p × tile bytes —
    noise next to the accumulate).  Blocks sharing (rb, caps) share a
    compile (``lo`` is traced); callers wanting maximal sharing pass
    uniform pow2 caps.

    Same plan/caps contract and output-layout contract as the fused
    kernel (valid slots form a compacted prefix per block).

    ``serialize=True`` (default) blocks on each block program before
    dispatching the next: XLA:CPU's multi-thread collective rendezvous
    deadlocks when device threads interleave DIFFERENT in-flight
    programs' gathers (observed at scale 18 — all threads futex-wait),
    so cross-program async pipelining is traded away; per-block
    dispatch overhead is noise next to the accumulate.  On hardware
    pods with ordered per-device streams, pass ``serialize=False`` to
    let dispatch run ahead."""
    assert len(flop_caps) == len(out_caps) == len(skip)
    lrA = A.local_rows
    parts = []
    nnz = None
    worst = jnp.int32(0)
    for g in packed_windows(skip):
        lo = g * block_rows
        rb = min(block_rows, lrA - lo)
        r, c, v, n, over = _windowed_block_dist(
            sr, A, B, lo, rb=rb,
            flop_cap=max(flop_caps[g], chunk_w),
            out_cap=out_caps[g], chunk_w=chunk_w,
        )
        if serialize:
            jax.block_until_ready(n)
        parts.append((r, c, v))
        nnz = n if nnz is None else nnz + n
        worst = jnp.maximum(worst, over[0, 0])
    if not parts:
        empty = SpParMat.from_global_coo(
            A.grid, np.zeros(0, np.int64), np.zeros(0, np.int64),
            np.zeros(0, A.vals.dtype), A.nrows, B.ncols,
        )
        return empty, jnp.int32(0)
    mat = SpParMat(
        rows=jnp.concatenate([p[0] for p in parts], axis=2),
        cols=jnp.concatenate([p[1] for p in parts], axis=2),
        vals=jnp.concatenate([p[2] for p in parts], axis=2),
        nnz=nnz, nrows=A.nrows, ncols=B.ncols, grid=A.grid,
    )
    return mat, worst


def resolve_spgemm_backend(backend: str | None = None) -> str:
    """Accumulate-backend resolution, shared by the router and the sized
    entries: explicit argument > ``COMBBLAS_SPGEMM_BACKEND`` env (parsed
    by ``tuner.config``, the one knob parser) > the platform default
    (``dot`` on TPU — no scatter unit — ``scatter`` elsewhere)."""
    from ..tuner import config as tuner_config

    if backend is None:
        backend = tuner_config.env_backend()
    if backend is None:
        backend = "dot" if jax.default_backend() == "tpu" else "scatter"
    assert backend in ("dot", "scatter"), backend
    return backend


def bucket_plan_caps(flop_caps, out_caps):
    """Pow2-round a windowed plan's capacities (1D int tuples or the 2D
    nested form) so per-block building-block programs share compiles:
    two blocks — or two PRODUCTS inside one shape bucket — whose caps
    round to the same powers of two hit one executable instead of
    compiling per exact count.  Caps are upper bounds, so rounding UP
    is always safe (≤2x extraction slots); callers that know the dense
    block geometry re-impose the cells clamp afterwards (the pow2 round
    can exceed a tail block's dense bound — see ``spgemm_windowed``).
    This is the r7/r9 per-block-program lesson generalized to the
    default path (disable with ``COMBBLAS_SPGEMM_BUCKET_CAPS=0``)."""
    rnd = lambda x: 1 << (max(int(x), 1) - 1).bit_length()

    def walk(t):
        return tuple(
            walk(x) if isinstance(x, tuple) else rnd(x) for x in t
        )

    return walk(flop_caps), walk(out_caps)


def panel_cap_from_bnnz(bnnz, capacity: int) -> int:
    """Static panel slice capacity from the per-(tile, window) B nnz
    counts: pow2-rounded max (compile reuse across inputs), clamped to
    the tile capacity (a slice can never hold more slots than exist)."""
    m = int(np.asarray(bnnz).max())
    return max(min(1 << max(m - 1, 1).bit_length(), capacity), 1)


def _oracle_out_caps_2d(
    sr, A: SpParMat, B: SpParMat, block_rows: int, block_cols: int,
    out_caps: tuple, skip: tuple,
) -> tuple[tuple, tuple]:
    """Tighten the 2D plan with the bit-packed support oracle
    (``spgemm_support_bits`` → ``support_window_counts``): per-window
    out caps become EXACT output counts instead of clamped-flops bounds
    (smaller extraction capacities / tighter col-window occupancy).
    Single-device only (the oracle computes a whole-matrix mask), and
    only sensible inside its dense envelope — callers gate on size."""
    from ..ops.spgemm import spgemm_support_bits, support_window_counts

    assert A.grid.size == 1 and block_cols % 32 == 0
    a = A.local_tile(A.rows, A.cols, A.vals, A.nnz)
    b = B.local_tile(B.rows, B.cols, B.vals, B.nnz)
    bits, _ = spgemm_support_bits(a, b)
    cnt = np.asarray(
        jax.device_get(
            support_window_counts(
                bits, block_rows, block_cols, A.local_rows, B.local_cols
            )
        )
    )
    new_caps, new_skip = [], []
    for g in range(len(out_caps)):
        row_c, row_s = [], []
        for h in range(len(out_caps[g])):
            exact = int(cnt[g, h])
            row_c.append(max(min(out_caps[g][h], exact), 1))
            row_s.append(bool(skip[g][h] or exact == 0))
        new_caps.append(tuple(row_c))
        new_skip.append(tuple(row_s))
    return tuple(new_caps), tuple(new_skip)


def spgemm_windowed(
    sr: Semiring,
    A: SpParMat,
    B: SpParMat,
    *,
    block_rows: int | None = None,
    block_cols: int | None = None,
    backend: str | None = None,
    mode: str = "f32",
    slack: float = 1.02,
    interpret: bool = False,
    oracle: bool = False,
    ring: bool = False,
    pipeline: bool = True,
    dispatch: str | None = None,
) -> SpParMat:
    """Sized entry for the windowed tier: device symbolic pass →
    ``windowed_plan`` (scatter, 1D) or ``windowed_plan_2d`` (dot, 2D) →
    the matching kernel (one host readback for sizing; benchmarks on
    readback-poisoned hardware size on host via
    ``summa_rowblock_flops_host`` / ``summa_window_flops_host`` +
    ``summa_window_bnnz_host`` instead).

    ``dispatch`` (argument > env ``COMBBLAS_SPGEMM_DISPATCH`` >
    ``"auto"``) picks the multi-device program decomposition for the
    scatter backend: ``"auto"`` (default) routes any product with more
    than one occupied row block through the BLOCKED building-block
    dispatch (``summa_spgemm_windowed_blocked`` — one small fixed-shape
    program per occupied block, caps pow2-bucketed so blocks share
    compiles), which bounds both first-touch compile time and the live
    set: no single XLA compile scales with the whole product (the
    scale-17 54-minute fused-compile wall cannot recur).  ``"fused"``
    forces the one-graph kernel (required by — and implied for — the
    ``ring`` carousel schedules); ``"blocked"`` forces per-block
    programs.  Single-device products already run per-block programs
    (``local_spgemm_windowed``); the dot backend's multi-device path
    has no blocked kernel yet and stays fused.

    ``oracle=True`` (dot, single device, inside the support-oracle
    envelope) replaces the clamped-flops out caps with the EXACT
    per-window output counts from the bit-packed support oracle — which
    also SHRINKS the packed launch list: flops-positive but
    output-empty windows become skips, so the kernel pays one MXU
    launch per genuinely occupied window
    (``spgemm.windowed.windows_packed`` / ``.pack_ratio``).

    ``ring=True`` (multi-device only) runs the stage-pipelined carousel
    schedule instead of the gathered one; ``pipeline=False`` pins the
    serial-chain control (see ``summa_spgemm_windowed``).
    """
    from ..tuner import config as tuner_config

    backend = resolve_spgemm_backend(backend)
    dispatch = tuner_config.resolve_dispatch(dispatch)
    bucket = tuner_config.bucket_caps_enabled()
    if block_rows is None:
        block_rows = default_block_rows(A.local_rows, B.local_cols)
    chunk_w = WINDOWED_CHUNK_W
    if backend == "dot":
        if block_cols is None:
            block_cols = default_block_cols(B.local_rows, B.local_cols)
        # chunk_w=1 (identity padding): the dot backend never consumes
        # the padded counts, so the symbolic pass runs its inner
        # gather+segment loop once instead of twice
        pair = host_value(
            summa_window_flops_pair(
                A, B, block_rows, block_cols, chunk_w=1
            )
        )
        pt = pair[1]
        flop_caps, out_caps, skip = windowed_plan_2d(
            None, pt, block_rows, block_cols,
            A.local_rows, B.local_cols, slack=slack,
        )
        if oracle:
            # the oracle densifies FULL bf16 supports (spgemm_support
            # _bits) — only admissible inside the mxu-tier size
            # envelope, on one device, with word-aligned windows
            if (
                A.grid.size == 1
                and block_cols % 32 == 0
                and max(A.local_rows, B.local_rows, B.local_cols)
                <= MXU_MAX_TILE_DIM
            ):
                out_caps, skip = _oracle_out_caps_2d(
                    sr, A, B, block_rows, block_cols, out_caps, skip
                )
            else:
                # requested but inapplicable: fall back to the
                # clamped-flops caps, observably (never silently)
                if obs.ENABLED:
                    obs.count("spgemm.windowed.oracle_skipped")
        if bucket:
            # pow2 caps AFTER oracle tightening: the bucket keeps the
            # compile-sharing property, the oracle keeps the skips;
            # then re-impose the dense-window bound the round may have
            # exceeded on tail blocks/windows (no slot can outnumber
            # the window's cells)
            flop_caps, out_caps = bucket_plan_caps(flop_caps, out_caps)
            out_caps = tuple(
                tuple(
                    min(
                        oc,
                        max(min(block_rows,
                                A.local_rows - g * block_rows), 1)
                        * max(min(block_cols,
                                  B.local_cols - h * block_cols), 1),
                    )
                    for h, oc in enumerate(row)
                )
                for g, row in enumerate(out_caps)
            )
        panel_cap = panel_cap_from_bnnz(
            host_value(summa_window_bnnz(B, block_cols)),
            int(B.capacity),
        )
        if obs.ENABLED:
            obs.count(
                "spgemm.windowed.dispatch",
                mode="local" if A.grid.size == 1 else "fused",
            )
            nsk = sum(sum(row) for row in skip)
            obs.count("spgemm.windowed.col_windows_skipped", nsk)
            npk = len(packed_windows_2d(skip))
            ntot = sum(len(row) for row in skip)
            obs.count("spgemm.windowed.windows_packed", npk)
            obs.gauge(
                "spgemm.windowed.pack_ratio",
                npk / ntot if ntot else 0.0,
            )
            obs.gauge(
                "spgemm.windowed.col_windows",
                len(skip[0]) if skip else 0,
            )
            obs.gauge(
                "spgemm.windowed.panel_cells",
                _pad128(B.local_rows) * _pad128(block_cols),
            )
            obs.gauge("spgemm.windowed.blocks", len(skip))
            # per-window symbolic mask density, averaged over the LIVE
            # windows (the 2D analog of spgemm.auto.mask_density)
            live_cells = live_bound = 0.0
            per_tile = np.asarray(pt).sum(axis=2).max(axis=(-1, -2))
            for g in range(len(skip)):
                rb = min(block_rows, A.local_rows - g * block_rows)
                for h in range(len(skip[g])):
                    if skip[g][h]:
                        continue
                    wc = min(
                        block_cols, B.local_cols - h * block_cols
                    )
                    live_cells += rb * wc
                    live_bound += min(float(per_tile[g, h]), rb * wc)
            obs.gauge(
                "spgemm.windowed.window_density",
                live_bound / live_cells if live_cells else 0.0,
            )
            obs.gauge(
                "spgemm.auto.mask_density",
                live_bound / max(A.local_rows * B.local_cols, 1),
            )
        if A.grid.size == 1:
            C, overflow = local_spgemm_windowed(
                sr, A, B, block_rows=block_rows, flop_caps=flop_caps,
                out_caps=out_caps, skip=skip, backend="dot",
                block_cols=block_cols, panel_cap=panel_cap, mode=mode,
                interpret=interpret,
            )
        else:
            C, overflow = summa_spgemm_windowed(
                sr, A, B, block_rows=block_rows, flop_caps=flop_caps,
                out_caps=out_caps, skip=skip, backend="dot", mode=mode,
                chunk_w=chunk_w, interpret=interpret,
                block_cols=block_cols, panel_cap=panel_cap,
                ring=ring, pipeline=pipeline,
            )
        over = int(overflow)
        assert over <= 0, (
            f"windowed tier overflowed its symbolic bound by {over}"
        )
        _record_realized_nnz(C)
        return C
    # one symbolic pass yields both the padded (expansion-capacity) and
    # true (output-bound) counts
    pair = host_value(
        summa_rowblock_flops_pair(A, B, block_rows, chunk_w=chunk_w)
    )
    pb, pt = pair[0], pair[1]
    flop_caps, out_caps, skip = windowed_plan(
        pb, pt, block_rows, A.local_rows, B.local_cols, slack=slack
    )
    if bucket:
        flop_caps, out_caps = bucket_plan_caps(flop_caps, out_caps)
        # dense-block bound re-imposed after the pow2 round (tail
        # blocks: rb * lcB may not be a power of two)
        out_caps = tuple(
            min(
                oc,
                max(min(block_rows, A.local_rows - g * block_rows), 1)
                * B.local_cols,
            )
            for g, oc in enumerate(out_caps)
        )
    # the building-block decomposition rule (round 10): any distributed
    # scatter product with >1 occupied block defaults to per-block
    # programs — the ring carousel is a fused-only schedule, so a ring
    # request keeps the fused graph even against dispatch="blocked"
    # (the more specific schedule ask wins; the conflict is counted)
    if ring and dispatch == "blocked":
        if obs.ENABLED:
            obs.count("spgemm.windowed.dispatch_conflict")
        dispatch = "fused"
    use_blocked = (
        A.grid.size > 1
        and backend == "scatter"
        and (
            dispatch == "blocked"
            or (
                dispatch == "auto"
                and not ring
                and len(packed_windows(skip)) > 1
            )
        )
    )
    if obs.ENABLED:
        obs.count(
            "spgemm.windowed.dispatch",
            mode=(
                "blocked" if use_blocked
                else "local" if A.grid.size == 1
                else "fused"
            ),
        )
    if obs.ENABLED:
        obs.count("spgemm.windowed.windows_skipped", sum(skip))
        npk = len(packed_windows(skip))
        obs.count("spgemm.windowed.windows_packed", npk)
        obs.gauge(
            "spgemm.windowed.pack_ratio",
            npk / len(skip) if skip else 0.0,
        )
        obs.gauge("spgemm.windowed.blocks", len(skip))
        cells = max(A.local_rows * B.local_cols, 1)
        obs.gauge(
            "spgemm.auto.mask_density",
            float(np.asarray(pt).sum(axis=1).max(axis=(-1, -2)).sum())
            / cells,
        )
    if A.grid.size == 1 and backend == "scatter":
        # single-device fast path: per-block programs (the fused
        # shard_map graph measures >2x slower on XLA:CPU — see
        # local_spgemm_windowed)
        C, overflow = local_spgemm_windowed(
            sr, A, B, block_rows=block_rows, flop_caps=flop_caps,
            out_caps=out_caps, skip=skip, chunk_w=chunk_w,
        )
    elif use_blocked:
        # distributed building-block dispatch: one small shard_map
        # program per occupied row block, bucketed caps shared — the
        # default that bounds first-touch compile AND the live set
        C, overflow = summa_spgemm_windowed_blocked(
            sr, A, B, block_rows=block_rows, flop_caps=flop_caps,
            out_caps=out_caps, skip=skip, chunk_w=chunk_w,
        )
    else:
        C, overflow = summa_spgemm_windowed(
            sr, A, B, block_rows=block_rows, flop_caps=flop_caps,
            out_caps=out_caps, skip=skip, backend=backend, mode=mode,
            chunk_w=chunk_w, interpret=interpret, ring=ring,
            pipeline=pipeline,
        )
    over = int(overflow)
    # out_caps are symbolic UPPER bounds — overflow means the symbolic
    # pass disagreed with the kernel (a bug), not an underestimate
    assert over <= 0, f"windowed tier overflowed its symbolic bound by {over}"
    _record_realized_nnz(C)
    return C


def coo_has_duplicates(M: SpParMat) -> bool:
    """True iff any tile holds a repeated (row, col) entry — the cheap
    nnz-vs-dedup check guarding the mxu tier's unique-entries
    precondition (``densify``'s unique_indices scatter).  One two-key
    sort per tile + one host readback; only spent where a densifying
    unique-indices tier is about to be chosen, and memoized on the
    matrix object so iterative callers (warm-plan serving, algorithm
    loops re-routing the same operand) pay the sort + D2H sync once
    — the readback is the expensive part on the target chip (bench.py
    axon D2H rule)."""
    from ..ops.spgemm import coo_sort_dedup

    cached = getattr(M, "_coo_has_duplicates", None)
    if cached is not None:
        return cached
    lr = M.local_rows

    def body(r, c):
        rows, cols = r[0, 0], c[0, 0]
        rs, _, dup = coo_sort_dedup(rows, cols)
        # padding slots (row == lr) are mutually equal — exclude them
        mine = jnp.sum((dup & (rs < lr)).astype(jnp.int32))
        return lax.psum(lax.psum(mine, ROW_AXIS), COL_AXIS)

    total = jax.shard_map(
        body,
        mesh=M.grid.mesh,
        in_specs=(TILE_SPEC,) * 2,
        out_specs=P(),
        check_vma=False,
    )(M.rows, M.cols)
    result = int(np.asarray(host_value(total))) > 0
    # frozen dataclass: bypass via object.__setattr__ (the attr is not
    # a pytree field, so transforms/copies simply drop it)
    object.__setattr__(M, "_coo_has_duplicates", result)
    return result


def choose_tier_from_counts(
    sr: Semiring,
    max_tile_dim: int,
    tile_cells: int,
    pr: int,
    flops_total: float,
    backend: str | None = None,
    k_dim: int | None = None,
    allow_mxu: bool = True,
    n_dim: int | None = None,
) -> str:
    """Pure tier gate over pre-computed counts — shared by the device
    router (``choose_spgemm_tier``) and host-sizing benchmark drivers
    (which must not touch the device to decide).  See
    ``choose_spgemm_tier`` for the rule.  ``k_dim`` is B's local row
    count and ``n_dim`` B's local col count (the dot backend's
    panel-feasibility check — ``dot_panel_feasible``; ``k_dim``
    defaults to ``max_tile_dim``); ``allow_mxu=False`` re-evaluates the
    ladder with the mxu rung removed (the duplicate-entry fallback)."""
    from ..ops.spgemm import scatter_combine_for

    backend = resolve_spgemm_backend(backend)
    if (
        allow_mxu
        and max_tile_dim <= MXU_MAX_TILE_DIM
        and sr.name in _PALLAS_KINDS
    ):
        return "mxu"
    dense_ok = (
        scatter_combine_for(sr) is not None
        and tile_cells <= WINDOWED_MAX_TILE_CELLS
        and tile_cells * pr * pr
        <= WINDOWED_MAX_CELLS_PER_FLOP * max(flops_total, 1.0)
    )
    if backend == "scatter" and dense_ok:
        return "windowed"
    if (
        backend == "dot"
        and dense_ok
        and sr.name in _PALLAS_KINDS
        and dot_panel_feasible(k_dim or max_tile_dim, n_dim)
    ):
        return "windowed"
    return "scan"


def choose_spgemm_tier(
    sr: Semiring,
    A: SpParMat,
    B: SpParMat,
    *,
    backend: str | None = None,
    assume_unique: bool = False,
    grid3=None,
) -> str:
    """The routing rule of ``spgemm_auto`` (host-side, observable):

      "mxu"       tiles fit the full-dense MXU envelope, the semiring
                  has a dense kernel, and the tiles hold UNIQUE entries
                  (checked via ``coo_has_duplicates`` unless
                  ``assume_unique`` — duplicate tiles would corrupt the
                  unique-indices densify, so they fall back to the
                  duplicate-absorbing windowed/scan rungs);
      "windowed"  the add monoid has a native scatter combiner, the
                  per-tile dense cell count is bounded, the output is
                  dense enough that one cell scan beats the ESC sort
                  (``WINDOWED_MAX_CELLS_PER_FLOP``), and the backend
                  can accumulate densely: ``scatter`` directly, or
                  ``dot`` (TPU) whenever a 512-wide B column panel fits
                  ``WINDOWED_MAX_PANEL_CELLS`` — the 2D windows bound
                  the stage operand, so TPU mid-scale products now
                  route here instead of falling through to scan;
      "scan"      everything else — output-bounded ESC (the general
                  fallback; exact for every semiring).

    With a LAYERED mesh available (``grid3`` with ``layers > 1`` whose
    layout fits the product — ``mesh3d.summa3d_compatible``), a product
    the 2D rule routes to ``windowed`` upgrades to ``"windowed3d"``:
    the same windowed kernel run per layer on the 3D mesh
    (``spgemm3d_windowed``), where layer replication cuts per-stage
    gather volume L-fold.  Products the 2D rule sends to mxu or scan
    keep their 2D tier (small tiles don't pay conversion; scan-sparse
    outputs would multiply the extraction scans by L).

    Forced override: ``spgemm_auto(tier=...)`` or env
    ``COMBBLAS_SPGEMM_TIER``; backend via argument, env
    ``COMBBLAS_SPGEMM_BACKEND``, or the platform default.
    """
    tier = _choose_spgemm_tier_2d(
        sr, A, B, backend=backend, assume_unique=assume_unique
    )
    if grid3 is not None and tier == "windowed":
        from .mesh3d import summa3d_compatible

        if grid3.layers > 1 and summa3d_compatible(
            grid3, A.nrows, A.ncols, B.ncols
        ):
            return "windowed3d"
    return tier


def _choose_spgemm_tier_2d(
    sr: Semiring,
    A: SpParMat,
    B: SpParMat,
    *,
    backend: str | None = None,
    assume_unique: bool = False,
) -> str:
    """The 2D rungs of ``choose_spgemm_tier`` (see its docstring)."""
    from ..ops.spgemm import scatter_combine_for

    backend = resolve_spgemm_backend(backend)
    max_dim = max(A.local_rows, A.local_cols, B.local_cols)
    cells = A.local_rows * B.local_cols
    if max_dim <= MXU_MAX_TILE_DIM and sr.name in _PALLAS_KINDS:
        # no symbolic pass needed for this gate — but the unique-entry
        # precondition of the densifying mxu tier must hold, else fall
        # back to a duplicate-absorbing rung (ISSUE 5 guard)
        if assume_unique or not (
            coo_has_duplicates(A)
            or (B is not A and coo_has_duplicates(B))
        ):
            return "mxu"
        if obs.ENABLED:
            obs.count("spgemm.auto.dedup_fallback", sr=sr.name)
        flops_total = float(
            np.asarray(host_value(summa_stage_flops(A, B, padded=False)))
            .astype(np.float64).sum()
        )
        return choose_tier_from_counts(
            sr, max_dim, cells, A.grid.pr, flops_total, backend,
            k_dim=B.local_rows, allow_mxu=False, n_dim=B.local_cols,
        )
    # evaluate every STATIC windowed precondition before paying the
    # symbolic pass: the device pass ends in a host readback, which on
    # the target chip permanently degrades later launches (bench.py
    # module docstring) — never spend it when windowed is structurally
    # ineligible (generic monoids, oversized tiles, infeasible panels)
    if (
        scatter_combine_for(sr) is None
        or cells > WINDOWED_MAX_TILE_CELLS
        or (
            backend == "dot"
            and (
                sr.name not in _PALLAS_KINDS
                or not dot_panel_feasible(B.local_rows, B.local_cols)
            )
        )
    ):
        return "scan"
    flops_total = float(
        np.asarray(host_value(summa_stage_flops(A, B, padded=False)))
        .astype(np.float64).sum()
    )
    return choose_tier_from_counts(
        sr,
        max_dim,
        cells,
        A.grid.pr,
        flops_total,
        backend,
        k_dim=B.local_rows,
        n_dim=B.local_cols,
    )


def spgemm_auto(
    sr: Semiring,
    A: SpParMat,
    B: SpParMat,
    *,
    out_capacity: int | None = None,
    slack: float = 1.1,
    max_retries: int = 3,
    mode: str = "f32",
    interpret: bool = False,
    tier: str | None = None,
    block_rows: int | None = None,
    block_cols: int | None = None,
    backend: str | None = None,
    oracle: bool = False,
    assume_unique: bool = False,
    grid3=None,
    ring: bool | None = None,
    pipeline: bool | None = None,
    dispatch: str | None = None,
    merge: str | None = None,
) -> SpParMat:
    """Auto-tiered sparse-output SpGEMM: route (shape, density, semiring)
    through the fastest applicable kernel instead of defaulting to ESC.

    ``ring``/``pipeline`` are tri-state here (None = "let the resolved
    plan decide"): an EXPLICIT True/False always beats a remembered
    record's schedule flags — the arg > store precedence holds for
    every knob, not just the tier.  ``merge`` (sort | runs | hash) is
    the combine-merge tier of the merge-consuming tiers (the esc
    stage-chunk combine, the windowed3d fiber reduce), resolved the
    same way: arg > record > env ``COMBBLAS_SPGEMM_MERGE`` >
    per-entry heuristic.

    The ladder (see docs/spgemm.md and ``choose_spgemm_tier``):

      "mxu"      full-dense MXU stage products + one windowed extraction
                 (small tiles, dense-kernel semirings);
      "windowed" dense WINDOW accumulators (scatter row blocks, or MXU
                 row-block × col-window 2D stage products) +
                 symbolically-sized windowed extraction with empty
                 windows skipped — the general mid-scale tier that
                 removes the ESC sort, on every backend;
      "scan"/"esc"  output-bounded / classic ESC (general fallback).

    Routing resolution (the precedence documented in
    ``tuner/config.py``): explicit ``tier`` argument > **plan store**
    (a measured plan remembered for this (shape bucket, density band,
    semiring, backend, grid) — ``combblas_tpu.tuner.store``, disabled
    via ``COMBBLAS_PLAN_STORE=0``) > env ``COMBBLAS_SPGEMM_TIER`` >
    the micro-probe pass (opt-in ``COMBBLAS_TUNER_PROBE=1``: measures
    the admissible rungs on a bounded proxy and persists the winner) >
    ``choose_spgemm_tier``'s heuristic ladder.  The winning source is
    the labeled ``spgemm.auto.plan_source`` counter.

    ``backend`` (or env ``COMBBLAS_SPGEMM_BACKEND``) forces the
    windowed accumulate backend; ``block_rows``/``block_cols`` (or envs
    ``COMBBLAS_SPGEMM_BLOCK_ROWS`` / ``COMBBLAS_SPGEMM_BLOCK_COLS``)
    override the window geometry; ``dispatch`` threads through to the
    windowed tier's program decomposition (see ``spgemm_windowed``).
    The chosen tier is recorded as the
    labeled ``spgemm.auto.tier`` counter, with
    ``spgemm.windowed.windows_skipped`` /
    ``spgemm.windowed.col_windows_skipped`` /
    ``spgemm.windowed.window_density`` / ``spgemm.auto.mask_density``
    exposing the skip lists and symbolic output density.

    ``mode`` sets the dense plus_times precision (see ``_mxu_dot``):
    "f32" (exact, slow MXU path), "bf16" (13.3 TFLOP/s — exact for
    bf16-representable values like 0/1 adjacency with counts < 2^24),
    "bf16x3" (split-float, f32-grade error, ~4x faster than f32).
    ``oracle=True`` lets the dot-backend windowed tier tighten its
    per-window extraction caps with the bit-packed support oracle.

    PRECONDITION (mxu tier only): input tiles must hold UNIQUE
    (row, col) entries — ``densify``'s scatter declares
    ``unique_indices`` and duplicate slots would combine
    unpredictably.  The router guards this (``coo_has_duplicates``
    check + fallback; skip it with ``assume_unique=True`` on compacted
    inputs).  Every other rung — INCLUDING the windowed tier's ``dot``
    backend, which densifies with the combining scatter
    (``densify_combine``) — absorbs duplicate COO entries exactly.
    """
    from ..tuner import config as tuner_config
    from ..tuner import store as tuner_store

    plan_source = "arg" if tier is not None else None
    merge_source = "arg" if merge is not None else None
    store = key = rec = None
    if tier is None:
        # resolution precedence (documented once in tuner/config.py):
        #   arg > plan store > env > probe-on-miss > heuristic
        store = tuner_store.get_store()
        # the key costs one memoized host-nnz readback per operand —
        # never pay it when the store has nothing to offer AND no probe
        # would persist a plan under it (the axon D2H rule)
        if store is not None and (
            store.entries() > 0 or tuner_config.probe_enabled()
        ):
            key = tuner_store.spgemm_plan_key(
                sr, A, B, resolve_spgemm_backend(backend), grid3=grid3
            )
            rec = store.lookup(key)
        # vet the remembered plan before trusting it — a rejected
        # record degrades down the precedence chain (obs: the raw
        # ``tuner.store.hits`` already counted the key match, so the
        # discard is made visible as ``tuner.store.rejected``)
        if rec is not None and rec.tier not in (
            "mxu", "windowed", "scan", "esc", "windowed3d"
        ):
            # e.g. a serve-lane record under a hand-mangled spgemm key
            if obs.ENABLED:
                obs.count("tuner.store.rejected", reason="tier")
            rec = None
        if rec is not None and rec.tier == "windowed3d" and grid3 is None:
            # a 3D plan is unusable without a layered mesh
            if obs.ENABLED:
                obs.count("tuner.store.rejected", reason="no_grid3")
            rec = None
        if rec is not None and rec.tier == "mxu" and not assume_unique:
            # a remembered plan never bypasses the mxu unique-entries
            # precondition: the record was measured on SOME input in
            # this bucket, not necessarily a duplicate-free one
            if coo_has_duplicates(A) or (
                B is not A and coo_has_duplicates(B)
            ):
                if obs.ENABLED:
                    obs.count("spgemm.auto.dedup_fallback", sr=sr.name)
                    obs.count("tuner.store.rejected", reason="dup")
                rec = None
        if rec is not None:
            tier = rec.tier
            plan_source = "store"
            if block_rows is None:
                block_rows = rec.block_rows
            if block_cols is None:
                block_cols = rec.block_cols
            if dispatch is None:
                dispatch = rec.dispatch
            # explicit args beat the record (tri-state: None = defer)
            if ring is None:
                ring = rec.ring
            if pipeline is None:
                pipeline = rec.pipeline
            if merge is None and rec.merge is not None:
                # provenance stays honest downstream: spgemm() /
                # spgemm3d_windowed label the counter with THIS source
                merge = rec.merge
                merge_source = "store"
    # env geometry fills in AFTER the store record (precedence: a
    # measured plan's block shape beats a fleet-wide env default)
    if block_rows is None:
        block_rows = tuner_config.env_block_rows()
    if block_cols is None:
        block_cols = tuner_config.env_block_cols()
    if tier is None:
        tier = tuner_config.env_tier()
        if tier is not None:
            plan_source = "env"
    if (
        tier is None
        and store is not None
        and grid3 is None  # probing covers the 2D ladder
        and tuner_config.probe_enabled()
    ):
        from ..tuner.probe import probe_spgemm

        rec = probe_spgemm(
            sr, A, B, backend=resolve_spgemm_backend(backend),
            store=store, key=key,
        )
        if rec is not None:
            tier = rec.tier
            plan_source = "probe"
    if tier is None:
        tier = choose_spgemm_tier(
            sr, A, B, backend=backend, assume_unique=assume_unique,
            grid3=grid3,
        )
        plan_source = "heuristic"
    # tri-state schedule flags -> concrete (the kernel defaults)
    ring = False if ring is None else bool(ring)
    pipeline = True if pipeline is None else bool(pipeline)
    assert tier in ("mxu", "windowed", "scan", "esc", "windowed3d"), tier
    if obs.ENABLED:
        obs.count("spgemm.auto.tier", tier=tier, sr=sr.name)
        obs.count(
            "spgemm.auto.plan_source", source=plan_source, tier=tier,
            op="spgemm",
        )
    with obs.span("spgemm.auto", sr=sr.name, tier=tier):
        if tier == "esc":
            return spgemm(sr, A, B, slack, merge=merge,
                          merge_source=merge_source)
        if tier == "scan":
            return spgemm_scan(
                sr, A, B, out_capacity=out_capacity, slack=slack,
                max_retries=max_retries,
            )
        if tier == "windowed":
            return spgemm_windowed(
                sr, A, B, block_rows=block_rows, block_cols=block_cols,
                backend=backend, mode=mode, slack=slack,
                interpret=interpret, oracle=oracle, ring=ring,
                pipeline=pipeline, dispatch=dispatch,
            )
        if tier == "windowed3d":
            # the layered route: 2D operands → 3D splits (on-device
            # redistribution), per-layer windowed SUMMA, fiber reduce,
            # back to the caller's 2D grid — one call, same contract
            assert grid3 is not None, (
                "tier='windowed3d' needs a grid3 (the layered mesh)"
            )
            from .mesh3d import SpParMat3D, spgemm3d_windowed

            A3 = SpParMat3D.from_spmat(A, grid3, split="col")
            B3 = SpParMat3D.from_spmat(B, grid3, split="row")
            # ring/pipeline now reach the per-layer 3D SUMMA too (the
            # round-13 carousel); oracle seeding stays 2D-plan-only
            C3 = spgemm3d_windowed(
                sr, A3, B3, block_rows=block_rows,
                block_cols=block_cols, backend=backend, mode=mode,
                slack=slack, interpret=interpret, merge=merge,
                ring=ring, pipeline=pipeline,
                merge_source=merge_source,
            )
            return C3.to_spmat(A.grid)
        # tier == "mxu": the round-4 whole-tile dense path
        if out_capacity is None:
            out_capacity = max(A.capacity, B.capacity, 64)
        out_capacity = 1 << (int(out_capacity) - 1).bit_length()
        over = 0
        for attempt in range(max_retries + 1):
            C, overflow = summa_spgemm_mxu(
                sr, A, B, out_capacity=out_capacity, mode=mode,
                interpret=interpret,
            )
            over = int(overflow)
            if over <= 0:
                if obs.ENABLED:
                    obs.count("spgemm.mxu.overflow_retries", attempt)
                    _record_realized_nnz(C)
                return C
            out_capacity = 1 << (out_capacity + over - 1).bit_length()
        raise ValueError(
            f"spgemm_auto still overflowing by {over} after {max_retries} "
            "retries; pass an explicit out_capacity"
        )
