"""Distributed SpGEMM: SUMMA over the device mesh (≈ ParFriends Mult_AnXBn_*).

The reference's baseline ``Mult_AnXBn_Synch`` (``ParFriends.h:1005-1108``)
runs √p stages; each stage broadcasts one A-block along the process row and
one B-block along the process column (``SpParHelper::BCastMatrix``), does a
local hash SpGEMM, and finally k-way-merges the √p stage outputs
(``MultiwayMerge.h:412``).

TPU-native schedule: the per-stage broadcasts collapse into ONE ``all_gather``
of the A-tiles over the ``"c"`` axis and of the B-tiles over the ``"r"`` axis
(same total bytes as the √p broadcasts, but a single fused ICI collective
that XLA can software-pipeline), then a static python loop over stages feeds
the local ESC kernel, and the merge is a single concat + sort + segmented
fold — the MultiwayMerge heap becomes the TPU's native sort.  The
double-buffered / overlapped variants (``ParFriends.h:799,1111``) are
subsumed: XLA overlaps the gather with the first stages automatically.

A ring variant (lower peak memory, ≈ SUMMA with in-place rotation à la
``BFSFriends``' carousel) swaps the all_gather for per-stage ``ppermute``;
see ``ring=True``.

Capacity model (the static-shape analog of ``EstimateFLOP`` /
``EstPerProcessNnzSUMMA``, ``ParFriends.h:356-448,1243-1349``): callers pass
``flop_capacity`` (per stage, per tile) and ``out_capacity`` (final tile
nnz), or use ``summa_capacities`` to measure them exactly with a cheap
distributed symbolic pass before jitting the numeric one.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops.compressed import CSR
from ..ops.spgemm import expand as esc_expand
from ..ops.tuples import SpTuples
from ..semiring import Semiring
from .grid import COL_AXIS, ROW_AXIS
from .spmat import TILE_SPEC, SpParMat


def _check_compat(A: SpParMat, B: SpParMat):
    """≈ CheckSpGEMMCompliance + ProductGrid (ParFriends.h:161,
    CommGrid.cpp:164)."""
    assert A.grid == B.grid, "A and B must share a grid"
    assert A.grid.is_square, "SUMMA requires a square grid (pr == pc)"
    assert A.ncols == B.nrows, f"dim mismatch {A.ncols} != {B.nrows}"
    assert A.grid.local_cols(A.ncols) == A.grid.local_rows(B.nrows), (
        "A col-blocking must equal B row-blocking"
    )


def _gather_stage_tiles(t: SpTuples, axis_name, p: int) -> list[SpTuples]:
    """All-gather a tile's arrays over a mesh axis → one SpTuples per stage.

    The fused-collective replacement for the reference's per-stage
    ``SpParHelper::BCastMatrix`` loop.
    """
    g = [lax.all_gather(x, axis_name) for x in (t.rows, t.cols, t.vals, t.nnz)]
    return [
        SpTuples(
            rows=g[0][s], cols=g[1][s], vals=g[2][s], nnz=g[3][s],
            nrows=t.nrows, ncols=t.ncols,
        )
        for s in range(p)
    ]


@partial(
    jax.jit,
    static_argnames=("sr", "flop_capacity", "out_capacity", "ring"),
)
def summa_spgemm(
    sr: Semiring,
    A: SpParMat,
    B: SpParMat,
    *,
    flop_capacity: int,
    out_capacity: int,
    ring: bool = False,
) -> SpParMat:
    """C = A ⊗ B over the grid.

    ``flop_capacity`` bounds ONE stage's expansion on one tile;
    ``out_capacity`` bounds the final per-tile nnz.
    """
    _check_compat(A, B)
    grid = A.grid
    p = grid.pr

    def body(ar, ac, av, an, br, bc, bv, bn):
        # stitch local tiles
        a_mine = A.local_tile(ar, ac, av, an)
        b_mine = B.local_tile(br, bc, bv, bn)

        def stage_output(a_stage: SpTuples, b_stage: SpTuples) -> SpTuples:
            b_csr = CSR.from_tuples(b_stage)
            return esc_expand(sr, a_stage, b_csr, flop_capacity)

        chunks = []
        if not ring:
            # A-tiles of my grid row / B-tiles of my grid column.
            a_stages = _gather_stage_tiles(a_mine, COL_AXIS, p)
            b_stages = _gather_stage_tiles(b_mine, ROW_AXIS, p)
            for s in range(p):
                chunks.append(stage_output(a_stages[s], b_stages[s]))
        else:
            # Cannon's algorithm: O(capacity) peak memory instead of
            # O(p·capacity). Pre-skew with one joint-axis ppermute so device
            # (i,j) starts with A_{i,(i+j)%p} and B_{(i+j)%p,j} — at stage s
            # both held tiles share the contraction index k=(i+j+s)%p — then
            # rotate A left / B up one step per stage (neighbor-only ICI
            # traffic, the ring schedule of the reference's carousel,
            # BitMapCarousel.h).
            def joint_permute(t: SpTuples, perm) -> SpTuples:
                return SpTuples(
                    rows=lax.ppermute(t.rows, (ROW_AXIS, COL_AXIS), perm),
                    cols=lax.ppermute(t.cols, (ROW_AXIS, COL_AXIS), perm),
                    vals=lax.ppermute(t.vals, (ROW_AXIS, COL_AXIS), perm),
                    nnz=lax.ppermute(t.nnz, (ROW_AXIS, COL_AXIS), perm),
                    nrows=t.nrows, ncols=t.ncols,
                )

            skew_a = [
                (i * p + (i + j) % p, i * p + j)
                for i in range(p) for j in range(p)
            ]
            skew_b = [
                (((i + j) % p) * p + j, i * p + j)
                for i in range(p) for j in range(p)
            ]
            rot_a = [
                (i * p + (j + 1) % p, i * p + j)
                for i in range(p) for j in range(p)
            ]
            rot_b = [
                (((i + 1) % p) * p + j, i * p + j)
                for i in range(p) for j in range(p)
            ]
            a_cur = joint_permute(a_mine, skew_a)
            b_cur = joint_permute(b_mine, skew_b)
            for s in range(p):
                chunks.append(stage_output(a_cur, b_cur))
                if s != p - 1:
                    a_cur = joint_permute(a_cur, rot_a)
                    b_cur = joint_permute(b_cur, rot_b)

        merged = SpTuples.concat(chunks)
        out = merged.compact(sr, capacity=out_capacity)
        return SpParMat._pack_tile(out)

    r, c, v, n = jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(TILE_SPEC,) * 8,
        out_specs=(TILE_SPEC,) * 4,
        check_vma=False,
    )(A.rows, A.cols, A.vals, A.nnz, B.rows, B.cols, B.vals, B.nnz)
    return SpParMat(
        rows=r, cols=c, vals=v, nnz=n,
        nrows=A.nrows, ncols=B.ncols, grid=grid,
    )


@jax.jit
def summa_stage_flops(A: SpParMat, B: SpParMat) -> jax.Array:
    """[p, pr, pc] float32 flop count per stage per output tile.

    The distributed symbolic pass (≈ EstimateFLOP, ParFriends.h:356-448).
    Values only (no ``vals`` arrays) cross the ICI: flop counting needs A's
    (rows, cols) for validity/contraction ids and B's rows for row lengths.
    """
    _check_compat(A, B)
    grid = A.grid
    p = grid.pr
    lrB = B.local_rows

    def body(ar, ac, br):
        a_rows, a_cols = ar[0, 0], ac[0, 0]
        b_rows = br[0, 0]
        ag_rows = lax.all_gather(a_rows, COL_AXIS)
        ag_cols = lax.all_gather(a_cols, COL_AXIS)
        bg_rows = lax.all_gather(b_rows, ROW_AXIS)
        per_stage = []
        for s in range(p):
            b_valid = bg_rows[s] < lrB
            blens = jax.ops.segment_sum(
                b_valid.astype(jnp.int32), bg_rows[s], num_segments=lrB + 1
            )
            a_valid = ag_rows[s] < A.local_rows
            k = jnp.minimum(ag_cols[s], lrB)
            per_entry = jnp.where(a_valid, blens[k], 0)
            per_stage.append(jnp.sum(per_entry.astype(jnp.float32)))
        return jnp.stack(per_stage)[:, None, None]

    return jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(TILE_SPEC,) * 3,
        out_specs=P(None, ROW_AXIS, COL_AXIS),
        check_vma=False,
    )(A.rows, A.cols, B.rows)


def _caps_from_stage_flops(per_stage: np.ndarray, dense_tile: int,
                           slack: float):
    flop_cap = max(int(per_stage.max() * slack) + 1, 1)
    total_per_tile = per_stage.sum(axis=0).max()
    out_cap = max(min(int(total_per_tile * slack) + 1, dense_tile), 1)
    return flop_cap, out_cap


def summa_capacities(A: SpParMat, B: SpParMat, slack: float = 1.05):
    """Host helper: symbolic pass → (flop_capacity, out_capacity).

    flop_capacity = max single-stage single-tile expansion; out_capacity =
    max per-tile total flops (a product has at most one output per flop),
    clamped to the dense tile size. ``slack`` covers the float32 rounding of
    the counts plus headroom for reusing compiled code across inputs.

    NOTE: reads the device symbolic pass back to host — on the axon chip
    use ``summa_capacities_host`` from the host COO *before* any device
    work (D2H poison, see bench.py).
    """
    per_stage = np.asarray(summa_stage_flops(A, B), dtype=np.float64)
    return _caps_from_stage_flops(
        per_stage, A.local_rows * B.local_cols, slack
    )


def summa_stage_flops_host(
    grid, rows_a, cols_a, rows_b, cols_b,
    nrows_a: int, ncols_a: int, ncols_b: int,
) -> np.ndarray:
    """Host-numpy twin of ``summa_stage_flops``: [p, pr, pc] flop counts
    computed from global COO arrays, with zero device interaction.

    For benchmarking on hardware where any D2H readback degrades later
    launches, the symbolic sizing must happen before upload; this computes
    the identical per-stage per-tile counts from the same owner math.
    """
    pr_, pc_ = grid.pr, grid.pc
    assert pr_ == pc_, "SUMMA requires a square grid"
    p = pr_
    lrA = grid.local_rows(nrows_a)
    lcA = grid.local_cols(ncols_a)
    lrB = grid.local_rows(ncols_a)
    lcB = grid.local_cols(ncols_b)
    assert lcA == lrB, "A col-blocking must equal B row-blocking"
    rows_a = np.asarray(rows_a, np.int64)
    cols_a = np.asarray(cols_a, np.int64)
    rows_b = np.asarray(rows_b, np.int64)
    cols_b = np.asarray(cols_b, np.int64)
    # countA[i, s, k] = nnz of A-tile (i,s) in local column k
    ia, sa, ka = rows_a // lrA, cols_a // lcA, cols_a % lcA
    countA = np.bincount(
        (ia * p + sa) * lcA + ka, minlength=p * p * lcA
    ).reshape(p, p, lcA)
    # countB[s, j, k] = nnz of B-tile (s,j) in local row k
    sb, jb, kb = rows_b // lrB, cols_b // lcB, rows_b % lrB
    countB = np.bincount(
        (sb * p + jb) * lrB + kb, minlength=p * p * lrB
    ).reshape(p, p, lrB)
    # flops[s, i, j] = sum_k countA[i,s,k] * countB[s,j,k]
    return np.einsum(
        "isk,sjk->sij", countA.astype(np.float64), countB.astype(np.float64)
    )


def summa_capacities_host(
    grid, rows_a, cols_a, rows_b, cols_b,
    nrows_a: int, ncols_a: int, ncols_b: int, slack: float = 1.05,
    per_stage: np.ndarray | None = None,
):
    """Host-only twin of ``summa_capacities`` (flop_capacity, out_capacity)
    from global COO arrays — the public entry for D2H-sensitive callers
    (benchmarks on the axon chip size capacities before any upload).

    Pass a precomputed ``per_stage`` (from ``summa_stage_flops_host``) to
    avoid recomputing the O(nnz) symbolic pass."""
    if per_stage is None:
        per_stage = summa_stage_flops_host(
            grid, rows_a, cols_a, rows_b, cols_b, nrows_a, ncols_a, ncols_b
        )
    dense_tile = grid.local_rows(nrows_a) * grid.local_cols(ncols_b)
    return _caps_from_stage_flops(per_stage, dense_tile, slack)


def mem_efficient_spgemm(
    sr: Semiring,
    A: SpParMat,
    B: SpParMat,
    phases: int,
    *,
    slack: float = 1.05,
    prune_fn=None,
) -> SpParMat:
    """Phased SUMMA: C = A ⊗ B computed over column chunks of B.

    Reference: ``MemEfficientSpGEMM`` (ParFriends.h:450-731) — B is
    ``ColSplit`` into ``phases`` local column chunks; each phase runs a full
    SUMMA plus an optional ``prune_fn`` hook (MCL's prune/recover/select,
    ParFriends.h:186-350), and phase outputs concatenate back. Peak expansion
    memory drops ~``phases``-fold at the cost of re-gathering A every phase.
    The reference auto-computes ``phases`` from a memory budget via
    ``EstPerProcessNnzSUMMA``; here the symbolic pass inside ``spgemm`` sizes
    each phase exactly, so callers choose ``phases`` directly.
    """
    lc = B.local_cols
    splittable = B.ncols == lc * B.grid.pc and lc % max(phases, 1) == 0
    if phases > 1 and not splittable:
        import warnings

        warnings.warn(
            f"mem_efficient_spgemm: ncols={B.ncols} not splittable into "
            f"{phases} phases on a {B.grid.pr}x{B.grid.pc} grid "
            "(needs ncols % (pc * phases) == 0); running unphased",
            stacklevel=2,
        )
        phases = 1
    if phases <= 1:
        C = spgemm(sr, A, B, slack)
        return prune_fn(C) if prune_fn is not None else C
    outs = []
    for Bs in B.col_split(phases):
        # A phase holds ~1/phases of the nnz but inherits B's full slot
        # capacity from col_split; truncate so the per-phase SUMMA gathers
        # phase-sized arrays (the point of phasing is peak-memory reduction).
        C = spgemm(sr, A, Bs.shrink_to_fit(), slack)
        if prune_fn is not None:
            C = prune_fn(C)
        outs.append(C)
    return SpParMat.col_concatenate(outs)


def block_spgemm(
    sr: Semiring,
    A: SpParMat,
    B: SpParMat,
    row_blocks: int = 1,
    col_blocks: int = 1,
    slack: float = 1.05,
):
    """Generator over output blocks: yields ((i, j), C_ij) where
    C_ij = A[rowblock_i, :] ⊗ B[:, colblock_j].

    Reference: ``BlockSpGEMM`` (BlockSpGEMM.h:16-137) — iterate SUMMA over
    logical output blocks so no more than one block's expansion is live at
    a time (out-of-core-style memory bounding; the driver streams blocks to
    the caller, e.g. for writeout). Splits are LOCAL like col_split;
    ``SpParMat.col_concatenate`` / stacking reassembles if needed.
    """
    a_rows = A.row_split(row_blocks) if row_blocks > 1 else [A]
    b_cols = B.col_split(col_blocks) if col_blocks > 1 else [B]
    b_cols = [b.shrink_to_fit() for b in b_cols]  # once, not per row block
    for i, Ai in enumerate(a_rows):
        Ai = Ai.shrink_to_fit()
        for j, Bj in enumerate(b_cols):
            yield (i, j), spgemm(sr, Ai, Bj, slack)


def estimate_flops(A: SpParMat, B: SpParMat) -> int:
    """Total semiring multiplications of A ⊗ B.

    Reference: ``EstimateFLOP`` (ParFriends.h:356-448) — here the exact
    distributed symbolic pass summed over stages and tiles.
    """
    import numpy as np

    return int(np.asarray(summa_stage_flops(A, B), np.float64).sum())


def calculate_phases(
    A: SpParMat, B: SpParMat, per_device_memory_bytes: int,
    slack: float = 1.05,
) -> int:
    """Phase count for ``mem_efficient_spgemm`` from a memory budget.

    Reference: ``CalculateNumberOfPhases`` (ParFriends.h:733-797) — there
    from ``perProcessMemory`` GB and the SUMMA nnz estimate; here from the
    peak per-device expansion of the unphased product (stage flops × slot
    bytes) against the caller's budget, rounded to a divisor-friendly
    power of two.
    """
    per_stage = np.asarray(summa_stage_flops(A, B), np.float64)
    slot_bytes = 4 + 4 + np.dtype(A.dtype).itemsize  # row + col + value
    # Peak per-device expansion follows the ALLOCATED shapes, not the valid
    # entries: summa_spgemm pads every one of the p coexisting stage chunks
    # to flop_capacity = max stage flops (static shapes), so the worst-case
    # skew allocates p x the single-stage max.
    p = A.grid.pr
    peak = per_stage.max() * p * slot_bytes * slack
    phases = max(1, int(np.ceil(peak / max(per_device_memory_bytes, 1))))
    phases = 1 << (phases - 1).bit_length()
    # Clamp to a divisor of B's local column count — a non-divisor would
    # make mem_efficient_spgemm fall back to unphased, defeating the budget.
    lc = B.local_cols
    phases = min(phases, max(lc, 1))
    while phases > 1 and lc % phases:
        phases >>= 1
    return phases


def estimate_nnz_upper(A: SpParMat, B: SpParMat) -> int:
    """Upper bound on nnz(C): per-tile flops clamped by the dense tile.

    The role of ``EstPerProcessNnzSUMMA``'s estimate (ParFriends.h:1243);
    exact nnz would need the hash symbolic pass — for capacity sizing the
    clamped-flops bound is what ``summa_capacities`` already uses.
    """
    import numpy as np

    per_stage = np.asarray(summa_stage_flops(A, B), np.float64)
    per_tile = per_stage.sum(axis=0)
    dense_tile = A.local_rows * B.local_cols
    return int(np.minimum(per_tile, dense_tile).sum())


def spgemm(
    sr: Semiring,
    A: SpParMat,
    B: SpParMat,
    slack: float = 1.05,
    *,
    pow2_caps: bool = True,
) -> SpParMat:
    """Convenience: symbolic pass → sized numeric SUMMA (unjitted entry).

    ≈ the user-facing ``Mult_AnXBn_Synch`` call; inside jit loops use
    ``summa_spgemm`` with pre-chosen capacities instead.

    ``pow2_caps`` rounds both capacities up to powers of two (≤2× memory
    slack) so iterative callers (MCL's expand loop, BC's per-level products)
    hit the XLA compilation cache instead of recompiling for every new nnz.
    """
    flop_cap, out_cap = summa_capacities(A, B, slack)
    if pow2_caps:
        dense_tile = A.local_rows * B.local_cols
        flop_cap = 1 << (flop_cap - 1).bit_length()
        out_cap = min(1 << (out_cap - 1).bit_length(), max(dense_tile, 1))
    return summa_spgemm(
        sr, A, B, flop_capacity=flop_cap, out_capacity=out_cap
    )
