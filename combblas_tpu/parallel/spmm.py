"""SpMM — sparse matrix × dense feature block, the MXU-resident lane.

Every serving kind before round 12 was VECTOR-valued (BFS / SSSP /
PageRank / BC lanes over [n, W] frontier matrices); the one shape the
MXU is actually built for — a sparse adjacency times a dense feature
panel — had no first-class kernel.  This module is that kernel family,
the graph-ML workload lane (k-hop feature propagation, embedding
smoothing) the ROADMAP names:

* ``_ell_local_spmm`` — per degree-class bucket, gather the neighbor
  FEATURE ROWS (``[nb, kb, F]`` — one gathered index fetches F lanes,
  the same per-index-bound amortization the batched BFS kernels ride)
  and contract the k axis.  Backend ``"mxu_gather"`` (plus_times only)
  contracts with a batched ``dot_general`` — a [1, kb] × [kb, F] matmul
  per bucket row, MXU-eligible; backend ``"scatter"`` is the
  VPU fold + row scatter of ``_ell_local_spmv_multi``, exact for every
  semiring (min_plus, max_min, ... ride ``_bucket_fold`` +
  ``_scatter_rows``'s duplicate-safe combine).

* ``dist_spmm_ell`` — the distributed entry over the EllParMat
  schedule: the feature panel replicates down grid columns, each tile
  folds locally, results reduce over the "c" axis.  O(lc·F) panel
  memory per device; the right shape when F is modest (serve lanes).

* ``summa_spmm`` — SUMMA over SpParMat tiles × a ``DenseParMat``
  feature panel (F split over grid columns like B's columns in
  SpGEMM).  ``ring=True`` reuses the round-9 carousel machinery
  (``_carousel_perms`` / ``_rotate_tiles``, two-slot operand buffers):
  the dense panel ROTATES one neighbor per stage while the current
  stage contracts, and with ``pipeline=True`` stage ``s+1``'s
  ``ppermute`` is issued before stage ``s``'s accumulate — O(2·panel)
  peak memory instead of the gathered schedule's O(p·panel).

* ``spmm_khop`` — fused k-hop propagation: hops chain DEVICE-RESIDENT
  (no host round-trip between hops), optional per-hop row
  normalization (``Y ← D⁻¹(A·Y)`` — value-identical to multiplying by
  the row-normalized twin the PageRank lane builds, derived here from
  the row degrees instead of materializing a second matrix).

Backend routing rides the round-10 tuner: ``dist_spmm`` resolves
``arg > plan store (op="spmm", feature-width bucket in the key) >
env COMBBLAS_SPMM_BACKEND > probe > heuristic`` through
``tuner.resolve.resolve_tier`` — see ``resolve_spmm_backend``.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import obs
from ..semiring import PLUS_TIMES, Semiring
from .collectives import axis_reduce
from .grid import COL_AXIS, ROW_AXIS
from .dense import DenseParMat
from .ellmat import EllParMat, _ell_local_spmm
from .spmat import SpParMat, TILE_SPEC
from .vec import DistMultiVec, DistVec

Array = jax.Array

#: The SpMM backend ladder (also the tuner's op="spmm" tier names).
SPMM_BACKENDS = ("mxu_gather", "scatter")


def pad_feature_width(f: int) -> int:
    """Pow2-padded feature width: SpMM programs compile per (shape,
    F) signature, so bucketing F to powers of two bounds the compiled
    program count exactly like the serve batcher's lane buckets bound
    the (kind, W) plans."""
    return 1 << max(int(f) - 1, 0).bit_length()


def pad_features(x, width: int | None = None) -> np.ndarray:
    """Host [n, F] → [n, pad_feature_width(F)] float32, zero-filled
    pad lanes.  Feature columns are INDEPENDENT through every kernel
    (no cross-lane fold), so pad lanes can never contaminate the real
    F lanes; the pad lanes themselves stay zero only under plus_times
    (0 is its semiring zero) — under min_plus/max_min they carry the
    fold of an all-zero input column, so consumers must slice back to
    the true F (spmm_khop callers and the serve lane do)."""
    x = np.asarray(x, np.float32)
    if x.ndim != 2:
        raise ValueError(f"features must be [n, F], got shape {x.shape}")
    fp = pad_feature_width(x.shape[1]) if width is None else int(width)
    if fp < x.shape[1]:
        raise ValueError(f"pad width {fp} < feature dim {x.shape[1]}")
    out = np.zeros((x.shape[0], fp), np.float32)
    out[:, : x.shape[1]] = x
    return out


def spmm_backend_heuristic(sr: Semiring) -> str:
    """The no-measurement fallback: plus_times contracts on the MXU,
    everything else folds on the VPU (the dense dot IS the plus_times
    contraction — there is no dot-shaped min_plus on this hardware
    short of a Pallas kernel)."""
    return "mxu_gather" if sr.name == "plus_times" else "scatter"


def admissible_spmm_backends(sr: Semiring) -> tuple[str, ...]:
    """Backends that produce exact results for ``sr`` — the probe's
    candidate gate (mirrors ``tuner.probe.admissible_tiers``'s role
    for SpGEMM)."""
    if sr.name == "plus_times":
        return ("mxu_gather", "scatter")
    return ("scatter",)


# -- distributed ELL entry ---------------------------------------------------
# (the LOCAL gather-contract kernel `_ell_local_spmm` lives in
# ellmat.py next to the format — the batched SpMV lanes share it as
# their scatter backend)


@partial(jax.jit, static_argnames=("sr", "backend"))
def dist_spmm_ell(
    sr: Semiring, E: EllParMat, X: DistMultiVec, backend: str = "scatter"
) -> DistMultiVec:
    """Y = E ⊗ X for a dense feature block X ([n, F] DistMultiVec) —
    the EllParMat schedule (panel replicated down grid columns, fold
    over the "c" axis), local kernel per ``backend``."""
    assert backend in SPMM_BACKENDS, backend
    assert X.length == E.ncols
    if obs.ENABLED:
        # trace-time: counts (re)traces per static config, the same
        # retrace-visibility convention as trace.summa_spgemm
        obs.count("trace.spmm_ell", backend=backend, sr=sr.name)
    X = X.realign("col")
    lr, lc = E.local_rows, E.local_cols
    nb = len(E.buckets)

    def body(xblk, *flat):
        buckets = [
            tuple(a[0, 0] for a in flat[3 * i : 3 * i + 3]) for i in range(nb)
        ]
        y = _ell_local_spmm(sr, buckets, xblk[0], lr, lc, backend)
        return axis_reduce(sr, y, COL_AXIS)[None]

    flat_args = [a for b in E.buckets for a in b]
    blocks = jax.shard_map(
        body,
        mesh=E.grid.mesh,
        in_specs=(P(COL_AXIS),) + (TILE_SPEC,) * (3 * nb),
        out_specs=P(ROW_AXIS),
    )(X.blocks, *flat_args)
    return DistMultiVec(
        blocks=blocks, length=E.nrows, align="row", grid=E.grid
    )


def dist_spmm(
    sr: Semiring, E: EllParMat, X: DistMultiVec,
    backend: str | None = None,
) -> DistMultiVec:
    """The ROUTED entry: resolve the backend through the tuner chain
    (arg > store > env > probe > heuristic), then run
    ``dist_spmm_ell``.  Callers that already know their backend (serve
    plans, which resolve once at engine build) call the jitted kernel
    directly."""
    backend = resolve_spmm_backend(sr, E, X.width, backend=backend, X=X)
    return dist_spmm_ell(sr, E, X, backend=backend)


# -- fused k-hop propagation -------------------------------------------------


def row_invdeg(E: EllParMat) -> DistVec:
    """Row-aligned 1/max(deg, 1) float32 DistVec — the per-hop
    normalization vector of ``spmm_khop(..., normalize=True)``
    (value-identical to building a row-normalized twin matrix, without
    the second matrix)."""
    deg = E.reduce(
        PLUS_TIMES, "cols", map_fn=lambda v: jnp.ones_like(v, jnp.float32)
    )
    return dataclasses.replace(
        deg, blocks=1.0 / jnp.maximum(deg.blocks.astype(jnp.float32), 1.0)
    )


@partial(jax.jit, static_argnames=("sr", "k", "backend", "normalize"))
def _spmm_khop_impl(
    sr: Semiring, E: EllParMat, X: DistMultiVec, invdeg,
    k: int, backend: str, normalize: bool,
) -> DistMultiVec:
    """k chained hops, fully device-resident (ONE program: no host
    round-trip, no per-hop dispatch)."""
    if obs.ENABLED:
        obs.count(
            "trace.spmm_khop", hops=k, backend=backend,
            normalize=normalize,
        )
    Y = X
    for _ in range(max(int(k), 0)):
        Y = dist_spmm_ell(sr, E, Y, backend=backend)
        if normalize:
            # Y is row-aligned after the hop; invdeg is row-aligned —
            # Y ← D⁻¹(E·Y), the row-normalized smoothing step
            inv = invdeg.realign("row")
            Y = dataclasses.replace(
                Y, blocks=Y.blocks * inv.blocks[..., None]
            )
    return Y


def spmm_khop(
    sr: Semiring, E: EllParMat, X, k: int,
    normalize: bool = False, backend: str | None = None,
) -> DistMultiVec:
    """Fused k-hop feature propagation Y = (D⁻¹)ᵏAᵏ·X (normalize=True)
    or Aᵏ·X over ``sr``.

    ``X``: a DistMultiVec or a host ``[n, F]`` array (padded to the
    pow2 feature width and uploaded).  Hops chain device-resident; the
    backend resolves once through the tuner chain.  ``normalize`` is
    plus_times-only (a normalized min_plus has no meaning) and applies
    the row-degree reciprocal AFTER each hop.
    """
    if normalize and sr.name != "plus_times":
        raise ValueError(
            f"normalize=True needs plus_times, got {sr.name}"
        )
    if not isinstance(X, DistMultiVec):
        X = DistMultiVec.from_global(
            E.grid, pad_features(X), align="col"
        )
    backend = resolve_spmm_backend(sr, E, X.width, backend=backend, X=X)
    invdeg = row_invdeg(E) if normalize else None
    return _spmm_khop_impl(
        sr, E, X, invdeg, int(k), backend, bool(normalize)
    )


# -- SUMMA SpMM over the 2D grid ---------------------------------------------


def _check_spmm_compat(A: SpParMat, X: DenseParMat):
    assert A.grid == X.grid, "A and X must share a grid"
    assert A.grid.is_square, "SUMMA SpMM requires a square grid"
    assert A.ncols == X.nrows, f"dim mismatch {A.ncols} != {X.nrows}"
    assert A.grid.local_cols(A.ncols) == A.grid.local_rows(X.nrows), (
        "A col-blocking must equal X row-blocking"
    )


def _stage_contract(
    sr: Semiring, t, xcur: Array, acc: Array, backend: str, mode: str,
    lr: int, lk: int,
):
    """acc ⊕= A_stage ⊗ X_stage for one carousel/gathered stage.

    ``mxu_gather``: densify the sparse stage tile with the COMBINING
    scatter (duplicate entries sum exactly — same dup-safety as the
    windowed tier's ``densify_combine``) and run the whole stage as one
    [lr, lk] × [lk, F] MXU product.  ``scatter``: per-tuple gather of
    the panel row + duplicate-safe combining scatter into the
    accumulator (every native add_kind)."""
    from .spgemm import _mxu_dot

    valid = t.valid_mask()
    if backend == "mxu_gather":
        da = jnp.zeros((lr, lk), acc.dtype).at[
            jnp.minimum(t.rows, lr - 1), jnp.minimum(t.cols, lk - 1)
        ].add(
            jnp.where(valid, t.vals, 0).astype(acc.dtype), mode="drop"
        )
        # the clamp above could alias a pad slot onto a real cell; the
        # where() already zeroed pad values so the alias adds 0
        return acc + _mxu_dot(da, xcur, mode, acc.dtype)
    F = xcur.shape[1]
    zero = sr.zero(acc.dtype)
    xpad = jnp.concatenate([xcur, jnp.full((1, F), zero, xcur.dtype)])
    px = xpad[jnp.minimum(t.cols, lk)]  # [cap, F]
    prods = sr.mul(t.vals[:, None].astype(acc.dtype), px.astype(acc.dtype))
    prods = jnp.where(valid[:, None], prods, zero)
    rows = jnp.where(valid, t.rows, lr)  # pad rows drop
    if sr.add_kind == "sum":
        return acc.at[rows].add(prods, mode="drop")
    if sr.add_kind == "min":
        return acc.at[rows].min(prods, mode="drop")
    if sr.add_kind == "max":
        return acc.at[rows].max(prods, mode="drop")
    raise NotImplementedError(
        f"summa_spmm scatter backend needs a native add_kind, "
        f"got {sr.add_kind!r} ({sr.name})"
    )


@partial(
    jax.jit,
    static_argnames=("sr", "backend", "mode", "ring", "pipeline"),
)
def summa_spmm(
    sr: Semiring,
    A: SpParMat,
    X: DenseParMat,
    *,
    backend: str = "mxu_gather",
    mode: str = "f32",
    ring: bool = False,
    pipeline: bool = True,
) -> DenseParMat:
    """C = A ⊗ X over the grid: SUMMA with a DENSE feature panel.

    X is tiled like SpGEMM's B (rows over grid rows, the F feature
    columns over grid columns), so stage s contracts A_{i,k(s)} against
    panel X_{k(s),j}.  ``ring=False`` gathers every stage operand up
    front (one fused all_gather per side — peak O(p·panel) dense
    memory); ``ring=True`` is the CAROUSEL: pre-skewed operands rotate
    one neighbor per stage (``_carousel_perms``, peak O(2·panel)), and
    ``pipeline=True`` issues stage s+1's ``ppermute`` BEFORE stage s's
    accumulate (two-slot buffers — the r9 latency-hiding schedule);
    ``pipeline=False`` pins the serial rotate→contract→rotate control
    with an optimization barrier (the measurement control).
    """
    from .spgemm import _carousel_stages_pair

    _check_spmm_compat(A, X)
    assert backend in SPMM_BACKENDS, backend
    if backend == "mxu_gather" and sr.name != "plus_times":
        raise ValueError(
            f"mxu_gather is the plus_times contraction; {sr.name} "
            "needs backend='scatter'"
        )
    grid = A.grid
    p = grid.pr
    lr = grid.local_rows(A.nrows)
    lk = grid.local_rows(X.nrows)
    out_dtype = jnp.result_type(A.vals.dtype, X.dtype)
    if obs.ENABLED:
        obs.count("trace.summa_spmm", ring=ring, backend=backend)
        if ring and pipeline and p > 1:
            obs.count("spmm.pipeline.stages_overlapped", p - 1)

    def body(ar, ac, av, an, xblk):
        a_mine = A.local_tile(ar, ac, av, an)
        x_mine = xblk[0, 0]  # [lk, fc]
        acc = jnp.full((lr, x_mine.shape[1]), sr.zero(out_dtype), out_dtype)
        if not ring:
            from .spgemm import _gather_stage_tiles

            a_st = _gather_stage_tiles(a_mine, COL_AXIS, p)
            x_all = lax.all_gather(x_mine, ROW_AXIS)  # [p, lk, fc]
            for s in range(p):
                acc = _stage_contract(
                    sr, a_st[s], x_all[s], acc, backend, mode, lr, lk
                )
        else:
            for s, a_cur, x_cur in _carousel_stages_pair(
                a_mine, x_mine, p, pipeline=pipeline, dep=lambda: acc
            ):
                acc = _stage_contract(
                    sr, a_cur, x_cur, acc, backend, mode, lr, lk
                )
        return acc[None, None]

    blocks = jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(TILE_SPEC,) * 5,
        out_specs=TILE_SPEC,
        check_vma=False,
    )(A.rows, A.cols, A.vals, A.nnz, X.blocks)
    return DenseParMat(
        blocks=blocks, nrows=A.nrows, ncols=X.ncols, grid=grid
    )


# -- tuner routing -----------------------------------------------------------


def resolve_spmm_backend(
    sr: Semiring,
    E,
    feat_width: int,
    backend: str | None = None,
    X: DistMultiVec | None = None,
) -> str:
    """Resolve the SpMM backend through the round-10 chain: explicit
    ``backend`` arg > plan store (``op="spmm"``, FEATURE-WIDTH bucket
    riding the key's third shape slot) > env ``COMBBLAS_SPMM_BACKEND``
    > micro-probe (both admissible backends measured ON THE REAL
    OPERANDS when ``X`` is given — SpMM probes are one warm run per
    candidate, bounded by the probe budget) > heuristic (plus_times →
    mxu_gather, else scatter).  Non-plus_times semirings short-circuit:
    scatter is the only exact backend, nothing to resolve."""
    allowed = admissible_spmm_backends(sr)
    if backend is not None:
        if backend not in allowed:
            raise ValueError(
                f"backend {backend!r} is not exact for {sr.name} "
                f"(admissible: {allowed})"
            )
        return backend
    if len(allowed) == 1:
        return allowed[0]
    from ..tuner import config as tuner_config
    from ..tuner import store as tuner_store
    from ..tuner.resolve import resolve_tier

    store = tuner_store.get_store()
    key = None
    if store is not None and (
        store.entries() > 0 or tuner_config.probe_enabled()
    ):
        key = tuner_store.spmm_plan_key(sr, E, feat_width)

    probe = None
    if X is not None:

        def probe():
            from ..tuner.probe import probe_spmm

            return probe_spmm(sr, E, X, store=store, key=key)

    tier, source, _rec = resolve_tier(
        key,
        allowed=allowed,
        heuristic=lambda: spmm_backend_heuristic(sr),
        op="spmm",
        store=store,
        probe=probe,
    )
    if tier not in allowed:
        # the env rung returns its value unvetted (resolve_tier only
        # vets STORE records); fail loudly naming the knob instead of
        # asserting deep inside the kernel — or, under python -O,
        # silently running the fallback branch
        raise ValueError(
            f"resolved SpMM backend {tier!r} (source: {source}) is "
            f"not admissible for {sr.name} — COMBBLAS_SPMM_BACKEND "
            f"takes one of {allowed}"
        )
    return tier
