"""Distributed dense / sparse vectors (≈ FullyDistVec / FullyDistSpVec).

The reference distributes vectors over ALL p processes in matrix-conformant
two-level blocks (``include/CombBLAS/FullyDist.h:44-57``) so that the
column-world allgather re-assembles exactly the x-block a local tile needs.
On TPU the replication that MPI must construct by communication comes for
free from sharding: a vector is stored as ``[pa, L]`` blocks sharded over ONE
mesh axis and *replicated* over the other by XLA — so the reference's
``TransposeVector + AllGatherVector`` pre-phase (``ParFriends.h:1388-1478``)
vanishes from SpMV entirely; only alignment conversions pay communication.

Alignment:
  * ``"col"``-aligned: block j lives on grid column j (what SpMV consumes).
  * ``"row"``-aligned: block i lives on grid row i (what SpMV produces).

``realign`` converts between them — a ``ppermute`` complement-rank pair
exchange on square grids (the reference's diagonal Sendrecv,
``SpParMat.cpp:3554-3570``), falling back to allgather+slice on rectangular
grids.

Sparse vectors (``SpDistVec``) carry padded (ind, val) slot arrays + nnz,
mirroring ``FullyDistSpVec``'s ind/num arrays (``FullyDistSpVec.h:75``).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.segment import segment_reduce
from ..semiring import Semiring
from .collectives import axis_reduce
from .grid import COL_AXIS, ROW_AXIS, Grid

Array = jax.Array


def _np_pad_blocks(x: np.ndarray, nblocks: int, fill) -> np.ndarray:
    L = -(-x.shape[0] // nblocks)
    out = np.full((nblocks, L), fill, dtype=x.dtype)
    flat = out.reshape(-1)
    flat[: x.shape[0]] = x
    return flat.reshape(nblocks, L)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["blocks"],
    meta_fields=["length", "align", "grid"],
)
@dataclasses.dataclass(frozen=True)
class DistVec:
    """Dense distributed vector: ``blocks[pa, L]`` sharded over one mesh axis.

    Padding slots (beyond ``length``) must hold values that are inert for the
    ops applied to them (constructors fill the reduction identity).
    """

    blocks: Array  # [pa, L]
    length: int
    align: str  # "row" | "col"
    grid: Grid

    @property
    def nblocks(self) -> int:
        return self.blocks.shape[0]

    @property
    def block_len(self) -> int:
        return self.blocks.shape[1]

    def axis_name(self) -> str:
        # Blocks of a row-aligned vector vary over grid rows (mesh axis "r").
        return ROW_AXIS if self.align == "row" else COL_AXIS

    def sharding(self) -> NamedSharding:
        return NamedSharding(self.grid.mesh, P(self.axis_name()))

    # --- construction -----------------------------------------------------

    @staticmethod
    def from_global(grid: Grid, x, align: str = "col", fill=0) -> "DistVec":
        x = np.asarray(x)
        pa = grid.pr if align == "row" else grid.pc
        blocks = _np_pad_blocks(x, pa, np.asarray(fill, dtype=x.dtype))
        sharding = NamedSharding(
            grid.mesh, P(ROW_AXIS if align == "row" else COL_AXIS)
        )
        return DistVec(
            blocks=jax.device_put(jnp.asarray(blocks), sharding),
            length=int(x.shape[0]),
            align=align,
            grid=grid,
        )

    @staticmethod
    def full(grid: Grid, length: int, value, dtype, align: str = "col") -> "DistVec":
        pa = grid.pr if align == "row" else grid.pc
        L = -(-length // pa)
        sharding = NamedSharding(
            grid.mesh, P(ROW_AXIS if align == "row" else COL_AXIS)
        )
        blocks = jax.device_put(
            jnp.full((pa, L), value, dtype=dtype), sharding
        )
        return DistVec(blocks=blocks, length=length, align=align, grid=grid)

    @staticmethod
    def iota(grid: Grid, length: int, dtype=jnp.int32, align: str = "col") -> "DistVec":
        """Reference: ``FullyDistVec::iota``."""
        pa = grid.pr if align == "row" else grid.pc
        L = -(-length // pa)
        vals = jnp.arange(pa * L, dtype=dtype).reshape(pa, L)
        sharding = NamedSharding(
            grid.mesh, P(ROW_AXIS if align == "row" else COL_AXIS)
        )
        return DistVec(
            blocks=jax.device_put(vals, sharding),
            length=length, align=align, grid=grid,
        )

    # --- host access (tests / small data) ---------------------------------

    def to_global(self) -> np.ndarray:
        return np.asarray(self.blocks).reshape(-1)[: self.length]

    # --- elementwise ------------------------------------------------------

    def apply(self, fn) -> "DistVec":
        """Reference: ``FullyDistVec::Apply``."""
        return dataclasses.replace(self, blocks=fn(self.blocks))

    def ewise(self, other: "DistVec", fn) -> "DistVec":
        """Blockwise binary op; alignments must match.

        Reference: ``FullyDistVec::EWiseApply`` (FullyDistVec.h).
        """
        assert self.align == other.align and self.length == other.length
        return dataclasses.replace(self, blocks=fn(self.blocks, other.blocks))

    def mask_padding(self, fill) -> "DistVec":
        """Force padding slots (global index >= length) to ``fill``."""
        pa, L = self.blocks.shape
        gids = jnp.arange(pa * L).reshape(pa, L)
        return dataclasses.replace(
            self,
            blocks=jnp.where(gids < self.length, self.blocks, fill),
        )

    # --- indirect addressing (the FullyDistVec subsref/ReduceAssign pair) --

    def gather(self, idx: "DistVec") -> "DistVec":
        """out[k] = self[idx[k]] — distributed vector subscript.

        Reference: ``FullyDistVec::operator()(FullyDistVec ri)`` (subsref,
        FullyDistVec.cpp) — there an Alltoallv request/response exchange; here
        a plain sharded gather, with GSPMD inserting the all-gather of
        ``self`` over ICI.  idx values must lie in [0, self.length); anything
        else (including idx's own padding slots) reads an unspecified slot —
        callers must mask those results.  Result is aligned like ``idx``.
        """
        full = self.blocks.reshape(-1)
        safe = jnp.clip(idx.blocks, 0, full.shape[0] - 1)
        return DistVec(
            blocks=full[safe],
            length=idx.length,
            align=idx.align,
            grid=idx.grid,
        )

    def scatter_combine(
        self, sr: Semiring, idx: "DistVec", src: "DistVec"
    ) -> "DistVec":
        """out[p] = sr.add(self[p], ⊕{src[k] : idx[k] == p}).

        Reference: ``FullyDistVec::ReduceAssign`` / the scatter helper used
        by LACC & FastSV hooking (CC.h:1033-1230, FastSV.h:68-146) — there an
        Alltoallv of (index, value) pairs + local fold; here one segment
        reduction over the flattened blocks (identity-filled empty segments
        make the final elementwise ``add`` a no-op for untouched slots).
        idx/src must share alignment and shape with each other; padding slots
        of idx (beyond idx.length) are dropped.
        """
        assert idx.align == src.align and idx.length == src.length
        pa, L = self.blocks.shape
        ids = idx.blocks.reshape(-1)
        vals = src.blocks.reshape(-1)
        pos = jnp.arange(ids.shape[0], dtype=jnp.int32)
        ids = jnp.where(pos < idx.length, ids, pa * L)  # drop padding sources
        ids = jnp.where((ids >= 0) & (ids < self.length), ids, pa * L)
        contrib = segment_reduce(sr, vals, ids, pa * L)
        out = sr.add(self.blocks.reshape(-1), contrib)
        return dataclasses.replace(self, blocks=out.reshape(pa, L))

    def reduce(self, sr: Semiring) -> Array:
        """Global fold with sr.add → replicated scalar.

        Padding must hold the identity (use mask_padding first if unsure).
        Reference: ``FullyDistVec::Reduce``.
        """
        if sr.add_kind == "sum":
            return jnp.sum(self.blocks)
        if sr.add_kind == "min":
            return jnp.min(self.blocks)
        if sr.add_kind == "max":
            return jnp.max(self.blocks)
        return jax.lax.reduce(
            self.blocks, sr.zero(self.blocks.dtype), sr.add, (0, 1)
        )

    # --- FullyDistVec op pack (sort / find / permute family) ---------------

    def sort(self) -> tuple["DistVec", "DistVec"]:
        """Ascending sort. Returns (sorted values, original indices).

        Reference: ``FullyDistVec::sort`` (there a psort; here XLA's native
        sharded sort over the global view — the distributed-sorting strategy
        of SURVEY §2.3(8) collapses into one collective sort on ICI).
        Padding slots sort to the tail regardless of their value.
        """
        return _sort_jit(self)

    def find_inds(self, pred) -> tuple["DistVec", Array]:
        """Global indices i (ascending) with ``pred(self[i])``.

        Reference: ``FullyDistVec::FindInds`` — there a variable-length
        result vector; here a fixed-capacity DistVec whose first ``count``
        slots hold the indices and whose tail holds the sentinel
        ``self.length``. Returns (indices, count). Pass a module-level
        ``pred`` for compile-cache hits.
        """
        return _find_inds_jit(self, pred)

    def invert(self, active: "DistVec", out_length: int, sr: Semiring) -> "DistVec":
        """out[self[i]] = i for active slots i; collisions resolved by
        ``sr.add``; untouched outputs get -1.

        Reference: ``FullyDistSpVec::Invert`` (FullyDistSpVec.h:89-93) — the
        value↔index flip with duplicate resolution. ``active`` is the
        bool mask standing in for the sparse vector's index set (our
        masked-dense FullyDistSpVec representation).
        """
        return _invert_jit(self, active, out_length, sr)

    def uniq(self, active: "DistVec") -> "DistVec":
        """New active mask keeping only the first (lowest-index) occurrence
        of each value among active slots.

        Reference: ``FullyDistSpVec::Uniq``. Setminus, the other index-set
        op of that family, is plain mask arithmetic on masked-dense vectors:
        ``a_active & ~b_active``.
        """
        return _uniq_jit(self, active)

    @staticmethod
    def randperm(grid: Grid, length: int, key, align: str = "col") -> "DistVec":
        """Uniform random permutation of [0, length).

        Reference: ``FullyDistVec::RandPerm`` (FullyDistVec.cpp:783-870) —
        there a random-destination Alltoallv + local shuffle; here one
        sort-by-random-key over the sharded global view.  ``key`` is a JAX
        PRNG key (the deterministic-stream analog of the reference's
        per-rank seeds).
        """
        v = DistVec.iota(grid, length, jnp.int32, align=align)
        return _randperm_jit(v, key)

    # --- alignment conversion (the TransposeVector analog) ----------------

    def realign(self, align: str) -> "DistVec":
        if align == self.align:
            return self
        grid = self.grid
        src_axis = self.axis_name()
        dst_pa = grid.pr if align == "row" else grid.pc
        dst_sharding = NamedSharding(
            grid.mesh, P(ROW_AXIS if align == "row" else COL_AXIS)
        )

        if grid.is_square:
            # Complement-rank pair exchange: device (i,j) holds block i
            # (row-aligned); after ppermute from (j,i), it holds block j.
            perm = grid.transpose_perm()

            def shift(b):  # b: [1, L]
                return lax.ppermute(b, (ROW_AXIS, COL_AXIS), perm)

            blocks = jax.shard_map(
                shift,
                mesh=grid.mesh,
                in_specs=P(src_axis),
                out_specs=P(ROW_AXIS if align == "row" else COL_AXIS),
                # The permutation provably delivers block j to every (i, j),
                # i.e. the output IS replicated along the unlisted axis, but
                # shard_map cannot infer that through ppermute.
                check_vma=False,
            )(self.blocks)
        else:
            # Rectangular grid: allgather the full vector along the source
            # axis, then let resharding slice out the destination blocks.
            full = self.blocks.reshape(-1)
            pa = dst_pa
            L = -(-full.shape[0] // pa)
            pad = pa * L - full.shape[0]
            if pad:
                full = jnp.concatenate([full, jnp.zeros((pad,), full.dtype)])
            blocks = jax.device_put(full.reshape(pa, L), dst_sharding)
        return DistVec(
            blocks=blocks, length=self.length, align=align, grid=grid
        )


# --- jitted impls of the op pack -------------------------------------------


def _global_ids(vec: DistVec) -> Array:
    pa, L = vec.blocks.shape
    return jnp.arange(pa * L, dtype=jnp.int32)


@jax.jit
def _sort_jit(vec: DistVec) -> tuple[DistVec, DistVec]:
    flat = vec.blocks.reshape(-1)
    gids = _global_ids(vec)
    pad = (gids >= vec.length).astype(jnp.int32)
    _, vals, idx = lax.sort((pad, flat, gids), num_keys=2)
    shape = vec.blocks.shape
    return (
        dataclasses.replace(vec, blocks=vals.reshape(shape)),
        dataclasses.replace(vec, blocks=idx.reshape(shape)),
    )


@partial(jax.jit, static_argnames=("pred",))
def _find_inds_jit(vec: DistVec, pred) -> tuple[DistVec, Array]:
    pa, L = vec.blocks.shape
    flat = vec.blocks.reshape(-1)
    gids = _global_ids(vec)
    mask = pred(flat) & (gids < vec.length)
    pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
    out = jnp.full((pa * L,), vec.length, jnp.int32)
    out = out.at[jnp.where(mask, pos, pa * L)].set(gids, mode="drop")
    count = jnp.sum(mask).astype(jnp.int32)
    return (
        DistVec(
            blocks=out.reshape(pa, L), length=vec.length, align=vec.align,
            grid=vec.grid,
        ),
        count,
    )


@partial(jax.jit, static_argnames=("out_length", "sr"))
def _invert_jit(
    vec: DistVec, active: DistVec, out_length: int, sr: Semiring
) -> DistVec:
    pa = vec.grid.pr if vec.align == "row" else vec.grid.pc
    L = -(-out_length // pa)
    flat = vec.blocks.reshape(-1).astype(jnp.int32)
    gids = _global_ids(vec)
    ok = active.blocks.reshape(-1) & (gids < vec.length)
    ids = jnp.where(ok & (flat >= 0) & (flat < out_length), flat, pa * L)
    contrib = segment_reduce(sr, gids, ids, pa * L)
    touched = jax.ops.segment_sum(
        ok.astype(jnp.int32), ids, num_segments=pa * L
    )
    out = jnp.where(touched > 0, contrib, -1)
    return DistVec(
        blocks=out.reshape(pa, L), length=out_length, align=vec.align,
        grid=vec.grid,
    )


@jax.jit
def _uniq_jit(vec: DistVec, active: DistVec) -> DistVec:
    pa, L = vec.blocks.shape
    flat = vec.blocks.reshape(-1)
    gids = _global_ids(vec)
    ok = active.blocks.reshape(-1) & (gids < vec.length)
    # Sort (inactive-last, value, gid); firsts of each active value run win.
    inact = (~ok).astype(jnp.int32)
    _, vals, idx = lax.sort((inact, flat, gids), num_keys=3)
    first = jnp.concatenate(
        [jnp.ones((1,), bool), vals[1:] != vals[:-1]]
    )
    n_active = jnp.sum(ok)
    keep_sorted = first & (jnp.arange(pa * L) < n_active)
    keep = jnp.zeros((pa * L,), bool).at[idx].set(keep_sorted)
    return dataclasses.replace(active, blocks=keep.reshape(pa, L))


@jax.jit
def _randperm_jit(vec: DistVec, key) -> DistVec:
    pa, L = vec.blocks.shape
    gids = _global_ids(vec)
    # 64 bits of random key per element: float32 uniforms would alias to
    # 2^23 values and stable-sort ties toward identity order, biasing large
    # permutations. Padding sorts last via the explicit leading key.
    k1, k2 = jax.random.split(key)
    r1 = jax.random.bits(k1, (pa * L,), jnp.uint32)
    r2 = jax.random.bits(k2, (pa * L,), jnp.uint32)
    pad = (gids >= vec.length).astype(jnp.int32)
    _, _, _, perm = lax.sort(
        (pad, r1, r2, vec.blocks.reshape(-1)), num_keys=3
    )
    return dataclasses.replace(vec, blocks=perm.reshape(pa, L))


# --- multi-vector (batched frontier; ≈ BetwCent's frontier-as-matrix) -------


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["blocks"],
    meta_fields=["length", "align", "grid"],
)
@dataclasses.dataclass(frozen=True)
class DistMultiVec:
    """W stacked distributed vectors: ``blocks[pa, L, W]``.

    The batched-frontier carrier for multi-source algorithms (Graph500's 64
    search keys, batched Brandes BC — SURVEY §2.3 strategy 7): one gathered
    index fetches W payload lanes, amortizing the per-index cost of TPU
    gathers across the batch (measured: W=8 costs the same as W=1 on v5e).
    Same alignment/padding contract as DistVec, width replicated everywhere.
    """

    blocks: Array  # [pa, L, W]
    length: int
    align: str  # "row" | "col"
    grid: Grid

    @property
    def width(self) -> int:
        return self.blocks.shape[2]

    @property
    def block_len(self) -> int:
        return self.blocks.shape[1]

    def axis_name(self) -> str:
        return ROW_AXIS if self.align == "row" else COL_AXIS

    @staticmethod
    def from_global(grid: Grid, x, align: str = "col", fill=0) -> "DistMultiVec":
        """x: [length, W] host array."""
        x = np.asarray(x)
        n, W = x.shape
        pa = grid.pr if align == "row" else grid.pc
        L = -(-n // pa)
        out = np.full((pa * L, W), fill, dtype=x.dtype)
        out[:n] = x
        sharding = NamedSharding(
            grid.mesh, P(ROW_AXIS if align == "row" else COL_AXIS)
        )
        return DistMultiVec(
            blocks=jax.device_put(jnp.asarray(out.reshape(pa, L, W)), sharding),
            length=int(n), align=align, grid=grid,
        )

    def to_global(self) -> np.ndarray:
        b = np.asarray(self.blocks)
        return b.reshape(-1, b.shape[2])[: self.length]

    def realign(self, align: str) -> "DistMultiVec":
        """Same exchange as DistVec.realign; the trailing width dim rides
        along (ppermute/all_gather are shape-agnostic past the block dim)."""
        if align == self.align:
            return self
        grid = self.grid
        src_axis = self.axis_name()
        dst_axis = ROW_AXIS if align == "row" else COL_AXIS
        dst_pa = grid.pr if align == "row" else grid.pc
        dst_sharding = NamedSharding(grid.mesh, P(dst_axis))
        if grid.is_square:
            perm = grid.transpose_perm()

            def shift(b):  # [1, L, W]
                return lax.ppermute(b, (ROW_AXIS, COL_AXIS), perm)

            blocks = jax.shard_map(
                shift,
                mesh=grid.mesh,
                in_specs=P(src_axis),
                out_specs=P(dst_axis),
                check_vma=False,
            )(self.blocks)
        else:
            W = self.width
            full = self.blocks.reshape(-1, W)
            L = -(-full.shape[0] // dst_pa)
            pad = dst_pa * L - full.shape[0]
            if pad:
                full = jnp.concatenate(
                    [full, jnp.zeros((pad, W), full.dtype)]
                )
            blocks = jax.device_put(
                full.reshape(dst_pa, L, W), dst_sharding
            )
        return DistMultiVec(
            blocks=blocks, length=self.length, align=align, grid=grid
        )


def concatenate(vecs, grid: "Grid | None" = None, align: str | None = None,
                fill=0) -> DistVec:
    """Cross-grid vector concatenation (≈ ``Concatenate``,
    ParFriends.h:61-159).

    The reference stitches FullyDistVecs living on DIFFERENT process grids
    into one vector on the union grid via pairwise exchanges. Here vectors
    may live on different meshes (or the same one): each input's blocks
    are flattened device-side, concatenated in order, re-padded, and
    device_put onto the target grid's sharding — XLA moves the bytes
    between device sets at the jit boundary. ``grid`` defaults to the
    first vector's grid; ``align`` to the first vector's alignment.
    """
    assert vecs, "concatenate needs at least one vector"
    grid = grid or vecs[0].grid
    align = align or vecs[0].align
    pa = grid.pr if align == "row" else grid.pc
    total = sum(v.length for v in vecs)
    # inputs may live on different device sets: land every part on the
    # TARGET mesh (replicated) before concatenating — the cross-grid hop
    rep = NamedSharding(grid.mesh, P())
    parts = [
        jax.device_put(v.blocks.reshape(-1)[: v.length], rep) for v in vecs
    ]
    flat = jnp.concatenate(parts)
    L = -(-total // pa)
    pad = pa * L - total
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.full((pad,), fill, flat.dtype)]
        )
    sharding = NamedSharding(
        grid.mesh, P(ROW_AXIS if align == "row" else COL_AXIS)
    )
    return DistVec(
        blocks=jax.device_put(flat.reshape(pa, L), sharding),
        length=total, align=align, grid=grid,
    )
