"""DenseParMat — distributed dense 2D matrix (≈ DenseParMat<IT,NT>).

The reference's minimal dense companion to SpParMat (``DenseParMat.h:128``,
used by betweenness centrality to accumulate per-vertex path counts /
dependencies). Tiles are stored as one ``[pr, pc, lr, lc]`` array sharded so
device (i,j) holds dense tile (i,j) — matrix-conformant with SpParMat's
block distribution, so sparse↔dense elementwise ops need no communication.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..semiring import Semiring
from .collectives import axis_reduce
from .grid import COL_AXIS, ROW_AXIS, Grid
from .spmat import TILE_SPEC, SpParMat
from .vec import DistVec

Array = jax.Array


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["blocks"],
    meta_fields=["nrows", "ncols", "grid"],
)
@dataclasses.dataclass(frozen=True)
class DenseParMat:
    """blocks: NT[pr, pc, lr, lc]; padding cells (beyond nrows/ncols) must
    stay inert for the ops applied (constructors zero-fill)."""

    blocks: Array
    nrows: int
    ncols: int
    grid: Grid

    @property
    def local_rows(self) -> int:
        return self.blocks.shape[2]

    @property
    def local_cols(self) -> int:
        return self.blocks.shape[3]

    @property
    def dtype(self):
        return self.blocks.dtype

    # --- construction -----------------------------------------------------

    @staticmethod
    def zeros(grid: Grid, nrows: int, ncols: int, dtype=jnp.float32):
        lr, lc = grid.local_rows(nrows), grid.local_cols(ncols)
        blocks = jax.device_put(
            jnp.zeros((grid.pr, grid.pc, lr, lc), dtype), grid.tile_sharding()
        )
        return DenseParMat(blocks=blocks, nrows=nrows, ncols=ncols, grid=grid)

    @staticmethod
    def from_global(grid: Grid, dense) -> "DenseParMat":
        dense = np.asarray(dense)
        m, n = dense.shape
        lr, lc = grid.local_rows(m), grid.local_cols(n)
        padded = np.zeros((grid.pr * lr, grid.pc * lc), dense.dtype)
        padded[:m, :n] = dense
        blocks = (
            padded.reshape(grid.pr, lr, grid.pc, lc).transpose(0, 2, 1, 3)
        )
        return DenseParMat(
            blocks=jax.device_put(jnp.asarray(blocks), grid.tile_sharding()),
            nrows=m, ncols=n, grid=grid,
        )

    def to_global(self) -> np.ndarray:
        b = np.asarray(self.blocks)
        full = b.transpose(0, 2, 1, 3).reshape(
            self.grid.pr * self.local_rows, self.grid.pc * self.local_cols
        )
        return full[: self.nrows, : self.ncols]

    # --- elementwise ------------------------------------------------------

    def apply(self, fn) -> "DenseParMat":
        return dataclasses.replace(self, blocks=fn(self.blocks))

    def ewise(self, other: "DenseParMat", fn) -> "DenseParMat":
        assert self.grid == other.grid
        return dataclasses.replace(self, blocks=fn(self.blocks, other.blocks))

    # --- sparse interplay -------------------------------------------------

    def add_spmat(self, S: SpParMat, combine=None) -> "DenseParMat":
        """self[i,j] ← combine(self[i,j], S[i,j]) on S's nonzero pattern
        (default: +).

        Reference: ``DenseParMat::operator+=(SpParMat)`` — the BC
        accumulation step (BetwCent.cpp:207). No communication: tiles align.
        """
        assert self.grid == S.grid
        assert (self.nrows, self.ncols) == (S.nrows, S.ncols)
        return _add_spmat_jit(self, S, combine)

    def filter_spmat(self, S: SpParMat, keep) -> SpParMat:
        """Drop entries of S where ``keep(sval, self[i,j])`` is False.

        The batched-BFS frontier prune of BC: fringe entries whose vertex
        already has a path count are discarded (reference
        ``EWiseMult(fringe, nsp, exclude)``, BetwCent.cpp:191-204).
        """
        assert self.grid == S.grid
        assert (self.nrows, self.ncols) == (S.nrows, S.ncols)
        return _filter_spmat_jit(self, S, keep)

    def scale_spmat(self, S: SpParMat, fn) -> SpParMat:
        """S with vals ← ``fn(sval, self[i,j])`` (dense-indexed rescale).

        The BC back-propagation weighting (reference ``EWiseScale`` +
        ``Apply(safemultinv)``, BetwCent.cpp:207-218).
        """
        assert self.grid == S.grid
        assert (self.nrows, self.ncols) == (S.nrows, S.ncols)
        return _scale_spmat_jit(self, S, fn)

    # --- reductions -------------------------------------------------------

    def reduce(self, sr: Semiring, axis: str, map_fn=None) -> DistVec:
        """Fold along ``axis`` like ``SpParMat.reduce`` (dense analog):
        axis="rows" → col-aligned vec[ncols]; axis="cols" → row-aligned
        vec[nrows]."""
        return _dense_reduce_jit(self, sr, axis, map_fn)


@partial(jax.jit, static_argnames=("combine",))
def _add_spmat_jit(D: DenseParMat, S: SpParMat, combine) -> DenseParMat:
    def body(blk, rows, cols, vals, nnz):
        t = S.local_tile(rows, cols, vals, nnz)
        b = blk[0, 0]
        if combine is None:
            out = b.at[t.rows, t.cols].add(
                jnp.where(t.valid_mask(), t.vals, 0).astype(b.dtype),
                mode="drop",
            )
        else:
            cur = _gather_dense_at(b, t)
            new = combine(cur, t.vals.astype(b.dtype))
            out = b.at[t.rows, t.cols].set(
                jnp.where(t.valid_mask(), new, cur), mode="drop"
            )
        return out[None, None]

    blocks = jax.shard_map(
        body,
        mesh=D.grid.mesh,
        in_specs=(TILE_SPEC,) * 5,
        out_specs=TILE_SPEC,
    )(D.blocks, S.rows, S.cols, S.vals, S.nnz)
    return dataclasses.replace(D, blocks=blocks)


def _gather_dense_at(b: Array, t) -> Array:
    """Per-tuple dense values b[t.rows, t.cols] (padding-safe clamp)."""
    return b[
        jnp.minimum(t.rows, b.shape[0] - 1),
        jnp.minimum(t.cols, b.shape[1] - 1),
    ]


@partial(jax.jit, static_argnames=("keep",))
def _filter_spmat_jit(D: DenseParMat, S: SpParMat, keep) -> SpParMat:
    def body(blk, rows, cols, vals, nnz):
        t = S.local_tile(rows, cols, vals, nnz)
        dval = _gather_dense_at(blk[0, 0], t)
        return SpParMat._pack_tile(
            t._select(t.valid_mask() & keep(t.vals, dval))
        )

    r, c, v, n = jax.shard_map(
        body,
        mesh=D.grid.mesh,
        in_specs=(TILE_SPEC,) * 5,
        out_specs=(TILE_SPEC,) * 4,
    )(D.blocks, S.rows, S.cols, S.vals, S.nnz)
    return dataclasses.replace(S, rows=r, cols=c, vals=v, nnz=n)


@partial(jax.jit, static_argnames=("fn",))
def _scale_spmat_jit(D: DenseParMat, S: SpParMat, fn) -> SpParMat:
    def body(blk, rows, cols, vals, nnz):
        t = S.local_tile(rows, cols, vals, nnz)
        dval = _gather_dense_at(blk[0, 0], t)
        new = jnp.where(t.valid_mask(), fn(t.vals, dval), t.vals)
        return SpParMat._pack_tile(dataclasses.replace(t, vals=new))

    r, c, v, n = jax.shard_map(
        body,
        mesh=D.grid.mesh,
        in_specs=(TILE_SPEC,) * 5,
        out_specs=(TILE_SPEC,) * 4,
    )(D.blocks, S.rows, S.cols, S.vals, S.nnz)
    return dataclasses.replace(S, rows=r, cols=c, vals=v, nnz=n)


@partial(jax.jit, static_argnames=("sr", "axis", "map_fn"))
def _dense_reduce_jit(
    D: DenseParMat, sr: Semiring, axis: str, map_fn
) -> DistVec:
    out_len = D.ncols if axis == "rows" else D.nrows
    align = "col" if axis == "rows" else "row"
    comm_axis = ROW_AXIS if axis == "rows" else COL_AXIS
    fold_dim = 0 if axis == "rows" else 1

    def body(blk):
        b = blk[0, 0]
        v = map_fn(b) if map_fn is not None else b
        zero = sr.zero(v.dtype)
        local = lax.reduce(v, zero, sr.add, (fold_dim,))
        return axis_reduce(sr, local, comm_axis)[None]

    out_specs = P(COL_AXIS) if axis == "rows" else P(ROW_AXIS)
    blocks = jax.shard_map(
        body,
        mesh=D.grid.mesh,
        in_specs=(TILE_SPEC,),
        out_specs=out_specs,
    )(D.blocks)
    return DistVec(blocks=blocks, length=out_len, align=align, grid=D.grid)
