"""SpParMat — the distributed 2D sparse matrix (≈ SpParMat<IT,NT,DER>).

The reference's core object (``include/CombBLAS/SpParMat.h:67-452``,
``SpParMat.cpp``: 5,125 lines) owns a CommGrid plus one local sequential
matrix per process.  The TPU-native re-design stores ALL tiles as stacked
global arrays of shape ``[pr, pc, capacity]`` sharded so device (i,j) holds
tile (i,j) — a single jittable pytree instead of p per-process objects.  The
"decoupling of parallel logic from sequential kernels" that the reference
achieves with the DER template parameter (``SpMat.h:54``) is achieved here by
every distributed op being ``shard_map(local-kernel-on-SpTuples)``: swap the
local kernel, keep the schedule.

Tile-local indices are int32; padding slots hold (local_rows, local_cols).
Global dims are padded to ceil-multiples of the grid shape (see grid.py).

COMPILATION-CACHE DISCIPLINE: every distributed op dispatches through a
module-level ``jax.jit``-wrapped impl whose non-array parameters (semiring,
axis, capacities, user callbacks) are static arguments.  Repeated calls with
the same shapes then reuse the compiled executable — the analog of the
reference's one-time template instantiation, and essential for iterative
drivers (MCL, BC, BFS sweeps) that would otherwise re-trace and re-compile
every iteration.  Callers supplying callbacks (``apply``/``prune``/
``reduce(map_fn=...)``) should pass module-level functions (not fresh
lambdas) to benefit.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops.segment import segment_reduce
from ..ops.tuples import SpTuples
from ..semiring import Semiring, _minval
from .collectives import axis_reduce
from .grid import COL_AXIS, ROW_AXIS, Grid
from .vec import DistVec

Array = jax.Array

TILE_SPEC = P(ROW_AXIS, COL_AXIS)


def _key_bits(dtype) -> int:
    """Radix width for ``kselect`` keys: 64 when x64 dtypes are in play."""
    dtype = jnp.dtype(dtype)
    return 64 if dtype.itemsize == 8 else 32


def _monotone_key_u32(v: Array) -> Array:
    """Order-preserving map of a value array onto unsigned integer keys
    (uint32 for <=32-bit dtypes, uint64 under x64 for 64-bit ones).

    The radix-select substrate for ``kselect``: floats use the sign-flip
    trick (negative floats bit-invert, positives set the MSB), signed ints
    XOR the sign bit, bools/unsigned cast. Total order matches the value
    order, so threshold search can run in integer bit-space exactly.
    """
    dtype = jnp.dtype(v.dtype)
    if dtype == jnp.bool_:
        return v.astype(jnp.uint32)
    if dtype.itemsize < 4 and jnp.issubdtype(dtype, jnp.integer):
        # Sub-32-bit ints widen losslessly; the sign-XOR below then applies
        # in 32-bit key space.
        v = v.astype(
            jnp.int32 if jnp.issubdtype(dtype, jnp.signedinteger) else jnp.uint32
        )
        dtype = jnp.dtype(v.dtype)
    assert dtype.itemsize in (4, 8), (
        f"kselect supports integer and 32/64-bit dtypes, got {dtype} (cast "
        "bf16/f16 values to float32 first)"
    )
    wide = dtype.itemsize == 8
    ut = jnp.uint64 if wide else jnp.uint32
    sign = jnp.asarray(1 << (64 - 1 if wide else 32 - 1), ut)
    allbits = jnp.asarray((1 << (64 if wide else 32)) - 1, ut)
    shift = jnp.asarray(63 if wide else 31, ut)
    if jnp.issubdtype(dtype, jnp.floating):
        u = lax.bitcast_convert_type(v, ut)
        mask = jnp.where((u >> shift) != 0, allbits, sign)
        return u ^ mask
    if jnp.issubdtype(dtype, jnp.signedinteger):
        return lax.bitcast_convert_type(v, ut) ^ sign
    return v.astype(ut)


def _u32_key_to_val(key: Array, dtype) -> Array:
    """Inverse of ``_monotone_key_u32``."""
    dtype = jnp.dtype(dtype)
    if dtype == jnp.bool_:
        return key.astype(jnp.bool_)
    wide = jnp.dtype(key.dtype).itemsize == 8
    ut = jnp.uint64 if wide else jnp.uint32
    sign = jnp.asarray(1 << (64 - 1 if wide else 32 - 1), ut)
    allbits = jnp.asarray((1 << (64 if wide else 32)) - 1, ut)
    shift = jnp.asarray(63 if wide else 31, ut)
    if jnp.issubdtype(dtype, jnp.floating):
        mask = jnp.where((key >> shift) != 0, sign, allbits)
        return lax.bitcast_convert_type(key ^ mask, dtype)
    if jnp.issubdtype(dtype, jnp.signedinteger):
        # Sub-32-bit ints were widened by _monotone_key_u32: bitcast back to
        # the matching-width signed type first, then narrow (a direct
        # bitcast to int8/int16 would add a trailing byte axis).
        it = jnp.int64 if wide else jnp.int32
        return lax.bitcast_convert_type(key ^ sign, it).astype(dtype)
    return key.astype(dtype)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["rows", "cols", "vals", "nnz"],
    meta_fields=["nrows", "ncols", "grid"],
)
@dataclasses.dataclass(frozen=True)
class SpParMat:
    """Distributed sparse matrix over a 2D grid.

    rows/cols: int32[pr, pc, cap] tile-local indices (padding = lr/lc).
    vals: NT[pr, pc, cap].
    nnz: int32[pr, pc] valid counts per tile.
    """

    rows: Array
    cols: Array
    vals: Array
    nnz: Array
    nrows: int
    ncols: int
    grid: Grid

    # --- static geometry --------------------------------------------------

    @property
    def capacity(self) -> int:
        return self.rows.shape[2]

    @property
    def local_rows(self) -> int:
        return self.grid.local_rows(self.nrows)

    @property
    def local_cols(self) -> int:
        return self.grid.local_cols(self.ncols)

    @property
    def dtype(self):
        return self.vals.dtype

    def getnnz(self) -> Array:
        """Total nonzeros. Reference: ``SpParMat::getnnz``."""
        return jnp.sum(self.nnz)

    def load_imbalance(self) -> Array:
        """max/avg tile nnz. Reference: ``SpParMat::LoadImbalance``."""
        return jnp.max(self.nnz) * self.grid.size / jnp.maximum(jnp.sum(self.nnz), 1)

    # --- tile pytree <-> shard_map plumbing -------------------------------

    def local_tile(self, rows, cols, vals, nnz) -> SpTuples:
        """Wrap per-device slices ([1,1,cap] / [1,1]) as a local SpTuples."""
        return SpTuples(
            rows=rows[0, 0],
            cols=cols[0, 0],
            vals=vals[0, 0],
            nnz=nnz[0, 0],
            nrows=self.local_rows,
            ncols=self.local_cols,
        )

    @staticmethod
    def _pack_tile(t: SpTuples):
        return (
            t.rows[None, None], t.cols[None, None], t.vals[None, None],
            t.nnz[None, None],
        )

    def tile_map(self, fn, out_like: "SpParMat | None" = None) -> "SpParMat":
        """Apply ``fn: SpTuples -> SpTuples`` to every tile (no comm).

        The local-kernel dispatch boundary — the analog of calling into the
        DER layer from SpParMat methods. For compile-cache hits pass a
        module-level ``fn``.
        """
        meta = (
            (out_like.nrows, out_like.ncols) if out_like is not None else None
        )
        return _tile_map_jit(self, fn, out_meta=meta, indexed=False)

    def tile_map_indexed(self, fn) -> "SpParMat":
        """Apply ``fn(tile, row_offset, col_offset) -> tile`` per tile.

        Offsets are the tile's global (row, col) origin, computed from the
        mesh position — how a local kernel learns its place in the global
        matrix (the reference threads this through CommGrid rank math).
        """
        return _tile_map_jit(self, fn, out_meta=None, indexed=True)

    def keep_ij(self, pred) -> "SpParMat":
        """Keep entries where ``pred(global_row, global_col)`` is True.

        Reference: ``SpParMat::PruneI`` (index-based prune family)."""
        return _keep_ij_jit(self, pred)

    def tril(self, strict: bool = True) -> "SpParMat":
        """Lower-triangular part (strict by default — the TC mask,
        ``TC.cpp:104``)."""
        return self.keep_ij(_pred_tril_strict if strict else _pred_tril)

    def triu(self, strict: bool = True) -> "SpParMat":
        return self.keep_ij(_pred_triu_strict if strict else _pred_triu)

    def remove_loops(self) -> "SpParMat":
        """Drop diagonal entries. Reference: ``SpParMat::RemoveLoops``
        (SpParMat.cpp:3257)."""
        return self.keep_ij(_pred_offdiag)

    # --- construction -----------------------------------------------------

    @staticmethod
    def from_global_coo(
        grid: Grid,
        rows,
        cols,
        vals,
        nrows: int,
        ncols: int,
        capacity: int | None = None,
        dedup_sr: Semiring | None = None,
    ) -> "SpParMat":
        """Host-side construction: bucket global tuples by owner tile.

        The host analog of the reference's tuple-Alltoallv redistribution
        ``SparseCommon`` (SpParMat.cpp:2893-2968); the fully on-device
        redistribution lives in ``parallel/redistribute.py``.
        """
        vals = np.asarray(vals)
        rows, cols, order, counts, starts, cap, lr, lc = bucket_by_tile(
            grid, rows, cols, nrows, ncols, capacity
        )
        vals = vals[order]
        pr_, pc_ = grid.pr, grid.pc
        R = np.full((pr_, pc_, cap), lr, dtype=np.int32)
        C = np.full((pr_, pc_, cap), lc, dtype=np.int32)
        V = np.zeros((pr_, pc_, cap), dtype=vals.dtype)
        for t in range(grid.size):
            i, j = divmod(t, pc_)
            s, e = starts[t], starts[t + 1]
            n = e - s
            R[i, j, :n] = rows[s:e] - i * lr
            C[i, j, :n] = cols[s:e] - j * lc
            V[i, j, :n] = vals[s:e]
        sharding = grid.tile_sharding()
        mat = SpParMat(
            rows=jax.device_put(jnp.asarray(R), sharding),
            cols=jax.device_put(jnp.asarray(C), sharding),
            vals=jax.device_put(jnp.asarray(V), sharding),
            nnz=jax.device_put(jnp.asarray(counts.reshape(pr_, pc_), jnp.int32), sharding),
            nrows=int(nrows),
            ncols=int(ncols),
            grid=grid,
        )
        if dedup_sr is not None:
            mat = mat.tile_map(_compact_fn(dedup_sr))
        return mat

    @staticmethod
    def from_dense(grid: Grid, dense, capacity=None, dedup_sr=None) -> "SpParMat":
        dense = np.asarray(dense)
        r, c = np.nonzero(dense)
        return SpParMat.from_global_coo(
            grid, r, c, dense[r, c], dense.shape[0], dense.shape[1],
            capacity=capacity, dedup_sr=dedup_sr,
        )

    # --- host access (tests) ----------------------------------------------

    def to_global_coo(self):
        lr, lc = self.local_rows, self.local_cols
        R = np.asarray(self.rows)
        C = np.asarray(self.cols)
        V = np.asarray(self.vals)
        N = np.asarray(self.nnz)
        out_r, out_c, out_v = [], [], []
        for i in range(self.grid.pr):
            for j in range(self.grid.pc):
                # Mask- rather than prefix-based: tiles need not be compacted
                # (e.g. right after concat-style ops like add_loops).
                m = R[i, j] < lr
                assert m.sum() == N[i, j]
                out_r.append(R[i, j, m].astype(np.int64) + i * lr)
                out_c.append(C[i, j, m].astype(np.int64) + j * lc)
                out_v.append(V[i, j, m])
        return (
            np.concatenate(out_r), np.concatenate(out_c), np.concatenate(out_v),
        )

    def to_dense(self) -> np.ndarray:
        r, c, v = self.to_global_coo()
        out = np.zeros((self.nrows, self.ncols), dtype=v.dtype)
        np.add.at(out, (r, c), v)
        return out

    # --- elementwise / structural (no communication) ----------------------

    def apply(self, fn) -> "SpParMat":
        """Reference: ``SpParMat::Apply`` (SpParMat.h:148)."""
        return _apply_jit(self, fn)

    def prune(self, pred) -> "SpParMat":
        """Drop entries where pred(val). Reference: ``SpParMat::Prune``."""
        return _prune_jit(self, pred)

    def ewise_mult(
        self, other: "SpParMat", negate: bool = False, combine=None
    ) -> "SpParMat":
        """A .* structure(B) (negate=False) or A .* !structure(B).

        Reference: ``EWiseMult`` (ParFriends.h:2157-2244). Local-only: grids
        and shapes must match, so tiles align elementwise.
        """
        assert self.grid == other.grid
        assert (self.nrows, self.ncols) == (other.nrows, other.ncols)
        return _ewise_mult_jit(self, other, negate, combine)

    def ewise_apply(
        self,
        other: "SpParMat",
        fn,
        *,
        allow_a_nulls: bool = False,
        allow_b_nulls: bool = False,
        a_null=0,
        b_null=0,
    ) -> "SpParMat":
        """Generalized elementwise apply with null handling.

        Reference: ``EWiseApply`` (ParFriends.h:2157-2807). The output
        pattern is the intersection, extended to b-only entries when
        ``allow_a_nulls`` (missing a reads ``a_null``) and to a-only
        entries when ``allow_b_nulls``. Local-only (tiles align).
        """
        assert self.grid == other.grid
        assert (self.nrows, self.ncols) == (other.nrows, other.ncols)
        # Nulls stay exact in the operand dtypes (hashable python scalars):
        # float() would corrupt int64 nulls beyond float64's exact range
        # and bool/object payload conventions.
        return _ewise_apply_jit(
            self, other, fn, allow_a_nulls, allow_b_nulls,
            np.asarray(a_null, self.dtype).item(),
            np.asarray(b_null, other.dtype).item(),
        )

    # --- elementwise union add (matrix +) ---------------------------------

    def ewise_add(
        self, other: "SpParMat", sr: Semiring, capacity: int | None = None
    ) -> "SpParMat":
        """C = A ⊕ B elementwise union: entries present in both are combined
        with ``sr.add``.

        Reference: ``SpParMat::operator+=`` (SpParMat.cpp:741) — there a
        local Dcsc merge; here a slot-array concat + compact (tiles align
        because grids and dims match, so no communication). Output capacity
        defaults to the sum of input capacities.
        """
        assert self.grid == other.grid
        assert (self.nrows, self.ncols) == (other.nrows, other.ncols)
        return _ewise_add_jit(self, other, sr, capacity)

    def add_loops(self, value) -> "SpParMat":
        """Set every diagonal entry to ``value`` (replacing any existing).

        Reference: ``SpParMat::AddLoops`` (SpParMat.cpp:3300-3341). Requires
        square blocking (local_rows == local_cols) so the diagonal lives in
        the (i,i) tiles. Output capacity grows by local_rows slots.
        """
        assert self.local_rows == self.local_cols, (
            "add_loops requires square blocking"
        )
        return _add_loops_jit(self, jnp.asarray(value, self.dtype))

    # --- per-column select / prune (the MCL support ops) -------------------

    def nnz_per_column(self) -> DistVec:
        """Col-aligned int32 vector of per-column nonzero counts.

        Reference: ``Reduce(Column, plus, 1)`` as used by
        MCLPruneRecoverySelect (ParFriends.h:186-350).
        """
        from ..semiring import PLUS_TIMES

        return self.reduce(PLUS_TIMES, "rows", map_fn=_ones_i32)

    def kselect(self, k) -> DistVec:
        """Per-column k-th largest value, as a col-aligned threshold vector.

        Reference: ``SpParMat::Kselect1`` (SpParMat.cpp:1120-1742) — there a
        chunked column gather + median-of-medians (TopKGather); here a
        radix-select over order-preserving 32-bit keys: 32 rounds of
        (per-column segment count + psum over the grid-row axis), fully
        jittable and free of data-dependent shapes.

        Columns with fewer than k entries get the dtype's minimum value
        ("keep everything" under a >= threshold test).  ``k`` is a positive
        int or a col-aligned int32 DistVec of per-column k's.
        """
        if isinstance(k, DistVec):
            return _kselect_jit(self, None, k.realign("col"))
        return _kselect_jit(self, int(k), None)

    def prune_column(self, vec: DistVec, keep) -> "SpParMat":
        """Keep entry (i,j) iff ``keep(val, vec[j])``.

        Reference: ``SpParMat::PruneColumn`` (SpParMat.cpp:2567-2779), with
        the predicate expressed as *keep* instead of prune.
        """
        return _prune_column_jit(self, vec.realign("col"), keep)

    def with_capacity(self, capacity: int) -> "SpParMat":
        """Grow or shrink every tile's slot capacity.

        Shrinking requires compacted tiles with max nnz <= capacity (checked
        host-side by ``shrink_to_fit``; under jit the caller guarantees it).
        """
        if capacity == self.capacity:
            return self
        return _with_capacity_jit(self, capacity)

    def shrink_to_fit(self, pow2: bool = True) -> "SpParMat":
        """Host helper: truncate capacity to the max tile nnz (optionally
        rounded up to a power of two for compile-cache stability).

        Keeps phased/iterative pipelines from dragging a large parent
        capacity through every collective (e.g. the col_split pieces of
        MemEfficientSpGEMM would otherwise all-gather full-size arrays).
        """
        need = max(int(np.max(np.asarray(self.nnz))), 1)
        if pow2:
            need = 1 << (need - 1).bit_length()
        return self.with_capacity(min(need, self.capacity))

    def prune_rowcol(self, rvec: DistVec, cvec: DistVec, keep) -> "SpParMat":
        """Keep entry (i,j) iff ``keep(val, rvec[i], cvec[j])``.

        The two-sided companion of ``prune_column`` — the zero-out step of
        SpAsgn (reference ``SpParMat::SpAsgn``, SpParMat.cpp:2427, expressed
        there as A - S*A*T with selection matrices; a direct masked prune is
        cheaper than two SpGEMMs).
        """
        return _prune_rowcol_jit(
            self, rvec.realign("row"), cvec.realign("col"), keep
        )

    # --- local column split / concat (phased execution) --------------------

    def col_split(self, nsplits: int) -> list["SpParMat"]:
        """Split into ``nsplits`` matrices, each holding every tile's s-th
        local column chunk.

        Reference: ``SpDCCols::ColSplit`` (SpDCCols.h:286, dcsc.h:103) — the
        phase splitter of MemEfficientSpGEMM (ParFriends.h:550-553). Like the
        reference, the split is LOCAL: globally the s-th output holds a
        strided family of column blocks, and ``col_concatenate`` restores the
        original order.  Requires no column padding and lc % nsplits == 0.
        """
        lc = self.local_cols
        assert self.ncols == lc * self.grid.pc, (
            "col_split requires ncols to divide evenly over the grid"
        )
        assert lc % nsplits == 0, f"local cols {lc} not divisible by {nsplits}"
        return list(_col_split_jit(self, nsplits))

    def row_split(self, nsplits: int) -> list["SpParMat"]:
        """Row-wise analog of ``col_split`` (≈ ``Dcsc::RowSplit``,
        dcsc.h / SpDCCols.h:281-284 — there the OpenMP threading split;
        here the row-block iterator of BlockSpGEMM)."""
        lr = self.local_rows
        assert self.nrows == lr * self.grid.pr, (
            "row_split requires nrows to divide evenly over the grid"
        )
        assert lr % nsplits == 0, f"local rows {lr} not divisible by {nsplits}"
        return list(_row_split_jit(self, nsplits))

    def kselect2(self, k: int):
        """(thresholds, any_active): ``Kselect2`` parity.

        Reference: ``SpParMat::Kselect2`` (SpParMat.h:137, SpParMat.cpp) —
        an alternative kth-largest implementation that iterates
        median-of-medians over only the columns with >= k entries and
        reports whether ANY column was active (callers skip the subsequent
        prune when none was, the "k_limit >= maxNnzInColumn" early-out).
        Here the radix-select computes the same thresholds for every
        column in one pass, so Kselect2 reduces to kselect plus the
        activity reduction.
        """
        th = self.kselect(k)
        active = self.nnz_per_column().blocks >= k
        return th, jnp.any(active)

    def block_split(
        self, row_blocks: int, col_blocks: int
    ) -> list[list["SpParMat"]]:
        """2D grid of submatrices: [row_blocks][col_blocks] pieces.

        Reference: ``SpParMat::BlockSplit`` (SpParMat.cpp:2974). Splits are
        LOCAL (each piece holds the matching chunk of every tile), composed
        from ``row_split`` x ``col_split``.
        """
        rows = self.row_split(row_blocks) if row_blocks > 1 else [self]
        return [
            r.col_split(col_blocks) if col_blocks > 1 else [r] for r in rows
        ]

    def induced_subgraphs(
        self, labels: DistVec, ngroups: int = 2
    ) -> list[tuple]:
        """Partition components into ``ngroups`` balanced groups and
        extract each group's induced subgraph.

        Reference: ``SpParMat::InducedSubgraphs2Procs``
        (SpParMat.cpp:4916) — HipMCL's post-clustering step that ships
        each cluster's induced subgraph to one of two process groups for
        recursive processing. Here every group's subgraph stays a
        first-class SpParMat on the SAME mesh (extraction is the SpRef
        A(vi, vi) path — two permutation SpGEMMs, SpParMat.cpp:2028);
        returns [(vertex_ids, subgraph), ...] with vertex_ids giving the
        original ids of each subgraph's rows (host arrays; the grouping
        decision is a host-side greedy bin-pack like the reference's).
        """
        from .indexing import subsref

        lab = np.asarray(labels.to_global())
        # vectorized grouping: component id -> member vertices
        uniq, inv = np.unique(lab, return_inverse=True)
        order = np.argsort(inv, kind="stable")
        counts = np.bincount(inv, minlength=len(uniq))
        bounds = np.concatenate([[0], np.cumsum(counts)])
        members = [
            order[bounds[i] : bounds[i + 1]] for i in range(len(uniq))
        ]
        # balanced greedy assignment, biggest components first
        sizes = sorted(members, key=len, reverse=True)
        groups = [[] for _ in range(ngroups)]
        loads = [0] * ngroups
        for verts in sizes:
            g = loads.index(min(loads))
            groups[g].extend(verts.tolist())
            loads[g] += len(verts)
        out = []
        for verts in groups:
            if not verts:
                continue
            vi = np.asarray(sorted(verts), dtype=np.int64)
            out.append((vi, subsref(self, vi, vi)))
        return out

    @staticmethod
    def col_concatenate(mats: list["SpParMat"]) -> "SpParMat":
        """Stitch ``col_split`` pieces (or phase outputs) back together.

        Reference: ``SpDCCols::ColConcatenate`` — the phase-output stitching
        of MemEfficientSpGEMM (ParFriends.h:700-720). Local-only; output
        capacity is the sum of piece capacities (not compacted).
        """
        ncols = sum(m.ncols for m in mats)
        assert ncols == sum(m.local_cols for m in mats) * mats[0].grid.pc
        return _col_concat_jit(tuple(mats))

    # --- reductions -------------------------------------------------------

    def reduce(self, sr: Semiring, axis: str, map_fn=None) -> DistVec:
        """Fold entries along ``axis`` with sr.add.

        axis="rows": fold each column's entries → col-aligned vec[ncols]
                     (reference Reduce(Column), SpParMat.cpp:888-1119).
        axis="cols": fold each row's entries → row-aligned vec[nrows]
                     (reference Reduce(Row)).
        map_fn transforms values before folding (the reference's __unary_op);
        pass a module-level function for compile-cache hits.
        """
        return _reduce_jit(self, sr, axis, map_fn)

    def square(self, sr: Semiring, slack: float = 1.05) -> "SpParMat":
        """A ⊗ A (≈ ``SpParMat::Square``, SpParMat.cpp:3456 — the MCL
        expansion step's unphased form)."""
        from .spgemm import spgemm

        return spgemm(sr, self, self, slack)

    # --- transpose --------------------------------------------------------

    def transpose(self) -> "SpParMat":
        """A^T via complement-rank tile exchange + local transpose.

        Reference: ``SpParMat::Transpose`` (SpParMat.cpp:3528-3585) — pairwise
        MPI exchange with GetComplementRank, here a single ``ppermute`` over
        both mesh axes. Square grids only (as is effectively true of the
        reference's vector-compatible usage).
        """
        assert self.grid.is_square, "transpose requires a square grid"
        return _transpose_jit(self)

    # --- scaling by distributed vectors -----------------------------------

    def dim_apply(self, vec: DistVec, fn, axis: str) -> "SpParMat":
        """Scale entries by a vector along a dimension.

        axis="cols": entry (i,j) ← fn(val, vec[j]) with col-aligned vec
                     (reference DimApply(Column), SpParMat.cpp:801).
        axis="rows": entry (i,j) ← fn(val, vec[i]) with row-aligned vec.
        """
        want_align = "col" if axis == "cols" else "row"
        return _dim_apply_jit(self, vec.realign(want_align), fn, axis)


def bucket_by_tile(
    grid: Grid, rows, cols, nrows: int, ncols: int, capacity: int | None
):
    """Shared host bucketing for tile constructors (SpParMat, SemanticGraph).

    Sorts global tuples by owner tile. Returns
    ``(rows_sorted, cols_sorted, order, counts, starts, cap, lr, lc)``;
    raises ValueError when an explicit ``capacity`` is too small.
    """
    rows = np.asarray(rows, dtype=np.int64)
    cols = np.asarray(cols, dtype=np.int64)
    lr, lc = grid.local_rows(nrows), grid.local_cols(ncols)
    tile_id = (rows // lr) * grid.pc + (cols // lc)
    order = np.argsort(tile_id, kind="stable")
    rows, cols = rows[order], cols[order]
    counts = np.bincount(tile_id, minlength=grid.size)
    cap = int(capacity) if capacity is not None else max(int(counts.max()), 1)
    if counts.max() > cap:
        raise ValueError(f"tile nnz {counts.max()} exceeds capacity {cap}")
    starts = np.concatenate([[0], np.cumsum(counts)])
    return rows, cols, order, counts, starts, cap, lr, lc


# --- module-level predicates / tile fns (stable identities for jit cache) --


def _pred_tril_strict(r, c):
    return r > c


def _pred_tril(r, c):
    return r >= c


def _pred_triu_strict(r, c):
    return r < c


def _pred_triu(r, c):
    return r <= c


def _pred_offdiag(r, c):
    return r != c


def ones_i32(v):
    """Structural-one map for ``reduce(map_fn=...)`` / ``apply`` callers.

    Module-level so repeated calls share one jit-cache entry (see the
    compilation-cache discipline note in the module docstring).
    """
    return jnp.ones(v.shape, jnp.int32)


def ones_f32(v):
    return jnp.ones(v.shape, jnp.float32)


_ones_i32 = ones_i32


@lru_cache(maxsize=None)
def _compact_fn(sr: Semiring, capacity: int | None = None):
    def f(t: SpTuples) -> SpTuples:
        return t.compact(sr, capacity=capacity)

    return f


# --- jitted impls ----------------------------------------------------------


@partial(jax.jit, static_argnames=("fn", "out_meta", "indexed"))
def _tile_map_jit(
    mat: SpParMat, fn, out_meta=None, indexed: bool = False
) -> SpParMat:
    nrows, ncols = out_meta if out_meta is not None else (mat.nrows, mat.ncols)
    lr, lc = mat.local_rows, mat.local_cols

    def body(rows, cols, vals, nnz):
        t = mat.local_tile(rows, cols, vals, nnz)
        if indexed:
            out = fn(
                t,
                (lax.axis_index(ROW_AXIS) * lr).astype(jnp.int32),
                (lax.axis_index(COL_AXIS) * lc).astype(jnp.int32),
            )
        else:
            out = fn(t)
        return SpParMat._pack_tile(out)

    r, c, v, n = jax.shard_map(
        body,
        mesh=mat.grid.mesh,
        in_specs=(TILE_SPEC,) * 4,
        out_specs=(TILE_SPEC,) * 4,
    )(mat.rows, mat.cols, mat.vals, mat.nnz)
    return dataclasses.replace(
        mat, rows=r, cols=c, vals=v, nnz=n, nrows=nrows, ncols=ncols
    )


@partial(jax.jit, static_argnames=("pred",))
def _keep_ij_jit(mat: SpParMat, pred) -> SpParMat:
    def f(t, ro, co):
        return t.select_ij(lambda r, c: pred(r + ro, c + co))

    return _tile_map_jit(mat, f, indexed=True)


@partial(jax.jit, static_argnames=("fn",))
def _apply_jit(mat: SpParMat, fn) -> SpParMat:
    return _tile_map_jit(mat, lambda t: t.apply(fn))


@partial(jax.jit, static_argnames=("pred",))
def _prune_jit(mat: SpParMat, pred) -> SpParMat:
    return _tile_map_jit(mat, lambda t: t.prune(pred))


@partial(jax.jit, static_argnames=("negate", "combine"))
def _ewise_mult_jit(
    a: SpParMat, b: SpParMat, negate: bool, combine
) -> SpParMat:
    from ..ops.ewise import ewise_mult as _ewise_mult

    return _tile_zip_jit(
        a, b, _EwiseMultFn(negate, combine)
    )


class _EwiseMultFn:
    """Hashable wrapper so (negate, combine) pairs key the jit cache."""

    def __init__(self, negate, combine):
        self.negate, self.combine = negate, combine

    def __call__(self, x, y):
        from ..ops.ewise import ewise_mult as _ewise_mult

        return _ewise_mult(x, y, negate=self.negate, combine=self.combine)

    def __hash__(self):
        return hash(("_EwiseMultFn", self.negate, self.combine))

    def __eq__(self, other):
        return (
            isinstance(other, _EwiseMultFn)
            and (self.negate, self.combine) == (other.negate, other.combine)
        )


@partial(jax.jit, static_argnames=("fn",))
def _tile_zip_jit(a: SpParMat, b: SpParMat, fn) -> SpParMat:
    def body(ar, ac, av, an, br, bc, bv, bn):
        ta = a.local_tile(ar, ac, av, an)
        tb = b.local_tile(br, bc, bv, bn)
        return SpParMat._pack_tile(fn(ta, tb))

    r, c, v, n = jax.shard_map(
        body,
        mesh=a.grid.mesh,
        in_specs=(TILE_SPEC,) * 8,
        out_specs=(TILE_SPEC,) * 4,
    )(a.rows, a.cols, a.vals, a.nnz, b.rows, b.cols, b.vals, b.nnz)
    return dataclasses.replace(a, rows=r, cols=c, vals=v, nnz=n)


@partial(
    jax.jit,
    static_argnames=(
        "fn", "allow_a_nulls", "allow_b_nulls", "a_null", "b_null",
    ),
)
def _ewise_apply_jit(
    a: SpParMat, b: SpParMat, fn, allow_a_nulls, allow_b_nulls, a_null,
    b_null,
) -> SpParMat:
    from ..ops.ewise import ewise_apply as _ewise_apply

    def tile_fn(ta, tb):
        return _ewise_apply(
            ta, tb, fn,
            allow_a_nulls=allow_a_nulls, allow_b_nulls=allow_b_nulls,
            a_null=a_null, b_null=b_null,
        )

    return _tile_zip_jit(a, b, tile_fn)


@partial(jax.jit, static_argnames=("sr", "capacity"))
def _ewise_add_jit(
    a: SpParMat, b: SpParMat, sr: Semiring, capacity: int | None
) -> SpParMat:
    comb = dataclasses.replace(
        a,
        rows=jnp.concatenate([a.rows, b.rows], axis=2),
        cols=jnp.concatenate([a.cols, b.cols], axis=2),
        vals=jnp.concatenate([a.vals, b.vals], axis=2),
        nnz=a.nnz + b.nnz,
    )
    return _tile_map_jit(comb, _compact_fn(sr, capacity))


@jax.jit
def _add_loops_jit(mat: SpParMat, value) -> SpParMat:
    lr, lc = mat.local_rows, mat.local_cols
    ndiag = min(mat.nrows, mat.ncols)
    dtype = mat.dtype

    def f(t: SpTuples, ro, co):
        base = t.select_ij(lambda r, c: (r + ro) != (c + co))
        d = jnp.arange(lr, dtype=jnp.int32)
        ok = (ro == co) & ((d + ro) < ndiag)
        extra = SpTuples(
            rows=jnp.where(ok, d, lr),
            cols=jnp.where(ok, d, lc),
            vals=jnp.full((lr,), value, dtype),
            nnz=jnp.sum(ok).astype(jnp.int32),
            nrows=t.nrows,
            ncols=t.ncols,
        )
        return SpTuples.concat([base, extra])

    return _tile_map_jit(mat, f, indexed=True)


@partial(jax.jit, static_argnames=("k",))
def _kselect_jit(mat: SpParMat, k, kvec: DistVec | None) -> DistVec:
    lc = mat.local_cols
    dtype = mat.dtype

    def body(rows, cols, vals, nnz, *maybe_k):
        t = mat.local_tile(rows, cols, vals, nnz)
        keys = _monotone_key_u32(t.vals)
        valid = t.valid_mask()
        ids = jnp.where(valid, t.cols, lc)
        idx = jnp.minimum(ids, lc - 1)
        kcol = (
            maybe_k[0][0].astype(jnp.int32)
            if maybe_k
            else jnp.full((lc,), k, jnp.int32)
        )

        def col_count(ge_mask):
            local = jax.ops.segment_sum(
                ge_mask.astype(jnp.int32), ids, num_segments=lc
            )
            return lax.psum(local, ROW_AXIS)

        total = col_count(valid)
        nbits = _key_bits(dtype)
        kt = keys.dtype
        thresh = jnp.zeros((lc,), kt)
        for b in range(nbits - 1, -1, -1):
            cand = thresh | jnp.asarray(1 << b, kt)
            cnt = col_count(valid & (keys >= cand[idx]))
            thresh = jnp.where(cnt >= kcol, cand, thresh)
        out = _u32_key_to_val(thresh, dtype)
        out = jnp.where(total < kcol, _minval(dtype), out)
        return out[None]

    args = (mat.rows, mat.cols, mat.vals, mat.nnz) + (
        (kvec.blocks,) if kvec is not None else ()
    )
    vspecs = (P(COL_AXIS),) if kvec is not None else ()
    blocks = jax.shard_map(
        body,
        mesh=mat.grid.mesh,
        in_specs=(TILE_SPEC,) * 4 + vspecs,
        out_specs=P(COL_AXIS),
        check_vma=False,
    )(*args)
    return DistVec(blocks=blocks, length=mat.ncols, align="col", grid=mat.grid)


@partial(jax.jit, static_argnames=("keep",))
def _prune_column_jit(mat: SpParMat, vec: DistVec, keep) -> SpParMat:
    def body(rows, cols, vals, nnz, vblk):
        t = mat.local_tile(rows, cols, vals, nnz)
        v = vblk[0]
        idx = jnp.minimum(t.cols, v.shape[0] - 1)
        keepmask = t.valid_mask() & keep(t.vals, v[idx])
        return SpParMat._pack_tile(t._select(keepmask))

    r, c, v, n = jax.shard_map(
        body,
        mesh=mat.grid.mesh,
        in_specs=(TILE_SPEC,) * 4 + (P(COL_AXIS),),
        out_specs=(TILE_SPEC,) * 4,
    )(mat.rows, mat.cols, mat.vals, mat.nnz, vec.blocks)
    return dataclasses.replace(mat, rows=r, cols=c, vals=v, nnz=n)


@partial(jax.jit, static_argnames=("capacity",))
def _with_capacity_jit(mat: SpParMat, capacity: int) -> SpParMat:
    return _tile_map_jit(mat, _with_capacity_fn(capacity))


@lru_cache(maxsize=None)
def _with_capacity_fn(capacity: int):
    def f(t: SpTuples) -> SpTuples:
        return t.with_capacity(capacity)

    return f


@partial(jax.jit, static_argnames=("keep",))
def _prune_rowcol_jit(
    mat: SpParMat, rvec: DistVec, cvec: DistVec, keep
) -> SpParMat:
    def body(rows, cols, vals, nnz, rblk, cblk):
        t = mat.local_tile(rows, cols, vals, nnz)
        rv, cv = rblk[0], cblk[0]
        rpad = jnp.concatenate([rv, jnp.zeros((1,), rv.dtype)])
        cpad = jnp.concatenate([cv, jnp.zeros((1,), cv.dtype)])
        ri = jnp.minimum(t.rows, rv.shape[0])
        ci = jnp.minimum(t.cols, cv.shape[0])
        keepmask = t.valid_mask() & keep(t.vals, rpad[ri], cpad[ci])
        return SpParMat._pack_tile(t._select(keepmask))

    r, c, v, n = jax.shard_map(
        body,
        mesh=mat.grid.mesh,
        in_specs=(TILE_SPEC,) * 4 + (P(ROW_AXIS), P(COL_AXIS)),
        out_specs=(TILE_SPEC,) * 4,
    )(mat.rows, mat.cols, mat.vals, mat.nnz, rvec.blocks, cvec.blocks)
    return dataclasses.replace(mat, rows=r, cols=c, vals=v, nnz=n)


@partial(jax.jit, static_argnames=("nsplits",))
def _col_split_jit(mat: SpParMat, nsplits: int):
    lc = mat.local_cols
    lw = lc // nsplits
    outs = []
    for s in range(nsplits):
        lo = s * lw

        def f(t: SpTuples, lo=lo):
            keep = t.valid_mask() & (t.cols >= lo) & (t.cols < lo + lw)
            sel = t._select(keep)
            cols = jnp.where(sel.valid_mask(), sel.cols - lo, lw)
            return SpTuples(
                rows=sel.rows, cols=cols, vals=sel.vals, nnz=sel.nnz,
                nrows=t.nrows, ncols=lw,
            )

        outs.append(
            _tile_map_jit(mat, f, out_meta=(mat.nrows, lw * mat.grid.pc))
        )
    return tuple(outs)


@partial(jax.jit, static_argnames=("nsplits",))
def _row_split_jit(mat: SpParMat, nsplits: int):
    lr = mat.local_rows
    lw = lr // nsplits
    outs = []
    for s in range(nsplits):
        lo = s * lw

        def f(t: SpTuples, lo=lo):
            keep = t.valid_mask() & (t.rows >= lo) & (t.rows < lo + lw)
            sel = t._select(keep)  # padding already carries (nrows, ncols)
            rows = jnp.where(sel.valid_mask(), sel.rows - lo, lw)
            return SpTuples(
                rows=rows, cols=sel.cols, vals=sel.vals, nnz=sel.nnz,
                nrows=lw, ncols=t.ncols,
            )

        outs.append(
            _tile_map_jit(mat, f, out_meta=(lw * mat.grid.pr, mat.ncols))
        )
    return tuple(outs)


@jax.jit
def _col_concat_jit(mats: tuple) -> SpParMat:
    g = mats[0].grid
    lcs = [m.local_cols for m in mats]
    lc_out = sum(lcs)
    ncols = sum(m.ncols for m in mats)
    pieces, off = [], 0
    for m, w in zip(mats, lcs):

        def f(t: SpTuples, off=off):
            cols = jnp.where(t.valid_mask(), t.cols + off, lc_out)
            return dataclasses.replace(t, cols=cols)

        pieces.append(_tile_map_jit(m, f))
        off += w
    return SpParMat(
        rows=jnp.concatenate([p.rows for p in pieces], axis=2),
        cols=jnp.concatenate([p.cols for p in pieces], axis=2),
        vals=jnp.concatenate([p.vals for p in pieces], axis=2),
        nnz=sum(p.nnz for p in pieces[1:]) + pieces[0].nnz,
        nrows=mats[0].nrows,
        ncols=ncols,
        grid=g,
    )


@partial(jax.jit, static_argnames=("sr", "axis", "map_fn"))
def _reduce_jit(mat: SpParMat, sr: Semiring, axis: str, map_fn) -> DistVec:
    lr, lc = mat.local_rows, mat.local_cols
    out_len = mat.ncols if axis == "rows" else mat.nrows
    align = "col" if axis == "rows" else "row"
    comm_axis = ROW_AXIS if axis == "rows" else COL_AXIS
    seg_n = lc if axis == "rows" else lr

    def body(rows, cols, vals, nnz):
        t = mat.local_tile(rows, cols, vals, nnz)
        v = map_fn(t.vals) if map_fn is not None else t.vals
        ids = t.cols if axis == "rows" else t.rows
        local = segment_reduce(sr, v, ids, seg_n)
        return axis_reduce(sr, local, comm_axis)[None]

    out_specs = P(COL_AXIS) if axis == "rows" else P(ROW_AXIS)
    blocks = jax.shard_map(
        body,
        mesh=mat.grid.mesh,
        in_specs=(TILE_SPEC,) * 4,
        out_specs=out_specs,
    )(mat.rows, mat.cols, mat.vals, mat.nnz)
    return DistVec(blocks=blocks, length=out_len, align=align, grid=mat.grid)


@jax.jit
def _transpose_jit(mat: SpParMat) -> SpParMat:
    grid = mat.grid
    perm = grid.transpose_perm()

    def body(rows, cols, vals, nnz):
        t = mat.local_tile(rows, cols, vals, nnz).transpose()
        packed = SpParMat._pack_tile(t)
        return tuple(
            lax.ppermute(x, (ROW_AXIS, COL_AXIS), perm) for x in packed
        )

    r, c, v, n = jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(TILE_SPEC,) * 4,
        out_specs=(TILE_SPEC,) * 4,
    )(mat.rows, mat.cols, mat.vals, mat.nnz)
    return SpParMat(
        rows=r, cols=c, vals=v, nnz=n,
        nrows=mat.ncols, ncols=mat.nrows, grid=grid,
    )


@partial(jax.jit, static_argnames=("fn", "axis"))
def _dim_apply_jit(mat: SpParMat, vec: DistVec, fn, axis: str) -> SpParMat:
    vspec = P(COL_AXIS) if axis == "cols" else P(ROW_AXIS)

    def body(rows, cols, vals, nnz, vblk):
        t = mat.local_tile(rows, cols, vals, nnz)
        v = vblk[0]
        vpad = jnp.concatenate([v, jnp.zeros((1,), v.dtype)])
        idx = t.cols if axis == "cols" else t.rows
        idx = jnp.minimum(idx, v.shape[0])
        new_vals = jnp.where(t.valid_mask(), fn(t.vals, vpad[idx]), t.vals)
        return SpParMat._pack_tile(dataclasses.replace(t, vals=new_vals))

    r, c, v, n = jax.shard_map(
        body,
        mesh=mat.grid.mesh,
        in_specs=(TILE_SPEC,) * 4 + (vspec,),
        out_specs=(TILE_SPEC,) * 4,
    )(mat.rows, mat.cols, mat.vals, mat.nnz, vec.blocks)
    return dataclasses.replace(mat, rows=r, cols=c, vals=v, nnz=n)
