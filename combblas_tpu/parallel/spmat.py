"""SpParMat — the distributed 2D sparse matrix (≈ SpParMat<IT,NT,DER>).

The reference's core object (``include/CombBLAS/SpParMat.h:67-452``,
``SpParMat.cpp``: 5,125 lines) owns a CommGrid plus one local sequential
matrix per process.  The TPU-native re-design stores ALL tiles as stacked
global arrays of shape ``[pr, pc, capacity]`` sharded so device (i,j) holds
tile (i,j) — a single jittable pytree instead of p per-process objects.  The
"decoupling of parallel logic from sequential kernels" that the reference
achieves with the DER template parameter (``SpMat.h:54``) is achieved here by
every distributed op being ``shard_map(local-kernel-on-SpTuples)``: swap the
local kernel, keep the schedule.

Tile-local indices are int32; padding slots hold (local_rows, local_cols).
Global dims are padded to ceil-multiples of the grid shape (see grid.py).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.segment import segment_reduce
from ..ops.tuples import SpTuples
from ..semiring import Semiring
from .collectives import axis_reduce
from .grid import COL_AXIS, ROW_AXIS, Grid
from .vec import DistVec

Array = jax.Array

TILE_SPEC = P(ROW_AXIS, COL_AXIS)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["rows", "cols", "vals", "nnz"],
    meta_fields=["nrows", "ncols", "grid"],
)
@dataclasses.dataclass(frozen=True)
class SpParMat:
    """Distributed sparse matrix over a 2D grid.

    rows/cols: int32[pr, pc, cap] tile-local indices (padding = lr/lc).
    vals: NT[pr, pc, cap].
    nnz: int32[pr, pc] valid counts per tile.
    """

    rows: Array
    cols: Array
    vals: Array
    nnz: Array
    nrows: int
    ncols: int
    grid: Grid

    # --- static geometry --------------------------------------------------

    @property
    def capacity(self) -> int:
        return self.rows.shape[2]

    @property
    def local_rows(self) -> int:
        return self.grid.local_rows(self.nrows)

    @property
    def local_cols(self) -> int:
        return self.grid.local_cols(self.ncols)

    @property
    def dtype(self):
        return self.vals.dtype

    def getnnz(self) -> Array:
        """Total nonzeros. Reference: ``SpParMat::getnnz``."""
        return jnp.sum(self.nnz)

    def load_imbalance(self) -> Array:
        """max/avg tile nnz. Reference: ``SpParMat::LoadImbalance``."""
        return jnp.max(self.nnz) * self.grid.size / jnp.maximum(jnp.sum(self.nnz), 1)

    # --- tile pytree <-> shard_map plumbing -------------------------------

    def local_tile(self, rows, cols, vals, nnz) -> SpTuples:
        """Wrap per-device slices ([1,1,cap] / [1,1]) as a local SpTuples."""
        return SpTuples(
            rows=rows[0, 0],
            cols=cols[0, 0],
            vals=vals[0, 0],
            nnz=nnz[0, 0],
            nrows=self.local_rows,
            ncols=self.local_cols,
        )

    @staticmethod
    def _pack_tile(t: SpTuples):
        return (
            t.rows[None, None], t.cols[None, None], t.vals[None, None],
            t.nnz[None, None],
        )

    def tile_map(self, fn, out_like: "SpParMat | None" = None) -> "SpParMat":
        """Apply ``fn: SpTuples -> SpTuples`` to every tile (no comm).

        The local-kernel dispatch boundary — the analog of calling into the
        DER layer from SpParMat methods.
        """
        ref = out_like if out_like is not None else self

        def body(rows, cols, vals, nnz):
            out = fn(self.local_tile(rows, cols, vals, nnz))
            return SpParMat._pack_tile(out)

        r, c, v, n = jax.shard_map(
            body,
            mesh=self.grid.mesh,
            in_specs=(TILE_SPEC, TILE_SPEC, TILE_SPEC, TILE_SPEC),
            out_specs=(TILE_SPEC, TILE_SPEC, TILE_SPEC, TILE_SPEC),
        )(self.rows, self.cols, self.vals, self.nnz)
        return dataclasses.replace(ref, rows=r, cols=c, vals=v, nnz=n)

    def tile_map_indexed(self, fn) -> "SpParMat":
        """Apply ``fn(tile, row_offset, col_offset) -> tile`` per tile.

        Offsets are the tile's global (row, col) origin, computed from the
        mesh position — how a local kernel learns its place in the global
        matrix (the reference threads this through CommGrid rank math).
        """
        lr, lc = self.local_rows, self.local_cols
        return self.tile_map(
            lambda t: fn(
                t,
                (lax.axis_index(ROW_AXIS) * lr).astype(jnp.int32),
                (lax.axis_index(COL_AXIS) * lc).astype(jnp.int32),
            )
        )

    def keep_ij(self, pred) -> "SpParMat":
        """Keep entries where ``pred(global_row, global_col)`` is True.

        Reference: ``SpParMat::PruneI`` (index-based prune family)."""
        return self.tile_map_indexed(
            lambda t, ro, co: t.select_ij(lambda r, c: pred(r + ro, c + co))
        )

    def tril(self, strict: bool = True) -> "SpParMat":
        """Lower-triangular part (strict by default — the TC mask,
        ``TC.cpp:104``)."""
        return self.keep_ij((lambda r, c: r > c) if strict else (lambda r, c: r >= c))

    def triu(self, strict: bool = True) -> "SpParMat":
        return self.keep_ij((lambda r, c: r < c) if strict else (lambda r, c: r <= c))

    def remove_loops(self) -> "SpParMat":
        """Drop diagonal entries. Reference: ``SpParMat::RemoveLoops``
        (SpParMat.cpp:3257)."""
        return self.keep_ij(lambda r, c: r != c)

    # --- construction -----------------------------------------------------

    @staticmethod
    def from_global_coo(
        grid: Grid,
        rows,
        cols,
        vals,
        nrows: int,
        ncols: int,
        capacity: int | None = None,
        dedup_sr: Semiring | None = None,
    ) -> "SpParMat":
        """Host-side construction: bucket global tuples by owner tile.

        The host analog of the reference's tuple-Alltoallv redistribution
        ``SparseCommon`` (SpParMat.cpp:2893-2968); the fully on-device
        redistribution lives in ``parallel/redistribute.py``.
        """
        rows = np.asarray(rows, dtype=np.int64)
        cols = np.asarray(cols, dtype=np.int64)
        vals = np.asarray(vals)
        lr, lc = grid.local_rows(nrows), grid.local_cols(ncols)
        oi = rows // lr
        oj = cols // lc
        tile_id = oi * grid.pc + oj
        order = np.argsort(tile_id, kind="stable")
        rows, cols, vals, tile_id = (
            rows[order], cols[order], vals[order], tile_id[order],
        )
        counts = np.bincount(tile_id, minlength=grid.size)
        cap = int(capacity) if capacity is not None else max(int(counts.max()), 1)
        if counts.max() > cap:
            raise ValueError(f"tile nnz {counts.max()} exceeds capacity {cap}")
        pr_, pc_ = grid.pr, grid.pc
        R = np.full((pr_, pc_, cap), lr, dtype=np.int32)
        C = np.full((pr_, pc_, cap), lc, dtype=np.int32)
        V = np.zeros((pr_, pc_, cap), dtype=vals.dtype)
        starts = np.concatenate([[0], np.cumsum(counts)])
        for t in range(grid.size):
            i, j = divmod(t, pc_)
            s, e = starts[t], starts[t + 1]
            n = e - s
            R[i, j, :n] = rows[s:e] - i * lr
            C[i, j, :n] = cols[s:e] - j * lc
            V[i, j, :n] = vals[s:e]
        sharding = grid.tile_sharding()
        mat = SpParMat(
            rows=jax.device_put(jnp.asarray(R), sharding),
            cols=jax.device_put(jnp.asarray(C), sharding),
            vals=jax.device_put(jnp.asarray(V), sharding),
            nnz=jax.device_put(jnp.asarray(counts.reshape(pr_, pc_), jnp.int32), sharding),
            nrows=int(nrows),
            ncols=int(ncols),
            grid=grid,
        )
        if dedup_sr is not None:
            mat = mat.tile_map(lambda t: t.compact(dedup_sr))
        return mat

    @staticmethod
    def from_dense(grid: Grid, dense, capacity=None, dedup_sr=None) -> "SpParMat":
        dense = np.asarray(dense)
        r, c = np.nonzero(dense)
        return SpParMat.from_global_coo(
            grid, r, c, dense[r, c], dense.shape[0], dense.shape[1],
            capacity=capacity, dedup_sr=dedup_sr,
        )

    # --- host access (tests) ----------------------------------------------

    def to_global_coo(self):
        lr, lc = self.local_rows, self.local_cols
        R = np.asarray(self.rows)
        C = np.asarray(self.cols)
        V = np.asarray(self.vals)
        N = np.asarray(self.nnz)
        out_r, out_c, out_v = [], [], []
        for i in range(self.grid.pr):
            for j in range(self.grid.pc):
                n = N[i, j]
                out_r.append(R[i, j, :n].astype(np.int64) + i * lr)
                out_c.append(C[i, j, :n].astype(np.int64) + j * lc)
                out_v.append(V[i, j, :n])
        return (
            np.concatenate(out_r), np.concatenate(out_c), np.concatenate(out_v),
        )

    def to_dense(self) -> np.ndarray:
        r, c, v = self.to_global_coo()
        out = np.zeros((self.nrows, self.ncols), dtype=v.dtype)
        np.add.at(out, (r, c), v)
        return out

    # --- elementwise / structural (no communication) ----------------------

    def apply(self, fn) -> "SpParMat":
        """Reference: ``SpParMat::Apply`` (SpParMat.h:148)."""
        return self.tile_map(lambda t: t.apply(fn))

    def prune(self, pred) -> "SpParMat":
        """Drop entries where pred(val). Reference: ``SpParMat::Prune``."""
        return self.tile_map(lambda t: t.prune(pred))

    def ewise_mult(
        self, other: "SpParMat", negate: bool = False, combine=None
    ) -> "SpParMat":
        """A .* structure(B) (negate=False) or A .* !structure(B).

        Reference: ``EWiseMult`` (ParFriends.h:2157-2244). Local-only: grids
        and shapes must match, so tiles align elementwise.
        """
        assert self.grid == other.grid
        assert (self.nrows, self.ncols) == (other.nrows, other.ncols)
        from ..ops.ewise import ewise_mult as _ewise_mult

        return self._tile_zip(
            lambda a, b: _ewise_mult(a, b, negate=negate, combine=combine), other
        )

    def _tile_zip(self, fn, other: "SpParMat") -> "SpParMat":
        def body(ar, ac, av, an, br, bc, bv, bn):
            a = self.local_tile(ar, ac, av, an)
            b = other.local_tile(br, bc, bv, bn)
            return SpParMat._pack_tile(fn(a, b))

        specs = (TILE_SPEC,) * 8
        r, c, v, n = jax.shard_map(
            body,
            mesh=self.grid.mesh,
            in_specs=specs,
            out_specs=(TILE_SPEC,) * 4,
        )(
            self.rows, self.cols, self.vals, self.nnz,
            other.rows, other.cols, other.vals, other.nnz,
        )
        return dataclasses.replace(self, rows=r, cols=c, vals=v, nnz=n)

    # --- reductions -------------------------------------------------------

    def reduce(self, sr: Semiring, axis: str, map_fn=None) -> DistVec:
        """Fold entries along ``axis`` with sr.add.

        axis="rows": fold each column's entries → col-aligned vec[ncols]
                     (reference Reduce(Column), SpParMat.cpp:888-1119).
        axis="cols": fold each row's entries → row-aligned vec[nrows]
                     (reference Reduce(Row)).
        map_fn transforms values before folding (the reference's __unary_op).
        """
        lr, lc = self.local_rows, self.local_cols
        out_len = self.ncols if axis == "rows" else self.nrows
        align = "col" if axis == "rows" else "row"
        comm_axis = ROW_AXIS if axis == "rows" else COL_AXIS
        seg_n = lc if axis == "rows" else lr

        def body(rows, cols, vals, nnz):
            t = self.local_tile(rows, cols, vals, nnz)
            v = map_fn(t.vals) if map_fn is not None else t.vals
            ids = t.cols if axis == "rows" else t.rows
            local = segment_reduce(sr, v, ids, seg_n)
            return axis_reduce(sr, local, comm_axis)[None]

        out_specs = P(COL_AXIS) if axis == "rows" else P(ROW_AXIS)
        blocks = jax.shard_map(
            body,
            mesh=self.grid.mesh,
            in_specs=(TILE_SPEC,) * 4,
            out_specs=out_specs,
        )(self.rows, self.cols, self.vals, self.nnz)
        return DistVec(
            blocks=blocks, length=out_len, align=align, grid=self.grid
        )

    # --- transpose --------------------------------------------------------

    def transpose(self) -> "SpParMat":
        """A^T via complement-rank tile exchange + local transpose.

        Reference: ``SpParMat::Transpose`` (SpParMat.cpp:3528-3585) — pairwise
        MPI exchange with GetComplementRank, here a single ``ppermute`` over
        both mesh axes. Square grids only (as is effectively true of the
        reference's vector-compatible usage).
        """
        grid = self.grid
        assert grid.is_square, "transpose requires a square grid"
        perm = grid.transpose_perm()

        def body(rows, cols, vals, nnz):
            t = self.local_tile(rows, cols, vals, nnz).transpose()
            packed = SpParMat._pack_tile(t)
            return tuple(
                lax.ppermute(x, (ROW_AXIS, COL_AXIS), perm) for x in packed
            )

        r, c, v, n = jax.shard_map(
            body,
            mesh=grid.mesh,
            in_specs=(TILE_SPEC,) * 4,
            out_specs=(TILE_SPEC,) * 4,
        )(self.rows, self.cols, self.vals, self.nnz)
        return SpParMat(
            rows=r, cols=c, vals=v, nnz=n,
            nrows=self.ncols, ncols=self.nrows, grid=grid,
        )

    # --- scaling by distributed vectors -----------------------------------

    def dim_apply(self, vec: DistVec, fn, axis: str) -> "SpParMat":
        """Scale entries by a vector along a dimension.

        axis="cols": entry (i,j) ← fn(val, vec[j]) with col-aligned vec
                     (reference DimApply(Column), SpParMat.cpp:801).
        axis="rows": entry (i,j) ← fn(val, vec[i]) with row-aligned vec.
        """
        want_align = "col" if axis == "cols" else "row"
        vec = vec.realign(want_align)
        vspec = P(COL_AXIS) if axis == "cols" else P(ROW_AXIS)

        def body(rows, cols, vals, nnz, vblk):
            t = self.local_tile(rows, cols, vals, nnz)
            v = vblk[0]
            vpad = jnp.concatenate([v, jnp.zeros((1,), v.dtype)])
            idx = t.cols if axis == "cols" else t.rows
            idx = jnp.minimum(idx, v.shape[0])
            new_vals = jnp.where(
                t.valid_mask(), fn(t.vals, vpad[idx]), t.vals
            )
            return SpParMat._pack_tile(
                dataclasses.replace(t, vals=new_vals)
            )

        r, c, v, n = jax.shard_map(
            body,
            mesh=self.grid.mesh,
            in_specs=(TILE_SPEC,) * 4 + (vspec,),
            out_specs=(TILE_SPEC,) * 4,
        )(self.rows, self.cols, self.vals, self.nnz, vec.blocks)
        return dataclasses.replace(self, rows=r, cols=c, vals=v, nnz=n)
