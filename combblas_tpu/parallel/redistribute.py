"""On-device tuple redistribution (≈ SpParMat::SparseCommon).

The reference routes arbitrarily-placed (i, j, v) tuples to their owner
tiles with one MPI_Alltoallv (``SpParMat.cpp:2893-2968``) — the engine
behind matrix construction from generated edge lists
(``SpParMat(DistEdgeList&)``, SpParMat.cpp:3140-3255). The TPU-native
counterpart keeps everything in HBM: each device holds a chunk of global
tuples (e.g. straight out of the on-device R-MAT generator) and routing is
two fixed-capacity ``all_to_all`` hops over the mesh axes — first by owner
column along "c", then by owner row along "r" (classic 2D dimension-ordered
routing; the ragged Alltoallv becomes padded buckets plus an overflow
count, the static-shape contract of SURVEY §7's hard-parts list).

Capacities: ``stage_capacity`` bounds one destination bucket on one device
per hop. Tuples beyond a full bucket are dropped and COUNTED — callers
check the returned drop count (host-side, once) and retry with a larger
capacity; with ``slack`` ≈ 2x over the balanced load this is rare (R-MAT's
per-tile skew is bounded by the hub rows).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import obs
from ..ops.tuples import SpTuples
from ..semiring import Semiring
from .grid import COL_AXIS, ROW_AXIS, Grid
from .spmat import SpParMat, TILE_SPEC

Array = jax.Array


def _bucket_route(dest, rows, cols, vals, ndest, cap, pad_row, pad_col):
    """Scatter tuples into [ndest, cap] padded buckets by ``dest`` id.

    Returns (rows, cols, vals, counts, dropped): slots beyond a bucket's
    capacity are dropped (counted). Padding slots carry (pad_row, pad_col).
    """
    # position of each tuple within its destination bucket
    one = jnp.ones_like(dest)
    within = (
        jnp.zeros((ndest,), jnp.int32)
        .at[dest]
        .add(one, mode="drop")
    )
    # stable per-destination offsets via sort by dest
    order = jnp.argsort(dest, stable=True)
    dsorted = dest[order]
    first = jnp.concatenate(
        [jnp.ones((1,), bool), dsorted[1:] != dsorted[:-1]]
    )
    pos_in_run = jnp.arange(dest.shape[0]) - jax.lax.associative_scan(
        jnp.maximum, jnp.where(first, jnp.arange(dest.shape[0]), 0)
    )
    slot = dsorted * cap + pos_in_run
    ok = (pos_in_run < cap) & (dsorted < ndest)
    slot = jnp.where(ok, slot, ndest * cap)
    br = jnp.full((ndest * cap,), pad_row, jnp.int32).at[slot].set(
        rows[order], mode="drop"
    )
    bc = jnp.full((ndest * cap,), pad_col, jnp.int32).at[slot].set(
        cols[order], mode="drop"
    )
    bv = jnp.zeros((ndest * cap,), vals.dtype).at[slot].set(
        vals[order], mode="drop"
    )
    dropped = jnp.sum(jnp.maximum(within - cap, 0))
    return (
        br.reshape(ndest, cap),
        bc.reshape(ndest, cap),
        bv.reshape(ndest, cap),
        dropped,
    )


@partial(
    jax.jit,
    static_argnames=("grid", "nrows", "ncols", "stage_capacity",
                     "tile_capacity", "dedup_sr"),
)
def redistribute_coo(
    grid: Grid,
    rows: Array,
    cols: Array,
    vals: Array,
    nrows: int,
    ncols: int,
    *,
    stage_capacity: int,
    tile_capacity: int,
    dedup_sr: Semiring | None = None,
) -> tuple[SpParMat, Array]:
    """Route device-resident global tuples to their owner tiles.

    rows/cols/vals: [pr, pc, chunk] — each device's arbitrary chunk of
    GLOBAL tuples (invalid slots: row >= nrows). Returns (SpParMat, total
    dropped tuple count) — check the count host-side once, after
    construction. The tile-overflow term counts DISTINCT keys when
    ``dedup_sr`` is set, so a zero count always means a complete matrix.
    """
    if obs.ENABLED:
        # trace-time only (jitted): counts (re)traces per static config
        obs.count("trace.redistribute_coo")
    lr = -(-nrows // grid.pr)
    lc = -(-ncols // grid.pc)
    pr_, pc_ = grid.pr, grid.pc

    def body(r, c, v):
        r0, c0, v0 = r[0, 0], c[0, 0], v[0, 0]
        valid = r0 < nrows
        # hop 1: route by owner COLUMN along the "c" axis
        oj = jnp.where(valid, c0 // lc, pc_)
        br, bc, bv, drop1 = _bucket_route(
            oj.astype(jnp.int32), r0, c0, v0, pc_, stage_capacity,
            jnp.int32(nrows), jnp.int32(ncols),
        )
        br = lax.all_to_all(br, COL_AXIS, split_axis=0, concat_axis=0)
        bc = lax.all_to_all(bc, COL_AXIS, split_axis=0, concat_axis=0)
        bv = lax.all_to_all(bv, COL_AXIS, split_axis=0, concat_axis=0)
        r1, c1, v1 = br.reshape(-1), bc.reshape(-1), bv.reshape(-1)
        # hop 2: route by owner ROW along the "r" axis
        valid1 = r1 < nrows
        oi = jnp.where(valid1, r1 // lr, pr_)
        br2, bc2, bv2, drop2 = _bucket_route(
            oi.astype(jnp.int32), r1, c1, v1, pr_, stage_capacity,
            jnp.int32(nrows), jnp.int32(ncols),
        )
        br2 = lax.all_to_all(br2, ROW_AXIS, split_axis=0, concat_axis=0)
        bc2 = lax.all_to_all(bc2, ROW_AXIS, split_axis=0, concat_axis=0)
        bv2 = lax.all_to_all(bv2, ROW_AXIS, split_axis=0, concat_axis=0)
        r2, c2, v2 = br2.reshape(-1), bc2.reshape(-1), bv2.reshape(-1)
        # localize to tile indices (padding maps to the sentinel)
        i = lax.axis_index(ROW_AXIS)
        j = lax.axis_index(COL_AXIS)
        ok = r2 < nrows
        lrow = jnp.where(ok, r2 - i * lr, lr).astype(jnp.int32)
        lcol = jnp.where(ok, c2 - j * lc, lc).astype(jnp.int32)
        t = SpTuples(
            rows=lrow, cols=lcol, vals=jnp.where(ok, v2, 0),
            nnz=jnp.sum(ok).astype(jnp.int32), nrows=lr, ncols=lc,
        )
        if dedup_sr is not None:
            # Exact overflow: count DISTINCT keys (duplicates collapse in
            # compact, so raw valid counts would over-report drops).
            ts = t.sort_rowmajor()
            same = (ts.rows[1:] == ts.rows[:-1]) & (ts.cols[1:] == ts.cols[:-1])
            is_new = ts.valid_mask() & ~jnp.concatenate(
                [jnp.zeros((1,), bool), same]
            )
            distinct = jnp.sum(is_new).astype(jnp.int32)
            drop3 = jnp.maximum(distinct - tile_capacity, 0)
            t = t.compact(dedup_sr, capacity=tile_capacity)
        else:
            nvalid = jnp.sum(ok).astype(jnp.int32)
            drop3 = jnp.maximum(nvalid - tile_capacity, 0)
            t = t._select(ok).with_capacity(tile_capacity)
        dropped = lax.psum(
            lax.psum(drop1 + drop2 + drop3, ROW_AXIS), COL_AXIS
        )
        return SpParMat._pack_tile(t) + (dropped[None],)

    r, c, v, n, dropped = jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(TILE_SPEC,) * 3,
        # drop count REPLICATED (P()): every process must be able to read
        # it whole for the host-side retry decision under multi-process
        out_specs=(TILE_SPEC,) * 4 + (P(),),
        check_vma=False,
    )(rows, cols, vals)
    mat = SpParMat(
        rows=r, cols=c, vals=v, nnz=n, nrows=int(nrows), ncols=int(ncols),
        grid=grid,
    )
    return mat, dropped[0]


def from_device_coo(
    grid: Grid,
    rows: Array,
    cols: Array,
    vals: Array,
    nrows: int,
    ncols: int,
    *,
    slack: float = 2.0,
    max_retries: int = 3,
    dedup_sr: Semiring | None = None,
    defer_drop_check: bool = False,
):
    """Convenience wrapper: size capacities from the chunk shape, route,
    and on drops retry with doubled capacities (skewed inputs — R-MAT hub
    columns — routinely exceed the balanced-load estimate). Raises only
    after ``max_retries`` doublings.

    ``defer_drop_check=True`` returns ``(mat, dropped)`` with the drop
    count as a DEVICE scalar and performs NO retries — for timed pipelines
    on the axon chip, where the retry loop's readback would permanently
    poison subsequent launches (bench.py module docstring); callers verify
    ``int(dropped) == 0`` after their timed section and rerun with bigger
    ``slack`` if not."""
    chunk = rows.shape[-1]
    # hop 2's buckets aggregate up to pc incoming hop-1 buckets, so size the
    # shared stage capacity from the larger of the two hops' balanced loads.
    per_dest1 = -(-chunk // grid.pc)
    per_dest2 = -(-chunk // grid.pr)
    stage_cap = 1 << max(
        int(np.ceil(np.log2(max(max(per_dest1, per_dest2) * slack, 1)))), 0
    )
    # total tuples = chunk * ndev over ndev tiles → ~chunk per tile.
    tile_cap = 1 << max(int(np.ceil(np.log2(max(chunk * slack, 1)))), 0)
    from .spgemm import host_value

    if defer_drop_check:
        if obs.ENABLED:
            obs.gauge("redistribute.stage_capacity", stage_cap)
            obs.gauge("redistribute.tile_capacity", tile_cap)
        mat, dropped = redistribute_coo(
            grid, rows, cols, vals, nrows, ncols,
            stage_capacity=stage_cap, tile_capacity=tile_cap,
            dedup_sr=dedup_sr,
        )
        return mat, dropped

    nd = 0
    with obs.span("redistribute", chunk=int(chunk)):
        for attempt in range(max_retries + 1):
            mat, dropped = redistribute_coo(
                grid, rows, cols, vals, nrows, ncols,
                stage_capacity=stage_cap, tile_capacity=tile_cap,
                dedup_sr=dedup_sr,
            )
            nd = int(host_value(dropped))
            if obs.ENABLED:
                # the actual drop count per attempt — zero on success, so
                # the counter reads as total tuples ever bounced
                obs.count("redistribute.dropped", nd)
                obs.span_event(
                    "route", attempt=attempt, dropped=nd,
                    stage_capacity=stage_cap, tile_capacity=tile_cap,
                )
            if nd == 0:
                return mat
            if obs.ENABLED:
                obs.count("redistribute.retries")
            stage_cap *= 2
            tile_cap *= 2
    raise ValueError(
        f"redistribute still dropped {nd} tuples after {max_retries} "
        "capacity doublings; call redistribute_coo with explicit capacities"
    )
