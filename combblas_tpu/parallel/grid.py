"""Grid — the device-mesh analog of the reference's CommGrid.

The reference builds a √p×√p process grid with row/column/diagonal MPI
subcommunicators via ``MPI_Comm_split``
(``include/CombBLAS/CommGrid.h:44-166``, ``src/CommGrid.cpp:37-101``).  The
TPU-native equivalent is a ``jax.sharding.Mesh`` with named axes: a
"communicator" is just an axis name passed to a collective inside
``shard_map`` —

* rowWorld  (ranks sharing a grid row)    ⇒ collectives over axis ``"c"``
* colWorld  (ranks sharing a grid column) ⇒ collectives over axis ``"r"``
* diagWorld / complement-rank pair exchange (``GetComplementRank``,
  CommGrid.h:99) ⇒ ``lax.ppermute`` with the transpose permutation over
  ``("r", "c")``
* world ⇒ collectives over ``("r", "c")``

Owner math: the reference gives every process ⌊m/pr⌋ rows with the remainder
on the last row of processes (``SpParMat.cpp:5076-5104``).  XLA wants equal
static tile shapes, so we instead pad the global dims to ceil-multiples and
give every tile exactly ``ceil(m/pr) × ceil(n/pc)`` — owner of global row r
is simply ``r // local_rows``.  This changes only the internal layout, never
a computed result.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROW_AXIS = "r"  # varies over grid rows  → collectives here act per grid-column (colWorld)
COL_AXIS = "c"  # varies over grid cols  → collectives here act per grid-row (rowWorld)
LAYER_AXIS = "l"  # 3D grids (CommGrid3D fiberWorld analog)


@dataclasses.dataclass(frozen=True)
class Grid:
    """A 2D pr×pc device grid (≈ CommGrid). Static trace-time object."""

    mesh: Mesh

    @staticmethod
    def make(pr: int, pc: int, devices=None) -> "Grid":
        if devices is None:
            devices = jax.devices()[: pr * pc]
        if len(devices) < pr * pc:
            raise ValueError(f"need {pr * pc} devices, have {len(devices)}")
        arr = np.asarray(devices[: pr * pc]).reshape(pr, pc)
        return Grid(mesh=Mesh(arr, (ROW_AXIS, COL_AXIS)))

    @staticmethod
    def make_default(n_devices: int | None = None) -> "Grid":
        """Squarest grid over the available devices (≈ CommGrid's √p×√p)."""
        n = n_devices if n_devices is not None else len(jax.devices())
        pr = int(math.sqrt(n))
        while n % pr:
            pr -= 1
        return Grid.make(pr, n // pr)

    @property
    def pr(self) -> int:
        return self.mesh.shape[ROW_AXIS]

    @property
    def pc(self) -> int:
        return self.mesh.shape[COL_AXIS]

    @property
    def size(self) -> int:
        return self.pr * self.pc

    @property
    def is_square(self) -> bool:
        return self.pr == self.pc

    def transpose_perm(self) -> list[tuple[int, int]]:
        """ppermute pairs sending (i,j)'s data to (j,i) over ("r","c").

        The complement-rank exchange of ``CommGrid::GetComplementRank``
        (CommGrid.h:99) used by vector transpose and matrix Transpose.
        Requires a square grid.
        """
        assert self.is_square, "transpose exchange needs pr == pc"
        p = self.pr
        return [(i * p + j, j * p + i) for i in range(p) for j in range(p)]

    # --- owner math (ceil-blocked; see module docstring) ------------------

    def local_rows(self, nrows: int) -> int:
        return -(-nrows // self.pr)

    def local_cols(self, ncols: int) -> int:
        return -(-ncols // self.pc)

    def row_owner(self, nrows: int, gr):
        return gr // self.local_rows(nrows)

    def col_owner(self, ncols: int, gc):
        return gc // self.local_cols(ncols)

    # --- sharding helpers -------------------------------------------------

    def tile_sharding(self) -> NamedSharding:
        """[pr, pc, ...] arrays: leading dims map to mesh axes."""
        return NamedSharding(self.mesh, P(ROW_AXIS, COL_AXIS))

    def row_aligned_sharding(self) -> NamedSharding:
        """[pr, L] vector blocks: block i on grid-row i, replicated over cols."""
        return NamedSharding(self.mesh, P(ROW_AXIS))

    def col_aligned_sharding(self) -> NamedSharding:
        """[pc, L] vector blocks: block j on grid-col j, replicated over rows."""
        return NamedSharding(self.mesh, P(COL_AXIS))

    def __hash__(self):
        return hash((Grid, self.mesh))

    def __eq__(self, other):
        return isinstance(other, Grid) and self.mesh == other.mesh


@dataclasses.dataclass(frozen=True)
class HostGrid:
    """Device-free stand-in for ``Grid`` carrying only the owner math —
    for host-only construction (``EllParMat.host_build``,
    ``build_csc_companion_host``) in processes that must never attach to
    a chip: the bench parent builds search structures while its timing
    children own the device (see bench.py's axon D2H note)."""

    pr: int
    pc: int

    @property
    def size(self) -> int:
        return self.pr * self.pc

    def local_rows(self, nrows: int) -> int:
        return -(-nrows // self.pr)

    def local_cols(self, ncols: int) -> int:
        return -(-ncols // self.pc)

    def row_owner(self, nrows: int, gr):
        return gr // self.local_rows(nrows)

    def col_owner(self, ncols: int, gc):
        return gc // self.local_cols(ncols)
