"""Multi-host initialization (≈ the MPI world the reference assumes).

The reference's distribution substrate is MPI_COMM_WORLD: every rank
enters main() via mpirun and CommGrid splits the world
(``CommGrid.cpp:37-75``). The JAX-native equivalent is
``jax.distributed.initialize`` + a mesh over ``jax.devices()`` (which,
after initialization, lists every device across all hosts): one
controller process per host, same SPMD program, XLA collectives ride ICI
within a slice and DCN across slices.

This module is the explicit init path VERDICT r1 flagged as missing. It
cannot be exercised in this single-host environment (the round's
acknowledged limit); the logic is deliberately thin so the first
multi-host run only needs correct coordinator addressing.
"""

from __future__ import annotations

import os

import jax


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> int:
    """Initialize the multi-host runtime (idempotent).

    With no arguments, defers to the standard JAX env vars /
    cloud-TPU metadata autodetection (``JAX_COORDINATOR_ADDRESS``,
    ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``) — the mpirun analog.
    Returns the global device count.
    """
    if num_processes is None:
        num_processes = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    if num_processes > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    return len(jax.devices())


def make_global_grid(pr: int | None = None, pc: int | None = None):
    """Squarest (or given) 2D Grid over the global devices.

    Call after ``init_distributed``; every host constructs the identical
    mesh (jax.devices() is globally consistent), which is what makes the
    single-program shard_map SPMD across hosts — the CommGrid ctor's
    ``MPI_Comm_split`` with ranks replaced by device ids.

    When ``pr * pc`` is smaller than the device count (e.g. the square
    SUMMA subgrid of a rectangular world), devices are picked round-robin
    ACROSS PROCESSES so every controller still owns addressable shards —
    a mesh confined to one process's devices would leave the others
    unable to read even replicated results.
    """
    from .grid import Grid

    if pr is None or pc is None:
        return Grid.make_default()
    devs = jax.devices()
    need = pr * pc
    if need < len(devs):
        by_proc: dict[int, list] = {}
        for d in devs:
            by_proc.setdefault(d.process_index, []).append(d)
        groups = [by_proc[k] for k in sorted(by_proc)]
        picked = []
        i = 0
        while len(picked) < need:
            g = groups[i % len(groups)]
            if g:
                picked.append(g.pop(0))
            i += 1
        return Grid.make(pr, pc, devices=picked)
    return Grid.make(pr, pc)
