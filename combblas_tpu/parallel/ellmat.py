"""EllParMat — bucketed sliced-ELL, the gather-only distributed SpMV format.

The reference's answer to SpMV efficiency is DCSC column walks + per-thread
row splits (``Friends.h:64-180``). On TPU the bottleneck inverts: gathers
are essentially free (HBM-bandwidth vectorized) while large scatters and
segmented scans serialize — a 16M-entry segment-max takes seconds where the
equivalent dense-gather formulation takes 0.05 ms (measured, v5e).

Scale-free graphs defeat plain ELL (one k covers the median but hubs push
most nnz into an overflow scatter — 61% of scale-19 R-MAT at k=64). The
fix is degree-bucketed sliced ELL: rows are grouped by degree class on a
1.5-step width ladder (1,2,3,4,6,8,12,...; ``_width_ladder``); bucket b
stores its rows densely as ``[nb, kb]`` with kb = ladder[b], so

* every row's entries live in exactly one bucket (no overflow COO),
* each bucket's fold is a DENSE reduction over its k axis (VPU-native),
* results scatter back by unique row ids — an n-sized .set scatter, cheap,
* total storage is < 1.5x nnz (kb < 1.5 x degree; measured 1.15x on
  scale-20 R-MAT, worth +12% end-to-end BFS on the target chip).

This is the reference's DER-swap seam (``SpMat.h:54``): same distributed
schedule (x replicated down grid columns, fold over the "c" axis), local
kernel chosen by type — ``dist_spmv``/``dist_spmv_masked`` dispatch on the
matrix type, so SpMV-only algorithms (BFS, CC, SSSP, MIS) accept an
EllParMat unchanged. Algorithms needing column reductions, apply, or the
SpMSpV path (PageRank's normalization, bfs_diropt) keep SpParMat.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops.segment import segment_reduce
from ..semiring import Semiring
from .collectives import axis_reduce
from .grid import COL_AXIS, ROW_AXIS, Grid
from .spmat import SpParMat, TILE_SPEC
from .vec import DistVec

Array = jax.Array


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["buckets"],
    meta_fields=["nrows", "ncols", "grid"],
)
@dataclasses.dataclass(frozen=True)
class EllParMat:
    """buckets: tuple of (cols [pr,pc,nb,kb], vals [pr,pc,nb,kb],
    rowids [pr,pc,nb]) — one entry per populated degree class.

    Padding: col slots hold local_cols (gathers the semiring zero), padded
    bucket rows hold rowid = local_rows (dropped by the result scatter).
    """

    buckets: tuple
    nrows: int
    ncols: int
    grid: Grid

    @property
    def local_rows(self) -> int:
        return self.grid.local_rows(self.nrows)

    @property
    def local_cols(self) -> int:
        return self.grid.local_cols(self.ncols)

    @property
    def dtype(self):
        return self.buckets[0][1].dtype if self.buckets else jnp.float32

    def getnnz(self) -> Array:
        lc = self.local_cols
        return sum(
            (jnp.sum(bc < lc) for bc, _, _ in self.buckets),
            start=jnp.int32(0),
        )

    @staticmethod
    def from_host_coo(
        grid: Grid, rows, cols, vals, nrows: int, ncols: int,
        max_k: int | None = None, ladder: str = "fine",
        headroom: float | None = None,
    ) -> "EllParMat":
        """Build directly from host global COO — fully numpy + one upload
        (the only safe construction path for real-chip benchmarking; see
        the axon D2H note in bench.py).

        ``max_k`` caps a bucket's width; rows with degree > max_k span
        multiple bucket rows whose partial folds recombine in the result
        scatter via the semiring add (each entry still appears once).

        ``ladder``: ``"fine"`` (default) uses the 1.5-step width ladder —
        ~1.15x slot padding, +12% on W=256 batched BFS; ``"coarse"`` uses
        power-of-two widths — FEWER bucket classes (fewer small gathers
        per sweep), measurably better for 1-lane payloads (single-vector
        SpMV) which cannot amortize the extra per-bucket sweeps.

        ``headroom`` (default: env ``COMBBLAS_DYNAMIC_HEADROOM``, 0)
        over-allocates every bucket class by that fraction of FREE
        padding rows: a high-churn dynamic graph's growing rows then
        re-bucket into the reserved slots (``dynamic.merge.
        headroom_used``) instead of spilling the whole merge to a
        rebuild (``dynamic.merge.spill{reason=bucket_full}``).
        """
        host = EllParMat.host_build(
            grid, rows, cols, vals, nrows, ncols, max_k=max_k,
            ladder=ladder, headroom=headroom,
        )
        return EllParMat.from_host_buckets(grid, host, nrows, ncols)

    @staticmethod
    def from_host_buckets(
        grid: Grid, host_buckets, nrows: int, ncols: int
    ) -> "EllParMat":
        """Upload pre-built host bucket arrays (``host_build`` output, or
        the same arrays round-tripped through an .npz): one device_put per
        array — the bench protocol's cheap per-child path (the parent
        builds once on host; children only upload)."""
        sh = grid.tile_sharding()
        put = lambda x: jax.device_put(jnp.asarray(x), sh)
        return EllParMat(
            buckets=tuple(
                (put(bc), put(bv), put(br)) for bc, bv, br in host_buckets
            ),
            nrows=int(nrows), ncols=int(ncols), grid=grid,
        )

    @staticmethod
    def host_build(
        grid: Grid, rows, cols, vals, nrows: int, ncols: int,
        max_k: int | None = None, ladder: str = "fine",
        headroom: float | None = None,
    ):
        """HOST-ONLY bucket construction (no device touch): returns a list
        of (bc, bv, br) numpy arrays — the serializable half of
        ``from_host_coo``, split out so a bench parent process can build
        once and ship the arrays to timing children via .npz without ever
        attaching to the chip itself.  ``headroom`` reserves extra free
        padding rows per class (see ``from_host_coo``)."""
        from ..tuner import config as tuner_config
        from .spmat import bucket_by_tile

        headroom = tuner_config.dynamic_headroom(headroom)

        vals = np.asarray(vals)
        rows, cols, order, counts, starts, _cap, lr, lc = bucket_by_tile(
            grid, rows, cols, nrows, ncols, None
        )
        vals = vals[order]
        pr_, pc_ = grid.pr, grid.pc
        if max_k is None:
            max_k = max(int(lc), 1)

        # Per tile: row-sort, then vectorized chunking of every nonempty row
        # into (class, row, start, take) with take <= max_k.
        ladder = _width_ladder(max_k, ladder)
        per_tile = []
        classes = set()
        for t in range(grid.size):
            s0, e0 = starts[t], starts[t + 1]
            r = rows[s0:e0] - (t // pc_) * lr
            c = cols[s0:e0] - (t % pc_) * lc
            v = vals[s0:e0]
            o = np.argsort(r, kind="stable")
            r, c, v = r[o], c[o], v[o]
            ptr = np.searchsorted(r, np.arange(lr + 1))
            deg = ptr[1:] - ptr[:-1]
            nz = np.nonzero(deg)[0]
            d_nz, s_nz = deg[nz], ptr[:-1][nz]
            nchunks = -(-d_nz // max_k)
            rep_row = np.repeat(nz, nchunks)
            rep_deg = np.repeat(d_nz, nchunks)
            rep_start = np.repeat(s_nz, nchunks)
            # chunk index within each row: global arange minus per-row base
            base = np.repeat(
                np.concatenate([[0], np.cumsum(nchunks)])[:-1], nchunks
            )
            chunk = np.arange(len(rep_row)) - base
            take = np.minimum(rep_deg - chunk * max_k, max_k).astype(np.int64)
            start = rep_start + chunk * max_k
            # width-class the chunk (fine ladder: ~1.15x average slot
            # padding; coarse: ~1.34x but fewer bucket sweeps)
            cls = np.searchsorted(ladder, take)
            classes.update(np.unique(cls).tolist())
            per_tile.append((cls, rep_row, start, take, c, v))

        buckets = []
        for b in sorted(classes):
            kb = int(ladder[b])
            nb = max(int((pt[0] == b).sum()) for pt in per_tile)
            nb = max(nb, 1)
            if headroom > 0:
                # reserved re-bucketing slack: every tile of this class
                # gets at least ceil(nb * headroom) FREE rows (padding
                # rowid = lr, inert for the kernels) on top of the
                # occupancy max — the dynamic merge's free-slot pool
                nb += int(np.ceil(nb * headroom))
            bc = np.full((pr_, pc_, nb, kb), lc, np.int32)
            bv = np.zeros((pr_, pc_, nb, kb), vals.dtype)
            br = np.full((pr_, pc_, nb), lr, np.int32)
            for t, (cls, rrow, rstart, rtake, c, v) in enumerate(per_tile):
                i, j = divmod(t, pc_)
                sel = cls == b
                if not sel.any():
                    continue
                srow, sstart, stake = rrow[sel], rstart[sel], rtake[sel]
                m = len(srow)
                # [m, kb] index matrix into the tile's sorted entry arrays
                idx = sstart[:, None] + np.arange(kb)[None, :]
                valid = np.arange(kb)[None, :] < stake[:, None]
                idx = np.where(valid, idx, 0)
                bc[i, j, :m] = np.where(valid, c[idx], lc)
                bv[i, j, :m] = np.where(valid, v[idx], 0)
                br[i, j, :m] = srow
            buckets.append((bc, bv, br))
        return buckets

    @staticmethod
    def from_spmat(
        A: SpParMat, max_k: int | None = None, ladder: str = "fine"
    ) -> "EllParMat":
        """Host conversion from an existing SpParMat (one-time per matrix —
        the kernel-1 pre-pass, like the reference's OptimizeForGraph500,
        SpParMat.cpp:3343). NOTE: reads the tiles back to host; on the axon
        chip prefer ``from_host_coo`` before any device work (D2H poison).
        """
        r, c, v = A.to_global_coo()
        return EllParMat.from_host_coo(
            A.grid, r, c, v, A.nrows, A.ncols, max_k=max_k, ladder=ladder
        )

    def reduce(self, sr: Semiring, axis: str, map_fn=None) -> DistVec:
        """Row-wise fold (axis="cols" → row-aligned vector), e.g. degrees
        with ``map_fn=ones``. Column-wise reductions should use the SpParMat
        the ELL was converted from."""
        assert axis == "cols", "EllParMat.reduce supports axis='cols' only"
        return _ell_reduce_rows_jit(self, sr, map_fn)

    def to_host_coo(self):
        """Read the buckets back and reconstruct the global COO, sorted
        by (row, col): ``(rows, cols, vals)`` numpy arrays.  Canonical —
        independent of bucket layout, slot order, or which class a
        sticky incremental merge left a row in — so two EllParMats with
        equal content compare bit-exact (the dynamic-merge acceptance
        check).  A D2H readback: test/tooling path only, never ahead of
        timed launches on readback-poisoned chips (bench.py)."""
        import jax

        lr, lc = self.local_rows, self.local_cols
        rows_all, cols_all, vals_all = [], [], []
        for bc, bv, br in self.buckets:
            bc = np.asarray(jax.device_get(bc))
            bv = np.asarray(jax.device_get(bv))
            br = np.asarray(jax.device_get(br))
            pr_, pc_ = bc.shape[0], bc.shape[1]
            valid = (bc < lc) & (br[..., None] < lr)
            gr = np.broadcast_to(
                (np.arange(pr_, dtype=np.int64)[:, None, None] * lr
                 + br)[..., None],
                bc.shape,
            )
            gc = (
                np.arange(pc_, dtype=np.int64)[None, :, None, None] * lc
                + bc
            )
            rows_all.append(gr[valid])
            cols_all.append(gc[valid])
            vals_all.append(bv[valid])
        if not rows_all:
            return (
                np.empty(0, np.int64), np.empty(0, np.int64),
                np.empty(0, np.float32),
            )
        r = np.concatenate(rows_all)
        c = np.concatenate(cols_all)
        v = np.concatenate(vals_all)
        order = np.argsort(r * np.int64(self.ncols) + c, kind="stable")
        return r[order], c[order], v[order]


def _width_ladder(max_k: int, kind: str = "fine") -> "np.ndarray":
    """Bucket widths clamped to include max_k.

    "fine": 1,2,3,4,6,8,12,... — alternating x1.5 (2^k → 3·2^(k-1)) and
    x4/3 (→ 2^(k+1)) steps, ~1.15x average slot padding.
    "coarse": powers of two — ~1.34x padding but ~half the bucket
    classes (fewer per-sweep gathers; better for 1-lane payloads)."""
    if kind not in ("fine", "coarse"):
        raise ValueError(f"ladder must be 'fine' or 'coarse', got {kind!r}")
    if kind == "coarse":
        widths = [1]
        while widths[-1] < max_k:
            widths.append(widths[-1] * 2)
    else:
        widths = [1, 2]
        while widths[-1] < max_k:
            n = widths[-1]
            widths.append(n * 3 // 2 if (n & (n - 1)) == 0 else n * 4 // 3)
    widths = [w for w in widths if w <= max_k]
    if not widths or widths[-1] != max_k:
        widths.append(max_k)
    return np.asarray(widths, np.int64)


def _bucket_fold(sr: Semiring, prods: Array) -> Array:
    if sr.add_kind == "sum":
        return jnp.sum(prods, axis=1)
    if sr.add_kind == "min":
        return jnp.min(prods, axis=1)
    if sr.add_kind == "max":
        return jnp.max(prods, axis=1)
    return lax.reduce(prods, sr.zero(prods.dtype), sr.add, (1,))


def _scatter_rows(sr: Semiring, y: Array, rowids: Array, yb: Array) -> Array:
    """Combine bucket results into y by row id (padding = lr dropped).
    Split hub rows may appear twice within a bucket — every path combines
    duplicates with sr.add (native scatter kinds do; the generic path goes
    through a duplicate-safe segment reduction)."""
    if sr.add_kind == "sum":
        return y.at[rowids].add(yb, mode="drop")
    if sr.add_kind == "min":
        return y.at[rowids].min(yb, mode="drop")
    if sr.add_kind == "max":
        return y.at[rowids].max(yb, mode="drop")
    contrib = segment_reduce(sr, yb, rowids, y.shape[0])
    return sr.add(y, contrib)


def _ell_local_spmv(sr: Semiring, buckets, x: Array, lr: int, lc: int) -> Array:
    """[lr] semiring row fold: per-bucket dense gather+reduce, no big
    scatter (result writes are one slot per bucket row)."""
    zero = sr.zero(x.dtype)
    xpad = jnp.concatenate([x, zero[None]])
    y = None
    out_dtype = None
    for bc, bv, br in buckets:
        g = xpad[jnp.minimum(bc, lc)]  # [nb, kb]
        prods = sr.mul(bv, g)
        yb = _bucket_fold(sr, prods)
        if y is None:
            out_dtype = yb.dtype
            y = jnp.full((lr,), sr.zero(out_dtype), out_dtype)
        y = _scatter_rows(sr, y, br, yb.astype(out_dtype))
    if y is None:
        y = jnp.full((lr,), zero, x.dtype)
    return y


@partial(jax.jit, static_argnames=("sr",))
def dist_spmv_ell(sr: Semiring, E: EllParMat, x: DistVec) -> DistVec:
    """y = E ⊗ x — same schedule as ``dist_spmv``, bucketed-ELL kernel."""
    assert x.length == E.ncols
    x = x.realign("col")
    lr, lc = E.local_rows, E.local_cols
    nb = len(E.buckets)

    def body(xblk, *flat):
        buckets = [tuple(a[0, 0] for a in flat[3 * i : 3 * i + 3]) for i in range(nb)]
        y = _ell_local_spmv(sr, buckets, xblk[0], lr, lc)
        return axis_reduce(sr, y, COL_AXIS)[None]

    flat_args = [a for b in E.buckets for a in b]
    blocks = jax.shard_map(
        body,
        mesh=E.grid.mesh,
        in_specs=(P(COL_AXIS),) + (TILE_SPEC,) * (3 * nb),
        out_specs=P(ROW_AXIS),
    )(x.blocks, *flat_args)
    return DistVec(blocks=blocks, length=E.nrows, align="row", grid=E.grid)


@partial(jax.jit, static_argnames=("sr",))
def dist_spmv_ell_masked(
    sr: Semiring, E: EllParMat, x: DistVec, row_active: DistVec
) -> DistVec:
    assert x.length == E.ncols
    x = x.realign("col")
    row_active = row_active.realign("row")
    lr, lc = E.local_rows, E.local_cols
    nb = len(E.buckets)

    def body(xblk, actblk, *flat):
        buckets = [tuple(a[0, 0] for a in flat[3 * i : 3 * i + 3]) for i in range(nb)]
        y = _ell_local_spmv(sr, buckets, xblk[0], lr, lc)
        y = jnp.where(actblk[0], y, sr.zero(y.dtype))
        return axis_reduce(sr, y, COL_AXIS)[None]

    flat_args = [a for b in E.buckets for a in b]
    blocks = jax.shard_map(
        body,
        mesh=E.grid.mesh,
        in_specs=(P(COL_AXIS), P(ROW_AXIS)) + (TILE_SPEC,) * (3 * nb),
        out_specs=P(ROW_AXIS),
    )(x.blocks, row_active.blocks, *flat_args)
    return DistVec(blocks=blocks, length=E.nrows, align="row", grid=E.grid)


@partial(jax.jit, static_argnames=("sr", "map_fn"))
def _ell_reduce_rows_jit(E: EllParMat, sr: Semiring, map_fn) -> DistVec:
    lr, lc = E.local_rows, E.local_cols
    nb = len(E.buckets)

    def body(*flat):
        buckets = [tuple(a[0, 0] for a in flat[3 * i : 3 * i + 3]) for i in range(nb)]
        y = None
        for bc, bv, br in buckets:
            valid = bc < lc
            v = map_fn(bv) if map_fn is not None else bv
            zero = sr.zero(v.dtype)
            v = jnp.where(valid, v, zero)
            yb = _bucket_fold(sr, v)
            if y is None:
                y = jnp.full((lr,), zero, v.dtype)
            y = _scatter_rows(sr, y, br, yb)
        if y is None:
            probe = (
                map_fn(jnp.zeros((), E.dtype))
                if map_fn is not None
                else jnp.zeros((), E.dtype)
            )
            y = jnp.full((lr,), sr.zero(probe.dtype), probe.dtype)
        return axis_reduce(sr, y, COL_AXIS)[None]

    flat_args = [a for b in E.buckets for a in b]
    blocks = jax.shard_map(
        body,
        mesh=E.grid.mesh,
        in_specs=(TILE_SPEC,) * (3 * nb),
        out_specs=P(ROW_AXIS),
    )(*flat_args)
    return DistVec(blocks=blocks, length=E.nrows, align="row", grid=E.grid)


# --- multi-root (batched) SpMV — frontier-as-matrix, SURVEY §2.3 #7 ---------


def _ell_local_spmm(
    sr: Semiring, buckets, x2: Array, lr: int, lc: int, backend: str
) -> Array:
    """[lr, F] semiring fold of one tile's buckets over a [lc, F]
    dense block — the ONE local gather-contract kernel shared by the
    batched SpMV lanes (W frontier columns) and the round-12 SpMM lane
    (F feature columns).

    Per bucket, ONE gather fetches each neighbor's whole payload row
    (``[rows, kb, F]`` — per-index bound on the target chip, so the
    width rides ~free), then the k axis contracts: backend
    ``"mxu_gather"`` (plus_times only) via a batched ``dot_general``
    ([1, kb] × [kb, F] per bucket row, MXU-eligible); backend
    ``"scatter"`` via the VPU ``_bucket_fold`` + the duplicate-safe
    ``_scatter_rows`` combine (every semiring).  Row slicing keeps the
    gather intermediate under the same byte envelope as the batched
    BFS step (``_bucket_row_slices``; the budget argument is BYTES per
    slot — F lanes × itemsize here where the int8 BFS step passed W).
    """
    F = x2.shape[1]
    zero = sr.zero(x2.dtype)
    xpad = jnp.concatenate([x2, jnp.full((1, F), zero, x2.dtype)])
    y = None
    for bc, bv, br in buckets:
        nb_, kb = bc.shape
        payload = F * max(jnp.dtype(x2.dtype).itemsize, 1)
        for s0, s1 in _bucket_row_slices(nb_, kb, payload):
            g = xpad[jnp.minimum(bc[s0:s1], lc)]  # [rows, kb, F]
            if backend == "mxu_gather":
                # pad slots: bv holds 0 there (host_build zero-fills),
                # so the plus_times contraction drops them exactly
                out_dtype = jnp.result_type(bv.dtype, x2.dtype)
                yb = lax.dot_general(
                    bv[s0:s1][:, None, :].astype(out_dtype),
                    g.astype(out_dtype),
                    dimension_numbers=(((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=out_dtype,
                )[:, 0, :]
            else:
                prods = sr.mul(bv[s0:s1][..., None], g)
                yb = _bucket_fold(sr, prods)  # [rows, F]
            if y is None:
                y = jnp.full((lr, F), sr.zero(yb.dtype), yb.dtype)
            y = _scatter_rows(sr, y, br[s0:s1], yb.astype(y.dtype))
    if y is None:
        y = jnp.full((lr, F), zero, x2.dtype)
    return y


def _ell_local_spmv_multi(sr: Semiring, buckets, x2: Array, lr, lc) -> Array:
    """[lr, W] semiring row fold over a [lc, W] input block.

    Identical structure to ``_ell_local_spmv`` with a trailing batch dim:
    one gathered index fetches W lanes (measured on v5e: W=8 costs the same
    wall time as W=1 — the gather is per-index bound, so the batch rides
    free; this is the kernel-side payoff of multi-source BFS batching).
    Since round 12 this IS the shared gather-contract kernel's scatter
    backend — which also bounds hub-bucket gather intermediates with the
    byte-envelope row slicing the int8 BFS step already had.
    """
    return _ell_local_spmm(sr, buckets, x2, lr, lc, "scatter")


@partial(jax.jit, static_argnames=("sr",))
def dist_spmv_ell_multi(sr: Semiring, E: EllParMat, X) -> "DistMultiVec":
    """Y = E ⊗ X for a DistMultiVec X (W stacked vectors) — the unmasked
    batched kernel: one gathered index feeds all W lanes (payload-width
    nearly free on the target chip), amortizing the per-index gather cost
    W ways for any W-chain iterative app (personalized PageRank, batched
    SSSP sources, BC pivot batches)."""
    from .vec import DistMultiVec

    assert X.length == E.ncols
    X = X.realign("col")
    lr, lc = E.local_rows, E.local_cols
    nb = len(E.buckets)

    def body(xblk, *flat):
        buckets = [
            tuple(a[0, 0] for a in flat[3 * i : 3 * i + 3]) for i in range(nb)
        ]
        y = _ell_local_spmv_multi(sr, buckets, xblk[0], lr, lc)
        return axis_reduce(sr, y, COL_AXIS)[None]

    flat_args = [a for b in E.buckets for a in b]
    blocks = jax.shard_map(
        body,
        mesh=E.grid.mesh,
        in_specs=(P(COL_AXIS),) + (TILE_SPEC,) * (3 * nb),
        out_specs=P(ROW_AXIS),
    )(X.blocks, *flat_args)
    return DistMultiVec(
        blocks=blocks, length=E.nrows, align="row", grid=E.grid
    )


@partial(jax.jit, static_argnames=("sr",))
def dist_spmv_ell_masked_multi(
    sr: Semiring, E: EllParMat, X, row_active
) -> "DistMultiVec":
    """Y = E ⊗ X for a DistMultiVec X (W stacked vectors), with per-lane
    row masking — the batched Graph500 kernel."""
    from .vec import DistMultiVec

    assert X.length == E.ncols
    X = X.realign("col")
    row_active = row_active.realign("row")
    lr, lc = E.local_rows, E.local_cols
    nb = len(E.buckets)

    def body(xblk, actblk, *flat):
        buckets = [
            tuple(a[0, 0] for a in flat[3 * i : 3 * i + 3]) for i in range(nb)
        ]
        y = _ell_local_spmv_multi(sr, buckets, xblk[0], lr, lc)
        y = jnp.where(actblk[0], y, sr.zero(y.dtype))
        return axis_reduce(sr, y, COL_AXIS)[None]

    flat_args = [a for b in E.buckets for a in b]
    blocks = jax.shard_map(
        body,
        mesh=E.grid.mesh,
        in_specs=(P(COL_AXIS), P(ROW_AXIS)) + (TILE_SPEC,) * (3 * nb),
        out_specs=P(ROW_AXIS),
    )(X.blocks, row_active.blocks, *flat_args)
    return DistMultiVec(
        blocks=blocks, length=E.nrows, align="row", grid=E.grid
    )


def _bucket_row_slices(nb: int, kb: int, W: int,
                       budget_bytes: int = 1 << 32):
    """Static row-slice bounds keeping any [rows, kb, W] gather
    intermediate under ~budget_bytes of int8 payload: XLA materializes
    the gather output of the fold pipeline, so an unsliced 30M-slot hub
    bucket at W=256 would allocate gigabytes — the scale-21 OOM.

    The budget must stay LARGE: slicing scale-20 buckets ~10 ways ran
    4.6x slower (57 vs 264 MTEPS — per-slice scatter and fusion
    overhead); 4GB (= 16M slots at W=256) leaves scale-20 whole, halves
    only the hub buckets, and measured 12% FASTER than unsliced
    (297 MTEPS). The budget scales with W so wider batches keep the same
    byte bound."""
    rows_per = max(budget_bytes // max(kb * max(W, 1), 1), 1)
    return [(s0, min(s0 + rows_per, nb)) for s0 in range(0, nb, rows_per)]


@partial(jax.jit, static_argnames=("ring",))
def _ell_levels_step(E: EllParMat, x8, undiscovered8, ring: bool = False):
    """One batched BFS level over int8 indicator frontiers.

    x8: [pc, lc, W] int8 col-aligned (1 = in frontier); undiscovered8:
    [pr, lr, W] int8 row-aligned (1 = not yet discovered). Returns
    reached8 [pr, lr, W]: 1 where an undiscovered row has a frontier
    in-neighbor. The gather payload is W BYTES per index instead of the
    4W of the parent-carrying kernel — on per-index-bound gather hardware
    with payload-width sensitivity above ~256B this is the difference
    between ~0.45s and ~1.6s per level at scale 20 x W=256.
    """
    lr, lc = E.local_rows, E.local_cols
    nb = len(E.buckets)

    def body(xblk, ublk, *flat):
        buckets = [
            tuple(a[0, 0] for a in flat[3 * i : 3 * i + 3]) for i in range(nb)
        ]
        x = xblk[0]  # [lc, W] int8
        W = x.shape[1]
        xpad = jnp.concatenate([x, jnp.zeros((1, W), jnp.int8)])
        y = jnp.zeros((lr, W), jnp.int8)
        for bc, _bv, br in buckets:
            nb_, kb = bc.shape
            for s0, s1 in _bucket_row_slices(nb_, kb, W):
                g = xpad[jnp.minimum(bc[s0:s1], lc)]  # [rows, kb, W] int8
                yb = jnp.max(g, axis=1)  # [rows, W]
                y = y.at[br[s0:s1]].max(yb, mode="drop")
        y = jnp.minimum(y, ublk[0])  # only undiscovered rows fire
        if ring:
            # the carousel schedule: neighbor ppermute rotation over the
            # 'c' mesh axis (COL_AXIS — same axis the pmax path reduces)
            # instead of the fused all-reduce
            from ..semiring import SELECT2ND_MAX
            from .collectives import axis_ring_reduce

            return axis_ring_reduce(SELECT2ND_MAX, y, COL_AXIS)[None]
        return lax.pmax(y, COL_AXIS)[None]

    flat_args = [a for b in E.buckets for a in b]
    return jax.shard_map(
        body,
        mesh=E.grid.mesh,
        in_specs=(P(COL_AXIS), P(ROW_AXIS)) + (TILE_SPEC,) * (3 * nb),
        out_specs=P(ROW_AXIS),
        # the ring fold provably replicates over "c" (a full rotation
        # visits every neighbor) but shard_map cannot infer that through
        # ppermute — same situation as DistVec.realign; the default pmax
        # path keeps the check on
        check_vma=not ring,
    )(x8, undiscovered8, *flat_args)


@partial(jax.jit, static_argnames=())
def _ell_parents_from_levels(E: EllParMat, levels_col, levels_row):
    """Parent reconstruction: for every (row, root) pick the max-id
    in-neighbor whose level is exactly level(row)-1.

    levels_col: [pc, lc, W] int8 (col-aligned levels, -1 undiscovered);
    levels_row: [pr, lr, W]. One W-byte-payload gather pass over the
    matrix — the whole-search parent information the compact BFS loop
    deliberately did not carry.
    """
    lr, lc = E.local_rows, E.local_cols
    nb = len(E.buckets)

    def body(lcb, lrb, *flat):
        buckets = [
            tuple(a[0, 0] for a in flat[3 * i : 3 * i + 3]) for i in range(nb)
        ]
        lvl_c = lcb[0]  # [lc, W] int8
        W = lvl_c.shape[1]
        lvl_r = lrb[0]  # [lr, W] int8
        cpad = jnp.concatenate([lvl_c, jnp.full((1, W), -1, jnp.int8)])
        j = lax.axis_index(COL_AXIS)
        col_base = j * lc
        y = jnp.full((lr, W), -1, jnp.int32)
        want = jnp.where(
            lvl_r > 0, lvl_r - 1, jnp.int8(-2)
        )  # rows at level 0 (roots) or undiscovered never match
        for bc, _bv, br in buckets:
            nb_, kb = bc.shape
            # int32 candidates: half the byte budget of the int8 step
            for s0, s1 in _bucket_row_slices(nb_, kb, W,
                                             budget_bytes=1 << 31):
                safe = jnp.minimum(bc[s0:s1], lc)
                g = cpad[safe]  # [rows, kb, W] int8 neighbor levels
                brs = br[s0:s1]
                wantb = want[jnp.minimum(brs, lr - 1)][:, None, :]
                gid = (col_base + safe).astype(jnp.int32)[:, :, None]
                cand = jnp.where(g == wantb, gid, -1)  # [rows, kb, W]
                yb = jnp.max(cand, axis=1)  # [rows, W]
                y = y.at[brs].max(yb, mode="drop")
        return lax.pmax(y, COL_AXIS)[None]

    flat_args = [a for b in E.buckets for a in b]
    return jax.shard_map(
        body,
        mesh=E.grid.mesh,
        in_specs=(P(COL_AXIS), P(ROW_AXIS)) + (TILE_SPEC,) * (3 * nb),
        out_specs=P(ROW_AXIS),
    )(levels_col, levels_row, *flat_args)


# --- budgeted union-frontier sparse step (direction optimization for the
# BATCHED search; ≈ the top-down regime of DirOptBFS applied to all W
# roots at once) -------------------------------------------------------------


def build_csc_companion(grid: Grid, rows, cols, nrows: int, ncols: int):
    """Host build of per-tile CSC structure arrays for column walks:
    (indptr [pr, pc, lc+1], rowidx [pr, pc, cap]) int32, cap = max tile
    nnz. The EllParMat's row buckets cannot walk COLUMNS; sparse
    union-frontier steps need exactly that (the reference's SpImpl CSC
    kernels, SpImpl.cpp:345-600)."""
    indptr, rowidx = build_csc_companion_host(grid, rows, cols, nrows, ncols)
    return upload_csc_companion(grid, indptr, rowidx)


def upload_csc_companion(grid: Grid, indptr, rowidx):
    """Upload pre-built host CSC arrays (``build_csc_companion_host``)."""
    sh = grid.tile_sharding()
    return (
        jax.device_put(jnp.asarray(indptr), sh),
        jax.device_put(jnp.asarray(rowidx), sh),
    )


def build_csr_companion(grid: Grid, rows, cols, nrows: int, ncols: int):
    """Row-major twin of ``build_csc_companion``: (indptr [pr, pc, lr+1],
    colidx [pr, pc, cap]) — per-tile ROW walks for the bottom-up BFS
    regime (``models/bfs.py`` "bu" tiers). For a SYMMETRIC matrix on a
    1x1 grid the CSC companion arrays are identical and may be reused."""
    indptr, colidx = build_csr_companion_host(grid, rows, cols, nrows, ncols)
    return upload_csc_companion(grid, indptr, colidx)


def build_csr_companion_host(grid: Grid, rows, cols, nrows: int, ncols: int):
    """Host-only half of ``build_csr_companion`` (numpy in, numpy out)."""
    return _companion_host(grid, rows, cols, nrows, ncols, major="row")


def build_csc_companion_host(grid: Grid, rows, cols, nrows: int, ncols: int):
    """Host-only half of ``build_csc_companion`` (numpy in, numpy out) —
    serializable for the bench parent → timing-children .npz handoff."""
    return _companion_host(grid, rows, cols, nrows, ncols, major="col")


def _companion_host(grid, rows, cols, nrows, ncols, *, major):
    """Shared per-tile walk-structure builder: sort each tile's tuples by
    the major axis, indptr over that axis, minor indices padded with the
    minor block size as the inert sentinel."""
    import numpy as np

    from .spmat import bucket_by_tile

    rows, cols, order, counts, starts, _cap, lr, lc = bucket_by_tile(
        grid, rows, cols, nrows, ncols, None
    )
    pr_, pc_ = grid.pr, grid.pc
    cap = max(int(counts.max()), 1)
    lmaj, lmin = (lr, lc) if major == "row" else (lc, lr)
    indptr = np.zeros((pr_, pc_, lmaj + 1), np.int32)
    minidx = np.full((pr_, pc_, cap), lmin, np.int32)
    for t in range(grid.size):
        i, j = divmod(t, pc_)
        s0, e0 = starts[t], starts[t + 1]
        r = rows[s0:e0] - i * lr
        c = cols[s0:e0] - j * lc
        maj, mino = (r, c) if major == "row" else (c, r)
        o = np.argsort(maj, kind="stable")
        indptr[i, j] = np.searchsorted(maj[o], np.arange(lmaj + 1))
        minidx[i, j, : e0 - s0] = mino[o]
    return indptr, minidx


@partial(jax.jit, static_argnames=("frontier_capacity", "edge_capacity"))
def _ell_union_sparse_step(
    E: EllParMat, csc_indptr, csc_rowidx, x8, undiscovered8,
    frontier_capacity: int, edge_capacity: int,
):
    """One batched BFS level touching ONLY the union-frontier columns.

    The dense level costs ~nnz gathers regardless of frontier size; when
    the UNION of all W frontiers is small (first levels, straggler tail),
    walking just those columns' edges costs ~edge_capacity instead. The
    caller guarantees the budgets (on-device cond in bfs_batch_compact).
    Semantics identical to _ell_levels_step.
    """
    from ..ops.segment import expand_ranges

    lr, lc = E.local_rows, E.local_cols

    def body(ipt, ridx, xblk, ublk):
        indptr = ipt[0, 0]  # [lc+1]
        rowid = ridx[0, 0]  # [cap]
        x = xblk[0]  # [lc, W] int8
        W = x.shape[1]
        act = jnp.max(x, axis=1) > 0  # [lc] union frontier
        # compact active local columns into F slots
        pos = jnp.cumsum(act.astype(jnp.int32)) - 1
        scatter = jnp.where(act, pos, frontier_capacity)
        fcols = (
            jnp.full((frontier_capacity,), lc, jnp.int32)
            .at[scatter]
            .set(jnp.arange(lc, dtype=jnp.int32), mode="drop")
        )
        ipt_pad = jnp.concatenate([indptr, indptr[-1:]])
        deg = jnp.where(
            fcols < lc, ipt_pad[fcols + 1] - ipt_pad[fcols], 0
        )
        owner, offset, valid, _ = expand_ranges(deg, edge_capacity)
        src_col = fcols[owner]  # local col of this edge
        slot = jnp.minimum(ipt_pad[jnp.minimum(src_col, lc)] + offset,
                           rowid.shape[0] - 1)
        tgt_row = jnp.where(valid, rowid[slot], lr)
        # per-root frontier value of the edge's source column: [Ecap, W]
        xpad = jnp.concatenate([x, jnp.zeros((1, W), jnp.int8)])
        contrib = xpad[jnp.minimum(src_col, lc)]
        contrib = jnp.where(valid[:, None], contrib, 0)
        y = jnp.zeros((lr, W), jnp.int8).at[tgt_row].max(
            contrib, mode="drop"
        )
        y = jnp.minimum(y, ublk[0])
        return lax.pmax(y, COL_AXIS)[None]

    return jax.shard_map(
        body,
        mesh=E.grid.mesh,
        in_specs=(TILE_SPEC, TILE_SPEC, P(COL_AXIS), P(ROW_AXIS)),
        out_specs=P(ROW_AXIS),
    )(csc_indptr, csc_rowidx, x8, undiscovered8)
