"""3D (communication-avoiding) distribution — ≈ CommGrid3D / SpParMat3D /
Mult_AnXBn_SUMMA3D.

The reference's 3D grid factors p = layers × (pr × pc): each layer runs 2D
SUMMA on a column- (or row-) slice of the matrix and partial products are
combined across the ``fiberWorld`` (``CommGrid3D.h:44-80``,
``SpParMat3D.h:43-92``, ``ParFriends.h:2919-3213``). The payoff is
communication-avoidance: per-layer broadcast volume shrinks L-fold at the
cost of L-fold result replication before the fiber reduce.

TPU-native mapping:

* Grid3D = a 3-axis ``Mesh`` ("l", "r", "c"); the fiberWorld is just the
  ``"l"`` axis name.
* SpParMat3D stores tiles as ``[L, pr, pc, cap]`` arrays — ONE pytree for
  all layers, like SpParMat's stacked tiles.
* Splits are LOCAL, exactly as the reference's ``ColSplit`` conversion
  (``SpParMat3D.cpp:74-145``): layer l holds the l-th slice of every 2D
  tile's local columns (col-split) or rows (row-split). Local splitting
  keeps every piece's owner computable without global re-bucketing — the
  same reason the reference chose it.
* SUMMA3D = per-layer 2D SUMMA (all_gathers over "c"/"r" act within a
  layer automatically — axis names ARE the subcommunicators) + an
  ``all_to_all`` over "l" of locally-col-split pieces + a compacting merge:
  the fiber reduce-scatter of ``ParFriends.h:3119-3180``.

Square layer grids and square matrices keep A's col-split aligned with B's
row-split over the contraction index (lr == lc), mirroring the reference's
usage (HipMCL 3D runs on square grids).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import obs
from ..ops.compressed import CSR
from ..ops.spgemm import expand as esc_expand
from ..ops.tuples import SpTuples
from ..semiring import Semiring
from .grid import COL_AXIS, LAYER_AXIS, ROW_AXIS, Grid

Array = jax.Array

TILE3_SPEC = P(LAYER_AXIS, ROW_AXIS, COL_AXIS)


@dataclasses.dataclass(frozen=True)
class Grid3D:
    """layers × pr × pc device mesh (≈ CommGrid3D)."""

    mesh: Mesh

    @staticmethod
    def make(layers: int, pr: int, pc: int, devices=None) -> "Grid3D":
        if devices is None:
            devices = jax.devices()[: layers * pr * pc]
        if len(devices) < layers * pr * pc:
            raise ValueError(
                f"need {layers * pr * pc} devices, have {len(devices)}"
            )
        arr = np.asarray(devices[: layers * pr * pc]).reshape(layers, pr, pc)
        return Grid3D(mesh=Mesh(arr, (LAYER_AXIS, ROW_AXIS, COL_AXIS)))

    @property
    def layers(self) -> int:
        return self.mesh.shape[LAYER_AXIS]

    @property
    def pr(self) -> int:
        return self.mesh.shape[ROW_AXIS]

    @property
    def pc(self) -> int:
        return self.mesh.shape[COL_AXIS]

    def local_rows(self, nrows: int) -> int:
        return -(-nrows // self.pr)

    def local_cols(self, ncols: int) -> int:
        return -(-ncols // self.pc)

    def tile_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, TILE3_SPEC)

    def __hash__(self):
        return hash((Grid3D, self.mesh))

    def __eq__(self, other):
        return isinstance(other, Grid3D) and self.mesh == other.mesh


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["rows", "cols", "vals", "nnz"],
    meta_fields=["nrows", "ncols", "split", "grid"],
)
@dataclasses.dataclass(frozen=True)
class SpParMat3D:
    """3D-distributed sparse matrix (≈ SpParMat3D<IT,NT,DER>).

    rows/cols: int32[L, pr, pc, cap] LAYER-LOCAL tile indices; a col-split
    layer tile spans [local_rows × local_cols/L], a row-split tile
    [local_rows/L × local_cols]. nrows/ncols are the GLOBAL matrix dims.
    """

    rows: Array
    cols: Array
    vals: Array
    nnz: Array
    nrows: int
    ncols: int
    split: str  # "col" | "row"
    grid: Grid3D

    @property
    def capacity(self) -> int:
        return self.rows.shape[3]

    @property
    def tile_rows(self) -> int:
        lr = self.grid.local_rows(self.nrows)
        return lr // self.grid.layers if self.split == "row" else lr

    @property
    def tile_cols(self) -> int:
        lc = self.grid.local_cols(self.ncols)
        return lc // self.grid.layers if self.split == "col" else lc

    def getnnz(self) -> Array:
        return jnp.sum(self.nnz)

    def local_tile(self, rows, cols, vals, nnz) -> SpTuples:
        return SpTuples(
            rows=rows[0, 0, 0], cols=cols[0, 0, 0], vals=vals[0, 0, 0],
            nnz=nnz[0, 0, 0], nrows=self.tile_rows, ncols=self.tile_cols,
        )

    # --- host construction / extraction ------------------------------------

    @staticmethod
    def from_global_coo(
        grid: Grid3D, rows, cols, vals, nrows, ncols, split: str = "col",
        capacity: int | None = None,
    ) -> "SpParMat3D":
        """Bucket global tuples by (layer, tile) with LOCAL split semantics:
        2D tile (i,j) = (r//lr, c//lc); layer = (local col)//(lc/L) for
        col-split, (local row)//(lr/L) for row-split."""
        assert split in ("col", "row")
        rows = np.asarray(rows, dtype=np.int64)
        cols = np.asarray(cols, dtype=np.int64)
        vals = np.asarray(vals)
        L = grid.layers
        lr, lc = grid.local_rows(nrows), grid.local_cols(ncols)
        assert (lc if split == "col" else lr) % L == 0, (
            "local dim must divide evenly over layers"
        )
        ti, tj = rows // lr, cols // lc
        lrow, lcol = rows - ti * lr, cols - tj * lc
        if split == "col":
            w = lc // L
            layer, lcol = lcol // w, lcol % w
            tr, tc = lr, w
        else:
            w = lr // L
            layer, lrow = lrow // w, lrow % w
            tr, tc = w, lc
        flat = ((layer * grid.pr + ti) * grid.pc + tj).astype(np.int64)
        order = np.argsort(flat, kind="stable")
        flat, lrow, lcol, vals_s = flat[order], lrow[order], lcol[order], vals[order]
        counts = np.bincount(flat, minlength=L * grid.pr * grid.pc)
        cap = int(capacity) if capacity else max(int(counts.max()), 1)
        R = np.full((L, grid.pr, grid.pc, cap), tr, np.int32)
        C = np.full((L, grid.pr, grid.pc, cap), tc, np.int32)
        V = np.zeros((L, grid.pr, grid.pc, cap), vals.dtype)
        starts = np.concatenate([[0], np.cumsum(counts)])
        for t in range(L * grid.pr * grid.pc):
            l_, rem = divmod(t, grid.pr * grid.pc)
            i, j = divmod(rem, grid.pc)
            s, e = starts[t], starts[t + 1]
            R[l_, i, j, : e - s] = lrow[s:e]
            C[l_, i, j, : e - s] = lcol[s:e]
            V[l_, i, j, : e - s] = vals_s[s:e]
        sh = grid.tile_sharding()
        return SpParMat3D(
            rows=jax.device_put(jnp.asarray(R), sh),
            cols=jax.device_put(jnp.asarray(C), sh),
            vals=jax.device_put(jnp.asarray(V), sh),
            nnz=jax.device_put(
                jnp.asarray(counts.reshape(L, grid.pr, grid.pc), jnp.int32), sh
            ),
            nrows=int(nrows), ncols=int(ncols), split=split, grid=grid,
        )

    def to_global_coo(self):
        """Inverse of ``from_global_coo`` (host, tests)."""
        L = self.grid.layers
        lr = self.grid.local_rows(self.nrows)
        lc = self.grid.local_cols(self.ncols)
        tr, tc = self.tile_rows, self.tile_cols
        R = np.asarray(self.rows)
        C = np.asarray(self.cols)
        V = np.asarray(self.vals)
        N = np.asarray(self.nnz)
        out = ([], [], [])
        for l_ in range(L):
            for i in range(self.grid.pr):
                for j in range(self.grid.pc):
                    m = R[l_, i, j] < tr
                    assert m.sum() == N[l_, i, j]
                    rr = R[l_, i, j, m].astype(np.int64)
                    cc = C[l_, i, j, m].astype(np.int64)
                    if self.split == "col":
                        gr = i * lr + rr
                        gc = j * lc + l_ * tc + cc
                    else:
                        gr = i * lr + l_ * tr + rr
                        gc = j * lc + cc
                    out[0].append(gr)
                    out[1].append(gc)
                    out[2].append(V[l_, i, j, m])
        return tuple(np.concatenate(x) for x in out)

    def to_dense(self) -> np.ndarray:
        r, c, v = self.to_global_coo()
        out = np.zeros((self.nrows, self.ncols), v.dtype)
        np.add.at(out, (r, c), v)
        return out

    # --- 2D <-> 3D conversions (on-device; see module-level functions) ------

    @staticmethod
    def from_spmat(
        A, grid3: "Grid3D", split: str = "col", **kw
    ) -> "SpParMat3D":
        """2D SpParMat → 3D (≈ ``SpParMat3D(SpParMat&)``)."""
        return spmat3d_from_spmat(A, grid3, split, **kw)

    def to_spmat(self, grid2, **kw):
        """3D → 2D SpParMat (≈ the layermat readback conversion)."""
        return spmat_from_spmat3d(self, grid2, **kw)

    def shrink_to_fit(self, pow2: bool = True) -> "SpParMat3D":
        """Host helper: truncate slot capacity to the max tile nnz (pieces
        from ``col_split`` are front-compacted, so slicing is safe)."""
        need = max(int(np.max(np.asarray(self.nnz))), 1)
        if pow2:
            need = 1 << (need - 1).bit_length()
        need = min(need, self.capacity)
        if need == self.capacity:
            return self
        return dataclasses.replace(
            self,
            rows=self.rows[..., :need],
            cols=self.cols[..., :need],
            vals=self.vals[..., :need],
        )

    # --- local column split / concat (3D phased execution) -----------------

    def col_split(self, nsplits: int) -> list["SpParMat3D"]:
        """Phase splitter for the 3D product (≈ the per-phase ColSplit of
        ``MemEfficientSpGEMM3D``, ParFriends.h:3215-3712).

        Row-split matrices only (B's orientation in C = A ⊗ B). The split
        is STRIDED per layer window: with w = tile_cols/L, piece s takes
        sub-window [s·w/nsplits, (s+1)·w/nsplits) of EVERY layer window, so
        the phase outputs of SUMMA3D land fiber-aligned and concatenate
        without inter-layer movement.
        """
        assert self.split == "row", "col_split phases a row-split operand"
        L = self.grid.layers
        tc = self.tile_cols
        assert tc % (L * nsplits) == 0, (
            f"tile cols {tc} must divide by layers*phases = {L * nsplits}"
        )
        assert self.ncols % nsplits == 0
        return list(_col_split3d_jit(self, nsplits))

    @staticmethod
    def col_concatenate(mats: list["SpParMat3D"]) -> "SpParMat3D":
        """Stitch ``col_split`` pieces / SUMMA3D phase outputs back.

        col-split pieces (phase OUTPUTS): per-layer windows are separate
        array dimensions, so stitching is a plain tile-column offset.
        row-split pieces (inverting ``col_split``): the strided interleave
        is undone per layer window.
        """
        L = mats[0].grid.layers
        tcs = [m.tile_cols for m in mats]
        tc_out = sum(tcs)
        if mats[0].split == "row":
            # inverse of the strided col_split: equal windows required
            assert len(set(tcs)) == 1, "row-split concat needs equal widths"
        arrays = {"rows": [], "cols": [], "vals": []}
        nnz = None
        off = 0
        for s, (m, tcp) in enumerate(zip(mats, tcs)):
            valid = m.rows < m.tile_rows
            if m.split == "col":
                newcol = m.cols + off  # cumulative: pieces may differ in width
            else:
                wp = tcp // L
                w_out = tc_out // L
                newcol = (m.cols // wp) * w_out + s * wp + (m.cols % wp)
            off += tcp
            arrays["rows"].append(m.rows)
            arrays["cols"].append(jnp.where(valid, newcol, tc_out))
            arrays["vals"].append(m.vals)
            nnz = m.nnz if nnz is None else nnz + m.nnz
        return dataclasses.replace(
            mats[0],
            rows=jnp.concatenate(arrays["rows"], axis=3),
            cols=jnp.concatenate(arrays["cols"], axis=3),
            vals=jnp.concatenate(arrays["vals"], axis=3),
            nnz=nnz,
            ncols=sum(m.ncols for m in mats),
        )


@partial(jax.jit, static_argnames=("nsplits",))
def _col_split3d_jit(mat: SpParMat3D, nsplits: int):
    """Strided per-layer-window selection (see ``col_split`` docstring),
    batched over the [L, pr, pc] tile axes with one argsort compaction
    along the slot axis per piece."""
    tr, tc = mat.tile_rows, mat.tile_cols
    L = mat.grid.layers
    w = tc // L  # per-layer output window in the contraction product
    wp = w // nsplits
    valid = mat.rows < tr
    l_win = mat.cols // w
    within = mat.cols % w
    outs = []
    for s in range(nsplits):
        keep = valid & (within // wp == s)
        newcol = l_win * wp + (within % wp)
        piece_tc = L * wp
        # kept entries first (original order), dropped entries pushed back
        order = jnp.argsort(jnp.where(keep, 0, 1), axis=3, stable=True)
        gather = lambda x: jnp.take_along_axis(x, order, axis=3)
        outs.append(
            dataclasses.replace(
                mat,
                rows=gather(jnp.where(keep, mat.rows, tr)),
                cols=gather(jnp.where(keep, newcol, piece_tc)),
                vals=gather(jnp.where(keep, mat.vals, 0)),
                nnz=jnp.sum(keep, axis=3).astype(jnp.int32),
                ncols=mat.ncols // nsplits,
            )
        )
    return tuple(outs)


def mem_efficient_spgemm3d(
    sr: Semiring,
    A: SpParMat3D,
    B: SpParMat3D,
    phases: int,
    *,
    slack: float = 1.05,
    prune_fn=None,
) -> SpParMat3D:
    """Phased 3D SUMMA: C = A ⊗ B over column chunks of B.

    Reference: ``MemEfficientSpGEMM3D`` (ParFriends.h:3215-3712) — the 3D
    expansion path of HipMCL with layers > 1: per phase, one SUMMA3D over a
    column slice of the row-split B, optional prune hook, outputs
    concatenated. A's gathers repeat per phase (the memory/time trade).
    """
    L = B.grid.layers
    assert B.split == "row", (
        "mem_efficient_spgemm3d phases the row-split operand B; got "
        f"split={B.split!r} (build B with split='row')"
    )

    def _splittable(ph: int) -> bool:
        return B.tile_cols % (L * ph) == 0 and B.ncols % ph == 0

    if phases > 1 and not _splittable(phases):
        # Snap DOWN to the nearest valid phase count: running unphased would
        # discard the caller's memory bound entirely, while a smaller valid
        # split preserves most of it.
        snapped = max(
            (ph for ph in range(phases - 1, 0, -1) if _splittable(ph)),
            default=1,
        )
        import warnings

        warnings.warn(
            f"mem_efficient_spgemm3d: tile_cols={B.tile_cols} / "
            f"ncols={B.ncols} not splittable into {phases} phases with "
            f"{L} layers (needs tile_cols % (layers*phases) == 0 and "
            f"ncols % phases == 0); snapping to {snapped} phases",
            stacklevel=2,
        )
        phases = snapped
    if phases <= 1:
        C = spgemm3d(sr, A, B, slack)
        return prune_fn(C) if prune_fn is not None else C
    outs = []
    for Bs in B.col_split(phases):
        # phase pieces inherit B's full slot capacity; truncate so each
        # SUMMA3D gathers phase-sized arrays (the point of phasing)
        C = spgemm3d(sr, A, Bs.shrink_to_fit(), slack)
        if prune_fn is not None:
            C = prune_fn(C)
        outs.append(C)
    return SpParMat3D.col_concatenate(outs)


def _fiber_exchange(partial_c: SpTuples, L: int, w_out: int,
                    piece_capacity: int, *, sort_pieces: bool = False):
    """Fiber exchange of one layer's partial product: split its local
    cols into L pieces of width ``w_out`` (rebased to piece-local
    columns) and ``all_to_all`` them over the layer axis.  The fiber
    Alltoallv of ``ParFriends.h:3119-3180``, shared by the ESC and
    windowed 3D kernels.  Returns (received piece runs — one sorted or
    order-preserved [piece_capacity] SpTuples per source layer — and
    the piece overflow: the max count of entries a piece had to DROP
    to fit ``piece_capacity``; zero means the exchange was lossless).
    Callers combine the runs with ``_fiber_merge``.

    ``sort_pieces=True`` row-major-sorts each OUTGOING piece before the
    exchange — the pre-sort the ``merge="runs"`` tier needs when the
    producing kernel's partial is not already (row, col)-sorted (ESC
    stage chunks, 2D-windowed dot2d chunk order).  L piece-local sorts
    are strictly cheaper than the one concat-sized sort they replace,
    and they ride the exchange side where the partial is still
    column-partitioned."""
    lr = partial_c.nrows
    piece_arrays = []
    worst = jnp.int32(0)
    for l_ in range(L):
        lo = l_ * w_out
        keep = (
            partial_c.valid_mask()
            & (partial_c.cols >= lo)
            & (partial_c.cols < lo + w_out)
        )
        nkeep = jnp.sum(keep).astype(jnp.int32)
        worst = jnp.maximum(worst, nkeep - piece_capacity)
        sel = partial_c._select(keep).with_capacity(piece_capacity)
        cols = jnp.where(sel.valid_mask(), sel.cols - lo, w_out)
        piece = SpTuples(
            rows=sel.rows, cols=cols, vals=sel.vals, nnz=sel.nnz,
            nrows=lr, ncols=w_out,
        )
        if sort_pieces:
            piece = piece.sort_rowmajor()
        piece_arrays.append((piece.rows, piece.cols, piece.vals,
                             piece.nnz))

    stacked = tuple(
        jnp.stack([pa[k] for pa in piece_arrays])
        for k in range(4)
    )  # each [L, piece_capacity] / [L]
    received = tuple(
        lax.all_to_all(x, LAYER_AXIS, split_axis=0, concat_axis=0)
        for x in stacked
    )
    runs = [
        SpTuples(
            rows=received[0][l_], cols=received[1][l_],
            vals=received[2][l_], nnz=received[3][l_],
            nrows=lr, ncols=w_out,
        )
        for l_ in range(L)
    ]
    return runs, worst


#: Valid fiber-reduce combine tiers (docs/spgemm.md "merge tiers") —
#: the ONE definition lives with the env vetting in tuner/config.py.
from ..tuner.config import MERGE_TIER_NAMES as MERGE_TIERS  # noqa: E402

#: Probe rounds of the hash merge tier before the counted overflow
#: fallback kicks in (load factor <= 0.25 via ``hash_table_capacity``
#: puts the per-element exhaustion odds near alpha^k ~ 1e-10 at this
#: budget — the fallback is a safety net, not a steady-state path).
HASH_MERGE_PROBES = 16


def _fiber_merge(
    sr: Semiring,
    runs: list[SpTuples],
    out_capacity: int,
    merge: str,
):
    """Combine the received fiber piece runs into one compacted tile —
    the merge half of the fiber reduce, in the selected tier:

      ``sort``  concat + full ``lax.sort`` compact (the classic path);
      ``runs``  k-way rank-space union of the (pre)sorted runs
                (``ops.spgemm.merge_sorted_runs``) + sort-free compact;
      ``hash``  bounded open-addressing accumulate
                (``ops.spgemm.hash_merge``) — unsorted output order.

    Returns ``(out, merge_over, hash_over)``: ``merge_over`` > 0 means
    the distinct-key count exceeded ``out_capacity`` (truncation),
    ``hash_over`` > 0 means the hash table failed to place entries
    (the caller MUST fall back to a sorted tier — the output is
    incomplete)."""
    from ..ops.spgemm import hash_merge, hash_table_capacity, \
        merge_sorted_runs

    if merge == "runs":
        merged = merge_sorted_runs(runs)
        out, distinct = merged.compact_counted(
            sr, capacity=out_capacity, assume_sorted=True
        )
        return out, distinct - out_capacity, jnp.int32(0)
    if merge == "hash":
        out, hash_over, distinct = hash_merge(
            sr, SpTuples.concat(runs), out_capacity=out_capacity,
            table_capacity=hash_table_capacity(out_capacity),
            n_probes=HASH_MERGE_PROBES,
        )
        return out, distinct - out_capacity, hash_over
    assert merge == "sort", merge
    out, distinct = SpTuples.concat(runs).compact_counted(
        sr, capacity=out_capacity
    )
    return out, distinct - out_capacity, jnp.int32(0)


def _merge_heuristic(sr: Semiring, L: int, expansion_ratio: float,
                     pieces_sorted: bool) -> str:
    """The merge-tier heuristic rung (arg > store > env > THIS):
    ``runs`` when the pieces arrive already sorted — the windowed
    tiers' structural freebie (no sort anywhere in the reduce; the
    r13 capture's 1.87x) always beats speculating on the hash table;
    ``hash`` for UNSORTED producers at high layer counts with heavy
    cross-layer collision (expansion_ratio ≈ total piece slots /
    distinct bound), where the open-addressing combine's O(nnz) beats
    both the pre-sorts and the one concat sort; ``sort`` otherwise
    (unsorted producers at low L — the r13 scale-12 sweep measured
    the piece pre-sort + union LOSING to the one concat sort at L=2,
    benchmarks/results/r13/).  CPU-mesh-measured thresholds; the
    plan store / probe override per key, and a TPU re-measure is an
    open ROADMAP item."""
    from ..ops.spgemm import scatter_combine_for

    if pieces_sorted:
        return "runs"
    if scatter_combine_for(sr) is not None and (
        L >= 4 and expansion_ratio >= 4.0
    ):
        return "hash"
    return "sort"


@partial(
    jax.jit,
    static_argnames=("sr", "flop_capacity", "out_capacity",
                     "piece_capacity", "ring", "merge"),
)
def summa3d_spgemm(
    sr: Semiring,
    A: SpParMat3D,
    B: SpParMat3D,
    *,
    flop_capacity: int,
    out_capacity: int,
    piece_capacity: int,
    ring: bool = False,
    merge: str = "sort",
) -> tuple[SpParMat3D, Array]:
    """C (col-split) = A (col-split) ⊗ B (row-split) over the 3D mesh.

    Reference: ``Mult_AnXBn_SUMMA3D`` (ParFriends.h:2919-3213). Layer l
    multiplies its contraction slice with a p-stage 2D SUMMA (gathers ride
    the within-layer "c"/"r" subcommunicators), the L partial products are
    exchanged as locally-col-split pieces over the fiber axis "l"
    (``all_to_all`` = the fiber Alltoallv at :3119-3180), and each layer
    merges its received pieces.

    ``flop_capacity``: one stage's expansion per tile; ``piece_capacity``:
    one outgoing fiber piece per tile; ``out_capacity``: final tile nnz.

    ``ring=True`` runs each layer's 2D SUMMA as the STAGE-PIPELINED
    carousel (``spgemm._carousel_stages``: two-slot neighbor-rotation
    buffers on the within-layer joint (row, col) axis, stage s+1's
    ppermute issued before stage s's expand consumes its tiles) instead
    of the up-front all_gathers — O(2·tile) sparse operand memory per
    layer, the r9 schedule the 3D tier was missing.  ``merge`` picks
    the fiber-reduce combine tier (``MERGE_TIERS``; ESC stage chunks
    are unsorted, so ``"runs"`` pre-sorts each outgoing piece).

    Returns ``(C, overflow[3])``: the per-device max of (fiber piece
    drop, merge distinct-keys − out_capacity, hash placement
    overflow) — all ≤ 0 means the product is exact; a positive hash
    overflow means the CALLER must rerun through a sorted tier.
    """
    assert A.split == "col" and B.split == "row"
    assert A.grid == B.grid and A.ncols == B.nrows
    assert merge in MERGE_TIERS, merge
    grid = A.grid
    p = grid.pr
    assert grid.pr == grid.pc, "SUMMA3D requires square layer grids"
    L = grid.layers
    lr = A.tile_rows  # full local rows of C
    lcB = B.tile_cols  # full local cols of C partials
    assert A.tile_cols == B.tile_rows, "contraction blocking mismatch"
    assert lcB % L == 0
    w_out = lcB // L
    if obs.ENABLED:
        # trace-time (jitted fn): counts (re)traces per static config
        obs.count("trace.summa3d_spgemm", ring=ring, merge=merge)
        if ring and p > 1:
            obs.count("spgemm.pipeline.stages_overlapped", p - 1)

    def body(ar, ac, av, an, br, bc, bv, bn):
        from .spgemm import _carousel_stages, _gather_stage_tiles

        a_mine = A.local_tile(ar, ac, av, an)
        b_mine = B.local_tile(br, bc, bv, bn)
        if ring:
            # per-layer carousel: the joint (row, col) ppermute acts
            # within each layer automatically (axis names ARE the
            # subcommunicators), so the 2D rotation schedule lifts to
            # the 3-axis mesh unchanged
            chunks = [
                esc_expand(sr, a_cur, CSR.from_tuples(b_cur),
                           flop_capacity)
                for _, a_cur, b_cur in _carousel_stages(
                    a_mine, b_mine, p
                )
            ]
        else:
            a_stages = _gather_stage_tiles(a_mine, COL_AXIS, p)
            b_stages = _gather_stage_tiles(b_mine, ROW_AXIS, p)
            chunks = [
                esc_expand(sr, a_stages[s], CSR.from_tuples(b_stages[s]),
                           flop_capacity)
                for s in range(p)
            ]
        partial_c = SpTuples.concat(chunks)  # [lr × lcB] partial, uncompacted
        runs, piece_over = _fiber_exchange(
            partial_c, L, w_out, piece_capacity,
            sort_pieces=(merge == "runs"),
        )
        out, merge_over, hash_over = _fiber_merge(
            sr, runs, out_capacity, merge
        )
        overflow = jnp.stack([piece_over, merge_over, hash_over])
        overflow = lax.pmax(
            lax.pmax(lax.pmax(overflow, ROW_AXIS), COL_AXIS), LAYER_AXIS
        )
        return (
            out.rows[None, None, None], out.cols[None, None, None],
            out.vals[None, None, None], out.nnz[None, None, None],
            overflow[None, None, None],
        )

    r, c, v, n, overflow = jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(TILE3_SPEC,) * 8,
        out_specs=(TILE3_SPEC,) * 5,
        check_vma=False,
    )(A.rows, A.cols, A.vals, A.nnz, B.rows, B.cols, B.vals, B.nnz)
    mat = SpParMat3D(
        rows=r, cols=c, vals=v, nnz=n,
        nrows=A.nrows, ncols=B.ncols, split="col", grid=grid,
    )
    return mat, overflow[0, 0, 0]


@jax.jit
def summa3d_stage_flops(A: SpParMat3D, B: SpParMat3D) -> Array:
    """[p, L, pr, pc] float32 flops per stage per (layer, tile).

    The distributed symbolic pass of the 3D product — same scheme as the 2D
    ``summa_stage_flops`` (index arrays only cross the ICI), one gather per
    within-layer axis.
    """
    grid = A.grid
    p = grid.pr
    lrB = B.tile_rows
    lrA = A.tile_rows
    lcA = A.tile_cols

    def body(ar, ac, br):
        a_rows, a_cols = ar[0, 0, 0], ac[0, 0, 0]
        b_rows = br[0, 0, 0]
        ag_rows = lax.all_gather(a_rows, COL_AXIS)
        ag_cols = lax.all_gather(a_cols, COL_AXIS)
        bg_rows = lax.all_gather(b_rows, ROW_AXIS)
        per_stage = []
        for s in range(p):
            b_valid = bg_rows[s] < lrB
            blens = jax.ops.segment_sum(
                b_valid.astype(jnp.int32), bg_rows[s], num_segments=lrB + 1
            )
            # chunked-expansion slots, not raw flops (ops.spgemm.CHUNK_W)
            from ..ops.spgemm import CHUNK_W

            blens = -(-blens // CHUNK_W) * CHUNK_W
            a_valid = ag_rows[s] < lrA
            k = jnp.minimum(ag_cols[s], lrB)
            per_stage.append(
                jnp.sum(jnp.where(a_valid, blens[k], 0).astype(jnp.float32))
            )
        mine = jnp.stack(per_stage)  # [p]
        # replicated output: host-addressable under multi-host (see the 2D
        # summa_stage_flops note)
        g = lax.all_gather(
            lax.all_gather(lax.all_gather(mine, COL_AXIS), ROW_AXIS),
            LAYER_AXIS,
        )  # [L, pr, pc, p]
        return jnp.transpose(g, (3, 0, 1, 2))

    return jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(TILE3_SPEC,) * 3,
        out_specs=P(),
        check_vma=False,
    )(A.rows, A.cols, B.rows)


# --- windowed 3D SUMMA (the round-9 tier: per-layer dense window
# accumulators on the 3-axis mesh, ParFriends.h:2919-3213 with the
# windowed local kernel in place of the hash SpGEMM) -------------------------


@partial(
    jax.jit, static_argnames=("block_rows", "block_cols", "chunk_w")
)
def summa3d_window_flops_pair(
    A3: SpParMat3D, B3: SpParMat3D, block_rows: int, block_cols: int,
    chunk_w: int = 1,
) -> Array:
    """[2, L, nblocks, ncolwin, p, pr, pc]: the 3D-resolved windowed
    symbolic pass — per-LAYER flop counts per (A row block, B col
    window) per stage per tile, same (chunk-padded, true) pair contract
    as the 2D ``summa_window_flops_pair`` (whose per-stage inner kernel
    it shares)."""
    from .spgemm import _window_stage_symbolic

    assert A3.split == "col" and B3.split == "row"
    assert A3.grid == B3.grid and A3.ncols == B3.nrows
    grid = A3.grid
    p = grid.pr
    assert grid.pr == grid.pc, "SUMMA3D requires square layer grids"
    lrA = A3.tile_rows
    lrB, lcB = B3.tile_rows, B3.tile_cols
    assert A3.tile_cols == lrB, "contraction blocking mismatch"
    nblocks = -(-lrA // block_rows)
    ncw = -(-lcB // block_cols)

    def body(ar, ac, br, bc):
        a_rows, a_cols = ar[0, 0, 0], ac[0, 0, 0]
        b_rows, b_cols = br[0, 0, 0], bc[0, 0, 0]
        ag_rows = lax.all_gather(a_rows, COL_AXIS)
        ag_cols = lax.all_gather(a_cols, COL_AXIS)
        bg_rows = lax.all_gather(b_rows, ROW_AXIS)
        bg_cols = lax.all_gather(b_cols, ROW_AXIS)
        per_stage = [
            _window_stage_symbolic(
                ag_rows[s], ag_cols[s], bg_rows[s], bg_cols[s],
                lrA, lrB, block_rows, block_cols, nblocks, ncw, chunk_w,
            )
            for s in range(p)
        ]
        mine = jnp.stack(per_stage)  # [p, 2, nblocks, ncw]
        g2 = lax.all_gather(
            lax.all_gather(lax.all_gather(mine, COL_AXIS), ROW_AXIS),
            LAYER_AXIS,
        )  # [L, pr, pc, p, 2, nblocks, ncw]
        # -> [2, L, nblocks, ncw, p, pr, pc]
        return jnp.transpose(g2, (4, 0, 5, 6, 3, 1, 2))

    return jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(TILE3_SPEC,) * 4,
        out_specs=P(),
        check_vma=False,
    )(A3.rows, A3.cols, B3.rows, B3.cols)


def summa3d_window_flops_host(
    grid3: Grid3D, rows_a, cols_a, rows_b, cols_b,
    nrows_a: int, ncols_a: int, ncols_b: int,
    block_rows: int, block_cols: int, chunk_w: int = 0,
) -> np.ndarray:
    """Host-numpy twin of ``summa3d_window_flops_pair`` (one chunk_w at
    a time): [L, nblocks, ncolwin, p, pr, pc] float64 from global COO
    arrays, zero device interaction — the axon-safe 3D sizing path."""
    L = grid3.layers
    p = grid3.pr
    assert grid3.pr == grid3.pc, "SUMMA3D requires square layer grids"
    lrA = grid3.local_rows(nrows_a)
    lcA = grid3.local_cols(ncols_a)
    lrB = grid3.local_rows(ncols_a)
    lcB = grid3.local_cols(ncols_b)
    assert lcA == lrB, "A col-blocking must equal B row-blocking"
    assert lcA % L == 0 and lrB % L == 0, (lcA, lrB, L)
    tcA = lcA // L  # A's per-layer contraction slice == B's trB
    nb = -(-lrA // block_rows)
    ncw = -(-lcB // block_cols)
    rows_a = np.asarray(rows_a, np.int64)
    cols_a = np.asarray(cols_a, np.int64)
    rows_b = np.asarray(rows_b, np.int64)
    cols_b = np.asarray(cols_b, np.int64)
    ia, sa = rows_a // lrA, cols_a // lcA
    la, ka = (cols_a % lcA) // tcA, (cols_a % lcA) % tcA
    ga = (rows_a % lrA) // block_rows
    countA = np.bincount(
        ((((la * p + ia) * p + sa) * nb) + ga) * tcA + ka,
        minlength=L * p * p * nb * tcA,
    ).reshape(L, p, p, nb, tcA)
    sb, jb = rows_b // lrB, cols_b // lcB
    lb, kb = (rows_b % lrB) // tcA, (rows_b % lrB) % tcA
    hb = (cols_b % lcB) // block_cols
    countB = np.bincount(
        ((((lb * p + sb) * p + jb) * ncw) + hb) * tcA + kb,
        minlength=L * p * p * ncw * tcA,
    ).reshape(L, p, p, ncw, tcA)
    if chunk_w:
        countB = -(-countB // chunk_w) * chunk_w
    # flops[l, g, h, s, i, j] = sum_k countA[l,i,s,g,k]*countB[l,s,j,h,k]
    return np.einsum(
        "lisgk,lsjhk->lghsij",
        countA.astype(np.float64), countB.astype(np.float64),
    )


@partial(jax.jit, static_argnames=("block_cols",))
def summa3d_window_bnnz(B3: SpParMat3D, block_cols: int) -> Array:
    """[L, pr, pc, ncolwin] int32, replicated: per-layer B-tile nnz per
    col window — the 3D twin of ``summa_window_bnnz`` (the dot
    backend's static panel slice capacity)."""
    lrB, lcB = B3.tile_rows, B3.tile_cols
    ncw = -(-lcB // block_cols)

    def body(br, bc):
        b_rows, b_cols = br[0, 0, 0], bc[0, 0, 0]
        valid = b_rows < lrB
        h = jnp.where(valid, b_cols // block_cols, ncw).astype(jnp.int32)
        mine = jax.ops.segment_sum(
            valid.astype(jnp.int32), h, num_segments=ncw + 1
        )[:ncw]
        return lax.all_gather(
            lax.all_gather(lax.all_gather(mine, COL_AXIS), ROW_AXIS),
            LAYER_AXIS,
        )  # [L, pr, pc, ncw]

    return jax.shard_map(
        body,
        mesh=B3.grid.mesh,
        in_specs=(TILE3_SPEC,) * 2,
        out_specs=P(),
        check_vma=False,
    )(B3.rows, B3.cols)


def summa3d_window_bnnz_host(
    grid3: Grid3D, rows_b, cols_b, ncols_a: int, ncols_b: int,
    block_cols: int,
) -> np.ndarray:
    """Host twin of ``summa3d_window_bnnz``: [L, pr, pc, ncolwin]."""
    L = grid3.layers
    lrB = grid3.local_rows(ncols_a)
    lcB = grid3.local_cols(ncols_b)
    trB = lrB // L
    ncw = -(-lcB // block_cols)
    rows_b = np.asarray(rows_b, np.int64)
    cols_b = np.asarray(cols_b, np.int64)
    sb, jb = rows_b // lrB, cols_b // lcB
    lb = (rows_b % lrB) // trB
    hb = (cols_b % lcB) // block_cols
    return np.bincount(
        (((lb * grid3.pr + sb) * grid3.pc + jb) * ncw) + hb,
        minlength=L * grid3.pr * grid3.pc * ncw,
    ).reshape(L, grid3.pr, grid3.pc, ncw)


def windowed_plan3d(
    per_window_padded: np.ndarray | None,
    per_window_true: np.ndarray,
    block_rows: int,
    block_cols: int,
    tile_rows: int,
    tile_cols_b: int,
    slack: float = 1.02,
) -> tuple[tuple, tuple, tuple]:
    """3D twin of ``windowed_plan_2d`` over [L, nb, ncw, p, pr, pc]
    counts: ONE SPMD program runs on every layer, so each window's caps
    are the MAX over layers and a window is skipped only when EVERY
    layer's symbolic count is zero.  Folding the layer axis into the
    tile axes makes this exactly the 2D plan rule."""
    from .spgemm import windowed_plan_2d

    def fold(x):
        if x is None:
            return None
        x = np.asarray(x, np.float64)
        return np.moveaxis(x, 0, 3)  # [nb, ncw, p, L, pr, pc]

    return windowed_plan_2d(
        fold(per_window_padded), fold(per_window_true),
        block_rows, block_cols, tile_rows, tile_cols_b, slack=slack,
    )


@partial(
    jax.jit,
    static_argnames=(
        "sr", "block_rows", "flop_caps", "out_caps", "skip", "backend",
        "mode", "chunk_w", "interpret", "block_cols", "panel_cap",
        "piece_capacity", "out_capacity", "ring", "pipeline", "merge",
    ),
)
def summa3d_spgemm_windowed(
    sr: Semiring,
    A3: SpParMat3D,
    B3: SpParMat3D,
    *,
    block_rows: int,
    flop_caps: tuple,
    out_caps: tuple,
    skip: tuple,
    backend: str = "scatter",
    mode: str = "f32",
    chunk_w: int = 8,
    interpret: bool = False,
    block_cols: int | None = None,
    panel_cap: int | None = None,
    piece_capacity: int,
    out_capacity: int,
    ring: bool = False,
    pipeline: bool = True,
    merge: str = "sort",
) -> tuple[SpParMat3D, Array]:
    """C (col-split) = A (col-split) ⊗ B (row-split): the WINDOWED 3D
    SUMMA — ``Mult_AnXBn_SUMMA3D`` with the sort-free windowed local
    kernel in place of the per-stage ESC expand.

    Each layer runs the per-device windowed accumulate+extract core of
    the 2D tier — ``spgemm._windowed_gathered_compute`` (default), or
    with ``ring=True`` the STAGE-PIPELINED CAROUSEL
    (``spgemm._windowed_carousel_compute``): operands rotate
    neighbor-to-neighbor in two-slot buffers on the within-layer joint
    (row, col) axis, O(2·tile) sparse operand memory instead of
    O(p·tile), and with ``pipeline=True`` stage s+1's ppermute issued
    before stage s's accumulate (``pipeline=False`` pins the
    rotate→compute→rotate serial chain via optimization_barrier — the
    A/B measurement control).  Both backends, duplicate-safe
    ``densify_combine``, packed launch list, per-window symbolic caps
    sized by ``windowed_plan3d`` over the layer slices, identical chunk
    layouts across schedules.  Each layer produces one sparse
    [tile_rows × tile_cols] partial; the L partials ride the fiber
    ``all_to_all`` (``_fiber_exchange``) and the ``merge``-selected
    combine tier (``_fiber_merge``).  With the scatter / 1D-dot
    backends the partial is already globally (row, col)-sorted
    (ascending row blocks of sorted extractions), so ``merge="runs"``
    eliminates the fiber reduce's sort ENTIRELY; the dot2d chunk order
    is window-major within a block, so its pieces pre-sort on the
    exchange side.  The payoff mirrors the reference's 3DSpGEMM:
    per-layer stage operands carry 1/L of the contraction, so
    per-stage gather volume shrinks L-fold where the 2D carousel
    saturates.

    Returns ``(C, overflow[4])``: per-device max of (extraction
    overflow, fiber piece drop, merge distinct-keys − out_capacity,
    hash placement overflow) — all ≤ 0 means exact (with symbolic caps
    the first two are structurally ≤ 0); a positive hash overflow
    means the CALLER must rerun through a sorted tier
    (``spgemm3d_windowed`` does this automatically).
    """
    from .spgemm import (
        _PALLAS_KINDS,
        _gather_stage_tiles,
        _windowed_carousel_compute,
        _windowed_gathered_compute,
    )
    from ..ops.spgemm import scatter_combine_for

    assert A3.split == "col" and B3.split == "row"
    assert A3.grid == B3.grid and A3.ncols == B3.nrows
    assert merge in MERGE_TIERS, merge
    grid = A3.grid
    p = grid.pr
    assert grid.pr == grid.pc, "SUMMA3D requires square layer grids"
    L = grid.layers
    lr = A3.tile_rows  # full local rows of C
    lrB, lcB = B3.tile_rows, B3.tile_cols
    assert A3.tile_cols == lrB, "contraction blocking mismatch"
    assert lcB % L == 0
    w_out = lcB // L
    two_d = backend == "dot" and block_cols is not None
    if backend == "dot":
        assert sr.name in _PALLAS_KINDS, sr.name
        if two_d:
            assert panel_cap is not None and panel_cap >= 1
    else:
        assert backend == "scatter", backend
    assert scatter_combine_for(sr) is not None, sr.name
    if obs.ENABLED:
        obs.count(
            "trace.summa3d_spgemm_windowed",
            backend=("dot2d" if two_d else backend),
            ring=ring, merge=merge,
        )
        if ring and pipeline and p > 1:
            # trace-time: per-layer carousel stages whose successor
            # rotation is issued early in this compiled program
            obs.count("spgemm.pipeline.stages_overlapped", p - 1)
    zero = float(np.asarray(sr.zero_fn(A3.vals.dtype)))
    static = dict(
        lrA=lr, lrB=lrB, lcB=lcB, block_rows=block_rows,
        flop_caps=flop_caps, out_caps=out_caps, skip=skip,
        backend=backend, mode=mode, chunk_w=chunk_w,
        interpret=interpret, block_cols=block_cols if two_d else None,
        panel_cap=panel_cap, zero=zero, dtype=A3.vals.dtype,
    )
    # scatter / 1D-dot chunk layout: ascending row blocks, each chunk
    # row-major-sorted by the windowed extraction → the concatenated
    # partial's valid entries are globally (row, col)-sorted and the
    # column-range piece selection preserves that; dot2d chunks are
    # window-major within a block and need the exchange-side pre-sort
    partial_sorted = not two_d

    def body(ar, ac, av, an, br, bc, bv, bn):
        a_mine = A3.local_tile(ar, ac, av, an)
        b_mine = B3.local_tile(br, bc, bv, bn)
        if ring:
            chunks, worst = _windowed_carousel_compute(
                sr, a_mine, b_mine, p=p, pipeline=pipeline, **static
            )
        else:
            a_stages = _gather_stage_tiles(a_mine, COL_AXIS, p)
            b_stages = _gather_stage_tiles(b_mine, ROW_AXIS, p)
            chunks, worst = _windowed_gathered_compute(
                sr, a_stages, b_stages, **static
            )
        if not chunks:  # every window skipped on this layer
            chunks.append(SpTuples.empty(lr, lcB, 1, A3.vals.dtype))
        partial_c = SpTuples.concat(chunks)
        runs, piece_over = _fiber_exchange(
            partial_c, L, w_out, piece_capacity,
            sort_pieces=(merge == "runs" and not partial_sorted),
        )
        out, merge_over, hash_over = _fiber_merge(
            sr, runs, out_capacity, merge
        )
        overflow = jnp.stack([worst, piece_over, merge_over, hash_over])
        overflow = lax.pmax(
            lax.pmax(lax.pmax(overflow, ROW_AXIS), COL_AXIS), LAYER_AXIS
        )
        return (
            out.rows[None, None, None], out.cols[None, None, None],
            out.vals[None, None, None], out.nnz[None, None, None],
            overflow[None, None, None],
        )

    r, c, v, n, overflow = jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(TILE3_SPEC,) * 8,
        out_specs=(TILE3_SPEC,) * 5,
        check_vma=False,
    )(A3.rows, A3.cols, A3.vals, A3.nnz, B3.rows, B3.cols, B3.vals, B3.nnz)
    mat = SpParMat3D(
        rows=r, cols=c, vals=v, nnz=n,
        nrows=A3.nrows, ncols=B3.ncols, split="col", grid=grid,
    )
    return mat, overflow[0, 0, 0]


def summa3d_compatible(grid3: Grid3D, nrows_a: int, ncols_a: int,
                       ncols_b: int) -> bool:
    """True iff (A: nrows_a × ncols_a) ⊗ (B: ncols_a × ncols_b) can be
    laid out on ``grid3`` (square layer grid; the col-split of A, the
    row-split of B, and C's fiber pieces all divide evenly over the
    layers) — the router's gate before choosing the 3D path."""
    L = grid3.layers
    if grid3.pr != grid3.pc:
        return False
    lcA = grid3.local_cols(ncols_a)
    lrB = grid3.local_rows(ncols_a)
    lcB = grid3.local_cols(ncols_b)
    return (
        lcA == lrB
        and lcA % L == 0
        and lrB % L == 0
        and lcB % L == 0
    )


def spgemm3d_windowed(
    sr: Semiring,
    A3: SpParMat3D,
    B3: SpParMat3D,
    *,
    block_rows: int | None = None,
    block_cols: int | None = None,
    backend: str | None = None,
    mode: str = "f32",
    slack: float = 1.02,
    interpret: bool = False,
    merge: str | None = None,
    ring: bool = False,
    pipeline: bool = True,
    merge_source: str | None = None,
) -> SpParMat3D:
    """Sized entry for the windowed 3D tier: 3D symbolic pass →
    ``windowed_plan3d`` (caps maxed over layers) → the compiled
    ``summa3d_spgemm_windowed``.  Both accumulate backends; benchmarks
    on readback-poisoned hardware size on host via
    ``summa3d_window_flops_host`` + ``summa3d_window_bnnz_host`` and
    call the kernel directly.

    ``merge`` picks the fiber-reduce combine tier (``MERGE_TIERS``;
    ``None`` resolves env ``COMBBLAS_SPGEMM_MERGE`` > the L/collision
    heuristic — callers with a plan record pass its merge explicitly,
    holding the arg > store > env > heuristic chain).  ``ring``/
    ``pipeline`` pick the per-layer SUMMA schedule (the r9 carousel).
    A hash-tier placement overflow is COUNTED
    (``spgemm.merge.hash_overflow``) and the product transparently
    reruns through the sorted-runs tier — never wrong, only slower.
    A fiber piece overflow raises a diagnostic naming the ``slack``
    knob instead of truncating downstream.  ``merge_source`` labels
    the ``spgemm.merge.tier`` counter when a ROUTER (``spgemm3d``)
    already resolved ``merge`` from its store/env rung — direct
    callers leave it None ("arg")."""
    from .spgemm import (
        WINDOWED_CHUNK_W,
        default_block_cols,
        default_block_rows,
        host_value,
        packed_windows,
        packed_windows_2d,
        panel_cap_from_bnnz,
        resolve_spgemm_backend,
    )
    from ..tuner import config as tuner_config

    backend = resolve_spgemm_backend(backend)
    grid = A3.grid
    L = grid.layers
    lr = A3.tile_rows
    lrB, lcB = B3.tile_rows, B3.tile_cols
    chunk_w = WINDOWED_CHUNK_W
    if block_rows is None:
        block_rows = default_block_rows(lr, lcB)
    if backend == "dot":
        if block_cols is None:
            block_cols = default_block_cols(lrB, lcB)
        pair = host_value(
            summa3d_window_flops_pair(A3, B3, block_rows, block_cols,
                                      chunk_w=1)
        )
        flop_caps, out_caps, skip = windowed_plan3d(
            None, pair[1], block_rows, block_cols, lr, lcB, slack=slack
        )
        panel_cap = panel_cap_from_bnnz(
            host_value(summa3d_window_bnnz(B3, block_cols)),
            int(B3.capacity),
        )
        npk = len(packed_windows_2d(skip))
        ntot = sum(len(row) for row in skip)
        per_block_bound = [sum(row) for row in out_caps]
        pieces_sorted = False  # dot2d chunk order is window-major
    else:
        # scatter: the window pass with ONE full-width window gives the
        # per-block (padded, true) pair in one kernel
        pair = host_value(
            summa3d_window_flops_pair(A3, B3, block_rows, lcB,
                                      chunk_w=chunk_w)
        )
        fc2, oc2, sk2 = windowed_plan3d(
            pair[0], pair[1], block_rows, lcB, lr, lcB, slack=slack
        )
        flop_caps = tuple(row[0] for row in fc2)
        out_caps = tuple(row[0] for row in oc2)
        skip = tuple(row[0] for row in sk2)
        block_cols = panel_cap = None
        npk = len(packed_windows(skip))
        ntot = len(skip)
        per_block_bound = list(out_caps)
        pieces_sorted = True
    # fiber piece / merge capacities from the same symbolic bounds: one
    # outgoing piece can hold at most the tile's whole extracted
    # partial; the merge receives L pieces and compacts to at most the
    # dense piece
    rnd = lambda x: 1 << (max(int(x), 1) - 1).bit_length()
    piece_cap = rnd(min(sum(per_block_bound), lr * lcB))
    out_cap = min(rnd(piece_cap * L), max(lr * (lcB // L), 1))
    if merge is not None and merge_source is None:
        merge_source = "arg"
    if merge is None:
        merge = tuner_config.env_merge()
        merge_source = "env" if merge is not None else None
    if merge is None:
        # collision estimate: total merge-input slots over the
        # distinct-key bound — ≈ how many partial entries fold into
        # each output key across the fiber
        merge = _merge_heuristic(
            sr, L, piece_cap * L / max(out_cap, 1), pieces_sorted
        )
        merge_source = "heuristic"
    assert merge in MERGE_TIERS, merge
    if obs.ENABLED:
        obs.gauge("spgemm.summa3d.layers", L)
        obs.count("spgemm.windowed.windows_packed", npk)
        obs.gauge(
            "spgemm.windowed.pack_ratio", npk / ntot if ntot else 0.0
        )
        obs.count(
            "spgemm.merge.tier", tier=merge, source=merge_source,
            op="spgemm3d",
        )
    C, overflow = summa3d_spgemm_windowed(
        sr, A3, B3, block_rows=block_rows, flop_caps=flop_caps,
        out_caps=out_caps, skip=skip, backend=backend, mode=mode,
        chunk_w=chunk_w, interpret=interpret, block_cols=block_cols,
        panel_cap=panel_cap, piece_capacity=piece_cap,
        out_capacity=out_cap, ring=ring, pipeline=pipeline,
        merge=merge,
    )
    extract_over, piece_over, merge_over, hash_over = (
        int(x) for x in np.asarray(host_value(overflow))
    )
    _check_fiber_overflow(piece_over, piece_cap, "spgemm3d_windowed",
                          slack)
    if hash_over > 0:
        # counted fallback: the hash table failed to place hash_over
        # entries — rerun through the sorted-runs tier (never wrong,
        # only slower); the counter is how operators notice a
        # mis-sized table / mis-routed plan
        if obs.ENABLED:
            obs.count("spgemm.merge.hash_overflow", hash_over)
        return spgemm3d_windowed(
            sr, A3, B3, block_rows=block_rows, block_cols=block_cols,
            backend=backend, mode=mode, slack=slack,
            interpret=interpret, merge="runs", ring=ring,
            pipeline=pipeline, merge_source="hash_fallback",
        )
    assert extract_over <= 0 and merge_over <= 0, (
        f"windowed 3D tier overflowed its symbolic bound "
        f"(extraction {extract_over}, merge {merge_over})"
    )
    return C


def _check_fiber_overflow(piece_over: int, piece_cap: int, who: str,
                          slack: float) -> None:
    """Shared fiber piece-overflow diagnostic: the exchange DETECTED
    dropped entries (round-13 satellite — before this the count was
    returned and silently ignored by some callers, truncating the
    product downstream).  Counted as ``spgemm.summa3d.piece_overflow``
    and raised with the knob that fixes it."""
    if piece_over <= 0:
        return
    if obs.ENABLED:
        obs.count("spgemm.summa3d.piece_overflow", piece_over)
    raise ValueError(
        f"{who}: fiber exchange overflowed — a piece exceeded its "
        f"piece_capacity={piece_cap} by {piece_over} entries and the "
        f"all_to_all would have dropped them; raise the sizing slack "
        f"(slack={slack} at this call; spgemm3d(..., slack=) / "
        f"{who}(..., slack=)) or pass a larger explicit piece capacity"
    )


def spgemm3d(
    sr: Semiring, A: SpParMat3D, B: SpParMat3D, slack: float = 1.05,
    *, tier: str | None = None, backend: str | None = None,
    mode: str = "f32", block_rows: int | None = None,
    block_cols: int | None = None, interpret: bool = False,
    merge: str | None = None, ring: bool | None = None,
    pipeline: bool | None = None, merge_source: str | None = None,
) -> SpParMat3D:
    """Unjitted entry: distributed symbolic sizing → compiled 3D SUMMA.

    ``tier`` picks the per-layer local kernel: ``"esc"`` (default — the
    classic expand/sort/compress stage kernel, exact for every
    semiring) or ``"windowed"`` (the sort-free dense-window tier,
    ``spgemm3d_windowed``).  Resolution follows the tuner precedence
    (tuner/config.py): argument > plan store (``op="spgemm3d"``
    records, written by benches/operators or the opt-in real-operand
    probe) > env ``COMBBLAS_SPGEMM3D_TIER`` > probe
    (``COMBBLAS_TUNER_PROBE=1``: ``tuner.probe.probe_spgemm3d``
    measures admissible (tier, merge) pairs on the REAL operands and
    persists the winner) > ``"esc"``.  The ESC sizing pass mirrors
    ``EstPerProcessNnzSUMMA``'s role (ParFriends.h:1243); capacities
    round to powers of two (clamped to the dense-tile bound) for
    compile-cache reuse.

    ``merge`` picks the fiber-reduce combine tier (``MERGE_TIERS``:
    sort | runs | hash), resolved arg > store record > env
    ``COMBBLAS_SPGEMM_MERGE`` > heuristic on L and the collision
    estimate.  ``ring``/``pipeline`` are tri-state (None = defer to
    the record, then the kernel defaults): the per-layer SUMMA's
    carousel schedule.
    """
    from .. import obs
    from ..ops.spgemm import scatter_combine_for
    from ..tuner import config as tuner_config
    from ..tuner import store as tuner_store
    from ..tuner.resolve import resolve_merge

    plan_source = "arg" if tier is not None else None
    st = rec = None
    if tier is None:
        st = tuner_store.get_store()
        # key construction costs host nnz readbacks (D2H syncs) — only
        # pay it when the store holds plans OR the opt-in probe would
        # persist one under the key (the axon D2H rule)
        if st is not None and (
            st.entries() > 0 or tuner_config.probe_enabled()
        ):
            key = tuner_store.spgemm3d_plan_key(
                sr, A, B,
                backend or tuner_config.env_backend() or "",
            )
            rec = st.lookup(key) if st.entries() > 0 else None
            if rec is not None and rec.tier not in ("esc", "windowed"):
                # a key-matched record with a non-3D tier is discarded
                # — made visible, like the 2D router, so hits-vs-
                # plan_source can't silently contradict
                if obs.ENABLED:
                    obs.count("tuner.store.rejected", reason="tier")
                rec = None
            if rec is not None:
                tier = rec.tier
                plan_source = "store"
                if block_rows is None:
                    block_rows = rec.block_rows
                if block_cols is None:
                    block_cols = rec.block_cols
                # tri-state schedule flags: an explicit arg beats the
                # record, None defers to it (the spgemm_auto contract)
                if ring is None:
                    ring = rec.ring
                if pipeline is None:
                    pipeline = rec.pipeline
    if tier is None:
        tier = tuner_config.env_tier3d()
        if tier is not None:
            plan_source = "env"
    if tier is None and st is not None and tuner_config.probe_enabled():
        from ..tuner.probe import probe_spgemm3d

        prec = probe_spgemm3d(sr, A, B, store=st, key=key)
        if prec is not None:
            tier = prec.tier
            plan_source = "probe"
            rec = prec
            if ring is None:
                ring = prec.ring
            if pipeline is None:
                pipeline = prec.pipeline
    if tier is None:
        tier = "esc"
        plan_source = "heuristic"
    # merge tier: arg > store record > env (heuristic resolves inside
    # the sized entries, where the collision estimate exists).
    # ``merge_source`` overrides the label when a CALLER already
    # resolved merge (the hash-overflow rerun below).
    caller_source = merge_source
    merge, merge_source = resolve_merge(merge, rec)
    if caller_source is not None:
        merge_source = caller_source
    elif merge_source == "store" and plan_source == "probe":
        # the record came from this call's probe pass, not the store
        merge_source = "probe"
    if obs.ENABLED:
        obs.count(
            "spgemm.auto.plan_source", source=plan_source, tier=tier,
            op="spgemm3d",
        )
    assert tier in ("esc", "windowed"), tier
    ring = False if ring is None else bool(ring)
    pipeline = True if pipeline is None else bool(pipeline)
    if tier == "windowed":
        return spgemm3d_windowed(
            sr, A, B, block_rows=block_rows, block_cols=block_cols,
            backend=backend, mode=mode, slack=max(slack - 0.03, 1.02),
            interpret=interpret, merge=merge, ring=ring,
            pipeline=pipeline, merge_source=merge_source,
        )
    if ring and not pipeline:
        # the ESC ring rides _carousel_stages, which is ALWAYS
        # pipelined (PR 7 dropped its dead pipeline param: trace order
        # alone is no serial control) — reject rather than mislabel a
        # pipelined run as the serial A/B control (the windowed tier
        # carries the real optimization_barrier control)
        raise ValueError(
            "spgemm3d: the esc tier's carousel has no serial "
            "(pipeline=False) control — use tier='windowed' for the "
            "pipelined-vs-serial A/B"
        )
    grid = A.grid
    L = grid.layers
    from .spgemm import host_value
    per_stage = host_value(summa3d_stage_flops(A, B)).astype(np.float64)
    flop_cap = max(int(per_stage.max() * slack) + 1, 1)
    total = per_stage.sum(axis=0)  # per (layer, tile)
    piece_cap = max(int(total.max() * slack) + 1, 1)
    dense_tile = A.tile_rows * (B.tile_cols // L)
    out_cap = max(min(int(total.max() * L * slack) + 1, dense_tile), 1)
    rnd = lambda x: 1 << (x - 1).bit_length()
    piece_cap = rnd(piece_cap)
    out_cap = min(rnd(out_cap), max(dense_tile, 1))
    if merge is None:
        # ESC stage chunks are UNSORTED (pieces_sorted=False): "runs"
        # would pay L piece-local pre-sorts, so the heuristic keeps the
        # one concat sort at low L and switches to hash only where the
        # collision estimate says the O(nnz) table amortizes
        merge = _merge_heuristic(
            sr, L, piece_cap * L / max(out_cap, 1), pieces_sorted=False
        )
        merge_source = "heuristic"
    if merge == "hash" and scatter_combine_for(sr) is None:
        # a forced hash (env/record/arg) on a generic monoid must
        # DEGRADE at the knob, not assert mid-trace inside the
        # shard_map body — the 2D spgemm entry's convention; runs is
        # exact for every semiring
        merge = "runs"
        merge_source = f"{merge_source}_degraded"
    assert merge in MERGE_TIERS, merge
    if obs.ENABLED:
        obs.count(
            "spgemm.merge.tier", tier=merge, source=merge_source,
            op="spgemm3d",
        )
    def run_kernel(mg):
        return summa3d_spgemm(
            sr, A, B,
            flop_capacity=rnd(flop_cap),
            out_capacity=out_cap,
            piece_capacity=piece_cap,
            ring=ring, merge=mg,
        )

    C, overflow = run_kernel(merge)
    piece_over, merge_over, hash_over = (
        int(x) for x in np.asarray(host_value(overflow))
    )
    _check_fiber_overflow(piece_over, piece_cap, "spgemm3d", slack)
    if hash_over > 0:
        # counted fallback: rerun the ALREADY-SIZED kernel through the
        # sorted-runs tier (no re-entry into the routing entry — one
        # logical call counts one plan_source resolution)
        if obs.ENABLED:
            obs.count("spgemm.merge.hash_overflow", hash_over)
            obs.count(
                "spgemm.merge.tier", tier="runs",
                source="hash_fallback", op="spgemm3d",
            )
        C, overflow = run_kernel("runs")
        piece_over, merge_over, _ = (
            int(x) for x in np.asarray(host_value(overflow))
        )
        _check_fiber_overflow(piece_over, piece_cap, "spgemm3d", slack)
    assert merge_over <= 0, (
        f"spgemm3d: merge distinct keys exceeded out_capacity by "
        f"{merge_over}; raise slack"
    )
    return C


# --- 2D <-> 3D conversions (≈ SpParMat3D(SpParMat&) / layermat readback,
# SpParMat3D.cpp:74-145, 197-320) ------------------------------------------


def _globalize2d(A):
    """2D tile arrays → global-id arrays [pr, pc, cap] (no communication:
    adds tile offsets on the sharded arrays in place; padding → nrows/ncols
    sentinels)."""
    from .spmat import SpParMat  # noqa: F401 (type context)

    g = A.grid
    lr, lc = A.local_rows, A.local_cols
    valid = A.rows < lr
    ioff = jnp.arange(g.pr, dtype=jnp.int32)[:, None, None]
    joff = jnp.arange(g.pc, dtype=jnp.int32)[None, :, None]
    gr = jnp.where(valid, A.rows + ioff * lr, A.nrows)
    gc = jnp.where(valid, A.cols + joff * lc, A.ncols)
    return gr.astype(jnp.int32), gc.astype(jnp.int32), A.vals


def _globalize3d(A3: SpParMat3D):
    """3D tile arrays → global-id arrays [L, pr, pc, cap] (split-aware)."""
    g = A3.grid
    L = g.layers
    lr, lc = g.local_rows(A3.nrows), g.local_cols(A3.ncols)
    tr, tc = A3.tile_rows, A3.tile_cols
    valid = A3.rows < tr
    loff = jnp.arange(L, dtype=jnp.int32)[:, None, None, None]
    ioff = jnp.arange(g.pr, dtype=jnp.int32)[None, :, None, None]
    joff = jnp.arange(g.pc, dtype=jnp.int32)[None, None, :, None]
    if A3.split == "col":
        gr = A3.rows + ioff * lr
        gc = A3.cols + joff * lc + loff * tc
    else:
        gr = A3.rows + ioff * lr + loff * tr
        gc = A3.cols + joff * lc
    gr = jnp.where(valid, gr, A3.nrows)
    gc = jnp.where(valid, gc, A3.ncols)
    return gr.astype(jnp.int32), gc.astype(jnp.int32), A3.vals


@partial(
    jax.jit,
    static_argnames=("grid", "nrows", "ncols", "split", "stage_capacity",
                     "tile_capacity"),
)
def redistribute_coo3d(
    grid: Grid3D,
    rows: Array,
    cols: Array,
    vals: Array,
    nrows: int,
    ncols: int,
    *,
    split: str,
    stage_capacity: int,
    tile_capacity: int,
):
    """Route device-resident GLOBAL tuples to their 3D owner tiles.

    rows/cols/vals: [L, pr, pc, chunk] arbitrary global tuples per device
    (invalid slots: row >= nrows). Three fixed-capacity all_to_all hops —
    by owner column over "c", owner row over "r", owner layer over "l" —
    the dimension-ordered extension of ``redistribute_coo``'s 2D routing
    (the fiber Alltoallv of the reference's 2D→3D conversion,
    SpParMat3D.cpp:74-145). Returns (SpParMat3D, dropped count).
    """
    from .redistribute import _bucket_route

    L = grid.layers
    lr, lc = grid.local_rows(nrows), grid.local_cols(ncols)
    split_dim = lc if split == "col" else lr
    if split_dim % L:
        raise ValueError(
            f"3D {split}-split needs the local {'column' if split == 'col' else 'row'} "
            f"count ({split_dim}) to divide evenly over {L} layers; pad the "
            f"matrix dims or choose a different grid"
        )
    w = split_dim // L
    tr = lr if split == "col" else w
    tc = w if split == "col" else lc
    pr_, pc_ = grid.pr, grid.pc

    def hop(r, c, v, dest, ndest, axis):
        br, bc, bv, drop = _bucket_route(
            dest.astype(jnp.int32), r, c, v, ndest, stage_capacity,
            jnp.int32(nrows), jnp.int32(ncols),
        )
        br = lax.all_to_all(br, axis, split_axis=0, concat_axis=0)
        bc = lax.all_to_all(bc, axis, split_axis=0, concat_axis=0)
        bv = lax.all_to_all(bv, axis, split_axis=0, concat_axis=0)
        return br.reshape(-1), bc.reshape(-1), bv.reshape(-1), drop

    def body(r, c, v):
        r0, c0, v0 = r[0, 0, 0], c[0, 0, 0], v[0, 0, 0]
        valid = r0 < nrows
        oj = jnp.where(valid, c0 // lc, pc_)
        r1, c1, v1, d1 = hop(r0, c0, v0, oj, pc_, COL_AXIS)
        valid = r1 < nrows
        oi = jnp.where(valid, r1 // lr, pr_)
        r2, c2, v2, d2 = hop(r1, c1, v1, oi, pr_, ROW_AXIS)
        valid = r2 < nrows
        if split == "col":
            ol = jnp.where(valid, (c2 % lc) // w, L)
        else:
            ol = jnp.where(valid, (r2 % lr) // w, L)
        r3, c3, v3, d3 = hop(r2, c2, v2, ol, L, LAYER_AXIS)
        # localize
        i = lax.axis_index(ROW_AXIS)
        j = lax.axis_index(COL_AXIS)
        ok = r3 < nrows
        if split == "col":
            lrow = jnp.where(ok, r3 - i * lr, tr)
            lcol = jnp.where(ok, (c3 - j * lc) % w, tc)
        else:
            lrow = jnp.where(ok, (r3 - i * lr) % w, tr)
            lcol = jnp.where(ok, c3 - j * lc, tc)
        nvalid = jnp.sum(ok).astype(jnp.int32)
        drop4 = jnp.maximum(nvalid - tile_capacity, 0)
        t = SpTuples(
            rows=lrow.astype(jnp.int32), cols=lcol.astype(jnp.int32),
            vals=jnp.where(ok, v3, 0), nnz=nvalid, nrows=tr, ncols=tc,
        )._select(ok).with_capacity(tile_capacity)
        dropped = lax.psum(
            lax.psum(lax.psum(d1 + d2 + d3 + drop4, ROW_AXIS), COL_AXIS),
            LAYER_AXIS,
        )
        return (
            t.rows[None, None, None], t.cols[None, None, None],
            t.vals[None, None, None], t.nnz[None, None, None],
            dropped[None],
        )

    r, c, v, n, dropped = jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(TILE3_SPEC,) * 3,
        # drop count replicated (multi-process-readable), see 2D twin
        out_specs=(TILE3_SPEC,) * 4 + (P(),),
        check_vma=False,
    )(rows, cols, vals)
    mat = SpParMat3D(
        rows=r, cols=c, vals=v, nnz=n, nrows=int(nrows), ncols=int(ncols),
        split=split, grid=grid,
    )
    return mat, dropped[0]


def _route_with_retry(route, chunk_cap: int, dest_fanouts, total: int,
                      ndev: int, slack: float, max_retries: int, what: str):
    """Shared conversion driver: size stage/tile capacities from the chunk
    shape and total nnz, route, and double capacities on dropped tuples."""
    per_dest = max(-(-chunk_cap // f) for f in dest_fanouts)
    stage_cap = 1 << max(int(np.ceil(np.log2(max(per_dest * slack, 1)))), 0)
    tile_cap = 1 << max(
        int(np.ceil(np.log2(max(total / ndev * slack, 1)))), 0
    )
    from .spgemm import host_value

    nd = 0
    for _ in range(max_retries + 1):
        mat, dropped = route(stage_cap, tile_cap)
        nd = int(host_value(dropped))
        if nd == 0:
            return mat
        stage_cap *= 2
        tile_cap *= 2
    raise ValueError(
        f"{what} dropped {nd} tuples after {max_retries} capacity doublings"
    )


def _rechunk(arr, ndev: int, sentinel):
    """Flatten tuple chunks and re-split over ``ndev`` devices, padding the
    tail with ``sentinel`` (an invalid row id — dropped by routing). Lets
    conversions change device count (a 2D square grid is never layers*p^2)."""
    flat = arr.reshape(-1)
    chunk = -(-flat.shape[0] // ndev)
    pad = ndev * chunk - flat.shape[0]
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.full((pad,), sentinel, flat.dtype)]
        )
    return flat, chunk


def spmat3d_from_spmat(
    A, grid3: Grid3D, split: str = "col", *, slack: float = 2.0,
    max_retries: int = 3,
) -> SpParMat3D:
    """2D → 3D conversion (≈ ``SpParMat3D(SpParMat&)``,
    SpParMat3D.cpp:74-145), fully on device.

    Globalizes the 2D tiles in place (no comm), reshards the tuple chunks
    onto the 3D mesh (XLA moves bytes over ICI at the jit boundary), then
    routes with ``redistribute_coo3d``. The source 2D grid may have ANY
    shape and device count (routing is by global id — no nested
    process-grid restriction), but the 3D grid's local split dimension must
    divide evenly over the layers (ValueError otherwise).
    """
    ndev3 = grid3.layers * grid3.pr * grid3.pc
    gr, gc, gv = _globalize2d(A)
    grf, cap = _rechunk(gr, ndev3, jnp.int32(A.nrows))
    gcf, _ = _rechunk(gc, ndev3, jnp.int32(A.ncols))
    gvf, _ = _rechunk(gv, ndev3, jnp.zeros((), gv.dtype))
    sh3 = grid3.tile_sharding()
    shape3 = (grid3.layers, grid3.pr, grid3.pc, cap)
    gr3 = jax.device_put(grf.reshape(shape3), sh3)
    gc3 = jax.device_put(gcf.reshape(shape3), sh3)
    gv3 = jax.device_put(gvf.reshape(shape3), sh3)
    total = int(np.asarray(jnp.sum(A.nnz)))

    def route(stage_cap, tile_cap):
        return redistribute_coo3d(
            grid3, gr3, gc3, gv3, A.nrows, A.ncols, split=split,
            stage_capacity=stage_cap, tile_capacity=tile_cap,
        )

    return _route_with_retry(
        route, cap, (grid3.pc, grid3.pr, grid3.layers), total, ndev3,
        slack, max_retries, "2D→3D conversion",
    )


def spmat_from_spmat3d(
    A3: SpParMat3D, grid2, *, slack: float = 2.0, max_retries: int = 3,
):
    """3D → 2D conversion (the layermat readback direction,
    SpParMat3D.cpp:197-320), fully on device: globalize, reshard chunks to
    the 2D mesh, route with the 2D ``redistribute_coo``."""
    from .redistribute import redistribute_coo

    gr, gc, gv = _globalize3d(A3)
    grf, cap = _rechunk(gr, grid2.size, jnp.int32(A3.nrows))
    gcf, _ = _rechunk(gc, grid2.size, jnp.int32(A3.ncols))
    gvf, _ = _rechunk(gv, grid2.size, jnp.zeros((), gv.dtype))
    sh2 = grid2.tile_sharding()
    shape2 = (grid2.pr, grid2.pc, cap)
    gr2 = jax.device_put(grf.reshape(shape2), sh2)
    gc2 = jax.device_put(gcf.reshape(shape2), sh2)
    gv2 = jax.device_put(gvf.reshape(shape2), sh2)
    total = int(np.asarray(jnp.sum(A3.nnz)))

    def route(stage_cap, tile_cap):
        return redistribute_coo(
            grid2, gr2, gc2, gv2, A3.nrows, A3.ncols,
            stage_capacity=stage_cap, tile_capacity=tile_cap,
        )

    return _route_with_retry(
        route, cap, (grid2.pc, grid2.pr), total, grid2.size,
        slack, max_retries, "3D→2D conversion",
    )


# --- 3D column operations (the MCL support ops on SpParMat3D) --------------
#
# A col-split SpParMat3D partitions global columns over (layer, grid-col):
# every global column lives wholly within one (l, j) tile column, spread
# over the pr row tiles. Column reductions are therefore the SAME kernels
# as 2D (segment-reduce per tile + psum over "r") run on the 3-axis mesh —
# the "r" collective acts within each layer automatically because axis
# names ARE the subcommunicators. This gives MemEfficientSpGEMM3D's prune
# hook real MCL semantics (≈ the column ops MCLPruneRecoverySelect needs,
# ParFriends.h:186-350, applied per layer as the reference does on its
# per-layer layermats).

COLVEC3_SPEC = P(LAYER_AXIS, COL_AXIS)


def _check_colsplit(A3: SpParMat3D):
    assert A3.split == "col", (
        "3D column ops operate on col-split matrices (columns partitioned "
        "over layer x grid-col); resplit row-split matrices first"
    )


@partial(jax.jit, static_argnames=("sr", "map_fn"))
def reduce3d_cols(sr: Semiring, A3: SpParMat3D, map_fn=None) -> Array:
    """Per-column fold over rows → [L, pc, tile_cols] (replicated over "r").

    The Reduce(Column) of the 3D matrix (≈ SpParMat::Reduce on each
    layermat)."""
    from ..ops.segment import segment_reduce

    _check_colsplit(A3)
    tc = A3.tile_cols

    def body(rows, cols, vals, nnz):
        t = A3.local_tile(rows, cols, vals, nnz)
        v = map_fn(t.vals) if map_fn is not None else t.vals
        local = segment_reduce(sr, v, t.cols, tc)
        from .collectives import axis_reduce

        return axis_reduce(sr, local, ROW_AXIS)[None, None]

    return jax.shard_map(
        body,
        mesh=A3.grid.mesh,
        in_specs=(TILE3_SPEC,) * 4,
        out_specs=COLVEC3_SPEC,
        check_vma=False,
    )(A3.rows, A3.cols, A3.vals, A3.nnz)


@jax.jit
def nnz_per_column3d(A3: SpParMat3D) -> Array:
    """[L, pc, tile_cols] int32 per-column nonzero counts."""
    _check_colsplit(A3)
    tc = A3.tile_cols

    def body(rows, cols, vals, nnz):
        t = A3.local_tile(rows, cols, vals, nnz)
        ids = jnp.where(t.valid_mask(), t.cols, tc)
        local = (
            jnp.zeros((tc,), jnp.int32).at[ids].add(1, mode="drop")
        )
        return lax.psum(local, ROW_AXIS)[None, None]

    return jax.shard_map(
        body,
        mesh=A3.grid.mesh,
        in_specs=(TILE3_SPEC,) * 4,
        out_specs=COLVEC3_SPEC,
        check_vma=False,
    )(A3.rows, A3.cols, A3.vals, A3.nnz)


@partial(jax.jit, static_argnames=("k",))
def kselect3d(A3: SpParMat3D, k: int, kvec: Array | None = None) -> Array:
    """Per-column k-th largest value → [L, pc, tile_cols].

    The Kselect1 of the 3D matrix (≈ SpParMat::Kselect1,
    SpParMat.cpp:1120-1742), via the same radix-select over
    order-preserving u32 keys as the 2D path. Columns with fewer than k
    entries return the dtype's minimum (keep-everything threshold).
    ``kvec``: optional [L, pc, tile_cols] per-column k override.
    """
    from .spmat import _key_bits, _monotone_key_u32, _u32_key_to_val
    from ..semiring import _minval

    _check_colsplit(A3)
    tc = A3.tile_cols
    dtype = A3.vals.dtype

    def body(rows, cols, vals, nnz, *maybe_k):
        t = A3.local_tile(rows, cols, vals, nnz)
        keys = _monotone_key_u32(t.vals)
        valid = t.valid_mask()
        ids = jnp.where(valid, t.cols, tc)
        idx = jnp.minimum(ids, tc - 1)
        kcol = (
            maybe_k[0][0, 0].astype(jnp.int32)
            if maybe_k
            else jnp.full((tc,), k, jnp.int32)
        )

        def col_count(ge_mask):
            local = jax.ops.segment_sum(
                ge_mask.astype(jnp.int32), ids, num_segments=tc
            )
            return lax.psum(local, ROW_AXIS)

        total = col_count(valid)
        kt = keys.dtype
        thresh = jnp.zeros((tc,), kt)
        for b in range(_key_bits(dtype) - 1, -1, -1):
            cand = thresh | jnp.asarray(1 << b, kt)
            cnt = col_count(valid & (keys >= cand[idx]))
            thresh = jnp.where(cnt >= kcol, cand, thresh)
        out = _u32_key_to_val(thresh, dtype)
        out = jnp.where(total < kcol, _minval(dtype), out)
        return out[None, None]

    args = (A3.rows, A3.cols, A3.vals, A3.nnz) + (
        (kvec,) if kvec is not None else ()
    )
    vspecs = (COLVEC3_SPEC,) if kvec is not None else ()
    return jax.shard_map(
        body,
        mesh=A3.grid.mesh,
        in_specs=(TILE3_SPEC,) * 4 + vspecs,
        out_specs=COLVEC3_SPEC,
        check_vma=False,
    )(*args)


@partial(jax.jit, static_argnames=("keep",))
def prune_column3d(A3: SpParMat3D, colvec: Array, keep) -> SpParMat3D:
    """Keep entry (i, j) iff ``keep(val, colvec[j])``
    (≈ SpParMat::PruneColumn, SpParMat.cpp:2567-2779)."""
    _check_colsplit(A3)

    def body(rows, cols, vals, nnz, vblk):
        t = A3.local_tile(rows, cols, vals, nnz)
        v = vblk[0, 0]
        idx = jnp.minimum(t.cols, v.shape[0] - 1)
        keepmask = t.valid_mask() & keep(t.vals, v[idx])
        s = t._select(keepmask)
        return (
            s.rows[None, None, None], s.cols[None, None, None],
            s.vals[None, None, None], s.nnz[None, None, None],
        )

    r, c, v, n = jax.shard_map(
        body,
        mesh=A3.grid.mesh,
        in_specs=(TILE3_SPEC,) * 4 + (COLVEC3_SPEC,),
        out_specs=(TILE3_SPEC,) * 4,
        check_vma=False,
    )(A3.rows, A3.cols, A3.vals, A3.nnz, colvec)
    return dataclasses.replace(A3, rows=r, cols=c, vals=v, nnz=n)


@partial(jax.jit, static_argnames=("pred",))
def prune3d(A3: SpParMat3D, pred) -> SpParMat3D:
    """Drop entries where ``pred(val)`` (≈ SpParMat::Prune)."""

    def body(rows, cols, vals, nnz):
        t = A3.local_tile(rows, cols, vals, nnz)
        s = t._select(t.valid_mask() & ~pred(t.vals))
        return (
            s.rows[None, None, None], s.cols[None, None, None],
            s.vals[None, None, None], s.nnz[None, None, None],
        )

    r, c, v, n = jax.shard_map(
        body,
        mesh=A3.grid.mesh,
        in_specs=(TILE3_SPEC,) * 4,
        out_specs=(TILE3_SPEC,) * 4,
        check_vma=False,
    )(A3.rows, A3.cols, A3.vals, A3.nnz)
    return dataclasses.replace(A3, rows=r, cols=c, vals=v, nnz=n)


@partial(jax.jit, static_argnames=("fn",))
def apply3d(A3: SpParMat3D, fn) -> SpParMat3D:
    """Elementwise value transform (≈ SpParMat::Apply)."""
    valid = A3.rows < A3.tile_rows
    return dataclasses.replace(
        A3, vals=jnp.where(valid, fn(A3.vals), A3.vals)
    )


@partial(jax.jit, static_argnames=("fn",))
def dim_apply3d_cols(A3: SpParMat3D, colvec: Array, fn) -> SpParMat3D:
    """vals[i,j] = fn(vals[i,j], colvec[j]) (≈ SpParMat::DimApply(Column))."""
    _check_colsplit(A3)

    def body(rows, cols, vals, nnz, vblk):
        t = A3.local_tile(rows, cols, vals, nnz)
        v = vblk[0, 0]
        vpad = jnp.concatenate([v, jnp.zeros((1,), v.dtype)])
        idx = jnp.minimum(t.cols, v.shape[0])
        new_vals = jnp.where(t.valid_mask(), fn(t.vals, vpad[idx]), t.vals)
        return (
            t.rows[None, None, None], t.cols[None, None, None],
            new_vals[None, None, None], t.nnz[None, None, None],
        )

    r, c, v, n = jax.shard_map(
        body,
        mesh=A3.grid.mesh,
        in_specs=(TILE3_SPEC,) * 4 + (COLVEC3_SPEC,),
        out_specs=(TILE3_SPEC,) * 4,
        check_vma=False,
    )(A3.rows, A3.cols, A3.vals, A3.nnz, colvec)
    return dataclasses.replace(A3, rows=r, cols=c, vals=v, nnz=n)


def resplit3d_fixed(
    A3: SpParMat3D, split: str, *, stage_capacity: int, tile_capacity: int
) -> tuple[SpParMat3D, Array]:
    """``resplit3d`` with CALLER-FROZEN capacities and no host sizing or
    retry: returns (converted matrix, device scalar dropped-tuple count).

    The zero-readback building block for iteration blocks (MCL
    ``chaos_every``): the caller checks ``dropped`` at its sync point and
    rerolls with bigger capacities instead of this function reading back
    per call."""
    if A3.split == split:
        return A3, jnp.zeros((), jnp.int32)
    gr, gc, gv = _globalize3d(A3)
    return redistribute_coo3d(
        A3.grid, gr, gc, gv, A3.nrows, A3.ncols, split=split,
        stage_capacity=stage_capacity, tile_capacity=tile_capacity,
    )


def resplit3d(A3: SpParMat3D, split: str, *, slack: float = 2.0,
              max_retries: int = 3) -> SpParMat3D:
    """Convert between col-split and row-split layouts on the same 3D grid
    (the orientation change MemEfficientSpGEMM3D needs between iterations:
    SUMMA3D consumes A col-split x B row-split and produces col-split).

    Globalize + 3-hop reroute; same engine as the 2D<->3D conversions.
    """
    if A3.split == split:
        return A3
    gr, gc, gv = _globalize3d(A3)
    total = int(np.asarray(jnp.sum(A3.nnz)))
    g3 = A3.grid

    def route(stage_cap, tile_cap):
        return redistribute_coo3d(
            g3, gr, gc, gv, A3.nrows, A3.ncols, split=split,
            stage_capacity=stage_cap, tile_capacity=tile_cap,
        )

    return _route_with_retry(
        route, gr.shape[-1], (g3.pc, g3.pr, g3.layers), total,
        g3.layers * g3.pr * g3.pc, slack, max_retries, "3D resplit",
    )
