"""The functor zoo (≈ Operations.h:46-300) as stable module-level callables.

The reference ships a collection of unary/binary functors for Apply/Reduce/
EWiseApply (maximum, minimum, safemultinv, SetIfNotEqual, bitwise ops,
sel2nd, totality, exponentiate, RandReduce). Here each is a module-level
jittable function — which doubles as the compile-cache discipline this
package asks of callbacks (stable identity → one compiled executable per
use site; see parallel/spmat.py docstring).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp


# --- binary fold / combine ops ---------------------------------------------

def maximum(a, b):
    """≈ maximum<T> (Operations.h:154)."""
    return jnp.maximum(a, b)


def minimum(a, b):
    """≈ minimum<T> (Operations.h:172)."""
    return jnp.minimum(a, b)


def plus(a, b):
    return a + b


def multiplies(a, b):
    return a * b


def sel1st(a, b):
    """Keep the first operand."""
    return a


def sel2nd(a, b):
    """≈ sel2nd (Operations.h) — keep the second operand."""
    return b


def logical_or(a, b):
    return jnp.logical_or(a != 0, b != 0)


def logical_and(a, b):
    return jnp.logical_and(a != 0, b != 0)


def bitwise_or(a, b):
    """≈ bitwise ops (Operations.h:233-300)."""
    return a | b


def bitwise_and(a, b):
    return a & b


def bitwise_xor(a, b):
    return a ^ b


@lru_cache(maxsize=None)
def set_if_not_equal(sentinel: float):
    """≈ SetIfNotEqual (Operations.h:207): keep a where a != sentinel, else
    take b. Returns a cached closure so each sentinel keys one executable."""

    def f(a, b):
        return jnp.where(a != sentinel, a, b)

    return f


def rand_reduce(key, a, b):
    """≈ RandReduce (Operations.h:185): pick between operands by a coin
    flip — callers thread a PRNG key (our deterministic stream analog)."""
    return jnp.where(jax.random.bernoulli(key, 0.5, jnp.shape(a)), a, b)


# --- unary ops --------------------------------------------------------------

def identity(v):
    return v


def safemultinv(v):
    """≈ safemultinv (Operations.h:103): 1/x with 0 mapped to 0 (the
    reference maps to numeric max; 0 is the inert choice under our padded
    representation — MakeColStochastic semantics are unchanged)."""
    return jnp.where(v != 0, 1.0 / jnp.where(v != 0, v, 1), 0.0)


def totality(v):
    """≈ totality (Operations.h): constant true — structural counting."""
    return jnp.ones(jnp.shape(v), jnp.bool_)


@lru_cache(maxsize=None)
def exponentiate(power: float):
    """≈ exponentiate (MCL's inflation functor), cached per power."""

    def f(v):
        return v**power

    return f


def negate(v):
    return -v


def absolute(v):
    return jnp.abs(v)
