"""PageRank — power iteration with teleport (≈ Applications/PageRank.cpp).

The reference computes out-degrees with ``A.Reduce(Column)``
(``PageRank.cpp:97``), normalizes columns with ``DimApply``, and runs the
``SpMV<PlusTimes>`` power loop (``:126-157``).  Same schedule here, with the
dangling-mass correction folded in (columns with zero out-degree teleport
uniformly), and the whole loop compiled as one ``lax.while_loop`` with an
L1-convergence test.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from . import PAD_ROOT
from ..semiring import PLUS_TIMES
from ..parallel.spmat import SpParMat, ones_f32
from ..parallel.spmv import dist_spmv
from ..parallel.vec import DistVec


def _scale(a, s):
    return a * s


def pagerank(A, alpha=0.85, tol=1e-6, max_iters=100):
    """Eager wrapper over ``_pagerank_impl`` (plain-outputs law)."""
    blocks, niter = _pagerank_impl(
        A, alpha=alpha, tol=tol, max_iters=max_iters
    )
    return (
        DistVec(blocks=blocks, length=A.nrows, align="row", grid=A.grid),
        niter,
    )


@partial(jax.jit, static_argnames=("alpha", "tol", "max_iters"))
def _pagerank_impl(
    A: SpParMat,
    alpha: float = 0.85,
    tol: float = 1e-6,
    max_iters: int = 100,
):
    """Ranks over the column-stochastic normalization of A.

    A[i, j] != 0 means edge j -> i (j links to i). Returns PLAIN
    (row-aligned float32 rank blocks summing to 1, iterations) — the
    eager wrapper above rebuilds the DistVec (plain-outputs law).
    """
    grid = A.grid
    n = A.nrows
    # Out-degree of j = # entries in column j (structural).
    outdeg = A.reduce(PLUS_TIMES, axis="rows", map_fn=ones_f32)
    inv_deg = outdeg.apply(
        lambda d: jnp.where(d > 0, 1.0 / jnp.maximum(d, 1.0), 0.0)
    )
    # Column-stochastic scale: P[i,j] = A[i,j] / outdeg[j] (structure-wise).
    P = A.apply(ones_f32).dim_apply(inv_deg, _scale, axis="cols")
    dangling = outdeg.apply(lambda d: (d == 0).astype(jnp.float32))
    # Mask padding columns out of the dangling-mass sum.
    col_gids = DistVec.iota(grid, n, jnp.int32, align="col").blocks
    dang_mask = jnp.where(col_gids < n, dangling.blocks, 0.0)

    x0 = jnp.where(
        DistVec.iota(grid, n, jnp.int32, align="row").blocks < n, 1.0 / n, 0.0
    )

    def mk_row(blocks):
        return DistVec(blocks=blocks, length=n, align="row", grid=grid)

    def cond(state):
        _, err, it = state
        return (err > tol) & (it < max_iters)

    def step(state):
        xb, _, it = state
        x_col = mk_row(xb).realign("col")
        spread = dist_spmv(PLUS_TIMES, P, x_col)
        dmass = jnp.sum(dang_mask * x_col.blocks)
        base = (1.0 - alpha) / n + alpha * dmass / n
        nb = alpha * spread.blocks + base
        nb = jnp.where(
            DistVec.iota(grid, n, jnp.int32, align="row").blocks < n, nb, 0.0
        )
        err = jnp.sum(jnp.abs(nb - xb))
        return nb, err, it + 1

    xb, _, niter = jax.lax.while_loop(
        cond, step, (x0, jnp.float32(jnp.inf), jnp.int32(0))
    )
    return xb, niter


def pagerank_batch(P_ell, sources, dangling, alpha=0.85, tol=1e-6,
                   max_iters=100):
    """Eager wrapper over ``_pagerank_batch_impl`` (plain-outputs law)."""
    from ..parallel.vec import DistMultiVec

    blocks, niter = _pagerank_batch_impl(
        P_ell, sources, dangling, alpha=alpha, tol=tol,
        max_iters=max_iters,
    )
    return (
        DistMultiVec(
            blocks=blocks, length=P_ell.nrows, align="row",
            grid=P_ell.grid,
        ),
        niter,
    )


@partial(jax.jit, static_argnames=("alpha", "tol", "max_iters"))
def _pagerank_batch_impl(
    P_ell,
    sources: jax.Array,
    dangling: "DistVec",
    alpha: float = 0.85,
    tol: float = 1e-6,
    max_iters: int = 100,
):
    """Personalized PageRank for W sources in ONE program (the multi-root
    amortization of the batched BFS applied to PageRank: the measured chip
    gather is per-INDEX bound with payload lanes nearly free, so W rank
    chains cost ~one — PERF_NOTES_r2.md 'batching many PageRank chains').

    ``P_ell``: the COLUMN-NORMALIZED transition matrix as an EllParMat
    (entry (i,j) = 1/outdeg(j) for edge j->i — normalize host-side while
    building the ELL buckets). ``sources``: [W] int32 personalization
    vertices; slots holding ``models.PAD_ROOT`` are inert padding lanes
    (all-zero ranks — the serve batcher's lane padding). Returns
    (row-aligned DistMultiVec of ranks [n, W] — each live lane sums to
    1, teleporting to ITS source — and the iteration count).

    Reference: ``PageRank.cpp:126-157``'s loop, batched; personalization
    follows the standard PPR formulation (teleport to e_s instead of 1/n).
    """
    from ..parallel.ellmat import dist_spmv_ell_multi
    from ..parallel.vec import DistMultiVec

    grid = P_ell.grid
    n = P_ell.nrows
    W = sources.shape[0]

    row_gids = DistVec.iota(grid, n, jnp.int32, align="row").blocks  # [pr, lr]
    # PAD_ROOT lanes get an all-zero teleport vector: they carry no mass
    # and converge immediately (the iota gid table never holds PAD_ROOT,
    # but the explicit guard keeps the contract independent of that)
    live = (sources[None, None, :] != PAD_ROOT)
    e_s = (
        (row_gids[..., None] == sources[None, None, :]) & live
    ).astype(jnp.float32)
    dang_row = dangling.realign("row").blocks  # [pr, lr]
    rowvalid = (row_gids < n)[..., None]

    def mk(blocks):
        return DistMultiVec(blocks=blocks, length=n, align="row", grid=grid)

    def cond(state):
        _, err, it = state
        return (err > tol) & (it < max_iters)

    def step(state):
        xb, _, it = state
        spread = dist_spmv_ell_multi(PLUS_TIMES, P_ell, mk(xb))
        # per-lane dangling mass teleports to that lane's source
        dmass = jnp.sum(dang_row[..., None] * xb, axis=(0, 1))  # [W]
        nb = alpha * (spread.blocks + dmass[None, None, :] * e_s) + (
            1.0 - alpha
        ) * e_s
        nb = jnp.where(rowvalid, nb, 0.0)
        err = jnp.max(jnp.sum(jnp.abs(nb - xb), axis=(0, 1)))
        return nb, err, it + 1

    xb, _, niter = jax.lax.while_loop(
        cond, step, (e_s, jnp.float32(jnp.inf), jnp.int32(0))
    )
    return xb, niter
