"""PageRank — power iteration with teleport (≈ Applications/PageRank.cpp).

The reference computes out-degrees with ``A.Reduce(Column)``
(``PageRank.cpp:97``), normalizes columns with ``DimApply``, and runs the
``SpMV<PlusTimes>`` power loop (``:126-157``).  Same schedule here, with the
dangling-mass correction folded in (columns with zero out-degree teleport
uniformly), and the whole loop compiled as one ``lax.while_loop`` with an
L1-convergence test.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..semiring import PLUS_TIMES
from ..parallel.spmat import SpParMat, ones_f32
from ..parallel.spmv import dist_spmv
from ..parallel.vec import DistVec


def _scale(a, s):
    return a * s


@partial(jax.jit, static_argnames=("alpha", "tol", "max_iters"))
def pagerank(
    A: SpParMat,
    alpha: float = 0.85,
    tol: float = 1e-6,
    max_iters: int = 100,
) -> tuple[DistVec, jax.Array]:
    """Ranks over the column-stochastic normalization of A.

    A[i, j] != 0 means edge j -> i (j links to i). Returns (row-aligned
    float32 ranks summing to 1, iterations).
    """
    grid = A.grid
    n = A.nrows
    # Out-degree of j = # entries in column j (structural).
    outdeg = A.reduce(PLUS_TIMES, axis="rows", map_fn=ones_f32)
    inv_deg = outdeg.apply(
        lambda d: jnp.where(d > 0, 1.0 / jnp.maximum(d, 1.0), 0.0)
    )
    # Column-stochastic scale: P[i,j] = A[i,j] / outdeg[j] (structure-wise).
    P = A.apply(ones_f32).dim_apply(inv_deg, _scale, axis="cols")
    dangling = outdeg.apply(lambda d: (d == 0).astype(jnp.float32))
    # Mask padding columns out of the dangling-mass sum.
    col_gids = DistVec.iota(grid, n, jnp.int32, align="col").blocks
    dang_mask = jnp.where(col_gids < n, dangling.blocks, 0.0)

    x0 = jnp.where(
        DistVec.iota(grid, n, jnp.int32, align="row").blocks < n, 1.0 / n, 0.0
    )

    def mk_row(blocks):
        return DistVec(blocks=blocks, length=n, align="row", grid=grid)

    def cond(state):
        _, err, it = state
        return (err > tol) & (it < max_iters)

    def step(state):
        xb, _, it = state
        x_col = mk_row(xb).realign("col")
        spread = dist_spmv(PLUS_TIMES, P, x_col)
        dmass = jnp.sum(dang_mask * x_col.blocks)
        base = (1.0 - alpha) / n + alpha * dmass / n
        nb = alpha * spread.blocks + base
        nb = jnp.where(
            DistVec.iota(grid, n, jnp.int32, align="row").blocks < n, nb, 0.0
        )
        err = jnp.sum(jnp.abs(nb - xb))
        return nb, err, it + 1

    xb, _, niter = jax.lax.while_loop(
        cond, step, (x0, jnp.float32(jnp.inf), jnp.int32(0))
    )
    return mk_row(xb), niter
