"""Betweenness centrality — batched Brandes (≈ Applications/BetwCent.cpp).

The reference runs BFS from ``batchSize`` roots simultaneously by making the
frontier a sparse n × batch MATRIX: each forward level is one SpGEMM
(``PSpGEMM<PTBOOLINT>``, BetwCent.cpp:179-218), path counts accumulate into a
``DenseParMat``, and the backward (dependency) sweep re-walks the stored
level fringes with elementwise rescales. This is parallelism strategy #7 of
SURVEY §2.3 — batch parallelism over sources — and it maps perfectly to the
TPU: the batch dimension widens every kernel, feeding the MXU/VPU lanes.

Forward, per level d (host loop, like the reference's; orientation:
A[i,j] != 0 is edge j→i, the BFS convention, so path counts PULL from
predecessors via A and dependencies pull from successors via Aᵀ):
    fringe ← A ⊗ fringe             (SUMMA on the n × batch fringe)
    fringe ← fringe .!(nsp > 0)     (drop already-settled vertices)
    nsp    ← nsp + fringe           (dense accumulate of path counts)
Backward (Brandes dependency):
    w      ← fringe_d .* (1 + delta)/nsp     (dense-indexed rescale)
    contrib← Aᵀ ⊗ w
    delta  ← delta + (contrib .* fringe_{d-1}) * nsp_{d-1}
    bc     ← bc + Σ_batch delta

``bc_batch_dense`` is the one-launch redesign: dense [n, W] level/path
lanes, both sweeps under lax control flow, zero readbacks.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..semiring import PLUS_TIMES
from ..parallel.dense import DenseParMat
from ..parallel.grid import Grid
from ..parallel.spgemm import spgemm
from ..parallel.spmat import SpParMat
from ..parallel.vec import DistVec


def _keep_unsettled(sval, nsp_val):
    return nsp_val == 0


def _replace_with_dense(sval, dval):
    return dval


def _mul_combine(a, b):
    return a * b


def _sources_fringe(grid: Grid, sources, n: int, dtype) -> SpParMat:
    """n × batch selector: column k starts at source_k with 1 path."""
    sources = np.asarray(sources, dtype=np.int64)
    return SpParMat.from_global_coo(
        grid, sources, np.arange(len(sources)), np.ones(len(sources), dtype),
        n, len(sources),
    )


def bc_batch(A: SpParMat, sources, AT: SpParMat | None = None) -> DistVec:
    """Partial BC scores from one batch of source vertices (row-aligned
    float vector of dependency sums; endpoints excluded per Brandes).

    ``AT`` lets multi-batch callers hoist the transpose (a full distributed
    tile exchange) out of the batch loop.
    """
    grid = A.grid
    n = A.nrows
    if AT is None:
        AT = A.transpose()
    fringe = _sources_fringe(grid, sources, n, np.dtype(A.dtype))
    nsp = DenseParMat.zeros(grid, n, len(np.asarray(sources)), A.dtype)
    nsp = nsp.add_spmat(fringe)

    levels: list[SpParMat] = [fringe]
    # Forward sweep (host loop: depth is data-dependent, as in the
    # reference's while(fringe.getnnz() > 0), BetwCent.cpp:179).
    # Orientation: A[i,j] != 0 is edge j->i (the BFS convention), so path
    # counts PULL from predecessors via A; the backward dependency sweep
    # pulls from successors via AT. (Round-2 had these swapped — invisible
    # on symmetric graphs, wrong on directed ones; caught by the
    # bc_batch_dense cross-check against textbook Brandes.)
    while True:
        fringe = spgemm(PLUS_TIMES, A, fringe)
        fringe = nsp.filter_spmat(fringe, _keep_unsettled)
        if int(fringe.getnnz()) == 0:
            break
        nsp = nsp.add_spmat(fringe)
        levels.append(fringe)

    delta = DenseParMat.zeros(grid, n, nsp.ncols, A.dtype)
    # Backward dependency sweep (BetwCent.cpp:207-218): per Brandes,
    # delta[v] = Σ_{succ w} (nsp[v]/nsp[w]) (1 + delta[w]); on level-d
    # structure, w carries (1+delta)/nsp, the product A⊗w propagates to the
    # d-1 fringe, and the fringe's own values supply the nsp[v] factor.
    for d in range(len(levels) - 1, 0, -1):
        ratio = delta.ewise(nsp, _one_plus_a_over_b)
        w = ratio.scale_spmat(levels[d], _replace_with_dense)
        contrib = spgemm(PLUS_TIMES, AT, w)
        upd = contrib.ewise_mult(levels[d - 1], combine=_mul_combine)
        delta = delta.add_spmat(upd)
    total = delta.reduce(PLUS_TIMES, "cols")
    # Brandes excludes the source's own accumulated dependency (bc[w] only
    # sums over w != s): subtract delta at each batch's (source_k, k) slot.
    src_delta = delta.scale_spmat(levels[0], _replace_with_dense)
    correction = src_delta.reduce(PLUS_TIMES, "cols")
    return total.ewise(correction, jnp.subtract)


def _one_plus_a_over_b(delta_b, nsp_b):
    return jnp.where(nsp_b > 0, (1.0 + delta_b) / jnp.maximum(nsp_b, 1e-30), 0.0)


def betweenness_centrality(
    A: SpParMat,
    batch_size: int | None = None,
    sources=None,
    normalize: bool = False,
) -> DistVec:
    """Exact (all-sources) or sampled BC.

    ``sources`` defaults to all vertices, processed in batches of
    ``batch_size`` (default: one batch). For undirected graphs each pair is
    counted twice — pass ``normalize=True`` to halve, matching the usual
    undirected convention.
    """
    n = A.nrows
    srcs = np.arange(n) if sources is None else np.asarray(sources)
    if len(srcs) == 0:
        return DistVec.full(A.grid, n, 0, A.dtype, align="row")
    bs = batch_size or len(srcs)
    AT = A.transpose()
    acc = None
    for s in range(0, len(srcs), bs):
        part = bc_batch(A, srcs[s : s + bs], AT=AT)
        acc = part if acc is None else acc.ewise(part, jnp.add)
    if normalize:
        acc = acc.apply(lambda b: b * 0.5)
    return acc


def bc_batch_dense(E, ET, sources, max_depth: int | None = None):
    """Eager wrapper over ``_bc_batch_dense_impl`` (plain-outputs law)."""
    total = _bc_batch_dense_impl(E, ET, sources, max_depth=max_depth)
    return DistVec(
        blocks=total, length=E.nrows, align="row", grid=E.grid
    )


def bc_batch_dense_lanes(E, ET, sources, max_depth: int | None = None):
    """Per-lane Brandes dependencies: the [n, W] delta matrix BEFORE the
    cross-lane sum — lane k is the single-source dependency vector of
    ``sources[k]`` (what a serve request for one root wants back).
    ``models.PAD_ROOT`` source slots yield all-zero lanes. Summing the
    lanes reproduces ``bc_batch_dense`` exactly.
    """
    from ..parallel.vec import DistMultiVec

    delta = _bc_batch_dense_impl(
        E, ET, sources, max_depth=max_depth, per_lane=True
    )
    return DistMultiVec(
        blocks=delta, length=E.nrows, align="row", grid=E.grid
    )


@partial(jax.jit, static_argnames=("max_depth", "per_lane"))
def _bc_batch_dense_impl(E, ET, sources, max_depth: int | None = None,
                         per_lane: bool = False):
    """Batched Brandes in ONE compiled program over dense [n, W] state.

    The host-loop ``bc_batch`` mirrors the reference's
    ``while(fringe.getnnz())`` shape (BetwCent.cpp:179) — per-level SpGEMM
    sizing readbacks, which are launch-poison on the target chip. This
    variant is the TPU-native redesign: levels and path counts live as
    dense [n, W] lanes (the batched-BFS state layout), every sweep step is
    one multi-lane ELL SpMV, and both sweeps run under ``lax`` control
    flow — zero device→host readbacks.

    ``E``: adjacency with entry (i, j) = edge j→i (the BFS gather
    orientation); ``ET``: its transpose (pass the same EllParMat for
    symmetric graphs). ``sources``: [W] int32. Returns the row-aligned
    partial BC DistVec (dependency sums over these W sources, endpoints
    excluded per Brandes).
    """
    from ..parallel.ellmat import dist_spmv_ell_multi
    from ..parallel.vec import DistMultiVec

    grid = E.grid
    n = E.nrows
    W = sources.shape[0]
    D = max_depth if max_depth is not None else n

    gids = DistVec.iota(grid, n, jnp.int32, align="row").blocks  # [pr, lr]
    # models.PAD_ROOT lanes are inert (all-zero dependencies — the
    # serve batcher's lane padding). The iota gid table pads with ids
    # >= n so PAD_ROOT can never match, but the explicit guard keeps
    # the contract independent of the gid-table padding convention
    # (the -1-padded _global_ids tables WOULD match).
    from . import PAD_ROOT

    live = sources[None, None, :] != PAD_ROOT
    is_src = (gids[..., None] == sources[None, None, :]) & live
    lvl0 = jnp.where(is_src, 0, -1).astype(jnp.int32)
    nsp0 = is_src.astype(E.dtype)

    def mk(blocks):
        return DistMultiVec(blocks=blocks, length=n, align="row", grid=grid)

    def fcond(st):
        d, _, _, active = st
        return active & (d < D)

    def fstep(st):
        d, lvl, nsp, _ = st
        frontier = jnp.where(lvl == d, nsp, 0)
        arriving = dist_spmv_ell_multi(PLUS_TIMES, E, mk(frontier)).blocks
        new = (arriving > 0) & (lvl < 0)
        lvl = jnp.where(new, d + 1, lvl)
        nsp = nsp + jnp.where(new, arriving, 0)
        return d + 1, lvl, nsp, jnp.any(new)

    depth, lvl, nsp, still_active = jax.lax.while_loop(
        fcond, fstep, (jnp.int32(0), lvl0, nsp0, jnp.bool_(True))
    )

    # Backward dependency sweep: d = depth ... 1; every level-(d) vertex
    # w exports (1+delta[w])/nsp[w]; level-(d-1) predecessors v collect it
    # along their out-edges and scale by nsp[v]. Starting at d = depth
    # (one past the last level on natural exit — a no-op there) keeps the
    # deepest level's exports when the max_depth bound cut the forward
    # sweep short; the loop bound is the TRACED depth, so only the real
    # levels run (fori_loop lowers a traced bound to a while_loop).
    def bstep(k, delta):
        d = depth - k
        wmask = (lvl == d) & (nsp > 0)
        w = jnp.where(
            wmask, (1.0 + delta) / jnp.maximum(nsp, 1e-30), 0
        ).astype(E.dtype)
        collected = dist_spmv_ell_multi(PLUS_TIMES, ET, mk(w)).blocks
        upd = jnp.where(lvl == d - 1, collected * nsp, 0)
        return delta + upd

    # on natural exit level `depth` is empty (the last step found
    # nothing) — skip its guaranteed no-op SpMV; when the max_depth bound
    # cut the sweep short (still_active), level `depth` is real
    start = jnp.where(still_active, 0, 1)
    delta = jax.lax.fori_loop(
        start, depth, bstep, jnp.zeros_like(nsp0)
    )
    # endpoints excluded: zero each lane's own source slot, sum lanes
    # (``per_lane=True`` skips the sum — the serve path hands each lane
    # back to its own request)
    delta = jnp.where(is_src, 0, delta)
    if per_lane:
        return delta
    total = jnp.sum(delta, axis=-1)
    return total
