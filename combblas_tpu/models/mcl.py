"""HipMCL — distributed Markov clustering (≈ Applications/MCL.cpp).

The reference's flagship application (Azad, Pavlopoulos, Ouzounis, Kyrpides,
Buluç; HipMCL, NAR'18): iterate {expand = A², inflate = Hadamard power +
column re-normalization, prune} until the "chaos" (per-column deviation from
idempotence) drops below EPS, then read clusters off the converged matrix as
connected components (``MCL.cpp:515-660``).

TPU-native expression:

* expansion is the phased SUMMA (``mem_efficient_spgemm``) with the
  prune/recover/select hook applied per phase, exactly the
  ``MemEfficientSpGEMM`` flow (ParFriends.h:450-731);
* pruning thresholds come from ``SpParMat.kselect`` — a radix-select over
  order-preserving keys instead of the reference's chunked column gather +
  median-of-medians (``SpParMat::Kselect1``, SpParMat.cpp:1120-1742);
* column stochasticization / inflation / chaos are Reduce(Column) +
  DimApply compositions, mirroring ``MakeColStochastic`` / ``Inflate`` /
  ``Chaos`` (``MCL.cpp:390-453``);
* cluster interpretation symmetrizes the converged matrix and runs FastSV
  connected components (``MCL.cpp:646``).

The outer loop is a host loop (like the reference's) because each iteration's
nnz — and therefore the static capacities — changes; every step inside an
iteration is one jitted SPMD program.
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache

import jax.numpy as jnp

from .. import obs
from ..semiring import MAX_MIN, PLUS_TIMES
from ..parallel.spgemm import mem_efficient_spgemm
from ..parallel.spmat import SpParMat
from ..parallel.vec import DistVec
from .cc import connected_components


# Module-level callbacks: stable identities keep the jit caches of
# dim_apply / prune / prune_column / reduce warm across MCL iterations.
def _square(v):
    return v * v


def _stochastic_scale(v, s):
    return jnp.where(s != 0, v / jnp.where(s != 0, s, 1), v)


def _keep_ge(v, t):
    return v >= t


@lru_cache(maxsize=None)
def _lt_pred(threshold: float):
    def pred(v):
        return v < threshold

    return pred


@lru_cache(maxsize=None)
def _pow_fn(power: float):
    def f(v):
        return v**power

    return f


def make_col_stochastic(A: SpParMat) -> SpParMat:
    """Scale each column to sum 1 (empty columns unchanged).

    Reference: ``MakeColStochastic`` (MCL.cpp:390: Reduce(Column, plus) +
    Apply(safemultinv) + DimApply(multiplies)).
    """
    sums = A.reduce(PLUS_TIMES, "rows")
    return A.dim_apply(sums, _stochastic_scale, "cols")


def chaos(A: SpParMat) -> jnp.ndarray:
    """max over columns of nnz_j · (column max − column sum-of-squares).

    The MCL convergence residual (``Chaos``, MCL.cpp:408-422): zero exactly
    when every column is idempotent (a single 1); the reference scales each
    column's deviation by its nonzero count. Assumes A is column-stochastic.
    """
    colmax = A.reduce(MAX_MIN, "rows")
    colssq = A.reduce(PLUS_TIMES, "rows", map_fn=_square)
    nnzc = A.nnz_per_column()
    diff = colmax.ewise(colssq, lambda m, s: m - s)
    # Empty/padding columns: colmax = -inf; force their term to 0 (the
    # reference's max-identity-0 behaves the same for nonneg matrices).
    scaled = diff.ewise(
        nnzc, lambda d, c: jnp.where(c > 0, d * c.astype(d.dtype), 0)
    )
    return scaled.reduce(MAX_MIN)


def inflate(A: SpParMat, power: float) -> SpParMat:
    """Hadamard power + column re-normalization.

    Reference: ``Inflate`` (MCL.cpp:447: Apply(exponentiate) +
    MakeColStochastic).
    """
    return make_col_stochastic(A.apply(_pow_fn(power)))


def mcl_prune_recovery_select(
    C: SpParMat,
    hard_threshold: float = 1e-8,
    select_num: int = 1100,
    recover_num: int = 1400,
    recover_pct: float = 0.9,
    device_gate: bool = False,
) -> SpParMat:
    """The MCL column sparsifier.

    Reference: ``MCLPruneRecoverySelect`` (ParFriends.h:186-350):
      1. hard-threshold prune (drop values below ``hard_threshold``),
      2. per-column top-``select_num`` selection via Kselect threshold,
      3. recovery: columns that lost more than ``1 - recover_pct`` of their
         mass relax to the top-``recover_num`` threshold instead (columns
         with fewer than ``recover_num`` entries recover fully).

    ``device_gate=True`` keeps the recovery decision ON DEVICE (always
    compute the recover-side kselect, blend with ``where``) — required
    inside a zero-readback iteration block (see ``mcl(chaos_every=...)``);
    the default host gate skips that kselect in the common no-recovery
    case, which is cheaper when the loop syncs anyway.
    """
    if hard_threshold > 0:
        C = C.prune(_lt_pred(float(hard_threshold)))
    s_th = C.kselect(select_num)
    pruned = C.prune_column(s_th, keep=_keep_ge)
    kept = pruned.reduce(PLUS_TIMES, "rows")
    orig = C.reduce(PLUS_TIMES, "rows")
    need_recover = kept.ewise(orig, lambda k, o: k < recover_pct * o)
    # Host-side gate (the per-sync loop): the recover-side kselect is the
    # sparsifier's most expensive collective — skip it in the common case
    # where no column lost enough mass, as the reference gates recovery on
    # the measured loss (ParFriends.h:266-311).
    if not device_gate and not bool(need_recover.blocks.any()):
        return pruned
    r_th = C.kselect(recover_num)
    relaxed = r_th.ewise(s_th, jnp.minimum)
    final = dataclasses.replace(
        s_th, blocks=jnp.where(need_recover.blocks, relaxed.blocks, s_th.blocks)
    )
    return C.prune_column(final, keep=_keep_ge)


def mcl(
    A: SpParMat,
    inflation: float = 2.0,
    *,
    eps: float = 1e-3,
    max_iters: int = 40,
    phases: int = 1,
    select_num: int = 1100,
    recover_num: int = 1400,
    recover_pct: float = 0.9,
    hard_threshold: float = 1e-4,
    add_self_loops: bool = True,
    layers: int = 1,
    grid3=None,
    scan: bool = False,
    chaos_every: int = 1,
    expansion: str = "sparse",
    dense_mode: str = "bf16x3",
    perturb_delta: float = 0.0,
) -> tuple[DistVec, int, float]:
    """Markov clustering. Returns (cluster labels, iterations, final chaos).

    ``phases > 1`` requires n % (grid.pc * phases) == 0 (the local column
    split); otherwise expansion falls back to unphased with a warning.

    ``layers > 1`` runs the communication-avoiding 3D expansion path
    (HipMCL's production configuration, MCL.cpp:574-588 with layers>1):
    the matrix converts on-device to a col-split ``SpParMat3D`` on a
    layers × pr × pc grid (``grid3`` overrides the default square
    factorization), every iteration resplits a row-split copy, expands with
    ``mem_efficient_spgemm3d`` + the 3D prune/recover/select hook, and
    stochasticization/chaos/inflation run as per-layer column ops. The
    converged matrix converts back to 2D for cluster interpretation.

    Reference driver: ``HipMCL`` (MCL.cpp:515-660); defaults mirror
    ``InitParam`` (MCL.cpp:144-150: prunelimit 1e-4, select 1100, recover
    1400/0.9). Per reference loop order, chaos is measured on the expanded
    (pre-inflation) matrix. ``eps`` defaults to 1e-3 rather than the
    reference's 1e-4 (MCL.cpp:55) because our matrices are float32: the
    inflation step doubles relative rounding noise each iteration, so 1e-4
    sits below the float32 noise floor that double-precision CombBLAS can
    reach. Before interpretation, sub-``hard_threshold`` residue is pruned
    (the double-precision reference reaches exact zeros instead). Labels are
    a row-aligned int32 DistVec where each vertex carries the smallest
    vertex id of its cluster (the component labeling of the converged
    attractor structure).

    ``expansion="dense"`` (round 4; single shard, n ≲ 32K) runs the whole
    clustering as ONE jitted ``lax.while_loop`` with dense MXU squaring —
    no capacities, no overflow, no per-iteration readbacks; ``dense_mode``
    picks the matmul precision (see ``parallel.spgemm._mxu_dot``).  On
    the target chip this is >10x per iteration over the sparse path at
    scale 12-14 (PERF_NOTES_r4).

    ``perturb_delta`` (dense path only) enables the plateau
    detect-and-perturb kicks — OFF by default: the escalating self-loop
    mass can move boundary vertices between clusters, so LIBRARY callers
    opt in explicitly (ADVICE r5); the bench driver enables it and the
    kick count is recorded as a span event + artifact field.

    ``chaos_every=K > 1`` runs K expansion iterations per host
    synchronization with the chaos residual carried ON DEVICE — zero
    device→host readbacks inside a K-block. On hardware where any D2H
    readback degrades later launches (the axon chip; bench.py module
    docstring), this is the difference between one poisoned sync per
    iteration and one per K. Capacities are frozen at block entry (2x
    headroom, power-of-two) and every block verifies on-device overflow
    flags at its sync point; on overflow the block RERUNS from its saved
    entry state with doubled capacities, so results are exact. Requires
    ``phases == 1`` (the scan expansion already bounds memory by the
    output). The reference has no analog — its loop Allreduces chaos
    every iteration (MCL.cpp:564-627).
    """
    if add_self_loops:
        A = A.add_loops(jnp.asarray(1, A.dtype))
    A = make_col_stochastic(A)

    if expansion == "dense":
        # round 4: single-shard dense one-launch loop (see _mcl_dense_loop)
        assert layers == 1 and A.grid.size == 1, (
            "expansion='dense' is the single-shard MXU path"
        )
        A, it, ch = _mcl_dense_loop(
            A, inflation, eps, max_iters,
            dict(
                hard_threshold=hard_threshold, select_num=select_num,
                recover_num=recover_num, recover_pct=recover_pct,
            ),
            mode=dense_mode,
            perturb_delta=perturb_delta,
        )
    elif layers > 1:
        if grid3 is None:
            import math

            from ..parallel.mesh3d import Grid3D

            p2 = A.grid.size // layers
            p3 = int(math.isqrt(p2))
            assert layers * p3 * p3 == A.grid.size, (
                f"cannot factor {A.grid.size} devices into "
                f"{layers} layers x square grid; pass grid3= explicitly"
            )
            grid3 = Grid3D.make(layers, p3, p3)
        A, it, ch = _mcl3d_loop(
            A, grid3, inflation, eps, max_iters, phases,
            dict(
                hard_threshold=hard_threshold, select_num=select_num,
                recover_num=recover_num, recover_pct=recover_pct,
            ),
            chaos_every=chaos_every,
        )
    elif chaos_every > 1:
        assert phases == 1, "chaos_every>1 requires phases=1 (scan bounds memory)"
        A, it, ch = _mcl2d_block_loop(
            A, inflation, eps, max_iters, chaos_every,
            dict(
                hard_threshold=hard_threshold, select_num=select_num,
                recover_num=recover_num, recover_pct=recover_pct,
            ),
        )
        if hard_threshold > 0:
            A = A.prune(_lt_pred(float(hard_threshold)))
    else:

        def prune_fn(C):
            return mcl_prune_recovery_select(
                C, hard_threshold, select_num, recover_num, recover_pct
            )

        ch = float("inf")
        it = 0
        for it in range(1, max_iters + 1):
            with obs.span("mcl.round", round=it):
                # scan=True bounds the expansion by the output — exactly
                # the high-collision A-squared regime, flops >> nnz_out
                A = mem_efficient_spgemm(
                    PLUS_TIMES, A, A, phases, prune_fn=prune_fn, scan=scan
                )
                A = make_col_stochastic(A)
                ch = float(chaos(A))
                A = inflate(A, inflation)
                obs.span_event("chaos", round=it, chaos=ch)
            if ch < eps:
                break

        if hard_threshold > 0:  # drop float32 residue before interpretation
            A = A.prune(_lt_pred(float(hard_threshold)))
    sym = A.ewise_add(A.transpose(), PLUS_TIMES)
    labels, _ = connected_components(sym)
    return labels, it, ch


# --- K-iterations-per-sync block loop (zero D2H inside a block) ------------


def _mcl_block_caps(A: SpParMat) -> tuple[int, int]:
    """Frozen block capacities from one symbolic pass at the sync point:
    2x headroom over the CURRENT iteration's needs, power-of-two for
    compile-cache reuse across blocks."""
    import numpy as np

    from ..parallel.spgemm import summa_stage_flops

    from ..parallel.spgemm import host_value

    per_stage = host_value(summa_stage_flops(A, A)).astype(np.float64)
    rnd = lambda x: 1 << max(int(x) - 1, 1).bit_length()
    dense_tile = A.local_rows * A.local_cols
    fcap = rnd(per_stage.max() * 2)
    ocap = min(rnd(per_stage.sum(axis=0).max() * 2), max(dense_tile, 1))
    return fcap, ocap


def _mcl2d_iter_device(A, caps, inflation, prune_kwargs):
    """ONE MCL iteration with frozen capacities, entirely on device.

    Returns (A_next, chaos_scalar, overflow_scalar): overflow > 0 means a
    capacity was exceeded (expansion slots or distinct output keys) and
    the iteration's result is untrustworthy — the caller rerolls the block
    with doubled capacities.
    """
    from ..parallel.spgemm import summa_spgemm_scan, summa_stage_flops

    fcap, ocap = caps
    flop_need = jnp.max(summa_stage_flops(A, A))
    C, ov_out = summa_spgemm_scan(
        PLUS_TIMES, A, A, flop_capacity=fcap, out_capacity=ocap
    )
    C = mcl_prune_recovery_select(C, device_gate=True, **prune_kwargs)
    C = make_col_stochastic(C)
    ch = chaos(C)
    A_next = inflate(C, inflation)
    overflow = jnp.maximum(
        ov_out, (flop_need > fcap).astype(jnp.int32) * jnp.int32(1 << 30)
    )
    return A_next, ch, overflow


def _mcl2d_block_loop(A, inflation, eps, max_iters, K, prune_kwargs):
    """Host loop over K-iteration device blocks: one readback per block,
    exact results via save-and-reroll on capacity overflow."""
    ch = float("inf")
    it = 0
    caps = None
    while it < max_iters:
        if caps is None:
            caps = _mcl_block_caps(A)
        k = min(K, max_iters - it)
        A_entry = A
        worst = jnp.int32(0)
        for _ in range(k):
            A, ch_dev, ov = _mcl2d_iter_device(
                A, caps, inflation, prune_kwargs
            )
            worst = jnp.maximum(worst, ov)
        # SYNC POINT: the block's only device->host readbacks
        if int(worst) > 0:
            if obs.ENABLED:
                obs.count("mcl.block_rerolls")
            dense_tile = max(A_entry.local_rows * A_entry.local_cols, 1)
            caps = (caps[0] * 2, min(caps[1] * 2, dense_tile))
            A = A_entry
            continue
        ch = float(ch_dev)
        it += k
        obs.span_event("mcl.block_sync", iters_done=it, chaos=ch)
        if ch < eps:
            break
    return A, it, ch


# --- dense one-launch MCL (round 4) ----------------------------------------


def dense_mcl_program(n, npad, inflation, eps, max_iters, *, hard, select,
                      recover, rpct, mode, perturb_delta=0.0):
    """The jittable whole-clustering program used by ``_mcl_dense_loop``
    (and AOT-compiled by the benchmark driver, which must not execute a
    warmup — the warmup's readback would poison the timed run on the
    target chip).  Returns ``run(rows, cols, vals) -> (M_final, iters,
    chaos, chaos_history[max_iters], n_perturbations)``; the state M is
    Aᵀ (see ``_mcl_dense_loop``).

    PLATEAU DETECT-AND-PERTURB (round 5, VERDICT r4 Missing #3): under
    float32, MCL at the HipMCL default select=1100 can enter a PERIOD-2
    ATTRACTOR (scale-14 R-MAT plateaus at chaos 0.248 forever) — the f32
    tie structure is too symmetric for inflation to break, where the
    reference's double precision (MCL.cpp:564-627) accumulates the
    asymmetric rounding residue that eventually collapses the flip-flop.
    The loop carries the last two chaos values; when chaos returns to
    within 1e-3 (relative) of its value TWO iterations ago while still
    >= eps, the state is multiplied by a deterministic per-entry jitter
    field (1 + perturb_delta * hash(i, j)/2^16) and re-normalized — an
    explicit, counted emulation of that residue (ties break
    asymmetrically; the attractor loses its mirror symmetry). A lone
    5e-5 jitter measured 21 ineffective kicks against the stable
    scale-14 flip-flop, so each kick ALSO adds escalating self-loop mass
    (alpha = delta*4^kicks, capped ~0.8) — van Dongen's flip-flop remedy
    and the role of the reference's AdjustLoops colmax loops
    (MCL.cpp:462-471). Early kicks are cluster-neutral; a deep
    escalation trades the oscillating boundary vertices' assignment for
    termination, and the artifact records the kick count
    ("perturbations") so that trade is visible. ``perturb_delta=0``
    (THE DEFAULT — because kicks can alter cluster assignments, library
    callers must opt in; the bench driver passes 5e-5 explicitly,
    ADVICE r5) disables. The two post-perturbation iterations are
    excused from the detector (chaos history resets to inf)."""
    import jax

    from ..parallel.spgemm import _mxu_dot

    kr = max(select, recover)

    def one_iter(m):
        c = _mxu_dot(m, m, mode, jnp.float32)  # (A²)ᵀ
        if hard > 0:
            c = jnp.where(c < hard, 0.0, c)  # values are >= 0 (stochastic)
        topv, _ = jax.lax.top_k(c, kr)
        s_th = topv[:, select - 1]
        kept = jnp.sum(topv[:, :select], axis=1)
        orig = jnp.sum(c, axis=1)
        r_th = topv[:, recover - 1]
        th = jnp.where(kept < rpct * orig, jnp.minimum(r_th, s_th), s_th)
        # rows with fewer than select/recover entries see th == 0 and
        # recover fully; ties at the threshold are kept (kselect parity)
        c = jnp.where(c >= th[:, None], c, 0.0)
        rs = jnp.sum(c, axis=1, keepdims=True)
        c = c / jnp.where(rs > 0, rs, 1.0)
        cmax = jnp.max(c, axis=1)
        cssq = jnp.sum(c * c, axis=1)
        nnzr = jnp.sum(c > 0, axis=1)
        ch = jnp.max(jnp.where(nnzr > 0, (cmax - cssq) * nnzr, 0.0))
        c = c ** inflation
        rs = jnp.sum(c, axis=1, keepdims=True)
        c = c / jnp.where(rs > 0, rs, 1.0)
        return c, ch

    def perturb(args):
        """Escalating self-loop damping + deterministic jitter, then row
        re-normalization. Flip-flop limit cycles are STABLE attractors of
        the MCL map (van Dongen §flip-flop; a 5e-5 jitter alone measured
        21 ineffective kicks at chaos 0.24825, apps_bench r5) — the
        classical cure is MORE LOOP MASS (the role of the reference's
        AdjustLoops colmax loops, MCL.cpp:462-471), so each kick adds
        alpha = delta * 4^k to the diagonal (k = kicks so far, capped at
        alpha ~ 0.8) and breaks residual mirror symmetry with the tiny
        per-entry jitter."""
        m, npert = args
        alpha = jnp.minimum(
            perturb_delta
            * jnp.exp2(2.0 * jnp.minimum(npert, 8).astype(jnp.float32)),
            0.8,
        )
        i = jnp.arange(npad, dtype=jnp.int32)[:, None]
        j = jnp.arange(npad, dtype=jnp.int32)[None, :]
        h = (i * jnp.int32(-1640531527) + j * jnp.int32(40503)) & 0xFFFF
        m = m * (1.0 + perturb_delta * h.astype(jnp.float32) / 65536.0)
        m = m + alpha * jnp.eye(npad, dtype=jnp.float32)
        rs = jnp.sum(m, axis=1, keepdims=True)
        return m / jnp.where(rs > 0, rs, 1.0)

    def run(rows, cols, vals):
        m0 = jnp.zeros((npad, npad), jnp.float32)
        # transpose on the way in: M[j, i] = A[i, j]
        m0 = m0.at[cols, rows].set(vals.astype(jnp.float32), mode="drop")
        hist0 = jnp.zeros((max_iters,), jnp.float32)
        inf = jnp.float32(jnp.inf)

        def cond(state):
            _, it, ch, _, _, _, _ = state
            return (ch >= eps) & (it < max_iters)

        def body(state):
            m, it, _, hist, ch1, ch2, npert = state
            m2, ch = one_iter(m)
            if perturb_delta > 0:
                stuck = (
                    (ch >= eps)
                    & jnp.isfinite(ch2)
                    & (jnp.abs(ch - ch2) < 1e-3 * jnp.maximum(ch, 1e-30))
                )
                m2 = jax.lax.cond(
                    stuck, perturb, lambda a: a[0], (m2, npert)
                )
                npert = npert + stuck.astype(jnp.int32)
                # reset the history after a kick: the next two chaos
                # values reflect the transient, not the attractor
                ch1_n = jnp.where(stuck, inf, ch)
                ch2_n = jnp.where(stuck, inf, ch1)
            else:
                ch1_n, ch2_n = ch, ch1
            return (m2, it + 1, ch, hist.at[it].set(ch), ch1_n, ch2_n,
                    npert)

        m, it, ch, hist, _, _, npert = jax.lax.while_loop(
            cond, body,
            (m0, jnp.int32(0), inf, hist0, inf, inf, jnp.int32(0)),
        )
        if hard > 0:
            m = jnp.where(m < hard, 0.0, m)
        return m, it, ch, hist, npert

    return run


def _mcl_dense_loop(A, inflation, eps, max_iters, prune_kwargs,
                    mode="bf16x3", perturb_delta=0.0):
    """Single-shard MCL with DENSE state: the whole clustering runs as ONE
    ``lax.while_loop`` on the MXU — zero device→host readbacks, zero
    capacity estimation, overflow structurally impossible.

    Why dense: on the target chip the sparse expansion pays the ~22 M/s
    per-element random-memory wall several times per iteration (measured
    48 s/iter at scale 12, overflow-flagged — PERF_NOTES_r3), while the
    MXU squares a 16K dense matrix in ~0.7 s (13.3 TFLOP/s bf16,
    probe_r4a/d).  Below ~32K vertices the dense formulation wins by >10x
    AND eliminates the whole frozen-capacity/reroll machinery: pruning is
    a thresholded mask (ties keep, like the reference's kselect), chaos
    rides in the loop carry, and the only readback is the final state.

    The state is the TRANSPOSE M = Aᵀ: (A²)ᵀ = Mᵀᵀ... = M·M, so column
    operations (stochasticize / select / chaos — MCL.cpp:390-453) become
    ROW operations, the native axis for ``lax.top_k`` and row reductions.

    ``mode`` is the `_mxu_dot` precision ("bf16x3" split-float by default:
    ~2^-16 relative error, well under the float32 chaos floor that sets
    ``eps``).

    Reference: the HipMCL iteration (MCL.cpp:564-627) with
    MCLPruneRecoverySelect (ParFriends.h:186-350) — select keeps ties
    (threshold semantics), recovery relaxes columns that lost more than
    1 - recover_pct of their mass.
    """
    import jax

    from ..parallel.spgemm import _mxu_dot
    from ..parallel.spmat import SpParMat
    from ..ops.spgemm import sparsify_windowed

    assert A.grid.size == 1 and A.nrows == A.ncols
    n = A.nrows
    npad = -(-n // 128) * 128
    hard = float(prune_kwargs.get("hard_threshold", 1e-4))
    select = min(int(prune_kwargs["select_num"]), n)
    recover = min(int(prune_kwargs["recover_num"]), n)
    rpct = float(prune_kwargs["recover_pct"])

    run = dense_mcl_program(
        n, npad, inflation, eps, max_iters,
        hard=hard, select=select, recover=recover, rpct=rpct, mode=mode,
        perturb_delta=perturb_delta,
    )
    t0 = A.local_tile(A.rows, A.cols, A.vals, A.nnz)
    with obs.span("mcl.dense", n=int(n), mode=mode):
        m, it, ch, _hist, _npert = jax.jit(run)(t0.rows, t0.cols, t0.vals)
        if obs.ENABLED:
            # this host loop already reads scalars back (int(it) below);
            # one more tiny readback records the perturbation kicks
            kicks = int(_npert)
            obs.count("mcl.perturb_kicks", kicks)
            obs.span_event(
                "mcl.converged", iters=int(it), chaos=float(ch),
                perturb_kicks=kicks,
            )

    # EXACT extraction sizing via the output-support oracle (round 6):
    # one tiny readback of the converged state's support count replaces
    # the former guess-and-retry loop (up to 6 grow-and-rerun extraction
    # launches); this host loop already syncs on int(it) above, so the
    # count costs no extra poison window.
    from ..ops.spgemm import dense_support_nnz

    nnz_exact = int(
        jax.jit(dense_support_nnz, static_argnums=(2, 3))(m, 0.0, n, n)
    )
    cap = 1 << max(int(nnz_exact), 1024).bit_length()
    t, total = jax.jit(
        lambda mm: sparsify_windowed(mm, 0.0, n, n, cap),
        static_argnums=(),
    )(m)
    assert int(total) == nnz_exact <= cap, (int(total), nnz_exact, cap)
    t = t.transpose()  # back from Aᵀ to A orientation
    out = SpParMat(
        rows=t.rows[None, None], cols=t.cols[None, None],
        vals=t.vals[None, None], nnz=t.nnz[None, None],
        nrows=n, ncols=n, grid=A.grid,
    )
    return out, int(it), float(ch)


# --- 3D (communication-avoiding) MCL path (≈ HipMCL layers>1) --------------
#
# The reference's flagship production configuration: expansion runs
# MemEfficientSpGEMM3D on a layered grid (MCL.cpp:574-588 with layers>1,
# ParFriends.h:3215-3712); pruning/inflation happen on the 3D matrix via
# per-layer column ops. Here the 3D column ops (mesh3d.reduce3d_cols /
# kselect3d / prune_column3d) run on the 3-axis mesh directly — "r"-axis
# collectives act within each layer automatically.


def make_col_stochastic3d(A3):
    from ..parallel.mesh3d import dim_apply3d_cols, reduce3d_cols

    sums = reduce3d_cols(PLUS_TIMES, A3)
    return dim_apply3d_cols(A3, sums, _stochastic_scale)


def chaos3d(A3) -> jnp.ndarray:
    from ..parallel.mesh3d import nnz_per_column3d, reduce3d_cols

    colmax = reduce3d_cols(MAX_MIN, A3)
    colssq = reduce3d_cols(PLUS_TIMES, A3, map_fn=_square)
    nnzc = nnz_per_column3d(A3)
    diff = colmax - colssq
    scaled = jnp.where(nnzc > 0, diff * nnzc.astype(diff.dtype), 0)
    return jnp.max(scaled)


def inflate3d(A3, power: float):
    from ..parallel.mesh3d import apply3d

    return make_col_stochastic3d(apply3d(A3, _pow_fn(power)))


def mcl_prune_recovery_select3d(
    C3,
    hard_threshold: float = 1e-8,
    select_num: int = 1100,
    recover_num: int = 1400,
    recover_pct: float = 0.9,
    device_gate: bool = False,
):
    """3D twin of ``mcl_prune_recovery_select`` (the MemEfficientSpGEMM3D
    prune hook, ParFriends.h:3215-3712 + MCLPruneRecoverySelect).
    ``device_gate=True`` keeps the recovery decision on device (see the 2D
    twin)."""
    from ..parallel.mesh3d import (
        kselect3d,
        prune3d,
        prune_column3d,
        reduce3d_cols,
    )

    if hard_threshold > 0:
        C3 = prune3d(C3, _lt_pred(float(hard_threshold)))
    s_th = kselect3d(C3, select_num)
    pruned = prune_column3d(C3, s_th, keep=_keep_ge)
    kept = reduce3d_cols(PLUS_TIMES, pruned)
    orig = reduce3d_cols(PLUS_TIMES, C3)
    need_recover = kept < recover_pct * orig
    if not device_gate and not bool(jnp.any(need_recover)):
        return pruned
    r_th = kselect3d(C3, recover_num)
    final = jnp.where(need_recover, jnp.minimum(r_th, s_th), s_th)
    return prune_column3d(C3, final, keep=_keep_ge)


def _mcl3d_block_caps(A3, B3):
    """Frozen 3D block capacities from one sync-point symbolic pass:
    (flop, out, piece) for summa3d + (stage, tile) for the resplit —
    2x headroom, powers of two."""
    import numpy as np

    from ..parallel.mesh3d import summa3d_stage_flops

    g3 = A3.grid
    L = g3.layers
    from ..parallel.spgemm import host_value

    per_stage = host_value(summa3d_stage_flops(A3, B3)).astype(np.float64)
    rnd = lambda x: 1 << max(int(x) - 1, 1).bit_length()
    total = per_stage.sum(axis=0)
    dense_tile = A3.tile_rows * max(B3.ncols // max(g3.pc * L, 1), 1)
    fcap = rnd(per_stage.max() * 2)
    pcap = rnd(total.max() * 2)
    ocap = max(min(rnd(total.max() * L * 2), dense_tile), 1)
    nnz_tot = float(host_value(jnp.sum(A3.nnz)))
    ndev = L * g3.pr * g3.pc
    chunk = A3.capacity
    per_dest = max(-(-chunk // f) for f in (g3.pc, g3.pr, L))
    stage_cap = rnd(per_dest * 2)
    tile_cap = rnd(max(nnz_tot / ndev * 4, 4))
    return fcap, ocap, pcap, stage_cap, tile_cap


def _mcl3d_iter_device(A3, caps, inflation, prune_kwargs):
    """One 3D MCL iteration with frozen capacities, entirely on device.
    Returns (A3_next, chaos, overflow)."""
    from ..parallel.mesh3d import (
        resplit3d_fixed,
        summa3d_spgemm,
        summa3d_stage_flops,
    )

    fcap, ocap, pcap, stage_cap, tile_cap = caps
    B3, dropped = resplit3d_fixed(
        A3, "row", stage_capacity=stage_cap, tile_capacity=tile_cap
    )
    flop_need = jnp.max(summa3d_stage_flops(A3, B3))
    C3, ov3 = summa3d_spgemm(
        PLUS_TIMES, A3, B3,
        flop_capacity=fcap, out_capacity=ocap, piece_capacity=pcap,
    )
    # out-capacity overflow signature: a tile filled to the brim (compact
    # clamps at capacity, so nnz == cap marks possible truncation)
    ov_out = jnp.max((C3.nnz >= ocap).astype(jnp.int32))
    # fiber piece drops (round 13: the exchange now REPORTS them
    # per-kernel) fold into the same reroll bit as the expansion flops
    # — both double fcap+pcap
    ov_piece = (ov3[0] > 0).astype(jnp.int32)
    C3 = mcl_prune_recovery_select3d(C3, device_gate=True, **prune_kwargs)
    C3 = make_col_stochastic3d(C3)
    ch = chaos3d(C3)
    A_next = inflate3d(C3, inflation)
    # discriminated overflow bits (ADVICE r3: doubling all five caps on
    # any flag wastes reroll memory/compiles): 1 = resplit stage/tile,
    # 2 = expansion flops, 4 = output keys
    overflow = (
        (dropped > 0).astype(jnp.int32)
        + jnp.maximum((flop_need > fcap).astype(jnp.int32), ov_piece) * 2
        + ov_out * 4
    )
    return A_next, ch, overflow


def _mcl3d_block_loop(A3, inflation, eps, max_iters, K, prune_kwargs):
    """3D twin of ``_mcl2d_block_loop``: one readback per K-iteration
    block, save-and-reroll on any frozen-capacity overflow."""
    from ..parallel.mesh3d import resplit3d

    ch = float("inf")
    it = 0
    caps = None
    dense_tile = None
    while it < max_iters:
        if caps is None:
            B3_probe = resplit3d(A3, "row")
            caps = _mcl3d_block_caps(A3, B3_probe)
            g3 = A3.grid
            dense_tile = A3.tile_rows * max(
                B3_probe.ncols // max(g3.pc * g3.layers, 1), 1
            )
        k = min(K, max_iters - it)
        A_entry = A3
        worst = jnp.int32(0)
        for _ in range(k):
            A3, ch_dev, ov = _mcl3d_iter_device(
                A3, caps, inflation, prune_kwargs
            )
            # ov carries discriminated BIT flags (1=resplit drop, 2=flop,
            # 4=out-capacity): accumulate with OR — max(4, 3) would lose
            # bits 1|2 across a K-iteration block (ADVICE r4)
            worst = jnp.bitwise_or(worst, ov)
        bits = int(worst)
        if (bits & 4) and caps[1] >= dense_tile:
            # a dense-tile-sized output cannot truncate: nnz == ocap is a
            # legitimately full tile, not an overflow (ADVICE r3)
            bits &= ~4
        if bits > 0:
            # SYNC: reroll the block, doubling ONLY the overflowed group
            # and clamping the out capacity at the dense tile (ADVICE r3)
            if obs.ENABLED:
                # same unlabeled series as the 2D loop (a label would
                # fragment the counter per distinct overflow-bit pattern)
                obs.count("mcl.block_rerolls")
            fcap, ocap, pcap, stage_cap, tile_cap = caps
            if bits & 1:
                stage_cap, tile_cap = stage_cap * 2, tile_cap * 2
            if bits & 2:
                fcap, pcap = fcap * 2, pcap * 2
            if bits & 4:
                ocap = min(ocap * 2, max(dense_tile, 1))
                pcap = pcap * 2
            caps = (fcap, ocap, pcap, stage_cap, tile_cap)
            A3 = A_entry
            continue
        ch = float(ch_dev)
        it += k
        if ch < eps:
            break
    return A3, it, ch


def _mcl3d_loop(
    A: SpParMat, grid3, inflation, eps, max_iters, phases, prune_kwargs,
    chaos_every: int = 1,
):
    """The 3D expansion loop: returns (converged 2D matrix, iters, chaos)."""
    from ..parallel.mesh3d import (
        SpParMat3D,
        mem_efficient_spgemm3d,
        prune3d,
        resplit3d,
    )

    A3 = SpParMat3D.from_spmat(A, grid3, split="col")

    if chaos_every > 1:
        assert phases == 1, "chaos_every>1 requires phases=1"
        A3, it, ch = _mcl3d_block_loop(
            A3, inflation, eps, max_iters, chaos_every, prune_kwargs
        )
        ht = prune_kwargs.get("hard_threshold", 0)
        if ht > 0:
            A3 = prune3d(A3, _lt_pred(float(ht)))
        return A3.to_spmat(A.grid), it, ch

    def prune_fn(C3):
        return mcl_prune_recovery_select3d(C3, **prune_kwargs)

    ch = float("inf")
    it = 0
    for it in range(1, max_iters + 1):
        B3 = resplit3d(A3, "row").shrink_to_fit()
        C3 = mem_efficient_spgemm3d(
            PLUS_TIMES, A3, B3, phases, prune_fn=prune_fn
        )
        C3 = make_col_stochastic3d(C3)
        ch = float(chaos3d(C3))
        A3 = inflate3d(C3, inflation)
        A3 = A3.shrink_to_fit()
        if ch < eps:
            break

    ht = prune_kwargs.get("hard_threshold", 0)
    if ht > 0:  # float32 residue, as in the 2D path
        A3 = prune3d(A3, _lt_pred(float(ht)))
    return A3.to_spmat(A.grid), it, ch
