"""Fill-reducing / bandwidth-reducing orderings (≈ Applications/Ordering/).

RCM (Reverse Cuthill-McKee, ``RCM.cpp:61-160``): BFS levels from a
pseudo-peripheral vertex, vertices ordered by (level, degree) and reversed.
The reference computes levels with ``SpMV<Select2ndMinSR>`` and sorts
(level, degree) keys with a distributed psort; here levels come from the
jitted BFS and the key sort is one multi-key ``lax.sort`` over the sharded
global view (the same collapse of distributed sorting onto the TPU's native
sort used by ``DistVec.sort``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..semiring import PLUS_TIMES
from ..parallel.spmat import SpParMat, ones_f32, ones_i32
from ..parallel.vec import DistVec
from .bfs import bfs


def pseudo_peripheral_vertex(A: SpParMat, max_probes: int = 6) -> int:
    """George-Liu style probe: start at a min-degree vertex, repeatedly BFS
    and jump to a min-degree vertex of the last level until the eccentricity
    stops growing (``RCM.cpp`` FindPeripheral loop)."""
    deg = np.asarray(A.reduce(PLUS_TIMES, "rows", map_fn=ones_i32).to_global())
    n = A.nrows
    # Min-degree among non-isolated vertices (isolated ones order last anyway).
    noniso = np.nonzero(deg > 0)[0]
    if len(noniso) == 0:
        return 0
    root = int(noniso[np.argmin(deg[noniso])])
    best_ecc = -1
    for _ in range(max_probes):
        _, levels, _ = bfs(A, root)
        lv = np.asarray(levels.to_global())
        ecc = int(lv.max())
        if ecc <= best_ecc:
            break
        best_ecc = ecc
        last = np.nonzero(lv == ecc)[0]
        root = int(last[np.argmin(deg[last])])
    return root


from functools import partial


@partial(jax.jit, static_argnames=("length",))
def _rcm_sort(levels_blocks, deg_blocks, length):
    """Permutation sorting by (level, degree, id) ascending, then reversed.

    Unreachable vertices (level -1) sort to the very end of the *forward*
    order — i.e. the FRONT of the reversed RCM order is the far end of the
    graph, matching the reference's per-component handling intent."""
    flat_lv = levels_blocks.reshape(-1)
    flat_dg = deg_blocks.reshape(-1)
    gids = jnp.arange(flat_lv.shape[0], dtype=jnp.int32)
    pad = (gids >= length).astype(jnp.int32)
    lv = jnp.where(flat_lv < 0, length, flat_lv)  # unreachable last
    _, _, _, perm = lax.sort((pad, lv, flat_dg, gids), num_keys=3)
    # reverse only the real slots
    real = perm[:length][::-1]
    return jnp.concatenate([real, perm[length:]])


def rcm_ordering(A: SpParMat, root: int | None = None) -> DistVec:
    """RCM permutation: ``perm[k]`` = old vertex id placed at new position k.

    Apply with ``indexing.subsref(A, p, p)`` to get the reordered matrix
    (the reference's ``A(ri, ri)`` SpRef, RCM.cpp driver).
    """
    grid = A.grid
    n = A.nrows
    if root is None:
        root = pseudo_peripheral_vertex(A)
    _, levels, _ = bfs(A, root)
    deg = A.reduce(PLUS_TIMES, "rows", map_fn=ones_i32).realign("row")
    perm_flat = _rcm_sort(levels.blocks, deg.blocks, n)  # full pa*L length
    pa, L = levels.blocks.shape
    return DistVec(
        blocks=perm_flat.reshape(pa, L), length=n, align="row", grid=grid
    )


def bandwidth(dense) -> int:
    """Host helper: max |i - j| over nonzeros (the RCM quality metric)."""
    r, c = np.nonzero(np.asarray(dense))
    return int(np.abs(r - c).max()) if len(r) else 0


def minimum_degree_ordering(A: SpParMat, max_steps: int | None = None) -> DistVec:
    """Minimum-degree elimination ordering — prototype-grade, matching the
    reference's MD prototype (Applications/Ordering/MD.cpp: SpRef/SpAsgn
    loops; explicitly a prototype there too).

    Per step: pick the minimum-degree uneliminated vertex v, connect its
    neighborhood into a clique (one rank-1 structural SpGEMM-equivalent via
    ewise_add of the outer product), and mask v out. O(n) distributed steps
    — usable at the small scales the reference's prototype targets.
    """
    from ..parallel.indexing import col_selector
    from ..parallel.spgemm import spgemm

    n = A.nrows
    grid = A.grid
    work = A.apply(ones_f32).remove_loops()
    alive = np.ones(n, bool)
    order = []
    steps = max_steps if max_steps is not None else n
    for _ in range(min(n, steps)):
        degv = work.reduce(PLUS_TIMES, "rows", map_fn=ones_i32).to_global()
        degv = np.where(alive, degv, np.iinfo(np.int32).max)
        v = int(np.argmin(degv))
        if not alive[v]:
            break
        order.append(v)
        alive[v] = False
        nbrs = None
        if degv[v] > 0 and degv[v] < np.iinfo(np.int32).max:
            # neighborhood of v as a column selection, clique = outer product
            sel = col_selector(grid, [v], n, np.float32)  # n×1 at (·, v)
            col_v = spgemm(PLUS_TIMES, work, sel)  # n×1 = neighbors of v
            nbr_mask = col_v.to_dense()[:, 0] > 0
            nbr_mask[v] = False
            nbrs = np.nonzero(nbr_mask & alive)[0]
        if nbrs is not None and len(nbrs) > 1:
            e = np.ones(len(nbrs), np.float32)
            u = SpParMat.from_global_coo(
                grid, nbrs, np.zeros(len(nbrs), np.int64), e, n, 1
            )
            ut = SpParMat.from_global_coo(
                grid, np.zeros(len(nbrs), np.int64), nbrs, e, 1, n
            )
            clique = spgemm(PLUS_TIMES, u, ut).remove_loops()
            # shrink after the union: ewise_add sums capacities, which would
            # otherwise grow (and retrace) every elimination step.
            work = (
                work.ewise_add(clique, PLUS_TIMES)
                .apply(_clamp01)
                .shrink_to_fit()
            )
        # mask out v's row and column
        rmask = DistVec.from_global(grid, alive, align="row", fill=False)
        cmask = DistVec.from_global(grid, alive, align="col", fill=False)
        work = work.prune_rowcol(rmask, cmask, _keep_both_alive)
    order.extend(np.nonzero(alive)[0].tolist())  # isolated leftovers
    return DistVec.from_global(
        grid, np.asarray(order, np.int32), align="row", fill=n
    )


def _clamp01(v):
    return jnp.minimum(v, 1.0)


def _keep_both_alive(v, r_alive, c_alive):
    return r_alive & c_alive
