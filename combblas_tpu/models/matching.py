"""Bipartite matchings (≈ Applications/BipartiteMatchings/).

The reference ships three layers (``BPMaximalMatching.h``,
``BPMaximumMatching.cpp:124-188``, ``ApproxWeightPerfectMatching.h``):

1. **Maximal matching** — greedy and Karp-Sipser initializations, expressed
   as rounds of (rows propose a free column; columns grant to one proposer).
   Here a round is: per-row masked structural min over free columns (a
   Reduce(Row) on a column-id matrix), a ``scatter_combine`` granting each
   column to its minimum proposer, and a scatter back to the rows — all
   distributed, no host data movement inside a round.
2. **Maximum cardinality matching** — augmenting-path phases. Each phase
   runs a distributed structural SpMV sweep to grow alternating layers and
   the augmentation of a vertex-disjoint path set on the host (gathered
   pointer arrays — the analog of the reference's serial augment over its
   locally-owned queue, BPMaximumMatching.cpp:156-188).
3. **AWPM** — heaviest-edge Karp-Sipser initialization + cardinality
   augmentation, the composition of the reference's AWPM driver.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..semiring import MAX_MIN, PLUS_TIMES, SELECT2ND_MIN
from ..parallel.grid import COL_AXIS, ROW_AXIS
from ..parallel.spmat import SpParMat, TILE_SPEC, ones_f32
from ..parallel.spmv import dist_spmv
from ..parallel.vec import DistVec

I32MAX = np.int32(np.iinfo(np.int32).max)


def _set_colid_vals(t, ro, co):
    vals = jnp.where(t.valid_mask(), (t.cols + co).astype(jnp.int32), I32MAX)
    return dataclasses.replace(t, vals=vals)


def _colid_matrix(A: SpParMat) -> SpParMat:
    """A with values replaced by global column ids (int32)."""
    return A.tile_map_indexed(_set_colid_vals)


def _mask_free_ids(v, free):
    return jnp.where(free, v, I32MAX)


def _mask_free_weights(v, free):
    return jnp.where(free, v, -jnp.inf)


def _one_if_free(v, free):
    return jnp.where(free, 1, 0).astype(jnp.int32)


def _gids(shape, n):
    pa, L = shape
    g = jnp.arange(pa * L, dtype=jnp.int32).reshape(pa, L)
    return jnp.where(g < n, g, I32MAX)


@jax.jit
def _mark_best(Aw: SpParMat, Aid: SpParMat, colfree: DistVec, wrow: DistVec):
    """Aid with vals = col id where (col free AND weight == row max) else
    I32MAX — the argmax-column selector for weighted proposals."""

    def body(wr, wc, wv, wn, ir, ic, iv, in_, freeb, rb):
        tw = Aw.local_tile(wr, wc, wv, wn)
        ti = Aid.local_tile(ir, ic, iv, in_)
        free, rmax = freeb[0], rb[0]
        fpad = jnp.concatenate([free, jnp.zeros((1,), free.dtype)])
        rpad = jnp.concatenate([rmax, jnp.full((1,), jnp.inf, rmax.dtype)])
        ci = jnp.minimum(tw.cols, free.shape[0])
        ri = jnp.minimum(tw.rows, rmax.shape[0])
        is_best = tw.valid_mask() & fpad[ci] & (tw.vals == rpad[ri])
        vals = jnp.where(is_best, ti.vals, I32MAX)
        return SpParMat._pack_tile(dataclasses.replace(ti, vals=vals))

    r, c, v, n = jax.shard_map(
        body,
        mesh=Aw.grid.mesh,
        in_specs=(TILE_SPEC,) * 8 + (P(COL_AXIS), P(ROW_AXIS)),
        out_specs=(TILE_SPEC,) * 4,
    )(
        Aw.rows, Aw.cols, Aw.vals, Aw.nnz,
        Aid.rows, Aid.cols, Aid.vals, Aid.nnz,
        colfree.blocks, wrow.blocks,
    )
    return dataclasses.replace(Aid, rows=r, cols=c, vals=v, nnz=n)


@partial(jax.jit, static_argnames=("heaviest",))
def _matching_round(
    Aid: SpParMat,
    Aw: SpParMat | None,
    mate_row,
    mate_col,
    only_deg1,
    heaviest: bool = False,
):
    """One propose/grant round → (mate_row', mate_col', newly matched count).

    mate_row: row-aligned int32 blocks (-1 = free); mate_col: col-aligned.
    ``only_deg1`` (traced bool) restricts proposers to rows with exactly one
    free-column neighbor — the Karp-Sipser rule.
    """
    grid = Aid.grid
    nr, nc = Aid.nrows, Aid.ncols

    colfree = DistVec(blocks=(mate_col < 0), length=nc, align="col", grid=grid)
    if heaviest:
        masked_w = Aw.dim_apply(colfree, _mask_free_weights, "cols")
        wcand = masked_w.reduce(MAX_MIN, "cols")  # row-aligned max weight
        cand = _mark_best(Aw, Aid, colfree, wcand.realign("row")).reduce(
            SELECT2ND_MIN, "cols"
        )
    else:
        masked_id = Aid.dim_apply(colfree, _mask_free_ids, "cols")
        cand = masked_id.reduce(SELECT2ND_MIN, "cols")  # min free col id

    deg_free = Aid.dim_apply(colfree, _one_if_free, "cols").reduce(
        PLUS_TIMES, "cols"
    )
    eligible = (mate_row < 0) & (cand.blocks != I32MAX)
    eligible = jnp.where(only_deg1, eligible & (deg_free.blocks == 1), eligible)

    row_gids = _gids(mate_row.shape, nr)
    prop_col = DistVec(
        blocks=jnp.where(eligible, cand.blocks, -1),
        length=nr, align="row", grid=grid,
    )
    prop_src = DistVec(
        blocks=jnp.where(eligible, row_gids, I32MAX),
        length=nr, align="row", grid=grid,
    )
    # Columns grant to the minimum proposing row.
    grant0 = DistVec(
        blocks=jnp.full(mate_col.shape, I32MAX, jnp.int32),
        length=nc, align="col", grid=grid,
    )
    granted = grant0.scatter_combine(SELECT2ND_MIN, idx=prop_col, src=prop_src)
    new_col = (granted.blocks != I32MAX) & (mate_col < 0)
    mate_col2 = jnp.where(new_col, granted.blocks, mate_col)

    # Rows learn their match via the reverse scatter.
    col_gids = _gids(mate_col.shape, nc)
    back_idx = DistVec(
        blocks=jnp.where(new_col, granted.blocks, -1),
        length=nc, align="col", grid=grid,
    )
    back_src = DistVec(
        blocks=jnp.where(new_col, col_gids, I32MAX),
        length=nc, align="col", grid=grid,
    )
    mrow0 = DistVec(
        blocks=jnp.full(mate_row.shape, I32MAX, jnp.int32),
        length=nr, align="row", grid=grid,
    )
    got = mrow0.scatter_combine(SELECT2ND_MIN, idx=back_idx, src=back_src)
    new_row = got.blocks != I32MAX
    mate_row2 = jnp.where(new_row, got.blocks, mate_row)
    return mate_row2, mate_col2, jnp.sum(new_col).astype(jnp.int32)


def maximal_matching(
    A: SpParMat, *, karp_sipser: bool = True, weighted: bool = False
) -> tuple[DistVec, DistVec]:
    """Maximal matching on A's nonzero pattern (rows = left, cols = right).

    Returns (mate_row, mate_col): row-/col-aligned int32 DistVecs with -1
    for unmatched. ``karp_sipser`` prioritizes degree-1 rows; ``weighted``
    proposes heaviest edges (the AWPM initialization). Reference:
    ``BPMaximalMatching.h``.
    """
    grid = A.grid
    nr, nc = A.nrows, A.ncols
    Aid = _colid_matrix(A)
    Aw = A if weighted else None
    mate_row = DistVec.full(grid, nr, -1, jnp.int32, align="row").blocks
    mate_col = DistVec.full(grid, nc, -1, jnp.int32, align="col").blocks
    while True:
        nnew_total = 0
        if karp_sipser:
            mate_row, mate_col, nnew = _matching_round(
                Aid, Aw, mate_row, mate_col, jnp.bool_(True), heaviest=weighted
            )
            nnew_total += int(nnew)
        if nnew_total == 0:
            mate_row, mate_col, nnew = _matching_round(
                Aid, Aw, mate_row, mate_col, jnp.bool_(False), heaviest=weighted
            )
            nnew_total += int(nnew)
        if nnew_total == 0:
            break
    return (
        DistVec(blocks=mate_row, length=nr, align="row", grid=grid),
        DistVec(blocks=mate_col, length=nc, align="col", grid=grid),
    )


@jax.jit
def _mcm_phase(AT: SpParMat, mate_row: DistVec, mate_col: DistVec):
    """One augmenting phase, entirely on device (VERDICT r3 item 6).

    Alternating-layer BFS from free rows: each layer is one
    ``dist_spmv(SELECT2ND_MIN, Aᵀ, frontier)`` whose result IS the parent
    assignment (the minimum adjacent frontier row per newly reached
    column — deterministic, matching the host reference).  The BFS stops
    at the first layer containing a free column; every free column found
    then traces its parent chain back in parallel (bounded while_loops of
    device gathers), and vertex-disjointness is decided by WINNER
    SELECTION: each candidate path scatter-mins its path id onto every
    row it uses; a path survives iff it won all its rows.  The globally
    minimal surviving id always wins all of its rows, so a phase that
    finds any path augments at least one — no livelock.  Conflicting
    paths simply wait for a later phase (the reference's serial augment
    over its local queue has the same effect,
    BPMaximumMatching.cpp:156-188).

    Returns (mate_row', mate_col', n_augmented).  The ONLY host traffic
    per phase is the caller's scalar termination readback.
    """
    grid = AT.grid
    nr, nc = AT.ncols, AT.nrows  # AT is [nc, nr]
    mr, mc = mate_row, mate_col

    row_gids = DistVec.iota(grid, nr, align="row")
    col_gids = DistVec.iota(grid, nc, align="col")
    ifree_row = mr.blocks < 0

    def vec(blocks, length, align):
        return DistVec(blocks=blocks, length=length, align=align, grid=grid)

    # --- alternating-layer BFS --------------------------------------------
    f0 = jnp.where(ifree_row & (row_gids.blocks < nr), row_gids.blocks, I32MAX)
    st0 = (
        f0,  # frontier: row gid at active rows else I32MAX
        jnp.full(mc.blocks.shape, -1, jnp.int32),  # col_parent
        jnp.zeros(mc.blocks.shape, bool),  # col_seen
        jnp.bool_(False),  # found a free column
        jnp.bool_(True),  # frontier nonempty
        jnp.int32(0),  # depth
    )

    def bfs_cond(st):
        _, _, _, found, nonempty, depth = st
        return (~found) & nonempty & (depth < nr + 2)

    def bfs_body(st):
        fr, col_parent, col_seen, _, _, depth = st
        reach = dist_spmv(SELECT2ND_MIN, AT, vec(fr, nr, "row"))
        newc = (
            (reach.blocks != I32MAX)
            & ~col_seen
            & (col_gids.blocks < nc)
        )
        col_parent = jnp.where(newc, reach.blocks, col_parent)
        col_seen = col_seen | newc
        free_new = newc & (mc.blocks < 0)
        found = jnp.sum(free_new.astype(jnp.int32)) > 0
        # next frontier: matched rows of newly seen matched columns
        nxt_rows = jnp.where(newc & (mc.blocks >= 0), mc.blocks, -1)
        fr2 = vec(
            jnp.full(mr.blocks.shape, I32MAX, jnp.int32), nr, "row"
        ).scatter_combine(
            SELECT2ND_MIN,
            idx=vec(nxt_rows, nc, "col"),
            src=vec(jnp.where(nxt_rows >= 0, nxt_rows, I32MAX), nc, "col"),
        )
        nonempty = jnp.sum((fr2.blocks != I32MAX).astype(jnp.int32)) > 0
        return (fr2.blocks, col_parent, col_seen, found, nonempty, depth + 1)

    _, col_parent, col_seen, found, _, depth = lax.while_loop(
        bfs_cond, bfs_body, st0
    )
    col_parent_v = vec(col_parent, nc, "col")

    # --- parallel back-chase (3 passes over the parent chains) ------------
    cand = found & col_seen & (mc.blocks < 0) & (col_gids.blocks < nc)
    path_id = jnp.where(cand, col_gids.blocks, I32MAX)  # lane = free col

    def chase(step_fn, carry0):
        """Walk all candidate chains simultaneously, <= depth+1 steps.
        state: (cur_col blocks [nc-lane], alive mask, step, carry)."""

        def cond(st):
            _, alive, step, _ = st
            return (jnp.sum(alive.astype(jnp.int32)) > 0) & (step <= depth)

        def body(st):
            cur, alive, step, carry = st
            r = col_parent_v.gather(vec(cur, nc, "col")).blocks
            r = jnp.where(alive, r, -1)
            carry = step_fn(carry, cur, r, alive, step)
            nxt = mr.gather(vec(jnp.where(r >= 0, r, 0), nr, "col")).blocks
            cont = alive & (r >= 0) & (nxt >= 0)
            cur = jnp.where(cont, nxt, cur)
            return (cur, cont, step + 1, carry)

        st = (jnp.where(cand, col_gids.blocks, 0), cand, jnp.int32(0), carry0)
        return lax.while_loop(cond, body, st)[3]

    # pass 1: claim rows (min path id wins each row)
    def claim_step(claims, cur, r, alive, step):
        return claims.scatter_combine(
            SELECT2ND_MIN,
            idx=vec(jnp.where(alive, r, -1), nc, "col"),
            src=vec(path_id, nc, "col"),
        )

    claims = chase(
        claim_step,
        vec(jnp.full(mr.blocks.shape, I32MAX, jnp.int32), nr, "row"),
    )

    # pass 2: a path survives iff it won every row on its chain
    def check_step(ok, cur, r, alive, step):
        won = claims.gather(vec(jnp.where(r >= 0, r, 0), nr, "col")).blocks
        return ok & jnp.where(alive, won == path_id, True)

    survive = chase(check_step, cand)

    # pass 3: augment surviving (disjoint) paths in parallel
    def aug_step(mrmc, cur, r, alive, step):
        mrb, mcb = mrmc
        act = alive & survive & (r >= 0)
        mrb = mrb.scatter_combine(
            SELECT2ND_MIN,
            idx=vec(jnp.where(act, r, -1), nc, "col"),
            src=vec(jnp.where(act, cur, I32MAX), nc, "col"),
        )
        mcb = mcb.scatter_combine(
            SELECT2ND_MIN,
            idx=vec(jnp.where(act, cur, -1), nc, "col"),
            src=vec(jnp.where(act, r, I32MAX), nc, "col"),
        )
        return (mrb, mcb)

    upd_r0 = vec(jnp.full(mr.blocks.shape, I32MAX, jnp.int32), nr, "row")
    upd_c0 = vec(jnp.full(mc.blocks.shape, I32MAX, jnp.int32), nc, "col")
    upd_r, upd_c = chase(aug_step, (upd_r0, upd_c0))
    mr2 = jnp.where(upd_r.blocks != I32MAX, upd_r.blocks, mr.blocks)
    mc2 = jnp.where(upd_c.blocks != I32MAX, upd_c.blocks, mc.blocks)
    n_aug = jnp.sum((survive & cand).astype(jnp.int32))
    return (
        vec(mr2, nr, "row"), vec(mc2, nc, "col"), n_aug,
    )


def maximum_matching_device(
    A: SpParMat, init: tuple | None = None
) -> tuple[DistVec, DistVec]:
    """Maximum-cardinality matching with ON-DEVICE augmentation.

    Each phase is one jitted SPMD program (``_mcm_phase``); the host loop
    reads back a single scalar per phase for termination — no gathered
    pointer arrays, no per-step D2H (VERDICT r3 item 6; the host-loop
    prototype remains as ``maximum_matching(device=False)`` and as the
    validation oracle).  Reference: ``BPMaximumMatching.cpp:124-188``.
    """
    mate_row, mate_col = init if init is not None else maximal_matching(A)
    AT = A.transpose().apply(ones_f32)
    while True:
        mate_row, mate_col, n_aug = _mcm_phase(AT, mate_row, mate_col)
        if int(n_aug) == 0:
            break
    return mate_row, mate_col


def maximum_matching(
    A: SpParMat, init: tuple | None = None, *, device: bool = True
) -> tuple[DistVec, DistVec]:
    """Maximum-cardinality matching via augmenting-path phases.

    ``device=True`` (default): on-device phases, one scalar readback each
    (``maximum_matching_device``).  ``device=False``: the host-augmentation
    prototype (distributed structural sweep + serial host augment over
    gathered pointer arrays — the analog of the reference's serial augment
    over its locally-owned queue, BPMaximumMatching.cpp:156-188); kept as
    the validation oracle.
    """
    if device:
        return maximum_matching_device(A, init=init)
    grid = A.grid
    nr, nc = A.nrows, A.ncols
    mate_row, mate_col = init if init is not None else maximal_matching(A)
    mr = np.asarray(mate_row.to_global()).copy().astype(np.int64)
    mc = np.asarray(mate_col.to_global()).copy().astype(np.int64)
    AT = A.transpose().apply(ones_f32)
    # Host CSC adjacency for path reconstruction: O(deg) per column lookup
    # instead of an O(nnz) scan per reached column.
    ar, ac, _ = A.to_global_coo()
    order = np.argsort(ac, kind="stable")
    ar_sorted = ar[order]
    col_ptr = np.searchsorted(ac[order], np.arange(nc + 1))

    def col_neighbors(j):
        return ar_sorted[col_ptr[j] : col_ptr[j + 1]]

    while True:
        col_parent = np.full(nc, -1, np.int64)
        col_seen = np.zeros(nc, bool)
        frontier_rows = np.nonzero(mr < 0)[0]
        found_free_cols: np.ndarray = np.array([], np.int64)
        guard = 0
        while len(frontier_rows) and guard <= nc + 1:
            guard += 1
            fmask = np.zeros(nr, np.float32)
            fmask[frontier_rows] = 1.0
            fr = DistVec.from_global(grid, fmask, align="col", fill=0)
            reach = dist_spmv(PLUS_TIMES, AT, fr)  # length nc, row-aligned
            reached = (np.asarray(reach.to_global()) > 0) & ~col_seen
            newcols = np.nonzero(reached)[0]
            if len(newcols) == 0:
                break
            in_frontier = np.zeros(nr, bool)
            in_frontier[frontier_rows] = True
            for j in newcols:  # deterministic min adjacent frontier row
                nbrs = col_neighbors(j)
                col_parent[j] = nbrs[in_frontier[nbrs]].min()
            col_seen[newcols] = True
            free_new = newcols[mc[newcols] < 0]
            if len(free_new):
                found_free_cols = free_new
                break
            frontier_rows = mc[newcols]
        if len(found_free_cols) == 0:
            break
        used_rows: set[int] = set()
        augmented = 0
        for j in found_free_cols:
            path = []
            cj = int(j)
            ok = True
            while True:
                ri = int(col_parent[cj])
                if ri < 0 or ri in used_rows:
                    ok = False
                    break
                path.append((ri, cj))
                if mr[ri] < 0:
                    break
                cj = int(mr[ri])
            if not ok:
                continue
            for ri, _ in path:
                used_rows.add(ri)
            for ri, cj in path:
                mr[ri] = cj
                mc[cj] = ri
            augmented += 1
        if augmented == 0:
            break

    return (
        DistVec.from_global(grid, mr.astype(np.int32), align="row", fill=-1),
        DistVec.from_global(grid, mc.astype(np.int32), align="col", fill=-1),
    )


def awpm(A: SpParMat) -> tuple[DistVec, DistVec]:
    """Approximate-weight perfect matching: heaviest-edge Karp-Sipser
    initialization + cardinality augmentation (the composition of the
    reference's AWPM driver, ``ApproxWeightPerfectMatching.h``)."""
    init = maximal_matching(A, karp_sipser=True, weighted=True)
    return maximum_matching(A, init=init)


# --- host validation helpers (tests / drivers) ------------------------------


def matching_weight(A_dense, mate_row) -> float:
    mr = np.asarray(mate_row)
    return float(
        sum(np.asarray(A_dense)[i, j] for i, j in enumerate(mr) if j >= 0)
    )


def is_valid_matching(A_dense, mate_row, mate_col) -> bool:
    mr, mc = np.asarray(mate_row), np.asarray(mate_col)
    cols_used = [j for j in mr if j >= 0]
    if len(cols_used) != len(set(cols_used)):
        return False
    for i, j in enumerate(mr):
        if j >= 0 and (not A_dense[i, j] or mc[j] != i):
            return False
    return all(i < 0 or mr[i] == j for j, i in enumerate(mc))


def is_maximal(A_dense, mate_row, mate_col) -> bool:
    mr, mc = np.asarray(mate_row), np.asarray(mate_col)
    A_dense = np.asarray(A_dense)
    for i in range(len(mr)):
        if mr[i] < 0:
            for j in np.nonzero(A_dense[i])[0]:
                if mc[j] < 0:
                    return False
    return True
