"""k-hop feature propagation — the graph-ML serving workload.

Embedding smoothing / recommendation-shaped traffic: every node carries
a dense feature row and queries want the k-hop NEIGHBORHOOD AGGREGATE
``(D⁻¹A)ᵏ·X`` (normalized) or ``Aᵏ·X`` — the SGC/LightGCN-style
propagation step, which is exactly the batched SpMM lane
(``parallel/spmm.py``) applied k times device-resident.

Two entries:

* :func:`propagate_features` — the whole-graph model API: host
  ``[n, F]`` features in, propagated ``[n, F]`` out (one fused
  ``spmm_khop`` launch; backend resolves through the op="spmm" tuner
  chain).

* :func:`_propagate_batch_impl` — the SERVE plan body (kind
  ``"propagate"``): a W-lane batch of root queries answered WITHOUT
  touching the full feature table per query.  Lane w's result is row
  ``v_w`` of ``(D⁻¹A)ᵏX``, computed by propagating the batch's
  indicator block through the TRANSPOSE operator —

      e_vᵀ(D⁻¹A)ᵏX  ==  ((AᵀD⁻¹)ᵏ e_v)ᵀ X

  so the k hops are ``dist_spmm_ell`` calls over a [n, W] dense block
  (per-batch cost scales with W, not with n·F), and the feature table
  enters once at the end as ONE [W, n] × [n, F]-shaped MXU contraction
  (psum over grid rows).  ``PAD_ROOT`` lanes have all-zero indicators:
  structurally inert, zero features out — the serve batcher's pad
  contract holds with no special casing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from . import PAD_ROOT
from .bfs import _global_ids
from ..parallel.grid import ROW_AXIS
from ..semiring import PLUS_TIMES


def propagate_features(
    E, X, k: int, normalize: bool = False, sr=PLUS_TIMES,
    backend: str | None = None,
) -> np.ndarray:
    """Whole-graph k-hop propagation: host ``[n, F]`` features →
    propagated host ``[n, F]`` (pow2 pad lanes stripped).  ``E`` is an
    ``EllParMat`` in the usual gather orientation (entry (i, j) = edge
    j → i): each hop aggregates IN-neighbor features; ``normalize``
    divides by the in-degree per hop (plus_times only)."""
    from ..parallel.spmm import spmm_khop

    F = int(np.asarray(X).shape[1])
    Y = spmm_khop(sr, E, X, k, normalize=normalize, backend=backend)
    return np.asarray(Y.to_global())[:, :F]


def _propagate_batch_impl(
    ET, X, invdeg, sources, *, hops: int, normalize: bool,
    backend: str,
):
    """W root queries → ``[F, W]`` propagated feature columns (lane
    axis LAST, the serve scatter contract).

    ``ET``: the hop operator in TRANSPOSE orientation (the engine's
    ``ET`` property — E itself on symmetric graphs); ``X``: row-aligned
    ``DistMultiVec`` feature table (pow2-padded F); ``invdeg``:
    col-aligned 1/deg ``DistVec`` when ``normalize`` else None;
    ``sources``: int32 [W] with ``PAD_ROOT`` pad slots."""
    import dataclasses

    from ..parallel.spmm import dist_spmm_ell
    from ..parallel.vec import DistMultiVec

    grid = ET.grid
    n = ET.ncols
    pc_, lc = grid.pc, grid.local_cols(n)
    col_gids = _global_ids(grid, pc_, lc, n, "col")
    src = sources.astype(jnp.int32)[None, None, :]  # [1, 1, W]
    live = src != PAD_ROOT
    # PAD_ROOT lanes: live=False keeps the pad source from matching the
    # -1 padding slots of the gid table — an all-zero indicator column,
    # inert through every hop and the final contraction
    q0 = ((col_gids[:, :, None] == src) & live).astype(jnp.float32)
    Q = DistMultiVec(blocks=q0, length=n, align="col", grid=grid)
    for _ in range(max(int(hops), 0)):
        if normalize:
            # (AᵀD⁻¹)Q: scale by the reciprocal degree BEFORE the
            # transpose hop — the adjoint of spmm_khop's post-hop
            # row normalization
            Qc = Q.realign("col")
            Q = dataclasses.replace(
                Qc, blocks=Qc.blocks * invdeg.blocks[..., None]
            )
        Q = dist_spmm_ell(PLUS_TIMES, ET, Q, backend=backend)
    Qr = Q.realign("row")

    def body(xb, qb):
        # one [F, L] × [L, W] MXU contraction per grid row, reduced
        # over the row axis — the only place the feature table is read
        r = jnp.dot(
            xb[0].T, qb[0], preferred_element_type=jnp.float32
        )
        return lax.psum(r, ROW_AXIS)

    return jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(P(ROW_AXIS), P(ROW_AXIS)),
        out_specs=P(),
    )(X.blocks, Qr.blocks)
