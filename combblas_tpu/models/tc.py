"""Triangle counting — masked SpGEMM (≈ Applications/TC.cpp).

The reference computes ``L = tril(A)``, ``C = (L * L) .* L`` with
``Mult_AnXBn_Synch<PlusTimesSRing>`` + ``EWiseMult``, then sums C
(``TC.cpp:104-116``).  Here: the SUMMA SpGEMM over the mesh, the mask as
``ewise_mult``, and the final sum as a column reduce + vector fold — each
triangle {i>j>k} contributes C[i,j] += 1 via the wedge through k.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from .. import obs
from ..ops.spgemm import (
    combine_hilo,
    coo_sort_dedup as _coo_sort_dedup,
    pack_support_bits,
    popcount_pair_counts,
)
from ..semiring import PLUS_TIMES
from ..parallel.spgemm import spgemm, summa_spgemm
from ..parallel.spmat import SpParMat, ones_f32

#: Above this dimension the dense [n, n] mask product would exceed a few
#: GB of HBM; the sparse SUMMA path takes over.
DENSE_MAX_DIM = 32768


def _tc_dense(rows, cols, n: int) -> jax.Array:
    """One-launch dense TC: sum((L·L) ⊙ L) on the MXU.

    bf16 0/1 inputs are exact; per-cell wedge counts < n < 2^24 are exact
    in the f32 accumulator.  No sparse extraction at all — the mask IS
    the (tiny) output support, so the whole computation is matmul + two
    elementwise passes.

    Returns an int32 [2] (hi, lo) split of the global triangle count:
    the GLOBAL total can exceed 2^31 for dense graphs within
    ``DENSE_MAX_DIM`` (a complete graph at n~3000 already would) while
    int64 is unavailable without x64 mode (ADVICE r4).  Per-row sums are
    int32-exact (< n^2 <= 2^30); each splits into 15-bit halves whose
    column sums stay < n * 2^15 <= 2^30.  ``_tc_combine`` reassembles the
    exact Python int (range 2^45 — beyond any n <= 32768 count).
    """
    npad = -(-n // 128) * 128
    keep = rows > cols  # strict lower triangle, loops dropped
    r = jnp.where(keep, rows, npad)
    c = jnp.where(keep, cols, npad)
    d = jnp.zeros((npad, npad), jnp.bfloat16)
    d = d.at[r, c].set(jnp.bfloat16(1.0), mode="drop")
    wedges = jnp.dot(d, d, preferred_element_type=jnp.float32)
    masked = wedges * d.astype(jnp.float32)
    # cast per CELL before the row sum: cells are f32-exact (< n < 2^24)
    # but an f32 row accumulation would round past 2^24; int32 row sums
    # are exact below n^2 <= 2^30
    rowsum = jnp.sum(masked.astype(jnp.int32), axis=1)
    hi = jnp.sum(rowsum >> 15)
    lo = jnp.sum(rowsum & 0x7FFF)
    return jnp.stack([hi, lo])


#: Edge-harvest ceilings: the symmetric adjacency must fit HBM — bf16
#: n^2 bytes*2 (8.6 GB at n = 65536), bit-packed n^2/8 bytes (8.6 GB at
#: n = 262144, i.e. scale 18 on the 16 GB chip).
EDGE_HARVEST_MAX_DIM = 65536
EDGE_HARVEST_BITS_MAX_DIM = 262144


# _coo_sort_dedup now lives in ops/spgemm.py (coo_sort_dedup) — it is the
# shared dedup front of every bit-packed kernel, imported above.


def _tc_edge_harvest(rows, cols, n: int, chunk: int = 4096) -> jax.Array:
    """One-launch TC past the dense-product ceiling (32K < n <= 64K):
    per-EDGE common-neighbor harvest against the dense adjacency.

    The full dense wedge product is 2n^3 FLOPs (~560 TFLOP at n = 64K —
    ~42 s even at MXU peak) and its f32 output doesn't fit HBM next to
    the operand. But TC only needs (A·A)[i,j] ON the edges: for each
    undirected edge (i>j), |N(i) ∩ N(j)| = <D[i,:], D[j,:]> = number of
    triangles through that edge, so

        3·T = Σ_{edges i>j} <D[i,:], D[j,:]>

    which is 2·nnz/2·n ≈ 1.3e11 multiply-adds (4000x fewer than dense)
    and is HBM-BOUND: ~2 full-row loads per edge ≈ nnz·n·2 B of traffic.
    A lax.scan walks static edge chunks; each step gathers [chunk, n]
    bf16 row pairs and dots them on the VPU (0/1 bf16 products are
    exact; per-edge counts < n < 2^24 are f32-exact).

    Returns the (hi, lo) int32 split of 3·T (``_tc_combine`` // 3 gives
    T; 3·T can exceed 2^31 — same split rationale as ``_tc_dense``).

    Reference role: the masked Mult_AnXBn of TC.cpp:104-116, redesigned
    output-driven for a chip with no scatter unit (the ESC sparse path
    pays the 22 M/s random-memory wall — 87 s at scale 16).
    """
    npad = -(-n // 128) * 128
    # ON-DEVICE DEDUP: the adjacency ``.set`` is idempotent, but the
    # EDGE WALK below is not — a duplicated COO entry would harvest its
    # common neighbors twice and double-count 3T; repeats are masked out
    # of the edge list.
    rows, cols, dup = _coo_sort_dedup(rows, cols)
    loops = rows == cols
    # dense SYMMETRIC adjacency (input is symmetrized; drop loops; padded
    # sentinel slots land in the dump row npad-? -> use drop mode)
    r_all = jnp.where(loops, npad, rows)
    d = jnp.zeros((npad, npad), jnp.bfloat16)
    d = d.at[r_all, cols].set(jnp.bfloat16(1.0), mode="drop")
    # strict-lower edge list, padded slots -> row 0 x col 0 with weight 0
    keep = (rows > cols) & ~dup
    nedge = rows.shape[0]
    epad = -(-nedge // chunk) * chunk
    er = jnp.where(keep, rows, 0)
    ec = jnp.where(keep, cols, 0)
    ew = keep.astype(jnp.float32)
    er = jnp.pad(er, (0, epad - nedge))
    ec = jnp.pad(ec, (0, epad - nedge))
    ew = jnp.pad(ew, (0, epad - nedge))

    def body(carry, eidx):
        hi, lo = carry
        ri = er[eidx]  # [chunk]
        ci = ec[eidx]
        wi = ew[eidx]
        gi = d[ri]  # [chunk, npad] bf16
        gj = d[ci]
        w = jnp.einsum(
            "bn,bn->b", gi, gj, preferred_element_type=jnp.float32
        )
        cnt = (w * wi).astype(jnp.int32)  # per-edge: exact (< n < 2^24)
        # renormalize the split each step: an unbounded lo accumulation
        # would itself wrap past 2^31 on triangle-rich graphs (the exact
        # regime this kernel exists for)
        lo = lo + jnp.sum(cnt & 0x7FFF)
        hi = hi + jnp.sum(cnt >> 15) + (lo >> 15)
        lo = lo & 0x7FFF
        return (hi, lo), None

    idx = jnp.arange(epad, dtype=jnp.int32).reshape(-1, chunk)
    (hi, lo), _ = jax.lax.scan(body, (jnp.int32(0), jnp.int32(0)), idx)
    return jnp.stack([hi, lo])


def _tc_edge_harvest_bits(rows, cols, n: int, chunk: int = 8192) -> jax.Array:
    """Bit-packed edge-harvest TC: the adjacency as a [n, n/32] uint32
    bitmask; each edge's common-neighbor count is popcount(row_i & row_j).

    Same mathematics as ``_tc_edge_harvest`` with 16x less gather
    traffic (8 KB/row at n = 64K instead of 131 KB of bf16) — the
    bf16 variant measured only ~12 GB/s of effective row-gather
    bandwidth on the chip, so traffic is the knob that matters. Packing
    is a scatter-ADD of 2^(c mod 32) at (r, c div 32): the input COO is
    dedup'd, so add ≡ bitwise-or (each bit lands exactly once).

    Returns the (hi, lo) int32 split of 3·T like ``_tc_edge_harvest``.
    """
    # ON-DEVICE DEDUP (duplicate COO entries would double-add a bit,
    # carrying into the NEXT bit and corrupting the adjacency — unlike
    # the idempotent .set of the bf16 variant): mask repeats, zero their
    # bit contribution AND their edge weight.
    rows, cols, dup = _coo_sort_dedup(rows, cols)
    loops = rows == cols
    r_all = jnp.where(loops | dup, n, rows)  # dropped (mode="drop")
    bits = pack_support_bits(r_all, cols, n, n, assume_unique=True)
    keep = (rows > cols) & ~dup
    nedge = rows.shape[0]
    epad = -(-nedge // chunk) * chunk
    er = jnp.pad(jnp.where(keep, rows, 0), (0, epad - nedge))
    ec = jnp.pad(jnp.where(keep, cols, 0), (0, epad - nedge))
    ew = jnp.pad(keep.astype(jnp.int32), (0, epad - nedge))
    return popcount_pair_counts(bits, bits, er, ec, ew, chunk=chunk)


#: Exact host-side total from a (hi, lo) split — shared with the other
#: bit-packed kernels (ops/spgemm.py).
_tc_combine = combine_hilo


@partial(jax.jit, static_argnames=("chunk",))
def _tc_edge_harvest_dist(A: SpParMat, chunk: int = 8192) -> jax.Array:
    """DISTRIBUTED bit-packed edge-harvest TC: the output-support oracle
    tier on a p x p mesh.

    Each device packs its tile of the symmetric adjacency into a
    [local_rows, lc/32] bitmask over its own LOCAL columns, gathers the
    packed tiles along its grid row and CONCATENATES them on the word
    axis (column tiles cover disjoint, word-aligned global column
    ranges — requires ``local_cols % 32 == 0``, which ``triangle_count``
    enforces), and fetches its grid COLUMN's row-block mask from the
    transpose-partner device with one ``ppermute`` (the mesh transpose,
    SpParMat.transpose's route).  Every device then harvests ONLY ITS
    OWN tile's strict-lower edges — the edge mask is already
    distributed — with ``popcount_pair_counts`` over the two local
    tables, and the (hi, lo) partial sums ``psum`` into the global
    3·T count.  Local-column packing keeps the gather transient at the
    table's own n²/(8p) bytes (packing full-width [lr, n/32] tiles and
    OR-folding would transiently materialize p copies = n²/8 — the
    single-shard footprint the distribution exists to avoid).
    """
    from ..parallel.grid import COL_AXIS, ROW_AXIS
    from ..parallel.spmat import TILE_SPEC
    from jax.sharding import PartitionSpec as P

    grid = A.grid
    p = grid.pr
    assert grid.is_square, "edge-harvest TC needs a square grid"
    n = A.nrows
    lr, lc = A.local_rows, A.local_cols
    assert lr == lc, "square blocking required (symmetric adjacency)"
    assert lc % 32 == 0 or p == 1, (
        f"distributed edge-harvest needs word-aligned column tiles "
        f"(local_cols {lc} % 32 != 0); pad the matrix or use "
        "kernel='sparse'"
    )
    nw_loc = -(-lc // 32)
    cap = A.capacity
    epad = -(-cap // chunk) * chunk

    def body(ar, ac):
        rows, cols = ar[0, 0], ac[0, 0]
        ri = lax.axis_index(ROW_AXIS)
        ci = lax.axis_index(COL_AXIS)
        valid = rows < lr
        grows = jnp.where(valid, rows + ri * lr, n)
        gcols = jnp.where(valid, cols + ci * lc, n)
        grows, gcols, dup = _coo_sort_dedup(grows, gcols)
        loops = grows == gcols
        # EXPLICIT drop mask, then localize: sentinel ARITHMETIC is a
        # trap here — with ceil-blocking over-cover (n % lr != 0) the
        # n-sentinel minus the last block's offset lands back INSIDE
        # [0, lr), and pack's scatter-ADD would pile every padded slot
        # onto one cell, carrying across bits.  Dropped slots get the
        # row sentinel lr directly (>= nrows ⇒ pack drops them whatever
        # their column).
        drop = dup | loops | (grows >= n)
        rloc = jnp.where(drop, lr, grows - ri * lr)
        cloc = jnp.where(drop, lc, gcols - ci * lc)
        bits_tile = pack_support_bits(
            rloc, cloc, lr, nw_loc * 32, assume_unique=True
        )
        # concat along the word axis: grid-row tiles cover disjoint,
        # word-aligned global column ranges (lc % 32 == 0), so the
        # gathered [p, lr, nw_loc] blocks ARE the full row mask
        g = lax.all_gather(bits_tile, COL_AXIS)
        rowbits = jnp.transpose(g, (1, 0, 2)).reshape(lr, p * nw_loc)
        # transpose partner: device (r, c) <- (c, r) row-block mask
        colbits = lax.ppermute(
            rowbits, (ROW_AXIS, COL_AXIS), grid.transpose_perm()
        )
        keep = (~dup) & (grows < n) & (grows > gcols)
        er = jnp.where(keep, grows - ri * lr, 0)
        ec = jnp.where(keep, gcols - ci * lc, 0)
        ew = keep.astype(jnp.int32)
        er = jnp.pad(er, (0, epad - cap))
        ec = jnp.pad(ec, (0, epad - cap))
        ew = jnp.pad(ew, (0, epad - cap))
        hilo = popcount_pair_counts(rowbits, colbits, er, ec, ew, chunk=chunk)
        return lax.psum(lax.psum(hilo, ROW_AXIS), COL_AXIS)

    return jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(TILE_SPEC,) * 2,
        out_specs=P(),
        check_vma=False,
    )(A.rows, A.cols)


def triangle_count(A: SpParMat, kernel: str = "auto") -> int:
    """Number of triangles in the simple undirected graph A (symmetric,
    loop-free nonzero structure).

    ``kernel="dense"`` (or "auto" on a single shard with n <=
    ``DENSE_MAX_DIM``) runs the round-4 one-launch MXU path: on the
    target chip the sparse masked SpGEMM pays the ~22 M/s random-memory
    wall (6.31 s at scale 14, PERF_NOTES_r3) while the dense product runs
    at 13.3 TFLOP/s and the mask removes any need for sparse extraction.
    ``kernel="edgeharvest"`` (the bit-packed output-support tier) now
    works on MULTI-DEVICE square grids too (round 6,
    ``_tc_edge_harvest_dist``): per-device row-block bitmasks, OR along
    grid rows, transpose-partner ppermute, psum'd popcount partials —
    and "auto" picks it for sharded graphs within the n²/(8p) per-device
    mask budget.  ``kernel="sparse"`` forces the distributed
    masked-SpGEMM path (TC.cpp:104-116 flow), the fallback beyond the
    mask budget and on non-square grids; NOTE it expects a deduplicated
    edge list (values are wedge counts), while the harvest kernels
    dedup on device.
    """
    p = A.grid.pr
    # distributed bitmask budget: two n²/(8p)-byte tables per device must
    # fit the single-shard kernel's one-table HBM envelope
    dist_bits_cap = int(EDGE_HARVEST_BITS_MAX_DIM * (p / 2) ** 0.5)
    if kernel == "auto":
        if A.grid.size == 1 and max(A.nrows, A.ncols) <= DENSE_MAX_DIM:
            kernel = "dense"
        elif (
            A.grid.size == 1
            and max(A.nrows, A.ncols) <= EDGE_HARVEST_BITS_MAX_DIM
        ) or (
            A.grid.size > 1
            and A.grid.is_square
            and A.local_cols % 32 == 0  # word-aligned tile concat
            and max(A.nrows, A.ncols) <= dist_bits_cap
        ):
            kernel = "edgeharvest"
        else:
            kernel = "sparse"
    if kernel == "dense":
        t = A.local_tile(A.rows, A.cols, A.vals, A.nnz)
        return _tc_combine(
            jax.jit(_tc_dense, static_argnums=2)(t.rows, t.cols, A.nrows)
        )
    harvest = {
        "edgeharvest": _tc_edge_harvest_bits,
        "edgeharvest_bf16": _tc_edge_harvest,
    }
    if kernel in harvest:
        if obs.ENABLED:
            obs.count("spgemm.auto.tier", tier=kernel, sr="plus_times")
        if A.grid.size > 1:
            # the DISTRIBUTED oracle tier: only the bit-packed variant
            # (the bf16 one has no distributed formulation — its gather
            # traffic is the reason the bitmask exists)
            if kernel != "edgeharvest":
                raise ValueError(
                    "distributed edge-harvest supports kernel="
                    f"'edgeharvest' only, got {kernel}"
                )
            if max(A.nrows, A.ncols) > dist_bits_cap:
                raise ValueError(
                    "distributed edgeharvest needs two n^2/(8p)-byte "
                    f"bitmasks per device: n <= {dist_bits_cap} on this "
                    f"{p}x{p} grid, got {max(A.nrows, A.ncols)}"
                )
            return combine_hilo(_tc_edge_harvest_dist(A)) // 3
        cap = (
            EDGE_HARVEST_BITS_MAX_DIM if kernel == "edgeharvest"
            else EDGE_HARVEST_MAX_DIM
        )
        if max(A.nrows, A.ncols) > cap:
            raise ValueError(
                f"{kernel} needs the dense adjacency in HBM: "
                f"n <= {cap}, got {max(A.nrows, A.ncols)}"
            )
        t = A.local_tile(A.rows, A.cols, A.vals, A.nnz)
        return _tc_combine(
            jax.jit(harvest[kernel], static_argnums=2)(
                t.rows, t.cols, A.nrows
            )
        ) // 3
    L = A.remove_loops().tril(strict=True).apply(ones_f32)
    B = spgemm(PLUS_TIMES, L, L)  # B[i,j] = # wedges i->k->j with i>k>j
    C = B.ewise_mult(L)  # keep wedge counts only where edge (i,j) closes
    colsums = C.reduce(PLUS_TIMES, axis="rows")
    return int(colsums.reduce(PLUS_TIMES))
