"""Triangle counting — masked SpGEMM (≈ Applications/TC.cpp).

The reference computes ``L = tril(A)``, ``C = (L * L) .* L`` with
``Mult_AnXBn_Synch<PlusTimesSRing>`` + ``EWiseMult``, then sums C
(``TC.cpp:104-116``).  Here: the SUMMA SpGEMM over the mesh, the mask as
``ewise_mult``, and the final sum as a column reduce + vector fold — each
triangle {i>j>k} contributes C[i,j] += 1 via the wedge through k.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..semiring import PLUS_TIMES
from ..parallel.spgemm import spgemm, summa_spgemm
from ..parallel.spmat import SpParMat, ones_f32


def triangle_count(A: SpParMat) -> int:
    """Number of triangles in the simple undirected graph A (symmetric,
    loop-free nonzero structure). Unjitted entry: runs the distributed
    symbolic pass to size the SpGEMM, then the compiled numeric pass.
    """
    L = A.remove_loops().tril(strict=True).apply(ones_f32)
    B = spgemm(PLUS_TIMES, L, L)  # B[i,j] = # wedges i->k->j with i>k>j
    C = B.ewise_mult(L)  # keep wedge counts only where edge (i,j) closes
    colsums = C.reduce(PLUS_TIMES, axis="rows")
    return int(colsums.reduce(PLUS_TIMES))
