"""Triangle counting — masked SpGEMM (≈ Applications/TC.cpp).

The reference computes ``L = tril(A)``, ``C = (L * L) .* L`` with
``Mult_AnXBn_Synch<PlusTimesSRing>`` + ``EWiseMult``, then sums C
(``TC.cpp:104-116``).  Here: the SUMMA SpGEMM over the mesh, the mask as
``ewise_mult``, and the final sum as a column reduce + vector fold — each
triangle {i>j>k} contributes C[i,j] += 1 via the wedge through k.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..semiring import PLUS_TIMES
from ..parallel.spgemm import spgemm, summa_spgemm
from ..parallel.spmat import SpParMat, ones_f32

#: Above this dimension the dense [n, n] mask product would exceed a few
#: GB of HBM; the sparse SUMMA path takes over.
DENSE_MAX_DIM = 32768


def _tc_dense(rows, cols, n: int) -> jax.Array:
    """One-launch dense TC: sum((L·L) ⊙ L) on the MXU.

    bf16 0/1 inputs are exact; per-cell wedge counts < n < 2^24 are exact
    in the f32 accumulator.  No sparse extraction at all — the mask IS
    the (tiny) output support, so the whole computation is matmul + two
    elementwise passes.

    Returns an int32 [2] (hi, lo) split of the global triangle count:
    the GLOBAL total can exceed 2^31 for dense graphs within
    ``DENSE_MAX_DIM`` (a complete graph at n~3000 already would) while
    int64 is unavailable without x64 mode (ADVICE r4).  Per-row sums are
    int32-exact (< n^2 <= 2^30); each splits into 15-bit halves whose
    column sums stay < n * 2^15 <= 2^30.  ``_tc_combine`` reassembles the
    exact Python int (range 2^45 — beyond any n <= 32768 count).
    """
    npad = -(-n // 128) * 128
    keep = rows > cols  # strict lower triangle, loops dropped
    r = jnp.where(keep, rows, npad)
    c = jnp.where(keep, cols, npad)
    d = jnp.zeros((npad, npad), jnp.bfloat16)
    d = d.at[r, c].set(jnp.bfloat16(1.0), mode="drop")
    wedges = jnp.dot(d, d, preferred_element_type=jnp.float32)
    masked = wedges * d.astype(jnp.float32)
    # cast per CELL before the row sum: cells are f32-exact (< n < 2^24)
    # but an f32 row accumulation would round past 2^24; int32 row sums
    # are exact below n^2 <= 2^30
    rowsum = jnp.sum(masked.astype(jnp.int32), axis=1)
    hi = jnp.sum(rowsum >> 15)
    lo = jnp.sum(rowsum & 0x7FFF)
    return jnp.stack([hi, lo])


def _tc_combine(hilo) -> int:
    """Exact host-side total from ``_tc_dense``'s (hi, lo) split."""
    import numpy as np

    hilo = np.asarray(hilo, np.int64)
    return int((hilo[0] << 15) + hilo[1])


def triangle_count(A: SpParMat, kernel: str = "auto") -> int:
    """Number of triangles in the simple undirected graph A (symmetric,
    loop-free nonzero structure).

    ``kernel="dense"`` (or "auto" on a single shard with n <=
    ``DENSE_MAX_DIM``) runs the round-4 one-launch MXU path: on the
    target chip the sparse masked SpGEMM pays the ~22 M/s random-memory
    wall (6.31 s at scale 14, PERF_NOTES_r3) while the dense product runs
    at 13.3 TFLOP/s and the mask removes any need for sparse extraction.
    ``kernel="sparse"`` forces the distributed masked-SpGEMM path
    (TC.cpp:104-116 flow) used for large or sharded inputs.
    """
    if kernel == "auto":
        kernel = (
            "dense"
            if A.grid.size == 1 and max(A.nrows, A.ncols) <= DENSE_MAX_DIM
            else "sparse"
        )
    if kernel == "dense":
        t = A.local_tile(A.rows, A.cols, A.vals, A.nnz)
        return _tc_combine(
            jax.jit(_tc_dense, static_argnums=2)(t.rows, t.cols, A.nrows)
        )
    L = A.remove_loops().tril(strict=True).apply(ones_f32)
    B = spgemm(PLUS_TIMES, L, L)  # B[i,j] = # wedges i->k->j with i>k>j
    C = B.ewise_mult(L)  # keep wedge counts only where edge (i,j) closes
    colsums = C.reduce(PLUS_TIMES, axis="rows")
    return int(colsums.reduce(PLUS_TIMES))
