"""Graph500 top-down BFS (≈ Applications/TopDownBFS.cpp).

The reference iterates ``fringe = SpMV(A, fringe, optbuf)`` with a
select-max semiring, prunes discovered vertices with ``EWiseMult``, and sets
parents (``TopDownBFS.cpp:437-444``; semiring ``SelectMaxSRing``
Semirings.h:166).  The frontier there is a ``FullyDistSpVec`` because on CPU
clusters touching only active vertices is the whole game.

On TPU the frontier is a *dense* distributed vector of parent candidates
(-1 = inactive): every step is one masked semiring SpMV + elementwise
updates, with zero dynamic shapes — the compiled program is identical every
iteration, which is what XLA wants.  This is the same observation that makes
the reference's *bottom-up* phase (``BFSFriends.h:457-560``) dense: we simply
run the dense formulation in both regimes.  TEPS is unchanged: inactive
lanes carry the additive identity through the same ALU ops the active lanes
use.

The sparse-frontier SpMSpV path still exists (``parallel/spmv.py`` +
``ops/spmv.spmspv``) for API parity and for workloads with tiny frontiers.
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp

from . import PAD_ROOT
from .. import obs
from ..semiring import PLUS_TIMES, SELECT2ND_MAX
from ..parallel.spmat import SpParMat, ones_i32
from ..parallel.spmv import dist_spmspv_masked, dist_spmv_masked
from ..parallel.vec import DistVec


def _global_ids(grid, nblocks, block_len, length, align):
    gids = jnp.arange(nblocks * block_len, dtype=jnp.int32).reshape(
        nblocks, block_len
    )
    return jnp.where(gids < length, gids, -1)


@partial(jax.jit, static_argnames=("max_iters", "sr"))
def bfs(
    A: SpParMat,
    source,
    max_iters: int | None = None,
    sr: "Semiring" = SELECT2ND_MAX,
):
    """Level-synchronous BFS from ``source`` over a select-style semiring
    (default SELECT2ND_MAX — structural; pass a value-aware semiring like
    ``semantic.FILTERED_SELECT2ND_MAX`` for on-the-fly edge filtering).

    A is interpreted as: entry (i, j) ≠ 0 means edge j → i (gather from
    in-neighbors, matching the reference's SpMV orientation). Symmetrize for
    undirected graphs.

    Returns (parents, levels, num_iters): row-aligned DistVecs of int32;
    undiscovered vertices hold -1.
    """
    grid = A.grid
    n = A.nrows
    pr_, lr = grid.pr, grid.local_rows(n)
    pc_, lc = grid.pc, grid.local_cols(A.ncols)
    iters = max_iters if max_iters is not None else n

    row_gids = _global_ids(grid, pr_, lr, n, "row")
    col_gids = _global_ids(grid, pc_, lc, A.ncols, "col")

    parents0 = jnp.where(row_gids == source, source, -1).astype(jnp.int32)
    levels0 = jnp.where(row_gids == source, 0, -1).astype(jnp.int32)
    x0 = jnp.where(col_gids == source, source, -1).astype(jnp.int32)

    def mk_row(blocks):
        return DistVec(blocks=blocks, length=n, align="row", grid=grid)

    def mk_col(blocks):
        return DistVec(blocks=blocks, length=A.ncols, align="col", grid=grid)

    def cond(state):
        _, _, x, level, active = state
        return active & (level < iters)

    def step(state):
        parents, levels, x, level, _ = state
        unvisited = mk_row(parents < 0)
        y = dist_spmv_masked(sr, A, mk_col(x), unvisited)
        new = (y.blocks >= 0) & (parents < 0) & (row_gids >= 0)
        parents = jnp.where(new, y.blocks, parents)
        levels = jnp.where(new, level + 1, levels)
        frontier_row = mk_row(jnp.where(new, row_gids, -1))
        x_next = frontier_row.realign("col").blocks
        active = jnp.any(new)
        return parents, levels, x_next, level + 1, active

    parents, levels, _, niter, _ = jax.lax.while_loop(
        cond, step, (parents0, levels0, x0, jnp.int32(0), jnp.bool_(True))
    )
    return mk_row(parents), mk_row(levels), niter


@partial(jax.jit, static_argnames=("sr",))
def _bfs_level_step(sr, A, parents, levels, x, row_gids, level):
    """ONE level of the dense-frontier BFS as its own jitted program —
    the host-stepped unit ``bfs_levels_instrumented`` drives. Returns
    (parents, levels, x_next, new-vertex count)."""
    grid = A.grid
    n = A.nrows
    unvisited = DistVec(blocks=parents < 0, length=n, align="row", grid=grid)
    xv = DistVec(blocks=x, length=A.ncols, align="col", grid=grid)
    y = dist_spmv_masked(sr, A, xv, unvisited)
    new = (y.blocks >= 0) & (parents < 0) & (row_gids >= 0)
    parents = jnp.where(new, y.blocks, parents)
    levels = jnp.where(new, level + 1, levels)
    frontier_row = DistVec(
        blocks=jnp.where(new, row_gids, -1), length=n, align="row", grid=grid,
    )
    x_next = frontier_row.realign("col").blocks
    return parents, levels, x_next, jnp.sum(new).astype(jnp.int32)


def bfs_levels_instrumented(
    A,
    source,
    max_iters: int | None = None,
    sr: "Semiring" = SELECT2ND_MAX,
):
    """Host-stepped level-synchronous BFS with one ``obs`` span PER HOP,
    each carrying a ``frontier`` event with the discovered-vertex count —
    the per-iteration table of the reference's TIMING builds
    (``TopDownBFS.cpp:472-479``), structured.

    DEBUG/OBSERVABILITY TOOL, not the benchmark kernel: every level pays
    a device→host sync for the frontier count (which also terminates the
    loop), exactly what the one-launch kernels (``bfs``, ``bfs_single``,
    ``bfs_batch``) exist to avoid on readback-poisoned hardware. Use it
    on CPU, in tests, or in a throwaway diagnostic process; the spans
    line up with ``jax.profiler`` traces via their TraceAnnotations.

    Works for SpParMat and EllParMat (``dist_spmv_masked`` dispatches).
    Returns (parents, levels, num_levels) like ``bfs``.
    """
    grid = A.grid
    n = A.nrows
    pr_, lr = grid.pr, grid.local_rows(n)
    pc_, lc = grid.pc, grid.local_cols(A.ncols)
    iters = max_iters if max_iters is not None else n

    row_gids = _global_ids(grid, pr_, lr, n, "row")
    col_gids = _global_ids(grid, pc_, lc, A.ncols, "col")
    parents = jnp.where(row_gids == source, jnp.int32(source), -1)
    levels = jnp.where(row_gids == source, 0, -1).astype(jnp.int32)
    x = jnp.where(col_gids == source, jnp.int32(source), -1)

    niter = 0
    with obs.span("bfs", source=int(source), nrows=int(n)):
        for hop in range(iters):
            with obs.span("bfs.hop", hop=hop):
                parents, levels, x, nnew = _bfs_level_step(
                    sr, A, parents, levels, x, row_gids, jnp.int32(hop)
                )
                frontier_nnz = int(nnew)  # the level's host sync
                obs.span_event(
                    "frontier", hop=hop + 1, nnz=frontier_nnz
                )
            # executed-iteration count, matching ``bfs``'s while_loop
            # semantics (the terminal empty level is counted too)
            niter = hop + 1
            if frontier_nnz == 0:
                break
    mk = lambda b: DistVec(blocks=b, length=n, align="row", grid=grid)
    return mk(parents), mk(levels), niter


@partial(jax.jit, static_argnames=("frontier_capacity", "exp_capacity"))
def _diropt_topdown_step(
    A, parents, levels, x, row_gids, level, frontier_capacity, exp_capacity
):
    """One sparse-frontier (top-down) level. x is the col-aligned dense
    candidate vector (-1 = inactive)."""
    grid = A.grid
    n = A.nrows
    unvisited = DistVec(
        blocks=parents < 0, length=n, align="row", grid=grid
    )
    xv = DistVec(blocks=x, length=A.ncols, align="col", grid=grid)
    xact = DistVec(blocks=x >= 0, length=A.ncols, align="col", grid=grid)
    y = dist_spmspv_masked(
        SELECT2ND_MAX, A, xv, xact, unvisited,
        frontier_capacity=frontier_capacity, exp_capacity=exp_capacity,
    )
    return _diropt_update(A, parents, levels, y, row_gids, level)


@jax.jit
def _diropt_bottomup_step(A, parents, levels, x, row_gids, level):
    """One dense (bottom-up regime) level: every unvisited vertex probes all
    its neighbors in one masked SpMV — the dense formulation that plays the
    role of the reference's BottomUpStep carousel (``BFSFriends.h:457-560``;
    the ring rotation is XLA's own ICI all-reduce lowering of the fold)."""
    grid = A.grid
    n = A.nrows
    unvisited = DistVec(blocks=parents < 0, length=n, align="row", grid=grid)
    xv = DistVec(blocks=x, length=A.ncols, align="col", grid=grid)
    y = dist_spmv_masked(SELECT2ND_MAX, A, xv, unvisited)
    return _diropt_update(A, parents, levels, y, row_gids, level)


def _diropt_update(A, parents, levels, y, row_gids, level):
    new = (y.blocks >= 0) & (parents < 0) & (row_gids >= 0)
    parents = jnp.where(new, y.blocks, parents)
    levels = jnp.where(new, level + 1, levels)
    frontier_row = DistVec(
        blocks=jnp.where(new, row_gids, -1), length=A.nrows, align="row",
        grid=A.grid,
    )
    x_next = frontier_row.realign("col").blocks
    nnew = jnp.sum(new).astype(jnp.int32)
    return parents, levels, x_next, nnew


@jax.jit
def _frontier_stats(x, deg_blocks):
    """(frontier vertex count, frontier out-edge count) from the col-aligned
    candidate vector.

    The edge count accumulates in float32: int32 would wrap for hub-heavy
    frontiers at Graph500 scale and silently corrupt the regime switch. The
    caller compensates for float32 rounding with a 1% comparison margin.
    """
    act = x >= 0
    cnt = jnp.sum(act)
    edges = jnp.sum(jnp.where(act, deg_blocks, 0).astype(jnp.float32))
    return cnt, edges


@partial(
    jax.jit,
    static_argnames=("frontier_capacity", "exp_capacity", "max_iters"),
)
def bfs_diropt(
    A: SpParMat,
    source,
    *,
    frontier_capacity: int,
    exp_capacity: int,
    max_iters: int | None = None,
):
    """Direction-optimizing BFS (≈ Applications/DirOptBFS.cpp, Beamer),
    fully on device.

    The per-level regime switch is a ``lax.cond`` on frontier statistics
    INSIDE the while_loop — both regimes compile once and zero
    device-to-host readbacks happen during the search (the round-1 host
    switch permanently degraded the chip's launch path via its per-level
    ``int(cnt)`` readbacks; see bench.py's D2H note). Top-down runs the
    budgeted sparse-frontier kernel (work ∝ the static budgets); bottom-up
    runs the dense masked SpMV (work ∝ tile nnz, the regime where the
    reference's carousel operates, ``DirOptBFS.cpp:374-424``).

    The caller chooses the static budgets; the switch takes top-down when
    the frontier fits BOTH budgets with the same 1% float32 margin the
    host version used.

    Returns (parents, levels, num_iters) like ``bfs``.
    """
    grid = A.grid
    n = A.nrows
    pr_, lr = grid.pr, grid.local_rows(n)
    pc_, lc = grid.pc, grid.local_cols(A.ncols)
    iters = max_iters if max_iters is not None else n

    row_gids = _global_ids(grid, pr_, lr, n, "row")
    col_gids = _global_ids(grid, pc_, lc, A.ncols, "col")
    parents0 = jnp.where(row_gids == source, jnp.int32(source), -1)
    levels0 = jnp.where(row_gids == source, 0, -1).astype(jnp.int32)
    x0 = jnp.where(col_gids == source, jnp.int32(source), -1)

    # out-degree per column (structural), for the edge-budget check
    deg = A.reduce(PLUS_TIMES, "rows", map_fn=ones_i32).blocks

    def cond(state):
        _, _, _, level, active = state
        return active & (level < iters)

    def step(state):
        parents, levels, x, level, _ = state
        cnt, edges = _frontier_stats(x, deg)
        use_topdown = (cnt <= frontier_capacity) & (
            edges <= 0.99 * exp_capacity
        )
        parents, levels, x, nnew = jax.lax.cond(
            use_topdown,
            lambda a: _diropt_topdown_step(
                A, a[0], a[1], a[2], row_gids, a[3],
                frontier_capacity, exp_capacity,
            ),
            lambda a: _diropt_bottomup_step(
                A, a[0], a[1], a[2], row_gids, a[3]
            ),
            (parents, levels, x, level),
        )
        return parents, levels, x, level + 1, nnew > 0

    parents, levels, _, niter, _ = jax.lax.while_loop(
        cond, step, (parents0, levels0, x0, jnp.int32(0), jnp.bool_(True))
    )
    mk = lambda b: DistVec(blocks=b, length=n, align="row", grid=grid)
    return mk(parents), mk(levels), niter


def bfs_diropt_auto(A: SpParMat, source, max_iters: int | None = None):
    """``bfs_diropt`` with the round-1 default budget heuristics
    (host-side, static: lc/8 frontier slots, nnz-capacity/8 edge slots)."""
    lc = A.grid.local_cols(A.ncols)
    cap = A.capacity
    fc = min(max(64, lc // 8 + 1), lc)
    ec = min(max(256, cap // 8 + 1), cap)
    return bfs_diropt(
        A, source, frontier_capacity=fc, exp_capacity=ec,
        max_iters=max_iters,
    )


def traversed_edges(A: SpParMat, parents: DistVec) -> jax.Array:
    """Graph500 kernel-2 edge count: edges with a discovered endpoint / 2.

    Matches the TEPS accounting of ``TopDownBFS.cpp:448-465`` for
    symmetrized graphs (each undirected edge stored twice).
    """
    deg = A.reduce(PLUS_TIMES, axis="cols", map_fn=ones_i32)
    disc = parents.realign("row").blocks >= 0
    return jnp.sum(jnp.where(disc, deg.blocks, 0)) // 2


def validate_bfs_tree(A_dense, source, parents, levels) -> list[str]:
    """Host-side BFS tree validation (Graph500 verify.c-style checks).

    Returns a list of violation strings (empty = valid).
    """
    import numpy as np

    A_dense = np.asarray(A_dense)
    p = np.asarray(parents)
    lv = np.asarray(levels)
    n = A_dense.shape[0]
    errs = []
    if p[source] != source or lv[source] != 0:
        errs.append("source not its own parent at level 0")
    for v in range(n):
        if v == source or p[v] < 0:
            continue
        if not A_dense[v, p[v]]:
            errs.append(f"tree edge ({p[v]},{v}) not in graph")
        if lv[v] != lv[p[v]] + 1:
            errs.append(f"level[{v}]={lv[v]} != level[parent]+1={lv[p[v]] + 1}")
    # reachability: discovered set must equal BFS-reachable set
    from collections import deque

    seen = {source}
    q = deque([source])
    while q:
        u = q.popleft()
        for w in np.nonzero(A_dense[:, u])[0]:
            if w not in seen:
                seen.add(w)
                q.append(w)
    disc = {int(v) for v in range(n) if p[v] >= 0}
    if disc != seen:
        errs.append(f"discovered {len(disc)} != reachable {len(seen)}")
    return errs


@jax.jit
def validate_bfs_device(E, parents, levels):
    """DEVICE-side Graph500 tree validation for chip-scale runs
    (``graph500-1.2 verify.c`` intent; the host ``validate_bfs_tree`` is
    O(n·m) Python and unusable at benchmark scales).

    ``E``: EllParMat adjacency; ``parents``/``levels``: row-aligned
    DistMultiVec int32 [n, W] (levels -1 = undiscovered). Checks, per
    lane, with a handful of bucket-sweep passes (each ~nnz per-slot ops):

      v1  roots: exactly one self-parent vertex at level 0 per lane;
      v2  level step: level[v] == level[parent[v]] + 1 for discovered
          non-root v (and parent discovered);
      v3  tree-edge membership: edge (parent[v], v) exists in the graph;
      v4  edge consistency: no graph edge joins a discovered vertex to an
          undiscovered one, and discovered endpoints' levels differ <= 1
          (levels are true BFS distances & discovery is closed).

    Returns a [4, W] int32 violation-count matrix (all zeros = valid).
    Run AFTER the timed section — its readback poisons later launches.
    """
    from jax.sharding import PartitionSpec as P

    from ..parallel.grid import COL_AXIS, ROW_AXIS
    from ..parallel.spmat import TILE_SPEC

    grid = E.grid
    n = E.nrows
    lr, lc = E.local_rows, E.local_cols
    nb = len(E.buckets)
    lcol = levels.realign("col")

    def body(prow_b, lrow_b, lcol_b, *flat):
        buckets = [
            tuple(a[0, 0] for a in flat[3 * i : 3 * i + 3]) for i in range(nb)
        ]
        prow, lrow = prow_b[0], lrow_b[0]  # [lr, W]
        lc_b = lcol_b[0]  # [lc, W]
        W = prow.shape[1]
        i = jax.lax.axis_index(ROW_AXIS)
        j = jax.lax.axis_index(COL_AXIS)
        row_g = jnp.arange(lr, dtype=jnp.int32) + i * lr  # global row ids
        rvalid = row_g < n

        # v1: root accounting (root = self-parent at level 0)
        is_root = (prow == row_g[:, None]) & (lrow == 0) & rvalid[:, None]
        nroots = jax.lax.psum(
            jnp.sum(is_root.astype(jnp.int32), axis=0), ROW_AXIS
        )
        v1 = jnp.abs(nroots - 1)

        # full per-lane level table for parent lookups (validation W is
        # small; all_gather of [lc, W] over "c" = the global vector)
        lvl_full = jax.lax.all_gather(lc_b, COL_AXIS).reshape(-1, W)[:n]
        disc = (lrow >= 0) & rvalid[:, None]
        nonroot = disc & ~is_root
        pidx = jnp.clip(prow, 0, n - 1)
        lane = jnp.arange(W, dtype=jnp.int32)[None, :]
        lp = lvl_full[pidx, lane]  # lp[v, w] = level[parent[v, w], w]
        v2 = jax.lax.psum(
            jnp.sum(
                (nonroot & ((lp < 0) | (lrow != lp + 1))).astype(jnp.int32),
                axis=0,
            ),
            ROW_AXIS,
        )

        # v3 + v4: one sweep over the ELL buckets
        lpad = jnp.concatenate(
            [lc_b, jnp.full((1, W), -1, lc_b.dtype)]
        )  # [lc+1, W]
        tree_found = jnp.zeros((lr, W), bool)
        v4 = jnp.zeros((W,), jnp.int32)
        for bc0, _bv0, br0 in buckets:  # the shard-LOCAL tile slices
            rowok = br0 < lr  # padded bucket rows are inert
            slot_ok = (bc0 < lc) & rowok[:, None]  # [nbk, kb]
            colg = jnp.where(slot_ok, bc0 + j * lc, n)
            g = lpad[jnp.minimum(bc0, lc)]  # [nbk, kb, W] neighbor levels
            rl = lrow[jnp.minimum(br0, lr - 1)]  # [nbk, W] row levels
            rd = rl >= 0
            nd = g >= 0
            bad_cross = slot_ok[..., None] & (rd[:, None, :] != nd)
            bad_far = (
                slot_ok[..., None]
                & rd[:, None, :] & nd
                & (jnp.abs(g - rl[:, None, :]) > 1)
            )
            v4 = v4 + jnp.sum(
                (bad_cross | bad_far).astype(jnp.int32), axis=(0, 1)
            )
            pv = prow[jnp.minimum(br0, lr - 1)]  # [nbk, W] parent ids
            match = slot_ok[..., None] & (colg[..., None] == pv[:, None, :])
            hit = jnp.any(match, axis=1) & rowok[:, None]  # [nbk, W]
            tree_found = tree_found.at[jnp.minimum(br0, lr - 1)].max(hit)
        # a row's full adjacency may span several grid columns
        tree_found = jax.lax.pmax(tree_found, COL_AXIS)
        v4 = jax.lax.psum(jax.lax.psum(v4, COL_AXIS), ROW_AXIS)
        v3 = jax.lax.psum(
            jnp.sum((nonroot & ~tree_found).astype(jnp.int32), axis=0),
            ROW_AXIS,
        )
        return jnp.stack([v1, v2, v3, v4])[None]

    flat_args = [a for b in E.buckets for a in b]
    out = jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(P(ROW_AXIS), P(ROW_AXIS), P(COL_AXIS))
        + (TILE_SPEC,) * (3 * nb),
        out_specs=P(None),
        check_vma=False,
    )(
        parents.realign("row").blocks, levels.realign("row").blocks,
        lcol.blocks, *flat_args,
    )
    return out[0]


def bfs_batch(
    A,
    sources,
    max_iters: int | None = None,
    sr: "Semiring" = SELECT2ND_MAX,
    track_levels: bool = True,
):
    """Eager wrapper over ``_bfs_batch_impl`` (plain-outputs law: a
    dataclass-wrapped jit output tripled the batch child's wall time in
    the r5 A/B — 90.8 vs 281.7 MTEPS; see probe_seq_r5 wa/wc)."""
    from ..parallel.vec import DistMultiVec

    p, l, niter = _bfs_batch_impl(
        A, sources, max_iters=max_iters, sr=sr,
        track_levels=track_levels,
    )
    mk = lambda b: DistMultiVec(
        blocks=b, length=A.nrows, align="row", grid=A.grid
    )
    return mk(p), mk(l), niter


@partial(jax.jit, static_argnames=("max_iters", "sr", "track_levels"))
def _bfs_batch_impl(
    A,
    sources,
    max_iters: int | None = None,
    sr: "Semiring" = SELECT2ND_MAX,
    track_levels: bool = True,
):
    """Multi-source batched BFS: W independent BFS trees in ONE program.

    Graph500 runs 64 search keys (the reference loops them host-side,
    ``TopDownBFS.cpp:437-444``); on TPU the whole batch advances together as
    a [n, W] frontier matrix — SURVEY §2.3 strategy 7 (BetwCent's
    frontier-as-matrix) applied to BFS itself. Two wins, both measured on
    v5e: (a) gathers are per-index bound, so W parent lanes ride one index
    fetch ~free; (b) the whole batch is one launch — one fixed ~100ms
    dispatch instead of W of them.

    ``sources``: int32 [W]. Returns (parents [pr, lr, W] int32 blocks,
    levels blocks, num_iters) — PLAIN ARRAYS (the eager wrapper above
    rebuilds the DistMultiVecs); num_iters is the MAX level over the
    batch (lanes that finish early idle through the remaining levels with
    no semantic effect; dense-regime level cost is frontier-independent).
    ``track_levels=False`` drops the level array from the loop carry,
    saving one [n, W] int32 buffer (it raised the feasible batch width
    from 256 toward 384 at scale 20 — W=512 still exceeds this chip's
    16G HBM; see benchmarks/results/bench_sweep_r2c.txt). Levels are then
    returned as a discovery indicator (0 discovered / -1 not).
    """
    from ..parallel.vec import DistMultiVec
    from ..parallel.ellmat import EllParMat, dist_spmv_ell_masked_multi

    grid = A.grid
    n = A.nrows
    pr_, lr = grid.pr, grid.local_rows(n)
    pc_, lc = grid.pc, grid.local_cols(A.ncols)
    W = sources.shape[0]
    iters = max_iters if max_iters is not None else n

    row_gids = _global_ids(grid, pr_, lr, n, "row")  # [pr, lr]
    col_gids = _global_ids(grid, pc_, lc, A.ncols, "col")

    src = sources.astype(jnp.int32)[None, None, :]  # [1, 1, W]
    # PAD_ROOT lanes (the serve batcher's lane padding) are inert: the
    # live guard keeps a pad source from matching the -1 padding slots
    # of the gid tables, so a pad lane starts (and stays) empty.
    live = src != PAD_ROOT
    is_src = (row_gids[:, :, None] == src) & live
    parents0 = jnp.where(is_src, src, jnp.int32(-1))  # [pr, lr, W]
    levels0 = (
        jnp.where(is_src, 0, -1).astype(jnp.int32)
        if track_levels
        else jnp.zeros((1, 1, 1), jnp.int32)  # placeholder carry
    )
    x0 = jnp.where(
        (col_gids[:, :, None] == src) & live, src, jnp.int32(-1)
    )

    def mk(b, align):
        return DistMultiVec(blocks=b, length=n, align=align, grid=grid)

    def cond(state):
        _, _, _, level, active = state
        return active & (level < iters)

    def step(state):
        parents, levels, x, level, _ = state
        unvisited = mk(parents < 0, "row")
        y = dist_spmv_ell_masked_multi(sr, A, mk(x, "col"), unvisited)
        new = (y.blocks >= 0) & (parents < 0) & (row_gids[:, :, None] >= 0)
        parents = jnp.where(new, y.blocks, parents)
        if track_levels:
            levels = jnp.where(new, level + 1, levels)
        x_next = mk(
            jnp.where(new, row_gids[:, :, None], -1), "row"
        ).realign("col").blocks
        active = jnp.any(new)
        return parents, levels, x_next, level + 1, active

    parents, levels, _, niter, _ = jax.lax.while_loop(
        cond, step, (parents0, levels0, x0, jnp.int32(0), jnp.bool_(True))
    )
    if not track_levels:
        # levels were not tracked: return discovery indicator (0 for the
        # sources / discovered? -1 undiscovered) — parents' sign carries it.
        levels = jnp.where(parents >= 0, 0, -1)
    return parents, levels, niter


@lru_cache(maxsize=16)
def _gid_blocks(grid, nblocks: int, block_len: int, length: int,
                align: str):
    """Materialized global-id blocks (``_global_ids`` as a DEVICE BUFFER,
    built host-side and uploaded once per (grid, shape)).

    BOUNDED cache (ADVICE r5): each entry pins an HBM buffer for its
    (grid, shape, align); unbounded, a long-lived process sweeping many
    shapes (the pytest session) would accumulate pinned device memory
    forever. 16 entries cover any realistic working set (the bench
    children are single-shape); eviction just re-uploads. Growth is
    visible through the ``cache.bfs.*`` gauges (``obs`` registry) and
    ``clear_bfs_caches()`` is the explicit release hook.

    Why not jnp.arange inside the jitted program: on the target backend
    an iota-derived gid table fuses into the while-loop body as a
    per-iteration rematerialization that executes SERIALLY — the
    otherwise-identical single-root BFS program measured 39.5 s with the
    in-program iota vs 1.7 s with the table passed as an operand
    (benchmarks/probe_seq_r5.py, modes v9 vs v7)."""
    import numpy as np

    g = np.arange(nblocks * block_len, dtype=np.int32).reshape(
        nblocks, block_len
    )
    g = np.where(g < length, g, -1)
    if grid.size == 1:
        # UNSHARDED on purpose: a NamedSharding'd vector operand makes
        # the whole compiled program execute ~25x slower on the target
        # backend (probe_seq_r5 w3 47.3 s vs v7 1.7 s — same loop, only
        # the gid operands' sharding differs)
        return jax.device_put(jnp.asarray(g))
    sh = (
        grid.row_aligned_sharding() if align == "row"
        else grid.col_aligned_sharding()
    )
    return jax.device_put(jnp.asarray(g), sh)


#: Global degree-class ladder shared by every bfs_single tier: class c
#: holds vertices with degree in (LADDER[c-1], LADDER[c]]; degrees past
#: the last rung only ever run the dense sweep.
BFS_CLASS_LADDER = (8, 64, 512, 4096, 32768, 131072)


@lru_cache(maxsize=8)
def _iota_operand(kmax: int):
    """[kmax] iota as a materialized device buffer — in-program iotas
    serialize inside while-loop fusions on the target backend (the v9
    pathology, see _gid_blocks). Bounded like ``_gid_blocks``."""
    import numpy as np

    return jax.device_put(jnp.asarray(np.arange(kmax, dtype=np.int32)))


def clear_bfs_caches() -> None:
    """Explicit release hook for every BFS-side cache: the gid/iota
    DEVICE BUFFERS and the jitted single-root programs that close over
    them (``_bfs_single_program``). Frees the pinned HBM; the next call
    rebuilds (ADVICE r5)."""
    _gid_blocks.cache_clear()
    _iota_operand.cache_clear()
    _bfs_single_program.cache_clear()


def _record_bfs_cache_stats() -> None:
    """obs provider: lru_cache hit/miss/size gauges, polled at export
    time so cache growth is visible without a counter on every access."""
    for label, fn in (
        ("gid_blocks", _gid_blocks),
        ("iota_operand", _iota_operand),
        ("single_program", _bfs_single_program),
    ):
        ci = fn.cache_info()
        obs.gauge(f"cache.bfs.{label}.hits", ci.hits)
        obs.gauge(f"cache.bfs.{label}.misses", ci.misses)
        obs.gauge(f"cache.bfs.{label}.size", ci.currsize)
        obs.gauge(f"cache.bfs.{label}.maxsize", ci.maxsize)


obs.register_provider(_record_bfs_cache_stats)


def bfs_single(E, source, csc, *, tiers, csr=None, coldeg=None,
               rowdeg=None, max_iters: int | None = None):
    """Frontier/undiscovered-proportional single-root BFS — see
    ``_bfs_single_program`` for the design. This wrapper resolves the
    cached program for (grid, shape, tiers) and fills test-path
    fallbacks: ``csr`` (per-tile row-major companion,
    ``ellmat.build_csr_companion`` — required for "bu" tiers),
    ``coldeg``/``rowdeg`` (global degree vectors as [pc, lc] / [pr, lr]
    blocks; pass precomputed blocks on the real chip).

    Returns (parents DistVec i32, levels DistVec i32, num_iters).
    """
    from ..semiring import PLUS_TIMES
    from ..parallel.spmat import ones_i32

    grid = E.grid
    if any(kind == "bu" for kind, _ in tiers) and csr is None:
        raise ValueError(
            "bu tiers need the row-major companion: "
            "csr=build_csr_companion(grid, rows, cols, nrows, ncols)"
        )
    if rowdeg is None:
        rowdeg = E.reduce(PLUS_TIMES, "cols", map_fn=ones_i32).blocks
    if coldeg is None:
        # test fallback; chip callers pass host-built blocks (the CSC
        # indptr derivation is the probe-v6 megascale-1-D pathology)
        rd = DistVec(
            blocks=rowdeg, length=E.nrows, align="row", grid=grid
        )
        coldeg = rd.realign("col").blocks
    if csr is None:
        csr = csc  # placeholder operand; no "bu" tier traces it
    run = _bfs_single_program(
        grid, E.nrows, E.ncols, len(E.buckets), tiers, max_iters
    )
    flat = [a for b in E.buckets for a in b]
    parents, levels, niter = run(
        jnp.int32(source), csc[0], csc[1], csr[0], csr[1], coldeg,
        rowdeg, *flat,
    )
    mk = lambda b: DistVec(blocks=b, length=E.nrows, align="row",
                           grid=grid)
    return mk(parents), mk(levels), niter


#: Default sequential-root tier ladder for Graph500-class graphs at
#: scale ~20 (sized from the measured level anatomy in
#: benchmarks/results/r5): a small top-down tier for the pre-peak
#: levels, two bottom-up tiers for the post-peak levels, dense for the
#: peak step. bench.py and the probes share this constant.
DEFAULT_SEQ_TIERS = (
    "td:1024,1024,512,128,16,2"
    "|bu:524288,16384,1024,0,0,0"
    "|bu:1048576,32768,2048,128,0,0"
)


def parse_tier_spec(spec: str):
    """``"td:1024,1024,512,128,16,2|bu:524288,16384,1024,0,0,0"`` →
    bfs_single tier tuple. Empty string → () (always-dense)."""
    tiers = []
    for part in spec.split("|"):
        if not part:
            continue
        kind, _, budg = part.partition(":")
        budgets = tuple(int(v) for v in budg.split(","))
        if kind not in ("td", "bu") or len(budgets) != len(
            BFS_CLASS_LADDER
        ):
            raise ValueError(
                f"bad tier spec {part!r}: want kind td|bu and "
                f"{len(BFS_CLASS_LADDER)} budgets"
            )
        tiers.append((kind, budgets))
    return tuple(tiers)


@lru_cache(maxsize=32)
def _bfs_single_program(grid, nrows, ncols, nbuckets, tiers,
                        max_iters: int | None = None):
    """Single-root BFS whose per-level cost follows the DIRECTION-OPTIMIZED
    work profile, not nnz — the Graph500 spec's SEQUENTIAL kernel 2
    (``TopDownBFS.cpp:437-479``; work ∝ frontier is the reference's
    top-down property, ``BFSFriends.h:59-182``; the bottom-up regime is
    Beamer's, ``DirOptBFS.cpp:374-424``).

    Measured scale-20 R-MAT level anatomy (benchmarks/results/r5, host
    profile): one step is heavy (expanding L2: 6-26M frontier edges —
    the dense sweep's regime), the steps before it have TINY frontiers
    (≤350K edges), and from L3 on the UNDISCOVERED side collapses
    (31K-445K edges among undiscovered rows). So each level picks, on
    device, the first fitting strategy from ``tiers``:

      ("td", budgets) — top-down class-bucketed CSC column walk: active
        columns are degree-classed on ``BFS_CLASS_LADDER``, compacted by
        ONE top_k (sort), and each class c walks at most budgets[c]
        columns with a [F_c, K_c] static gather; parents scatter-max
        into rows. Work ∝ Σ F_c·K_c (~1.5M slots for the default small
        tier).
      ("bu", budgets) — bottom-up class-bucketed CSR row walk: same
        machinery over UNDISCOVERED rows; each row folds its in-edge
        candidates with a gather (NO edge-sized scatter — the r1 lesson
        that built EllParMat), then one [ΣF_c]-sized row scatter.
      else — the dense ELL gather sweep (cost ~nnz slots, 0.3 s at
        scale 20).

    Budget semantics: class c may hold at most budgets[c] active
    vertices (0 = none allowed); any vertex past the ladder's last rung
    forces the next strategy. Conditions are 7 masked reductions per
    side per level, computed once.

    TPU-pathology notes baked into this design (probe_seq_r5):
    in-program iota/cumsum/1-D megascatter serialize on this backend
    (1.6-1.9 s per 1M elements; 39.5 s-vs-1.7 s for the v9/v7 program
    pair), so compaction is top_k (sort, ~50 ms/M), iota and gid tables
    are passed as materialized operands, and all index math is gathers.

    W=1 also kills the batch kernels' two other single-root taxes: the
    gather payload is a SCALAR (no 128-lane padding waste), and parents
    ride the gathers directly as int32 candidates (no reconstruction
    pass) — the frontier value of column c is c's global id, exactly
    the reference's SelectMax parent semantics (Semirings.h:166).

    Whole traversal is ONE launch (lax.while_loop + lax.switch; zero
    host readbacks).

    CLOSURE-CONSTANT LAW (this backend, measured): the gid/iota tables
    must be CLOSED OVER by the jitted program, not passed as arguments —
    the identical loop runs 1.65 s with them as closure constants and
    27.3 s as parameters (probe_seq_r5 w4 vs w7; in-program jnp.arange
    is 39.5 s, v9). Hence this factory: one cached jitted program per
    (grid, shape, tiers), taking only the per-graph arrays as arguments.

    Returns ``run(source, csc_indptr, csc_rowidx, csr_indptr,
    csr_colidx, coldeg, rowdeg, *flat_bucket_arrays) -> (parents,
    levels, niter)`` over plain [pr, lr] block arrays.
    """
    import functools

    from jax.sharding import PartitionSpec as P

    from ..parallel.grid import COL_AXIS, ROW_AXIS
    from ..parallel.spmat import TILE_SPEC

    n = nrows
    lr = grid.local_rows(n)
    lc = grid.local_cols(ncols)
    nb = nbuckets
    iters = max_iters if max_iters is not None else n
    LADDER = BFS_CLASS_LADDER
    NC = len(LADDER)
    assert lc <= 1 << 21 and lr <= 1 << 21, "class sort packs ids in 21 bits"
    row_gids = _gid_blocks(grid, grid.pr, lr, n, "row")
    col_gids = _gid_blocks(grid, grid.pc, lc, ncols, "col")
    iota_k = _iota_operand(LADDER[-1])

    @jax.jit
    def run(source, csc_indptr, csc_rowidx, csr_indptr, csr_colidx,
            coldeg, rowdeg, *flat_args):
        parents0 = jnp.where(row_gids == source, jnp.int32(source), -1)
        levels0 = jnp.where(row_gids == source, 0, -1).astype(jnp.int32)
        # frontier: col-aligned int32 parent candidates (vertex's own
        # global id when in the frontier, -1 inactive)
        x0 = jnp.where(col_gids == source, jnp.int32(source), -1)

        def classify(d):
            """Degree → ladder class (0..NC-1; NC = beyond the ladder)."""
            c = jnp.zeros_like(d)
            for K in LADDER:
                c = c + (d > K).astype(d.dtype)
            return c

        def class_counts(mask, degblocks):
            """[NC+1] active-vertex count per class (last = beyond ladder)."""
            d = jnp.where(mask, degblocks, -1)
            lo = -1
            cnts = []
            for K in LADDER:
                cnts.append(jnp.sum(((d > lo) & (d <= K)).astype(jnp.int32)))
                lo = K
            cnts.append(jnp.sum((d > LADDER[-1]).astype(jnp.int32)))
            return cnts

        def dense_level(x, undisc):
            """Dense ELL gather sweep (the heavy-step regime): one
            scalar-payload gather over every ELL slot, parents carried."""

            def body(xblk, ublk, *flat):
                buckets = [
                    tuple(a[0, 0] for a in flat[3 * i : 3 * i + 3])
                    for i in range(nb)
                ]
                xv = xblk[0]  # [lc] i32 candidates
                xpad = jnp.concatenate([xv, jnp.full((1,), -1, jnp.int32)])
                y = jnp.full((lr,), -1, jnp.int32)
                for bc, _bv, br in buckets:
                    g = xpad[jnp.minimum(bc, lc)]  # [nb_, kb] i32
                    yb = jnp.max(g, axis=1)
                    y = y.at[br].max(yb, mode="drop")
                y = jnp.where(ublk[0], y, -1)
                return jax.lax.pmax(y, COL_AXIS)[None]

            return jax.shard_map(
                body, mesh=grid.mesh,
                in_specs=(P(COL_AXIS), P(ROW_AXIS)) + (TILE_SPEC,) * (3 * nb),
                out_specs=P(ROW_AXIS),
                check_vma=False,
            )(x, undisc, *flat_args)

        def _classed_walk(kind, budgets):
            """Shared class-bucketed walk for both directions.

            td: compact ACTIVE COLUMNS, walk their CSC ranges, scatter-max
            parent candidates into rows ([F_c, K_c] edge scatter).
            bu: compact UNDISCOVERED ROWS, walk their CSR ranges, fold each
            row's neighbor candidates by gather-max, one [F_c] row scatter.
            """
            # cap per-class budgets at the block length: oversized
            # static budgets (tuned for scale 20) would make small-graph
            # walks gather more slots than the whole matrix
            L_cap = lc if kind == "td" else lr
            budgets = tuple(min(b, L_cap) for b in budgets)
            FT = sum(b for b in budgets if b > 0)

            def body(ipt, vidx, iota, xblk, ublk, cdgb, rdgb, gidb):
                indptr = ipt[0, 0]
                vid = vidx[0, 0]  # csc: rowidx / csr: colidx
                xv = xblk[0]  # [lc] i32 frontier candidates
                ub = ublk[0]  # [lr] bool undiscovered
                xpad = jnp.concatenate([xv, jnp.full((1,), -1, jnp.int32)])
                ipt_pad = jnp.concatenate([indptr, indptr[-1:]])
                if kind == "td":
                    L, gdeg, gid = lc, cdgb[0], gidb[0]
                    active = xv >= 0
                    ax = COL_AXIS
                else:
                    L, gdeg, gid = lr, rdgb[0], gidb[0]
                    active = ub & (gid >= 0)
                    ax = ROW_AXIS
                j = jax.lax.axis_index(ax)
                lid = gid - j * L  # local index within this block
                dcls = classify(gdeg)
                key = jnp.where(
                    active & (dcls < NC),
                    ((NC - dcls) << 21) | lid,
                    -1,
                )
                k = min(FT, L)
                topv, _ = jax.lax.top_k(key, k)  # class-asc, id-desc blocks
                ids = jnp.where(topv >= 0, topv & 0x1FFFFF, L)
                if k < FT:
                    ids = jnp.pad(ids, (0, FT - k), constant_values=L)
                # per-class starts (tiny scalar chain, not a prefix op)
                d_act = jnp.where(active, gdeg, -1)
                lo = -1
                starts, start = [], jnp.int32(0)
                for K in LADDER:
                    starts.append(start)
                    start = start + jnp.sum(
                        ((d_act > lo) & (d_act <= K)).astype(jnp.int32)
                    )
                    lo = K
                cap = vid.shape[0]
                gdeg_pad = jnp.concatenate([gdeg, jnp.zeros((1,), gdeg.dtype)])
                y = jnp.full((lr,), -1, jnp.int32)
                lo = -1
                for c, K in enumerate(LADDER):
                    F = budgets[c]
                    if F <= 0:
                        lo = K
                        continue
                    sl = jax.lax.dynamic_slice(ids, (starts[c],), (F,))
                    safe = jnp.minimum(sl, L)
                    gd = gdeg_pad[safe]
                    # class membership re-check excludes clamp/pad strays
                    okc = (sl < L) & (gd > lo) & (gd <= K)
                    st = ipt_pad[safe]
                    ldeg = ipt_pad[jnp.minimum(sl + 1, L)] - st
                    ik = iota[:K][None, :]  # static slice of the operand
                    valid = okc[:, None] & (ik < ldeg[:, None])
                    slot = jnp.where(valid, st[:, None] + ik, cap - 1)
                    other = jnp.where(valid, vid[slot], lc)
                    if kind == "td":
                        # scatter parent candidates into target rows
                        tgt = jnp.where(valid, other, lr)
                        contrib = jnp.where(
                            valid, xpad[jnp.minimum(safe, lc)][:, None], -1
                        )
                        y = y.at[tgt].max(contrib, mode="drop")
                    else:
                        # fold neighbor candidates per row, tiny row scatter
                        g = jnp.where(
                            valid, xpad[jnp.minimum(other, lc)], -1
                        )
                        yb = jnp.max(g, axis=1)  # [F]
                        y = y.at[jnp.where(okc, sl, lr)].max(
                            yb, mode="drop"
                        )
                    lo = K
                y = jnp.where(ub, y, -1)
                return jax.lax.pmax(y, COL_AXIS)[None]

            ipt, vidx = (csc_indptr, csc_rowidx) if kind == "td" else (
                csr_indptr, csr_colidx
            )
            gidb = col_gids if kind == "td" else row_gids
            gid_spec = P(COL_AXIS) if kind == "td" else P(ROW_AXIS)

            def run(x, undisc):
                return jax.shard_map(
                    body, mesh=grid.mesh,
                    in_specs=(TILE_SPEC, TILE_SPEC, P(), P(COL_AXIS),
                              P(ROW_AXIS), P(COL_AXIS), P(ROW_AXIS),
                              gid_spec),
                    out_specs=P(ROW_AXIS),
                    check_vma=False,
                )(ipt, vidx, iota_k, x, undisc, coldeg, rowdeg, gidb)

            return run

        branches = [
            _classed_walk(kind, budgets) for kind, budgets in tiers
        ] + [dense_level]

        def cond(state):
            _, _, _, level, active = state
            return active & (level < iters)

        def step(state):
            parents, levels, x, level, _ = state
            undisc = parents < 0
            if tiers:
                fc = class_counts(x >= 0, coldeg)
                uc = class_counts(undisc & (row_gids >= 0), rowdeg)
                sel = jnp.int32(len(tiers))
                for t in reversed(range(len(tiers))):
                    kind, budgets = tiers[t]
                    cnts = fc if kind == "td" else uc
                    ok = cnts[NC] == 0
                    for c in range(NC):
                        ok = ok & (cnts[c] <= budgets[c])
                    sel = jnp.where(ok, jnp.int32(t), sel)
                y = jax.lax.switch(sel, branches, x, undisc)
            else:
                y = dense_level(x, undisc)  # tiers=(): always-dense path
            new = (y >= 0) & undisc & (row_gids >= 0)
            parents = jnp.where(new, y, parents)
            levels = jnp.where(new, level + 1, levels)
            frontier_row = DistVec(
                blocks=jnp.where(new, row_gids, -1), length=n, align="row",
                grid=grid,
            )
            x_next = frontier_row.realign("col").blocks
            return parents, levels, x_next, level + 1, jnp.any(new)

        parents, levels, _, niter, _ = jax.lax.while_loop(
            cond, step, (parents0, levels0, x0, jnp.int32(0),
                         jnp.bool_(True))
        )
        # PLAIN ARRAYS out: DistVec-wrapping inside the jit executes
        # ~60x slower on this backend (probe wa 1.6 s vs wc 110 s)
        return parents, levels, niter

    return run


@jax.jit
def single_traversed_edges(deg_row_blocks, parents: DistVec) -> jax.Array:
    """Kernel-2 edge count for one root, on device (uint32-safe like
    ``batch_traversed_edges``): sum of degrees over discovered / 2."""
    disc = parents.blocks >= 0  # [pr, lr]
    te = jnp.sum(
        jnp.where(disc, deg_row_blocks, 0).astype(jnp.uint32)
    )
    return (te // 2).astype(jnp.int32)


@jax.jit
def batch_traversed_edges(deg_row_blocks, parents) -> jax.Array:
    """Graph500 kernel-2 edge count per root, ON DEVICE: [W] int array of
    (sum of degrees over discovered vertices) / 2 — so the benchmark's only
    D2H readback is one tiny vector AFTER the timed batch.

    ``deg_row_blocks``: [pr, lr] structural out-degrees (row-aligned,
    padding 0); ``parents``: the DistMultiVec from ``bfs_batch``.
    """
    disc = parents.blocks >= 0  # [pr, lr, W]
    # uint32 accumulation: a giant component's per-root degree sum can reach
    # the full symmetrized endpoint count ~2^(scale+5) at edgefactor 16,
    # which crosses 2^31 near scale 26 — uint32 extends the safe range to
    # scale ~27 (the [W] output is tiny, so width costs nothing).
    te = jnp.sum(
        jnp.where(disc, deg_row_blocks[:, :, None], 0).astype(jnp.uint32),
        axis=(0, 1),
    )
    return (te // 2).astype(jnp.int32)


def bfs_batch_compact(A, sources, max_iters: int | None = None,
                      ring: bool = False, csc=None,
                      frontier_capacity: int | None = None,
                      edge_capacity: int | None = None):
    """Eager wrapper: the jitted program returns plain block arrays (the
    plain-outputs law — DistVec/DistMultiVec dataclass wrapping inside
    jit measured 60x slower on the target backend, probe_seq_r5 wa/wc);
    this wrapper rebuilds the DistMultiVecs outside."""
    from ..parallel.vec import DistMultiVec

    p, l, niter = _bfs_batch_compact_impl(
        A, sources, max_iters=max_iters, ring=ring, csc=csc,
        frontier_capacity=frontier_capacity, edge_capacity=edge_capacity,
    )
    mk = lambda b: DistMultiVec(
        blocks=b, length=A.nrows, align="row", grid=A.grid
    )
    return mk(p), mk(l), niter


@partial(
    jax.jit,
    static_argnames=("max_iters", "ring", "frontier_capacity",
                     "edge_capacity"),
)
def _bfs_batch_compact_impl(A, sources, max_iters: int | None = None,
                            ring: bool = False, csc=None,
                            frontier_capacity: int | None = None,
                            edge_capacity: int | None = None):
    """Level-compressed multi-source BFS: int8 frontiers, parents
    reconstructed in ONE pass after the search.

    ``bfs_batch`` carries int32 parent candidates through every gather —
    4W bytes of payload per gathered index. This variant carries only a
    one-byte level indicator per root (W bytes/index): the search loop
    discovers LEVELS, and parents come from a single final sweep picking,
    per (vertex, root), the max-id in-neighbor at level-1 (any valid
    Graph500 tree; the reference's SelectMax tie-break). On
    payload-width-sensitive gather hardware this cuts dense-level cost
    ~3-4x at W=256 and halves the memory footprint (int8 state).

    Level range: int8 caps at 126 levels — far beyond any Graph500 R-MAT
    diameter; ``max_iters`` defaults to that cap.

    ``ring=True`` folds each level's partials with the explicit
    ppermute carousel schedule (``collectives.axis_ring_reduce`` — the
    BitMapCarousel analog, neighbor-only ICI traffic) instead of the
    fused all-reduce; results are identical.

    Direction optimization for the batch: pass ``csc`` (the
    ``ellmat.build_csc_companion`` arrays) plus static ``frontier_capacity``
    / ``edge_capacity`` budgets, and each level checks ON DEVICE whether
    the UNION of all W frontiers fits the budgets — if so it walks only
    those columns' edges (cost ∝ budgets) instead of the full dense sweep
    (cost ∝ nnz). First levels and the straggler tail of a 256-root batch
    are exactly this regime. ``lax.cond`` keeps both kernels compiled
    once; zero host readbacks.

    Returns (parents int32 blocks, levels int8 blocks, num_iters) —
    PLAIN ARRAYS (the eager wrapper above rebuilds the DistMultiVecs) —
    with the same conventions as ``bfs_batch``.
    """
    from ..parallel.ellmat import (
        EllParMat,
        _ell_levels_step,
        _ell_parents_from_levels,
        _ell_union_sparse_step,
    )
    from ..parallel.vec import DistMultiVec
    from ..parallel.grid import COL_AXIS, ROW_AXIS
    from jax.sharding import PartitionSpec as P

    grid = A.grid
    n = A.nrows
    pr_, lr = grid.pr, grid.local_rows(n)
    pc_, lc = grid.pc, grid.local_cols(A.ncols)
    W = sources.shape[0]
    if max_iters is not None and max_iters > 126:
        raise ValueError(
            f"bfs_batch_compact stores levels as int8 (max depth 126); "
            f"max_iters={max_iters} cannot be honored — use bfs_batch for "
            "graphs with eccentricity beyond 126"
        )
    iters = max_iters if max_iters is not None else 126

    row_gids = _global_ids(grid, pr_, lr, n, "row")
    col_gids = _global_ids(grid, pc_, lc, A.ncols, "col")
    src = sources.astype(jnp.int32)[None, None, :]
    # PAD_ROOT lanes stay empty (see _bfs_batch_impl's live guard)
    live = src != PAD_ROOT

    levels0 = jnp.where(
        (row_gids[:, :, None] == src) & live, 0, -1
    ).astype(jnp.int8)  # [pr, lr, W]
    x0 = ((col_gids[:, :, None] == src) & live).astype(
        jnp.int8
    )  # [pc, lc, W]

    def mk(b, align):
        return DistMultiVec(blocks=b, length=n, align=align, grid=grid)

    diropt = (
        csc is not None
        and frontier_capacity is not None
        and edge_capacity is not None
    )
    if diropt:
        csc_indptr, csc_rowidx = csc

        def colde_body(ipt):
            d = ipt[0, 0][1:] - ipt[0, 0][:-1]
            return jax.lax.psum(d, ROW_AXIS)[None]

        coldeg = jax.shard_map(
            colde_body,
            mesh=grid.mesh,
            in_specs=(P(ROW_AXIS, COL_AXIS),),
            out_specs=P(COL_AXIS),
            check_vma=False,
        )(csc_indptr)  # [pc, lc] per-column degrees

    def cond(state):
        _, _, level, active = state
        return active & (level < iters)

    def step(state):
        levels, x, level, _ = state
        undisc = (levels < 0).astype(jnp.int8)
        if diropt:
            act = jnp.max(x, axis=2) > 0  # [pc, lc] union frontier
            cnt = jnp.sum(act.astype(jnp.int32))
            edges = jnp.sum(jnp.where(act, coldeg, 0))
            use_sparse = (cnt <= frontier_capacity) & (
                edges <= edge_capacity
            )
            reached = jax.lax.cond(
                use_sparse,
                lambda a: _ell_union_sparse_step(
                    A, csc_indptr, csc_rowidx, a[0], a[1],
                    frontier_capacity, edge_capacity,
                ),
                lambda a: _ell_levels_step(A, a[0], a[1], ring=ring),
                (x, undisc),
            )
        else:
            reached = _ell_levels_step(A, x, undisc, ring=ring)
        new = reached > 0
        levels = jnp.where(new, (level + 1).astype(jnp.int8), levels)
        x_next = mk(reached, "row").realign("col").blocks
        return levels, x_next, level + 1, jnp.any(new)

    levels, _, niter, _ = jax.lax.while_loop(
        cond, step, (levels0, x0, jnp.int8(0), jnp.bool_(True))
    )

    levels_col = mk(levels, "row").realign("col").blocks
    parents = _ell_parents_from_levels(A, levels_col, levels)
    # roots are their own parents; undiscovered stay -1
    parents = jnp.where(
        (row_gids[:, :, None] == src) & live, src, parents
    )
    parents = jnp.where(
        (levels < 0) | (row_gids[:, :, None] < 0), -1, parents
    )
    # plain arrays out (see the eager wrapper above)
    return parents, levels, niter.astype(jnp.int32)
