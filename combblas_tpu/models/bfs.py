"""Graph500 top-down BFS (≈ Applications/TopDownBFS.cpp).

The reference iterates ``fringe = SpMV(A, fringe, optbuf)`` with a
select-max semiring, prunes discovered vertices with ``EWiseMult``, and sets
parents (``TopDownBFS.cpp:437-444``; semiring ``SelectMaxSRing``
Semirings.h:166).  The frontier there is a ``FullyDistSpVec`` because on CPU
clusters touching only active vertices is the whole game.

On TPU the frontier is a *dense* distributed vector of parent candidates
(-1 = inactive): every step is one masked semiring SpMV + elementwise
updates, with zero dynamic shapes — the compiled program is identical every
iteration, which is what XLA wants.  This is the same observation that makes
the reference's *bottom-up* phase (``BFSFriends.h:457-560``) dense: we simply
run the dense formulation in both regimes.  TEPS is unchanged: inactive
lanes carry the additive identity through the same ALU ops the active lanes
use.

The sparse-frontier SpMSpV path still exists (``parallel/spmv.py`` +
``ops/spmv.spmspv``) for API parity and for workloads with tiny frontiers.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..semiring import PLUS_TIMES, SELECT2ND_MAX
from ..parallel.spmat import SpParMat, ones_i32
from ..parallel.spmv import dist_spmspv_masked, dist_spmv_masked
from ..parallel.vec import DistVec


def _global_ids(grid, nblocks, block_len, length, align):
    gids = jnp.arange(nblocks * block_len, dtype=jnp.int32).reshape(
        nblocks, block_len
    )
    return jnp.where(gids < length, gids, -1)


@partial(jax.jit, static_argnames=("max_iters", "sr"))
def bfs(
    A: SpParMat,
    source,
    max_iters: int | None = None,
    sr: "Semiring" = SELECT2ND_MAX,
):
    """Level-synchronous BFS from ``source`` over a select-style semiring
    (default SELECT2ND_MAX — structural; pass a value-aware semiring like
    ``semantic.FILTERED_SELECT2ND_MAX`` for on-the-fly edge filtering).

    A is interpreted as: entry (i, j) ≠ 0 means edge j → i (gather from
    in-neighbors, matching the reference's SpMV orientation). Symmetrize for
    undirected graphs.

    Returns (parents, levels, num_iters): row-aligned DistVecs of int32;
    undiscovered vertices hold -1.
    """
    grid = A.grid
    n = A.nrows
    pr_, lr = grid.pr, grid.local_rows(n)
    pc_, lc = grid.pc, grid.local_cols(A.ncols)
    iters = max_iters if max_iters is not None else n

    row_gids = _global_ids(grid, pr_, lr, n, "row")
    col_gids = _global_ids(grid, pc_, lc, A.ncols, "col")

    parents0 = jnp.where(row_gids == source, source, -1).astype(jnp.int32)
    levels0 = jnp.where(row_gids == source, 0, -1).astype(jnp.int32)
    x0 = jnp.where(col_gids == source, source, -1).astype(jnp.int32)

    def mk_row(blocks):
        return DistVec(blocks=blocks, length=n, align="row", grid=grid)

    def mk_col(blocks):
        return DistVec(blocks=blocks, length=A.ncols, align="col", grid=grid)

    def cond(state):
        _, _, x, level, active = state
        return active & (level < iters)

    def step(state):
        parents, levels, x, level, _ = state
        unvisited = mk_row(parents < 0)
        y = dist_spmv_masked(sr, A, mk_col(x), unvisited)
        new = (y.blocks >= 0) & (parents < 0) & (row_gids >= 0)
        parents = jnp.where(new, y.blocks, parents)
        levels = jnp.where(new, level + 1, levels)
        frontier_row = mk_row(jnp.where(new, row_gids, -1))
        x_next = frontier_row.realign("col").blocks
        active = jnp.any(new)
        return parents, levels, x_next, level + 1, active

    parents, levels, _, niter, _ = jax.lax.while_loop(
        cond, step, (parents0, levels0, x0, jnp.int32(0), jnp.bool_(True))
    )
    return mk_row(parents), mk_row(levels), niter


@partial(jax.jit, static_argnames=("frontier_capacity", "exp_capacity"))
def _diropt_topdown_step(
    A, parents, levels, x, row_gids, level, frontier_capacity, exp_capacity
):
    """One sparse-frontier (top-down) level. x is the col-aligned dense
    candidate vector (-1 = inactive)."""
    grid = A.grid
    n = A.nrows
    unvisited = DistVec(
        blocks=parents < 0, length=n, align="row", grid=grid
    )
    xv = DistVec(blocks=x, length=A.ncols, align="col", grid=grid)
    xact = DistVec(blocks=x >= 0, length=A.ncols, align="col", grid=grid)
    y = dist_spmspv_masked(
        SELECT2ND_MAX, A, xv, xact, unvisited,
        frontier_capacity=frontier_capacity, exp_capacity=exp_capacity,
    )
    return _diropt_update(A, parents, levels, y, row_gids, level)


@jax.jit
def _diropt_bottomup_step(A, parents, levels, x, row_gids, level):
    """One dense (bottom-up regime) level: every unvisited vertex probes all
    its neighbors in one masked SpMV — the dense formulation that plays the
    role of the reference's BottomUpStep carousel (``BFSFriends.h:457-560``;
    the ring rotation is XLA's own ICI all-reduce lowering of the fold)."""
    grid = A.grid
    n = A.nrows
    unvisited = DistVec(blocks=parents < 0, length=n, align="row", grid=grid)
    xv = DistVec(blocks=x, length=A.ncols, align="col", grid=grid)
    y = dist_spmv_masked(SELECT2ND_MAX, A, xv, unvisited)
    return _diropt_update(A, parents, levels, y, row_gids, level)


def _diropt_update(A, parents, levels, y, row_gids, level):
    new = (y.blocks >= 0) & (parents < 0) & (row_gids >= 0)
    parents = jnp.where(new, y.blocks, parents)
    levels = jnp.where(new, level + 1, levels)
    frontier_row = DistVec(
        blocks=jnp.where(new, row_gids, -1), length=A.nrows, align="row",
        grid=A.grid,
    )
    x_next = frontier_row.realign("col").blocks
    nnew = jnp.sum(new).astype(jnp.int32)
    return parents, levels, x_next, nnew


@jax.jit
def _frontier_stats(x, deg_blocks):
    """(frontier vertex count, frontier out-edge count) from the col-aligned
    candidate vector.

    The edge count accumulates in float32: int32 would wrap for hub-heavy
    frontiers at Graph500 scale and silently corrupt the regime switch. The
    caller compensates for float32 rounding with a 1% comparison margin.
    """
    act = x >= 0
    cnt = jnp.sum(act)
    edges = jnp.sum(jnp.where(act, deg_blocks, 0).astype(jnp.float32))
    return cnt, edges


@partial(
    jax.jit,
    static_argnames=("frontier_capacity", "exp_capacity", "max_iters"),
)
def bfs_diropt(
    A: SpParMat,
    source,
    *,
    frontier_capacity: int,
    exp_capacity: int,
    max_iters: int | None = None,
):
    """Direction-optimizing BFS (≈ Applications/DirOptBFS.cpp, Beamer),
    fully on device.

    The per-level regime switch is a ``lax.cond`` on frontier statistics
    INSIDE the while_loop — both regimes compile once and zero
    device-to-host readbacks happen during the search (the round-1 host
    switch permanently degraded the chip's launch path via its per-level
    ``int(cnt)`` readbacks; see bench.py's D2H note). Top-down runs the
    budgeted sparse-frontier kernel (work ∝ the static budgets); bottom-up
    runs the dense masked SpMV (work ∝ tile nnz, the regime where the
    reference's carousel operates, ``DirOptBFS.cpp:374-424``).

    The caller chooses the static budgets; the switch takes top-down when
    the frontier fits BOTH budgets with the same 1% float32 margin the
    host version used.

    Returns (parents, levels, num_iters) like ``bfs``.
    """
    grid = A.grid
    n = A.nrows
    pr_, lr = grid.pr, grid.local_rows(n)
    pc_, lc = grid.pc, grid.local_cols(A.ncols)
    iters = max_iters if max_iters is not None else n

    row_gids = _global_ids(grid, pr_, lr, n, "row")
    col_gids = _global_ids(grid, pc_, lc, A.ncols, "col")
    parents0 = jnp.where(row_gids == source, jnp.int32(source), -1)
    levels0 = jnp.where(row_gids == source, 0, -1).astype(jnp.int32)
    x0 = jnp.where(col_gids == source, jnp.int32(source), -1)

    # out-degree per column (structural), for the edge-budget check
    deg = A.reduce(PLUS_TIMES, "rows", map_fn=ones_i32).blocks

    def cond(state):
        _, _, _, level, active = state
        return active & (level < iters)

    def step(state):
        parents, levels, x, level, _ = state
        cnt, edges = _frontier_stats(x, deg)
        use_topdown = (cnt <= frontier_capacity) & (
            edges <= 0.99 * exp_capacity
        )
        parents, levels, x, nnew = jax.lax.cond(
            use_topdown,
            lambda a: _diropt_topdown_step(
                A, a[0], a[1], a[2], row_gids, a[3],
                frontier_capacity, exp_capacity,
            ),
            lambda a: _diropt_bottomup_step(
                A, a[0], a[1], a[2], row_gids, a[3]
            ),
            (parents, levels, x, level),
        )
        return parents, levels, x, level + 1, nnew > 0

    parents, levels, _, niter, _ = jax.lax.while_loop(
        cond, step, (parents0, levels0, x0, jnp.int32(0), jnp.bool_(True))
    )
    mk = lambda b: DistVec(blocks=b, length=n, align="row", grid=grid)
    return mk(parents), mk(levels), niter


def bfs_diropt_auto(A: SpParMat, source, max_iters: int | None = None):
    """``bfs_diropt`` with the round-1 default budget heuristics
    (host-side, static: lc/8 frontier slots, nnz-capacity/8 edge slots)."""
    lc = A.grid.local_cols(A.ncols)
    cap = A.capacity
    fc = min(max(64, lc // 8 + 1), lc)
    ec = min(max(256, cap // 8 + 1), cap)
    return bfs_diropt(
        A, source, frontier_capacity=fc, exp_capacity=ec,
        max_iters=max_iters,
    )


def traversed_edges(A: SpParMat, parents: DistVec) -> jax.Array:
    """Graph500 kernel-2 edge count: edges with a discovered endpoint / 2.

    Matches the TEPS accounting of ``TopDownBFS.cpp:448-465`` for
    symmetrized graphs (each undirected edge stored twice).
    """
    deg = A.reduce(PLUS_TIMES, axis="cols", map_fn=ones_i32)
    disc = parents.realign("row").blocks >= 0
    return jnp.sum(jnp.where(disc, deg.blocks, 0)) // 2


def validate_bfs_tree(A_dense, source, parents, levels) -> list[str]:
    """Host-side BFS tree validation (Graph500 verify.c-style checks).

    Returns a list of violation strings (empty = valid).
    """
    import numpy as np

    A_dense = np.asarray(A_dense)
    p = np.asarray(parents)
    lv = np.asarray(levels)
    n = A_dense.shape[0]
    errs = []
    if p[source] != source or lv[source] != 0:
        errs.append("source not its own parent at level 0")
    for v in range(n):
        if v == source or p[v] < 0:
            continue
        if not A_dense[v, p[v]]:
            errs.append(f"tree edge ({p[v]},{v}) not in graph")
        if lv[v] != lv[p[v]] + 1:
            errs.append(f"level[{v}]={lv[v]} != level[parent]+1={lv[p[v]] + 1}")
    # reachability: discovered set must equal BFS-reachable set
    from collections import deque

    seen = {source}
    q = deque([source])
    while q:
        u = q.popleft()
        for w in np.nonzero(A_dense[:, u])[0]:
            if w not in seen:
                seen.add(w)
                q.append(w)
    disc = {int(v) for v in range(n) if p[v] >= 0}
    if disc != seen:
        errs.append(f"discovered {len(disc)} != reachable {len(seen)}")
    return errs


@jax.jit
def validate_bfs_device(E, parents, levels):
    """DEVICE-side Graph500 tree validation for chip-scale runs
    (``graph500-1.2 verify.c`` intent; the host ``validate_bfs_tree`` is
    O(n·m) Python and unusable at benchmark scales).

    ``E``: EllParMat adjacency; ``parents``/``levels``: row-aligned
    DistMultiVec int32 [n, W] (levels -1 = undiscovered). Checks, per
    lane, with a handful of bucket-sweep passes (each ~nnz per-slot ops):

      v1  roots: exactly one self-parent vertex at level 0 per lane;
      v2  level step: level[v] == level[parent[v]] + 1 for discovered
          non-root v (and parent discovered);
      v3  tree-edge membership: edge (parent[v], v) exists in the graph;
      v4  edge consistency: no graph edge joins a discovered vertex to an
          undiscovered one, and discovered endpoints' levels differ <= 1
          (levels are true BFS distances & discovery is closed).

    Returns a [4, W] int32 violation-count matrix (all zeros = valid).
    Run AFTER the timed section — its readback poisons later launches.
    """
    from jax.sharding import PartitionSpec as P

    from ..parallel.grid import COL_AXIS, ROW_AXIS
    from ..parallel.spmat import TILE_SPEC

    grid = E.grid
    n = E.nrows
    lr, lc = E.local_rows, E.local_cols
    nb = len(E.buckets)
    lcol = levels.realign("col")

    def body(prow_b, lrow_b, lcol_b, *flat):
        buckets = [
            tuple(a[0, 0] for a in flat[3 * i : 3 * i + 3]) for i in range(nb)
        ]
        prow, lrow = prow_b[0], lrow_b[0]  # [lr, W]
        lc_b = lcol_b[0]  # [lc, W]
        W = prow.shape[1]
        i = jax.lax.axis_index(ROW_AXIS)
        j = jax.lax.axis_index(COL_AXIS)
        row_g = jnp.arange(lr, dtype=jnp.int32) + i * lr  # global row ids
        rvalid = row_g < n

        # v1: root accounting (root = self-parent at level 0)
        is_root = (prow == row_g[:, None]) & (lrow == 0) & rvalid[:, None]
        nroots = jax.lax.psum(
            jnp.sum(is_root.astype(jnp.int32), axis=0), ROW_AXIS
        )
        v1 = jnp.abs(nroots - 1)

        # full per-lane level table for parent lookups (validation W is
        # small; all_gather of [lc, W] over "c" = the global vector)
        lvl_full = jax.lax.all_gather(lc_b, COL_AXIS).reshape(-1, W)[:n]
        disc = (lrow >= 0) & rvalid[:, None]
        nonroot = disc & ~is_root
        pidx = jnp.clip(prow, 0, n - 1)
        lane = jnp.arange(W, dtype=jnp.int32)[None, :]
        lp = lvl_full[pidx, lane]  # lp[v, w] = level[parent[v, w], w]
        v2 = jax.lax.psum(
            jnp.sum(
                (nonroot & ((lp < 0) | (lrow != lp + 1))).astype(jnp.int32),
                axis=0,
            ),
            ROW_AXIS,
        )

        # v3 + v4: one sweep over the ELL buckets
        lpad = jnp.concatenate(
            [lc_b, jnp.full((1, W), -1, lc_b.dtype)]
        )  # [lc+1, W]
        tree_found = jnp.zeros((lr, W), bool)
        v4 = jnp.zeros((W,), jnp.int32)
        for bc0, _bv0, br0 in buckets:  # the shard-LOCAL tile slices
            rowok = br0 < lr  # padded bucket rows are inert
            slot_ok = (bc0 < lc) & rowok[:, None]  # [nbk, kb]
            colg = jnp.where(slot_ok, bc0 + j * lc, n)
            g = lpad[jnp.minimum(bc0, lc)]  # [nbk, kb, W] neighbor levels
            rl = lrow[jnp.minimum(br0, lr - 1)]  # [nbk, W] row levels
            rd = rl >= 0
            nd = g >= 0
            bad_cross = slot_ok[..., None] & (rd[:, None, :] != nd)
            bad_far = (
                slot_ok[..., None]
                & rd[:, None, :] & nd
                & (jnp.abs(g - rl[:, None, :]) > 1)
            )
            v4 = v4 + jnp.sum(
                (bad_cross | bad_far).astype(jnp.int32), axis=(0, 1)
            )
            pv = prow[jnp.minimum(br0, lr - 1)]  # [nbk, W] parent ids
            match = slot_ok[..., None] & (colg[..., None] == pv[:, None, :])
            hit = jnp.any(match, axis=1) & rowok[:, None]  # [nbk, W]
            tree_found = tree_found.at[jnp.minimum(br0, lr - 1)].max(hit)
        # a row's full adjacency may span several grid columns
        tree_found = jax.lax.pmax(tree_found, COL_AXIS)
        v4 = jax.lax.psum(jax.lax.psum(v4, COL_AXIS), ROW_AXIS)
        v3 = jax.lax.psum(
            jnp.sum((nonroot & ~tree_found).astype(jnp.int32), axis=0),
            ROW_AXIS,
        )
        return jnp.stack([v1, v2, v3, v4])[None]

    flat_args = [a for b in E.buckets for a in b]
    out = jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(P(ROW_AXIS), P(ROW_AXIS), P(COL_AXIS))
        + (TILE_SPEC,) * (3 * nb),
        out_specs=P(None),
        check_vma=False,
    )(
        parents.realign("row").blocks, levels.realign("row").blocks,
        lcol.blocks, *flat_args,
    )
    return out[0]


@partial(jax.jit, static_argnames=("max_iters", "sr", "track_levels"))
def bfs_batch(
    A,
    sources,
    max_iters: int | None = None,
    sr: "Semiring" = SELECT2ND_MAX,
    track_levels: bool = True,
):
    """Multi-source batched BFS: W independent BFS trees in ONE program.

    Graph500 runs 64 search keys (the reference loops them host-side,
    ``TopDownBFS.cpp:437-444``); on TPU the whole batch advances together as
    a [n, W] frontier matrix — SURVEY §2.3 strategy 7 (BetwCent's
    frontier-as-matrix) applied to BFS itself. Two wins, both measured on
    v5e: (a) gathers are per-index bound, so W parent lanes ride one index
    fetch ~free; (b) the whole batch is one launch — one fixed ~100ms
    dispatch instead of W of them.

    ``sources``: int32 [W]. Returns (parents DistMultiVec [n, W] row-aligned,
    levels DistMultiVec, num_iters) — num_iters is the MAX level over the
    batch (lanes that finish early idle through the remaining levels with
    no semantic effect; dense-regime level cost is frontier-independent).
    ``track_levels=False`` drops the level array from the loop carry,
    saving one [n, W] int32 buffer (it raised the feasible batch width
    from 256 toward 384 at scale 20 — W=512 still exceeds this chip's
    16G HBM; see benchmarks/results/bench_sweep_r2c.txt). Levels are then
    returned as a discovery indicator (0 discovered / -1 not).
    """
    from ..parallel.vec import DistMultiVec
    from ..parallel.ellmat import EllParMat, dist_spmv_ell_masked_multi

    grid = A.grid
    n = A.nrows
    pr_, lr = grid.pr, grid.local_rows(n)
    pc_, lc = grid.pc, grid.local_cols(A.ncols)
    W = sources.shape[0]
    iters = max_iters if max_iters is not None else n

    row_gids = _global_ids(grid, pr_, lr, n, "row")  # [pr, lr]
    col_gids = _global_ids(grid, pc_, lc, A.ncols, "col")

    src = sources.astype(jnp.int32)[None, None, :]  # [1, 1, W]
    parents0 = jnp.where(
        row_gids[:, :, None] == src, src, jnp.int32(-1)
    )  # [pr, lr, W]
    levels0 = (
        jnp.where(row_gids[:, :, None] == src, 0, -1).astype(jnp.int32)
        if track_levels
        else jnp.zeros((1, 1, 1), jnp.int32)  # placeholder carry
    )
    x0 = jnp.where(col_gids[:, :, None] == src, src, jnp.int32(-1))

    def mk(b, align):
        return DistMultiVec(blocks=b, length=n, align=align, grid=grid)

    def cond(state):
        _, _, _, level, active = state
        return active & (level < iters)

    def step(state):
        parents, levels, x, level, _ = state
        unvisited = mk(parents < 0, "row")
        y = dist_spmv_ell_masked_multi(sr, A, mk(x, "col"), unvisited)
        new = (y.blocks >= 0) & (parents < 0) & (row_gids[:, :, None] >= 0)
        parents = jnp.where(new, y.blocks, parents)
        if track_levels:
            levels = jnp.where(new, level + 1, levels)
        x_next = mk(
            jnp.where(new, row_gids[:, :, None], -1), "row"
        ).realign("col").blocks
        active = jnp.any(new)
        return parents, levels, x_next, level + 1, active

    parents, levels, _, niter, _ = jax.lax.while_loop(
        cond, step, (parents0, levels0, x0, jnp.int32(0), jnp.bool_(True))
    )
    if not track_levels:
        # levels were not tracked: return discovery indicator (0 for the
        # sources / discovered? -1 undiscovered) — parents' sign carries it.
        levels = jnp.where(parents >= 0, 0, -1)
    return mk(parents, "row"), mk(levels, "row"), niter


@jax.jit
def batch_traversed_edges(deg_row_blocks, parents) -> jax.Array:
    """Graph500 kernel-2 edge count per root, ON DEVICE: [W] int array of
    (sum of degrees over discovered vertices) / 2 — so the benchmark's only
    D2H readback is one tiny vector AFTER the timed batch.

    ``deg_row_blocks``: [pr, lr] structural out-degrees (row-aligned,
    padding 0); ``parents``: the DistMultiVec from ``bfs_batch``.
    """
    disc = parents.blocks >= 0  # [pr, lr, W]
    # uint32 accumulation: a giant component's per-root degree sum can reach
    # the full symmetrized endpoint count ~2^(scale+5) at edgefactor 16,
    # which crosses 2^31 near scale 26 — uint32 extends the safe range to
    # scale ~27 (the [W] output is tiny, so width costs nothing).
    te = jnp.sum(
        jnp.where(disc, deg_row_blocks[:, :, None], 0).astype(jnp.uint32),
        axis=(0, 1),
    )
    return (te // 2).astype(jnp.int32)


@partial(
    jax.jit,
    static_argnames=("max_iters", "ring", "frontier_capacity",
                     "edge_capacity"),
)
def bfs_batch_compact(A, sources, max_iters: int | None = None,
                      ring: bool = False, csc=None,
                      frontier_capacity: int | None = None,
                      edge_capacity: int | None = None):
    """Level-compressed multi-source BFS: int8 frontiers, parents
    reconstructed in ONE pass after the search.

    ``bfs_batch`` carries int32 parent candidates through every gather —
    4W bytes of payload per gathered index. This variant carries only a
    one-byte level indicator per root (W bytes/index): the search loop
    discovers LEVELS, and parents come from a single final sweep picking,
    per (vertex, root), the max-id in-neighbor at level-1 (any valid
    Graph500 tree; the reference's SelectMax tie-break). On
    payload-width-sensitive gather hardware this cuts dense-level cost
    ~3-4x at W=256 and halves the memory footprint (int8 state).

    Level range: int8 caps at 126 levels — far beyond any Graph500 R-MAT
    diameter; ``max_iters`` defaults to that cap.

    ``ring=True`` folds each level's partials with the explicit
    ppermute carousel schedule (``collectives.axis_ring_reduce`` — the
    BitMapCarousel analog, neighbor-only ICI traffic) instead of the
    fused all-reduce; results are identical.

    Direction optimization for the batch: pass ``csc`` (the
    ``ellmat.build_csc_companion`` arrays) plus static ``frontier_capacity``
    / ``edge_capacity`` budgets, and each level checks ON DEVICE whether
    the UNION of all W frontiers fits the budgets — if so it walks only
    those columns' edges (cost ∝ budgets) instead of the full dense sweep
    (cost ∝ nnz). First levels and the straggler tail of a 256-root batch
    are exactly this regime. ``lax.cond`` keeps both kernels compiled
    once; zero host readbacks.

    Returns (parents DistMultiVec int32, levels DistMultiVec int8,
    num_iters) with the same conventions as ``bfs_batch``.
    """
    from ..parallel.ellmat import (
        EllParMat,
        _ell_levels_step,
        _ell_parents_from_levels,
        _ell_union_sparse_step,
    )
    from ..parallel.vec import DistMultiVec
    from ..parallel.grid import COL_AXIS, ROW_AXIS
    from jax.sharding import PartitionSpec as P

    grid = A.grid
    n = A.nrows
    pr_, lr = grid.pr, grid.local_rows(n)
    pc_, lc = grid.pc, grid.local_cols(A.ncols)
    W = sources.shape[0]
    if max_iters is not None and max_iters > 126:
        raise ValueError(
            f"bfs_batch_compact stores levels as int8 (max depth 126); "
            f"max_iters={max_iters} cannot be honored — use bfs_batch for "
            "graphs with eccentricity beyond 126"
        )
    iters = max_iters if max_iters is not None else 126

    row_gids = _global_ids(grid, pr_, lr, n, "row")
    col_gids = _global_ids(grid, pc_, lc, A.ncols, "col")
    src = sources.astype(jnp.int32)[None, None, :]

    levels0 = jnp.where(
        row_gids[:, :, None] == src, 0, -1
    ).astype(jnp.int8)  # [pr, lr, W]
    x0 = (col_gids[:, :, None] == src).astype(jnp.int8)  # [pc, lc, W]

    def mk(b, align):
        return DistMultiVec(blocks=b, length=n, align=align, grid=grid)

    diropt = (
        csc is not None
        and frontier_capacity is not None
        and edge_capacity is not None
    )
    if diropt:
        csc_indptr, csc_rowidx = csc

        def colde_body(ipt):
            d = ipt[0, 0][1:] - ipt[0, 0][:-1]
            return jax.lax.psum(d, ROW_AXIS)[None]

        coldeg = jax.shard_map(
            colde_body,
            mesh=grid.mesh,
            in_specs=(P(ROW_AXIS, COL_AXIS),),
            out_specs=P(COL_AXIS),
            check_vma=False,
        )(csc_indptr)  # [pc, lc] per-column degrees

    def cond(state):
        _, _, level, active = state
        return active & (level < iters)

    def step(state):
        levels, x, level, _ = state
        undisc = (levels < 0).astype(jnp.int8)
        if diropt:
            act = jnp.max(x, axis=2) > 0  # [pc, lc] union frontier
            cnt = jnp.sum(act.astype(jnp.int32))
            edges = jnp.sum(jnp.where(act, coldeg, 0))
            use_sparse = (cnt <= frontier_capacity) & (
                edges <= edge_capacity
            )
            reached = jax.lax.cond(
                use_sparse,
                lambda a: _ell_union_sparse_step(
                    A, csc_indptr, csc_rowidx, a[0], a[1],
                    frontier_capacity, edge_capacity,
                ),
                lambda a: _ell_levels_step(A, a[0], a[1], ring=ring),
                (x, undisc),
            )
        else:
            reached = _ell_levels_step(A, x, undisc, ring=ring)
        new = reached > 0
        levels = jnp.where(new, (level + 1).astype(jnp.int8), levels)
        x_next = mk(reached, "row").realign("col").blocks
        return levels, x_next, level + 1, jnp.any(new)

    levels, _, niter, _ = jax.lax.while_loop(
        cond, step, (levels0, x0, jnp.int8(0), jnp.bool_(True))
    )

    levels_col = mk(levels, "row").realign("col").blocks
    parents = _ell_parents_from_levels(A, levels_col, levels)
    # roots are their own parents; undiscovered stay -1
    parents = jnp.where(row_gids[:, :, None] == src, src, parents)
    parents = jnp.where(
        (levels < 0) | (row_gids[:, :, None] < 0), -1, parents
    )
    return mk(parents, "row"), mk(levels, "row"), niter.astype(jnp.int32)
