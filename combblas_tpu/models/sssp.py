"""Single-source shortest paths — Bellman-Ford over MIN_PLUS (≈ SSSP.cpp).

The reference iterates ``SpMV<MinPlusSRing>`` until the distance vector
stops improving (``Applications/SSSP.cpp`` main loop).  Identical here: the
tropical semiring SpMV relaxes every edge each round; the loop is a
``lax.while_loop`` fixed point, bounded by n rounds (longest possible
shortest path), so one compiled program covers any source.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..semiring import MIN_PLUS
from ..parallel.spmat import SpParMat
from ..parallel.spmv import dist_spmv
from ..parallel.vec import DistVec


def sssp(A: SpParMat, source) -> tuple[DistVec, jax.Array]:
    """Eager wrapper over ``_sssp_impl`` (plain-outputs law,
    PERF_NOTES_r5 §1)."""
    blocks, niter = _sssp_impl(A, source)
    return (
        DistVec(blocks=blocks, length=A.nrows, align="row", grid=A.grid),
        niter,
    )


@jax.jit
def _sssp_impl(A: SpParMat, source):
    """Distances from ``source``; unreachable vertices hold +inf.

    A[i, j] = w is the weight of edge j -> i (same gather orientation as
    BFS); weights must be non-negative for meaningful results (Bellman-Ford
    itself tolerates negatives but the fixed-point bound assumes no negative
    cycles).  Returns (dist row-aligned float DistVec, iterations).
    """
    grid = A.grid
    n = A.nrows
    dtype = A.dtype
    inf = MIN_PLUS.zero(dtype)

    gids = DistVec.iota(grid, n, jnp.int32, align="row").blocks
    d0 = jnp.where(gids == source, jnp.zeros((), dtype), inf)

    def mk(blocks):
        return DistVec(blocks=blocks, length=n, align="row", grid=grid)

    def cond(state):
        _, changed, it = state
        return changed & (it < n)

    def step(state):
        db, _, it = state
        d = mk(db)
        relaxed = dist_spmv(MIN_PLUS, A, d.realign("col"))
        nb = jnp.minimum(db, relaxed.blocks)
        return nb, jnp.any(nb != db), it + 1

    db, _, niter = jax.lax.while_loop(
        cond, step, (d0, jnp.bool_(True), jnp.int32(0))
    )
    return db, niter


def sssp_batch(E, sources):
    """Eager wrapper over ``_sssp_batch_impl`` (plain-outputs law)."""
    from ..parallel.vec import DistMultiVec

    blocks, niter = _sssp_batch_impl(E, sources)
    return (
        DistMultiVec(
            blocks=blocks, length=E.nrows, align="row", grid=E.grid
        ),
        niter,
    )


@jax.jit
def _sssp_batch_impl(E, sources):
    """Multi-source Bellman-Ford: distances from W sources in ONE program.

    ``E``: weighted EllParMat (entry (i,j) = w(j->i), non-negative).
    ``sources``: [W] int32. Returns (row-aligned PLAIN [pr, lr, W] blocks (wrapper rebuilds the DistMultiVec) of
    distances — +inf where unreachable — and the iteration count).

    The multi-root amortization of the batched BFS applied to SSSP: the
    chip's gather cost is per-INDEX with payload lanes nearly free, so W
    Bellman-Ford chains advance for ~the cost of one (compare the
    single-source loop above, which pays the full gather per source).
    Reference: ``Applications/SSSP`` role; the reference has no batched
    variant — this is TPU-native surface.
    """
    from ..parallel.ellmat import dist_spmv_ell_multi
    from ..parallel.vec import DistMultiVec

    grid = E.grid
    n = E.nrows
    dtype = E.dtype
    inf = MIN_PLUS.zero(dtype)

    gids = DistVec.iota(grid, n, jnp.int32, align="row").blocks  # [pr, lr]
    # models.PAD_ROOT lanes are inert padding (all-inf distances — the
    # serve batcher's lane padding); same guard as _bfs_batch_impl
    from . import PAD_ROOT

    live = sources[None, None, :] != PAD_ROOT
    d0 = jnp.where(
        (gids[..., None] == sources[None, None, :]) & live,
        jnp.zeros((), dtype), inf,
    )

    def mk(blocks):
        return DistMultiVec(blocks=blocks, length=n, align="row", grid=grid)

    def cond(state):
        _, changed, it = state
        return changed & (it < n)

    def step(state):
        db, _, it = state
        relaxed = dist_spmv_ell_multi(MIN_PLUS, E, mk(db))
        nb = jnp.minimum(db, relaxed.blocks)
        return nb, jnp.any(nb != db), it + 1

    db, _, niter = jax.lax.while_loop(
        cond, step, (d0, jnp.bool_(True), jnp.int32(0))
    )
    return db, niter
