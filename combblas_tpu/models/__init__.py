"""Application suite (≈ Applications/): BFS, SSSP, PageRank, BC, CC, TC,
MCL, MIS, matchings, orderings — plus the shared batch-lane conventions
the query-serving subsystem (``combblas_tpu.serve``) builds on.
"""

#: Lane-padding sentinel for every batched multi-root kernel
#: (``bfs.bfs_batch``, ``bfs.bfs_batch_compact``, ``sssp.sssp_batch``,
#: ``pagerank.pagerank_batch``, ``bc.bc_batch_dense_lanes``): a source
#: slot holding PAD_ROOT is an INERT lane — it discovers nothing,
#: carries zero rank/mass, and its outputs are undefined-but-harmless
#: (callers must drop pad lanes, which ``serve.batcher`` does). The
#: value is negative so it can never collide with a vertex id.
PAD_ROOT: int = -1
