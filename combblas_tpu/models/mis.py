"""Maximal independent set — Luby's algorithm (≈ Applications/FilteredMIS.cpp).

The reference's MIS driver runs Luby rounds with ``SpMV<Select2nd>`` and
elementwise ops (``FilteredMIS.cpp``, SURVEY.md §2.5): each round every
undecided vertex draws a random priority; vertices whose priority beats all
undecided neighbors join the set, their neighbors leave.

TPU-native expression: priorities are a random permutation of vertex ids
(unique, so no tie handling), the neighborhood minimum is one SELECT2ND_MIN
SpMV, and the "neighbor joined" test is a second SpMV over the candidate
indicator — the whole loop is a ``lax.while_loop``, O(log n) expected rounds.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..semiring import SELECT2ND_MAX, SELECT2ND_MIN
from ..parallel.spmat import SpParMat
from ..parallel.spmv import dist_spmv
from ..parallel.vec import DistVec

UNDECIDED, IN_SET, EXCLUDED = 0, 1, -1


def mis(A: SpParMat, key: jax.Array) -> tuple[DistVec, jax.Array]:
    """Eager wrapper over ``_mis_impl`` (plain-outputs law)."""
    blocks, niter = _mis_impl(A, key)
    return (
        DistVec(blocks=blocks, length=A.nrows, align="row", grid=A.grid),
        niter,
    )


@jax.jit
def _mis_impl(A: SpParMat, key: jax.Array):
    """Maximal independent set of the symmetric loop-free graph A.

    Returns (status row-aligned int32: 1 = in set, -1 = excluded,
    padding slots -1; iterations).
    """
    grid = A.grid
    n = A.nrows

    gids = DistVec.iota(grid, n, jnp.int32, align="row").blocks
    pa, L = gids.shape
    # Unique random priorities: a permutation of [0, pa*L).
    prio = jax.random.permutation(key, pa * L).reshape(pa, L).astype(jnp.int32)
    status0 = jnp.where(gids < n, UNDECIDED, EXCLUDED).astype(jnp.int32)

    def mk(blocks):
        return DistVec(blocks=blocks, length=n, align="row", grid=grid)

    big = SELECT2ND_MIN.zero(jnp.int32)  # INT32_MAX

    def cond(state):
        sb, it = state
        return jnp.any(sb == UNDECIDED) & (it < n)

    def step(state):
        sb, it = state
        undecided = sb == UNDECIDED
        # Priority of undecided vertices; decided ones are inert (+inf).
        x = mk(jnp.where(undecided, prio, big)).realign("col")
        nbr_min = dist_spmv(SELECT2ND_MIN, A, x)
        cand = undecided & (prio < nbr_min.blocks)
        # Neighbors of new set members become excluded.
        ci = mk(jnp.where(cand, 1, -1)).realign("col")
        nbr_cand = dist_spmv(SELECT2ND_MAX, A, ci)
        sb = jnp.where(cand, IN_SET, sb)
        sb = jnp.where(
            (sb == UNDECIDED) & (nbr_cand.blocks == 1), EXCLUDED, sb
        )
        return sb, it + 1

    sb, niter = jax.lax.while_loop(cond, step, (status0, jnp.int32(0)))
    return sb, niter
