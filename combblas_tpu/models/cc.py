"""Connected components — FastSV (≈ Applications/FastSV.cpp/.h).

The reference's FastSV (Zhang, Azad, Hu; SIAM PP'20 implementation at
``Applications/FastSV.h``) iterates three label-lowering rules until the
parent vector stabilizes, each expressed in CombBLAS as a
``SpMV<Select2ndMinSR>`` over grandparent labels plus scatter-assign
(``FastSV.h:347-359`` SpMV on grandparents, ``FastSV.h:68-146``
Assign/ReduceAssign):

  1. stochastic hooking : f[f[i]] <- min(f[f[i]], u[i])
  2. aggressive hooking : f[i]    <- min(f[i],    u[i])
  3. shortcutting       : f[i]    <- min(f[i],    f[f[i]])

with ``u[i] = min over neighbors j of gf[j]`` and ``gf = f[f]``.

TPU-native expression: ``u`` is one semiring SpMV (SELECT2ND_MIN) over the
mesh; hooking is ``DistVec.scatter_combine`` (segment-min); the whole loop is
a ``lax.while_loop`` with a fixed-point convergence test — no host round
trips, the entire CC run is one XLA program.

``lacc`` below is a real implementation of LACC (``Applications/CC.h``,
Azad-Buluç IPDPS'19) — the star-hooking algorithm the reference's ctest
suite exercises — not an alias: conditional/unconditional star hooking,
star tracking, and shortcutting, each phase a dense vectorized step so the
whole run is one XLA program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..semiring import SELECT2ND_MIN
from ..parallel.spmat import SpParMat
from ..parallel.spmv import dist_spmv
from ..parallel.vec import DistVec


def connected_components(A: SpParMat) -> tuple[DistVec, jax.Array]:
    """Eager wrapper over ``_connected_components_impl`` (plain-outputs
    law, PERF_NOTES_r5 §1: dataclass-wrapped jit outputs ran the batched
    BFS child 3x slower in the r5 A/B)."""
    blocks, niter = _connected_components_impl(A)
    return (
        DistVec(blocks=blocks, length=A.nrows, align="row", grid=A.grid),
        niter,
    )


@jax.jit
def _connected_components_impl(A: SpParMat):
    """Component labels (min vertex id in each component) + iteration count.

    A is interpreted structurally (any nonzero = edge) and must be
    symmetric; returns PLAIN row-aligned int32 label BLOCKS (the eager
    wrapper above rebuilds the DistVec); padding slots carry their own
    (out-of-range) ids and never interact with real vertices.
    """
    grid = A.grid
    n = A.nrows

    f0 = DistVec.iota(grid, n, jnp.int32, align="row")

    def mk(blocks):
        return DistVec(blocks=blocks, length=n, align="row", grid=grid)

    def cond(state):
        _, changed, it = state
        return changed & (it < n)

    def step(state):
        fb, _, it = state
        f = mk(fb)
        gf = f.gather(f)  # grandparent labels f[f[i]]
        # u[i] = min over neighbors j of gf[j]  (one semiring SpMV)
        u = dist_spmv(SELECT2ND_MIN, A, gf.realign("col"))
        # stochastic hooking: lower the parent's label
        f1 = f.scatter_combine(SELECT2ND_MIN, idx=f, src=u)
        # aggressive hooking + shortcutting (elementwise minimums)
        nb = jnp.minimum(jnp.minimum(f1.blocks, u.blocks), gf.blocks)
        changed = jnp.any(nb != fb)
        return nb, changed, it + 1

    fb, _, niter = jax.lax.while_loop(
        cond, step, (f0.blocks, jnp.bool_(True), jnp.int32(0))
    )

    # Final pointer-jumping: compress remaining parent chains to roots.
    def jcond(state):
        fb, changed = state
        return changed

    def jstep(state):
        fb, _ = state
        f = mk(fb)
        gf = f.gather(f)
        return gf.blocks, jnp.any(gf.blocks != fb)

    fb, _ = jax.lax.while_loop(jcond, jstep, (fb, jnp.bool_(True)))
    return fb, niter


_STAR, _NONSTAR, _CONVERGED = jnp.int32(1), jnp.int32(0), jnp.int32(2)


def lacc(A: SpParMat) -> tuple[DistVec, jax.Array]:
    """Eager wrapper over ``_lacc_impl`` (plain-outputs law)."""
    blocks, niter = _lacc_impl(A)
    return (
        DistVec(blocks=blocks, length=A.nrows, align="row", grid=A.grid),
        niter,
    )


@jax.jit
def _lacc_impl(A: SpParMat):
    """LACC connected components (≈ Applications/CC.h:1035-1530,
    Azad-Buluç IPDPS'19): conditional star hooking, unconditional star
    hooking, shortcutting, and star detection, iterated until every vertex
    is converged. Returns (labels, iterations) like
    ``connected_components``.

    TPU-native reformulation: the reference's FullyDistSpVec
    Extract/Assign/EWiseApply choreography becomes dense masked gathers and
    scatter-mins on the [pa, L] parent/star blocks, and the whole loop is
    one ``lax.while_loop`` (no host round trips). Two deviations, both
    conservative-correct: (a) the reference's iteration-1 special cases
    (skipping the parent-star propagation, CC.h:1445-1462,1475-1485) are
    replaced by the uniform star-tracking path — marking extra vertices
    NONSTAR is always safe because StarCheck re-promotes them; (b) hook
    duplicate resolution is a deterministic scatter-min instead of the
    reference's unordered Assign.
    """
    grid = A.grid
    n = A.nrows
    NOHOOK = jnp.int32(2**31 - 1)  # SELECT2ND_MIN identity = "no neighbor"

    iota = DistVec.iota(grid, n, jnp.int32, align="row")

    def mk(blocks):
        return DistVec(blocks=blocks, length=n, align="row", grid=grid)

    # isolated vertices (degree 0) start converged (CC.h:1416-1417)
    from ..semiring import PLUS_TIMES
    from ..parallel.spmat import ones_i32

    deg = A.reduce(PLUS_TIMES, "cols", map_fn=ones_i32)
    star0 = jnp.where(deg.blocks == 0, _CONVERGED, _STAR)
    # padding slots: converged, pointing at themselves, never hook
    star0 = mk(star0).mask_padding(_CONVERGED).blocks

    def scatter_min(vec: DistVec, idx_blocks, src_blocks):
        return vec.scatter_combine(
            SELECT2ND_MIN, idx=mk(idx_blocks), src=mk(src_blocks)
        )

    def scatter_set(base_blocks, idx_blocks, src_blocks):
        """out[p] = (min over src hitting p) if any hit else base[p].

        The reference's Assign/Set hook application (overwrite, not
        monoid-combine) with deterministic min dup-resolution: a plain
        scatter-min into base would silently drop hooks whose value
        exceeds the target's current parent — livelocking unconditional
        hooking (the hooked star would stay a star forever)."""
        fresh = mk(jnp.full_like(base_blocks, NOHOOK))
        hit = scatter_min(fresh, idx_blocks, src_blocks).blocks
        return jnp.where(hit != NOHOOK, hit, base_blocks)

    def cond(state):
        _, star, it, done = state
        return (~done) & (it < n)

    def step(state):
        parent_b, star_b, it, _ = state
        parent = mk(parent_b)

        # --- conditional star hooking (CC.h:1195-1240) -----------------
        # mnp[u] = min over neighbors of parent[neighbor]
        mnp = dist_spmv(SELECT2ND_MIN, A, parent.realign("col"))
        hook = (star_b == _STAR) & (mnp.blocks < parent_b)
        # hook the star's root: parent[parent[u]] <- min mnp[u]
        tgt = jnp.where(hook, parent_b, -1)
        val = jnp.where(hook, mnp.blocks, NOHOOK)
        parent_b = scatter_min(mk(parent_b), tgt, val).blocks

        # star tracking after hooking (CC.h:1035-1064, uniform path):
        # hooks, their roots, and the hook targets all become NONSTAR.
        star_b = jnp.where(hook, _NONSTAR, star_b)
        star_b = scatter_min(mk(star_b), tgt, jnp.where(hook, _NONSTAR, NOHOOK)).blocks
        star_b = scatter_min(
            mk(star_b), val, jnp.where(hook, _NONSTAR, NOHOOK)
        ).blocks
        # stars read their parent's star flag (propagate non-starness)
        pstar = mk(star_b).gather(mk(parent_b))
        star_b = jnp.where(
            (star_b == _STAR) & (pstar.blocks == _NONSTAR), _NONSTAR, star_b
        )

        # --- unconditional star hooking (CC.h:1243-1320) ----------------
        # exclude star trees as targets: their parent-values become the
        # SELECT2ND_MIN identity, so only nonstar neighbors contribute.
        masked_parent = jnp.where(star_b == _STAR, NOHOOK, parent_b)
        mnp2 = dist_spmv(
            SELECT2ND_MIN, A, mk(masked_parent).realign("col")
        )
        hook2 = (star_b == _STAR) & (mnp2.blocks != NOHOOK)
        tgt2 = jnp.where(hook2, parent_b, -1)
        val2 = jnp.where(hook2, mnp2.blocks, NOHOOK)
        parent_b = scatter_set(parent_b, tgt2, val2)

        star_b = jnp.where(hook2, _NONSTAR, star_b)
        star_b = scatter_min(
            mk(star_b), tgt2, jnp.where(hook2, _NONSTAR, NOHOOK)
        ).blocks
        star_b = scatter_min(
            mk(star_b), val2, jnp.where(hook2, _NONSTAR, NOHOOK)
        ).blocks
        pstar = mk(star_b).gather(mk(parent_b))
        star_b = jnp.where(
            (star_b == _STAR) & (pstar.blocks == _NONSTAR), _NONSTAR, star_b
        )

        # remaining stars are converged (CC.h:1477)
        star_b = jnp.where(star_b == _STAR, _CONVERGED, star_b)
        done = jnp.all(star_b == _CONVERGED)

        # --- shortcut on nonstars (CC.h:1332-1345) ----------------------
        parent = mk(parent_b)
        gp = parent.gather(parent)
        parent_b = jnp.where(star_b == _NONSTAR, gp.blocks, parent_b)

        # --- star detection on nonstars (CC.h:1070-1124) ----------------
        active = star_b == _NONSTAR
        star_b = jnp.where(active, _STAR, star_b)
        parent = mk(parent_b)
        gp = parent.gather(parent)
        bad = active & (gp.blocks != parent_b)
        star_b = jnp.where(bad, _NONSTAR, star_b)
        # parents and grandparents of deep vertices are NONSTAR
        star_b = scatter_min(
            mk(star_b), jnp.where(bad, parent_b, -1),
            jnp.where(bad, _NONSTAR, NOHOOK),
        ).blocks
        star_b = scatter_min(
            mk(star_b), jnp.where(bad, gp.blocks, -1),
            jnp.where(bad, _NONSTAR, NOHOOK),
        ).blocks
        # leaves read their parent's flag
        pstar = mk(star_b).gather(mk(parent_b))
        star_b = jnp.where(
            active & (star_b == _STAR) & (pstar.blocks == _NONSTAR),
            _NONSTAR, star_b,
        )
        return parent_b, star_b, it + 1, done

    parent_b, _, niter, _ = jax.lax.while_loop(
        cond, step, (iota.blocks, star0, jnp.int32(0), jnp.bool_(False))
    )

    # compress remaining chains (stars may point one level up)
    def jcond(state):
        _, changed = state
        return changed

    def jstep(state):
        fb, _ = state
        gf = mk(fb).gather(mk(fb))
        return gf.blocks, jnp.any(gf.blocks != fb)

    parent_b, _ = jax.lax.while_loop(
        jcond, jstep, (parent_b, jnp.bool_(True))
    )
    return parent_b, niter


def num_components(labels: DistVec) -> int:
    """Host helper: count distinct labels among real (non-padding) slots."""
    import numpy as np

    return int(np.unique(labels.to_global()).size)
