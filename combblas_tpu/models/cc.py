"""Connected components — FastSV (≈ Applications/FastSV.cpp/.h).

The reference's FastSV (Zhang, Azad, Hu; SIAM PP'20 implementation at
``Applications/FastSV.h``) iterates three label-lowering rules until the
parent vector stabilizes, each expressed in CombBLAS as a
``SpMV<Select2ndMinSR>`` over grandparent labels plus scatter-assign
(``FastSV.h:347-359`` SpMV on grandparents, ``FastSV.h:68-146``
Assign/ReduceAssign):

  1. stochastic hooking : f[f[i]] <- min(f[f[i]], u[i])
  2. aggressive hooking : f[i]    <- min(f[i],    u[i])
  3. shortcutting       : f[i]    <- min(f[i],    f[f[i]])

with ``u[i] = min over neighbors j of gf[j]`` and ``gf = f[f]``.

TPU-native expression: ``u`` is one semiring SpMV (SELECT2ND_MIN) over the
mesh; hooking is ``DistVec.scatter_combine`` (segment-min); the whole loop is
a ``lax.while_loop`` with a fixed-point convergence test — no host round
trips, the entire CC run is one XLA program.

LACC (``Applications/CC.h``, Azad-Buluç IPDPS'19) is the older algorithm with
the same SpMV+hooking skeleton; FastSV supersedes it in the reference and
here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..semiring import SELECT2ND_MIN
from ..parallel.spmat import SpParMat
from ..parallel.spmv import dist_spmv
from ..parallel.vec import DistVec


@jax.jit
def connected_components(A: SpParMat) -> tuple[DistVec, jax.Array]:
    """Component labels (min vertex id in each component) + iteration count.

    A is interpreted structurally (any nonzero = edge) and must be
    symmetric; labels are a row-aligned int32 DistVec, padding slots carry
    their own (out-of-range) ids and never interact with real vertices.
    """
    grid = A.grid
    n = A.nrows

    f0 = DistVec.iota(grid, n, jnp.int32, align="row")

    def mk(blocks):
        return DistVec(blocks=blocks, length=n, align="row", grid=grid)

    def cond(state):
        _, changed, it = state
        return changed & (it < n)

    def step(state):
        fb, _, it = state
        f = mk(fb)
        gf = f.gather(f)  # grandparent labels f[f[i]]
        # u[i] = min over neighbors j of gf[j]  (one semiring SpMV)
        u = dist_spmv(SELECT2ND_MIN, A, gf.realign("col"))
        # stochastic hooking: lower the parent's label
        f1 = f.scatter_combine(SELECT2ND_MIN, idx=f, src=u)
        # aggressive hooking + shortcutting (elementwise minimums)
        nb = jnp.minimum(jnp.minimum(f1.blocks, u.blocks), gf.blocks)
        changed = jnp.any(nb != fb)
        return nb, changed, it + 1

    fb, _, niter = jax.lax.while_loop(
        cond, step, (f0.blocks, jnp.bool_(True), jnp.int32(0))
    )

    # Final pointer-jumping: compress remaining parent chains to roots.
    def jcond(state):
        fb, changed = state
        return changed

    def jstep(state):
        fb, _ = state
        f = mk(fb)
        gf = f.gather(f)
        return gf.blocks, jnp.any(gf.blocks != fb)

    fb, _ = jax.lax.while_loop(jcond, jstep, (fb, jnp.bool_(True)))
    return mk(fb), niter


#: LACC (Azad-Buluç IPDPS'19, Applications/CC.h) is the older algorithm the
#: reference ships alongside FastSV; both share the SpMV<Select2ndMin> +
#: hooking + shortcutting skeleton and compute identical labelings. FastSV
#: (same research group's successor) is the single implementation here; the
#: alias keeps the reference's entry-point name.
lacc = connected_components


def num_components(labels: DistVec) -> int:
    """Host helper: count distinct labels among real (non-padding) slots."""
    import numpy as np

    return int(np.unique(labels.to_global()).size)
