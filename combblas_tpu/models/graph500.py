"""Graph500 kernel 1 — distributed graph construction, composed on device.

The reference's Graph500 driver builds the matrix distributed
(``TopDownBFS.cpp:270-370`` calling ``DistEdgeList::GenGraph500Data``,
``PermEdges``/``RenameVertices`` from ``DistEdgeList.cpp``, then the
``SpParMat`` Graph500 constructor ``SpParMat.cpp:3140-3441``: Alltoallv to
owner processes → dedup → Symmetricize → RemoveLoops → random-permutation
relabel → SpRef of non-isolated vertices → OptimizeForGraph500).  The
TPU-native composition below runs every distributed stage as XLA programs
over the grid mesh:

  generate (device threefry R-MAT, ``utils/rmat.py``)
  → symmetricize + de-loop (mask arithmetic on the edge list)
  → route to owner tiles (``redistribute_coo`` two-hop all_to_all) + dedup
  → optional extra random relabel (``permute_vertices`` — the
    PermEdges/RenameVertices analog, also used for file-loaded graphs)
  → isolated-vertex compression (rank-by-degree relabel: the static-shape
    analog of the reference's shrinking SpRef — non-isolated vertices are
    renumbered into a dense prefix [0, nkeep), isolated ones to the tail;
    the matrix keeps its static n, the tail rows/cols are empty)

Everything except capacity sizing (trace-time constants) and the
drop-retry check stays on device.
"""

from __future__ import annotations

import os
import time

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import obs
from ..parallel.grid import COL_AXIS, ROW_AXIS, Grid
from ..parallel.redistribute import from_device_coo
from ..parallel.spmat import TILE_SPEC, SpParMat
from ..parallel.vec import DistVec
from ..semiring import PLUS_TIMES, SELECT2ND_MAX


def permute_vertices(A: SpParMat, p: DistVec, *, slack: float = 2.0,
                     max_retries: int = 3) -> SpParMat:
    """Symmetric relabel: A'[p[i], p[j]] = A[i, j].

    The distributed analog of ``DistEdgeList::RenameVertices`` /
    ``PermEdges`` (DistEdgeList.cpp) and of the driver's random-permutation
    SpRef — the load-balancing relabel the reference applies to
    file-loaded graphs before BFS.  ``p`` is a permutation of
    [0, nrows) (e.g. ``DistVec.randperm``); requires a square matrix.

    Each tile maps its local tuples to permuted GLOBAL coordinates via the
    row-/col-aligned slices of ``p``, then one two-hop all_to_all routes
    them to their new owner tiles (capacity-doubling retry like
    ``from_device_coo`` — permutations preserve nnz but can skew tiles).
    """
    assert A.nrows == A.ncols, "vertex permutation needs a square matrix"
    grid = A.grid
    n = A.nrows
    lr, lc = A.local_rows, A.local_cols
    prow = p.realign("row").blocks  # [pr, lr] new id for each local row
    pcol = p.realign("col").blocks  # [pc, lc] new id for each local col

    def to_global(rows, cols, vals, nnz, pr_blk, pc_blk):
        valid = rows[0, 0] < lr
        pr_pad = jnp.concatenate([pr_blk[0], jnp.full((1,), n, jnp.int32)])
        pc_pad = jnp.concatenate([pc_blk[0], jnp.full((1,), n, jnp.int32)])
        gr = pr_pad[jnp.minimum(rows[0, 0], lr)]
        gc = pc_pad[jnp.minimum(cols[0, 0], lc)]
        gr = jnp.where(valid, gr, n)
        gc = jnp.where(valid, gc, n)
        return gr[None, None], gc[None, None], vals

    gr, gc, gv = jax.shard_map(
        to_global,
        mesh=grid.mesh,
        in_specs=(TILE_SPEC,) * 4 + (P(ROW_AXIS), P(COL_AXIS)),
        out_specs=(TILE_SPEC,) * 3,
        check_vma=False,
    )(A.rows, A.cols, A.vals, A.nnz, prow, pcol)

    return from_device_coo(
        grid, gr, gc, gv, n, n, slack=slack, max_retries=max_retries
    )


def isolated_compression_perm(A: SpParMat) -> tuple[DistVec, jax.Array]:
    """Permutation renumbering non-isolated vertices into a dense prefix.

    Returns (p, nkeep): ``p[v]`` is v's new id — vertices with degree > 0
    (counting either direction; A is assumed symmetric here, matching the
    Graph500 pipeline) get ranks [0, nkeep) in original order, isolated
    vertices get [nkeep, n).  The static-shape analog of the reference's
    shrinking ``SpRef`` of non-isolated vertices (SpParMat.cpp:3140-3441
    pipeline): instead of shrinking the matrix (dynamic shape), relabel so
    the live vertices are a prefix and report ``nkeep``.
    """
    deg = A.nnz_per_column()  # col-aligned [pc, lc]
    grid = A.grid
    n = A.ncols

    def body(dblk):
        local = dblk[0]  # [lc]
        has = (local > 0).astype(jnp.int32)
        # global exclusive scan: local prefix + offset of preceding blocks
        local_cum = jnp.cumsum(has) - has  # exclusive within block
        tot = jnp.sum(has)
        j = lax.axis_index(COL_AXIS)
        totals = lax.all_gather(tot, COL_AXIS)  # [pc]
        before = jnp.sum(jnp.where(jnp.arange(grid.pc) < j, totals, 0))
        nkeep = jnp.sum(totals)
        # isolated ranks: same construction over the complement
        iso = 1 - has
        iso_cum = jnp.cumsum(iso) - iso
        iso_tot = jnp.sum(iso)
        iso_totals = lax.all_gather(iso_tot, COL_AXIS)
        iso_before = jnp.sum(
            jnp.where(jnp.arange(grid.pc) < j, iso_totals, 0)
        )
        rank = jnp.where(
            has == 1,
            before + local_cum,
            nkeep + iso_before + iso_cum,
        ).astype(jnp.int32)
        return rank[None], nkeep[None]

    blocks, nkeep = jax.shard_map(
        body,
        mesh=grid.mesh,
        in_specs=(P(COL_AXIS),),
        out_specs=(P(COL_AXIS), P()),
        check_vma=False,
    )(deg.blocks)
    p = DistVec(blocks=blocks, length=n, align="col", grid=grid)
    return p, nkeep[0]


def kernel1_device(
    grid: Grid,
    scale: int,
    edgefactor: int,
    key,
    *,
    extra_relabel: bool = False,
    compress_isolated: bool = True,
    slack: float = 2.0,
):
    """Graph500 kernel 1, composed from distributed device stages.

    Returns ``(A, degrees, nkeep, timings)``: the symmetric dedup'd
    adjacency SpParMat (non-isolated vertices renumbered to a dense prefix
    when ``compress_isolated``), its row-degree DistVec, the device scalar
    count of non-isolated vertices, and a stage→seconds dict (wall-clock,
    synchronized per stage with ``block_until_ready`` — indicative on CPU,
    construction-grade on chip where it is timed in its own process).
    """
    from ..utils.rmat import rmat_edges

    import sys

    def _klog(msg):
        if os.environ.get("BENCH_K1_LOG"):
            print(f"[kernel1] {time.strftime('%H:%M:%S')} {msg}",
                  file=sys.stderr, flush=True)

    timings: dict[str, float] = {}
    n = 1 << scale
    ndev = grid.pr * grid.pc
    _klog("generate...")

    t0 = time.perf_counter()
    with obs.span("k1.generate", scale=scale):
        # generate (spec's vertex scramble included), symmetricize, de-loop
        src, dst = rmat_edges(key, scale, edgefactor * n)
        rows = jnp.concatenate([src, dst])
        cols = jnp.concatenate([dst, src])
        keep = rows != cols
        rows = jnp.where(keep, rows, n).astype(jnp.int32)
        cols = jnp.where(keep, cols, n).astype(jnp.int32)
        # shard the flat edge list into per-device chunks for routing
        total = rows.shape[0]
        chunk = -(-total // ndev)
        pad = chunk * ndev - total
        if pad:
            rows = jnp.concatenate([rows, jnp.full((pad,), n, jnp.int32)])
            cols = jnp.concatenate([cols, jnp.full((pad,), n, jnp.int32)])
        shape = (grid.pr, grid.pc, chunk)
        rows = jax.device_put(rows.reshape(shape), grid.tile_sharding())
        cols = jax.device_put(cols.reshape(shape), grid.tile_sharding())
        jax.block_until_ready((rows, cols))
    timings["generate_s"] = time.perf_counter() - t0
    _klog(f"generate done {timings['generate_s']:.1f}s; route...")

    t0 = time.perf_counter()
    with obs.span("k1.route_dedup"):
        vals = jnp.ones(shape, jnp.float32)
        # defer_drop_check: the capacity-retry readback would POISON this
        # process on the axon chip (bench.py docstring); the drop count
        # rides along as a device scalar (timings["dropped_dev"]) for the
        # caller to verify AFTER its timed section.
        A, dropped = from_device_coo(
            grid, rows, cols, vals, n, n, slack=slack,
            dedup_sr=SELECT2ND_MAX, defer_drop_check=True,
        )
        jax.block_until_ready(A.vals)
    timings["route_dedup_s"] = time.perf_counter() - t0
    timings["dropped_dev"] = dropped
    _klog(f"route done {timings['route_dedup_s']:.1f}s")

    if extra_relabel:
        t0 = time.perf_counter()
        with obs.span("k1.relabel"):
            p = DistVec.randperm(grid, n, jax.random.fold_in(key, 1))
            A = permute_vertices(A, p)
            jax.block_until_ready(A.vals)
        timings["relabel_s"] = time.perf_counter() - t0

    nkeep = jnp.asarray(n, jnp.int32)
    if compress_isolated:
        t0 = time.perf_counter()
        with obs.span("k1.compress_isolated"):
            p, nkeep = isolated_compression_perm(A)
            A = permute_vertices(A, p)
            jax.block_until_ready(A.vals)
        timings["compress_isolated_s"] = time.perf_counter() - t0

    t0 = time.perf_counter()
    with obs.span("k1.degree"):
        degrees = A.reduce(
            PLUS_TIMES, "row", map_fn=lambda v: (v != 0).astype(v.dtype)
        )
        jax.block_until_ready(degrees.blocks)
    timings["degree_s"] = time.perf_counter() - t0
    if obs.ENABLED:
        # kernel-1 stage times as histograms (the per-stage TIMING table)
        for k, v in timings.items():
            if isinstance(v, float):
                obs.observe("k1." + k, v)
    return A, degrees, nkeep, timings
