"""combblas_tpu — a TPU-native distributed sparse linear-algebra and
graph-analytics framework with the capabilities of CombBLAS.

Layer map (mirrors SURVEY.md §1, re-designed for JAX/XLA):

* ``semiring``   — trace-time semiring protocol (≈ Semirings.h functors).
* ``ops``        — local (single-tile) kernels on padded static-shape sparse
                   tiles: tuples/CSR/CSC formats, segment reductions, SpMV,
                   SpMSpV, SpGEMM, merge (≈ the sequential layer: dcsc/
                   SpDCCols/Friends/mtSpGEMM/MultiwayMerge/SpImpl).
* ``parallel``   — device-mesh grid, distributed matrices/vectors and the
                   SUMMA/SpMV/3D collective schedules (≈ CommGrid, SpParMat,
                   FullyDist*, ParFriends) expressed with shard_map +
                   psum/all_gather/ppermute/all_to_all over ICI.
* ``models``     — the application suite (BFS, CC, TC, PageRank, SSSP, MCL,
                   BC, MIS, matchings, RCM ≈ Applications/).
* ``utils``      — I/O (Matrix Market, Graph500 R-MAT generator),
                   profiling timers, checkpointing.
"""

from .semiring import (
    MAX_MIN,
    MIN_PLUS,
    OR_AND,
    PLUS_TIMES,
    SELECT2ND_MAX,
    SELECT2ND_MIN,
    STANDARD_SEMIRINGS,
    Semiring,
)
from .ops.tuples import SpTuples
from .ops.compressed import CSC, CSR

__version__ = "0.1.0"
