"""combblas_tpu — a TPU-native distributed sparse linear-algebra and
graph-analytics framework with the capabilities of CombBLAS.

Layer map (mirrors SURVEY.md §1, re-designed for JAX/XLA):

* ``semiring``   — trace-time semiring protocol (≈ Semirings.h functors).
* ``ops``        — local (single-tile) kernels on padded static-shape sparse
                   tiles: tuples/CSR/CSC formats, segment reductions, SpMV,
                   SpMSpV, SpGEMM, merge (≈ the sequential layer: dcsc/
                   SpDCCols/Friends/mtSpGEMM/MultiwayMerge/SpImpl).
* ``parallel``   — device-mesh grid, distributed matrices/vectors and the
                   SUMMA/SpMV/3D collective schedules (≈ CommGrid, SpParMat,
                   FullyDist*, ParFriends) expressed with shard_map +
                   psum/all_gather/ppermute/all_to_all over ICI.
* ``models``     — the application suite (BFS, CC, TC, PageRank, SSSP, MCL,
                   BC, MIS, matchings, RCM ≈ Applications/).
* ``utils``      — I/O (Matrix Market, Graph500 R-MAT generator),
                   profiling timers, checkpointing.
"""

from . import _compat  # jax.shard_map adapter for older runtimes (first!)

from .semiring import (
    MAX_MIN,
    MIN_PLUS,
    OR_AND,
    PLUS_TIMES,
    SELECT2ND_MAX,
    SELECT2ND_MIN,
    STANDARD_SEMIRINGS,
    Semiring,
)
from .ops.tuples import SpTuples
from .ops.compressed import CSC, CSR

# Distributed layer (the reference's public surface).
from .parallel.grid import Grid
from .parallel.mesh3d import Grid3D, SpParMat3D, spgemm3d
from .parallel.dense import DenseParMat
from .parallel.ellmat import EllParMat
from .parallel.spmat import SpParMat
from .parallel.vec import DistVec
from .parallel.spgemm import (
    PhaseAdjustedWarning,
    block_spgemm,
    calculate_phases,
    choose_spgemm_tier,
    coo_has_duplicates,
    default_block_cols,
    default_block_rows,
    estimate_flops,
    estimate_nnz_upper,
    mem_efficient_spgemm,
    resolve_spgemm_backend,
    spgemm,
    spgemm_auto,
    spgemm_scan,
    spgemm_windowed,
    summa_spgemm_mxu,
    summa_spgemm_windowed,
)
from .parallel.spmv import dist_spmspv, dist_spmv, dist_spmv_masked
from .parallel.vec import DistMultiVec, concatenate
from .parallel.indexing import spasgn, subsref
from .semantic import SemanticGraph, filtered_bfs, filtered_mis

# Telemetry (metrics registry + span traces + JSONL export); see
# docs/observability.md. Zero-cost when disabled (the default).
from . import obs

# Query serving (GraphEngine + batched, backpressured Server); see
# docs/serving.md. Pure host-side layering over models/parallel —
# importing it costs nothing until an engine is built.
from . import serve

# Streaming graph mutation (DeltaBuffer + incremental apply_delta +
# warm-restart refresh); see docs/dynamic.md. Host-side like serve.
from . import dynamic

__version__ = "0.1.0"

__all__ = [
    # semirings
    "Semiring", "PLUS_TIMES", "MIN_PLUS", "MAX_MIN", "OR_AND",
    "SELECT2ND_MAX", "SELECT2ND_MIN", "STANDARD_SEMIRINGS",
    # local formats
    "SpTuples", "CSR", "CSC",
    # distributed objects
    "Grid", "Grid3D", "SpParMat", "SpParMat3D", "DenseParMat", "EllParMat",
    "DistVec",
    # distributed algebra
    "spgemm", "spgemm_scan", "spgemm_auto", "spgemm_windowed",
    "choose_spgemm_tier", "coo_has_duplicates", "resolve_spgemm_backend",
    "default_block_rows", "default_block_cols", "mem_efficient_spgemm",
    "block_spgemm", "spgemm3d", "summa_spgemm_mxu",
    "summa_spgemm_windowed", "PhaseAdjustedWarning",
    "estimate_flops", "estimate_nnz_upper", "calculate_phases",
    "dist_spmv", "dist_spmv_masked", "dist_spmspv", "subsref", "spasgn",
    "concatenate", "DistMultiVec",
    # semantic graphs
    "SemanticGraph", "filtered_bfs", "filtered_mis",
    # telemetry
    "obs",
    # query serving
    "serve",
    # streaming mutation lane
    "dynamic",
]
