"""Shared ``store > env > probe > heuristic`` tier resolution.

``spgemm_auto`` and ``mesh3d.spgemm3d`` resolve their tier through the
precedence chain documented in :mod:`~combblas_tpu.tuner.config`; the
bench drivers (which must decide from HOST counts before touching the
device — the axon D2H rule) used to re-implement that chain inline,
and the copies skipped the library's record vetting: a hand-mangled or
wrong-op store line would route a bench where the library would have
rejected it.  :func:`resolve_tier` is the ONE walk of the chain both
benches share.

The library routers keep their own inlined resolution (they interleave
record geometry / ring / dispatch fills the benches don't carry), but
the VETTING semantics — unknown tier rejected with
``tuner.store.rejected{reason=tier}``, the winning source counted as
``spgemm.auto.plan_source`` — are identical by construction here.
"""

from __future__ import annotations

from .. import obs
from . import config
from . import store as tuner_store


def resolve_tier(
    key,
    *,
    allowed: tuple,
    heuristic,
    op: str = "spgemm",
    tier: str | None = None,
    store=None,
    probe=None,
    account: bool = True,
):
    """Resolve one tier through ``arg > store > env > probe >
    heuristic``.  Returns ``(tier, source, record)`` where ``source``
    names the winning rung (``arg`` / ``store`` / ``env`` / ``probe`` /
    ``heuristic``) and ``record`` is the vetted ``PlanRecord`` when the
    store won (callers replay its block geometry / schedule flags).

    * ``key`` — the :class:`~combblas_tpu.tuner.store.PlanKey` to look
      up (``None`` skips the store rung);
    * ``allowed`` — tiers this op accepts; a key-matched record outside
      it is DISCARDED with ``tuner.store.rejected{reason=tier}`` (the
      library's record vetting) and resolution degrades down the chain;
    * ``heuristic`` — the fallback: a tier name, or a zero-arg callable
      evaluated only when every other rung passed;
    * ``probe`` — optional zero-arg callable returning a
      ``PlanRecord`` (or None); tried only when probing is enabled
      (``COMBBLAS_TUNER_PROBE=1``) and the store missed;
    * ``account`` — ``True`` uses ``store.lookup`` (hit/miss counters +
      ``spgemm.auto.plan_source``); ``False`` uses ``store.peek`` and
      emits NOTHING — the mirror mode for callers whose library call
      does the accounted resolution itself (spgemm3d_bench's
      provenance JSON).
    """
    if tier is not None:
        source, rec = "arg", None
    else:
        source = rec = None
        if store is None:
            store = tuner_store.get_store()
        if store is not None and key is not None:
            rec = store.lookup(key) if account else store.peek(key)
        if rec is not None and rec.tier not in allowed:
            # the record vetting the inline bench copies skipped
            if account and obs.ENABLED:
                obs.count("tuner.store.rejected", reason="tier")
            rec = None
        if rec is not None:
            tier, source = rec.tier, "store"
        if tier is None:
            if op == "spgemm3d":
                env_val = config.env_tier3d()
            elif op == "spmm":
                env_val = config.env_spmm_backend()
            else:
                env_val = config.env_tier()
            if env_val is not None:
                tier, source = env_val, "env"
        if (
            tier is None
            and probe is not None
            and store is not None
            and config.probe_enabled()
        ):
            prec = probe()
            if prec is not None:
                tier, source, rec = prec.tier, "probe", prec
        if tier is None:
            tier = heuristic() if callable(heuristic) else heuristic
            source = "heuristic"
    if account and obs.ENABLED:
        obs.count(
            "spgemm.auto.plan_source", source=source, tier=tier, op=op,
        )
    return tier, source, rec


def resolve_merge(merge: str | None, rec):
    """Resolve the SpGEMM combine-merge tier through the top of the
    chain: ``arg > store record > env COMBBLAS_SPGEMM_MERGE``.  Returns
    ``(merge, source)`` — ``(None, None)`` when nothing above decided,
    in which case the SIZED ENTRY runs the heuristic (it alone holds
    the L / collision estimate the heuristic needs) and emits the
    ``spgemm.merge.tier`` counter with the final source.

    A record's merge field is vetted at store LOAD
    (``PlanRecord.from_json``), so anything reaching here is a valid
    tier name."""
    if merge is not None:
        assert merge in config.MERGE_TIER_NAMES, merge
        return merge, "arg"
    if rec is not None and rec.merge is not None:
        return rec.merge, "store"
    env_val = config.env_merge()
    if env_val is not None:
        return env_val, "env"
    return None, None
