"""Measured-cost plan store: remembered SpGEMM routing decisions.

The reference CombBLAS picks kernels from compile-time functors and
hand-reasoned flop models; our port's ``choose_spgemm_tier`` inherited
that spirit — every measured win was per-session folklore.  The store
replaces re-derivation with REMEMBERED MEASUREMENTS: plans keyed by
(shape bucket, density band, semiring, backend, grid / grid3) hold the
chosen tier, window geometry, schedule flags, and the measured cost,
persisted as schema-versioned JSONL next to the XLA compile cache so a
warm fleet ships plans to new replicas alongside compiled executables.

File format — one JSON object per line, append-only (later lines win):

    {"v": "combblas_tpu.plans/v1", "key": {...}, "plan": {...}}

Robustness contract: a corrupted, truncated, or schema-mismatched line
is IGNORED (counted in ``stats()['invalid_lines']`` and, under obs, the
``tuner.store.invalid`` counter) and routing falls back to the next
rung of the precedence chain — a bad plans file can never take the
library down.  Writes append a fully formed line (single ``write``
call), so a torn write from a dying process truncates to an invalid
LAST line, not a poisoned store.

The store also remembers SERVE WARMUP LANES: the (kind, width) plan
cache entries a serving process actually used, so a fresh replica's
``GraphEngine.warmup()`` pre-traces exactly the lanes the fleet serves
(zero steady-state retraces without re-measuring).

Host-side counters (``stats()``) are plain ints and always live; the
obs mirrors (``tuner.store.{hits,misses,entries}`` ...) cost nothing
when telemetry is disabled.
"""

from __future__ import annotations

import dataclasses
import fcntl
import json
import math
import os
import threading

import numpy as np

from .. import obs
from . import config

#: JSONL schema tag — bump on any incompatible key/plan layout change;
#: records carrying another tag are ignored at load (never guessed at).
SCHEMA = "combblas_tpu.plans/v1"

_TIERS = (
    "mxu", "windowed", "scan", "esc", "windowed3d", "serve",
    # op="spmm" backends (round 12): the MXU gather-contract lane and
    # its exact-everywhere scatter/fold fallback
    "mxu_gather", "scatter",
)


def shape_bucket(dim: int) -> int:
    """Pow2 shape bucket: ceil(log2(dim)).  Two products whose global
    dims round to the same pow2 share plans (and, with bucketed caps,
    compiled building blocks)."""
    return max(int(dim) - 1, 0).bit_length()


def density_band(nnz: int, dim: int) -> int:
    """Log2 band of the average degree (nnz per row): the density axis
    of the plan key.  Clamped so pathological inputs can't mint
    unbounded key cardinality."""
    deg = max(int(nnz), 1) / max(int(dim), 1)
    return int(min(max(round(math.log2(max(deg, 2.0 ** -8))), -8), 48))


@dataclasses.dataclass(frozen=True)
class PlanKey:
    """What a plan is keyed by.  ``op`` distinguishes the 2D router
    ("spgemm"), the 3D entry ("spgemm3d"), and serve warmup lane sets
    ("serve"); ``grid3`` is "" for 2D products."""

    op: str
    shape: tuple[int, int, int]   # shape buckets of (m, k, n)
    band: tuple[int, int]         # density bands of (A, B)
    sr: str
    backend: str
    grid: str                     # "pr x pc", e.g. "2x2"
    grid3: str = ""               # "L x pr x pc" for 3D, else ""
    platform: str = ""            # jax.default_backend()

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["shape"] = list(self.shape)
        d["band"] = list(self.band)
        return d

    @staticmethod
    def from_json(d: dict) -> "PlanKey":
        return PlanKey(
            op=str(d["op"]),
            shape=tuple(int(x) for x in d["shape"]),
            band=tuple(int(x) for x in d["band"]),
            sr=str(d["sr"]),
            backend=str(d["backend"]),
            grid=str(d["grid"]),
            grid3=str(d.get("grid3", "")),
            platform=str(d.get("platform", "")),
        )


@dataclasses.dataclass
class PlanRecord:
    """One remembered decision: the winning tier plus the knobs it was
    measured with and the measured cost.  ``block_rows``/``block_cols``
    of ``None`` mean "the kernel default for this shape" (the probe
    records what it actually ran).  ``lanes`` is the serve-warmup
    variant's payload ((kind, width) pairs); spgemm records leave it
    empty."""

    tier: str
    block_rows: int | None = None
    block_cols: int | None = None
    ring: bool = False
    pipeline: bool = True
    dispatch: str | None = None
    mode: str | None = None
    #: Combine-merge tier (round 13: sort | runs | hash); ``None``
    #: means "whatever the entry's env/heuristic resolves" — pre-r13
    #: lines load as None, so the field is schema-additive.
    merge: str | None = None
    cost_s: float | None = None
    source: str = "probe"          # probe | manual | bench
    probe_dim: int | None = None   # proxy dimension the cost came from
    lanes: tuple = ()
    #: Measurement wall-clock (``time.time()``): the aging policy's
    #: eviction order — records without one age out first.  Excluded
    #: from equality (bookkeeping, not part of the decision).
    ts: float | None = dataclasses.field(default=None, compare=False)

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["lanes"] = [list(x) for x in self.lanes]
        return d

    @staticmethod
    def from_json(d: dict) -> "PlanRecord":
        tier = str(d["tier"])
        if tier not in _TIERS:
            raise ValueError(f"unknown tier {tier!r}")
        disp = d.get("dispatch")
        if disp is not None and disp not in ("auto", "fused", "blocked"):
            # vetted at LOAD time so a schema-valid but hand-mangled
            # line is skipped as invalid, never asserted on at routing
            raise ValueError(f"unknown dispatch {disp!r}")
        merge = d.get("merge")
        if merge is not None and merge not in config.MERGE_TIER_NAMES:
            raise ValueError(f"unknown merge tier {merge!r}")
        br = d.get("block_rows")
        bc = d.get("block_cols")
        return PlanRecord(
            tier=tier,
            block_rows=None if br is None else int(br),
            block_cols=None if bc is None else int(bc),
            ring=bool(d.get("ring", False)),
            pipeline=bool(d.get("pipeline", True)),
            dispatch=d.get("dispatch"),
            mode=d.get("mode"),
            merge=merge,
            cost_s=(
                None if d.get("cost_s") is None else float(d["cost_s"])
            ),
            source=str(d.get("source", "probe")),
            probe_dim=(
                None if d.get("probe_dim") is None
                else int(d["probe_dim"])
            ),
            lanes=tuple(
                (str(k), int(w)) for k, w in d.get("lanes", ())
            ),
            ts=None if d.get("ts") is None else float(d["ts"]),
        )


class PlanStore:
    """Load-once, append-on-write JSONL plan store (threadsafe)."""

    def __init__(self, path: str):
        #: Directory holding ``plans.jsonl``.
        self.path = os.path.abspath(path)
        self.file = os.path.join(self.path, "plans.jsonl")
        self._lock = threading.Lock()
        self._plans: dict[PlanKey, PlanRecord] = {}
        self._hits = 0
        self._misses = 0
        self._invalid = 0
        self._probe_runs = 0
        self._probe_seconds = 0.0
        self._compacted = 0
        self._evicted = 0
        self._load()
        if obs.ENABLED:
            obs.gauge("tuner.store.entries", len(self._plans),
                      dir=self.path)

    # -- persistence -------------------------------------------------------

    def _load(self) -> None:
        try:
            with open(self.file, encoding="utf-8") as f:
                lines = f.readlines()
                # size snapshot of what we actually read: the
                # compaction rewrite below refuses to replace a file
                # another process has appended to since (fleet stores
                # are shared; see _compact)
                self._loaded_size = os.fstat(f.fileno()).st_size
        except OSError:
            return  # no store yet: every lookup is a miss
        valid_lines = 0
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
                if d.get("v") != SCHEMA:
                    raise ValueError(f"schema {d.get('v')!r}")
                key = PlanKey.from_json(d["key"])
                rec = PlanRecord.from_json(d["plan"])
            except (ValueError, KeyError, TypeError):
                # corrupted / truncated / wrong-schema line: count it,
                # skip it, keep loading — the robustness contract
                self._invalid += 1
                if obs.ENABLED:
                    obs.count("tuner.store.invalid")
                continue
            valid_lines += 1
            self._plans[key] = rec  # append-only log: later lines win
        # -- aging (round 11): the append-only log grows one line per
        # superseded plan / refreshed lane set; bound BOTH the loaded
        # set (max-entries cap, oldest-cost eviction) and the file
        # (compaction rewrite of last-wins shadowed lines)
        superseded = valid_lines - len(self._plans)
        evicted = self._evict_to_cap(config.store_max_entries())
        if superseded + evicted >= max(config.store_compact_min(), 1):
            self._compact(superseded + evicted)

    def _evict_to_cap(self, cap: int, protect: "PlanKey | None" = None
                      ) -> int:
        """Drop OLDEST-COST entries (the ``ts`` stamped when the cost
        was measured; records without one age out first, insertion
        order breaking ties) until at most ``cap`` remain.  Load-time
        and put-time callers; counted in ``tuner.store.evicted``.  One
        sort, then prefix deletion — a per-eviction min-scan would be
        O(n * evicted) exactly when a grossly over-cap fleet file is
        what triggered the eviction."""
        cap = max(cap, 1)
        if len(self._plans) <= cap:
            return 0
        order = {k: i for i, k in enumerate(self._plans)}
        victims = sorted(
            (k for k in self._plans if k != protect),
            key=lambda k: ((self._plans[k].ts or 0.0), order[k]),
        )
        n = 0
        for k in victims:
            if len(self._plans) <= cap:
                break
            del self._plans[k]
            n += 1
        if n:
            self._evicted += n
            if obs.ENABLED:
                obs.count("tuner.store.evicted", n)
        return n

    def _lock_file(self) -> str:
        """Sidecar advisory-lock path — the data file itself is
        ``os.replace``d by compaction, so flocking it would pin the
        OLD inode while a sibling locks the new one."""
        return self.file + ".lock"

    def _compact(self, removed_lines: int) -> None:
        """Rewrite ``plans.jsonl`` as exactly the surviving entries
        (insertion order preserved), atomically — a crash mid-rewrite
        leaves either the old or the new file, never a torn one.

        Fleet stores are SHARED (round 17, the multi-process fleet):
        the rewrite runs under an EXCLUSIVE advisory ``fcntl.flock``
        on a sidecar lock file — contention (a sibling compacting)
        SKIPS the compaction outright, and appends take the SHARED
        lock around their single ``write`` (still concurrent with
        each other, excluded only for the microseconds of a rewrite),
        so no append can land inside the stat→replace window and be
        clobbered (the PR 9 caveat, now closed).  A file that grew
        between our load and taking the lock is left alone — losing a
        sibling's fresh measurement to save a few stale lines is the
        wrong trade; the next loader compacts instead."""
        tmp = self.file + ".tmp"
        lf = None
        try:
            os.makedirs(self.path, exist_ok=True)
            lf = os.open(
                self._lock_file(), os.O_CREAT | os.O_RDWR, 0o644
            )
            try:
                fcntl.flock(lf, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                # a sibling holds the lock (compacting or mid-append):
                # skip — compaction is an optimization, never worth
                # waiting on or racing
                if obs.ENABLED:
                    obs.count("tuner.store.compact_skipped")
                return
            if os.path.getsize(self.file) != getattr(
                self, "_loaded_size", -1
            ):
                return  # sibling appended since we read: leave it
            with open(tmp, "w", encoding="utf-8") as f:
                for key, rec in self._plans.items():
                    f.write(json.dumps({
                        "v": SCHEMA, "key": key.to_json(),
                        "plan": rec.to_json(),
                    }) + "\n")
            os.replace(tmp, self.file)
        except OSError:
            # read-only replica: the in-memory view is compact anyway
            if obs.ENABLED:
                obs.count("tuner.store.write_errors")
            return
        finally:
            if lf is not None:
                try:
                    fcntl.flock(lf, fcntl.LOCK_UN)
                except OSError:
                    pass
                os.close(lf)
        self._compacted += removed_lines
        if obs.ENABLED:
            obs.count("tuner.store.compacted", removed_lines)

    def _append(self, key: PlanKey, rec: PlanRecord) -> None:
        line = json.dumps(
            {"v": SCHEMA, "key": key.to_json(), "plan": rec.to_json()}
        ) + "\n"
        lf = None
        try:
            os.makedirs(self.path, exist_ok=True)
            # SHARED flock (concurrent with other appenders — never a
            # queue between them) around ONE O_APPEND write syscall:
            # whole lines under concurrency (the kernel's atomic
            # append seek), and a compaction rewrite (EXCLUSIVE lock)
            # cannot interleave with a FENCED in-flight append.  The
            # lock attempt is NON-BLOCKING with a short bounded retry:
            # appends must never hang on a wedged lock holder (the
            # serving write path cannot afford an unbounded wait) —
            # after the retries the append proceeds UNFENCED, which
            # re-opens only the narrow lost-to-compaction window and
            # only while a sibling holds the lock for far longer than
            # a rewrite takes.  A torn write still only truncates the
            # LAST line, which the loader skips as invalid.
            lf = os.open(
                self._lock_file(), os.O_CREAT | os.O_RDWR, 0o644
            )
            locked = False
            for _ in range(10):
                try:
                    fcntl.flock(lf, fcntl.LOCK_SH | fcntl.LOCK_NB)
                    locked = True
                    break
                except OSError:
                    import time

                    time.sleep(0.005)  # a rewrite lasts ~ms
            if not locked:
                os.close(lf)
                lf = None
                if obs.ENABLED:
                    obs.count("tuner.store.append_unfenced")
            fd = os.open(
                self.file, os.O_APPEND | os.O_CREAT | os.O_WRONLY,
                0o644,
            )
            try:
                os.write(fd, line.encode("utf-8"))
            finally:
                os.close(fd)
        except OSError:
            # read-only replica: the in-memory plan still routes
            if obs.ENABLED:
                obs.count("tuner.store.write_errors")
        finally:
            if lf is not None:
                try:
                    fcntl.flock(lf, fcntl.LOCK_UN)
                except OSError:
                    pass
                os.close(lf)

    # -- lookup / record ---------------------------------------------------

    def lookup(self, key: PlanKey) -> PlanRecord | None:
        with self._lock:
            rec = self._plans.get(key)
            if rec is None:
                self._misses += 1
            else:
                self._hits += 1
        if obs.ENABLED:
            obs.count(
                "tuner.store.misses" if rec is None
                else "tuner.store.hits",
                op=key.op,
            )
        return rec

    def peek(self, key: PlanKey) -> PlanRecord | None:
        """Lookup WITHOUT hit/miss accounting — for store maintenance
        (e.g. a bench deciding whether its measurement beats the
        remembered one), not routing."""
        with self._lock:
            return self._plans.get(key)

    def put(self, key: PlanKey, rec: PlanRecord,
            persist: bool = True) -> None:
        import time

        if rec.ts is None:
            rec.ts = time.time()  # the aging policy's eviction order
        with self._lock:
            self._plans[key] = rec
            # cap holds at put time too (the file keeps the evicted
            # line until the next load-time compaction reclaims it)
            self._evict_to_cap(config.store_max_entries(), protect=key)
        if persist:
            self._append(key, rec)
        if obs.ENABLED:
            obs.gauge("tuner.store.entries", len(self._plans),
                      dir=self.path)

    def add_serve_lane(self, key: PlanKey, kind: str,
                       width: int) -> bool:
        """Merge one (kind, width) into the serve-lane record for
        ``key``; returns True (and persists) iff the lane is new."""
        import time

        lane = (str(kind), int(width))
        with self._lock:
            rec = self._plans.get(key)
            if rec is None:
                rec = PlanRecord(tier="serve", source="serve")
                self._plans[key] = rec
            if lane in rec.lanes:
                return False
            rec.lanes = tuple(sorted(set(rec.lanes) | {lane}))
            rec.ts = time.time()  # an actively-serving graph's lane
            # set stays young under the aging policy
        self._append(key, rec)
        return True

    def serve_lanes(self, key: PlanKey) -> tuple:
        with self._lock:
            rec = self._plans.get(key)
            return rec.lanes if rec is not None else ()

    # -- bookkeeping -------------------------------------------------------

    def record_probe(self, runs: int, seconds: float) -> None:
        with self._lock:
            self._probe_runs += runs
            self._probe_seconds += seconds

    def entries(self) -> int:
        with self._lock:
            return len(self._plans)

    def stats(self) -> dict:
        with self._lock:
            return {
                "path": self.path,
                "entries": len(self._plans),
                "hits": self._hits,
                "misses": self._misses,
                "invalid_lines": self._invalid,
                "probe_runs": self._probe_runs,
                "probe_seconds": round(self._probe_seconds, 4),
                "compacted_lines": self._compacted,
                "evicted": self._evicted,
            }


# -- process-wide store -----------------------------------------------------

_store: PlanStore | None = None
_store_path: str | None = None
_store_lock = threading.Lock()


def get_store() -> PlanStore | None:
    """The process's plan store, or ``None`` when disabled
    (``COMBBLAS_PLAN_STORE=0``).  The dir is re-resolved per call so a
    test's ``monkeypatch.setenv`` takes effect without process-global
    surgery; the loaded instance is cached per resolved path."""
    global _store, _store_path
    path = config.store_dir()
    if path is None:
        return None
    with _store_lock:
        if _store is None or _store_path != path:
            _store = PlanStore(path)
            _store_path = path
        return _store


def _reset_for_tests() -> None:
    """Drop the cached instance so the next ``get_store`` reloads from
    disk (TEST-ONLY: lets a test observe an on-disk mutation or a
    changed env var within one process)."""
    global _store, _store_path
    with _store_lock:
        _store = None
        _store_path = None


# -- key builders -----------------------------------------------------------


def _host_nnz(M) -> int:
    """Total live nnz of a distributed matrix as a host int, memoized
    on the object (the ``coo_has_duplicates`` convention: one D2H sync
    per matrix, ever — the readback is the expensive part on the
    target chip)."""
    cached = getattr(M, "_host_nnz_cache", None)
    if cached is not None:
        return cached
    import jax

    val = int(np.asarray(jax.device_get(M.getnnz())))
    object.__setattr__(M, "_host_nnz_cache", val)
    return val


def plan_key_from_counts(
    sr_name: str,
    m: int, k: int, n: int,
    nnz_a: int, nnz_b: int,
    backend: str,
    grid: str,
    grid3: str = "",
    op: str = "spgemm",
    platform: str | None = None,
) -> PlanKey:
    """The canonical key from host-side counts — benches (which must
    not touch the device to decide) and the matrix-based builder below
    MUST agree, so both funnel through here."""
    if platform is None:
        import jax

        platform = jax.default_backend()
    return PlanKey(
        op=op,
        shape=(shape_bucket(m), shape_bucket(k), shape_bucket(n)),
        band=(density_band(nnz_a, m), density_band(nnz_b, k)),
        sr=sr_name,
        backend=backend,
        grid=grid,
        grid3=grid3,
        platform=platform,
    )


def spgemm_plan_key(sr, A, B, backend: str, grid3=None) -> PlanKey:
    """Plan key for a 2D ``spgemm_auto`` product (one memoized host
    nnz readback per operand)."""
    g3 = (
        f"{grid3.layers}x{grid3.pr}x{grid3.pc}"
        if grid3 is not None else ""
    )
    return plan_key_from_counts(
        sr.name, int(A.nrows), int(A.ncols), int(B.ncols),
        _host_nnz(A), _host_nnz(B) if B is not A else _host_nnz(A),
        backend, f"{A.grid.pr}x{A.grid.pc}", grid3=g3,
    )


def spgemm3d_plan_key(sr, A3, B3, backend: str) -> PlanKey:
    """Plan key for the 3D entry (``mesh3d.spgemm3d``)."""
    g = A3.grid
    return plan_key_from_counts(
        sr.name, int(A3.nrows), int(A3.ncols), int(B3.ncols),
        _host_nnz(A3), _host_nnz(B3) if B3 is not A3 else _host_nnz(A3),
        backend, f"{g.pr}x{g.pc}",
        grid3=f"{g.layers}x{g.pr}x{g.pc}", op="spgemm3d",
    )


def spmm_plan_key(sr, E, feat_width: int,
                  platform: str | None = None) -> PlanKey:
    """Plan key for the batched SpMM lane (round 12): the FEATURE-WIDTH
    bucket rides the key's third shape slot (two products over the same
    graph at F=64 and F=512 can rank the backends differently — the
    MXU contraction amortizes with F, the fold does not), the density
    band comes from the sparse operand only (the feature panel is
    dense by construction, its band carries no information)."""
    if platform is None:
        import jax

        platform = jax.default_backend()
    return PlanKey(
        op="spmm",
        shape=(
            shape_bucket(int(E.nrows)), shape_bucket(int(E.ncols)),
            shape_bucket(int(feat_width)),
        ),
        band=(density_band(_host_nnz(E), int(E.nrows)), 0),
        sr=sr.name,
        backend="",
        grid=f"{E.grid.pr}x{E.grid.pc}",
        platform=platform,
    )


def serve_plan_key(engine) -> PlanKey:
    """Key for a serving engine's warmup-lane record: the graph's shape
    bucket + density band + grid (version-independent — hot-swapped
    same-shape versions keep the same lane set)."""
    v = engine.version
    nnz = max(int(getattr(v, "nnz", -1)), 1)
    return PlanKey(
        op="serve",
        shape=(shape_bucket(int(v.nrows)),
               shape_bucket(int(v.ncols)), 0),
        band=(density_band(nnz, int(v.nrows)), 0),
        sr="",
        backend="",
        grid=f"{engine.grid.pr}x{engine.grid.pc}",
    )
