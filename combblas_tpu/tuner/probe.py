"""Micro-probe pass: MEASURE the admissible SpGEMM rungs on a bounded
downsampled proxy and write the winner into the plan store.

On a plan-store miss (tuner probing enabled, no arg/env override) the
router calls ``probe_spgemm``:

1. **Deterministic, degree-preserving downsample** — the operands'
   host COO maps through a seeded permutation into a pow2 proxy
   rectangle (``COMBBLAS_TUNER_PROBE_MAX_DIM``, default 2048), with
   one axis RESTRICTED and the other FOLDED per operand so the proxy
   keeps the density band the plan key records (see
   ``downsample_coo``).  The same inputs + seed always yield the same
   proxy, so two replicas probing the same miss converge on the same
   plan.
2. **Admissibility at REAL scale** — candidate rungs are gated on the
   REAL shapes (a tier admissible at proxy scale may be structurally
   impossible at production scale, e.g. the mxu envelope), using the
   same predicates as ``choose_spgemm_tier``.
3. **Bounded measurement** — each candidate compiles once (untimed)
   then one timed run; the cumulative timed seconds are capped by
   ``COMBBLAS_TUNER_PROBE_BUDGET_S`` (default 30 s) with the
   heuristic's own choice always measured FIRST, so budget exhaustion
   still yields a measured plan.  Probe cost is obs-visible
   (``tuner.probe.{runs,seconds,winner}``) and recorded in the store's
   host counters either way.

The proxy runs on the SAME grid as the real product (stage collectives
and per-device tile shapes are part of what distinguishes the rungs).
Probing currently covers the 2D ladder; products routed with a
``grid3`` fall back to the heuristic's windowed3d upgrade rule.
"""

from __future__ import annotations

import time

import numpy as np

from .. import obs
from . import config
from .store import PlanRecord, PlanStore, PlanKey


def downsample_coo(
    rows,
    cols,
    dims: tuple[int, int],
    proxy_dims: tuple[int, int],
    seed: int = 0,
    modes: tuple[str, str] = ("restrict", "fold"),
):
    """Deterministically downsample a host COO to a proxy rectangle,
    PRESERVING the density band the plan key records.

    Each axis is mapped through a seeded permutation of its length and
    then either ``"restrict"``-ed (keep ids < proxy dim — drops a
    1/ratio fraction of entries) or ``"fold"``-ed (id mod proxy dim —
    keeps every entry).  Restricting ONE axis and folding the other
    keeps the per-row average degree of the original (restricting both
    would shrink degree by the sampling ratio and measure the rungs at
    the wrong density band — the scan/windowed ranking flips with
    density, r7 data).  The probe uses ``("restrict", "fold")`` for A
    and ``("fold", "restrict")`` for B, so the shared k axis carries
    the SAME permutation+fold on both operands (same (length, seed)
    pair → same permutation) and A·B stays structurally consistent.
    Pure function of (inputs, seed): the determinism contract."""
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    keep = np.ones(len(rows), bool)
    out = []
    for x, dim, pdim, mode in (
        (rows, dims[0], proxy_dims[0], modes[0]),
        (cols, dims[1], proxy_dims[1], modes[1]),
    ):
        mapped = _axis_perm(dim, seed)[x]
        if mode == "restrict":
            keep &= mapped < pdim
        else:
            assert mode == "fold", mode
            mapped = mapped % pdim
        out.append(mapped)
    return (
        out[0][keep].astype(np.int64),
        out[1][keep].astype(np.int64),
        keep,
    )


def _dedup_sum(r, c, v, ncols: int):
    """Host sum-combine of duplicate (row, col) proxy entries."""
    key = r.astype(np.int64) * np.int64(ncols) + c
    uniq, inv = np.unique(key, return_inverse=True)
    vv = np.zeros(len(uniq), np.asarray(v).dtype)
    np.add.at(vv, inv, np.asarray(v))
    return (
        (uniq // ncols).astype(np.int64),
        (uniq % ncols).astype(np.int64),
        vv,
    )


def _axis_perm(length: int, seed: int) -> np.ndarray:
    """One seeded permutation per (axis length, seed): shared axes (the
    k dimension of A·B, or all three axes of A²) map identically."""
    rng = np.random.default_rng(np.uint64(seed * 1_000_003 + length))
    return rng.permutation(int(length))


def _proxy_dim(dim: int, max_dim: int) -> int:
    """Pow2 proxy dimension: probe compiles land in a handful of fixed
    shapes shared across keys.  Never exceeds ``max_dim`` — when the
    pow2 ceiling would overshoot a non-pow2 cap, round DOWN instead
    (the operator's probe budget is a bound, not a suggestion)."""
    d = min(int(dim), int(max_dim))
    p = 1 << max(d - 1, 1).bit_length()
    if p > max_dim:
        p >>= 1
    return max(p, 2)


def admissible_tiers(sr, A, B, backend: str) -> list[str]:
    """Candidate rungs for the probe, gated at REAL scale with the
    router's own predicates; the heuristic's choice is listed FIRST
    (it is measured even when the budget runs out after one rung)."""
    from ..ops.spgemm import scatter_combine_for
    from ..parallel import spgemm as sp

    cands = []
    max_dim = max(A.local_rows, A.local_cols, B.local_cols)
    cells = A.local_rows * B.local_cols
    if (
        max_dim <= sp.MXU_MAX_TILE_DIM
        and sr.name in sp._PALLAS_KINDS
        and not (
            sp.coo_has_duplicates(A)
            or (B is not A and sp.coo_has_duplicates(B))
        )
    ):
        cands.append("mxu")
    if (
        scatter_combine_for(sr) is not None
        and cells <= sp.WINDOWED_MAX_TILE_CELLS
        and (
            backend == "scatter"
            or (
                sr.name in sp._PALLAS_KINDS
                and sp.dot_panel_feasible(B.local_rows, B.local_cols)
            )
        )
    ):
        cands.append("windowed")
    cands.append("scan")
    heur = sp._choose_spgemm_tier_2d(
        sr, A, B, backend=backend, assume_unique=True
    )
    if heur in cands:
        cands.remove(heur)
        cands.insert(0, heur)
    return cands


def _default_measure(fn) -> float:
    """Wall-time one warm run (the closure compiles untimed before)."""
    import jax

    t0 = time.perf_counter()
    out = fn()
    jax.block_until_ready(out.vals)
    return time.perf_counter() - t0


def probe_spgemm(
    sr,
    A,
    B,
    *,
    backend: str,
    store: PlanStore | None = None,
    key: PlanKey | None = None,
    budget_s: float | None = None,
    max_dim: int | None = None,
    seed: int = 0,
    host_coo_a=None,
    host_coo_b=None,
    measure=None,
    tier_order=None,
    geometry: bool = True,
) -> PlanRecord | None:
    """Measure the admissible rungs on the downsampled proxy; return
    the winning :class:`PlanRecord` (and persist it into ``store``
    under ``key`` when both are given), or ``None`` when no
    measurement was possible (empty proxy) — the caller then falls
    back to the heuristic.

    ``host_coo_a``/``host_coo_b`` ((rows, cols, vals) host arrays) skip
    the operand readback for callers that still hold the construction
    COO (benches: the axon D2H rule).  ``measure`` injects the cost
    functional (tests use a deterministic fake; default wall time);
    ``tier_order`` overrides the admissibility-gated candidate list
    and ``geometry=False`` skips the windowed block-shape sweep (both
    for deterministic tests — production callers leave the defaults)."""
    from ..parallel.spmat import SpParMat

    budget_s = config.probe_budget_s() if budget_s is None else budget_s
    max_dim = config.probe_max_dim() if max_dim is None else max_dim
    measure = _default_measure if measure is None else measure

    def host_coo(M, given):
        if given is not None:
            return given
        return M.to_global_coo()

    ra, ca, va = host_coo(A, host_coo_a)
    pm = _proxy_dim(A.nrows, max_dim)
    pk = _proxy_dim(A.ncols, max_dim)
    pn = _proxy_dim(B.ncols, max_dim)
    # degree-preserving split: A restricts rows / folds cols, B folds
    # rows / restricts cols — both operands keep the density band their
    # plan key records, and the shared k axis folds identically
    par, pac, keep_a = downsample_coo(
        ra, ca, (A.nrows, A.ncols), (pm, pk), seed=seed,
        modes=("restrict", "fold"),
    )
    rb, cb, vb = (ra, ca, va) if (B is A and host_coo_b is None) \
        else host_coo(B, host_coo_b)
    pbr, pbc, keep_b = downsample_coo(
        rb, cb, (B.nrows, B.ncols), (pk, pn), seed=seed,
        modes=("fold", "restrict"),
    )
    if len(par) == 0 or len(pbr) == 0:
        return None  # degenerate proxy: nothing to measure
    grid = A.grid
    # folding can alias two source entries onto one proxy cell — dedup
    # (sum-combine) so the mxu candidate's unique-entries precondition
    # holds on the proxy exactly as on a compacted production input
    pA = SpParMat.from_global_coo(
        grid, *_dedup_sum(par, pac, np.asarray(va)[keep_a], pk), pm, pk
    )
    pB = SpParMat.from_global_coo(
        grid, *_dedup_sum(pbr, pbc, np.asarray(vb)[keep_b], pn), pk, pn
    )

    from ..parallel.spgemm import spgemm_auto

    cands = (
        list(tier_order) if tier_order is not None
        else admissible_tiers(sr, A, B, backend)
    )
    costs: dict[str, float] = {}
    spent = 0.0
    runs = 0
    with obs.span("tuner.probe", sr=sr.name, dim=pm):
        for tier in cands:
            if costs and spent >= budget_s:
                if obs.ENABLED:
                    obs.count("tuner.probe.budget_exhausted")
                break

            def run(tier=tier):
                return spgemm_auto(
                    sr, pA, pB, tier=tier, backend=backend,
                    assume_unique=(tier != "mxu"),
                )

            try:
                run()  # compile + warm (untimed)
                dt = float(measure(run))
            except Exception:
                # a rung that faults on the proxy is simply not a
                # candidate (never let probing take the caller down)
                if obs.ENABLED:
                    obs.count("tuner.probe.errors", tier=tier)
                continue
            costs[tier] = dt
            spent += dt
            runs += 1
            if obs.ENABLED:
                obs.count("tuner.probe.runs", tier=tier)
    if store is not None:
        store.record_probe(runs, spent)
    if obs.ENABLED:
        obs.count("tuner.probe.seconds", spent)
    if not costs:
        return None
    winner = min(costs, key=costs.get)
    if obs.ENABLED:
        obs.count("tuner.probe.winner", tier=winner)
    # -- window-geometry sweep (round 12, ROADMAP follow-up): the tier
    # probe measured the WINDOWED rung at its default block geometry;
    # when windowed won and budget remains, sweep a small block_rows /
    # block_cols grid on the same proxy and persist the winning
    # geometry WITH the plan (before this, geometry was recordable only
    # via BENCH_PLAN_RECORD=1).  Proxy-scale geometry transfers as a
    # measured hint — a bench-recorded real-scale plan (source="bench")
    # overwrites it on the next record.
    best_geo = (None, None)
    if geometry and winner == "windowed" and spent < budget_s:
        best_cost = costs[winner]
        geo_runs, geo_spent = 0, 0.0
        geo_cands = _geometry_candidates(pm, pn)
        with obs.span("tuner.probe.geometry", dim=pm):
            for br, bc in geo_cands:
                if spent + geo_spent >= budget_s:
                    if obs.ENABLED:
                        obs.count("tuner.probe.budget_exhausted")
                    break

                def run_geo(br=br, bc=bc):
                    return spgemm_auto(
                        sr, pA, pB, tier="windowed", backend=backend,
                        block_rows=br, block_cols=bc,
                        assume_unique=True,
                    )

                try:
                    run_geo()  # compile + warm (untimed)
                    dt = float(measure(run_geo))
                except Exception:
                    if obs.ENABLED:
                        obs.count("tuner.probe.errors", tier="windowed")
                    continue
                geo_spent += dt
                geo_runs += 1
                if obs.ENABLED:
                    obs.count("tuner.probe.geometry_runs")
                if dt < best_cost:
                    best_cost, best_geo = dt, (br, bc)
        if store is not None:
            store.record_probe(geo_runs, geo_spent)
        if obs.ENABLED and geo_spent:
            obs.count("tuner.probe.seconds", geo_spent)
        costs[winner] = best_cost
        if best_geo != (None, None):
            # the candidates are FRACTIONS of the proxy dims; persist
            # them rescaled to the REAL dims the plan key describes —
            # replaying a proxy-absolute block size at production
            # scale would mint thousands of tiny windows (when the
            # proxy wasn't downsampled the factor is 1: the exact
            # measured geometry ships)
            sm = -(-int(A.nrows) // pm)
            sn = -(-int(B.ncols) // pn)
            br, bc = best_geo
            best_geo = (
                None if br is None else int(br) * sm,
                None if bc is None else int(bc) * sn,
            )
    rec = PlanRecord(
        tier=winner, cost_s=costs[winner], source="probe",
        probe_dim=pm,
        block_rows=best_geo[0], block_cols=best_geo[1],
    )
    if store is not None and key is not None:
        store.put(key, rec)
    return rec


def _geometry_candidates(pm: int, pn: int) -> list[tuple]:
    """Bounded non-default block-geometry grid for the windowed sweep:
    a handful of pow2 fractions of the proxy dims (the kernel default
    was already measured by the tier pass), deduped and capped at FOUR
    so the sweep stays a small multiple of one tier measurement —
    every candidate is one real compile on the proxy."""
    brs = sorted({max(pm // 8, 16), max(pm // 2, 32)})
    bcs = [None, max(pn // 4, 16)]
    cands = [(br, bc) for br in brs for bc in bcs]
    seen, out = set(), []
    for g in cands:
        if g not in seen and g != (None, None):
            seen.add(g)
            out.append(g)
    return out[:4]


def probe_spgemm3d(
    sr,
    A3,
    B3,
    *,
    store: PlanStore | None = None,
    key: PlanKey | None = None,
    budget_s: float | None = None,
    measure=None,
    candidates=None,
) -> PlanRecord | None:
    """Measure admissible (tier, merge) pairs of the 3D entry ON THE
    REAL OPERANDS and return / persist the winner — the op="spgemm3d"
    micro-probe (round 13; before it the 3D entry had no probe pass
    and store records could only be bench-seeded).

    Like ``probe_spmm`` there is no downsampled proxy: a 3D probe run
    is a warm run of a kernel the caller was about to run anyway, the
    candidate list is small (≤ 5), and the pass is opt-in
    (``COMBBLAS_TUNER_PROBE=1``) and budget-bounded with the
    heuristic's own choice (esc + its default merge) measured FIRST,
    so exhaustion still yields a measured plan.  The sweep covers the
    merge knob — the fiber reduce's combine tier is exactly what the
    CPU-mesh schedule measurement can rank (sort work is local) —
    and persists the winner's ``merge`` in the plan record."""
    import jax

    from ..ops.spgemm import scatter_combine_for
    from ..parallel import mesh3d

    budget_s = config.probe_budget_s() if budget_s is None else budget_s

    if candidates is None:
        # heuristic first (esc with its own merge resolution), then the
        # merge alternates, then the windowed tier with ITS heuristic
        # merge + the sort control — ≤ 5 real-scale runs, each one a
        # kernel the caller could legitimately route to
        candidates = [("esc", None), ("esc", "runs")]
        if scatter_combine_for(sr) is not None:
            candidates += [
                ("windowed", None), ("windowed", "sort"),
            ]
            if A3.grid.layers >= 2:
                candidates.append(("windowed", "hash"))
        # a fleet-wide COMBBLAS_SPGEMM_MERGE makes the None-merge
        # candidates resolve to the env value — dedupe so the budget
        # never times the IDENTICAL kernel twice (and noise never
        # picks between two equal entries)
        env_merge = config.env_merge()
        if env_merge is not None:
            seen, uniq = set(), []
            for tier, mg in candidates:
                eff = (tier, mg if mg is not None else env_merge)
                if eff not in seen:
                    seen.add(eff)
                    uniq.append((tier, mg))
            candidates = uniq

    def _measure_default(fn) -> float:
        t0 = time.perf_counter()
        out = fn()
        jax.block_until_ready(out.vals)
        return time.perf_counter() - t0

    measure = _measure_default if measure is None else measure
    costs: dict[tuple, float] = {}
    spent = 0.0
    runs = 0
    with obs.span(
        "tuner.probe", sr=sr.name, dim=int(A3.nrows), op="spgemm3d"
    ):
        for tier, merge in candidates:
            if costs and spent >= budget_s:
                if obs.ENABLED:
                    obs.count("tuner.probe.budget_exhausted")
                break

            def run(tier=tier, merge=merge):
                return mesh3d.spgemm3d(sr, A3, B3, tier=tier,
                                       merge=merge)

            try:
                run()  # compile + warm (untimed)
                dt = float(measure(run))
            except Exception:
                if obs.ENABLED:
                    obs.count("tuner.probe.errors", tier=tier)
                continue
            costs[(tier, merge)] = dt
            spent += dt
            runs += 1
            if obs.ENABLED:
                obs.count("tuner.probe.runs", tier=tier)
    if store is not None:
        store.record_probe(runs, spent)
    if obs.ENABLED:
        obs.count("tuner.probe.seconds", spent)
    if not costs:
        return None
    winner = min(costs, key=costs.get)
    if obs.ENABLED:
        obs.count("tuner.probe.winner", tier=winner[0])
    rec = PlanRecord(
        tier=winner[0], merge=winner[1], cost_s=costs[winner],
        source="probe", probe_dim=int(A3.nrows),
    )
    if store is not None and key is not None:
        store.put(key, rec)
    return rec


def probe_spmm(
    sr,
    E,
    X,
    *,
    store: PlanStore | None = None,
    key: PlanKey | None = None,
    budget_s: float | None = None,
    measure=None,
) -> PlanRecord | None:
    """Measure the admissible SpMM backends ON THE REAL OPERANDS and
    return / persist the winner (the op="spmm" micro-probe).

    Unlike the SpGEMM probe there is no downsampled proxy: an SpMM
    probe is at most two warm runs of a kernel the caller was about to
    run anyway (the candidate set is {mxu_gather, scatter} for
    plus_times, a single backend otherwise — in which case there is
    nothing to measure and ``None`` is returned).  The heuristic's
    choice is measured FIRST so budget exhaustion still yields a
    measured plan; cost is obs-visible under the same
    ``tuner.probe.*`` counters as the SpGEMM pass."""
    from ..parallel import spmm as spmm_mod

    cands = list(spmm_mod.admissible_spmm_backends(sr))
    if len(cands) < 2:
        return None
    heur = spmm_mod.spmm_backend_heuristic(sr)
    if heur in cands:
        cands.remove(heur)
        cands.insert(0, heur)
    budget_s = config.probe_budget_s() if budget_s is None else budget_s

    def _measure_default(fn) -> float:
        import jax

        t0 = time.perf_counter()
        out = fn()
        jax.block_until_ready(out.blocks)
        return time.perf_counter() - t0

    measure = _measure_default if measure is None else measure
    costs: dict[str, float] = {}
    spent = 0.0
    runs = 0
    with obs.span("tuner.probe", sr=sr.name, dim=int(E.nrows), op="spmm"):
        for backend in cands:
            if costs and spent >= budget_s:
                if obs.ENABLED:
                    obs.count("tuner.probe.budget_exhausted")
                break

            def run(backend=backend):
                return spmm_mod.dist_spmm_ell(sr, E, X, backend=backend)

            try:
                run()  # compile + warm (untimed)
                dt = float(measure(run))
            except Exception:
                if obs.ENABLED:
                    obs.count("tuner.probe.errors", tier=backend)
                continue
            costs[backend] = dt
            spent += dt
            runs += 1
            if obs.ENABLED:
                obs.count("tuner.probe.runs", tier=backend)
    if store is not None:
        store.record_probe(runs, spent)
    if obs.ENABLED:
        obs.count("tuner.probe.seconds", spent)
    if not costs:
        return None
    winner = min(costs, key=costs.get)
    if obs.ENABLED:
        obs.count("tuner.probe.winner", tier=winner)
    rec = PlanRecord(
        tier=winner, cost_s=costs[winner], source="probe",
        probe_dim=int(E.nrows),
    )
    if store is not None and key is not None:
        store.put(key, rec)
    return rec
