"""The ONE place the ``COMBBLAS_SPGEMM_*`` / tuner knobs are parsed.

Before round 10 the env parsing was scattered: ``spgemm_auto`` read
``COMBBLAS_SPGEMM_TIER`` / ``_BLOCK_ROWS`` / ``_BLOCK_COLS`` inline,
``resolve_spgemm_backend`` read ``COMBBLAS_SPGEMM_BACKEND``,
``mesh3d.spgemm3d`` read ``COMBBLAS_SPGEMM3D_TIER``, and every bench
re-implemented the same ``or None`` / ``"0" means default`` conventions.
This module centralizes the parsing so the tuner, the router, and the
benches all read identical semantics.

Resolution precedence (documented ONCE, here):

    explicit argument  >  plan store  >  env var  >  heuristic

* **argument** — a caller passing ``tier=`` / ``backend=`` /
  ``block_rows=`` etc. always wins (tests and forced benches).
* **plan store** — a measured plan persisted by the micro-probe pass
  (``combblas_tpu.tuner.store``); this is what makes tier choice
  reproducible across processes.  Disable with ``COMBBLAS_PLAN_STORE=0``.
* **env var** — the classic fleet-wide override knobs below.
* **heuristic** — ``choose_spgemm_tier``'s hand-tuned ladder, the
  fallback when nothing above decided.  The opt-in micro-probe pass
  (``COMBBLAS_TUNER_PROBE=1``) runs at this point — on a store miss
  with no arg/env override it MEASURES the admissible rungs and writes
  the winner back, so the heuristic is consulted only when probing is
  disabled or over budget.

Env-var conventions shared by every knob: unset or empty means
"default"; for the integer knobs ``"0"`` also means default (the bench
convention since round 6).
"""

from __future__ import annotations

import os

#: SpGEMM routing / geometry knobs (round-6/7/9 compatible names).
ENV_TIER = "COMBBLAS_SPGEMM_TIER"
ENV_BACKEND = "COMBBLAS_SPGEMM_BACKEND"
ENV_BLOCK_ROWS = "COMBBLAS_SPGEMM_BLOCK_ROWS"
ENV_BLOCK_COLS = "COMBBLAS_SPGEMM_BLOCK_COLS"
ENV_TIER3D = "COMBBLAS_SPGEMM3D_TIER"
#: Windowed multi-device dispatch: fused | blocked | auto (default).
ENV_DISPATCH = "COMBBLAS_SPGEMM_DISPATCH"
#: Pow2-bucket the per-block plan capacities ("0" disables).
ENV_BUCKET_CAPS = "COMBBLAS_SPGEMM_BUCKET_CAPS"

#: Plan-store knobs (round 10).
ENV_PLAN_STORE = "COMBBLAS_PLAN_STORE"      # dir | "0"/"off" disables
ENV_PROBE = "COMBBLAS_TUNER_PROBE"          # "1" enables the probe pass
ENV_PROBE_BUDGET = "COMBBLAS_TUNER_PROBE_BUDGET_S"
ENV_PROBE_MAX_DIM = "COMBBLAS_TUNER_PROBE_MAX_DIM"

#: Plan-store aging knobs (round 11): long-lived fleet stores grow one
#: appended line per superseded plan and one per new serve lane; these
#: bound the file and the loaded set.
ENV_STORE_MAX = "COMBBLAS_PLAN_STORE_MAX"             # entries cap
ENV_STORE_COMPACT = "COMBBLAS_PLAN_STORE_COMPACT_MIN"  # superseded-line
#                                                     # rewrite trigger

#: Dynamic-graph mutation knobs (round 11, docs/dynamic.md).
ENV_DYNAMIC_SPILL = "COMBBLAS_DYNAMIC_SPILL_FRAC"

#: Round-12 knobs: the batched-SpMM backend override (the op="spmm"
#: analog of COMBBLAS_SPGEMM_TIER) and headroom-aware bucket sizing —
#: the slack fraction of padding slots every ELL bucket class reserves
#: at build so high-churn dynamic graphs re-bucket instead of spilling
#: (docs/dynamic.md; counter ``dynamic.merge.headroom_used``).
ENV_SPMM_BACKEND = "COMBBLAS_SPMM_BACKEND"
ENV_DYNAMIC_HEADROOM = "COMBBLAS_DYNAMIC_HEADROOM"

#: Round-14 knobs: the multi-tenant engine pool and the replicated
#: serving fleet (docs/serving.md "Multi-tenant pool & fleet").
#: ``COMBBLAS_POOL_BYTE_BUDGET`` bounds the pool's resident DEVICE
#: bytes (LRU eviction past it; 0/unset = unbounded),
#: ``COMBBLAS_POOL_QUANTUM`` is the weighted-fair-queueing deficit
#: quantum (requests granted per round per unit weight), and
#: ``COMBBLAS_FLEET_REPLICAS`` the default ``FleetRouter.build``
#: replica count.
ENV_POOL_BYTE_BUDGET = "COMBBLAS_POOL_BYTE_BUDGET"
ENV_POOL_QUANTUM = "COMBBLAS_POOL_QUANTUM"
ENV_FLEET_REPLICAS = "COMBBLAS_FLEET_REPLICAS"

#: Round-15 knob: deterministic per-request trace sampling rate for the
#: serve path (``obs/trace.py``).  A request is traced iff obs is
#: enabled AND ``crc32(request id) mod 1e6 < rate * 1e6`` — same ids +
#: same rate = same sampled set on every replica.  Unset/empty/0 = no
#: tracing (the zero-cost default).
ENV_OBS_TRACE_SAMPLE = "COMBBLAS_OBS_TRACE_SAMPLE"

#: Round-16 knobs: the serve durability layer (docs/serving.md
#: "Durability & self-healing").  ``COMBBLAS_WAL`` names the directory
#: holding the write-ahead log and its checkpoints (unset/0/off = no
#: durability — the zero-cost default: one attribute read per write);
#: ``COMBBLAS_WAL_FSYNC`` the append fsync policy (``always`` — every
#: acknowledged write is on disk before its future exists — or ``off``,
#: the OS-buffered throughput mode); ``COMBBLAS_CHECKPOINT_EVERY`` the
#: merge count between automatic background snapshots;
#: ``COMBBLAS_CHECKPOINT_RETAIN`` how many snapshots are retained (the
#: corrupt-snapshot fallback depth).
ENV_WAL = "COMBBLAS_WAL"
ENV_WAL_FSYNC = "COMBBLAS_WAL_FSYNC"
ENV_CHECKPOINT_EVERY = "COMBBLAS_CHECKPOINT_EVERY"
ENV_CHECKPOINT_RETAIN = "COMBBLAS_CHECKPOINT_RETAIN"

#: Valid WAL fsync policies (vetted at the knob, the MERGE precedent).
WAL_FSYNC_POLICIES = ("always", "off")

#: Round-18 knobs: the fleet observability plane (docs/observability.md
#: "Process-fleet observability").  ``COMBBLAS_FLEETLOG`` overrides the
#: supervision-timeline JSONL path the process fleet appends to
#: (default: ``fleetlog.jsonl`` under the fleet's workdir; unset/``0``/
#: ``off`` fall through to that default).  ``COMBBLAS_OBS_HB_METRICS_S``
#: is the minimum seconds between child registry snapshots piggybacked
#: on replica heartbeats (metrics federation — the fleet-scrape wire
#: cadence; unset/``0`` = default).
ENV_FLEETLOG = "COMBBLAS_FLEETLOG"
ENV_OBS_HB_METRICS_S = "COMBBLAS_OBS_HB_METRICS_S"

#: Round-19 knobs: the network front door (docs/serving.md "Network
#: front door").  ``COMBBLAS_NET_PORT`` is the TCP listen port
#: (unset/``0`` = OS-assigned ephemeral — read the bound port back
#: from ``NetFrontend.port``); ``COMBBLAS_NET_MAX_CONNS`` caps open
#: connections (past it a hello gets a typed ``backpressure`` wire
#: reply, never a silent close); ``COMBBLAS_NET_ACCEPT_BACKLOG`` is
#: the kernel ``listen()`` queue depth.  The ``BENCH_NET_*`` knobs
#: parameterize the open-loop load generator
#: (``serve/net/loadgen.py``): target arrival rate (req/s),
#: concurrent connections, and run length — parsed HERE (not inline
#: in the bench) so the vetting and "0 means default" semantics match
#: every other knob.
ENV_NET_PORT = "COMBBLAS_NET_PORT"
ENV_NET_MAX_CONNS = "COMBBLAS_NET_MAX_CONNS"
ENV_NET_ACCEPT_BACKLOG = "COMBBLAS_NET_ACCEPT_BACKLOG"
ENV_BENCH_NET_RATE = "BENCH_NET_RATE"
ENV_BENCH_NET_CONNS = "BENCH_NET_CONNS"
ENV_BENCH_NET_SECONDS = "BENCH_NET_SECONDS"

#: Round-21 knobs: the sharded hop wire protocol (docs/serving.md
#: "Sharded hop wire protocol").  ``COMBBLAS_SHARD_FRONTIER`` picks
#: the frontier encoding the router stamps on each bulk-synchronous
#: hop: ``sparse`` (COO triples of the live frontier), ``dense`` (the
#: r20 ``[n, W]`` operand), or ``auto`` (sparse until the frontier
#: crosses the density threshold, then dense per hop — the diropt
#: regime switch applied at the wire).  ``COMBBLAS_SHARD_DENSITY`` is
#: that threshold as a frontier-nnz fraction of ``n*W`` (auto mode
#: only).  ``COMBBLAS_SHARD_WIRE`` opts propagate's inherently-dense
#: ``q`` into bf16 wire encoding (``f32`` | ``bf16``; the router
#: obs-tracks the quantization error as
#: ``serve.shard.wire_quant_err``).
ENV_SHARD_FRONTIER = "COMBBLAS_SHARD_FRONTIER"
ENV_SHARD_DENSITY = "COMBBLAS_SHARD_DENSITY"
ENV_SHARD_WIRE = "COMBBLAS_SHARD_WIRE"

#: Valid sharded frontier encodings / wire dtypes (vetted at the knob,
#: the MERGE/WAL_FSYNC precedent).
SHARD_FRONTIER_MODES = ("auto", "sparse", "dense")
SHARD_WIRE_MODES = ("f32", "bf16")

#: Round-13 knob: the SpGEMM combine-merge tier (sort | runs | hash) —
#: how partial-product pieces (3D fiber pieces, 2D ESC stage chunks)
#: fold into one compacted tile.  Resolution: arg > plan-store record
#: > this env > the L/collision heuristic (docs/spgemm.md "merge
#: tiers").
ENV_MERGE = "COMBBLAS_SPGEMM_MERGE"

#: Valid merge-tier names (parallel/mesh3d re-exports this as
#: MERGE_TIERS — one definition, vetting and kernel asserts agree).
MERGE_TIER_NAMES = ("sort", "runs", "hash")

#: Default probe budget: total measured seconds across all candidate
#: rungs for ONE store miss (compiles excluded from the budget check
#: only insofar as the first candidate always completes).
DEFAULT_PROBE_BUDGET_S = 30.0
#: Proxy dimension cap for the downsampled probe operands.
DEFAULT_PROBE_MAX_DIM = 2048
#: Plan-store entry cap (oldest-cost eviction past it) and the
#: superseded-line count that triggers a load-time compaction rewrite.
DEFAULT_STORE_MAX_ENTRIES = 4096
DEFAULT_STORE_COMPACT_MIN = 32
#: Structural-change fraction above which ``dynamic.apply_delta``
#: spills to a full rebuild (the incremental path's amortization bound).
DEFAULT_DYNAMIC_SPILL_FRAC = 0.10
#: Default bucket-slot headroom: none (static graphs pay no padding
#: tax; dynamic engines opt in via from_coo(headroom=) or the env).
DEFAULT_DYNAMIC_HEADROOM = 0.0
#: Pool defaults (round 14): unbounded resident bytes (an operator
#: opts into eviction by setting a budget) and a 16-request WFQ
#: quantum per unit weight per round.
DEFAULT_POOL_BYTE_BUDGET = 0
DEFAULT_POOL_QUANTUM = 16
DEFAULT_FLEET_REPLICAS = 2
#: Durability defaults (round 16): fsync every acknowledged write
#: (durability-first; ``off`` is the opt-out), snapshot every 8 merges,
#: retain 2 snapshots (current + the corrupt-fallback predecessor).
DEFAULT_WAL_FSYNC = "always"
DEFAULT_CHECKPOINT_EVERY = 8
DEFAULT_CHECKPOINT_RETAIN = 2
#: Federation default (round 18): snapshot the child registry onto the
#: heartbeat at most once a second — fresh enough for scrape cadences,
#: cheap enough to vanish in the heartbeat noise.
DEFAULT_OBS_HB_METRICS_S = 1.0
#: Net front-door defaults (round 19): ephemeral port, 512 connection
#: slots (a thread apiece — thread-per-connection's practical ceiling,
#: not a protocol limit), a 128-deep kernel accept queue.
DEFAULT_NET_PORT = 0
DEFAULT_NET_MAX_CONNS = 512
DEFAULT_NET_ACCEPT_BACKLOG = 128
#: Open-loop load-generator defaults (round 19): 200 req/s offered
#: over 128 connections for 8 seconds — small enough for a laptop,
#: large enough that coordinated omission would be visible if the
#: harness had it.
DEFAULT_BENCH_NET_RATE = 200.0
DEFAULT_BENCH_NET_CONNS = 128
DEFAULT_BENCH_NET_SECONDS = 8.0
#: Sharded-wire defaults (round 21): adaptive frontier encoding with
#: dense fallback once the live frontier fills a quarter of the
#: ``[n, W]`` operand (past ~0.25 the per-entry triple overhead —
#: 5-9 B vs 4 B — plus scatter work loses to the dense memcpy), and
#: f32 on the wire (bf16 is the explicit opt-in: it halves propagate's
#: hop bytes but trades bit-exactness for allclose).
DEFAULT_SHARD_FRONTIER = "auto"
DEFAULT_SHARD_DENSITY = 0.25
DEFAULT_SHARD_WIRE = "f32"


def _str_env(name: str) -> str | None:
    v = os.environ.get(name)
    return v if v else None


def _int_env(name: str) -> int | None:
    """Unset, empty, and "0" all mean "use the default" (the bench
    knob convention: BENCH_BLOCK_ROWS=0 falls through)."""
    v = os.environ.get(name)
    if not v:
        return None
    return int(v) or None


def env_tier() -> str | None:
    return _str_env(ENV_TIER)


def env_backend() -> str | None:
    return _str_env(ENV_BACKEND)


def env_block_rows() -> int | None:
    return _int_env(ENV_BLOCK_ROWS)


def env_block_cols() -> int | None:
    return _int_env(ENV_BLOCK_COLS)


def env_tier3d() -> str | None:
    return _str_env(ENV_TIER3D)


def env_dispatch() -> str | None:
    return _str_env(ENV_DISPATCH)


def bucket_caps_enabled() -> bool:
    """Pow2 cap bucketing is ON by default: it is what lets per-block
    building-block programs share compiles across blocks and across
    products inside one shape bucket (the bounded first-touch-compile
    half of round 10)."""
    return os.environ.get(ENV_BUCKET_CAPS, "1") not in ("", "0")


def resolve_dispatch(dispatch: str | None = None) -> str:
    """Windowed-tier dispatch: argument > env > ``"auto"``.

    ``auto`` routes multi-device scatter products with more than one
    occupied row block through the BLOCKED building-block dispatch
    (``summa_spgemm_windowed_blocked``) so no single XLA compile scales
    with the whole product; ``fused`` forces the one-graph kernel (the
    carousel/ring schedules live there); ``blocked`` forces per-block
    programs."""
    if dispatch is None:
        dispatch = env_dispatch()
    if dispatch is None:
        dispatch = "auto"
    assert dispatch in ("auto", "fused", "blocked"), dispatch
    return dispatch


def store_dir() -> str | None:
    """The plan-store directory, or ``None`` when the store is disabled.

    ``COMBBLAS_PLAN_STORE``: a path uses that dir; ``0``/``off``
    disables the store entirely.  Unset: the sibling of the XLA compile
    cache dir (``utils/compile_cache.py`` — ``.plan_store`` next to
    ``.jax_cache``), so a fleet that ships its compile cache ships its
    plans with the same rsync."""
    v = os.environ.get(ENV_PLAN_STORE)
    if v is not None:
        if v.strip().lower() in ("", "0", "off", "none"):
            return None
        return os.path.abspath(v)
    from ..utils import compile_cache

    return compile_cache.plan_store_dir()


def probe_enabled() -> bool:
    return os.environ.get(ENV_PROBE, "0") not in ("", "0")


def probe_budget_s() -> float:
    v = os.environ.get(ENV_PROBE_BUDGET)
    return float(v) if v else DEFAULT_PROBE_BUDGET_S


def probe_max_dim() -> int:
    v = os.environ.get(ENV_PROBE_MAX_DIM)
    return int(v) if v else DEFAULT_PROBE_MAX_DIM


def store_max_entries() -> int:
    """Plan-store entry cap: past it the loader evicts oldest-cost
    entries (``tuner.store.evicted``).  ``0``/unset = the default."""
    v = _int_env(ENV_STORE_MAX)
    return DEFAULT_STORE_MAX_ENTRIES if v is None else v


def store_compact_min() -> int:
    """Superseded (last-wins-shadowed) line count that triggers the
    load-time compaction rewrite (``tuner.store.compacted``)."""
    v = _int_env(ENV_STORE_COMPACT)
    return DEFAULT_STORE_COMPACT_MIN if v is None else v


def env_merge() -> str | None:
    """Fleet-wide SpGEMM merge-tier override (round 13).  A bogus
    value raises here — naming the knob — instead of surfacing as a
    bare kernel assert deep in a shard_map body (the round-12
    SPMM_BACKEND vetting precedent)."""
    v = _str_env(ENV_MERGE)
    if v is not None and v not in MERGE_TIER_NAMES:
        raise ValueError(
            f"{ENV_MERGE} must be one of {'|'.join(MERGE_TIER_NAMES)}; "
            f"got {v!r}"
        )
    return v


def env_spmm_backend() -> str | None:
    """Fleet-wide SpMM backend override (``mxu_gather``/``scatter``) —
    the op="spmm" rung ``tuner.resolve.resolve_tier`` walks."""
    return _str_env(ENV_SPMM_BACKEND)


def dynamic_headroom(given: float | None = None) -> float:
    """Bucket-slot headroom fraction: explicit argument >
    ``COMBBLAS_DYNAMIC_HEADROOM`` > 0.  Clamped to >= 0 (a negative
    headroom would under-allocate the real rows)."""
    if given is not None:
        return max(float(given), 0.0)
    v = os.environ.get(ENV_DYNAMIC_HEADROOM)
    return max(float(v), 0.0) if v else DEFAULT_DYNAMIC_HEADROOM


def pool_byte_budget(given: int | None = None) -> int:
    """Resident-device-byte budget of a serve ``EnginePool``: explicit
    argument > ``COMBBLAS_POOL_BYTE_BUDGET`` > unbounded.  0 (and the
    usual unset/empty) means UNBOUNDED — eviction is opt-in."""
    if given is not None:
        return max(int(given), 0)
    v = _int_env(ENV_POOL_BYTE_BUDGET)
    return DEFAULT_POOL_BYTE_BUDGET if v is None else max(v, 0)


def pool_quantum(given: int | None = None) -> int:
    """Weighted-fair-queueing deficit quantum (requests per round per
    unit weight): explicit argument > ``COMBBLAS_POOL_QUANTUM`` > 16."""
    if given is not None:
        return max(int(given), 1)
    v = _int_env(ENV_POOL_QUANTUM)
    return DEFAULT_POOL_QUANTUM if v is None else max(v, 1)


def fleet_replicas(given: int | None = None) -> int:
    """Default ``FleetRouter.build`` replica count: explicit argument >
    ``COMBBLAS_FLEET_REPLICAS`` > 2."""
    if given is not None:
        return max(int(given), 1)
    v = _int_env(ENV_FLEET_REPLICAS)
    return DEFAULT_FLEET_REPLICAS if v is None else max(v, 1)


def obs_trace_sample(given: float | None = None) -> float:
    """Per-request trace sampling rate in [0, 1]: explicit argument >
    ``COMBBLAS_OBS_TRACE_SAMPLE`` > 0 (off).  Clamped to [0, 1]."""
    if given is None:
        v = os.environ.get(ENV_OBS_TRACE_SAMPLE)
        given = float(v) if v else 0.0
    return min(max(float(given), 0.0), 1.0)


def wal_dir(given: str | None = None) -> str | None:
    """The serve durability directory (WAL + checkpoints), or ``None``
    when durability is disabled: explicit argument >
    ``COMBBLAS_WAL`` > off.  ``0``/``off``/``none`` (argument or env)
    disable explicitly — the plan-store convention."""
    v = os.environ.get(ENV_WAL) if given is None else given
    if v is None or v.strip().lower() in ("", "0", "off", "none"):
        return None
    return os.path.abspath(v)


def wal_fsync(given: str | None = None) -> str:
    """WAL append fsync policy: explicit argument >
    ``COMBBLAS_WAL_FSYNC`` > ``always``.  A bogus value raises naming
    the knob (the MERGE/SPMM_BACKEND vetting precedent) instead of
    surfacing as a silent durability downgrade."""
    v = _str_env(ENV_WAL_FSYNC) if given is None else given
    if v is None:
        return DEFAULT_WAL_FSYNC
    if v not in WAL_FSYNC_POLICIES:
        raise ValueError(
            f"{ENV_WAL_FSYNC} must be one of "
            f"{'|'.join(WAL_FSYNC_POLICIES)}; got {v!r}"
        )
    return v


def fleetlog_path(given: str | None = None) -> str | None:
    """Supervision-timeline JSONL path override, or ``None`` to use the
    fleet's own default (``fleetlog.jsonl`` under its workdir):
    explicit argument > ``COMBBLAS_FLEETLOG`` > fleet default.
    ``0``/``off``/``none``/empty fall through to the default — the
    wal_dir convention."""
    v = os.environ.get(ENV_FLEETLOG) if given is None else given
    if v is None or v.strip().lower() in ("", "0", "off", "none"):
        return None
    return os.path.abspath(v)


def obs_hb_metrics_interval(given: float | None = None) -> float:
    """Minimum seconds between child registry snapshots piggybacked on
    replica heartbeats (metrics federation): explicit argument >
    ``COMBBLAS_OBS_HB_METRICS_S`` > 1.0.  Clamped >= 0.05 so a typo
    cannot turn every heartbeat into a full registry serialization."""
    if given is None:
        v = os.environ.get(ENV_OBS_HB_METRICS_S)
        given = float(v) if v else 0.0
    given = float(given)
    if given <= 0.0:
        return DEFAULT_OBS_HB_METRICS_S
    return max(given, 0.05)


def _vet_int(name: str, v, what: str) -> int:
    """Integer-knob vetting shared by the round-19 net knobs: a bogus
    value raises NAMING the knob (the WAL_FSYNC/MERGE precedent)
    instead of surfacing as a bare ``int()`` traceback from deep
    inside socket setup."""
    try:
        return int(v)
    except (TypeError, ValueError):
        raise ValueError(
            f"{name} must be {what}; got {v!r}"
        ) from None


def net_port(given: int | str | None = None) -> int:
    """The front door's TCP listen port: explicit argument >
    ``COMBBLAS_NET_PORT`` > 0 (OS-assigned ephemeral).  Vetted to
    [0, 65535], raising naming the knob."""
    v = os.environ.get(ENV_NET_PORT) if given is None else given
    if v is None or v == "":
        return DEFAULT_NET_PORT
    p = _vet_int(ENV_NET_PORT, v, "an integer port (0 = ephemeral)")
    if not (0 <= p <= 65535):
        raise ValueError(
            f"{ENV_NET_PORT} must be in [0, 65535]; got {v!r}"
        )
    return p


def net_max_conns(given: int | str | None = None) -> int:
    """Open-connection cap of the net frontend: explicit argument >
    ``COMBBLAS_NET_MAX_CONNS`` > 512.  ``0``/unset = default; clamped
    >= 1 (a zero-slot front door would reject its own hello)."""
    v = os.environ.get(ENV_NET_MAX_CONNS) if given is None else given
    if v is None or v == "":
        return DEFAULT_NET_MAX_CONNS
    n = _vet_int(ENV_NET_MAX_CONNS, v, "an integer connection cap")
    return DEFAULT_NET_MAX_CONNS if n == 0 else max(n, 1)


def net_accept_backlog(given: int | str | None = None) -> int:
    """Kernel ``listen()`` backlog: explicit argument >
    ``COMBBLAS_NET_ACCEPT_BACKLOG`` > 128.  ``0``/unset = default;
    clamped >= 1."""
    v = (
        os.environ.get(ENV_NET_ACCEPT_BACKLOG)
        if given is None else given
    )
    if v is None or v == "":
        return DEFAULT_NET_ACCEPT_BACKLOG
    n = _vet_int(ENV_NET_ACCEPT_BACKLOG, v, "an integer backlog")
    return DEFAULT_NET_ACCEPT_BACKLOG if n == 0 else max(n, 1)


def bench_net_rate(given: float | str | None = None) -> float:
    """Open-loop offered arrival rate (req/s): explicit argument >
    ``BENCH_NET_RATE`` > 200.  ``0``/unset = default; a bogus value
    raises naming the knob."""
    v = os.environ.get(ENV_BENCH_NET_RATE) if given is None else given
    if v is None or v == "":
        return DEFAULT_BENCH_NET_RATE
    try:
        r = float(v)
    except (TypeError, ValueError):
        raise ValueError(
            f"{ENV_BENCH_NET_RATE} must be a request rate in req/s; "
            f"got {v!r}"
        ) from None
    return DEFAULT_BENCH_NET_RATE if r == 0 else max(r, 0.1)


def bench_net_conns(given: int | str | None = None) -> int:
    """Open-loop concurrent connection count: explicit argument >
    ``BENCH_NET_CONNS`` > 128.  ``0``/unset = default; clamped >= 1."""
    v = os.environ.get(ENV_BENCH_NET_CONNS) if given is None else given
    if v is None or v == "":
        return DEFAULT_BENCH_NET_CONNS
    n = _vet_int(ENV_BENCH_NET_CONNS, v, "an integer connection count")
    return DEFAULT_BENCH_NET_CONNS if n == 0 else max(n, 1)


def bench_net_seconds(given: float | str | None = None) -> float:
    """Open-loop run length in seconds: explicit argument >
    ``BENCH_NET_SECONDS`` > 8.  ``0``/unset = default."""
    v = (
        os.environ.get(ENV_BENCH_NET_SECONDS)
        if given is None else given
    )
    if v is None or v == "":
        return DEFAULT_BENCH_NET_SECONDS
    try:
        s = float(v)
    except (TypeError, ValueError):
        raise ValueError(
            f"{ENV_BENCH_NET_SECONDS} must be a duration in seconds; "
            f"got {v!r}"
        ) from None
    return DEFAULT_BENCH_NET_SECONDS if s == 0 else max(s, 0.1)


def shard_frontier(given: str | None = None) -> str:
    """Sharded hop frontier encoding: explicit argument >
    ``COMBBLAS_SHARD_FRONTIER`` > ``auto``.  A bogus value raises
    naming the knob (the WAL_FSYNC/MERGE vetting precedent) instead of
    surfacing as a silently-dense wire."""
    v = _str_env(ENV_SHARD_FRONTIER) if given is None else given
    if v is None:
        return DEFAULT_SHARD_FRONTIER
    if v not in SHARD_FRONTIER_MODES:
        raise ValueError(
            f"{ENV_SHARD_FRONTIER} must be one of "
            f"{'|'.join(SHARD_FRONTIER_MODES)}; got {v!r}"
        )
    return v


def shard_density(given: float | str | None = None) -> float:
    """Auto-mode dense-fallback threshold as a frontier-nnz fraction
    of ``n*W``: explicit argument > ``COMBBLAS_SHARD_DENSITY`` > 0.25.
    ``0``/unset = default; vetted to (0, 1] — a fraction above 1 can
    never trigger and reads as a typo'd percentage."""
    v = os.environ.get(ENV_SHARD_DENSITY) if given is None else given
    if v is None or v == "":
        return DEFAULT_SHARD_DENSITY
    try:
        f = float(v)
    except (TypeError, ValueError):
        raise ValueError(
            f"{ENV_SHARD_DENSITY} must be a fraction in (0, 1]; "
            f"got {v!r}"
        ) from None
    if f == 0:
        return DEFAULT_SHARD_DENSITY
    if not (0.0 < f <= 1.0):
        raise ValueError(
            f"{ENV_SHARD_DENSITY} must be a fraction in (0, 1]; "
            f"got {v!r}"
        )
    return f


def shard_wire(given: str | None = None) -> str:
    """Sharded dense-payload wire dtype (propagate's ``q``): explicit
    argument > ``COMBBLAS_SHARD_WIRE`` > ``f32``.  A bogus value
    raises naming the knob instead of surfacing as a silent precision
    downgrade."""
    v = _str_env(ENV_SHARD_WIRE) if given is None else given
    if v is None:
        return DEFAULT_SHARD_WIRE
    if v not in SHARD_WIRE_MODES:
        raise ValueError(
            f"{ENV_SHARD_WIRE} must be one of "
            f"{'|'.join(SHARD_WIRE_MODES)}; got {v!r}"
        )
    return v


def checkpoint_every(given: int | None = None) -> int:
    """Merges between automatic background snapshots: explicit
    argument > ``COMBBLAS_CHECKPOINT_EVERY`` > 8."""
    if given is not None:
        return max(int(given), 1)
    v = _int_env(ENV_CHECKPOINT_EVERY)
    return DEFAULT_CHECKPOINT_EVERY if v is None else max(v, 1)


def checkpoint_retain(given: int | None = None) -> int:
    """Snapshots retained after an automatic checkpoint: explicit
    argument > ``COMBBLAS_CHECKPOINT_RETAIN`` > 2.  Clamped >= 1 —
    retaining zero snapshots would delete the one recovery just
    needs."""
    if given is not None:
        return max(int(given), 1)
    v = _int_env(ENV_CHECKPOINT_RETAIN)
    return DEFAULT_CHECKPOINT_RETAIN if v is None else max(v, 1)


def dynamic_spill_frac() -> float:
    """Structural-change fraction above which the incremental merge
    spills to a full rebuild (``dynamic.merge.spill{reason=threshold}``).
    """
    v = os.environ.get(ENV_DYNAMIC_SPILL)
    return float(v) if v else DEFAULT_DYNAMIC_SPILL_FRAC
