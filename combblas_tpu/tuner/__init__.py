"""``combblas_tpu.tuner`` — measured-cost autotuner with persisted plans.

Three pieces (see docs/autotuning.md):

* :mod:`~combblas_tpu.tuner.config` — the ONE parser for the
  ``COMBBLAS_SPGEMM_*`` / plan-store env knobs, and the documented
  resolution precedence: **arg > store > env > heuristic**.
* :mod:`~combblas_tpu.tuner.store` — the schema-versioned JSONL plan
  store (``.plan_store/plans.jsonl`` next to the XLA compile cache):
  plans keyed by (shape bucket, density band, semiring, backend,
  grid/grid3) holding the measured tier/window/schedule choice.
* :mod:`~combblas_tpu.tuner.probe` — the opt-in micro-probe pass
  (``COMBBLAS_TUNER_PROBE=1``): on a store miss, time the admissible
  rungs on a bounded deterministic proxy and write the winner back.

``parallel.spgemm.spgemm_auto`` and ``parallel.mesh3d.spgemm3d``
consult the store; ``serve.GraphEngine`` records/replays warmup lanes
through it.  The probe module is imported lazily (it pulls in the
kernels); config and store are dependency-light.
"""

from . import config  # noqa: F401
from .resolve import resolve_tier  # noqa: F401
from .store import (  # noqa: F401
    PlanKey,
    PlanRecord,
    PlanStore,
    SCHEMA,
    density_band,
    get_store,
    plan_key_from_counts,
    serve_plan_key,
    shape_bucket,
    spgemm3d_plan_key,
    spgemm_plan_key,
    spmm_plan_key,
)

__all__ = [
    "config",
    "resolve_tier",
    "PlanKey",
    "PlanRecord",
    "PlanStore",
    "SCHEMA",
    "density_band",
    "get_store",
    "plan_key_from_counts",
    "serve_plan_key",
    "shape_bucket",
    "spgemm3d_plan_key",
    "spgemm_plan_key",
    "spmm_plan_key",
]
