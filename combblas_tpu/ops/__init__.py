from .compressed import CSC, CSR
from .segment import expand_ranges, segment_reduce
from .spmv import spmspv, spmv, spmv_masked
from .tuples import SpTuples
