"""SpTuples — padded static-capacity COO tile, the interchange format.

Mirrors the role of the reference's ``SpTuples<IT,NT>``
(``include/CombBLAS/SpTuples.h:64-120``): the column/row-sorted triple format
every kernel, merge, redistribution, and I/O path speaks.  The TPU-native
difference: XLA requires static shapes, so a tile carries a fixed ``capacity``
of slots plus a dynamic ``nnz`` scalar.  Invalid (padding) slots hold
``row == nrows, col == ncols`` so that

* scatters drop them (out-of-range + ``mode='drop'``),
* row-major / col-major sorts push them to the tail,
* gathers hit a dedicated padded slot holding the semiring zero.

All ops are jit-compatible; ``nrows/ncols/capacity`` are trace-time static.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..semiring import Semiring
from .segment import segment_reduce

Array = jax.Array


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["rows", "cols", "vals", "nnz"],
    meta_fields=["nrows", "ncols"],
)
@dataclasses.dataclass(frozen=True)
class SpTuples:
    """Padded COO tile. Valid entries occupy a prefix iff compacted.

    rows/cols: int32[cap]; padding slots hold (nrows, ncols).
    vals: NT[cap]; padding values are unspecified (protected by index drop).
    nnz: int32 scalar — number of valid entries.
    """

    rows: Array
    cols: Array
    vals: Array
    nnz: Array
    nrows: int
    ncols: int

    @property
    def capacity(self) -> int:
        return self.rows.shape[0]

    @property
    def dtype(self):
        return self.vals.dtype

    # --- constructors -----------------------------------------------------

    @staticmethod
    def from_coo(rows, cols, vals, nrows, ncols, capacity=None) -> "SpTuples":
        """Build from concrete (host) index/value arrays (unsorted ok)."""
        rows = np.asarray(rows, dtype=np.int32)
        cols = np.asarray(cols, dtype=np.int32)
        vals = np.asarray(vals)
        n = rows.shape[0]
        cap = int(capacity) if capacity is not None else max(n, 1)
        if n > cap:
            raise ValueError(f"nnz {n} exceeds capacity {cap}")
        pr = np.full(cap, nrows, dtype=np.int32)
        pc = np.full(cap, ncols, dtype=np.int32)
        pv = np.zeros(cap, dtype=vals.dtype)
        pr[:n], pc[:n], pv[:n] = rows, cols, vals
        return SpTuples(
            rows=jnp.asarray(pr),
            cols=jnp.asarray(pc),
            vals=jnp.asarray(pv),
            nnz=jnp.asarray(n, dtype=jnp.int32),
            nrows=int(nrows),
            ncols=int(ncols),
        )

    @staticmethod
    def from_dense(dense, capacity=None, zero=0) -> "SpTuples":
        """Host-side convenience (tests / small inputs)."""
        dense = np.asarray(dense)
        r, c = np.nonzero(dense != zero)
        return SpTuples.from_coo(
            r, c, dense[r, c], dense.shape[0], dense.shape[1], capacity
        )

    @staticmethod
    def empty(nrows, ncols, capacity, dtype) -> "SpTuples":
        return SpTuples(
            rows=jnp.full((capacity,), nrows, dtype=jnp.int32),
            cols=jnp.full((capacity,), ncols, dtype=jnp.int32),
            vals=jnp.zeros((capacity,), dtype=dtype),
            nnz=jnp.asarray(0, dtype=jnp.int32),
            nrows=int(nrows),
            ncols=int(ncols),
        )

    # --- basic queries ----------------------------------------------------

    def valid_mask(self) -> Array:
        return self.rows < self.nrows

    def to_dense(self, sr: Semiring = None) -> Array:
        """Densify; duplicates are combined with ``sr.add`` (default: sum)."""
        zero = sr.zero(self.dtype) if sr is not None else jnp.zeros((), self.dtype)
        out = jnp.full((self.nrows + 1, self.ncols + 1), zero, dtype=self.dtype)
        if sr is None or sr.add_kind == "sum":
            out = out.at[self.rows, self.cols].add(
                jnp.where(self.valid_mask(), self.vals, 0), mode="drop"
            )
        elif sr.add_kind == "min":
            out = out.at[self.rows, self.cols].min(self.vals, mode="drop")
        elif sr.add_kind == "max":
            out = out.at[self.rows, self.cols].max(self.vals, mode="drop")
        else:
            # Generic monoid: flatten (row, col) to one segment id and run the
            # order-respecting segmented reduction (scatter .set would be
            # last-write-wins with unspecified order).
            flat_ids = self.rows * (self.ncols + 1) + self.cols
            flat = segment_reduce(
                sr, self.vals, flat_ids, (self.nrows + 1) * (self.ncols + 1)
            )
            out = flat.reshape(self.nrows + 1, self.ncols + 1)
        return out[: self.nrows, : self.ncols]

    # --- structural transforms -------------------------------------------

    def sort_rowmajor(self) -> "SpTuples":
        # A fused single-uint32-key variant was tried and measured on the
        # target chip: no improvement over the two-key sort
        # (benchmarks/results/microbench_r2f.txt, 28.6s vs 26.6s) — the
        # sort is bandwidth/pass-bound, not operand-count-bound.
        r, c, v = lax.sort((self.rows, self.cols, self.vals), num_keys=2)
        return dataclasses.replace(self, rows=r, cols=c, vals=v)

    def sort_colmajor(self) -> "SpTuples":
        c, r, v = lax.sort((self.cols, self.rows, self.vals), num_keys=2)
        return dataclasses.replace(self, rows=r, cols=c, vals=v)

    def transpose(self) -> "SpTuples":
        """Swap rows/cols. Reference: ``SpTuples`` transpose ctor flag."""
        return SpTuples(
            rows=jnp.where(self.valid_mask(), self.cols, self.ncols),
            cols=jnp.where(self.valid_mask(), self.rows, self.nrows),
            vals=self.vals,
            nnz=self.nnz,
            nrows=self.ncols,
            ncols=self.nrows,
        )

    def with_capacity(self, capacity: int) -> "SpTuples":
        """Grow/shrink the slot count.

        Shrinking requires a compacted tile with ``nnz <= capacity``; entries
        beyond the new capacity are lost and ``nnz`` is clamped to match.
        """
        cap = self.capacity
        if capacity == cap:
            return self
        if capacity > cap:
            pad = capacity - cap
            return dataclasses.replace(
                self,
                rows=jnp.concatenate(
                    [self.rows, jnp.full((pad,), self.nrows, jnp.int32)]
                ),
                cols=jnp.concatenate(
                    [self.cols, jnp.full((pad,), self.ncols, jnp.int32)]
                ),
                vals=jnp.concatenate(
                    [self.vals, jnp.zeros((pad,), self.vals.dtype)]
                ),
            )
        return dataclasses.replace(
            self,
            rows=self.rows[:capacity],
            cols=self.cols[:capacity],
            vals=self.vals[:capacity],
            nnz=jnp.minimum(self.nnz, jnp.int32(capacity)),
        )

    def compact_counted(
        self,
        sr: Semiring,
        *,
        capacity: int | None = None,
        assume_sorted: bool = False,
    ) -> tuple["SpTuples", Array]:
        """``compact`` that also returns the EXACT distinct-key count
        (before any truncation) — the per-tile role of the reference's
        ``estimateNNZ_Hash`` (mtSpGEMM.h:807): callers compare it against
        ``capacity`` to detect truncation and retry with exact sizing.

        Sort row-major, combine duplicates with ``sr.add``, drop explicit
        zeros, and pack valid entries to the front.

        Mirrors ``SpTuples::RemoveDuplicates(BinOp)`` (SpTuples.h:89) plus the
        sort that every DCSC build performs.

        INVARIANT: ``capacity`` must be >= the number of distinct (row, col)
        keys; entries whose combined slot lands beyond it are truncated (the
        static-shape price of XLA — callers size capacities from symbolic
        estimates, see ops/spgemm.py). ``nnz`` is clamped to ``capacity`` so
        the result stays self-consistent either way.

        ``assume_sorted=True`` skips the row-major sort (caller guarantees
        slots are already (row, col)-sorted with padding at the tail).
        """
        cap = capacity if capacity is not None else self.capacity
        t = self if assume_sorted else self.sort_rowmajor()
        valid = t.valid_mask()
        prev_same = jnp.concatenate(
            [
                jnp.zeros((1,), bool),
                (t.rows[1:] == t.rows[:-1]) & (t.cols[1:] == t.cols[:-1]),
            ]
        )
        is_new = valid & ~prev_same
        seg = jnp.cumsum(is_new.astype(jnp.int32)) - 1
        seg = jnp.where(valid, seg, cap)
        vals = segment_reduce(sr, t.vals, seg, cap, ids_sorted=True)
        distinct = jnp.sum(is_new).astype(jnp.int32)
        # ONE input-sized permutation scatter + output-sized gathers
        # (instead of one input-sized scatter per index array): the output
        # is typically several-fold smaller than the expansion, and this
        # chip prices scatters/gathers per ELEMENT (~22-27 M/s,
        # benchmarks/results/scatter_probe_r3.txt).
        # distinct OOB sentinels keep the unique_indices contract for the
        # dropped (non-representative) slots
        slot_ids = jnp.arange(t.capacity, dtype=jnp.int32)
        scatter_idx = jnp.where(is_new, seg, cap + slot_ids)
        perm = jnp.zeros((cap,), jnp.int32).at[scatter_idx].set(
            slot_ids, mode="drop", unique_indices=True,
        )
        out_valid = jnp.arange(cap, dtype=jnp.int32) < distinct
        rows = jnp.where(out_valid, t.rows[perm], self.nrows)
        cols = jnp.where(out_valid, t.cols[perm], self.ncols)
        nnz = jnp.minimum(distinct, jnp.int32(cap))
        out = SpTuples(
            rows=rows, cols=cols, vals=vals, nnz=nnz,
            nrows=self.nrows, ncols=self.ncols,
        )
        return out.prune_zeros(sr), distinct

    def compact(
        self,
        sr: Semiring,
        *,
        capacity: int | None = None,
        assume_sorted: bool = False,
    ) -> "SpTuples":
        out, _ = self.compact_counted(
            sr, capacity=capacity, assume_sorted=assume_sorted
        )
        return out

    def prune_zeros(self, sr: Semiring) -> "SpTuples":
        """Drop entries equal to the additive identity (compacted output)."""
        zero = sr.zero(self.dtype)
        keep = self.valid_mask() & (self.vals != zero)
        return self._select(keep)

    def prune(self, pred) -> "SpTuples":
        """Drop entries where ``pred(val)`` is True.

        Reference: ``SpParMat::Prune`` (SpParMat.h:162-198) local part.
        """
        keep = self.valid_mask() & ~pred(self.vals)
        return self._select(keep)

    def select_ij(self, keep_fn) -> "SpTuples":
        """Keep entries where ``keep_fn(row, col)`` (tile-local ids) is True.

        The structural counterpart of ``prune``: used for tril/triu/
        RemoveLoops (reference ``SpParMat::PruneI`` / ``RemoveLoops``,
        SpParMat.cpp:3257).
        """
        keep = self.valid_mask() & keep_fn(self.rows, self.cols)
        return self._select(keep)

    def _select(self, keep: Array) -> "SpTuples":
        """Stable-compact entries where ``keep`` to the front.

        One permutation scatter + per-array gathers (not one scatter per
        array): scatters and gathers cost the same per element on the
        target chip, so 1 scatter + 3 gathers beats 3 scatters whenever
        XLA can fuse the gathers, and never loses.
        """
        cap = self.capacity
        nkeep = jnp.sum(keep).astype(jnp.int32)
        pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
        slot_ids = jnp.arange(cap, dtype=jnp.int32)
        scatter_idx = jnp.where(keep, pos, cap + slot_ids)
        perm = jnp.zeros((cap,), jnp.int32).at[scatter_idx].set(
            slot_ids, mode="drop", unique_indices=True,
        )
        out_valid = slot_ids < nkeep
        return SpTuples(
            rows=jnp.where(out_valid, self.rows[perm], self.nrows),
            cols=jnp.where(out_valid, self.cols[perm], self.ncols),
            vals=jnp.where(out_valid, self.vals[perm], 0),
            nnz=nkeep,
            nrows=self.nrows, ncols=self.ncols,
        )

    def apply(self, fn) -> "SpTuples":
        """Elementwise value transform on valid entries.

        Reference: ``SpParMat::Apply`` (SpParMat.h:148).
        """
        vals = jnp.where(self.valid_mask(), fn(self.vals), self.vals)
        return dataclasses.replace(self, vals=vals)

    # --- concatenation (merge input) -------------------------------------

    @staticmethod
    def concat(tiles: list["SpTuples"]) -> "SpTuples":
        """Stack slot arrays of same-shape tiles (pre-merge). All tiles must
        share (nrows, ncols). Output capacity = sum of capacities."""
        t0 = tiles[0]
        return SpTuples(
            rows=jnp.concatenate([t.rows for t in tiles]),
            cols=jnp.concatenate([t.cols for t in tiles]),
            vals=jnp.concatenate([t.vals for t in tiles]),
            nnz=sum((t.nnz for t in tiles[1:]), start=t0.nnz),
            nrows=t0.nrows,
            ncols=t0.ncols,
        )
