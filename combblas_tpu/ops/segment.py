"""Monoid segment-reductions — the TPU analog of the reference's SPA.

Every irregular accumulation in the reference (sparse accumulator / SPA in
``SpImpl.h:184-200`` + ``PreAllocatedSPA.h``, hash accumulation in
``mtSpGEMM.h:292-440``, heap merges in ``MultiwayMerge.h:185``) reduces to one
primitive: combine values that share a key with the semiring's ``add``.  On
TPU the native expression of that primitive is a segment reduction:

* monoids with an XLA scatter fast path (``sum`` / ``min`` / ``max``) lower to
  a single fused scatter op;
* arbitrary monoids use a sort-free segmented ``lax.associative_scan`` over
  values paired with their segment ids (ids must be pre-sorted, which our
  sorted-tuple invariant provides for free).

Out-of-range segment ids (>= num_segments) are dropped — this is how padded
(invalid) tuple slots stay inert without masks.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from ..semiring import Semiring


def segment_reduce(
    sr: Semiring,
    vals: jax.Array,
    ids: jax.Array,
    num_segments: int,
    *,
    ids_sorted: bool = False,
) -> jax.Array:
    """``out[s] = sr.add-fold of vals[ids == s]``; empty segments get ``sr.zero``.

    ids >= num_segments (padding) are dropped.
    """
    zero = sr.zero(vals.dtype)
    if sr.add_kind == "sum":
        # segment_sum's natural fill (0) is the additive identity of any
        # '+'-monoid — no empty-segment patch needed on the hottest path.
        # The sorted-indices hint is worth ~15-20% scatter throughput on
        # the target chip (benchmarks/results/scatter_probe_r3.txt).
        return jax.ops.segment_sum(
            vals, ids, num_segments=num_segments,
            indices_are_sorted=ids_sorted,
        )
    if sr.add_kind == "min":
        out = jax.ops.segment_min(
            vals, ids, num_segments=num_segments,
            indices_are_sorted=ids_sorted,
        )
    elif sr.add_kind == "max":
        out = jax.ops.segment_max(
            vals, ids, num_segments=num_segments,
            indices_are_sorted=ids_sorted,
        )
    else:
        return _generic_segment_reduce(
            sr, vals, ids, num_segments, ids_sorted=ids_sorted
        )
    # Natural identity of the scatter op may differ from the semiring zero
    # (e.g. select2nd_max has zero=-1 but segment_max fills INT_MIN); patch
    # empty segments.
    counts = jax.ops.segment_sum(
        jnp.ones_like(ids, dtype=jnp.int32), ids, num_segments=num_segments
    )
    return jnp.where(counts > 0, out, zero)


def _generic_segment_reduce(
    sr: Semiring,
    vals: jax.Array,
    ids: jax.Array,
    num_segments: int,
    *,
    ids_sorted: bool,
) -> jax.Array:
    zero = sr.zero(vals.dtype)
    if not ids_sorted:
        ids, vals = lax.sort((ids, vals), num_keys=1)

    def combine(a, b):
        va, ia = a
        vb, ib = b
        return jnp.where(ia == ib, sr.add(va, vb), vb), ib

    scanned_vals, _ = lax.associative_scan(combine, (vals, ids))
    # The last slot of each id-run holds the full fold; scatter it out.
    is_last = jnp.concatenate(
        [ids[1:] != ids[:-1], jnp.ones((1,), dtype=bool)]
    )
    scatter_ids = jnp.where(is_last, ids, num_segments)
    out = jnp.full((num_segments,), zero, dtype=vals.dtype)
    return out.at[scatter_ids].set(scanned_vals, mode="drop")


def expand_ranges(lens: jax.Array, capacity: int):
    """Flatten variable-length ranges into static-capacity slots.

    Given ``lens[i]`` items contributed by source ``i``, produce for each flat
    output slot ``f`` in ``[0, capacity)`` the pair ``(owner[f], offset[f])``
    such that slot ``f`` is item ``offset[f]`` of source ``owner[f]``, plus a
    validity mask (``f < sum(lens)``).

    This is the static-shape analog of the reference's per-column expansion
    loops in local SpGEMM (``mtSpGEMM.h:292-440``) and column walks in SpMSpV
    (``SpImpl.cpp:53-180``): instead of data-dependent loop bounds, we
    materialize a fixed ``capacity`` of slots and map each back to its source.

    The flop->owner map is computed by SCATTER + CUMULATIVE MAX, not
    searchsorted: scatter each source's index (and start) at its start
    position, then a streaming cummax fills the run. On the target chip a
    searchsorted here costs ~0.4 us per slot (measured 24.8 s of a 30.7 s
    scale-14 SpGEMM, benchmarks/results/scatter_probe_r3.txt) while the
    two scatters touch only ``len(lens)`` slots and the cummaxes stream.
    """
    lens = lens.astype(jnp.int32)
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(lens, dtype=jnp.int32)]
    )
    total = starts[-1]
    n = lens.shape[0]
    pos = starts[:-1]  # scatter position of each source (>= capacity drops)
    # owner[f] = max{i : starts[i] <= f}; duplicates (zero-length sources)
    # resolve to the highest index, matching searchsorted(side='right') - 1.
    seed = jnp.full((capacity,), -1, jnp.int32).at[pos].max(
        jnp.arange(n, dtype=jnp.int32), mode="drop"
    )
    owner = jnp.clip(lax.cummax(seed), 0, n - 1)
    # base[f] = starts[owner[f]] by the same construction (starts monotone)
    base = jnp.zeros((capacity,), jnp.int32).at[pos].max(pos, mode="drop")
    base = lax.cummax(base)
    f = jnp.arange(capacity, dtype=jnp.int32)
    offset = f - base
    valid = f < total
    return owner, offset, valid, total
