"""Compressed local tile formats (CSR / CSC) built on sorted tuples.

The reference's workhorse local format is DCSC (doubly-compressed sparse
column, ``include/CombBLAS/dcsc.h:46-135``) chosen because hypersparse tiles
on large process grids have far fewer nonempty columns than columns.  On TPU
the trade-off flips: gathers/scatters over a static-capacity index array are
cheap and column-pointer *compression* buys nothing once shapes must be
static — so the native analogs are:

* ``CSR``: row-pointer array ``indptr[nrows+1]`` + column/value slot arrays.
  Plays the role of ``SpDCCols`` for row-wise access (SpMV, SpGEMM B-side
  row lookup).
* ``CSC``: symmetric for column-wise access (SpMSpV column walks, SpGEMM
  A-side).

Both keep the padded-slot invariant of ``SpTuples`` (entries beyond ``nnz``
hold out-of-range indices) and carry static ``nrows/ncols/capacity``.
Hypersparsity is instead handled where it matters on TPU: capacities are per
-tile trace-time constants, so an almost-empty tile compiles to almost-no
work.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from ..semiring import Semiring
from .segment import expand_ranges, segment_reduce
from .tuples import SpTuples

Array = jax.Array


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["indptr", "indices", "vals", "nnz"],
    meta_fields=["nrows", "ncols"],
)
@dataclasses.dataclass(frozen=True)
class CSR:
    """Row-compressed tile. ``indices`` are column ids, row-major sorted."""

    indptr: Array  # int32[nrows + 1]
    indices: Array  # int32[cap]; padding = ncols
    vals: Array  # NT[cap]
    nnz: Array  # int32 scalar
    nrows: int
    ncols: int

    @property
    def capacity(self) -> int:
        return self.indices.shape[0]

    @staticmethod
    def from_tuples(t: SpTuples, *, assume_sorted: bool = False) -> "CSR":
        if not assume_sorted:
            t = t.sort_rowmajor()
        counts = jax.ops.segment_sum(
            jnp.ones_like(t.rows), t.rows, num_segments=t.nrows
        )
        indptr = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts, dtype=jnp.int32)]
        )
        return CSR(
            indptr=indptr, indices=t.cols, vals=t.vals, nnz=t.nnz,
            nrows=t.nrows, ncols=t.ncols,
        )

    def row_lens(self) -> Array:
        return self.indptr[1:] - self.indptr[:-1]

    def to_tuples(self) -> SpTuples:
        owner, _, valid, _ = expand_ranges(self.row_lens(), self.capacity)
        rows = jnp.where(valid, owner, self.nrows)
        return SpTuples(
            rows=rows, cols=self.indices, vals=self.vals, nnz=self.nnz,
            nrows=self.nrows, ncols=self.ncols,
        )

    def to_bitmask(self) -> Array:
        """Packed [nrows, ceil(ncols/32)] uint32 support bitmask of the
        tile — the output-support oracle's storage format (32x less
        gather traffic than bool; see ops/spgemm.pack_support_bits).
        CSR entries are unique by construction, so no dedup pass."""
        from .spgemm import pack_support_bits

        t = self.to_tuples()
        return pack_support_bits(
            t.rows, t.cols, self.nrows, self.ncols, assume_unique=True
        )


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["indptr", "indices", "vals", "nnz"],
    meta_fields=["nrows", "ncols"],
)
@dataclasses.dataclass(frozen=True)
class CSC:
    """Column-compressed tile. ``indices`` are row ids, col-major sorted."""

    indptr: Array  # int32[ncols + 1]
    indices: Array  # int32[cap]; padding = nrows
    vals: Array
    nnz: Array
    nrows: int
    ncols: int

    @property
    def capacity(self) -> int:
        return self.indices.shape[0]

    @staticmethod
    def from_tuples(t: SpTuples, *, assume_sorted: bool = False) -> "CSC":
        if not assume_sorted:
            t = t.sort_colmajor()
        counts = jax.ops.segment_sum(
            jnp.ones_like(t.cols), t.cols, num_segments=t.ncols
        )
        indptr = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts, dtype=jnp.int32)]
        )
        return CSC(
            indptr=indptr, indices=t.rows, vals=t.vals, nnz=t.nnz,
            nrows=t.nrows, ncols=t.ncols,
        )

    def col_lens(self) -> Array:
        return self.indptr[1:] - self.indptr[:-1]

    def to_tuples(self) -> SpTuples:
        owner, _, valid, _ = expand_ranges(self.col_lens(), self.capacity)
        cols = jnp.where(valid, owner, self.ncols)
        return SpTuples(
            rows=self.indices, cols=cols, vals=self.vals, nnz=self.nnz,
            nrows=self.nrows, ncols=self.ncols,
        )

    def to_bitmask(self) -> Array:
        """Packed [ncols, ceil(nrows/32)] uint32 COLUMN-support bitmask
        (bit (j, i) set iff entry (i, j) exists) — the transpose-side
        oracle table: pairing a CSR row mask with a CSC column mask makes
        each output cell's support test one popcount (ops/spgemm)."""
        from .spgemm import pack_support_bits

        t = self.to_tuples()
        return pack_support_bits(
            t.cols, t.rows, self.ncols, self.nrows, assume_unique=True
        )
