"""Local semiring SpGEMM — expansion / sort / compression (ESC).

The reference's local SpGEMM (``include/CombBLAS/mtSpGEMM.h:214-440``) runs a
two-pass symbolic+numeric hash/heap kernel with a per-column heap-vs-hash
choice (compression ratio < 2.0 → heap, :310-311) and OpenMP over columns.
Per-column dynamic hashing is hostile to TPU vectorization, so the TPU-native
kernel is the classic ESC formulation — every phase is a primitive XLA is
good at:

  1. EXPAND: one slot per scalar multiply (flop). For A entry (i,k,a) and
     B's row k, emit (i, j, a⊗b) for each (k,j,b) — flattened to a static
     ``flop_capacity`` via ``expand_ranges`` (no per-column loops).
  2. SORT: lexicographic (row, col) ``lax.sort`` — TPU's native sort.
  3. COMPRESS: segmented semiring fold + compaction (``SpTuples.compact``).

The symbolic pass of the reference (``estimateFLOP`` :1058,
``estimateNNZ_Hash`` :807) maps to ``flops`` below: exact flop counting is a
one-gather + segment-sum, and callers size ``flop_capacity`` from it outside
jit (capacities are trace-time constants — the XLA analog of the
reference's exact preallocation).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..semiring import Semiring
from .compressed import CSR
from .segment import expand_ranges
from .tuples import SpTuples

Array = jax.Array


def flops(a: SpTuples, b_csr: CSR) -> Array:
    """Scalar-multiply count of a·b (≈ estimateFLOP, mtSpGEMM.h:1058).

    Accumulated in float32: true counts can exceed int32 at scale (the
    reference uses int64, which JAX disables by default), and a capacity
    estimate only needs ~7 significant digits — callers add multiplicative
    slack (see ``summa_capacities``).
    """
    assert a.ncols == b_csr.nrows
    lens_pad = jnp.concatenate([b_csr.row_lens(), jnp.zeros((1,), jnp.int32)])
    k = jnp.minimum(a.cols, b_csr.nrows)
    per_entry = jnp.where(a.valid_mask(), lens_pad[k], 0)
    return jnp.sum(per_entry.astype(jnp.float32))


def expand(sr: Semiring, a: SpTuples, b_csr: CSR, flop_capacity: int) -> SpTuples:
    """EXPAND phase: uncombined product tuples (duplicates included).

    Output tile has shape (a.nrows, b.ncols) and capacity ``flop_capacity``;
    flops beyond the capacity are silently truncated — callers must size via
    ``flops`` (for exactness) or a proven bound.
    """
    assert a.ncols == b_csr.nrows
    lens_pad = jnp.concatenate([b_csr.row_lens(), jnp.zeros((1,), jnp.int32)])
    starts_pad = jnp.concatenate([b_csr.indptr[:-1], jnp.zeros((1,), jnp.int32)])
    k = jnp.minimum(a.cols, b_csr.nrows)
    deg = jnp.where(a.valid_mask(), lens_pad[k], 0)
    owner, offset, valid, _ = expand_ranges(deg, flop_capacity)
    k_o = jnp.minimum(a.cols[owner], b_csr.nrows)
    b_slot = jnp.minimum(starts_pad[k_o] + offset, b_csr.capacity - 1)
    rows = jnp.where(valid, a.rows[owner], a.nrows)
    cols = jnp.where(valid, b_csr.indices[b_slot], b_csr.ncols)
    vals = sr.mul(a.vals[owner], b_csr.vals[b_slot])
    return SpTuples(
        rows=rows,
        cols=cols,
        vals=vals,
        nnz=jnp.sum(valid).astype(jnp.int32),
        nrows=a.nrows,
        ncols=b_csr.ncols,
    )


def local_spgemm(
    sr: Semiring,
    a: SpTuples,
    b_csr: CSR,
    *,
    flop_capacity: int,
    out_capacity: int,
) -> SpTuples:
    """C = A ⊗ B on one tile: expand → sort → compress.

    ≈ ``LocalHybridSpGEMM`` (mtSpGEMM.h:214) with the hash/heap accumulator
    replaced by sort+segmented-fold.
    """
    return expand(sr, a, b_csr, flop_capacity).compact(
        sr, capacity=out_capacity
    )


def densify(t: SpTuples, pad_rows: int, pad_cols: int, zero) -> Array:
    """Tile tuples → dense [pad_rows, pad_cols] (padding cells = ``zero``).

    The scatter uses sorted/unique index hints (tiles are compacted and
    row-major sortable), which XLA can turn into a vectorized store.
    """
    t = t.sort_rowmajor()
    # Invalid slots get DISTINCT out-of-bounds indices (base + slot id) so
    # the unique_indices contract holds even for padding; mode='drop'
    # discards them all. Sortedness survives: valid entries occupy an
    # ascending prefix below base, invalid tail slots get base + position.
    oob = pad_rows * pad_cols + jnp.arange(t.capacity, dtype=jnp.int32)
    flat = jnp.where(t.valid_mask(), t.rows * pad_cols + t.cols, oob)
    dense = jnp.full((pad_rows * pad_cols,), zero, t.vals.dtype)
    dense = dense.at[flat].set(
        t.vals, mode="drop", indices_are_sorted=True, unique_indices=True
    )
    return dense.reshape(pad_rows, pad_cols)


def sparsify(
    dense: Array, zero, nrows: int, ncols: int, capacity: int
) -> tuple[SpTuples, Array]:
    """Dense [R, C] block → (SpTuples with ``capacity`` slots, exact
    nonzero count).

    Row-structured extraction: per-row nonzero counts feed
    ``expand_ranges`` (whose binary search runs over the tiny [R+1]
    prefix array — cache-resident), and each slot finds its column with a
    manual binary search over its OWN row's prefix sums. A flat
    searchsorted over the full R*C cumsum measured 26 s for 33M queries
    on the target chip (0.78 us/query of HBM-random binary probes); the
    row-local formulation cuts the big-array probes ~2x and keeps the
    heavy first search in cache.
    """
    from .segment import expand_ranges

    R, C = dense.shape
    mask = dense != zero
    if C != ncols:
        mask = mask & (jnp.arange(C, dtype=jnp.int32)[None, :] < ncols)
    if R != nrows:
        mask = mask & (jnp.arange(R, dtype=jnp.int32)[:, None] < nrows)
    m32 = mask.astype(jnp.int32)
    rowcnt = jnp.sum(m32, axis=1)
    rowcum = jnp.cumsum(m32, axis=1).reshape(-1)  # flat [R*C]
    owner, offset, valid, total = expand_ranges(rowcnt, capacity)
    # smallest c with rowcum[owner, c] >= offset+1
    want = offset + 1
    lo = jnp.zeros((capacity,), jnp.int32)
    hi = jnp.full((capacity,), C - 1, jnp.int32)
    nsteps = max(int(np.ceil(np.log2(max(C, 2)))), 1)
    base = owner * C
    for _ in range(nsteps):
        mid = (lo + hi) >> 1
        v = rowcum[base + mid]
        lo = jnp.where(v < want, mid + 1, lo)
        hi = jnp.where(v < want, hi, mid)
    col = hi
    rows = jnp.where(valid, owner, nrows).astype(jnp.int32)
    cols = jnp.where(valid, col, ncols).astype(jnp.int32)
    vals = jnp.where(valid, dense.reshape(-1)[base + col], 0)
    return (
        SpTuples(
            rows=rows, cols=cols, vals=vals,
            nnz=jnp.minimum(total, capacity).astype(jnp.int32),
            nrows=nrows, ncols=ncols,
        ),
        total,
    )
